"""Breaking the dispatch floor (ISSUE 6): µs/step of the small-cell
LSTM train step through Trainer at scan_window K ∈ {1, 8, 32}.

PERF.md round 4 attributed the reference-grid h256/bs64 LSTM cell to a
30-55 µs/step host-dispatch floor, and the round-5 async loop only HIDES
that floor (the host stops waiting per step, but still issues one
`Executor.run` per step). The scan window removes it: K steps compile
into one lax.scan program, so the host issues 1/K as many dispatches.
This experiment drives the SAME Trainer loop in five arms — sync
(per-step fence), async (cadence fence), scan K ∈ {1, 8, 32} — over a
fixed-seed 2-layer LSTM classifier at the small-cell shape, interleaved
(PERF.md methodology), and records µs/step + the deterministic
dispatch/sync counters to benchmarks/scan_window.json.

Run: python experiments/exp_scan_window.py   (TPU via the ambient
tunnel; JAX_PLATFORMS=cpu for a host-overhead-only reading — on CPU the
per-step python/dispatch overhead stands in for the device dispatch
floor, same mechanism, different constant).

Env: STEPS (default 64), BATCH (64), HIDDEN (256), SEQLEN (CPU default
8 to keep compute out of the way; use 100 on TPU for the grid cell),
REPS (3 interleaved rounds).
"""
import json
import os
import time

import numpy as np

STEPS = int(os.environ.get("STEPS", 64))
BATCH = int(os.environ.get("BATCH", 64))
HIDDEN = int(os.environ.get("HIDDEN", 256))
REPS = int(os.environ.get("REPS", 3))


def build(batch, hidden, seqlen, vocab=3000, emb_dim=128):
    import paddle_tpu as pt
    from paddle_tpu import models

    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 11
    with pt.program_guard(prog, startup):
        words = pt.layers.data("words", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.lstm_benchmark_net(
            words, vocab_size=vocab, emb_dim=emb_dim, hidden=hidden,
            max_len=seqlen)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    return prog, startup, loss


def make_reader(batch, seqlen, vocab=3000):
    from paddle_tpu.core.lod import LoDArray

    rng = np.random.RandomState(0)
    data = []
    for _ in range(STEPS):
        seqs = [rng.randint(0, vocab, (seqlen,)).astype(np.int32)
                for _ in range(batch)]
        data.append({
            "words": LoDArray.from_sequences(
                seqs, capacity=batch * seqlen, max_seqs=batch),
            "label": rng.randint(0, 2, (batch, 1)).astype(np.int32),
        })

    def reader():
        yield from data
    return reader


def run_arm(mode, interval, window, seqlen):
    import paddle_tpu as pt

    prog, startup, loss = build(BATCH, HIDDEN, seqlen)
    trainer = pt.Trainer(loss, main_program=prog, startup_program=startup)
    reader = make_reader(BATCH, seqlen)
    # pass 0 pays compiles (incl. the committed-sharding variant); the
    # timed passes are steady state
    trainer.train(reader, num_passes=1, log_interval=interval,
                  scan_window=window)
    best = None
    for _ in range(REPS):
        s0, d0 = trainer.host_sync_count, trainer.host_dispatch_count
        t0 = time.perf_counter()
        trainer.train(reader, num_passes=1, log_interval=interval,
                      scan_window=window)
        dt = time.perf_counter() - t0
        rec = {
            "us_per_step": round(1e6 * dt / STEPS, 1),
            "dispatches_per_step": round(
                (trainer.host_dispatch_count - d0) / STEPS, 4),
            "syncs_per_step": round(
                (trainer.host_sync_count - s0) / STEPS, 4),
        }
        if best is None or rec["us_per_step"] < best["us_per_step"]:
            best = rec
    print(f"  {mode:10s} {best['us_per_step']:10.1f} us/step  "
          f"{best['dispatches_per_step']:.3f} disp/step  "
          f"{best['syncs_per_step']:.3f} sync/step")
    return best


def main():
    import jax

    kind = jax.devices()[0].device_kind
    on_cpu = jax.default_backend() == "cpu"
    seqlen = int(os.environ.get("BENCH_SEQLEN" if not on_cpu else "SEQLEN",
                                100 if not on_cpu else 8))
    print(f"device={kind} steps={STEPS} batch={BATCH} hidden={HIDDEN} "
          f"seqlen={seqlen}")
    arms = [
        ("sync", 1, 0),
        ("async", STEPS, 0),
        ("scan_k1", STEPS, 1),
        ("scan_k8", STEPS, 8),
        ("scan_k32", STEPS, 32),
    ]
    out = {
        "experiment": "scan_window_dispatch_floor",
        "device_kind": kind,
        "steps": STEPS, "batch": BATCH, "hidden": HIDDEN, "seqlen": seqlen,
        "arms": {},
    }
    for mode, interval, window in arms:
        out["arms"][mode] = run_arm(mode, interval, window, seqlen)
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "scan_window.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print(f"wrote {path}")


if __name__ == "__main__":
    main()
