"""A/B: the book stacked_lstm_net (understand_sentiment, 3 layers)
through the single stacked_lstm op vs the per-layer fc+dynamic_lstm
build — the N-layer generalization of the r4 stacked_lstm2 lever.

Same-process interleaved (PERF.md methodology). Two regimes:
- hid 128 (the book's scale): below the fused-LSTM window, so the win
  is the single all-layers scan vs 3 scans + 2 fc op chains (the
  dispatch-floor lever);
- hid 512: in-window, per-layer fused kernels + batched inter-layer
  matmuls vs per-layer scan ops.
Run on TPU: python experiments/exp_stacked_book.py
"""
import os
import time

import numpy as np

STEPS = int(os.environ.get("STEPS", 60))
T = 128


def build(variant, hid, batch):
    """variant: "per_layer" (book multi-op build), "op" (stacked_lstm
    op, layer-by-layer default), "op_scan" (stacked_lstm op, the
    flag-gated all-layers single scan)."""
    import paddle_tpu as pt
    from paddle_tpu.core.lod import LoDArray
    from paddle_tpu.flags import FLAGS

    FLAGS.stacked_lstm_single_scan = variant == "op_scan"
    vocab = 30000
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        ids = pt.layers.data("words", shape=[-1], dtype=np.int32,
                             lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        emb = pt.layers.embedding(ids, size=[vocab, 128])
        fc1 = pt.layers.fc(emb, size=hid * 4)
        if variant in ("op", "op_scan"):
            fc_seq, h_seq = pt.layers.stacked_lstm(
                fc1, size=hid * 4, stacked_num=3, max_len=T)
        else:
            fc_seq = fc1
            h_seq = pt.layers.dynamic_lstm(fc1, size=hid * 4, max_len=T)
            for _ in range(2):
                fc_seq = pt.layers.fc([fc_seq, h_seq], size=hid * 4)
                h_seq = pt.layers.dynamic_lstm(fc_seq, size=hid * 4,
                                               max_len=T)
        fc_last = pt.layers.sequence_pool(fc_seq, "max")
        h_last = pt.layers.sequence_pool(h_seq, "max")
        logits = pt.layers.fc([fc_last, h_last], size=2)
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Adam(learning_rate=2e-3).minimize(loss)
    prog.set_amp("bfloat16")
    rng = np.random.RandomState(0)
    seqs = [rng.randint(2, vocab, (T,)).astype(np.int32)
            for _ in range(batch)]
    feed = {"words": LoDArray.from_sequences(seqs, capacity=batch * T,
                                             max_seqs=batch),
            "label": rng.randint(0, 2, (batch, 1)).astype(np.int32)}
    return prog, startup, loss, feed


def main():
    import jax

    import paddle_tpu as pt

    from paddle_tpu.flags import FLAGS

    exe = pt.Executor(donate_state=True)
    arms = ("per_layer", "op", "op_scan")
    for hid, batch in ((128, 128), (512, 128)):
        variants = {}
        for variant in arms:
            prog, startup, loss, feed = build(variant, hid, batch)
            feed = {k: jax.device_put(v) for k, v in feed.items()}
            for v in feed.values():
                for leaf in jax.tree.leaves(v):
                    np.asarray(leaf.ravel()[0])
            exe.run(startup)
            for _ in range(3):  # first run traces under the arm's flag
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            FLAGS.stacked_lstm_single_scan = False
            assert np.isfinite(l), f"variant={variant} loss {l}"
            variants[variant] = (prog, loss, feed)
        res = {v: [] for v in arms}
        for rep in range(3):
            for variant in arms:
                prog, loss, feed = variants[variant]
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                                   return_numpy=False)
                float(np.asarray(l))
                dt = (time.perf_counter() - t0) / STEPS
                res[variant].append(dt)
                print(f"hid={hid} rep{rep} {variant:>9}: "
                      f"{dt*1e3:6.1f} ms/step "
                      f"{batch*T/dt/1e3:7.1f}k tok/s", flush=True)
        base = sorted(res["per_layer"])[1]
        for variant in arms[1:]:
            m = sorted(res[variant])[1]
            print(f"hid={hid}: {variant} speedup {base/m:.3f}x "
                  f"({batch*T/base/1e3:.1f}k -> {batch*T/m/1e3:.1f}k "
                  f"tok/s)", flush=True)


if __name__ == "__main__":
    main()
