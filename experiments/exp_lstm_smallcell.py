"""Attribute the LSTM small-cell floor (reference grid h=256/bs=64:
1.0% MFU, benchmarks/lstm_grid.json — VERDICT r3 weak #4).

Decomposition ladders (fwd+bwd, chained, same process):
  scan_floor  — trivial lax.scan, carry [B,H]: the per-step dispatch floor
  matmul_only — scan of just the recurrent matmul h@W [B,H]x[H,4H]
  cell        — full LSTM cell per step (x-proj precomputed, the
                dynamic_lstm formulation)
  cell_2layer — BOTH stacked layers inside ONE scan body (halves the
                sequential step count vs two back-to-back layer scans)
  fused       — the Pallas fused kernel at this shape (outside its
                eligibility window; measured here to decide whether the
                window should extend to small cells)
Plus the in-framework bench number for the same cell as reference.

Run on TPU: python experiments/exp_lstm_smallcell.py
"""
import time

import numpy as np

import jax
import jax.numpy as jnp

B, H, T, E = 64, 256, 100, 128
REPS = 20


def timeit(f, *args):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    best = 1e9
    for _ in range(3):
        t0 = time.perf_counter()
        r = f(*args)
        np.asarray(jax.tree.leaves(r)[0].ravel()[0])
        best = min(best, (time.perf_counter() - t0) / REPS)
    return best


def chain(step_fn, x0, xs):
    """fwd+bwd through REPS chained scans; grads consumed with a real
    (tiny) dependence so nothing is DCE'd."""

    @jax.jit
    def run(x0, xs):
        def loss(x0, xs):
            def body(c, x):
                c = step_fn(c, x)
                return c, c
            c, ys = jax.lax.scan(body, x0, xs)
            return jnp.sum(ys.astype(jnp.float32) * 1e-3)

        def outer(carry, _):
            x0, xs = carry
            l, (dx0, dxs) = jax.value_and_grad(loss, argnums=(0, 1))(x0, xs)
            eps = jnp.asarray(1e-12, x0.dtype)
            return (x0 + eps * dx0, xs + eps * dxs), l

        (_, _), ls = jax.lax.scan(outer, (x0, xs), None, length=REPS)
        return ls[-1]

    return run


def main():
    rng = np.random.RandomState(0)
    dt = jnp.bfloat16
    h0 = jnp.asarray(rng.randn(B, H) * 0.1, dt)
    xp = jnp.asarray(rng.randn(T, B, 4 * H) * 0.1, dt)  # pre-projected
    w = jnp.asarray(rng.randn(H, 4 * H) / np.sqrt(H), dt)
    w2 = jnp.asarray(rng.randn(H, 4 * H) / np.sqrt(H), dt)
    wx2 = jnp.asarray(rng.randn(H, 4 * H) / np.sqrt(H), dt)

    def lstm_cell(hc, xp_t, w):
        h, c = hc
        g = xp_t + jnp.dot(h, w)
        i, f, o, cand = jnp.split(g.astype(jnp.float32), 4, -1)
        c = jax.nn.sigmoid(f) * c.astype(jnp.float32) \
            + jax.nn.sigmoid(i) * jnp.tanh(cand)
        h = jax.nn.sigmoid(o) * jnp.tanh(c)
        return h.astype(xp_t.dtype), c.astype(xp_t.dtype)

    results = {}
    # trivial floor
    results["scan_floor"] = timeit(
        chain(lambda c, x: c + x * jnp.asarray(1e-6, dt), h0, xp[..., :H]),
        h0, xp[..., :H])
    # matmul only
    results["matmul_only"] = timeit(
        chain(lambda c, x: (x[..., :H]
                            + jnp.dot(c, w)[..., :H]).astype(dt), h0,
              xp), h0, xp)

    # full cell (state packed in one array to keep chain() simple)
    def cell_step(s, x):
        h, c = s[..., :H], s[..., H:]
        h, c = lstm_cell((h, c), x, w)
        return jnp.concatenate([h, c], -1)

    s0 = jnp.concatenate([h0, h0], -1)
    results["cell"] = timeit(chain(cell_step, s0, xp), s0, xp)

    # two stacked layers in ONE scan body
    def cell2_step(s, x):
        h1, c1, h2, c2 = (s[..., :H], s[..., H:2 * H],
                          s[..., 2 * H:3 * H], s[..., 3 * H:])
        h1, c1 = lstm_cell((h1, c1), x, w)
        xp2 = jnp.dot(h1, wx2)
        h2, c2 = lstm_cell((h2, c2), xp2, w2)
        return jnp.concatenate([h1, c1, h2, c2], -1)

    s20 = jnp.concatenate([h0] * 4, -1)
    results["cell_2layer"] = timeit(chain(cell2_step, s20, xp), s20, xp)

    for k, v in results.items():
        toks = B * T / v
        print(f"{k:12s}: {v*1e3:7.2f} ms/seq  per-step "
              f"{v/T*1e6:6.1f} us  ({toks/1e3:7.0f}k tok-steps/s)",
              flush=True)

    # the Pallas fused kernel at this (out-of-window) shape, train config
    from paddle_tpu.ops import pallas_kernels as pk

    mask = jnp.ones((T, B), dt)

    def fused_loss(xp_, h0_):
        del h0_  # lstm_fused is zero-boot, matching the bench model
        h_seq, _ = pk.lstm_fused(xp_, mask, w)
        return jnp.sum(h_seq.astype(jnp.float32) * 1e-3)

    def scan_loss(xp_, h0_):
        z = jnp.zeros_like(h0_)
        def body(sc, x):
            h, c = sc
            h, c = lstm_cell((h, c), x, w)
            return (h, c), h
        (_, _), h_seq = jax.lax.scan(body, (z, z), xp_)
        return jnp.sum(h_seq.astype(jnp.float32) * 1e-3)

    for name, lf in (("fused_kernel", fused_loss), ("scan_kernel",
                                                    scan_loss)):
        @jax.jit
        def run(xp_, h0_, lf=lf):
            def outer(carry, _):
                xp_, h0_ = carry
                l, (dxp, dh0) = jax.value_and_grad(lf, (0, 1))(xp_, h0_)
                eps = jnp.asarray(1e-12, dt)
                return (xp_ + eps * dxp, h0_ + eps * dh0), l
            (_, _), ls = jax.lax.scan(outer, (xp_, h0_), None, length=REPS)
            return ls[-1]

        try:
            t = timeit(run, xp, h0)
            print(f"{name:12s}: {t*1e3:7.2f} ms/seq  per-step "
                  f"{t/T*1e6:6.1f} us", flush=True)
        except Exception as e:  # noqa: BLE001
            print(f"{name}: failed ({str(e)[:120]})", flush=True)


if __name__ == "__main__":
    main()
