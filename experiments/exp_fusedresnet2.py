"""Ablation: where does the fused conv+BN protocol's time go?

Variants (same process, interleaved):
  unfused   — baseline conv2d+batch_norm graph
  proto4d   — raw-stats protocol, 4-D conv_general formulation (default)
  proto2d   — protocol with every eligible 1x1 conv as a 2-D jnp dot
              (fused_conv_dot_max_n=inf): isolates the relayout cost
  pallas    — 2-D dispatch through the hand-written Pallas kernel
Each timed fwd-only and full-train.

Run on TPU: python experiments/exp_fusedresnet2.py
"""
import os
import time

import numpy as np

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.flags import FLAGS

BATCH = int(os.environ.get("BATCH", 128))
STEPS = int(os.environ.get("STEPS", 30))


def build(fused, train, dot_max_n=0, pallas=False):
    FLAGS.use_fused_conv = fused
    FLAGS.fused_conv_dot_max_n = dot_max_n
    FLAGS.fused_conv_pallas = pallas
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        img = pt.layers.data("img", shape=[224, 224, 3])
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.resnet_imagenet(img, class_dim=1000,
                                        data_format="NHWC")
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        if train:
            pt.optimizer.Momentum(learning_rate=0.1,
                                  momentum=0.9).minimize(loss)
    prog.set_amp("bfloat16")
    return prog, startup, loss


def main():
    import jax

    rng = np.random.RandomState(0)
    feed = {
        "img": rng.randn(BATCH, 224, 224, 3).astype(np.float32),
        "label": rng.randint(0, 1000, (BATCH, 1)).astype(np.int32),
    }
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for v in feed.values():
        np.asarray(v.ravel()[0])

    BIG = 1 << 30
    configs = {}
    for train in (False, True):
        t = "train" if train else "fwd"
        configs[f"unfused-{t}"] = (False, train, 0, False)
        configs[f"proto4d-{t}"] = (True, train, 0, False)
        configs[f"proto2d-{t}"] = (True, train, BIG, False)
        configs[f"pallas-{t}"] = (True, train, BIG, True)

    exe = pt.Executor(donate_state=True)
    variants = {}
    for name, cfg in configs.items():
        # the op kernels read the dispatch FLAGS at TRACE time (the first
        # exe.run), so each variant must build AND warm before the next
        # variant's flags are set
        prog, startup, loss = build(*cfg)
        exe.run(startup)
        for _ in range(2):
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
        assert np.isfinite(l), f"{name}: loss {l}"
        print(f"compiled {name}: loss {float(l):.4f}", flush=True)
        variants[name] = (prog, startup, loss)

    for rep in range(2):
        for name, (prog, startup, loss) in variants.items():
            t0 = time.perf_counter()
            for _ in range(STEPS):
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                               return_numpy=False)
            float(np.asarray(l))
            dt = (time.perf_counter() - t0) / STEPS
            print(f"rep{rep} {name}: {dt*1e3:.1f} ms/step", flush=True)


if __name__ == "__main__":
    main()
