"""Prototype: fused 1x1-conv(matmul) + BN chain in Pallas vs XLA.

ResNet's 1x1 convs are ~46% of its FLOPs and each is chased by a BatchNorm
whose stats pass + normalize pass re-read/re-write the whole activation
(PERF.md: BN costs ~34% of the step). This prototype fuses, per layer:
  prologue: x_norm = relu((x - mean) * inv * gamma + beta)   [prev BN]
  matmul:   y = x_norm @ W                                   [MXU]
  epilogue: per-channel sum/sumsq of y accumulated across row tiles
so each layer reads x once and writes y once; the stats for layer k's BN
come out of layer k's kernel for free and are APPLIED inside layer k+1's
prologue. Chain of L layers, ResNet stage-3-like shapes.

Run on TPU: python experiments/exp_fusedbn.py
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def timeit(f, *args, reps=1):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / reps


# ---------------------------------------------------------------- kernels --
def _fused_kernel(x_ref, w_ref, mean_ref, inv_ref, g_ref, b_ref,
                  y_ref, sum_ref, sq_ref, acc_sum, acc_sq, *, apply_bn):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_sum[:] = jnp.zeros_like(acc_sum)
        acc_sq[:] = jnp.zeros_like(acc_sq)

    x = x_ref[:].astype(jnp.float32)
    if apply_bn:
        xn = (x - mean_ref[:]) * inv_ref[:] * g_ref[:] + b_ref[:]
        xn = jnp.maximum(xn, 0.0)
    else:
        xn = x
    y = jnp.dot(xn.astype(jnp.bfloat16), w_ref[:],
                preferred_element_type=jnp.float32)
    y_ref[:] = y.astype(y_ref.dtype)
    acc_sum[:] = acc_sum[:] + jnp.sum(y, axis=0, keepdims=True)
    acc_sq[:] = acc_sq[:] + jnp.sum(y * y, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        sum_ref[:] = acc_sum[:]
        sq_ref[:] = acc_sq[:]


def fused_layer(x, w, stats, gamma, beta, apply_bn, block_rows=1024):
    """One fused layer. stats = (mean[C], inv[C]) of x (None for first).
    Returns y [N, Cout] bf16 and (sum[Cout], sumsq[Cout]) of y."""
    N, Cin = x.shape
    Cout = w.shape[1]
    mean, inv = stats if stats is not None else (
        jnp.zeros((1, Cin), jnp.float32), jnp.ones((1, Cin), jnp.float32))
    grid = (N // block_rows,)
    y, s, sq = pl.pallas_call(
        functools.partial(_fused_kernel, apply_bn=apply_bn),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_rows, Cin), lambda i: (i, 0)),
            pl.BlockSpec((Cin, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cin), lambda i: (0, 0)),
            pl.BlockSpec((1, Cin), lambda i: (0, 0)),
            pl.BlockSpec((1, Cin), lambda i: (0, 0)),
            pl.BlockSpec((1, Cin), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((block_rows, Cout), lambda i: (i, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
            pl.BlockSpec((1, Cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((N, Cout), jnp.bfloat16),
            jax.ShapeDtypeStruct((1, Cout), jnp.float32),
            jax.ShapeDtypeStruct((1, Cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, Cout), jnp.float32),
            pltpu.VMEM((1, Cout), jnp.float32),
        ],
    )(x, w, mean, inv, gamma.reshape(1, -1), beta.reshape(1, -1))
    return y, (s, sq)


def chain_fused(x, ws, gammas, betas, L, N):
    stats = None
    for k in range(L):
        y, (s, sq) = fused_layer(x, ws[k], stats, gammas[k], betas[k],
                                 apply_bn=stats is not None)
        mean = s / N
        var = sq / N - mean * mean
        stats = (mean, jax.lax.rsqrt(var + 1e-5))
        x = y
    # final normalize folded into a mean readout for timing comparability
    return jnp.sum(x.astype(jnp.float32))


def chain_xla(x, ws, gammas, betas, L, N):
    for k in range(L):
        if k > 0:
            x32 = x.astype(jnp.float32)
            m = jnp.mean(x32, 0)
            v = jnp.var(x32, 0)
            x = (jnp.maximum((x32 - m) * jax.lax.rsqrt(v + 1e-5) *
                             gammas[k] + betas[k], 0.0)).astype(jnp.bfloat16)
        x = jnp.dot(x, ws[k], preferred_element_type=jnp.float32
                    ).astype(jnp.bfloat16)
    return jnp.sum(x.astype(jnp.float32))


def main():
    N, C, L = 128 * 28 * 28, 512, 8
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(N, C) * 0.1, jnp.bfloat16)
    ws = [jnp.asarray(rng.randn(C, C) * (1.0 / np.sqrt(C)), jnp.bfloat16)
          for _ in range(L)]
    gs = [jnp.ones((C,), jnp.float32) for _ in range(L)]
    bs = [jnp.zeros((C,), jnp.float32) for _ in range(L)]

    # correctness cross-check on small shapes first (CPU interpret would
    # diverge in perf but here both run on TPU)
    fx = jax.jit(lambda x: chain_xla(x, ws, gs, bs, L, N))
    ff = jax.jit(lambda x: chain_fused(x, ws, gs, bs, L, N))
    a = float(np.asarray(fx(x)))
    b = float(np.asarray(ff(x)))
    print(f"xla={a:.1f} fused={b:.1f} rel-diff={abs(a-b)/max(abs(a),1):.2e}",
          flush=True)

    REPS = 20

    def many(f):
        # carry in x's dtype (an f32 carry would promote the bf16 input)
        # and a real (tiny) dependence so nothing is folded away
        @jax.jit
        def run(x):
            def body(xc, _):
                l = f(xc)
                return xc * jnp.asarray(1.0, xc.dtype) + jnp.asarray(
                    1e-12, xc.dtype) * l.astype(xc.dtype), l
            xc, ls = jax.lax.scan(body, x, None, length=REPS)
            return ls[-1]
        return run

    t_x = timeit(many(lambda x: chain_xla(x, ws, gs, bs, L, N)), x, reps=REPS)
    t_f = timeit(many(lambda x: chain_fused(x, ws, gs, bs, L, N)), x, reps=REPS)
    fl = 2 * N * C * C * L
    print(f"XLA chain:   {t_x*1e3:7.2f} ms  {fl/t_x/1e12:5.1f} TF/s")
    print(f"fused chain: {t_f*1e3:7.2f} ms  {fl/t_f/1e12:5.1f} TF/s "
          f"(speedup {t_x/t_f:.2f}x)")


if __name__ == "__main__":
    main()
