"""Decompose the LSTM headline step (h512 bs128 T100 bf16, 2-layer
stacked, fused kernels) into its bound parts, for the PERF.md ceiling
model. Measures, same-process chained:

  A. full bench-equivalent train step (staged feed, Adam)
  B. the recurrence alone: 2x lstm_fused fwd+bwd (jax.grad through both
     layers + inter-layer projection, dgates consumed)
  C. the batched remainder: embedding + x-projection + logits head + CE
     + Adam on a precomputed recurrence output (what A minus B leaves)

Per-grid-step latency = B / (4*T grid steps + the bwd's batched
recompute); the ceiling statement lives in PERF.md "Round 5: the
headline ceiling model".
Run on TPU: python experiments/exp_lstm_ceiling.py
"""
import os
import time

import numpy as np

STEPS = int(os.environ.get("STEPS", 60))
T, B, H, E, V = 100, 128, 512, 128, 30000


def timed(fn, *args):
    import jax

    out = fn(*args)
    jax.tree.leaves(out)
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])
    t0 = time.perf_counter()
    for _ in range(STEPS):
        out = fn(*args)
    np.asarray(jax.tree.leaves(out)[0].ravel()[0])
    return (time.perf_counter() - t0) / STEPS


def main():
    import jax
    import jax.numpy as jnp

    from paddle_tpu.ops import pallas_kernels as pk

    rng = np.random.RandomState(0)
    dt = jnp.bfloat16
    toks = jnp.asarray(rng.randint(0, V, (B, T)), jnp.int32)
    emb = jnp.asarray(rng.randn(V, E) * 0.1, dt)
    wx1 = jnp.asarray(rng.randn(E, 4 * H) * 0.02, dt)
    w1 = jnp.asarray(rng.randn(H, 4 * H) * 0.02, dt)
    wx2 = jnp.asarray(rng.randn(H, 4 * H) * 0.02, dt)
    w2 = jnp.asarray(rng.randn(H, 4 * H) * 0.02, dt)
    wo = jnp.asarray(rng.randn(H, 2) * 0.02, dt)
    mask = jnp.ones((T, B), jnp.float32)
    labels = jnp.asarray(rng.randint(0, 2, (B,)), jnp.int32)

    # B: recurrence alone (2 fused kernels + inter-layer matmul),
    # fwd+bwd with the gradient consumed
    @jax.jit
    def recurrence(x_tbh, w1, wx2, w2):
        def f(x_tbh, w1, wx2, w2):
            h1, _ = pk.lstm_fused(x_tbh, mask, w1)
            xp2 = jnp.dot(h1, wx2,
                          preferred_element_type=jnp.float32).astype(dt)
            h2, _ = pk.lstm_fused(xp2, mask, w2)
            return jnp.sum(h2.astype(jnp.float32) ** 2)
        l, g = jax.value_and_grad(f, argnums=(0, 1, 2, 3))(
            x_tbh, w1, wx2, w2)
        return l, g

    x_tbh = jnp.asarray(rng.randn(T, B, 4 * H) * 0.1, dt)
    t_rec = timed(recurrence, x_tbh, w1, wx2, w2)

    # A: the full step (embedding + proj + recurrence + head + CE),
    # grads for all weights, SGD-style update (optimizer cost ~Adam's
    # elementwise pass; exact optimizer choice is noise at this size)
    @jax.jit
    def full(params):
        def loss_fn(p):
            e = p["emb"][toks]                          # [B, T, E]
            x = jnp.einsum("bte,ek->tbk", e.astype(dt), p["wx1"]).astype(dt)
            h1, _ = pk.lstm_fused(x, mask, p["w1"])
            xp2 = jnp.dot(h1, p["wx2"],
                          preferred_element_type=jnp.float32).astype(dt)
            h2, _ = pk.lstm_fused(xp2, mask, p["w2"])
            logits = jnp.dot(h2[-1].astype(jnp.float32),
                             p["wo"].astype(jnp.float32))
            lse = jax.nn.logsumexp(logits, -1)
            return jnp.mean(lse - logits[jnp.arange(B), labels])
        l, g = jax.value_and_grad(loss_fn)(params)
        return l, jax.tree.map(lambda p, gg: p - 1e-3 * gg.astype(p.dtype),
                               params, g)

    params = {"emb": emb, "wx1": wx1, "w1": w1, "wx2": wx2, "w2": w2,
              "wo": wo}
    t_full = timed(full, params)

    # C: batched remainder (same graph, recurrence replaced by its
    # input reshaped — isolates emb/proj/head/update cost)
    @jax.jit
    def batched_only(params):
        def loss_fn(p):
            e = p["emb"][toks]
            x = jnp.einsum("bte,ek->tbk", e.astype(dt), p["wx1"]).astype(dt)
            h2 = jnp.tanh(x[..., :H])   # stand-in, no recurrence
            xp2 = jnp.dot(h2, p["wx2"],
                          preferred_element_type=jnp.float32).astype(dt)
            logits = jnp.dot(xp2[-1, :, :H].astype(jnp.float32),
                             p["wo"].astype(jnp.float32))
            lse = jax.nn.logsumexp(logits, -1)
            return jnp.mean(lse - logits[jnp.arange(B), labels])
        l, g = jax.value_and_grad(loss_fn)(params)
        return l, jax.tree.map(lambda p, gg: p - 1e-3 * gg.astype(p.dtype),
                               params, g)

    t_batched = timed(batched_only, params)

    grid_steps = 4 * T  # 2 layers x (fwd + bwd) kernels, grid=(T,)
    print(f"full step:        {t_full*1e3:7.2f} ms "
          f"({B*T/t_full/1e3:.0f}k tok/s)")
    print(f"recurrence alone: {t_rec*1e3:7.2f} ms "
          f"({100*t_rec/t_full:.0f}% of full)")
    print(f"batched parts:    {t_batched*1e3:7.2f} ms")
    print(f"per-grid-step latency ~ {t_rec/grid_steps*1e6:.1f} us "
          f"({grid_steps} sequential kernel grid steps)")


if __name__ == "__main__":
    main()
