"""NMT (seq2seq-attention) per-component breakdown on the real chip.

VERDICT r2 weak #2: the NMT number needs ResNet-grade rigor. Strategy:
time the FULL train step and ablations in ONE process (relative numbers
are robust to the tunnel's day-to-day drift — PERF.md), attributing the
step to encoder / decoder scan / output projection / fused-GRU effect.

Variants:
  full          the bench model (bi-GRU enc + attention GRU dec + 30k out)
  scan_enc      full, FLAGS.use_fused_rnn=0 (encoder GRUs on lax.scan)
  plain_dec     attention decoder replaced by a plain dynamic_gru
                (drops: per-step attention, input-feed concat)
  no_out        full minus the [512, 30k] output projection + 30k CE
  enc_only      encoder + pooled loss only (no decoder, no projection)

Writes benchmarks/nmt_breakdown.json.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np

import jax

BATCH = int(os.environ.get("BENCH_BATCH", 128))
SEQLEN = 50
HIDDEN = 512
VOCAB = 30000
STEPS = 30


def build(variant):
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray

    pt.reset()
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 3
    with pt.program_guard(prog, startup):
        src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                             append_batch_size=False)
        trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                                lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        if variant in ("full", "scan_enc", "no_out"):
            import paddle_tpu.layers as L
            from paddle_tpu.models.seq2seq import _encoder

            enc, boot_src = _encoder(src, VOCAB, HIDDEN, HIDDEN, SEQLEN, "s2s")
            boot = L.fc(boot_src, size=HIDDEN, act="tanh",
                        param_attr="s2s.boot_w", bias_attr="s2s.boot_b")
            trg_emb = L.embedding(trg_in, size=[VOCAB, HIDDEN],
                                  param_attr="s2s.trg_emb")
            dec_h = L.attention_gru_decoder(
                enc, trg_emb, boot, size=HIDDEN, src_max_len=SEQLEN,
                trg_max_len=SEQLEN, name="s2s.dec")
            if variant == "no_out":
                tok_loss = pt.layers.elementwise_mul(dec_h, dec_h)
            else:
                logits = L.fc(dec_h, size=VOCAB, param_attr="s2s.out_w",
                              bias_attr="s2s.out_b")
                tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
        elif variant == "plain_dec":
            import paddle_tpu.layers as L
            from paddle_tpu.models.seq2seq import _encoder

            enc, _ = _encoder(src, VOCAB, HIDDEN, HIDDEN, SEQLEN, "s2s")
            trg_emb = L.embedding(trg_in, size=[VOCAB, HIDDEN],
                                  param_attr="s2s.trg_emb")
            proj = L.fc(trg_emb, size=3 * HIDDEN, bias_attr=False)
            dec_h = L.dynamic_gru(proj, size=HIDDEN, max_len=SEQLEN)
            logits = L.fc(dec_h, size=VOCAB, param_attr="s2s.out_w",
                          bias_attr="s2s.out_b")
            tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
        elif variant == "enc_only":
            from paddle_tpu.models.seq2seq import _encoder

            enc, _ = _encoder(src, VOCAB, HIDDEN, HIDDEN, SEQLEN, "s2s")
            tok_loss = pt.layers.elementwise_mul(enc, enc)
        loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
        pt.optimizer.Adam(learning_rate=5e-4).minimize(loss)
    prog.set_amp("bfloat16")

    from paddle_tpu.flags import FLAGS

    FLAGS.use_fused_rnn = variant != "scan_enc"

    rng = np.random.RandomState(0)
    pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
        seqs, capacity=BATCH * SEQLEN, max_seqs=BATCH)
    seqs = lambda: [rng.randint(2, VOCAB, (SEQLEN,)).astype(np.int32)  # noqa: E731
                    for _ in range(BATCH)]
    feed = {"src": pack(seqs()), "trg_in": pack(seqs()),
            "label": pack(seqs())}
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    exe = pt.Executor(donate_state=True)
    exe.run(startup)
    return exe, prog, loss, feed


def timeit(variant):
    exe, prog, loss, feed = build(variant)
    for _ in range(3):
        (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
    assert np.isfinite(float(np.asarray(l))), variant
    t0 = time.perf_counter()
    for _ in range(STEPS):
        (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                       return_numpy=False)
    float(np.asarray(l))  # d2h forces the chain
    dt = (time.perf_counter() - t0) / STEPS
    toks = BATCH * SEQLEN / dt
    print({variant: f"{dt*1e3:.2f} ms/step, {toks/1e3:.0f}k tok/s"},
          flush=True)
    return dt


if __name__ == "__main__":
    rows = {}
    for v in ("full", "scan_enc", "plain_dec", "no_out", "enc_only"):
        rows[v] = timeit(v)
    full = rows["full"]
    out = {
        "config": {"batch": BATCH, "seqlen": SEQLEN, "hidden": HIDDEN,
                   "vocab": VOCAB, "steps": STEPS},
        "ms_per_step": {k: round(v * 1e3, 3) for k, v in rows.items()},
        "attribution_ms": {
            "fused_gru_encoder_saving": round(
                (rows["scan_enc"] - full) * 1e3, 3),
            "attention_plus_input_feed": round(
                (full - rows["plain_dec"]) * 1e3, 3),
            "output_proj_and_30k_ce": round(
                (full - rows["no_out"]) * 1e3, 3),
            "encoder_alone": round(rows["enc_only"] * 1e3, 3),
        },
        "tokens_per_sec_full": round(BATCH * SEQLEN / full, 1),
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks", "nmt_breakdown.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
