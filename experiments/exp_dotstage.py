"""Stage-selective dot dispatch sweep for the fused conv protocol.

XLA's conv emitter loses ~2x to a plain dot at late-stage shapes
(exp_protomicro: 2048->512 convgen 15.4ms vs dot 8.4ms) while early
stages prefer convs (relayout cost scales with tensor size). Sweep the
N-threshold below which the protocol's 1x1 convs run as 2-D dots
(PT_FUSED_CONV_DOT_MAX_N), with and without the Pallas kernel.

Run on TPU: python experiments/exp_dotstage.py
"""
import os
import time

import numpy as np

BATCH = 128
STEPS = 30


def build():
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.flags import FLAGS

    FLAGS.use_fused_conv = True
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        img = pt.layers.data("img", shape=[224, 224, 3])
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.resnet_imagenet(img, class_dim=1000,
                                        data_format="NHWC")
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    prog.set_amp("bfloat16")
    return prog, startup, loss


def main():
    import jax

    import paddle_tpu as pt

    rng = np.random.RandomState(0)
    feed = {
        "img": rng.randn(BATCH, 224, 224, 3).astype(np.float32),
        "label": rng.randint(0, 1000, (BATCH, 1)).astype(np.int32),
    }
    feed = {k: jax.device_put(v) for k, v in feed.items()}
    for v in feed.values():
        np.asarray(v.ravel()[0])

    # (dot_max_n, pallas): 6272 = stage5 only; 25088 = stages 4+5;
    # 100352 = stages 3+4+5
    from paddle_tpu.flags import FLAGS

    configs = [(0, "0"), (6272, "0"), (25088, "0"), (100352, "0"),
               (25088, "1"), (6272, "1")]
    variants = {}
    exe = pt.Executor(donate_state=True)
    for thr, pal in configs:
        # the op kernel reads these FLAGS at trace time (first run below)
        FLAGS.fused_conv_dot_max_n = thr
        FLAGS.fused_conv_pallas = pal == "1"
        prog, startup, loss = build()
        exe.run(startup)
        for _ in range(2):
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
        assert np.isfinite(l)
        variants[(thr, pal)] = (prog, loss)
        print(f"compiled thr={thr} pallas={pal}: loss {float(l):.4f}",
              flush=True)

    for rep in range(2):
        for (thr, pal), (prog, loss) in variants.items():
            t0 = time.perf_counter()
            for _ in range(STEPS):
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                               return_numpy=False)
            float(np.asarray(l))
            dt = (time.perf_counter() - t0) / STEPS
            print(f"rep{rep} thr={thr:6d} pallas={pal}: {dt*1e3:6.1f} "
                  f"ms/step ({BATCH/dt:.0f} img/s)", flush=True)


if __name__ == "__main__":
    main()
