"""dp-scaling record on the 8-virtual-device CPU mesh — the preparable
analogue of the reference's 4-GPU scaling table (benchmark/README.md:
72-96, AlexNet 3.85x at 4 GPUs).

THE CAVEAT, written down: the virtual devices timeshare ONE physical
core, so dpN runs N per-shard programs serially on that core — the
measured drop vs dp1 (0.77/0.65/0.44 at dp2/4/8) is per-shard
amortization (each program runs batch 64/N, which vectorizes worse)
plus collective overhead, NOT hardware scaling. Real multi-chip
scaling needs the hardware (BASELINE.json north star: v5e-16); this
artifact proves the sharded program runs end-to-end at every dp and
regression-guards it (tests/test_bench_mesh.py::
test_dp_scaling_efficiency_floor, floor 0.3 — an accidental full
replication would land ~8x under dp1, far below it).

Run (CPU): python experiments/exp_mesh_scaling.py
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

MODEL_ENV = {
    "BENCH_MODEL": "lstm", "BENCH_BATCH": "64", "BENCH_HIDDEN": "256",
    "BENCH_SEQLEN": "16", "BENCH_STEPS": "6", "BENCH_AMP": "0",
    "BENCH_CALIBRATE": "0",
}


def run_dp(dp):
    env = dict(os.environ)
    env.update(MODEL_ENV)
    env.update({
        "JAX_PLATFORMS": "cpu",
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8",
        "PYTHONPATH": REPO + os.pathsep + env.get("PYTHONPATH", ""),
    })
    if dp > 1:
        env["BENCH_MESH"] = f"dp{dp}"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=1200)
    if r.returncode != 0:
        return {"error": (r.stderr or r.stdout)[-400:]}
    return json.loads(r.stdout.strip().splitlines()[-1])


def main():
    rows = []
    base = None
    for dp in (1, 2, 4, 8):
        rec = run_dp(dp)
        val = rec.get("value")
        if dp == 1:
            base = val
        rows.append({
            "dp": dp, "tokens_per_sec": val,
            "efficiency_vs_dp1": (round(val / base, 3)
                                  if val and base else None),
        })
        print(json.dumps(rows[-1]), flush=True)
    out = {
        "note": ("8-virtual-device CPU mesh, fixed global batch: devices "
                 "timeshare one host, so ideal = FLAT throughput; "
                 "efficiency measures GSPMD sharding overhead, not "
                 "hardware speedup (see module docstring). Reference "
                 "analogue: benchmark/README.md:72-96 4-GPU columns."),
        "model": MODEL_ENV,
        "rows": rows,
    }
    with open(os.path.join(REPO, "benchmarks", "mesh_scaling.json"),
              "w") as f:
        json.dump(out, f, indent=1)
    print("written benchmarks/mesh_scaling.json")


if __name__ == "__main__":
    main()
