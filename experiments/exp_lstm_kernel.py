"""Microbench: Pallas fused LSTM vs lax.scan, isolated recurrence, real TPU.

Writes benchmarks/lstm_kernel_microbench.json (the VERDICT-required
evidence for defaulting the fused kernel on). Timing note: the axon
tunnel's d2h readback costs ~100-200 ms, so each timed region chains many
iterations inside one jit and reads a scalar once (see PERF.md).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import pallas_kernels
from paddle_tpu.ops.rnn_ops import lstm_scan


def timeit(f, *args, reps=1):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / reps


def bench(T, B, H, dtype, reps=30):
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(T, B, 4 * H) * 0.1, dtype)
    w = jnp.asarray(rng.randn(H, 4 * H) * 0.05, dtype)
    mask = jnp.ones((T, B), jnp.float32)

    def many(core):
        # chain `reps` evaluations; the carry must REALLY depend on the
        # gradients (tiny nonzero scale, same dtype) or XLA dead-code
        # eliminates the whole backward pass — `x + 0.0 * dx` gets folded
        # and the "fwd+bwd" bench silently times forward only
        def loss(x, w):
            h_seq, (hT, cT) = core(x, mask, w)
            return jnp.sum(hT.astype(jnp.float32))

        @jax.jit
        def run(x, w):
            def body(carry, _):
                x, w = carry
                l, (dx, dw) = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
                eps = jnp.asarray(1e-12, x.dtype)
                return (x + eps * dx, w + eps * dw), l
            (x, w), ls = jax.lax.scan(body, (x, w), None, length=reps)
            return ls[-1]
        return run

    scan_core = lambda x, m, w: lstm_scan(x, m, w, None)
    fused_core = lambda x, m, w: pallas_kernels.lstm_fused(x, m, w)
    t_scan = timeit(many(scan_core), x, w, reps=reps)
    t_fused = timeit(many(fused_core), x, w, reps=reps)
    flops = 3 * 2 * T * B * H * 4 * H  # fwd+bwd ~3x; MACs x2
    row = {
        "T": T, "B": B, "H": H, "dtype": str(dtype.__name__),
        "scan_ms": round(t_scan * 1e3, 3),
        "fused_ms": round(t_fused * 1e3, 3),
        "speedup": round(t_scan / t_fused, 3),
        "fused_tflops": round(flops / t_fused / 1e12, 2),
    }
    print(row, flush=True)
    return row


if __name__ == "__main__":
    rows = [
        bench(100, 128, 512, jnp.bfloat16),
        bench(100, 128, 512, jnp.float32),
        bench(200, 128, 256, jnp.bfloat16),
        bench(50, 256, 512, jnp.bfloat16),
    ]
    out = {
        "bench": "fused LSTM recurrence (fwd+bwd) vs lax.scan, one chip",
        "device": str(jax.devices()[0].device_kind),
        "method": "chained in-jit reps, single d2h readback",
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "lstm_kernel_microbench.json")
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
