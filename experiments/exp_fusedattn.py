"""In-framework A/B: fused Bahdanau decoder vs XLA scan, NMT train.

Same-process interleaved (PERF.md methodology), bs 128 and 256.
Run on TPU: python experiments/exp_fusedattn.py
"""
import os
import time

import numpy as np

STEPS = int(os.environ.get("STEPS", 60))
SEQLEN = 50


def build(fused, batch):
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray
    from paddle_tpu.flags import FLAGS

    FLAGS.use_fused_attention = fused
    vocab, hidden = 30000, 512
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                             append_batch_size=False)
        trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                                lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        logits = models.seq2seq_attention(
            src, trg_in, src_vocab=vocab, trg_vocab=vocab,
            emb_dim=hidden, enc_hidden=hidden, dec_hidden=hidden,
            src_max_len=SEQLEN, trg_max_len=SEQLEN)
        tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
        loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
        pt.optimizer.Adam(learning_rate=5e-4).minimize(loss)
    prog.set_amp("bfloat16")
    rng = np.random.RandomState(0)
    pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
        seqs, capacity=batch * SEQLEN, max_seqs=batch)
    seqs = [rng.randint(2, vocab, (SEQLEN,)).astype(np.int32)
            for _ in range(batch)]
    feed = {"src": pack(seqs), "trg_in": pack(seqs), "label": pack(seqs)}
    return prog, startup, loss, feed


def main():
    import jax

    import paddle_tpu as pt

    exe = pt.Executor(donate_state=True)
    for batch in (128, 256):
        variants = {}
        for fused in (False, True):
            prog, startup, loss, feed = build(fused, batch)
            feed = {k: jax.device_put(v) for k, v in feed.items()}
            for v in feed.values():
                for leaf in jax.tree.leaves(v):
                    np.asarray(leaf.ravel()[0])
            exe.run(startup)
            for _ in range(3):
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            assert np.isfinite(l), f"fused={fused} loss {l}"
            variants[fused] = (prog, loss, feed, float(l))
        print(f"bs={batch} warm losses: unfused={variants[False][3]:.3f} "
              f"fused={variants[True][3]:.3f}", flush=True)
        res = {False: [], True: []}
        for rep in range(3):
            for fused in (False, True):
                prog, loss, feed, _ = variants[fused]
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                                   return_numpy=False)
                float(np.asarray(l))
                dt = (time.perf_counter() - t0) / STEPS
                res[fused].append(dt)
                toks = batch * SEQLEN / dt
                print(f"bs={batch} rep{rep} fused={int(fused)}: "
                      f"{dt*1e3:6.1f} ms/step {toks/1e3:7.1f}k tok/s",
                      flush=True)
        mu = sorted(res[False])[1]
        mf = sorted(res[True])[1]
        print(f"bs={batch}: speedup {mu/mf:.3f}x "
              f"({batch*SEQLEN/mu/1e3:.1f}k -> {batch*SEQLEN/mf/1e3:.1f}k "
              f"tok/s)", flush=True)


if __name__ == "__main__":
    main()
