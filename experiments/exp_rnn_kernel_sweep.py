"""Microbench: fused LSTM **and GRU** (fwd+bwd) vs lax.scan across H, real TPU.

Round 3: the GRU now has a hand-written reverse-time backward kernel and
both cells have an outer-einsum dW path past H=640, so the eligibility
windows must be re-measured — including the NMT config (H=512) and the
reference's largest published config (H=1280,
/root/reference/benchmark/README.md:129-136).

Writes benchmarks/rnn_kernel_microbench.json. Timing per PERF.md: chained
in-jit reps, DCE-proof grad consumption, single d2h scalar readback.
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops import pallas_kernels
from paddle_tpu.ops.rnn_ops import gru_scan, lstm_scan


def timeit(f, *args):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return time.perf_counter() - t0


def bench(cell, T, B, H, dtype, reps=30):
    rng = np.random.RandomState(0)
    G = 4 if cell == "lstm" else 3
    x = jnp.asarray(rng.randn(T, B, G * H) * 0.1, dtype)
    w = jnp.asarray(rng.randn(H, G * H) * 0.05, dtype)
    mask = jnp.ones((T, B), jnp.float32)

    def many(core):
        def loss(x, w):
            out = core(x, mask, w)
            hT = out[1][0] if cell == "lstm" else out[1]
            return jnp.sum(hT.astype(jnp.float32))

        @jax.jit
        def run(x, w):
            def body(carry, _):
                x, w = carry
                l, (dx, dw) = jax.value_and_grad(loss, argnums=(0, 1))(x, w)
                eps = jnp.asarray(1e-12, x.dtype)  # DCE-proof (PERF.md)
                return (x + eps * dx, w + eps * dw), l
            (x, w), ls = jax.lax.scan(body, (x, w), None, length=reps)
            return ls[-1]
        return run

    if cell == "lstm":
        scan_core = lambda x, m, w: lstm_scan(x, m, w, None)  # noqa: E731
        fused_core = lambda x, m, w: pallas_kernels.lstm_fused(x, m, w)  # noqa: E731
    else:
        scan_core = lambda x, m, w: gru_scan(x, m, w, None)  # noqa: E731
        fused_core = lambda x, m, w: pallas_kernels.gru_fused(x, m, w)  # noqa: E731

    row = {"cell": cell, "T": T, "B": B, "H": H, "dtype": dtype.__name__}
    try:
        t_fused = timeit(many(fused_core), x, w) / reps
    except Exception as e:  # noqa: BLE001 — record compile failures as data
        row["fused_error"] = str(e).split("\n")[0][:200]
        t_fused = None
    t_scan = timeit(many(scan_core), x, w) / reps
    flops = 3 * 2 * T * B * H * G * H
    row["scan_ms"] = round(t_scan * 1e3, 3)
    if t_fused:
        row.update(
            fused_ms=round(t_fused * 1e3, 3),
            speedup=round(t_scan / t_fused, 3),
            fused_tflops=round(flops / t_fused / 1e12, 2),
        )
    print(row, flush=True)
    return row


if __name__ == "__main__":
    rows = []
    for H in (128, 256, 384, 512, 640, 768, 1024, 1280):
        rows.append(bench("gru", 100, 128, H, jnp.bfloat16))
    for H in (512, 768, 1024, 1280):
        rows.append(bench("lstm", 100, 128, H, jnp.bfloat16))
    # the reference's largest published LSTM config: h=1280 bs=256
    rows.append(bench("lstm", 100, 256, 1280, jnp.bfloat16))
    out = {
        "bench": "fused recurrence (fwd+bwd, hand-written bwd kernels) vs "
                 "lax.scan, one chip",
        "device": str(jax.devices()[0].device_kind),
        "method": "chained in-jit reps, single d2h readback, DCE-proof",
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "rnn_kernel_microbench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
