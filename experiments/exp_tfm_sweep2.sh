#!/bin/bash
# Transformer MFU sweep round 2: configs sized to fit 15.75G HBM.
# f32 Adam state on params is the floor: 16 B/param + bf16 copy 2 B/param.
cd /root/repo
OUT=experiments/tfm_sweep2.log
: > $OUT
run() {
  echo "=== $* ===" >> $OUT
  timeout 900 env "$@" BENCH_MODEL=transformer python bench.py 2>>$OUT | tail -1 >> $OUT
  echo >> $OUT
}
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=8 BENCH_REMAT=dots
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=8 BENCH_REMAT=full
run BENCH_HIDDEN=2048 BENCH_DEPTH=6 BENCH_BATCH=12 BENCH_REMAT=full
run BENCH_HIDDEN=2048 BENCH_DEPTH=12 BENCH_BATCH=6 BENCH_REMAT=full
echo DONE >> $OUT
