#!/bin/bash
# Transformer MFU sweep (round 3, VERDICT item 1).
# Sequential — never two processes against the axon tunnel at once.
cd /root/repo
OUT=experiments/tfm_sweep.log
: > $OUT
run() {
  echo "=== $* ===" >> $OUT
  timeout 900 env "$@" BENCH_MODEL=transformer python bench.py 2>>$OUT | tail -1 >> $OUT
  echo >> $OUT
}
# r02 baseline repro
run BENCH_HIDDEN=2048 BENCH_DEPTH=12 BENCH_BATCH=4
# bigger batch via remat at same width
run BENCH_HIDDEN=2048 BENCH_DEPTH=12 BENCH_BATCH=8 BENCH_REMAT=dots
run BENCH_HIDDEN=2048 BENCH_DEPTH=12 BENCH_BATCH=16 BENCH_REMAT=full
# wider, fewer layers: best MXU shapes
run BENCH_HIDDEN=4096 BENCH_DEPTH=4 BENCH_BATCH=8 BENCH_REMAT=full
run BENCH_HIDDEN=3072 BENCH_DEPTH=6 BENCH_BATCH=8 BENCH_REMAT=full
echo DONE >> $OUT
