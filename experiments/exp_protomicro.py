"""Per-stage microbench: 1x1-conv+BN chain in four formulations.

  convgen : lax.conv_general_dilated + jnp mean/var BN   (framework baseline)
  dot     : reshape+jnp.dot + jnp mean/var BN            (exp_fusedbn's "XLA")
  proto   : raw-stats protocol in pure jnp (_jnp_fused)
  pallas  : raw-stats protocol through the Pallas kernel

exp_fusedbn measured pallas 1.15x over *dot* — this decides whether that
was a strawman (convgen faster than dot) and where the in-model 2x fwd
regression comes from. Run on TPU: python experiments/exp_protomicro.py
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.fused_conv_ops import (_fused_fn, _jnp_fused,
                                           fused_conv_eligible)

L = 6
REPS = 10

# (B, HW, Cin, Cout) — ResNet-50 bs128 stage shapes (the two 1x1 convs of
# each bottleneck) + the stage-2 small-channel pair
SHAPES = [
    (128, 56, 256, 64),
    (128, 56, 64, 256),
    (128, 28, 512, 128),
    (128, 28, 128, 512),
    (128, 14, 1024, 256),
    (128, 14, 256, 1024),
    (128, 7, 2048, 512),
    (128, 7, 512, 2048),
]


def timeit(f, *args):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / REPS


def many(f):
    @jax.jit
    def run(x):
        def body(xc, _):
            l = f(xc)
            return xc + jnp.asarray(1e-12, xc.dtype) * l.astype(xc.dtype), l
        _, ls = jax.lax.scan(body, x, None, length=REPS)
        return ls[-1]
    return run


def bn_relu(y, g, b):
    yf = y.astype(jnp.float32)
    m = jnp.mean(yf, axis=0)
    v = jnp.var(yf, axis=0)
    out = (yf - m) * jax.lax.rsqrt(v + 1e-5) * g + b
    return jnp.maximum(out, 0.0).astype(y.dtype)


def chain_convgen(x4, ws, gs, bs):
    # x4 [B, H, W, C]; ws[k] [Cin, Cout] -> HWIO [1,1,Cin,Cout]
    for k in range(L):
        w = ws[k][None, None]
        y = jax.lax.conv_general_dilated(
            x4, w, (1, 1), [(0, 0), (0, 0)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        yf = y.astype(jnp.float32)
        m = jnp.mean(yf, axis=(0, 1, 2))
        v = jnp.var(yf, axis=(0, 1, 2))
        out = (yf - m) * jax.lax.rsqrt(v + 1e-5) * gs[k] + bs[k]
        x4 = jnp.maximum(out, 0.0).astype(y.dtype)
    return jnp.sum(x4.astype(jnp.float32))


def chain_dot(x, ws, gs, bs):
    for k in range(L):
        y = jnp.dot(x, ws[k])
        x = bn_relu(y, gs[k], bs[k])
    return jnp.sum(x.astype(jnp.float32))


def _chain_proto(x, ws, gs, bs, unit):
    pm = pi = None
    g_prev = b_prev = None
    for k in range(L):
        if pm is None:
            y, s, sq = unit(x, ws[k], None, None, None, None, False)
        else:
            y, s, sq = unit(x, ws[k], pm, pi, g_prev, b_prev, True)
        n = float(y.shape[0])
        m = s / n
        v = jnp.maximum(sq / n - m * m, 0.0)
        pm, pi = m, jax.lax.rsqrt(v + 1e-5)
        g_prev, b_prev = gs[k], bs[k]
        x = y
    # final normalize folded into readout
    return jnp.sum(((x.astype(jnp.float32) - pm) * pi * g_prev + b_prev))


def unit_jnp(x, w, pm, pi, ps, pb, prologue):
    return _jnp_fused(x, w, pm, pi, ps, pb, prologue, True)


def unit_pallas(x, w, pm, pi, ps, pb, prologue):
    if not prologue:
        c = x.shape[1]
        pm = jnp.zeros((c,), jnp.float32)
        pi = jnp.ones((c,), jnp.float32)
        ps = jnp.ones((c,), jnp.float32)
        pb = jnp.zeros((c,), jnp.float32)
    f = _fused_fn(prologue, True, False)
    return f(x, w, pm, pi, ps, pb)


def main():
    for (B, HW, Cin, Cout) in SHAPES:
        N = B * HW * HW
        rng = np.random.RandomState(0)
        x = jnp.asarray(rng.randn(N, Cin) * 0.5, jnp.bfloat16)
        x4 = x.reshape(B, HW, HW, Cin)
        # alternate Cin->Cout->Cin so the chain is shape-stable
        ws, gs, bs = [], [], []
        for k in range(L):
            ci, co = (Cin, Cout) if k % 2 == 0 else (Cout, Cin)
            ws.append(jnp.asarray(rng.randn(ci, co) / np.sqrt(ci),
                                  jnp.bfloat16))
            gs.append(jnp.ones((co,), jnp.float32))
            bs.append(jnp.zeros((co,), jnp.float32))
        flops = sum(2 * N * w.shape[0] * w.shape[1] for w in ws) * REPS

        res = {}
        res["convgen"] = timeit(many(
            lambda a: chain_convgen(a.reshape(B, HW, HW, Cin), ws, gs, bs)
        ), x)
        res["dot"] = timeit(many(lambda a: chain_dot(a, ws, gs, bs)), x)
        res["proto"] = timeit(many(
            lambda a: _chain_proto(a, ws, gs, bs, unit_jnp)), x)
        eligible = fused_conv_eligible(N, Cin, Cout, jnp.bfloat16) and \
            fused_conv_eligible(N, Cout, Cin, jnp.bfloat16)
        if eligible:
            res["pallas"] = timeit(many(
                lambda a: _chain_proto(a, ws, gs, bs, unit_pallas)), x)
        line = f"N={N:6d} {Cin:4d}->{Cout:4d}: "
        for k, t in res.items():
            line += f"{k}={t*1e3:6.2f}ms ({flops/t/1e12:5.1f}TF/s)  "
        print(line, flush=True)


if __name__ == "__main__":
    main()
