"""Perf experiment: raw-JAX ResNet-50 train step, NCHW vs NHWC, batch sweep.

Establishes the chip's achievable ceiling outside the framework so we know
how much of the MFU gap is layout/batch vs executor overhead.
Run on the real TPU: python experiments/exp_layout.py
"""
import functools
import time

import jax
import jax.numpy as jnp
import numpy as np

# ResNet-50 config: (blocks, channels) per stage
STAGES = [(3, 64), (4, 128), (6, 256), (3, 512)]
STRIDES = []  # per-block strides, static (filled by init_params)


def init_params(rng, layout):
    STRIDES.clear()

    def conv(cin, cout, k):
        nonlocal rng
        rng, sub = jax.random.split(rng)
        w = jax.random.normal(sub, (cout, cin, k, k), jnp.float32) * 0.05
        if layout == "NHWC":
            w = jnp.transpose(w, (2, 3, 1, 0))  # HWIO
        return w

    def bn(c):
        return (jnp.ones((c,)), jnp.zeros((c,)))

    p = {"stem": (conv(3, 64, 7), bn(64))}
    cin = 64
    blocks = []
    for si, (n, ch) in enumerate(STAGES):
        for bi in range(n):
            cout = ch * 4
            stride = 2 if (si > 0 and bi == 0) else 1
            blk = {
                "c1": (conv(cin, ch, 1), bn(ch)),
                "c2": (conv(ch, ch, 3), bn(ch)),
                "c3": (conv(ch, cout, 1), bn(cout)),
            }
            if cin != cout or stride != 1:
                blk["proj"] = (conv(cin, cout, 1), bn(cout))
            blocks.append(blk)
            STRIDES.append(stride)
            cin = cout
    p["blocks"] = blocks
    rng, sub = jax.random.split(rng)
    p["fc"] = jax.random.normal(sub, (cin, 1000), jnp.float32) * 0.01
    return p


def conv_op(x, w, stride, layout, bf16):
    dn = ("NCHW", "OIHW", "NCHW") if layout == "NCHW" else ("NHWC", "HWIO", "NHWC")
    if bf16:
        x = x.astype(jnp.bfloat16)
        w = w.astype(jnp.bfloat16)
    k = w.shape[2] if layout == "NCHW" else w.shape[0]
    pad = (k - 1) // 2
    return jax.lax.conv_general_dilated(
        x, w, (stride, stride), [(pad, pad), (pad, pad)], dimension_numbers=dn
    )


def bn_op(x, scale, bias, layout):
    x32 = x.astype(jnp.float32)
    axes = (0, 2, 3) if layout == "NCHW" else (0, 1, 2)
    shape = (1, -1, 1, 1) if layout == "NCHW" else (1, 1, 1, -1)
    m = jnp.mean(x32, axes)
    v = jnp.var(x32, axes)
    out = (x32 - m.reshape(shape)) * jax.lax.rsqrt(v.reshape(shape) + 1e-5)
    return (out * scale.reshape(shape) + bias.reshape(shape)).astype(x.dtype)


def forward(p, x, layout, bf16):
    w, (s, b) = p["stem"]
    x = jax.nn.relu(bn_op(conv_op(x, w, 2, layout, bf16), s, b, layout))
    if layout == "NCHW":
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 1, 3, 3), (1, 1, 2, 2), ((0, 0), (0, 0), (1, 1), (1, 1)))
    else:
        x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1), ((0, 0), (1, 1), (1, 1), (0, 0)))
    for bi, blk in enumerate(p["blocks"]):
        st = STRIDES[bi]
        w1, (s1, b1) = blk["c1"]
        w2, (s2, b2) = blk["c2"]
        w3, (s3, b3) = blk["c3"]
        y = jax.nn.relu(bn_op(conv_op(x, w1, 1, layout, bf16), s1, b1, layout))
        y = jax.nn.relu(bn_op(conv_op(y, w2, st, layout, bf16), s2, b2, layout))
        y = bn_op(conv_op(y, w3, 1, layout, bf16), s3, b3, layout)
        if "proj" in blk:
            wp, (sp, bp) = blk["proj"]
            x = bn_op(conv_op(x, wp, st, layout, bf16), sp, bp, layout)
        x = jax.nn.relu(x + y)
    axes = (2, 3) if layout == "NCHW" else (1, 2)
    x = jnp.mean(x.astype(jnp.float32), axes)
    return x @ p["fc"]


def loss_fn(p, x, y, layout, bf16):
    logits = forward(p, x, layout, bf16)
    return jnp.mean(-jax.nn.log_softmax(logits)[jnp.arange(x.shape[0]), y])


def bench(layout, batch, bf16=True, steps=40):
    rng = jax.random.PRNGKey(0)
    p = init_params(rng, layout)
    shape = (batch, 3, 224, 224) if layout == "NCHW" else (batch, 224, 224, 3)
    x = jnp.asarray(np.random.randn(*shape), jnp.float32)
    y = jnp.asarray(np.random.randint(0, 1000, (batch,)))

    @jax.jit
    def step(p, x, y):
        g = jax.grad(lambda p: loss_fn(p, x, y, layout, bf16))(p)
        return jax.tree.map(lambda a, b: a - 0.01 * b, p, g)

    p = step(p, x, y)  # compile + 1
    np.asarray(jax.tree.leaves(p)[0])[0]  # d2h: block_until_ready is a
    # no-op on the tunneled axon platform; a host read forces completion
    t0 = time.perf_counter()
    for _ in range(steps):
        p = step(p, x, y)
    np.asarray(jax.tree.leaves(p)[0])[0]
    dt = (time.perf_counter() - t0) / steps
    imgs = batch / dt
    # bench.py accounting: fwd = 4.1 GMACs = 8.2 GFLOPs (2 FLOPs/MAC),
    # train = fwd + bwd ~= 3x fwd
    mfu = (3 * 8.2e9 * batch / dt) / 197e12
    print(f"{layout} bs={batch} bf16={bf16}: {dt*1e3:.1f} ms/step, "
          f"{imgs:.0f} img/s, MFU={mfu*100:.1f}%", flush=True)
    return imgs


if __name__ == "__main__":
    for layout in ("NCHW", "NHWC"):
        for batch in (128, 256):
            bench(layout, batch)
