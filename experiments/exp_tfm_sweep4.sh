#!/bin/bash
# Transformer MFU sweep 4: no-remat variants at L8/L6 (remat recompute cost
# visible: L12 bs4 none 35.5% > L12 bs5 full 33.1%).
cd /root/repo
OUT=experiments/tfm_sweep4.log
: > $OUT
run() {
  echo "=== $* ===" >> $OUT
  timeout 900 env "$@" BENCH_MODEL=transformer python bench.py 2>>$OUT | tail -1 >> $OUT
  echo >> $OUT
}
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=8
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=10
run BENCH_HIDDEN=2048 BENCH_DEPTH=6 BENCH_BATCH=14
run BENCH_HIDDEN=2048 BENCH_DEPTH=6 BENCH_BATCH=16
echo DONE >> $OUT
