"""Reproduce the reference's flagship conv-net benchmark tables cell by
cell on the TPU (the image-side counterpart of benchmarks/lstm_grid.json).

Reference cells: K40m ms/batch for AlexNet bs64-512, GoogleNet bs64-256,
SmallNet bs64-512 (benchmark/README.md:33-59, PaddlePaddle rows) and the
CPU MKL-DNN VGG-19 train img/s (IntelOptimizedPaddle.md:30-36) + the
VGG-19 bs16 inference row (IntelOptimizedPaddle.md:66-73, 96.75 img/s).

Each cell runs in its own subprocess (fresh HBM) through bench.py's own
timing loop; records land in benchmarks/conv_grid.json with the
calibration probes. Run on TPU: python experiments/exp_conv_grid.py
"""
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CELLS = [
    ("alexnet", 64, {}), ("alexnet", 128, {}), ("alexnet", 256, {}),
    ("alexnet", 512, {}),
    ("googlenet", 64, {}), ("googlenet", 128, {}), ("googlenet", 256, {}),
    ("smallnet", 64, {"BENCH_STEPS": "200"}),
    ("smallnet", 128, {"BENCH_STEPS": "200"}),
    ("smallnet", 256, {"BENCH_STEPS": "200"}),
    ("smallnet", 512, {"BENCH_STEPS": "100"}),
    ("vgg", 64, {}), ("vgg", 128, {}),
    ("vgg", 256, {"BENCH_REMAT": "dots"}),
    ("vgg_infer", 16, {"BENCH_MODEL": "vgg", "BENCH_INFER": "1",
                       "BENCH_STEPS": "60"}),
]


def run_cell(model, batch, extra):
    env = dict(os.environ)
    env.update({"BENCH_MODEL": model, "BENCH_BATCH": str(batch),
                "BENCH_STEPS": "40"})
    env.update(extra)
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")],
        env=env, capture_output=True, text=True, timeout=2400)
    if r.returncode != 0:
        return {"error": (r.stderr or r.stdout)[-400:]}
    return json.loads(r.stdout.strip().splitlines()[-1])


def main():
    results = []
    for model, batch, extra in CELLS:
        rec = run_cell(model, batch, extra)
        rec.update({"cell_model": model, "cell_batch": batch})
        if "value" in rec and rec.get("unit") == "images/sec":
            rec["ms_per_batch"] = round(batch / rec["value"] * 1000.0, 3)
        results.append(rec)
        print(json.dumps(rec), flush=True)
    out = {
        "note": ("reference cells: K40m ms/batch benchmark/README.md:33-59"
                 " (PaddlePaddle rows); VGG-19 train img/s + bs16 infer "
                 "IntelOptimizedPaddle.md:30-36,66-73. vs_baseline = our "
                 "img/s over the reference's."),
        "device": "TPU v5e (1 chip, axon tunnel), bf16 AMP",
        "cells": results,
    }
    with open(os.path.join(REPO, "benchmarks", "conv_grid.json"), "w") as f:
        json.dump(out, f, indent=1)
    print("written benchmarks/conv_grid.json")


if __name__ == "__main__":
    main()
