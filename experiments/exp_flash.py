"""Flash attention vs XLA attention on the real chip: correctness + bench.

Writes benchmarks/flash_attention_microbench.json. fwd+bwd (training
shape), calling the Pallas kernel DIRECTLY (_flash_kernel) — the public
dispatcher routes small shapes to the jnp reference by design, which
would make this bench measure the reference against itself. The XLA
formulation materializes [B, H, T, T] scores, so the capability row
(T=32k) fails to compile there while the kernel runs — that memory
boundary, not speed at small T, is what the kernel buys (PERF.md).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.flash_ops import _flash_kernel, _reference


def timeit(f, *args, reps=1):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / reps


def bench(B, T, H, D, reps=60):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)

    # correctness (fwd + a grad probe)
    o_f = _flash_kernel(q, k, v, causal=True)
    o_r = _reference(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(o_f.astype(jnp.float32) -
                                o_r.astype(jnp.float32))))
    g_f = jax.grad(lambda q: jnp.sum(
        _flash_kernel(q, k, v, causal=True).astype(jnp.float32)))(q)
    g_r = jax.grad(lambda q: jnp.sum(
        _reference(q, k, v, causal=True).astype(jnp.float32)))(q)
    gerr = float(jnp.max(jnp.abs(g_f.astype(jnp.float32) -
                                 g_r.astype(jnp.float32))))

    def many(fn):
        # the carry must depend on the gradient with a nonzero scale in
        # q's own dtype, or (a) XLA DCEs the backward pass and (b) the
        # f32 carry promotes bf16 q — both silently invalidate the bench
        @jax.jit
        def run(q, k, v):
            def body(qc, _):
                l, g = jax.value_and_grad(lambda q: jnp.sum(
                    fn(q, k, v, True).astype(jnp.float32)))(qc)
                return qc + jnp.asarray(1e-12, qc.dtype) * g, l
            qc, ls = jax.lax.scan(body, q, None, length=reps)
            return ls[-1]
        return run

    t_flash = timeit(many(lambda q, k, v, c: _flash_kernel(q, k, v, c)),
                     q, k, v, reps=reps)
    try:
        t_xla = timeit(many(lambda q, k, v, c: _reference(q, k, v, c)),
                       q, k, v, reps=reps)
    except Exception as e:  # XLA formulation OOMs at long T
        t_xla = None
    # causal fwd+bwd FLOPs ~ 3.5 * 2 * B*H*T^2*D (two matmuls fwd, ~2.5x bwd) / 2 causal
    row = {
        "B": B, "T": T, "H": H, "D": D,
        "max_err_fwd": round(err, 4), "max_err_grad": round(gerr, 4),
        "flash_ms": round(t_flash * 1e3, 2),
        "xla_ms": None if t_xla is None else round(t_xla * 1e3, 2),
        "speedup": None if t_xla is None else round(t_xla / t_flash, 2),
    }
    print(row, flush=True)
    return row


def capability(B, T, H, D):
    """Long-T row: flash executes where the XLA formulation cannot even
    compile (the [B, H, T, T] score buffer)."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)

    def run(fn):
        try:
            @jax.jit
            def f(q):
                l, g = jax.value_and_grad(lambda q: jnp.sum(
                    fn(q, q, q, True).astype(jnp.float32)))(q)
                # consume the gradient (dtype-preserving) so the backward
                # is not DCE'd — this is the training-shape claim
                return q + jnp.asarray(1e-12, q.dtype) * g
            r = f(q)
            np.asarray(r.ravel()[0])
            t0 = time.perf_counter()
            for _ in range(10):
                r = f(r)
            np.asarray(r.ravel()[0])
            return round((time.perf_counter() - t0) / 10 * 1e3, 1)
        except Exception:
            return None

    row = {
        "B": B, "T": T, "H": H, "D": D,
        "flash_ms": run(lambda q, k, v, c: _flash_kernel(q, k, v, c)),
        "xla_ms": run(lambda q, k, v, c: _reference(q, k, v, c)),
        "note": "xla_ms null = OOM/compile failure at this T",
    }
    print(row, flush=True)
    return row


if __name__ == "__main__":
    rows = [
        bench(2, 1024, 8, 128),
        bench(2, 2048, 8, 128),
        bench(2, 4096, 8, 64),
        bench(1, 8192, 8, 128),
        capability(1, 32768, 4, 128),
    ]
    out = {
        "bench": "flash attention (fused TPU kernel) vs XLA attention, fwd+bwd, causal",
        "device": str(jax.devices()[0].device_kind),
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "flash_attention_microbench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
