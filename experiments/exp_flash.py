"""Flash attention vs XLA attention on the real chip: correctness + bench.

Writes benchmarks/flash_attention_microbench.json. fwd+bwd (training
shape); the XLA formulation materializes [B, H, T, T] scores so it also
hits a memory wall the flash kernel does not (the T=8192 row's XLA
entry OOMs ~4 GB of scores at B2 H8 — reported as null).
"""
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np

from paddle_tpu.ops.flash_ops import _reference, flash_attention


def timeit(f, *args, reps=1):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter() - t0) / reps


def bench(B, T, H, D, reps=60):
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)
    k = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)
    v = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)

    # correctness (fwd + a grad probe)
    o_f = flash_attention(q, k, v, causal=True)
    o_r = _reference(q, k, v, causal=True)
    err = float(jnp.max(jnp.abs(o_f.astype(jnp.float32) -
                                o_r.astype(jnp.float32))))
    g_f = jax.grad(lambda q: jnp.sum(
        flash_attention(q, k, v, causal=True).astype(jnp.float32)))(q)
    g_r = jax.grad(lambda q: jnp.sum(
        _reference(q, k, v, causal=True).astype(jnp.float32)))(q)
    gerr = float(jnp.max(jnp.abs(g_f.astype(jnp.float32) -
                                 g_r.astype(jnp.float32))))

    def many(fn):
        @jax.jit
        def run(q, k, v):
            def body(c, _):
                l, g = jax.value_and_grad(lambda q: jnp.sum(
                    fn(q, k, v, True).astype(jnp.float32)))(q + c * 0)
                return l * 0.0, None
            c, _ = jax.lax.scan(body, jnp.float32(0), None, length=reps)
            return c
        return run

    t_flash = timeit(many(lambda q, k, v, c: flash_attention(q, k, v, c)),
                     q, k, v, reps=reps)
    try:
        t_xla = timeit(many(lambda q, k, v, c: _reference(q, k, v, c)),
                       q, k, v, reps=reps)
    except Exception as e:  # XLA formulation OOMs at long T
        t_xla = None
    # causal fwd+bwd FLOPs ~ 3.5 * 2 * B*H*T^2*D (two matmuls fwd, ~2.5x bwd) / 2 causal
    row = {
        "B": B, "T": T, "H": H, "D": D,
        "max_err_fwd": round(err, 4), "max_err_grad": round(gerr, 4),
        "flash_ms": round(t_flash * 1e3, 2),
        "xla_ms": None if t_xla is None else round(t_xla * 1e3, 2),
        "speedup": None if t_xla is None else round(t_xla / t_flash, 2),
    }
    print(row, flush=True)
    return row


def capability(B, T, H, D):
    """Long-T row: flash executes where the XLA formulation cannot even
    compile (the [B, H, T, T] score buffer)."""
    rng = np.random.RandomState(0)
    q = jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)

    def run(fn):
        try:
            @jax.jit
            def f(q):
                l, _ = jax.value_and_grad(lambda q: jnp.sum(
                    fn(q, q, q, True).astype(jnp.float32)))(q)
                return l
            r = f(q)
            float(np.asarray(r))
            t0 = time.perf_counter()
            for _ in range(10):
                r = f(q + r * 0)
            float(np.asarray(r))
            return round((time.perf_counter() - t0) / 10 * 1e3, 1)
        except Exception:
            return None

    row = {
        "B": B, "T": T, "H": H, "D": D,
        "flash_ms": run(lambda q, k, v, c: flash_attention(q, k, v, c)),
        "xla_ms": run(lambda q, k, v, c: _reference(q, k, v, c)),
        "note": "xla_ms null = OOM/compile failure at this T",
    }
    print(row, flush=True)
    return row


if __name__ == "__main__":
    rows = [
        bench(2, 1024, 8, 128),
        bench(2, 2048, 8, 128),
        bench(2, 4096, 8, 64),
        bench(1, 8192, 8, 128),
        capability(1, 32768, 4, 128),
    ]
    out = {
        "bench": "flash attention (fused TPU kernel) vs XLA attention, fwd+bwd, causal",
        "device": str(jax.devices()[0].device_kind),
        "rows": rows,
    }
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "flash_attention_microbench.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
