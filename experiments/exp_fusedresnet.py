"""In-framework A/B: fused conv+BN protocol vs unfused, ResNet-50 train.

Same-process interleaved measurement (PERF.md methodology — tunnel drift
makes cross-process absolutes incomparable): both programs built and
compiled once, then timed in alternating chained blocks.

Run on TPU: python experiments/exp_fusedresnet.py
"""
import os
import time

import numpy as np

import paddle_tpu as pt
from paddle_tpu import models
from paddle_tpu.flags import FLAGS

BATCH = int(os.environ.get("BATCH", 128))
STEPS = int(os.environ.get("STEPS", 40))
REPS = int(os.environ.get("REPS", 3))


def build(fused):
    FLAGS.use_fused_conv = fused
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        img = pt.layers.data("img", shape=[224, 224, 3])
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.resnet_imagenet(img, class_dim=1000,
                                        data_format="NHWC")
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    prog.set_amp("bfloat16")
    return prog, startup, loss


def main():
    import jax

    rng = np.random.RandomState(0)
    feed_np = {
        "img": rng.randn(BATCH, 224, 224, 3).astype(np.float32),
        "label": rng.randint(0, 1000, (BATCH, 1)).astype(np.int32),
    }
    progs = {}
    exe = pt.Executor(donate_state=True)
    for fused in (False, True):
        progs[fused] = build(fused)
    feed = {k: jax.device_put(v) for k, v in feed_np.items()}
    for v in feed.values():
        np.asarray(v.ravel()[0])  # force h2d now (block_until_ready no-op)

    losses = {}
    for fused in (False, True):
        prog, startup, loss = progs[fused]
        exe.run(startup)
        for _ in range(3):  # compile + warm
            (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
        losses[fused] = float(l)
        assert np.isfinite(l), f"fused={fused} non-finite loss {l}"
    print(f"warm losses: unfused={losses[False]:.4f} "
          f"fused={losses[True]:.4f}", flush=True)

    times = {False: [], True: []}
    for rep in range(REPS):
        for fused in (False, True):
            prog, _, loss = progs[fused]
            t0 = time.perf_counter()
            for _ in range(STEPS):
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                               return_numpy=False)
            float(np.asarray(l))  # single d2h readback forces the chain
            dt = (time.perf_counter() - t0) / STEPS
            times[fused].append(dt)
            print(f"rep{rep} fused={int(fused)}: {dt*1e3:.1f} ms/step "
                  f"({BATCH/dt:.0f} img/s)", flush=True)

    for fused in (False, True):
        best = min(times[fused])
        med = sorted(times[fused])[len(times[fused]) // 2]
        mfu = (3 * 8.2e9 * BATCH / med) / 197e12
        print(f"fused={int(fused)}: median {med*1e3:.1f} ms/step, "
              f"{BATCH/med:.0f} img/s, MFU {mfu*100:.1f}% "
              f"(best {BATCH/best:.0f})")
    print(f"speedup (median): "
          f"{sorted(times[False])[REPS//2]/sorted(times[True])[REPS//2]:.3f}x")


if __name__ == "__main__":
    main()
