"""In-framework A/B: whole-sequence fused decoder BACKWARD kernel vs the
reverse-scan-of-per-step-kernels backward (both with the fused forward).

Same-process interleaved (PERF.md methodology), bs 128 and 256.
FLAGS.fused_attention_seq_bwd is read at trace time, so each variant's
program must be warmed (= traced) while the flag holds its value.
Run on TPU: python experiments/exp_megabwd.py
"""
import os
import time

import numpy as np

STEPS = int(os.environ.get("STEPS", 60))
SEQLEN = 50


def build(batch):
    import paddle_tpu as pt
    from paddle_tpu import models
    from paddle_tpu.core.lod import LoDArray

    vocab, hidden = 30000, 512
    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        src = pt.layers.data("src", shape=[-1], dtype=np.int32, lod_level=1,
                             append_batch_size=False)
        trg_in = pt.layers.data("trg_in", shape=[-1], dtype=np.int32,
                                lod_level=1, append_batch_size=False)
        label = pt.layers.data("label", shape=[-1], dtype=np.int32,
                               lod_level=1, append_batch_size=False)
        logits = models.seq2seq_attention(
            src, trg_in, src_vocab=vocab, trg_vocab=vocab,
            emb_dim=hidden, enc_hidden=hidden, dec_hidden=hidden,
            src_max_len=SEQLEN, trg_max_len=SEQLEN)
        tok_loss = pt.layers.softmax_with_cross_entropy(logits, label)
        loss = pt.layers.mean(pt.layers.sequence_pool(tok_loss, "sum"))
        pt.optimizer.Adam(learning_rate=5e-4).minimize(loss)
    prog.set_amp("bfloat16")
    rng = np.random.RandomState(0)
    pack = lambda seqs: LoDArray.from_sequences(  # noqa: E731
        seqs, capacity=batch * SEQLEN, max_seqs=batch)
    seqs = [rng.randint(2, vocab, (SEQLEN,)).astype(np.int32)
            for _ in range(batch)]
    feed = {"src": pack(seqs), "trg_in": pack(seqs), "label": pack(seqs)}
    return prog, startup, loss, feed


def main():
    import jax

    import paddle_tpu as pt
    from paddle_tpu.flags import FLAGS
    from paddle_tpu.ops import bahdanau_kernels as bk

    exe = pt.Executor(donate_state=True)
    for batch in (128, 256):
        variants = {}
        for mega in (False, True):
            FLAGS.fused_attention_seq_bwd = mega
            bk.reset_dispatch_stats()
            prog, startup, loss, feed = build(batch)
            feed = {k: jax.device_put(v) for k, v in feed.items()}
            for v in feed.values():
                for leaf in jax.tree.leaves(v):
                    np.asarray(leaf.ravel()[0])
            exe.run(startup)
            for _ in range(3):  # first run traces under this flag value
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            assert np.isfinite(l), f"mega={mega} loss {l}"
            want = "seq_bwd" if mega else "scan_bwd"
            assert bk.dispatch_stats[want] >= 1, (mega, bk.dispatch_stats)
            variants[mega] = (prog, loss, feed, float(l))
        print(f"bs={batch} warm losses: scan={variants[False][3]:.3f} "
              f"mega={variants[True][3]:.3f}", flush=True)
        res = {False: [], True: []}
        for rep in range(3):
            for mega in (False, True):
                prog, loss, feed, _ = variants[mega]
                t0 = time.perf_counter()
                for _ in range(STEPS):
                    (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                                   return_numpy=False)
                float(np.asarray(l))
                dt = (time.perf_counter() - t0) / STEPS
                res[mega].append(dt)
                toks = batch * SEQLEN / dt
                print(f"bs={batch} rep{rep} mega={int(mega)}: "
                      f"{dt*1e3:6.1f} ms/step {toks/1e3:7.1f}k tok/s",
                      flush=True)
        ms = sorted(res[False])[1]
        mm = sorted(res[True])[1]
        print(f"bs={batch}: speedup {ms/mm:.3f}x "
              f"({batch*SEQLEN/ms/1e3:.1f}k -> {batch*SEQLEN/mm/1e3:.1f}k "
              f"tok/s)", flush=True)


if __name__ == "__main__":
    main()
