"""ResNet escape route B: batch sweep with restructured BN stats.

VERDICT r3 #4: bs 128/256/512 x {f32-upcast stats (default), bf16-compute
stats with f32 reduction accumulation} — the one unexplored path to >35%
on train-mode-BN ResNet-50 named by PERF.md r3. FLAGS.bn_bf16_stats
switches batch_norm's stats pass to square in bf16 and reduce with f32
accumulation (jnp.mean/var dtype=f32 over the bf16 activation).

Run on TPU: python experiments/exp_bnbatch.py
"""
import os
import time

import numpy as np

STEPS = {128: 30, 256: 15, 512: 8}


def build(batch):
    import paddle_tpu as pt
    from paddle_tpu import models

    prog, startup = pt.Program(), pt.Program()
    startup.random_seed = 7
    with pt.program_guard(prog, startup):
        img = pt.layers.data("img", shape=[224, 224, 3])
        label = pt.layers.data("label", shape=[1], dtype=np.int32)
        logits = models.resnet_imagenet(img, class_dim=1000,
                                        data_format="NHWC")
        loss = pt.layers.mean(
            pt.layers.softmax_with_cross_entropy(logits, label))
        pt.optimizer.Momentum(learning_rate=0.1, momentum=0.9).minimize(loss)
    prog.set_amp("bfloat16")
    return prog, startup, loss


def main():
    import jax

    import paddle_tpu as pt

    exe = pt.Executor(donate_state=True)
    for batch in (128, 256, 512):
        rng = np.random.RandomState(0)
        feed = {
            "img": rng.randn(batch, 224, 224, 3).astype(np.float32),
            "label": rng.randint(0, 1000, (batch, 1)).astype(np.int32),
        }
        feed = {k: jax.device_put(v) for k, v in feed.items()}
        for v in feed.values():
            np.asarray(v.ravel()[0])
        steps = STEPS[batch]
        for bf16_stats in ("0", "1"):
            __import__("paddle_tpu").flags.FLAGS.bn_bf16_stats = bf16_stats == "1"
            prog, startup, loss = build(batch)
            exe.run(startup)
            for _ in range(2):
                (l,) = exe.run(prog, feed=feed, fetch_list=[loss])
            assert np.isfinite(l), f"bs{batch} bf16_stats={bf16_stats}: {l}"
            for rep in range(2):
                t0 = time.perf_counter()
                for _ in range(steps):
                    (l,) = exe.run(prog, feed=feed, fetch_list=[loss],
                                   return_numpy=False)
                float(np.asarray(l))
                dt = (time.perf_counter() - t0) / steps
                mfu = (3 * 8.2e9 * batch / dt) / 197e12
                print(f"bs={batch} bf16_stats={bf16_stats} rep{rep}: "
                      f"{dt*1e3:6.1f} ms/step {batch/dt:7.0f} img/s "
                      f"MFU {mfu*100:.1f}%", flush=True)
        del feed


if __name__ == "__main__":
    main()
