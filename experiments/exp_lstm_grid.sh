#!/bin/bash
# The reference's FULL published LSTM grid (benchmark/README.md:113-136):
# hidden 256/512/1280 x batch 64/128/256, seq 100 — one row each through
# bench.py so vs_baseline lands against the matching K40m cell.
cd /root/repo
OUT=benchmarks/lstm_grid.jsonl
: > $OUT
for H in 256 512 1280; do
  for B in 64 128 256; do
    line=$(timeout 900 env BENCH_MODEL=lstm BENCH_HIDDEN=$H BENCH_BATCH=$B python bench.py 2>/dev/null | tail -1)
    echo "{\"hidden\": $H, \"batch\": $B, \"row\": $line}" >> $OUT
    echo "h$H b$B: $line"
  done
done
echo DONE
