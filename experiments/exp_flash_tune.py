"""Flash-attention block-size tuning for v5e (VERDICT r2 weak #3).

The library kernel's get_default() is all-128 blocks (its own TODO admits
no heuristic); v5e's MXU wants bigger tiles. Sweep block configurations
at the T=1k-16k training shapes where round-2 measured flash/XLA
0.59-0.71x, same DCE-proof chained fwd+bwd harness as exp_flash.py.

Writes benchmarks/flash_block_tuning.json.
"""
import itertools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental.pallas.ops.tpu.flash_attention import (
    BlockSizes,
    flash_attention as tpu_flash,
)

from paddle_tpu.ops.flash_ops import _reference


def timeit(f, *args):
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0 = time.perf_counter()
    r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return time.perf_counter() - t0


def make_blocks(q_blk, k_blk, T):
    q_blk, k_blk = min(q_blk, T), min(k_blk, T)
    return BlockSizes(
        block_q=q_blk, block_k_major=k_blk, block_k=k_blk, block_b=1,
        block_q_major_dkv=q_blk, block_k_major_dkv=k_blk,
        block_k_dkv=k_blk, block_q_dkv=q_blk,
        block_k_major_dq=k_blk, block_k_dq=k_blk, block_q_dq=q_blk,
    )


def bench_point(B, T, H, D, reps=40):
    rng = np.random.RandomState(0)
    mk = lambda: jnp.asarray(rng.randn(B, T, H, D) * 0.3, jnp.bfloat16)  # noqa: E731
    q, k, v = mk(), mk(), mk()
    bhtd = lambda x: jnp.transpose(x, (0, 2, 1, 3))  # noqa: E731
    qh, kh, vh = bhtd(q), bhtd(k), bhtd(v)
    scale = float(1.0 / np.sqrt(D))

    def many(fn, *xs):
        @jax.jit
        def run(qc, *rest):
            def body(qc, _):
                l, g = jax.value_and_grad(lambda q: jnp.sum(
                    fn(q, *rest).astype(jnp.float32)))(qc)
                return qc + jnp.asarray(1e-12, qc.dtype) * g, l
            qc, ls = jax.lax.scan(body, qc, None, length=reps)
            return ls[-1]
        return timeit(run, *xs) / reps

    t_xla = many(lambda q, k, v: _reference(q, k, v, True), q, k, v)
    results = {"xla_ms": round(t_xla * 1e3, 3)}
    best = None
    for q_blk, k_blk in itertools.product((128, 256, 512, 1024),
                                          (128, 256, 512, 1024)):
        if q_blk > T or k_blk > T:
            continue
        try:
            bs = make_blocks(q_blk, k_blk, T)
            t = many(lambda qq, kk, vv: tpu_flash(
                qq, kk, vv, causal=True, sm_scale=scale, block_sizes=bs),
                qh, kh, vh)
            results[f"flash_q{q_blk}_k{k_blk}_ms"] = round(t * 1e3, 3)
            if best is None or t < best[1]:
                best = ((q_blk, k_blk), t)
        except Exception as e:  # noqa: BLE001 — config may not compile
            results[f"flash_q{q_blk}_k{k_blk}_ms"] = \
                "err:" + str(e).split("\n")[0][:80]
        print({"B": B, "T": T, "last": list(results.items())[-1]},
              flush=True)
    results.update(
        B=B, T=T, H=H, D=D,
        best_blocks=None if best is None else list(best[0]),
        best_ms=None if best is None else round(best[1] * 1e3, 3),
        best_speedup_vs_xla=(None if best is None
                             else round(t_xla / best[1], 3)),
    )
    return results


if __name__ == "__main__":
    rows = [
        bench_point(2, 1024, 8, 128),
        bench_point(2, 2048, 8, 128),
        bench_point(2, 4096, 8, 64),
        bench_point(1, 8192, 8, 128),
        bench_point(1, 16384, 8, 128, reps=20),
    ]
    out = {"bench": "flash block-size sweep vs XLA, fwd+bwd causal, one chip",
           "device": str(jax.devices()[0].device_kind), "rows": rows}
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmarks",
        "flash_block_tuning.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=1)
    print("wrote", path)
