import time, sys, jax, jax.numpy as jnp, numpy as np

def timeit(f, *args, n=5):
    r = f(*args); np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    t0=time.perf_counter()
    for _ in range(n): r = f(*args)
    np.asarray(jax.tree.leaves(r)[0].ravel()[0])
    return (time.perf_counter()-t0)/n

B=128
configs = [
    (64, 64, 3, 56, 1), (64, 256, 1, 56, 1),
    (128, 128, 3, 28, 1), (256, 256, 3, 14, 1),
    (512, 512, 3, 7, 1), (3, 64, 7, 224, 2),
]
for cin,cout,k,hw,st in configs:
    x = jnp.asarray(np.random.randn(B,hw,hw,cin), jnp.bfloat16)
    w = jnp.asarray(np.random.randn(k,k,cin,cout), jnp.bfloat16)
    pad=(k-1)//2
    f = jax.jit(lambda x,w,st=st,pad=pad: jax.lax.conv_general_dilated(x,w,(st,st),[(pad,pad)]*2, dimension_numbers=("NHWC","HWIO","NHWC")))
    dt = timeit(f,x,w)
    ho=hw//st
    fl = 2*B*ho*ho*cout*cin*k*k
    print(f"conv {cin:4d}->{cout:4d} k{k} {hw}x{hw}/{st}: {dt*1e3:7.2f} ms {fl/dt/1e12:6.1f} TF/s", flush=True)
x = jnp.asarray(np.random.randn(B*28*28, 512), jnp.bfloat16)
w = jnp.asarray(np.random.randn(512, 512), jnp.bfloat16)
f = jax.jit(lambda x,w: x@w)
dt = timeit(f,x,w)
fl = 2*x.shape[0]*512*512
print(f"matmul [{x.shape[0]}x512]@[512x512]: {dt*1e3:.2f} ms {fl/dt/1e12:.1f} TF/s", flush=True)
