#!/bin/bash
# Transformer MFU sweep 3: full remat won (36.7% at L8 bs8); push batch.
cd /root/repo
OUT=experiments/tfm_sweep3.log
: > $OUT
run() {
  echo "=== $* ===" >> $OUT
  timeout 900 env "$@" BENCH_MODEL=transformer python bench.py 2>>$OUT | tail -1 >> $OUT
  echo >> $OUT
}
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=12 BENCH_REMAT=full
run BENCH_HIDDEN=2048 BENCH_DEPTH=8 BENCH_BATCH=14 BENCH_REMAT=full
run BENCH_HIDDEN=2048 BENCH_DEPTH=10 BENCH_BATCH=8 BENCH_REMAT=full
run BENCH_HIDDEN=2048 BENCH_DEPTH=12 BENCH_BATCH=5 BENCH_REMAT=full
echo DONE >> $OUT
