// C inference ABI over the framework (capi parity).
//
// Reference: paddle/capi — load a merged/deployed model from C and run
// forward (gradient_machine.h:27-94, examples in capi/examples/). The
// compute engine here is JAX, so this library embeds CPython — exactly
// the reference's own embedding trick (TrainerConfigHelper.cpp:58 runs
// config_parser.py inside the C++ trainer) — and drives
// paddle_tpu.capi_support.Predictor. The C caller sees only raw
// buffers; no Python types cross the ABI.
//
// Thread-safety: calls are serialized through the GIL.

#include <Python.h>

#include <cstdint>
#include <cstring>
#include <string>

namespace {

std::string g_error;

void set_error_from_python() {
  PyObject *type, *value, *tb;
  PyErr_Fetch(&type, &value, &tb);
  if (value) {
    PyObject* s = PyObject_Str(value);
    const char* msg = s ? PyUnicode_AsUTF8(s) : nullptr;
    g_error = msg ? msg : "unknown python error";
    PyErr_Clear();  // AsUTF8 may set a new error
    Py_XDECREF(s);
  } else {
    g_error = "unknown error";
  }
  Py_XDECREF(type);
  Py_XDECREF(value);
  Py_XDECREF(tb);
}

struct Predictor {
  PyObject* obj;  // capi_support.Predictor
};

bool ensure_python() {
  if (Py_IsInitialized()) return true;
  Py_InitializeEx(0);
  if (!Py_IsInitialized()) return false;
  // release the GIL the init left held, so any thread (including this
  // one, via PyGILState_Ensure) can take it later
  PyEval_SaveThread();
  return true;
}

}  // namespace

extern "C" {

const char* pt_last_error() { return g_error.c_str(); }

// model_dir: a save_inference_model directory. Returns NULL on error
// (see pt_last_error). Honors PYTHONPATH/JAX_PLATFORMS from the env.
void* pt_predictor_create(const char* model_dir) {
  if (!ensure_python()) {
    g_error = "cannot initialize python";
    return nullptr;
  }
  PyGILState_STATE gil = PyGILState_Ensure();
  void* result = nullptr;
  PyObject* mod = PyImport_ImportModule("paddle_tpu.capi_support");
  if (!mod) {
    set_error_from_python();
    PyGILState_Release(gil);
    return nullptr;
  }
  PyObject* obj =
      PyObject_CallMethod(mod, "create", "s", model_dir);
  Py_DECREF(mod);
  if (!obj) {
    set_error_from_python();
  } else {
    auto* p = new Predictor();
    p->obj = obj;
    result = p;
  }
  PyGILState_Release(gil);
  return result;
}

int pt_predictor_num_fetch(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  PyObject* n = PyObject_CallMethod(p->obj, "num_fetch", nullptr);
  int out = n ? (int)PyLong_AsLong(n) : -1;
  Py_XDECREF(n);
  if (out < 0) set_error_from_python();
  PyGILState_Release(gil);
  return out;
}

// Runs one forward. Feeds: n buffers; feed_shapes is the concatenation
// of each feed's dims (feed_ndims[i] entries each); dtypes are numpy
// names ("float32", "int32"). The fetch is copied into out_buf (cap
// bytes); *out_bytes gets the true size, *out_ndim/out_shape (cap 8)
// the shape, out_dtype (cap 16, NUL-terminated) the numpy dtype name.
// Returns 0, or -1 on error, or -2 if out_buf is too small.
int pt_predictor_run(void* handle, const char** feed_names,
                     const char** feed_data, const int64_t* feed_bytes,
                     const int64_t* feed_shapes, const int* feed_ndims,
                     const char** feed_dtypes, int n_feeds, int fetch_idx,
                     char* out_buf, int64_t out_cap, int64_t* out_bytes,
                     int64_t* out_shape, int* out_ndim, char* out_dtype) {
  auto* p = static_cast<Predictor*>(handle);
  PyGILState_STATE gil = PyGILState_Ensure();
  int rc = -1;
  PyObject *names = PyList_New(n_feeds), *blobs = PyList_New(n_feeds),
           *shapes = PyList_New(n_feeds), *dtypes = PyList_New(n_feeds);
  const int64_t* sp = feed_shapes;
  for (int i = 0; i < n_feeds; i++) {
    PyList_SetItem(names, i, PyUnicode_FromString(feed_names[i]));
    PyList_SetItem(blobs, i,
                   PyBytes_FromStringAndSize(feed_data[i], feed_bytes[i]));
    PyObject* shp = PyList_New(feed_ndims[i]);
    for (int d = 0; d < feed_ndims[i]; d++)
      PyList_SetItem(shp, d, PyLong_FromLongLong(*sp++));
    PyList_SetItem(shapes, i, shp);
    PyList_SetItem(dtypes, i, PyUnicode_FromString(feed_dtypes[i]));
  }
  PyObject* res = PyObject_CallMethod(p->obj, "run_raw", "OOOOi", names,
                                      blobs, shapes, dtypes, fetch_idx);
  Py_DECREF(names);
  Py_DECREF(blobs);
  Py_DECREF(shapes);
  Py_DECREF(dtypes);
  if (!res) {
    set_error_from_python();
    PyGILState_Release(gil);
    return -1;
  }
  PyObject *bytes_obj, *shape_obj, *dtype_obj;
  if (PyArg_ParseTuple(res, "SOU", &bytes_obj, &shape_obj, &dtype_obj)) {
    char* buf;
    Py_ssize_t blen;
    PyBytes_AsStringAndSize(bytes_obj, &buf, &blen);
    *out_bytes = blen;
    int nd = (int)PyList_Size(shape_obj);
    *out_ndim = nd > 8 ? 8 : nd;
    for (int d = 0; d < *out_ndim; d++)
      out_shape[d] = PyLong_AsLongLong(PyList_GetItem(shape_obj, d));
    if (out_dtype) {
      const char* dt = PyUnicode_AsUTF8(dtype_obj);
      snprintf(out_dtype, 16, "%s", dt ? dt : "");
    }
    if (blen > out_cap) {
      rc = -2;
      g_error = "output buffer too small";
    } else {
      memcpy(out_buf, buf, blen);
      rc = 0;
    }
  } else {
    set_error_from_python();
  }
  Py_DECREF(res);
  PyGILState_Release(gil);
  return rc;
}

void pt_predictor_destroy(void* handle) {
  auto* p = static_cast<Predictor*>(handle);
  if (Py_IsInitialized()) {
    PyGILState_STATE gil = PyGILState_Ensure();
    Py_XDECREF(p->obj);
    PyGILState_Release(gil);
  }
  delete p;
}

}  // extern "C"
