/* Pure-C inference example (reference: paddle/capi/examples — a C
 * program loads a deployed model and runs forward with no Python
 * source in sight). Build via `make capi` then:
 *
 *   ./build/capi_example <model_dir> <in_dim> <batch>
 *
 * Feeds a batch of ones through feed var "x" and prints the first
 * fetch. Exit 0 on success.
 */
#include <stdint.h>
#include <stdio.h>
#include <stdlib.h>

extern void* pt_predictor_create(const char* model_dir);
extern int pt_predictor_num_fetch(void* p);
extern int pt_predictor_run(void* p, const char** feed_names,
                            const char** feed_data, const int64_t* feed_bytes,
                            const int64_t* feed_shapes, const int* feed_ndims,
                            const char** feed_dtypes, int n_feeds,
                            int fetch_idx, char* out_buf, int64_t out_cap,
                            int64_t* out_bytes, int64_t* out_shape,
                            int* out_ndim, char* out_dtype);
extern void pt_predictor_destroy(void* p);
extern const char* pt_last_error(void);

int main(int argc, char** argv) {
  if (argc < 4) {
    fprintf(stderr, "usage: %s model_dir in_dim batch\n", argv[0]);
    return 2;
  }
  const char* model_dir = argv[1];
  int in_dim = atoi(argv[2]);
  int batch = atoi(argv[3]);

  void* p = pt_predictor_create(model_dir);
  if (!p) {
    fprintf(stderr, "create failed: %s\n", pt_last_error());
    return 1;
  }
  printf("num_fetch=%d\n", pt_predictor_num_fetch(p));

  float* input = malloc(sizeof(float) * batch * in_dim);
  for (int i = 0; i < batch * in_dim; i++) input[i] = 1.0f;

  const char* names[1] = {"x"};
  const char* data[1] = {(const char*)input};
  int64_t nbytes[1] = {(int64_t)sizeof(float) * batch * in_dim};
  int64_t shapes[2] = {batch, in_dim};
  int ndims[1] = {2};
  const char* dtypes[1] = {"float32"};

  char out[1 << 20];
  int64_t out_bytes, out_shape[8];
  int out_ndim;
  char out_dtype[16];
  int rc = pt_predictor_run(p, names, data, nbytes, shapes, ndims, dtypes, 1,
                            0, out, sizeof(out), &out_bytes, out_shape,
                            &out_ndim, out_dtype);
  if (rc != 0) {
    fprintf(stderr, "run failed (%d): %s\n", rc, pt_last_error());
    return 1;
  }
  printf("out_dtype=%s ", out_dtype);
  printf("out_shape=");
  for (int d = 0; d < out_ndim; d++) printf("%lld,", (long long)out_shape[d]);
  printf(" first_vals=");
  const float* of = (const float*)out;
  int n = (int)(out_bytes / sizeof(float));
  for (int i = 0; i < (n < 4 ? n : 4); i++) printf("%.4f ", of[i]);
  printf("\n");
  free(input);
  pt_predictor_destroy(p);
  printf("CAPI_OK\n");
  return 0;
}
