// RecordIO: chunked, CRC-checked record file format + reader/writer.
//
// Reference: the Go recordio package the master's task dispatch shards
// over (go/master/service.go:106 partitions record files into chunk
// tasks) and the CRC-validated checkpoint framing of the Go pserver
// (go/pserver/service.go:346, WrongChecksum go/pserver/service.go:60).
//
// Layout: file := chunk*;
//   chunk := magic(u32) | num_records(u32) | body_len(u64) | crc32(u32)
//            | body;  body := (len(u32) | bytes)*
// Records are opaque byte strings; chunks flush at ~1 MiB so the master
// can hand out (path, chunk_index) tasks and readers can seek.

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <string>
#include <vector>

namespace {

constexpr uint32_t kMagic = 0x50544352;  // "PTCR"
constexpr size_t kChunkBytes = 1 << 20;

uint32_t crc_table[256];
bool crc_init_done = [] {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
    crc_table[i] = c;
  }
  return true;
}();

uint32_t crc32(const char* buf, size_t len) {
  uint32_t c = 0xFFFFFFFFu;
  for (size_t i = 0; i < len; i++)
    c = crc_table[(c ^ static_cast<uint8_t>(buf[i])) & 0xFF] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

struct Writer {
  FILE* f = nullptr;
  std::string body;
  uint32_t num_records = 0;

  bool flush_chunk() {
    if (num_records == 0) return true;
    uint32_t magic = kMagic, n = num_records, crc = crc32(body.data(), body.size());
    uint64_t blen = body.size();
    if (fwrite(&magic, 4, 1, f) != 1 || fwrite(&n, 4, 1, f) != 1 ||
        fwrite(&blen, 8, 1, f) != 1 || fwrite(&crc, 4, 1, f) != 1 ||
        (blen && fwrite(body.data(), 1, blen, f) != blen))
      return false;
    body.clear();
    num_records = 0;
    return true;
  }
};

struct Reader {
  FILE* f = nullptr;
  std::vector<char> body;
  size_t pos = 0;        // cursor into body
  uint32_t remaining = 0;  // records left in current chunk
  std::string last_error;

  bool load_chunk() {
    uint32_t magic, n, crc;
    uint64_t blen;
    if (fread(&magic, 4, 1, f) != 1) return false;  // clean EOF
    if (magic != kMagic || fread(&n, 4, 1, f) != 1 ||
        fread(&blen, 8, 1, f) != 1 || fread(&crc, 4, 1, f) != 1 ||
        blen > (1ull << 31)) {  // bound the alloc: corrupt header, not OOM
      last_error = "corrupt chunk header";
      return false;
    }
    body.resize(blen);
    if (blen && fread(body.data(), 1, blen, f) != blen) {
      last_error = "truncated chunk body";
      return false;
    }
    if (crc32(body.data(), blen) != crc) {
      last_error = "chunk crc mismatch";
      return false;
    }
    pos = 0;
    remaining = n;
    return true;
  }
};

}  // namespace

extern "C" {

void* rio_writer_open(const char* path) {
  FILE* f = fopen(path, "wb");
  if (!f) return nullptr;
  auto* w = new Writer();
  w->f = f;
  return w;
}

int rio_writer_write(void* handle, const char* buf, uint64_t len) {
  auto* w = static_cast<Writer*>(handle);
  uint32_t l32 = static_cast<uint32_t>(len);
  w->body.append(reinterpret_cast<char*>(&l32), 4);
  w->body.append(buf, len);
  w->num_records++;
  if (w->body.size() >= kChunkBytes) return w->flush_chunk() ? 0 : -1;
  return 0;
}

int rio_writer_close(void* handle) {
  auto* w = static_cast<Writer*>(handle);
  int rc = w->flush_chunk() ? 0 : -1;
  fclose(w->f);
  delete w;
  return rc;
}

void* rio_reader_open(const char* path) {
  FILE* f = fopen(path, "rb");
  if (!f) return nullptr;
  auto* r = new Reader();
  r->f = f;
  return r;
}

// Returns record length and sets *out (valid until the next call), or
// -1 at EOF, -2 on corruption.
int64_t rio_reader_next(void* handle, const char** out) {
  auto* r = static_cast<Reader*>(handle);
  if (r->remaining == 0) {
    if (!r->load_chunk()) return r->last_error.empty() ? -1 : -2;
  }
  if (r->pos + 4 > r->body.size()) return -2;
  uint32_t len;
  memcpy(&len, r->body.data() + r->pos, 4);
  r->pos += 4;
  if (r->pos + len > r->body.size()) return -2;
  *out = r->body.data() + r->pos;
  r->pos += len;
  r->remaining--;
  return static_cast<int64_t>(len);
}

void rio_reader_close(void* handle) {
  auto* r = static_cast<Reader*>(handle);
  fclose(r->f);
  delete r;
}

int64_t rio_num_records(const char* path) {
  void* h = rio_reader_open(path);
  if (!h) return -1;
  int64_t n = 0;
  const char* buf;
  int64_t rc;
  while ((rc = rio_reader_next(h, &buf)) >= 0) n++;
  rio_reader_close(h);
  return rc == -2 ? -1 : n;
}

}  // extern "C"
