// Async double-buffered record prefetcher.
//
// Reference: gserver/dataproviders/DataProvider.h:292 — the base
// DataProvider runs a background thread that keeps a bounded buffer of
// ready batches ahead of the trainer (double buffering, getNextBatch
// :328 / asyncLoadBatch :375). Here: N reader threads stream records
// from recordio shards into a bounded ring; the consumer (the Python
// feed pipeline) pops byte records and builds device arrays while the
// disks keep streaming.

#include <condition_variable>
#include <cstdint>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <thread>
#include <vector>

extern "C" {
void* rio_reader_open(const char* path);
int64_t rio_reader_next(void* handle, const char** out);
void rio_reader_close(void* handle);
}

namespace {

struct Prefetcher {
  std::mutex mu;
  std::condition_variable not_full, not_empty;
  std::deque<std::string> queue;
  size_t capacity;
  std::vector<std::thread> threads;
  int live_threads = 0;
  bool stop = false;
  std::string error;    // first shard failure (unopenable / corrupt)
  std::string current;  // last popped record, owned for the caller

  void fail(const std::string& msg) {
    std::lock_guard<std::mutex> g(mu);
    if (error.empty()) error = msg;
  }

  void produce(std::vector<std::string> paths) {
    for (auto& p : paths) {
      void* r = rio_reader_open(p.c_str());
      if (!r) {
        fail("cannot open " + p);
        break;
      }
      const char* buf;
      int64_t len;
      while ((len = rio_reader_next(r, &buf)) >= 0) {
        std::unique_lock<std::mutex> lk(mu);
        not_full.wait(lk, [&] { return queue.size() < capacity || stop; });
        if (stop) {
          rio_reader_close(r);
          goto out;
        }
        queue.emplace_back(buf, len);
        not_empty.notify_one();
      }
      rio_reader_close(r);
      if (len == -2) {
        fail("corrupt recordio file " + p);
        break;
      }
    }
  out: {
    std::lock_guard<std::mutex> g(mu);
    live_threads--;
    not_empty.notify_all();
  }
  }
};

}  // namespace

extern "C" {

// Shards `paths` round-robin over n_threads reader threads; `capacity`
// bounds the ready-record ring.
void* prefetch_create(const char** paths, int n_paths, int n_threads,
                      int capacity) {
  auto* p = new Prefetcher();
  p->capacity = capacity > 0 ? capacity : 1024;
  n_threads = std::max(1, std::min(n_threads, n_paths > 0 ? n_paths : 1));
  std::vector<std::vector<std::string>> shards(n_threads);
  for (int i = 0; i < n_paths; i++) shards[i % n_threads].push_back(paths[i]);
  p->live_threads = n_threads;
  for (int t = 0; t < n_threads; t++)
    p->threads.emplace_back(&Prefetcher::produce, p, shards[t]);
  return p;
}

// Blocks for the next record; returns its length and sets *out (valid
// until the next call), -1 when all shards are exhausted cleanly, or
// -2 if any shard failed (unopenable / corrupt) — after draining the
// records queued before the failure.
int64_t prefetch_next(void* handle, const char** out) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::unique_lock<std::mutex> lk(p->mu);
  p->not_empty.wait(lk, [&] { return !p->queue.empty() || p->live_threads == 0; });
  if (p->queue.empty()) return p->error.empty() ? -1 : -2;
  p->current = std::move(p->queue.front());
  p->queue.pop_front();
  p->not_full.notify_one();
  *out = p->current.data();
  return static_cast<int64_t>(p->current.size());
}

// Returns the first error message ("" if none); valid until destroy.
const char* prefetch_error(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  std::lock_guard<std::mutex> g(p->mu);
  return p->error.c_str();
}

void prefetch_destroy(void* handle) {
  auto* p = static_cast<Prefetcher*>(handle);
  {
    std::lock_guard<std::mutex> g(p->mu);
    p->stop = true;
    p->not_full.notify_all();
  }
  for (auto& t : p->threads) t.join();
  delete p;
}

}  // extern "C"
