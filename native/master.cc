// Fault-tolerant task-dispatch master.
//
// Reference: go/master/service.go — dataset partitioned into tasks
// (:106), three-queue lifecycle Todo/Pending/Done/Failed (:81-84),
// pending-task timeout + failure-count eviction (:313-355), snapshot
// for crash recovery (:166-230). The etcd snapshot becomes a local
// file (single-coordinator deployment); the RPC surface becomes a C
// ABI driven through ctypes by the trainer's reader — multi-host
// trainers would front this with a socket server, the queue semantics
// are identical.

#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstring>
#include <deque>
#include <mutex>
#include <string>
#include <unordered_map>
#include <vector>

namespace {

double now_s() {
  return std::chrono::duration<double>(
             std::chrono::steady_clock::now().time_since_epoch())
      .count();
}

struct Task {
  int64_t id;
  std::string meta;  // opaque (e.g. "path:chunk_idx")
  int fail_count = 0;
};

struct Master {
  std::mutex mu;
  std::deque<Task> todo;
  std::unordered_map<int64_t, Task> pending;  // id → task
  std::unordered_map<int64_t, double> deadline;
  std::vector<Task> done;
  std::vector<Task> failed;  // evicted (fail_count exceeded)
  int64_t next_id = 0;
  double timeout_s;
  int max_failures;
  std::string snapshot_path;

  void requeue_timed_out() {  // caller holds mu
    double t = now_s();
    std::vector<int64_t> expired;
    for (auto& kv : deadline)
      if (kv.second <= t) expired.push_back(kv.first);
    for (int64_t id : expired) {
      Task task = pending[id];
      pending.erase(id);
      deadline.erase(id);
      task.fail_count++;
      if (task.fail_count > max_failures)
        failed.push_back(task);
      else
        todo.push_back(task);
    }
  }

  bool snapshot() {  // caller holds mu
    if (snapshot_path.empty()) return true;
    std::string tmp = snapshot_path + ".tmp";
    FILE* f = fopen(tmp.c_str(), "wb");
    if (!f) return false;
    bool ok = true;
    auto put = [&](const Task& t, char state) {
      uint32_t len = t.meta.size();
      ok = ok && fwrite(&state, 1, 1, f) == 1 && fwrite(&t.id, 8, 1, f) == 1 &&
           fwrite(&t.fail_count, 4, 1, f) == 1 && fwrite(&len, 4, 1, f) == 1 &&
           (len == 0 || fwrite(t.meta.data(), 1, len, f) == len);
    };
    ok = fwrite(&next_id, 8, 1, f) == 1;
    // pending counts as todo on recovery (the worker may have died)
    for (auto& t : todo) put(t, 'T');
    for (auto& kv : pending) put(kv.second, 'T');
    for (auto& t : done) put(t, 'D');
    for (auto& t : failed) put(t, 'F');
    ok = fclose(f) == 0 && ok;
    if (!ok) {  // never clobber the last good snapshot with a partial one
      remove(tmp.c_str());
      return false;
    }
    return rename(tmp.c_str(), snapshot_path.c_str()) == 0;
  }

  bool recover() {
    FILE* f = fopen(snapshot_path.c_str(), "rb");
    if (!f) return false;
    if (fread(&next_id, 8, 1, f) != 1) {
      fclose(f);
      return false;
    }
    char state;
    while (fread(&state, 1, 1, f) == 1) {
      Task t;
      uint32_t len;
      if (fread(&t.id, 8, 1, f) != 1 || fread(&t.fail_count, 4, 1, f) != 1 ||
          fread(&len, 4, 1, f) != 1)
        break;
      t.meta.resize(len);
      if (len && fread(&t.meta[0], 1, len, f) != len) break;
      if (state == 'T')
        todo.push_back(t);
      else if (state == 'D')
        done.push_back(t);
      else
        failed.push_back(t);
    }
    fclose(f);
    return true;
  }
};

}  // namespace

extern "C" {

// Creates the master; recovers state from snapshot_path if the file
// exists (pass "" to disable snapshots).
void* master_create(const char* snapshot_path, double timeout_s,
                    int max_failures) {
  auto* m = new Master();
  m->timeout_s = timeout_s;
  m->max_failures = max_failures;
  m->snapshot_path = snapshot_path ? snapshot_path : "";
  if (!m->snapshot_path.empty()) m->recover();
  return m;
}

void master_destroy(void* handle) { delete static_cast<Master*>(handle); }

int64_t master_add_task(void* handle, const char* meta, int64_t len) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  Task t;
  t.id = m->next_id++;
  t.meta.assign(meta, len);
  m->todo.push_back(t);
  return t.id;
}

// Pops a task: copies meta into buf (cap bytes) and its exact length
// into *meta_len. Returns the task id, -1 if nothing is available
// (all pending/done), or -2 if the meta does not fit in cap (the task
// stays in todo).
int64_t master_get_task(void* handle, char* buf, int64_t cap,
                        int64_t* meta_len) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  m->requeue_timed_out();
  if (m->todo.empty()) return -1;
  if (static_cast<int64_t>(m->todo.front().meta.size()) > cap) return -2;
  Task t = m->todo.front();
  m->todo.pop_front();
  *meta_len = t.meta.size();
  memcpy(buf, t.meta.data(), t.meta.size());
  m->pending[t.id] = t;
  m->deadline[t.id] = now_s() + m->timeout_s;
  return t.id;
}

int master_task_finished(void* handle, int64_t id) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(id);
  if (it == m->pending.end()) return -1;  // late/duplicate report
  m->done.push_back(it->second);
  m->pending.erase(it);
  m->deadline.erase(id);
  m->snapshot();
  return 0;
}

int master_task_failed(void* handle, int64_t id) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  auto it = m->pending.find(id);
  if (it == m->pending.end()) return -1;
  Task t = it->second;
  m->pending.erase(it);
  m->deadline.erase(id);
  t.fail_count++;
  if (t.fail_count > m->max_failures)
    m->failed.push_back(t);
  else
    m->todo.push_back(t);
  m->snapshot();
  return 0;
}

// counts: [todo, pending, done, failed]
void master_counts(void* handle, int64_t* out4) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  m->requeue_timed_out();
  out4[0] = m->todo.size();
  out4[1] = m->pending.size();
  out4[2] = m->done.size();
  out4[3] = m->failed.size();
}

// End of pass: move done back to todo (go master re-dispatches the
// dataset every pass; service.go SetDataset per pass).
void master_new_pass(void* handle) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  for (auto& t : m->done) {
    t.fail_count = 0;
    m->todo.push_back(t);
  }
  m->done.clear();
  m->snapshot();
}

int master_snapshot_now(void* handle) {
  auto* m = static_cast<Master*>(handle);
  std::lock_guard<std::mutex> g(m->mu);
  return m->snapshot() ? 0 : -1;
}

}  // extern "C"
