"""Finite-difference gradient checking — the reference's core test oracle.

Reference: gserver/tests/test_LayerGrad.cpp + LayerGradUtil.h:298-306
(`testLayerGrad` perturbs inputs/params and compares numeric vs analytic
gradients for every layer) and the whole-trainer `--job=checkgrad` mode
(paddle/trainer/Trainer.cpp:303, perturbation at :281). Fluid's OpTest
`check_grad` (fluid/tests/op_test.py:361) is the same idea per op.

Here the analytic side is jax.grad over the traced program (the `autodiff`
meta-op); the numeric side is central differences on sampled elements of
each parameter, both evaluated through the same Executor so the check
covers the full trace path, not just an isolated kernel.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from .core.backward import append_backward
from .core.executor import Executor, Scope, global_scope
from .core.program import Program, Variable, grad_var_name

__all__ = ["check_gradient"]


def check_gradient(
    loss: Variable,
    feed: Dict[str, np.ndarray],
    params: Optional[Sequence[str]] = None,
    scope: Optional[Scope] = None,
    eps: float = 1e-3,
    rtol: float = 1e-2,
    atol: float = 1e-4,
    max_elements: int = 8,
    seed: int = 7,
) -> Dict[str, float]:
    """Compare analytic vs numeric d(loss)/d(param) on sampled elements.

    Works on a for_test clone of the program (optimizer pass stripped, fixed
    RNG) so the caller's training program and scope are untouched. Returns
    {param: max_abs_diff}; raises AssertionError on mismatch.
    """
    src_scope = scope or global_scope()
    program = loss.block.program
    prog = program.clone(for_test=True)
    prog.random_seed = seed
    loss_var = prog.global_block().var(loss.name)
    if params is None:
        params = [p.name for p in prog.parameters() if p.trainable]
    param_vars = [prog.global_block().var(p) for p in params]
    pg = append_backward(loss_var, parameter_list=param_vars)

    # private scope: copy of the needed persistables, in float64 where
    # possible for a tighter numeric baseline is NOT done — the check runs in
    # the same dtype the program trains in, as the reference does.
    work = Scope()
    for v in prog.persistables():
        if src_scope.has(v.name):
            work.set(v.name, np.array(np.asarray(src_scope.get(v.name))))

    exe = Executor()

    def run_loss_and_grads(fetch_grads: bool):
        fetch = [loss_var.name] + (
            [grad_var_name(p) for p in params] if fetch_grads else []
        )
        outs = exe.run(prog, feed=dict(feed), fetch_list=fetch, scope=work)
        return [np.asarray(o) for o in outs]

    analytic = run_loss_and_grads(True)
    grads = dict(zip(params, analytic[1:]))

    rng = np.random.RandomState(seed)
    max_diffs: Dict[str, float] = {}
    for p in params:
        value = np.array(work.get(p), copy=True)
        flat = value.reshape(-1)
        n = flat.size
        idxs = (
            np.arange(n)
            if n <= max_elements
            else rng.choice(n, size=max_elements, replace=False)
        )
        worst = 0.0
        for i in idxs:
            orig = flat[i]
            flat[i] = orig + eps
            work.set(p, value)
            (lp,) = run_loss_and_grads(False)
            flat[i] = orig - eps
            work.set(p, value)
            (lm,) = run_loss_and_grads(False)
            flat[i] = orig
            work.set(p, value)
            numeric = (float(lp) - float(lm)) / (2 * eps)
            a = float(grads[p].reshape(-1)[i])
            diff = abs(a - numeric)
            tol = atol + rtol * max(abs(a), abs(numeric))
            if diff > tol:
                raise AssertionError(
                    f"gradient mismatch for {p}[{i}]: analytic={a:.6g} "
                    f"numeric={numeric:.6g} (|diff|={diff:.3g} > tol={tol:.3g})"
                )
            worst = max(worst, diff)
        max_diffs[p] = worst
    return max_diffs
