"""Cost-model-guided candidate search (Autotuner v2).

v1's harness swept EVERY legal candidate per shape signature — fine for
the bahdanau space (a handful of divisors) but quadratic for flash
(|q blocks| x |k blocks|) and a cold table meant minutes of warmup
timing. CUDA-L2 (arXiv:2512.02551) and CLBlast (arXiv:1705.05249 §3)
both land on the same recipe this module implements:

1. a LIGHTWEIGHT COST MODEL ranks candidates before anything is timed.
   The features are computable from tune/space.py's legality model
   alone — no hardware, no compile: estimated HBM traffic (the
   arithmetic-intensity term), kernel grid steps (the per-dispatch
   overhead term), and VMEM pressure (working-set bytes against
   ops/pallas_kernels._VMEM_BUDGET — the spill term; every measured
   "big tile loses" result in PERF.md is a spill, not a bandwidth
   effect, so the penalty is quadratic once the working set passes half
   the budget: borderline configs flip with the compiler's scratch
   scheduling, pallas_kernels.py's hard-won comment);

2. SUCCESSIVE HALVING times only the top-ranked fraction: every
   survivor gets a cheap low-iteration probe, the better half advances
   to a higher-iteration rung, and the search stops EARLY when the
   leader is stable across rungs — so the expensive high-confidence
   medians are spent on the 2-3 genuine contenders, not the whole
   space.

The searcher takes an INJECTABLE timing oracle (`oracle(config, iters)
-> median seconds`) because harness.py refuses to time off-TPU: the
real oracle wraps the compile+measure loop, and the tier-1 CPU suite
proves guided-vs-exhaustive quality on a deterministic SimulatedOracle
instead (same protocol, synthetic-but-plausible timing surface). The
guided-search acceptance bar — >= 95% of exhaustive-search quality
while timing <= 40% of the candidate space — is asserted against that
oracle in tests and measured for real by bench.py
BENCH_MODEL=tune_search.
"""

from __future__ import annotations

import hashlib
import math
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from . import space

Config = Dict[str, Any]

# effective-bandwidth / per-grid-step-overhead constants: these only
# need to produce a sane RANKING (the oracle decides the winner), so
# one set serves every device generation. v5e-ish: ~800 GB/s HBM,
# ~2 us of grid/dispatch overhead per kernel grid step.
_HBM_BYTES_PER_S = 8e11
_GRID_STEP_S = 2e-6
# spill penalty engages past this fraction of the VMEM budget
# (pallas_kernels.py: borderline working sets flip between compiling
# and overflowing with the compiler's scratch scheduling)
_SPILL_KNEE = 0.5
_SPILL_GAIN = 4.0


def config_key(config: Config) -> Tuple[Tuple[str, Any], ...]:
    """Canonical hashable identity of a candidate config."""
    return tuple(sorted(config.items()))


# ------------------------------------------------------ cost features --
def _features_bahdanau(params: Dict[str, Any], cfg: Config):
    B, Sp, A, C = params["B"], params["Sp"], params["A"], params["C"]
    item = 2 if params.get("dtype") == "bfloat16" else 4
    b = int(cfg["bblk"])
    grid = B // max(1, b)
    # io traffic is tile-invariant (every ep/enc/dep byte moves once);
    # what varies is the dispatch overhead and the five f32 [b, Sp, A]
    # working arrays' VMEM take (the spill axis the 8-vs-16 NMT
    # measurement lives on)
    hbm = (2 * Sp * (A + C) + Sp * A) * B * item
    ws = ((2 * Sp * (A + C) + Sp * A) * b * item + 5 * b * Sp * A * 4)
    return hbm, grid, ws


def _features_flash(params: Dict[str, Any], cfg: Config):
    Tq, Tk = params["Tq"], params["Tk"]
    item = 2 if params.get("dtype", "bfloat16") == "bfloat16" else 4
    D = 128  # nominal head dim: a constant scale, irrelevant to ranking
    bq, bk = int(cfg["block_q"]), int(cfg["block_k"])
    grid = (Tq // max(1, bq)) * (Tk // max(1, bk))
    # k/v stream through VMEM once per q block (the flash loop): small
    # q blocks re-read the whole kv sequence
    hbm = (Tq * D + (Tq // max(1, bq)) * 2 * Tk * D) * item
    ws = (bq * D + 2 * bk * D) * item + bq * bk * 4 + bq * D * 4
    return hbm, grid, ws


def _features_conv(params: Dict[str, Any], cfg: Config):
    n, cin, cout = params["n"], params["cin"], params["cout"]
    item = 2 if params.get("dtype") == "bfloat16" else 4
    b = int(cfg["block_rows"])
    grid = n // max(1, b)
    # the weight panel re-streams per row block; io moves once
    hbm = n * (cin + cout) * item + grid * cin * cout * item
    ws = cin * cout * item + 2 * b * (cin + cout) * item \
        + 2 * 4 * cout + 4 * cin * 4
    return hbm, grid, ws


def _features_rnn(kind: str):
    def f(params: Dict[str, Any], cfg: Config):
        B, H = params["B"], params["H"]
        item = 2 if params.get("dtype") == "bfloat16" else 4
        g = 4 if kind == "lstm" else 3
        if cfg.get("fused"):
            from ..ops import pallas_kernels as pk

            dw = (pk._LSTM_FUSED_DW_MAX_H if kind == "lstm"
                  else pk._GRU_FUSED_DW_MAX_H)
            return (g * H * H * item + B * H * item, 1,
                    pk._bwd_vmem_bytes(B, H, g, item, dw))
        # scan formulation: weights re-stream per step (T unknown at
        # tune time; 32 is a nominal sequence), no VMEM pressure
        return (32 * g * H * H * item, 32, 0)

    return f


def _features_quant_matmul(params: Dict[str, Any], cfg: Config):
    """int8 GEMM features — also the bench's CPU proxy for the serving
    fast path: with dtype 'int8' the x/w panels stream at 1 B/elem
    (plus the f32 dequant epilogue write); the SAME formula at a float
    dtype models the unquantized matmul the site replaced, so
    bench.py's HBM-bytes-per-request ratio (BENCH_MODEL=serving_quant)
    is one feature function evaluated at two itemsizes."""
    M, K, N = params["M"], params["K"], params["N"]
    item = _FEATURE_ITEMSIZE.get(params.get("dtype", "int8"), 1)
    bm = int(cfg.get("block_m", M) or M)
    bn = int(cfg.get("block_n", N) or N)
    gm, gn = M // max(1, bm), N // max(1, bn)
    grid = gm * gn
    # x panel re-streams per n-block, w panel per m-block; the output
    # writes once — int32 accumulator materialized at 4 B then scaled
    hbm = gn * M * K * item + gm * K * N * item + M * N * 4
    ws = 2 * (bm * K + K * bn) * item + bm * bn * 4
    return hbm, grid, ws


_FEATURE_ITEMSIZE = {"int8": 1, "bfloat16": 2, "float32": 4}


_FEATURES: Dict[str, Callable] = {
    "bahdanau_attention": _features_bahdanau,
    "flash_attention": _features_flash,
    "fused_conv": _features_conv,
    "fused_lstm": _features_rnn("lstm"),
    "fused_gru": _features_rnn("gru"),
    "quant_matmul": _features_quant_matmul,
}


def predicted_cost(family: str, params: Dict[str, Any],
                   config: Config) -> float:
    """Model-predicted wall seconds for one dispatch of `config` at
    `params`. Absolute scale is nominal — only the ORDERING feeds the
    guided search."""
    fam = space.get_family(family)
    hbm, grid, ws = _FEATURES[fam.name](params, config)
    mem_s = hbm / _HBM_BYTES_PER_S
    overhead_s = grid * _GRID_STEP_S
    frac = ws / space._vmem_budget()
    spill = mem_s * _SPILL_GAIN * max(0.0, frac - _SPILL_KNEE) ** 2 \
        / (1.0 - _SPILL_KNEE) ** 2
    return mem_s + overhead_s + spill


def rank_candidates(family: str, params: Dict[str, Any],
                    dtype: str) -> List[Config]:
    """The family's legal candidates, best-predicted first (ties broken
    by config key for determinism)."""
    fam = space.get_family(family)
    norm = fam.normalize(params, dtype)
    cands = fam.candidates(norm)
    return sorted(cands, key=lambda c: (predicted_cost(fam.name, norm, c),
                                        config_key(c)))


# ------------------------------------------------------ guided search --
class SearchResult:
    """What the guided searcher hands back: the winner, its median, and
    the audit trail (which configs were timed, at which rungs, and why
    the search stopped)."""

    def __init__(self, best: Config, best_s: float,
                 timings: Dict[Tuple, float], n_candidates: int,
                 rungs_run: int, stopped_early: bool):
        self.best = best
        self.best_s = best_s
        self.timings = timings  # config_key -> best median observed
        self.n_candidates = n_candidates
        self.rungs_run = rungs_run
        self.stopped_early = stopped_early

    @property
    def n_timed(self) -> int:
        return len(self.timings)

    @property
    def timed_fraction(self) -> float:
        return self.n_timed / max(1, self.n_candidates)


def guided_search(
    candidates: Sequence[Config],
    oracle: Callable[[Config, int], float],
    *,
    ranked: bool = True,
    budget_fraction: float = 0.4,
    min_probes: int = 3,
    rungs: Sequence[int] = (1, 3, 7),
    stable_rounds: int = 2,
) -> SearchResult:
    """Successive-halving search over `candidates` (already cost-model
    ranked when `ranked`; pass ranked=False to shuffle-free-sweep an
    unranked list — the A/B baseline).

    - probes the top max(min_probes, budget_fraction * |space|)
      candidates, never more than the space holds;
    - rung r times every survivor at `rungs[r]` iterations and keeps
      the better half (the oracle's median at higher iters REPLACES the
      cheaper estimate — a lucky low-iter probe can't coast to a win);
    - stops early once the leader has been the same config for
      `stable_rounds` consecutive rungs, or when one survivor remains.

    The oracle returns median seconds for (config, iters); +inf marks a
    config that failed numerics/compile and drops it immediately.
    """
    cands = list(candidates)
    if not cands:
        raise ValueError("guided_search: empty candidate list")
    # floor, not ceil: "time at most budget_fraction of the space" must
    # hold exactly for spaces where the bound bites (8 candidates at
    # 0.4 probes 3, not 4); min_probes floors only the tiny spaces
    # where a fraction would probe nothing
    k = min(len(cands), max(int(min_probes),
                            int(budget_fraction * len(cands))))
    survivors = cands[:k]
    timings: Dict[Tuple, float] = {}
    leader: Optional[Tuple] = None
    stable = 0
    rungs_run = 0
    stopped_early = False
    for iters in rungs:
        rungs_run += 1
        scored = []
        for cfg in survivors:
            t = oracle(cfg, iters)
            key = config_key(cfg)
            timings[key] = t if key not in timings \
                else (t if t != float("inf") else timings[key])
            if t != float("inf"):
                scored.append((t, key, cfg))
        if not scored:
            raise RuntimeError(
                "guided_search: every probed candidate failed the "
                "oracle (numerics/compile) — refusing to pick a winner")
        scored.sort(key=lambda x: (x[0], x[1]))
        new_leader = scored[0][1]
        stable = stable + 1 if new_leader == leader else 1
        leader = new_leader
        if len(scored) == 1:
            break
        if stable >= stable_rounds:
            stopped_early = True
            break
        survivors = [cfg for _, _, cfg in
                     scored[:max(1, math.ceil(len(scored) / 2))]]
    best_s, best_key, best = scored[0]
    return SearchResult(best, best_s, timings, len(cands), rungs_run,
                        stopped_early)


# --------------------------------------------------- simulated oracle --
class SimulatedOracle:
    """Deterministic synthetic timing surface for off-TPU tests and the
    CPU leg of bench.py tune_search.

    The surface is the cost model's shape DISTORTED per config: each
    config's true time is predicted_cost times a deterministic
    pseudo-random factor in [1-noise, 1+noise] (sha256 of seed+config —
    reproducible across processes, no RNG state), so the model's #1
    pick is frequently NOT the true best and the searcher has to earn
    the win by probing. `calls` counts oracle invocations and `timed`
    the distinct configs probed — the two numbers the <=40% acceptance
    bound reads."""

    def __init__(self, family: str, params: Dict[str, Any], dtype: str,
                 seed: int = 0, noise: float = 0.10):
        fam = space.get_family(family)
        self.family = fam.name
        self.params = fam.normalize(params, dtype)
        self.seed = seed
        self.noise = noise
        self.calls = 0
        self._timed: set = set()

    def _jitter(self, key: Tuple) -> float:
        h = hashlib.sha256(
            f"{self.seed}|{self.family}|{sorted(self.params.items())}"
            f"|{key}".encode()).digest()
        u = int.from_bytes(h[:8], "big") / 2 ** 64  # [0, 1)
        return 1.0 + self.noise * (2.0 * u - 1.0)

    def true_time(self, config: Config) -> float:
        key = config_key(config)
        return predicted_cost(self.family, self.params, config) \
            * self._jitter(key)

    def __call__(self, config: Config, iters: int) -> float:
        self.calls += 1
        self._timed.add(config_key(config))
        return self.true_time(config)

    @property
    def timed(self) -> int:
        return len(self._timed)

    def exhaustive_best(self, candidates: Sequence[Config]) \
            -> Tuple[Config, float]:
        """Ground truth: the true best over the whole space (what an
        exhaustive sweep would find), without counting probes."""
        best, best_s = None, float("inf")
        for cfg in candidates:
            t = self.true_time(cfg)
            if t < best_s or (t == best_s and best is not None
                              and config_key(cfg) < config_key(best)):
                best, best_s = cfg, t
        return best, best_s
