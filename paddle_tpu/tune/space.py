"""Per-kernel candidate spaces: legality predicates + generators.

THE design rule of this module: the legality model is defined ONCE and
shared by the tuner and the runtime. `ops/bahdanau_kernels._bblk`
imports `bahdanau_blk_legal` from here; `ops/fused_conv_ops._block_rows`
imports `conv_rows_legal`; `ops/flash_ops` imports `flash_block_legal`.
So a candidate this module emits is exactly a config the runtime will
accept, and a config the runtime accepts is exactly one this module can
enumerate — the tuner can never measure a config that later fails to
lower, and the property test (tests/test_tune.py) pins the equivalence.

Legality has two ingredients per family:
- Mosaic tile rules: the last-two-dims (8k, 128k)-or-full block-shape
  rule (see the hard-won comments in bahdanau_kernels._tmask_bt), lane
  alignment, and divide-the-array constraints;
- the VMEM-budget working-set models lifted from the kernels (sized
  against the 15 MiB scoped budget in ops/pallas_kernels._VMEM_BUDGET,
  which reproduces every measured compile overflow — see its comment).

Anything in `ops/` is imported lazily: this module loads during
`paddle_tpu.core` import (via tune.overrides via the Executor), before
the ops package exists.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

Params = Dict[str, Any]
Config = Dict[str, Any]


def _vmem_budget() -> int:
    from ..ops.pallas_kernels import _VMEM_BUDGET

    return _VMEM_BUDGET


def pad_s(s: int) -> int:
    """Source-length padding shared with bahdanau_kernels._pad_s: the
    attention kernels run over S padded to a sublane-tileable multiple
    of 16."""
    return ((s + 15) // 16) * 16


def _dtype_of(name: str):
    import jax.numpy as jnp

    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "int8": jnp.int8}[name]


# io dtypes the spaces can key on: int8 joined with the quantized-matmul
# family (the serving fast path) — tuned int8 is just another column of
# the same per-device table.
DTYPES = ("bfloat16", "float32", "int8")


def _itemsize(dtype_name: str) -> int:
    return {"bfloat16": 2, "float32": 4, "int8": 1}[dtype_name]


# ------------------------------------------------------------- bahdanau --
def bahdanau_blk_legal(b: int, B: int, Sp: int, A: int, C: int,
                       itemsize: int) -> bool:
    """Batch-tile legality shared by ALL the attention kernels (fwd,
    bwd-step, phase-2 share one eligibility so a config never runs fused
    forward and then fails to tile the backward). Divisibility: b must
    divide B, and be a sublane multiple (8) unless it spans the whole
    batch dim — the Mosaic last-two-dims (8k, 128k)-or-full rule (B=4
    and B=2 verified lowering on v5e hardware, round 5). The VMEM term
    models the largest working set in the family (phase-2's):
    double-buffered ep/enc io tiles, the once-written io-dtype dep
    output block, and five f32 [blk, Sp, A] working arrays."""
    if b <= 0 or B <= 0 or B % b:
        return False
    if b % 8 and b != B:
        return False
    return ((2 * Sp * (A + C) + Sp * A) * b * itemsize
            + 5 * b * Sp * A * 4) <= _vmem_budget()


def bahdanau_candidates(params: Params) -> List[Config]:
    B, Sp, A, C = params["B"], params["Sp"], params["A"], params["C"]
    item = _itemsize(params["dtype"])
    out = []
    for b in range(1, B + 1):
        if B % b == 0 and bahdanau_blk_legal(b, B, Sp, A, C, item):
            out.append({"bblk": b})
    return out


def bahdanau_default(params: Params) -> Optional[Config]:
    """The runtime's analytic choice (bahdanau_kernels._bblk fallback
    order): 8 measured best on v5e at the NMT shapes; 4 and 2 for small
    batches only."""
    B, Sp, A, C = params["B"], params["Sp"], params["A"], params["C"]
    item = _itemsize(params["dtype"])
    for b in (8, 4, 2):
        if bahdanau_blk_legal(b, B, Sp, A, C, item):
            return {"bblk": b}
    return None


def _bahdanau_case(params: Params, dtype: str) -> "Case":
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..ops import bahdanau_kernels as bk

    B, Sp, A, C = params["B"], params["Sp"], params["A"], params["C"]
    rng = np.random.RandomState(0)
    dt = _dtype_of(dtype)
    ep = jnp.asarray(rng.randn(B, Sp, A) * 0.3, dt)
    enc = jnp.asarray(rng.randn(B, Sp, C) * 0.3, dt)
    dp = jnp.asarray(rng.randn(B, A) * 0.3, dt)
    v = jnp.asarray(rng.randn(A) / np.sqrt(A), dt)
    maskf = jnp.ones((B, Sp), jnp.float32)
    interpret = jax.default_backend() != "tpu"
    args = (ep, enc, dp, v, maskf)

    def make(config: Config) -> Callable[[], Any]:
        from . import overrides

        def f(ep, enc, dp, v, maskf):
            return bk._attn_fwd(ep, enc, dp, v, maskf, interpret)[0]

        jf = jax.jit(f)
        with overrides.forcing("bahdanau_attention", config):
            jf(*args)  # trace+compile while the forced tile is active
        return lambda: jf(*args)

    def ref():
        epf, encf = np.asarray(ep, np.float32), np.asarray(enc, np.float32)
        dpf, vf = np.asarray(dp, np.float32), np.asarray(v, np.float32)
        t = np.tanh(epf + dpf[:, None, :])
        scores = (t * vf[None, None, :]).sum(-1)
        scores = np.where(np.asarray(maskf) > 0, scores, -1e9)
        e = np.exp(scores - scores.max(-1, keepdims=True))
        alpha = e / e.sum(-1, keepdims=True)
        return [np.einsum("bs,bsc->bc", alpha, encf)]

    return Case("bahdanau_attention", make, ref,
                tol=2e-2 if dtype == "bfloat16" else 2e-5)


# ---------------------------------------------------------------- flash --
FLASH_BLOCK_GRID = (128, 256, 384, 512, 640, 768, 1024, 1536, 2048)


def flash_block_legal(bq: int, bk: int, Tq: int, Tk: int) -> bool:
    """The TPU flash kernel requires blocks to DIVIDE the sequence and
    be lane-aligned (128) — ops/flash_ops._v5e_block_sizes rounds its
    target down through exactly this predicate."""
    return (bq > 0 and bk > 0 and bq % 128 == 0 and bk % 128 == 0
            and Tq % bq == 0 and Tk % bk == 0)


def flash_candidates(params: Params) -> List[Config]:
    Tq, Tk = params["Tq"], params["Tk"]
    qs = [b for b in FLASH_BLOCK_GRID if flash_block_legal(b, 128, Tq, 128)]
    ks = [b for b in FLASH_BLOCK_GRID if flash_block_legal(128, b, 128, Tk)]
    return [{"block_q": q, "block_k": k} for q in qs for k in ks]


def flash_default(params: Params) -> Optional[Config]:
    """The v5e-tuned heuristic (flash_ops._v5e_block_sizes): 512-wide
    blocks up to T=4096, 1024 from 8192, rounded down to a divisor."""
    def blk(T):
        if T % 128:
            return 0
        b = min(T, 512 if T < 8192 else 1024)
        while T % b:
            b -= 128
        return b

    bq, bk = blk(params["Tq"]), blk(params["Tk"])
    if not bq or not bk:
        return None
    return {"block_q": bq, "block_k": bk}


def _flash_case(params: Params, dtype: str) -> "Case":
    import numpy as np

    import jax.numpy as jnp

    from ..ops import flash_ops

    B = params.get("B", 4)
    H = params.get("H", 8)
    D = params.get("D", 128)
    Tq, Tk = params["Tq"], params["Tk"]
    rng = np.random.RandomState(0)
    dt = _dtype_of(dtype)
    q = jnp.asarray(rng.randn(B, Tq, H, D) * 0.1, dt)
    k = jnp.asarray(rng.randn(B, Tk, H, D) * 0.1, dt)
    v = jnp.asarray(rng.randn(B, Tk, H, D) * 0.1, dt)
    args = (q, k, v)

    def make(config: Config) -> Callable[[], Any]:
        import jax

        from . import overrides

        jf = jax.jit(lambda q, k, v: flash_ops._flash_kernel(
            q, k, v, causal=False))
        with overrides.forcing("flash_attention", config):
            jf(*args)
        return lambda: jf(*args)

    def ref():
        return [np.asarray(
            flash_ops.scaled_dot_product_attention(q, k, v, causal=False),
            np.float32)]

    return Case("flash_attention", make, ref,
                tol=5e-2 if dtype == "bfloat16" else 2e-4)


# ----------------------------------------------------------- fused conv --
CONV_ROW_BLOCKS = (1024, 896, 768, 640, 512, 448, 384, 320, 256, 192,
                   128, 64, 32, 16, 8)


def conv_rows_legal(b: int, n: int, cin: int, cout: int,
                    itemsize: int) -> bool:
    """Row-block legality for the fused 1x1-conv+BN kernel: tiles the
    8-row sublane, divides n, and fits the working set (x/y blocks
    double-buffered by the pipeline machinery, full weight panel, f32
    accumulators) in VMEM."""
    if b <= 0 or b % 8 or n % b:
        return False
    weight = cin * cout * itemsize
    io = 2 * b * (cin + cout) * itemsize
    return weight + io + 2 * 4 * cout + 4 * cin * 4 <= _vmem_budget()


def conv_candidates(params: Params) -> List[Config]:
    n, cin, cout = params["n"], params["cin"], params["cout"]
    item = _itemsize(params["dtype"])
    return [{"block_rows": b} for b in sorted(CONV_ROW_BLOCKS)
            if conv_rows_legal(b, n, cin, cout, item)]


def conv_default(params: Params) -> Optional[Config]:
    """The runtime's analytic choice (fused_conv_ops._block_rows):
    largest legal block in the fixed descending list."""
    n, cin, cout = params["n"], params["cin"], params["cout"]
    item = _itemsize(params["dtype"])
    for b in CONV_ROW_BLOCKS:
        if conv_rows_legal(b, n, cin, cout, item):
            return {"block_rows": b}
    return None


def _conv_case(params: Params, dtype: str) -> "Case":
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..ops import fused_conv_ops as fc

    n, cin, cout = params["n"], params["cin"], params["cout"]
    rng = np.random.RandomState(0)
    dt = _dtype_of(dtype)
    x = jnp.asarray(rng.randn(n, cin) * 0.3, dt)
    w = jnp.asarray(rng.randn(cin, cout) / np.sqrt(cin), dt)
    pm = jnp.asarray(rng.randn(cin) * 0.1, jnp.float32)
    pi = jnp.asarray(1.0 + 0.1 * rng.rand(cin), jnp.float32)
    ps = jnp.asarray(1.0 + 0.1 * rng.rand(cin), jnp.float32)
    pb = jnp.asarray(rng.randn(cin) * 0.1, jnp.float32)
    interpret = jax.default_backend() != "tpu"
    args = (x, w, pm, pi, ps, pb)

    def make(config: Config) -> Callable[[], Any]:
        from . import overrides

        def f(x, w, pm, pi, ps, pb):
            return fc._pallas_fwd(x, w, pm, pi, ps, pb, True, True,
                                  interpret)

        jf = jax.jit(f)
        with overrides.forcing("fused_conv", config):
            jf(*args)
        return lambda: jf(*args)

    def ref():
        y, s, sq = fc._jnp_fused(x, w, pm, pi, ps, pb, True, True)
        return [np.asarray(y, np.float32), np.asarray(s), np.asarray(sq)]

    return Case("fused_conv", make, ref,
                tol=5e-2 if dtype == "bfloat16" else 2e-4)


# ------------------------------------------------------------- RNN cells --
def _rnn_hard_ok(kind: str, B: int, H: int, itemsize: int) -> bool:
    """Hard (non-empirical) fused-RNN legality: tile alignment + the
    backward-kernel VMEM model from ops/pallas_kernels — everything in
    lstm_supported/gru_supported EXCEPT the measured H-window, which is
    exactly the judgment the tuner replaces."""
    from ..ops import pallas_kernels as pk

    if not (B >= 8 and B % 8 == 0 and H % 128 == 0):
        return False
    g = 4 if kind == "lstm" else 3
    dw_max = (pk._LSTM_FUSED_DW_MAX_H if kind == "lstm"
              else pk._GRU_FUSED_DW_MAX_H)
    return pk._bwd_vmem_bytes(B, H, g, itemsize, dw_max) <= pk._VMEM_BUDGET


def _rnn_candidates(kind: str):
    def gen(params: Params) -> List[Config]:
        out = [{"fused": False}]
        if _rnn_hard_ok(kind, params["B"], params["H"],
                        _itemsize(params["dtype"])):
            out.insert(0, {"fused": True})
        return out

    return gen


def _rnn_default(kind: str):
    def default(params: Params) -> Config:
        B, H = params["B"], params["H"]
        if not _rnn_hard_ok(kind, B, H, _itemsize(params["dtype"])):
            return {"fused": False}
        # the measured windows (benchmarks/rnn_kernel_microbench.json)
        if kind == "lstm":
            return {"fused": 384 <= H <= 1280}
        return {"fused": 128 <= H <= 1280 and H != 384}

    return default


# ----------------------------------------------------------- quant matmul --
# Output-tile grids for the int8 GEMM: block_m walks the int8 sublane
# tile (32 — Mosaic's (32, 128) minimum int8 tile, pallas guide), block_n
# the 128 lane dim.
QUANT_BLOCK_M = (32, 64, 128, 256, 512)
QUANT_BLOCK_N = (128, 256, 512, 1024)


def quant_matmul_legal(bm: int, bn: int, M: int, K: int, N: int) -> bool:
    """Tile legality of the int8×int8→int32 kernel
    (ops/quant_kernels._quant_matmul_pallas): blocks divide the output,
    respect int8's (32, 128) minimum tile (unless spanning the whole
    dim), and the working set — double-buffered int8 x/w panels plus
    the int32 accumulator block — fits VMEM."""
    if bm <= 0 or bn <= 0 or M % bm or N % bn:
        return False
    if bm % 32 and bm != M:
        return False
    if bn % 128 and bn != N:
        return False
    ws = 2 * (bm * K + K * bn) * 1 + bm * bn * 4
    return ws <= _vmem_budget()


def quant_matmul_candidates(params: Params) -> List[Config]:
    M, K, N = params["M"], params["K"], params["N"]
    # M and N themselves join the grids so shapes below the minimum
    # tile (e.g. a batch-1 bucket) still have the whole-dim candidate
    ms = sorted({b for b in (*QUANT_BLOCK_M, M) if M % b == 0})
    ns = sorted({b for b in (*QUANT_BLOCK_N, N) if N % b == 0})
    return [{"block_m": bm, "block_n": bn}
            for bm in ms for bn in ns
            if quant_matmul_legal(bm, bn, M, K, N)]


def quant_matmul_default(params: Params) -> Optional[Config]:
    """Analytic choice of the runtime fallback: the largest legal
    output tile (fewest grid steps — the int8 panels are small enough
    that dispatch overhead, not VMEM, dominates at serving shapes)."""
    M, K, N = params["M"], params["K"], params["N"]
    best = None
    for bm in sorted({*QUANT_BLOCK_M, M}, reverse=True):
        if M % bm:
            continue
        for bn in sorted({*QUANT_BLOCK_N, N}, reverse=True):
            if N % bn:
                continue
            if quant_matmul_legal(bm, bn, M, K, N):
                return {"block_m": bm, "block_n": bn}
    return best


def _quant_case(params: Params, dtype: str) -> "Case":
    import numpy as np

    import jax

    from ..ops import quant_kernels as qk

    M, K, N = params["M"], params["K"], params["N"]
    rng = np.random.RandomState(0)
    import jax.numpy as jnp

    xq = jnp.asarray(rng.randint(-127, 128, (M, K)), jnp.int8)
    wq = jnp.asarray(rng.randint(-127, 128, (K, N)), jnp.int8)
    args = (xq, wq)

    def make(config: Config) -> Callable[[], Any]:
        from . import overrides

        jf = jax.jit(lambda x, w: qk.quant_matmul(x, w))
        with overrides.forcing("quant_matmul", config):
            jf(*args)
        return lambda: jf(*args)

    def ref():
        return [np.asarray(qk._quant_matmul_ref(xq, wq), np.int64)
                .astype(np.float32)]

    # integer contraction: every candidate must be EXACT, not close
    return Case("quant_matmul", make, ref, tol=0.0)


# --------------------------------------------------------------- registry --
class Case:
    """A runnable tuning case: `make(config)` returns a zero-arg
    compiled thunk (traced while the config override was forced), and
    `reference()` the analytic-lowering outputs for the numeric
    cross-check."""

    def __init__(self, kernel: str, make, reference, tol: float):
        self.kernel = kernel
        self.make = make
        self.reference = reference
        self.tol = tol


class KernelSpace:
    def __init__(self, name: str, param_names, candidates, default,
                 make_case=None, doc: str = ""):
        self.name = name
        self.param_names = tuple(param_names)
        self._candidates = candidates
        self._default = default
        self._make_case = make_case
        self.doc = doc

    def normalize(self, params: Params, dtype: str) -> Params:
        """Validated, canonically-ordered params incl. dtype — the shape
        signature the cache keys on."""
        if dtype not in DTYPES:
            raise ValueError(f"{self.name}: dtype must be one of "
                             f"{DTYPES}, got {dtype!r}")
        missing = [k for k in self.param_names if k not in params]
        if missing:
            raise ValueError(
                f"{self.name}: missing shape params {missing}; needs "
                f"{list(self.param_names)}")
        norm = {k: int(params[k]) for k in self.param_names}
        norm["dtype"] = dtype
        return norm

    def candidates(self, params: Params) -> List[Config]:
        return self._candidates(params)

    def default(self, params: Params) -> Optional[Config]:
        return self._default(params)

    def make_case(self, params: Params, dtype: str) -> Case:
        if self._make_case is None:
            raise NotImplementedError(
                f"kernel family {self.name!r} has no measurement runner "
                "yet (candidates/--dry-run only)")
        return self._make_case(params, dtype)


FAMILIES: Dict[str, KernelSpace] = {
    "bahdanau_attention": KernelSpace(
        "bahdanau_attention", ("B", "Sp", "A", "C"),
        bahdanau_candidates, bahdanau_default, _bahdanau_case,
        doc="batch tile (bblk) of the fused Bahdanau decoder kernels"),
    "flash_attention": KernelSpace(
        "flash_attention", ("Tq", "Tk"),
        flash_candidates, flash_default, _flash_case,
        doc="q/k block sizes of the TPU flash-attention kernel"),
    "fused_conv": KernelSpace(
        "fused_conv", ("n", "cin", "cout"),
        conv_candidates, conv_default, _conv_case,
        doc="row block of the fused 1x1-conv+BN kernel"),
    "fused_lstm": KernelSpace(
        "fused_lstm", ("B", "H"),
        _rnn_candidates("lstm"), _rnn_default("lstm"),
        doc="fused-vs-scan dispatch of the whole-sequence LSTM kernel"),
    "fused_gru": KernelSpace(
        "fused_gru", ("B", "H"),
        _rnn_candidates("gru"), _rnn_default("gru"),
        doc="fused-vs-scan dispatch of the whole-sequence GRU kernel"),
    "quant_matmul": KernelSpace(
        "quant_matmul", ("M", "K", "N"),
        quant_matmul_candidates, quant_matmul_default, _quant_case,
        doc="output tile (block_m, block_n) of the int8×int8→int32 "
            "quantized-matmul kernel"),
}

ALIASES = {"bahdanau": "bahdanau_attention", "attention": "bahdanau_attention",
           "flash": "flash_attention", "conv": "fused_conv",
           "lstm": "fused_lstm", "gru": "fused_gru",
           "quant": "quant_matmul", "int8": "quant_matmul"}


def get_family(name: str) -> KernelSpace:
    key = ALIASES.get(name, name)
    if key not in FAMILIES:
        raise KeyError(
            f"unknown kernel family {name!r}; known: "
            f"{sorted(FAMILIES)} (aliases {sorted(ALIASES)})")
    return FAMILIES[key]


def config_legal(family: str, params: Params, dtype: str,
                 config: Config) -> bool:
    """Is `config` a legal candidate for `params` — i.e. would the
    candidate generator itself have emitted it? THE re-validation gate
    for shape-interpolated lookups (tune/overrides.py): a config tuned
    at a NEIGHBORING shape is only usable at the target shape if it is
    inside the target's own candidate set, so an interpolated consult
    can never hand the runtime a tile its legality model rejects.
    Membership (not just predicate re-evaluation) is deliberate: the
    generators encode extra structure — divisor grids, the fixed block
    lists — that a bare predicate check would miss. Malformed
    params/config degrade to False, never raise (interpolation feeds
    arbitrary table contents through here)."""
    try:
        fam = get_family(family)
        norm = fam.normalize(params, dtype)
        return dict(config) in fam.candidates(norm)
    except (KeyError, ValueError, TypeError):
        return False


# ------------------------------------------------- model program sweep --
def cases_from_program(program=None, dp: int = 1) -> List[Dict[str, Any]]:
    """Best-effort scan of a Program for tunable kernel sites with
    concrete shapes: returns [{family, params, dtype, op}] — the CLI's
    `tune --config model.py` sweep source. Sites whose shapes aren't
    fully concrete (e.g. -1 batch) are skipped; the per-kernel
    `--kernel/--shape` path covers those.

    `dp` is the data-parallel degree the model will RUN under: the
    fused kernels dispatch inside shard_map at the PER-SHARD batch
    (ops/mesh_dispatch.local_batch — ADVICE.md's per-shard eligibility
    lesson), so tuning must key on the per-shard shape too, or every
    mesh run misses the table and a global-batch entry tunes a shape
    that never dispatches. Batch-carrying params divide by dp;
    non-divisible sites are skipped (the runtime falls back to the
    scan/XLA formulation there — nothing to tune). The fused-conv
    kernel is not mesh-wrapped at all (mesh_dispatch docstring), so its
    sites are skipped entirely under dp > 1."""
    from ..core.program import default_main_program

    program = program or default_main_program()
    if dp < 1:
        raise ValueError(f"dp must be >= 1, got {dp}")
    amp_dt = "bfloat16" if getattr(program, "amp_dtype", None) else "float32"
    out = []

    def var_shape(block, name):
        try:
            return [int(d) for d in block.var(name).shape]
        except (KeyError, TypeError, ValueError):
            return None

    for block in program.blocks:
        for op in block.ops:
            if op.type == "flash_attention":
                # only the sequence lengths key the flash space — a -1
                # batch dim (the usual data() declaration) is fine
                s = var_shape(block, op.inputs["Q"][0])
                k = var_shape(block, op.inputs["K"][0])
                if not s or not k or len(s) < 3 or s[1] <= 0 or k[1] <= 0:
                    continue
                out.append({"family": "flash_attention",
                            "params": {"Tq": s[1], "Tk": k[1]},
                            "dtype": amp_dt, "op": op.type})
            elif op.type == "fused_conv_bn":
                if dp > 1:
                    continue  # not mesh-wrapped: falls back under a mesh
                s = var_shape(block, op.inputs["X"][0])
                w = var_shape(block, op.inputs["Filter"][0])
                if not s or not w or len(s) != 4 or min(s) <= 0:
                    continue
                stride = int(op.attrs.get("stride", 1))
                h, wd = s[1] // stride, s[2] // stride
                out.append({"family": "fused_conv",
                            "params": {"n": s[0] * h * wd, "cin": w[1],
                                       "cout": w[0]},
                            "dtype": amp_dt, "op": op.type})
            elif op.type == "attention_gru_decoder":
                enc = var_shape(block, op.inputs["EncState"][0])
                wa = var_shape(block, op.inputs["WaEnc"][0])
                h0 = var_shape(block, op.inputs["H0"][0])
                if not enc or not wa or not h0 or h0[0] <= 0:
                    continue
                if h0[0] % dp:
                    continue  # ragged shard: runtime scans, nothing to tune
                src = int(op.attrs.get("src_max_len") or 0)
                if src <= 0:
                    continue
                out.append({"family": "bahdanau_attention",
                            "params": {"B": h0[0] // dp, "Sp": pad_s(src),
                                       "A": wa[1], "C": enc[-1]},
                            "dtype": amp_dt, "op": op.type})
            elif op.type in ("quantized_mul", "quantized_matmul"):
                # int8 sites (quant/convert.py rewrite): the weight
                # panel [K, N] is static; the row count comes from X
                # when concrete (serving buckets expand the -1 case via
                # engine.decode_tune_cases)
                x = var_shape(block, op.inputs["X"][0])
                w = var_shape(block, op.inputs["Y"][0])
                if not x or not w or len(w) != 2 or min(w) <= 0:
                    continue
                xd = int(op.attrs.get("x_num_col_dims", 1))
                lead = x[:xd]
                if any(d <= 0 for d in lead):
                    continue
                m = 1
                for d in lead:
                    m *= d
                if m % dp:
                    continue
                out.append({"family": "quant_matmul",
                            "params": {"M": m // dp, "K": w[0],
                                       "N": w[1]},
                            "dtype": "int8", "op": op.type})
            # dynamic_lstm/dynamic_gru sites are LoD-batched: their
            # runtime batch is not static in the program, so the model
            # sweep skips them — tune those via --kernel lstm/gru with
            # an explicit --shape B=...,H=...
    return out
