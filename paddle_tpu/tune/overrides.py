"""Central per-kernel config override registry.

Every tunable kernel consults THIS module at trace time instead of
reading env vars or tables itself. Lookup precedence (Autotuner v2):

  1. forced override — programmatic `force()` (the harness pins each
     candidate this way while timing it) or a legacy env knob
     (PT_ATTN_BBLK keeps working, routed through here);
  2. the EXACT persistent tuned table (tune/cache.py), keyed by
     (kernel, shape signature, dtype, device_kind) — the user's local
     table first, then the read-through BASE table shipped with the
     package for this device kind (tune/tables/<device_kind>.json;
     a local entry always shadows the shipped one);
  3. shape INTERPOLATION — a lookup miss falls through to the nearest
     tuned entry for the same kernel/dtype/device by log-space shape
     distance (CLBlast's database lesson: a config measured at a
     nearby shape transfers most of its win), but ONLY if that config
     passes the target shape's own legality model
     (space.config_legal) — an interpolated consult can never hand
     the runtime a tile it would reject. Neighbors that fail the
     re-check are skipped in distance order; none legal -> analytic;
  4. None — the caller applies its analytic default.

Every consult's PROVENANCE is recorded (`consult_stats()`:
forced/env/table/interpolated/analytic) and exported as
`pt_tune_consults_total{source=}` through obs.MetricsRegistry, so one
/metrics scrape shows the tuned-coverage of a live process.

The consumer contract (see ops/bahdanau_kernels._bblk): a FORCED config
that fails the family's legality predicate warns and disables the fused
path (the operator asked for exactly that tile; silently substituting
another would invalidate their sweep), while a stale TABLE or
INTERPOLATED entry that fails legality is ignored and the analytic
default applies (a shipped table must never break a model).
`Override.source` tells the cases apart.

`fingerprint()` is the piece the Executor folds into its jit cache key:
a content hash over everything that can change a lookup result — forced
configs, legacy env knobs, the local AND base tables, and the
FLAGS.use_tuned_table / FLAGS.tune_interpolate knobs — so ANY future
kernel knob invalidates the jit cache without the executor learning
about it.
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import math
import os
import threading
from typing import Any, Dict, NamedTuple, Optional, Tuple

from ..flags import FLAGS
from . import cache as _cache

# legacy env knobs, mapped into override configs: kernel -> (env var,
# config key, parser). A parsed value of 0/empty means "unset" (the
# pre-tuner PT_ATTN_BBLK semantics).
ENV_KNOBS = {
    "bahdanau_attention": ("PT_ATTN_BBLK", "bblk", int),
}

# interpolation acceptance radius in log-space: sqrt(sum_k ln(p/q)^2)
# over the shared shape params. 2x on every axis of a 2-param family is
# ~0.98; the default admits roughly "within 4x on one axis or 2-3x on
# two" — far enough to bridge bucket grids, near enough that the tile
# economics plausibly transfer. Beyond it the analytic default is the
# better guess.
INTERP_MAX_DIST = 1.5

CONSULT_SOURCES = ("forced", "env", "table", "interpolated", "analytic")


class Override(NamedTuple):
    config: Dict[str, Any]
    source: str  # "forced" | "env" | "table" | "interpolated"
    # for interpolated lookups: the donor entry's shape signature (the
    # provenance trail warmup reports name)
    origin: Optional[str] = None


_lock = threading.RLock()
_forced: Dict[str, Dict[str, Any]] = {}
_table: Optional[_cache.TunedTable] = None
_table_path: Optional[str] = None  # None -> flag/env/default resolution
_base: Optional[_cache.TunedTable] = None
_base_loaded = False
_consults: Dict[str, int] = {s: 0 for s in CONSULT_SOURCES}
# interpolation results are pure functions of (tables, target key) —
# memoized per table fingerprints so the trace-time cost of a miss is
# one dict hit after the first consult of a shape
_interp_cache: Dict[Tuple, Optional[Tuple[Dict[str, Any], str]]] = {}


# ------------------------------------------------------------- forcing --
def force(kernel: str, config: Optional[Dict[str, Any]]) -> None:
    """Pin (or with None, unpin) a kernel family's config
    process-wide. Takes effect at the next trace — the Executor's cache
    key includes fingerprint(), so the next run() re-traces."""
    with _lock:
        if config is None:
            _forced.pop(kernel, None)
        else:
            _forced[kernel] = dict(config)


@contextlib.contextmanager
def forcing(kernel: str, config: Optional[Dict[str, Any]]):
    """Scoped force() — the harness traces each candidate under this."""
    with _lock:
        prev = _forced.get(kernel)
    force(kernel, config)
    try:
        yield
    finally:
        force(kernel, prev)


def _env_override(kernel: str) -> Optional[Dict[str, Any]]:
    knob = ENV_KNOBS.get(kernel)
    if not knob:
        return None
    env_var, key, parse = knob
    raw = os.environ.get(env_var)
    if not raw:
        return None
    try:
        val = parse(raw)
    except (TypeError, ValueError):
        return None
    return {key: val} if val else None


def forced_config(kernel: str) -> Optional[Override]:
    """Forced layer only (programmatic beats env)."""
    with _lock:
        cfg = _forced.get(kernel)
    if cfg is not None:
        return Override(dict(cfg), "forced")
    env = _env_override(kernel)
    if env is not None:
        return Override(env, "env")
    return None


# --------------------------------------------------------------- table --
def table() -> _cache.TunedTable:
    """The process's LOCAL tuned table, lazily loaded from
    set_table_path() else PT_TUNE_CACHE else the per-user default. A
    missing file is an empty table (every lookup misses -> base table /
    interpolation / analytic defaults)."""
    global _table
    with _lock:
        if _table is None:
            _table = _cache.TunedTable(_table_path or _cache.default_path())
        return _table


def base_table() -> Optional[_cache.TunedTable]:
    """The read-through base layer: the pre-tuned table the package
    ships for this device kind (tune/tables/<device_kind>.json), or
    None when there is none — every non-TPU dev box, which is exactly
    why shipping tables can never change CPU-suite behavior. Loaded
    once per process (reload_table() re-probes)."""
    global _base, _base_loaded
    with _lock:
        if not _base_loaded:
            path = _cache.base_table_path()
            _base = _cache.TunedTable(path) if path else None
            _base_loaded = True
        return _base


def set_table_path(path: Optional[str]) -> None:
    """Point the registry at a table file (None reverts to the
    default resolution); the current table is dropped and reloaded
    lazily."""
    global _table, _table_path
    with _lock:
        _table_path = path
        _table = None
        _interp_cache.clear()


def reload_table() -> None:
    """Drop the in-memory tables so the next lookup rereads the files —
    call after an external tune run wrote new entries."""
    global _table, _base, _base_loaded
    with _lock:
        _table = None
        _base = None
        _base_loaded = False
        _interp_cache.clear()


# ------------------------------------------------------- interpolation --
def _log_distance(a: Dict[str, int], b: Dict[str, int]) -> float:
    """Log-space euclidean shape distance (CLBlast §4's nearest-shape
    criterion): symmetric in the ratio per axis, so (B=64 -> B=128) is
    as far as (B=128 -> B=64), and axes compose euclideanly. Requires
    the same param-name set — entries from an older schema of a family
    never match. inf on any non-positive dim."""
    if set(a) != set(b):
        return float("inf")
    d2 = 0.0
    for k, va in a.items():
        vb = b[k]
        if va <= 0 or vb <= 0:
            return float("inf")
        d2 += math.log(va / vb) ** 2
    return math.sqrt(d2)


def _interpolate(kernel: str, params: Dict[str, Any], dtype: str
                 ) -> Optional[Tuple[Dict[str, Any], str]]:
    """Nearest tuned neighbor whose config is LEGAL at the target
    shape, or None. Pool = local table entries + base-table entries
    (local shadows base per exact signature); candidates are walked in
    distance order and each must pass space.config_legal for the
    TARGET params before it may win — the property test's contract."""
    from . import space as _space

    target = {k: int(v) for k, v in params.items() if k != "dtype"}
    pool: Dict[str, Tuple[Dict[str, int], Dict[str, Any]]] = {}
    base = base_table()
    if base is not None:
        for p, cfg, _meta in base.entries_for(kernel, dtype):
            pool[_cache.make_sig(p)] = (p, cfg)
    for p, cfg, _meta in table().entries_for(kernel, dtype):
        pool[_cache.make_sig(p)] = (p, cfg)
    target_sig = _cache.make_sig(target)
    ranked = sorted(
        ((_log_distance(target, p), sig, cfg)
         for sig, (p, cfg) in pool.items() if sig != target_sig),
        key=lambda x: (x[0], x[1]))
    for dist, sig, cfg in ranked:
        if dist > INTERP_MAX_DIST:
            break
        if _space.config_legal(kernel, target, dtype, cfg):
            return dict(cfg), sig
    return None


def _interpolated_lookup(kernel: str, params: Dict[str, Any],
                         dtype: str) -> Optional[Override]:
    base = base_table()
    key = (table().fingerprint(),
           base.fingerprint() if base is not None else "",
           kernel, _cache.make_sig(params), dtype, _cache.device_kind())
    with _lock:
        if key in _interp_cache:
            hit = _interp_cache[key]
            return Override(dict(hit[0]), "interpolated", hit[1]) \
                if hit is not None else None
    hit = _interpolate(kernel, params, dtype)
    with _lock:
        if len(_interp_cache) > 4096:
            _interp_cache.clear()
        _interp_cache[key] = hit
    if hit is None:
        return None
    return Override(dict(hit[0]), "interpolated", hit[1])


# -------------------------------------------------------------- lookup --
def _record(source: str) -> None:
    with _lock:
        _consults[source] = _consults.get(source, 0) + 1


def consult_stats() -> Dict[str, int]:
    """Per-source consult counts since process start / reset() — the
    pt_tune_consults_total{source=} families (obs/metrics.py
    collector). Every source key is always present, 0 included, so the
    first scrape already shows the full surface."""
    with _lock:
        return {s: _consults.get(s, 0) for s in CONSULT_SOURCES}


def lookup(kernel: str, params: Dict[str, Any],
           dtype: str) -> Optional[Override]:
    """The one consult point kernels call at trace time. `params` is
    the family's canonical shape dict (space.KernelSpace.param_names
    order is irrelevant — the signature sorts); `dtype` the io dtype
    name ('bfloat16'/'float32'). Precedence: forced -> env -> exact
    table (local, then shipped base) -> interpolated -> None
    (analytic)."""
    f = forced_config(kernel)
    if f is not None:
        _record(f.source)
        return f
    if not FLAGS.use_tuned_table:
        _record("analytic")
        return None
    cfg = table().get(kernel, params, dtype)
    if cfg is not None:
        _record("table")
        return Override(cfg, "table")
    base = base_table()
    if base is not None:
        cfg = base.get(kernel, params, dtype)
        if cfg is not None:
            _record("table")
            return Override(cfg, "table")
    if FLAGS.tune_interpolate:
        ov = _interpolated_lookup(kernel, params, dtype)
        if ov is not None:
            _record("interpolated")
            return ov
    _record("analytic")
    return None


def classify(kernel: str, params: Dict[str, Any],
             dtype: str) -> Tuple[str, Optional[str]]:
    """What WOULD lookup() resolve this consult to — (source, origin) —
    without recording it in the consult counters. The serving warmup
    coverage report uses this to name untuned-vs-interpolated shapes
    without inflating the very counters an operator would then read."""
    f = forced_config(kernel)
    if f is not None:
        return f.source, None
    if not FLAGS.use_tuned_table:
        return "analytic", None
    if table().get(kernel, params, dtype) is not None:
        return "table", None
    base = base_table()
    if base is not None and base.get(kernel, params, dtype) is not None:
        return "table", None
    if FLAGS.tune_interpolate:
        ov = _interpolated_lookup(kernel, params, dtype)
        if ov is not None:
            return "interpolated", ov.origin
    return "analytic", None


# --------------------------------------------------------- fingerprint --
def fingerprint() -> str:
    """Content hash over every override source. Folded into the
    Executor jit cache key: any knob change — a forced config, a legacy
    env sweep variable, a retuned/reloaded local or base table, the
    use_tuned_table / tune_interpolate flags — re-traces instead of
    silently reusing a stale kernel config."""
    with _lock:
        forced = {k: _forced[k] for k in sorted(_forced)}
    env = {var: os.environ.get(var, "")
           for (var, _, _) in ENV_KNOBS.values()}
    use_table = bool(FLAGS.use_tuned_table)
    interp = bool(FLAGS.tune_interpolate)
    tbl = table().fingerprint() if use_table else ""
    base = base_table() if use_table else None
    base_fp = base.fingerprint() if base is not None else ""
    blob = json.dumps([forced, env, use_table, interp, tbl, base_fp],
                      sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def reset() -> None:
    """Test isolation: clear forced configs, consult counters, and drop
    the tables."""
    global _table, _table_path, _base, _base_loaded
    with _lock:
        _forced.clear()
        _table = None
        _table_path = None
        _base = None
        _base_loaded = False
        _interp_cache.clear()
        for s in CONSULT_SOURCES:
            _consults[s] = 0
