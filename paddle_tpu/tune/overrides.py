"""Central per-kernel config override registry.

Every tunable kernel consults THIS module at trace time instead of
reading env vars or tables itself. Lookup precedence:

  1. forced override — programmatic `force()` (the harness pins each
     candidate this way while timing it) or a legacy env knob
     (PT_ATTN_BBLK keeps working, routed through here);
  2. the persistent tuned table (tune/cache.py), keyed by (kernel,
     shape signature, dtype, device_kind) — misses on any device the
     table wasn't measured on;
  3. None — the caller applies its analytic default.

The consumer contract (see ops/bahdanau_kernels._bblk): a FORCED config
that fails the family's legality predicate warns and disables the fused
path (the operator asked for exactly that tile; silently substituting
another would invalidate their sweep), while a stale TABLE entry that
fails legality is ignored and the analytic default applies (a shipped
table must never break a model). `Override.source` tells the two apart.

`fingerprint()` is the piece the Executor folds into its jit cache key:
a content hash over everything that can change a lookup result — forced
configs, legacy env knobs, the loaded table, and FLAGS.use_tuned_table —
so ANY future kernel knob invalidates the jit cache without the
executor learning about it (this replaced the raw PT_ATTN_BBLK string
in core/executor.py).
"""

from __future__ import annotations

import contextlib
import hashlib
import json
import os
import threading
from typing import Any, Dict, NamedTuple, Optional

from ..flags import FLAGS
from . import cache as _cache

# legacy env knobs, mapped into override configs: kernel -> (env var,
# config key, parser). A parsed value of 0/empty means "unset" (the
# pre-tuner PT_ATTN_BBLK semantics).
ENV_KNOBS = {
    "bahdanau_attention": ("PT_ATTN_BBLK", "bblk", int),
}


class Override(NamedTuple):
    config: Dict[str, Any]
    source: str  # "forced" | "env" | "table"


_lock = threading.RLock()
_forced: Dict[str, Dict[str, Any]] = {}
_table: Optional[_cache.TunedTable] = None
_table_path: Optional[str] = None  # None -> flag/env/default resolution


# ------------------------------------------------------------- forcing --
def force(kernel: str, config: Optional[Dict[str, Any]]) -> None:
    """Pin (or with None, unpin) a kernel family's config
    process-wide. Takes effect at the next trace — the Executor's cache
    key includes fingerprint(), so the next run() re-traces."""
    with _lock:
        if config is None:
            _forced.pop(kernel, None)
        else:
            _forced[kernel] = dict(config)


@contextlib.contextmanager
def forcing(kernel: str, config: Optional[Dict[str, Any]]):
    """Scoped force() — the harness traces each candidate under this."""
    with _lock:
        prev = _forced.get(kernel)
    force(kernel, config)
    try:
        yield
    finally:
        force(kernel, prev)


def _env_override(kernel: str) -> Optional[Dict[str, Any]]:
    knob = ENV_KNOBS.get(kernel)
    if not knob:
        return None
    env_var, key, parse = knob
    raw = os.environ.get(env_var)
    if not raw:
        return None
    try:
        val = parse(raw)
    except (TypeError, ValueError):
        return None
    return {key: val} if val else None


def forced_config(kernel: str) -> Optional[Override]:
    """Forced layer only (programmatic beats env)."""
    with _lock:
        cfg = _forced.get(kernel)
    if cfg is not None:
        return Override(dict(cfg), "forced")
    env = _env_override(kernel)
    if env is not None:
        return Override(env, "env")
    return None


# --------------------------------------------------------------- table --
def table() -> _cache.TunedTable:
    """The process's tuned table, lazily loaded from set_table_path()
    else PT_TUNE_CACHE else the per-user default. A missing file is an
    empty table (every lookup misses -> analytic defaults)."""
    global _table
    with _lock:
        if _table is None:
            _table = _cache.TunedTable(_table_path or _cache.default_path())
        return _table


def set_table_path(path: Optional[str]) -> None:
    """Point the registry at a table file (None reverts to the
    default resolution); the current table is dropped and reloaded
    lazily."""
    global _table, _table_path
    with _lock:
        _table_path = path
        _table = None


def reload_table() -> None:
    """Drop the in-memory table so the next lookup rereads the file —
    call after an external tune run wrote new entries."""
    global _table
    with _lock:
        _table = None


# -------------------------------------------------------------- lookup --
def lookup(kernel: str, params: Dict[str, Any],
           dtype: str) -> Optional[Override]:
    """The one consult point kernels call at trace time. `params` is
    the family's canonical shape dict (space.KernelSpace.param_names
    order is irrelevant — the signature sorts); `dtype` the io dtype
    name ('bfloat16'/'float32')."""
    f = forced_config(kernel)
    if f is not None:
        return f
    if not FLAGS.use_tuned_table:
        return None
    cfg = table().get(kernel, params, dtype)
    if cfg is not None:
        return Override(cfg, "table")
    return None


# --------------------------------------------------------- fingerprint --
def fingerprint() -> str:
    """Content hash over every override source. Folded into the
    Executor jit cache key: any knob change — a forced config, a legacy
    env sweep variable, a retuned/reloaded table, the use_tuned_table
    flag — re-traces instead of silently reusing a stale kernel
    config."""
    with _lock:
        forced = {k: _forced[k] for k in sorted(_forced)}
    env = {var: os.environ.get(var, "")
           for (var, _, _) in ENV_KNOBS.values()}
    use_table = bool(FLAGS.use_tuned_table)
    tbl = table().fingerprint() if use_table else ""
    blob = json.dumps([forced, env, use_table, tbl], sort_keys=True)
    return hashlib.sha1(blob.encode()).hexdigest()[:16]


def reset() -> None:
    """Test isolation: clear forced configs and drop the table."""
    global _table, _table_path
    with _lock:
        _forced.clear()
        _table = None
        _table_path = None
