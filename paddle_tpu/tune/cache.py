"""Persistent tuned-config table: JSON on disk, LRU in process.

Key model (the CLBlast lesson, arXiv:1705.05249 §4): a tuned config is
only valid for the exact (kernel family, shape signature, dtype, device
kind) it was measured on — a v5e-optimal tile is a guess on v4, and a
bf16 tile model doubles its VMEM take at f32. The table therefore keys
on all four, and lookups from a different device kind simply miss (the
runtime then uses its analytic default — the same code path as an
untuned machine, so shipping a table can never CHANGE behavior on
hardware it wasn't measured on).

Durability discipline:
- writes are atomic (tempfile in the target dir + os.replace), so a
  killed tune run can't leave a half-written table for every later
  process to choke on;
- the file carries a schema version; a version mismatch is ignored with
  a warning (forward-compat: an old runtime reading a new table must
  fall back to analytic defaults, not crash);
- a corrupt file (truncated, hand-edited, wrong types) is moved aside
  to `<path>.corrupt` and an empty table takes its place — the tuner
  must never be able to break model execution;
- reads go through a small in-process LRU front so the per-trace lookup
  cost is a dict hit, not repeated signature formatting.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import tempfile
import warnings
from typing import Any, Dict, Optional

TABLE_VERSION = 1
_LRU_CAP = 512

# itemsize -> dtype name for kernels whose shape model only sees the io
# itemsize (bahdanau _bblk, the RNN eligibility): the fused families
# admit exactly bf16/f32, so the mapping is bijective
ITEMSIZE_DTYPE = {2: "bfloat16", 4: "float32"}


def device_kind() -> str:
    """Canonical device identity for table keys: jax's device_kind
    string (e.g. 'TPU v5 lite'), lowercased with spaces collapsed so the
    key survives JSON round-trips and shell quoting. 'cpu' off-TPU —
    which is exactly why CPU test runs can never hit TPU-tuned entries."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # no backend at all — still a valid (empty) key
        kind = "unknown"
    return "-".join(str(kind).lower().split())


def make_sig(params: Dict[str, Any]) -> str:
    """Canonical shape signature: sorted k=v pairs. Params must be
    scalars (ints/strs) — the signature is a JSON object key. A 'dtype'
    key is excluded: dtype is its own key dimension (space.normalize
    carries it inside params for the candidate generators, runtime
    lookups pass pure shape dicts — both must map to one signature)."""
    return ",".join(f"{k}={params[k]}" for k in sorted(params)
                    if k != "dtype")


def entry_key(kernel: str, sig: str, dtype: str, device: str) -> str:
    return "|".join((kernel, sig, dtype, device))


class TunedTable:
    """entries: key -> {"config": {...}, "meta": {...}}."""

    def __init__(self, path: Optional[str] = None, autoload: bool = True):
        self.path = path
        self.entries: Dict[str, Dict[str, Any]] = {}
        self._lru: "collections.OrderedDict[str, Any]" = (
            collections.OrderedDict())
        self._fp: Optional[str] = None
        if path and autoload:
            self.load(path)

    # -------------------------------------------------------- lookups --
    def get(self, kernel: str, params: Dict[str, Any], dtype: str,
            device: Optional[str] = None) -> Optional[Dict[str, Any]]:
        key = entry_key(kernel, make_sig(params), dtype,
                        device if device is not None else device_kind())
        if key in self._lru:
            self._lru.move_to_end(key)
            cfg = self._lru[key]
        else:
            e = self.entries.get(key)
            cfg = dict(e["config"]) if e else None
            self._lru[key] = cfg
            if len(self._lru) > _LRU_CAP:
                self._lru.popitem(last=False)
        # fresh dict per caller: a consumer mutating its config must not
        # corrupt the cached copy
        return dict(cfg) if cfg is not None else None

    def put(self, kernel: str, params: Dict[str, Any], dtype: str,
            config: Dict[str, Any], device: Optional[str] = None,
            meta: Optional[Dict[str, Any]] = None) -> str:
        key = entry_key(kernel, make_sig(params), dtype,
                        device if device is not None else device_kind())
        self.entries[key] = {"config": dict(config),
                             "meta": dict(meta or {})}
        self._lru.pop(key, None)
        self._fp = None
        return key

    def __len__(self) -> int:
        return len(self.entries)

    def fingerprint(self) -> str:
        """Content hash over the entry set — folded into the Executor's
        jit cache key (a reloaded/retuned table must re-trace) and
        recorded in saved-model metadata (serving detects staleness)."""
        if self._fp is None:
            blob = json.dumps(self.entries, sort_keys=True).encode()
            self._fp = hashlib.sha1(blob).hexdigest()[:16]
        return self._fp

    # ------------------------------------------------------------- io --
    def load(self, path: Optional[str] = None) -> "TunedTable":
        path = path or self.path
        self.path = path
        self.entries = {}
        self._lru.clear()
        self._fp = None
        if not path or not os.path.exists(path):
            return self
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError("table root must be an object")
            if doc.get("version") != TABLE_VERSION:
                warnings.warn(
                    f"tuned table {path} has schema version "
                    f"{doc.get('version')!r} (this runtime reads "
                    f"{TABLE_VERSION}); ignoring it — analytic defaults "
                    "apply", stacklevel=2)
                return self
            entries = doc.get("entries", {})
            if not isinstance(entries, dict) or not all(
                    isinstance(e, dict) and isinstance(e.get("config"), dict)
                    for e in entries.values()):
                raise ValueError("malformed entries")
            self.entries = entries
        except (json.JSONDecodeError, ValueError, KeyError, TypeError) as e:
            quarantine = path + ".corrupt"
            try:
                os.replace(path, quarantine)
                moved = f"; moved aside to {quarantine}"
            except OSError:
                moved = ""
            warnings.warn(
                f"tuned table {path} is corrupt ({e}){moved}; starting "
                "empty — analytic defaults apply", stacklevel=2)
        return self

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("TunedTable.save: no path configured")
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        doc = {"version": TABLE_VERSION, "device_kind": device_kind(),
               "entries": self.entries}
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tuned-", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic on POSIX
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


def default_path() -> str:
    """PT_TUNE_CACHE env, else the XDG-ish per-user location."""
    env = os.environ.get("PT_TUNE_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "paddle_tpu", "tuned.json")
