"""Persistent tuned-config table: JSON on disk, LRU in process.

Key model (the CLBlast lesson, arXiv:1705.05249 §4): a tuned config is
only valid for the exact (kernel family, shape signature, dtype, device
kind) it was measured on — a v5e-optimal tile is a guess on v4, and a
bf16 tile model doubles its VMEM take at f32. The table therefore keys
on all four, and lookups from a different device kind simply miss (the
runtime then uses its analytic default — the same code path as an
untuned machine, so shipping a table can never CHANGE behavior on
hardware it wasn't measured on).

Fleet sharing (Autotuner v2): the same file format is the EXCHANGE
format — `paddle_tpu tune export/import/merge` move tables between
hosts, and pre-tuned per-device tables ship with the package under
`paddle_tpu/tune/tables/<device_kind>.json` (auto-consulted as a
read-through base layer beneath the user's local table; see
tune/overrides.py). To make merging well-defined, every entry's meta
carries its PROVENANCE ("measured" from the timing harness,
"interpolated" from a nearest-shape materialization) and an
`updated_at` epoch stamp; `merge_entry` resolves conflicts as
measured-beats-interpolated first, newest-wins second — a fleet member
can therefore blindly merge a colleague's table without ever letting a
guessed config shadow a measured one.

Durability discipline:
- writes are atomic (tempfile in the target dir + os.replace), so a
  killed tune run can't leave a half-written table for every later
  process to choke on;
- the file carries a schema version; a version mismatch is ignored with
  a warning (forward-compat: an old runtime reading a new table must
  fall back to analytic defaults, not crash) — `tune import` REJECTS
  it loudly instead (an operator merging tables wants the error, not a
  silent no-op);
- a corrupt file (truncated, hand-edited, wrong types) is moved aside
  to `<path>.corrupt` and an empty table takes its place — the tuner
  must never be able to break model execution;
- reads go through a small in-process LRU front so the per-trace lookup
  cost is a dict hit, not repeated signature formatting.
"""

from __future__ import annotations

import collections
import hashlib
import json
import os
import tempfile
import time
import warnings
from typing import Any, Dict, List, Optional, Tuple

TABLE_VERSION = 1
_LRU_CAP = 512

# entry provenance vocabulary (meta["provenance"]): measured entries
# come from the timing harness, interpolated ones from a materialized
# nearest-shape match. Unknown/missing provenance merges as weakest.
MEASURED = "measured"
INTERPOLATED = "interpolated"
_PROVENANCE_RANK = {MEASURED: 2, INTERPOLATED: 1}

# itemsize -> dtype name for kernels whose shape model only sees the io
# itemsize (bahdanau _bblk, the RNN eligibility): the fused families
# admit exactly bf16/f32, so the mapping is bijective
ITEMSIZE_DTYPE = {2: "bfloat16", 4: "float32"}


def device_kind() -> str:
    """Canonical device identity for table keys: jax's device_kind
    string (e.g. 'TPU v5 lite'), lowercased with spaces collapsed so the
    key survives JSON round-trips and shell quoting. 'cpu' off-TPU —
    which is exactly why CPU test runs can never hit TPU-tuned entries."""
    import jax

    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # no backend at all — still a valid (empty) key
        kind = "unknown"
    return "-".join(str(kind).lower().split())


def make_sig(params: Dict[str, Any]) -> str:
    """Canonical shape signature: sorted k=v pairs. Params must be
    scalars (ints/strs) — the signature is a JSON object key. A 'dtype'
    key is excluded: dtype is its own key dimension (space.normalize
    carries it inside params for the candidate generators, runtime
    lookups pass pure shape dicts — both must map to one signature)."""
    return ",".join(f"{k}={params[k]}" for k in sorted(params)
                    if k != "dtype")


def entry_key(kernel: str, sig: str, dtype: str, device: str) -> str:
    return "|".join((kernel, sig, dtype, device))


def parse_key(key: str) -> Optional[Tuple[str, str, str, str]]:
    """entry_key inverse: (kernel, sig, dtype, device), or None for a
    malformed key (hand-edited tables must degrade, not crash)."""
    parts = key.split("|")
    if len(parts) != 4:
        return None
    return parts[0], parts[1], parts[2], parts[3]


def sig_to_params(sig: str) -> Optional[Dict[str, int]]:
    """Shape signature back to its params dict (int-valued keys only —
    exactly what make_sig emits for the kernel families)."""
    if not sig:
        return None
    out: Dict[str, int] = {}
    for kv in sig.split(","):
        k, eq, v = kv.partition("=")
        if not eq:
            return None
        try:
            out[k] = int(v)
        except ValueError:
            return None
    return out


def merge_entry(mine: Optional[Dict[str, Any]],
                theirs: Dict[str, Any]) -> Dict[str, Any]:
    """Conflict resolution for one key: measured beats interpolated,
    then newest `updated_at` wins (a fresh re-measurement supersedes an
    old one; ties keep the incumbent — merging a table into itself is a
    no-op). Entries without provenance/updated_at rank weakest/oldest,
    so a modern entry always survives a legacy one."""
    if mine is None:
        return theirs
    rank_m = _PROVENANCE_RANK.get(
        (mine.get("meta") or {}).get("provenance"), 0)
    rank_t = _PROVENANCE_RANK.get(
        (theirs.get("meta") or {}).get("provenance"), 0)
    if rank_t != rank_m:
        return theirs if rank_t > rank_m else mine
    at_m = float((mine.get("meta") or {}).get("updated_at", 0) or 0)
    at_t = float((theirs.get("meta") or {}).get("updated_at", 0) or 0)
    return theirs if at_t > at_m else mine


class TunedTable:
    """entries: key -> {"config": {...}, "meta": {...}}."""

    def __init__(self, path: Optional[str] = None, autoload: bool = True):
        self.path = path
        self.entries: Dict[str, Dict[str, Any]] = {}
        self._lru: "collections.OrderedDict[str, Any]" = (
            collections.OrderedDict())
        self._fp: Optional[str] = None
        if path and autoload:
            self.load(path)

    # -------------------------------------------------------- lookups --
    def get(self, kernel: str, params: Dict[str, Any], dtype: str,
            device: Optional[str] = None) -> Optional[Dict[str, Any]]:
        key = entry_key(kernel, make_sig(params), dtype,
                        device if device is not None else device_kind())
        if key in self._lru:
            self._lru.move_to_end(key)
            cfg = self._lru[key]
        else:
            e = self.entries.get(key)
            cfg = dict(e["config"]) if e else None
            self._lru[key] = cfg
            if len(self._lru) > _LRU_CAP:
                self._lru.popitem(last=False)
        # fresh dict per caller: a consumer mutating its config must not
        # corrupt the cached copy
        return dict(cfg) if cfg is not None else None

    def put(self, kernel: str, params: Dict[str, Any], dtype: str,
            config: Dict[str, Any], device: Optional[str] = None,
            meta: Optional[Dict[str, Any]] = None,
            provenance: Optional[str] = None) -> str:
        key = entry_key(kernel, make_sig(params), dtype,
                        device if device is not None else device_kind())
        m = dict(meta or {})
        if provenance is not None:
            m["provenance"] = provenance
            m.setdefault("updated_at", int(time.time()))
        self.entries[key] = {"config": dict(config), "meta": m}
        self._lru.pop(key, None)
        self._fp = None
        return key

    def __len__(self) -> int:
        return len(self.entries)

    def entries_for(self, kernel: str, dtype: str,
                    device: Optional[str] = None
                    ) -> List[Tuple[Dict[str, int], Dict[str, Any],
                                    Dict[str, Any]]]:
        """All (params, config, meta) tuned for this kernel/dtype/device
        — the interpolation neighbor pool (tune/overrides.py). Malformed
        keys/signatures are skipped, never fatal."""
        device = device if device is not None else device_kind()
        out = []
        for key, e in self.entries.items():
            parsed = parse_key(key)
            if parsed is None:
                continue
            k, sig, dt, dev = parsed
            if k != kernel or dt != dtype or dev != device:
                continue
            params = sig_to_params(sig)
            if params is None or not isinstance(e.get("config"), dict):
                continue
            out.append((params, dict(e["config"]),
                        dict(e.get("meta") or {})))
        return out

    def merge_from(self, other: "TunedTable") -> Dict[str, int]:
        """Merge `other`'s entries into this table under the
        measured-beats-interpolated / newest-wins policy. Returns
        {"added", "replaced", "kept"} counts for the CLI report."""
        stats = {"added": 0, "replaced": 0, "kept": 0}
        for key, theirs in other.entries.items():
            if not isinstance(theirs, dict) \
                    or not isinstance(theirs.get("config"), dict):
                continue
            mine = self.entries.get(key)
            winner = merge_entry(mine, theirs)
            if mine is None:
                stats["added"] += 1
            elif winner is theirs:
                stats["replaced"] += 1
            else:
                stats["kept"] += 1
                continue
            self.entries[key] = {"config": dict(theirs["config"]),
                                 "meta": dict(theirs.get("meta") or {})}
            self._lru.pop(key, None)
            self._fp = None
        return stats

    def fingerprint(self) -> str:
        """Content hash over the entry set — folded into the Executor's
        jit cache key (a reloaded/retuned table must re-trace) and
        recorded in saved-model metadata (serving detects staleness)."""
        if self._fp is None:
            blob = json.dumps(self.entries, sort_keys=True).encode()
            self._fp = hashlib.sha1(blob).hexdigest()[:16]
        return self._fp

    # ------------------------------------------------------------- io --
    def load(self, path: Optional[str] = None) -> "TunedTable":
        path = path or self.path
        self.path = path
        self.entries = {}
        self._lru.clear()
        self._fp = None
        if not path or not os.path.exists(path):
            return self
        try:
            with open(path) as f:
                doc = json.load(f)
            if not isinstance(doc, dict):
                raise ValueError("table root must be an object")
            if doc.get("version") != TABLE_VERSION:
                warnings.warn(
                    f"tuned table {path} has schema version "
                    f"{doc.get('version')!r} (this runtime reads "
                    f"{TABLE_VERSION}); ignoring it — analytic defaults "
                    "apply", stacklevel=2)
                return self
            entries = doc.get("entries", {})
            if not isinstance(entries, dict) or not all(
                    isinstance(e, dict) and isinstance(e.get("config"), dict)
                    for e in entries.values()):
                raise ValueError("malformed entries")
            self.entries = entries
        except (json.JSONDecodeError, ValueError, KeyError, TypeError) as e:
            quarantine = path + ".corrupt"
            try:
                os.replace(path, quarantine)
                moved = f"; moved aside to {quarantine}"
            except OSError:
                moved = ""
            warnings.warn(
                f"tuned table {path} is corrupt ({e}){moved}; starting "
                "empty — analytic defaults apply", stacklevel=2)
        return self

    def save(self, path: Optional[str] = None) -> str:
        path = path or self.path
        if not path:
            raise ValueError("TunedTable.save: no path configured")
        self.path = path
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        doc = {"version": TABLE_VERSION, "device_kind": device_kind(),
               "entries": self.entries}
        fd, tmp = tempfile.mkstemp(dir=d, prefix=".tuned-", suffix=".json")
        try:
            with os.fdopen(fd, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)  # atomic on POSIX
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return path


class TableFormatError(ValueError):
    """A table file that must not be silently ignored (tune import /
    merge): wrong schema version, malformed JSON, bad entry shape."""


def load_strict(path: str) -> TunedTable:
    """Load a table for import/merge: unlike TunedTable.load (runtime
    read-path, degrades to empty with a warning), this RAISES
    TableFormatError on schema-version mismatch or corruption — an
    operator moving tables between hosts wants the loud failure."""
    try:
        with open(path) as f:
            doc = json.load(f)
    except OSError as e:
        raise TableFormatError(f"cannot read table {path}: {e}") from e
    except json.JSONDecodeError as e:
        raise TableFormatError(f"table {path} is not JSON: {e}") from e
    if not isinstance(doc, dict):
        raise TableFormatError(f"table {path}: root must be an object")
    if doc.get("version") != TABLE_VERSION:
        raise TableFormatError(
            f"table {path} has schema version {doc.get('version')!r}; "
            f"this build reads version {TABLE_VERSION} — re-export it "
            "from a matching build")
    entries = doc.get("entries", {})
    if not isinstance(entries, dict) or not all(
            isinstance(e, dict) and isinstance(e.get("config"), dict)
            for e in entries.values()):
        raise TableFormatError(f"table {path}: malformed entries")
    t = TunedTable(path, autoload=False)
    t.entries = entries
    return t


def default_path() -> str:
    """PT_TUNE_CACHE env, else the XDG-ish per-user location."""
    env = os.environ.get("PT_TUNE_CACHE")
    if env:
        return env
    base = os.environ.get("XDG_CACHE_HOME",
                          os.path.join(os.path.expanduser("~"), ".cache"))
    return os.path.join(base, "paddle_tpu", "tuned.json")


def base_table_dir() -> str:
    """Where the pre-tuned fleet tables live: PT_TUNE_TABLES_DIR env
    (tests point it at a tmpdir; empty string disables the base layer
    entirely), else the package's shipped `tune/tables/` directory."""
    env = os.environ.get("PT_TUNE_TABLES_DIR")
    if env is not None:
        return env
    return os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "tables")


def base_table_path(device: Optional[str] = None) -> Optional[str]:
    """The shipped table for this device kind, or None when the package
    carries none (every non-TPU dev box): `tables/<device_kind>.json`,
    device_kind already filename-safe (lowercased, '-'-joined)."""
    d = base_table_dir()
    if not d:
        return None
    path = os.path.join(d, f"{device or device_kind()}.json")
    return path if os.path.exists(path) else None
