"""Empirical measurement loop: compile, warm up, time, cross-check.

Methodology (the CLBlast recipe, arXiv:1705.05249 §3, adapted to XLA):

- each candidate config is traced+compiled with the config FORCED in
  the override registry (overrides.forcing), so the measurement
  exercises the exact consult path production dispatch uses;
- warmup runs absorb the compile + first-dispatch cost, then the timed
  runs block on the result (`jax.block_until_ready`) so the timer sees
  device work, not async enqueue (profiler.py's design note);
- the score is the MEDIAN of k timed runs (profiler.Stat keeps the
  samples when asked) — medians shrug off the one-off d2h/interrupt
  outliers that poisoned round-1's RNN measurements (PERF.md);
- every candidate's output is cross-checked against the family's
  reference lowering before it may win: a fast-but-wrong tile (e.g. one
  that silently overflows an accumulator) must never enter the table.

Autotuner v2: the default search mode is GUIDED (tune/search.py) — a
cost model over the legality features ranks the space and successive
halving times only the top fraction, with the exhaustive v1 sweep kept
as the A/B baseline (`mode="exhaustive"` / CLI `--search exhaustive`).
Timing goes through an injectable ORACLE (make_oracle builds the real
compile+measure one), so search quality is testable off-TPU against
recorded/simulated timings without weakening the refusal below.

Determinism guard: timing is REFUSED off-TPU (TuningUnavailable) — a
CPU/interpret timing would write meaningless configs into the
per-device table, and the tier-1 CPU suite must stay byte-deterministic.
Lookups off-TPU still work and simply miss (device_kind mismatch), so
the untimed path falls back to analytic defaults deterministically.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import profiler
from . import cache as _cache
from . import overrides, search as _search, space


class TuningUnavailable(RuntimeError):
    """Raised when empirical timing is requested on a backend whose
    timings must not enter the per-device table."""


def ensure_timeable() -> None:
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        raise TuningUnavailable(
            f"refusing to time kernels on backend {backend!r}: empirical "
            "timings off-TPU would poison the per-device table. Run on "
            "TPU hardware, or use --dry-run to list candidates.")


def measure(thunk, iters: int = 5, warmup: int = 2,
            stat_set: Optional[profiler.StatSet] = None,
            name: str = "tune/measure") -> float:
    """Median-of-k wall seconds for `thunk()` (a zero-arg compiled
    call). Samples land in a StatSet so the full distribution is
    inspectable (`stat_set.get(name).samples`)."""
    import jax

    stats = stat_set if stat_set is not None \
        else profiler.StatSet(keep_samples=iters)
    for _ in range(max(0, warmup)):
        jax.block_until_ready(thunk())
    for _ in range(max(1, iters)):
        with stats.timer(name, always=True):
            jax.block_until_ready(thunk())
    return stats.get(name).median


def _numerics_ok(got, want: List[np.ndarray], tol: float) -> bool:
    import jax

    got_leaves = [np.asarray(g, np.float32)
                  for g in jax.tree_util.tree_leaves(got)]
    if len(got_leaves) != len(want):
        return False
    return all(
        np.allclose(g, np.asarray(w, np.float32), rtol=tol, atol=tol)
        for g, w in zip(got_leaves, want))


def make_oracle(case: space.Case, ref, warmup: int = 2,
                stat_set: Optional[profiler.StatSet] = None):
    """The REAL timing oracle over a runnable Case: compile-once per
    config (thunks are memoized), numeric cross-check ONCE per config
    before any timing (a fast-but-wrong tile must never win), then
    median-of-`iters` wall timing. Protocol: oracle(config, iters) ->
    median seconds, +inf for a config that failed numerics. The guided
    searcher takes any callable with this protocol — tests and the CPU
    bench leg inject search.SimulatedOracle instead, which is the whole
    reason the oracle is a parameter and not a hard-wired loop."""
    thunks: Dict[tuple, Any] = {}

    def oracle(config: Dict[str, Any], iters: int) -> float:
        key = _search.config_key(config)
        if key not in thunks:
            thunk = case.make(config)
            thunks[key] = thunk if _numerics_ok(thunk(), ref, case.tol) \
                else None
        thunk = thunks[key]
        if thunk is None:
            return float("inf")
        return measure(thunk, iters=iters, warmup=warmup,
                       stat_set=stat_set, name=f"tune/{case.kernel}")

    return oracle


def tune_case(family: str, params: Dict[str, Any], dtype: str,
              table: Optional[_cache.TunedTable] = None,
              iters: int = 5, warmup: int = 2,
              require_tpu: bool = True,
              mode: str = "guided",
              budget_fraction: float = 0.4,
              oracle=None) -> Dict[str, Any]:
    """Tune one (kernel family, shape, dtype) case and optionally
    record the winner in `table` (provenance "measured"). Returns the
    report dict the CLI renders:

      {kernel, params, dtype, device_kind, default, best,
       rows: [{config, median_s, numerics_ok, is_default}, ...],
       search: {mode, candidates, timed, timed_fraction, ...}}

    `mode` picks the searcher: "guided" (default — cost-model ranking +
    successive-halving early stop, times a fraction of the space;
    tune/search.py) or "exhaustive" (v1 behavior: every candidate at
    full iters — the A/B baseline and the `--search exhaustive` CLI
    path). Untimed candidates appear in rows with median_s None.

    `oracle` overrides the timing source (protocol: oracle(config,
    iters) -> median seconds, +inf = failed). Default None builds the
    real compile+measure oracle — which is why `require_tpu` stays
    True for production entry points; an injected oracle skips the
    backend check entirely (recorded/simulated timings are
    deterministic anywhere, and the tier-1 guided-vs-exhaustive
    quality tests run exactly that way).

    `require_tpu=False` exists for the CPU test suite to exercise the
    loop mechanics in interpret mode — production entry points
    (cli tune) always require TPU.
    """
    if mode not in ("guided", "exhaustive"):
        raise ValueError(f"mode must be guided or exhaustive, got {mode!r}")
    fam = space.get_family(family)
    params = fam.normalize(params, dtype)
    if oracle is None:
        if require_tpu:
            ensure_timeable()
        case = fam.make_case(params, dtype)
        oracle = make_oracle(case, case.reference(), warmup=warmup)
    cands = fam.candidates(params)
    if not cands:
        raise ValueError(
            f"{fam.name}: no legal candidates at {params} — the shape "
            "is outside the fused kernel's eligibility entirely")
    default_cfg = fam.default(params)

    if mode == "guided":
        ranked = sorted(cands, key=lambda c: (
            _search.predicted_cost(fam.name, params, c),
            _search.config_key(c)))
        result = _search.guided_search(
            ranked, oracle, budget_fraction=budget_fraction,
            rungs=(max(1, iters // 4), max(2, iters // 2), iters))
        timings = result.timings
        best_cfg, best_s = result.best, result.best_s
        search_info = {
            "mode": "guided",
            "candidates": result.n_candidates,
            "timed": result.n_timed,
            "timed_fraction": result.timed_fraction,
            "rungs_run": result.rungs_run,
            "stopped_early": result.stopped_early,
        }
    else:
        timings = {}
        for cfg in cands:
            timings[_search.config_key(cfg)] = oracle(cfg, iters)
        finite = {k: v for k, v in timings.items() if v != float("inf")}
        if not finite:
            raise RuntimeError(
                f"{fam.name}: every candidate failed the numeric "
                f"cross-check at {params} — refusing to tune (kernel "
                "bug, not a slow config)")
        best_key = min(finite, key=lambda k: (finite[k], k))
        best_cfg = dict(best_key)
        best_s = finite[best_key]
        search_info = {"mode": "exhaustive", "candidates": len(cands),
                       "timed": len(cands), "timed_fraction": 1.0}

    rows = []
    for cfg in cands:
        key = _search.config_key(cfg)
        med = timings.get(key)
        rows.append({
            "config": cfg,
            "median_s": med if med != float("inf") else float("inf"),
            "numerics_ok": med != float("inf"),  # untimed: presumed-legal
            "is_default": cfg == default_cfg,
            "timed": key in timings,
        })
    report = {
        "kernel": fam.name,
        "params": params,
        "dtype": dtype,
        "device_kind": _cache.device_kind(),
        "default": default_cfg,
        "best": best_cfg,
        "rows": rows,
        "search": search_info,
    }
    dkey = _search.config_key(default_cfg) if default_cfg else None
    if dkey in timings and timings[dkey] not in (None, float("inf")):
        report["speedup_vs_default"] = (
            timings[dkey] / best_s if best_s > 0 else 1.0)
    if table is not None:
        table.put(fam.name, params, dtype, best_cfg,
                  meta={"median_s": best_s, "iters": iters,
                        "default": default_cfg},
                  provenance=_cache.MEASURED)
    return report


def list_candidates(family: str, params: Dict[str, Any],
                    dtype: str) -> Dict[str, Any]:
    """The --dry-run half: enumerate legal candidates without compiling
    or timing anything (works on any backend)."""
    fam = space.get_family(family)
    params = fam.normalize(params, dtype)
    return {
        "kernel": fam.name,
        "params": params,
        "dtype": dtype,
        "default": fam.default(params),
        "candidates": fam.candidates(params),
    }
