"""Empirical measurement loop: compile, warm up, time, cross-check.

Methodology (the CLBlast recipe, arXiv:1705.05249 §3, adapted to XLA):

- each candidate config is traced+compiled with the config FORCED in
  the override registry (overrides.forcing), so the measurement
  exercises the exact consult path production dispatch uses;
- warmup runs absorb the compile + first-dispatch cost, then the timed
  runs block on the result (`jax.block_until_ready`) so the timer sees
  device work, not async enqueue (profiler.py's design note);
- the score is the MEDIAN of k timed runs (profiler.Stat keeps the
  samples when asked) — medians shrug off the one-off d2h/interrupt
  outliers that poisoned round-1's RNN measurements (PERF.md);
- every candidate's output is cross-checked against the family's
  reference lowering before it may win: a fast-but-wrong tile (e.g. one
  that silently overflows an accumulator) must never enter the table.

Determinism guard: timing is REFUSED off-TPU (TuningUnavailable) — a
CPU/interpret timing would write meaningless configs into the
per-device table, and the tier-1 CPU suite must stay byte-deterministic.
Lookups off-TPU still work and simply miss (device_kind mismatch), so
the untimed path falls back to analytic defaults deterministically.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import profiler
from . import cache as _cache
from . import overrides, space


class TuningUnavailable(RuntimeError):
    """Raised when empirical timing is requested on a backend whose
    timings must not enter the per-device table."""


def ensure_timeable() -> None:
    import jax

    backend = jax.default_backend()
    if backend != "tpu":
        raise TuningUnavailable(
            f"refusing to time kernels on backend {backend!r}: empirical "
            "timings off-TPU would poison the per-device table. Run on "
            "TPU hardware, or use --dry-run to list candidates.")


def measure(thunk, iters: int = 5, warmup: int = 2,
            stat_set: Optional[profiler.StatSet] = None,
            name: str = "tune/measure") -> float:
    """Median-of-k wall seconds for `thunk()` (a zero-arg compiled
    call). Samples land in a StatSet so the full distribution is
    inspectable (`stat_set.get(name).samples`)."""
    import jax

    stats = stat_set if stat_set is not None \
        else profiler.StatSet(keep_samples=iters)
    for _ in range(max(0, warmup)):
        jax.block_until_ready(thunk())
    for _ in range(max(1, iters)):
        with stats.timer(name, always=True):
            jax.block_until_ready(thunk())
    return stats.get(name).median


def _numerics_ok(got, want: List[np.ndarray], tol: float) -> bool:
    import jax

    got_leaves = [np.asarray(g, np.float32)
                  for g in jax.tree_util.tree_leaves(got)]
    if len(got_leaves) != len(want):
        return False
    return all(
        np.allclose(g, np.asarray(w, np.float32), rtol=tol, atol=tol)
        for g, w in zip(got_leaves, want))


def tune_case(family: str, params: Dict[str, Any], dtype: str,
              table: Optional[_cache.TunedTable] = None,
              iters: int = 5, warmup: int = 2,
              require_tpu: bool = True) -> Dict[str, Any]:
    """Sweep one (kernel family, shape, dtype) case: time every legal
    candidate, cross-check numerics, optionally record the winner in
    `table`. Returns the report dict the CLI renders:

      {kernel, params, dtype, device_kind, default, best,
       rows: [{config, median_s, numerics_ok, is_default}, ...]}

    `require_tpu=False` exists for the CPU test suite to exercise the
    loop mechanics in interpret mode — production entry points
    (cli tune) always require TPU.
    """
    fam = space.get_family(family)
    params = fam.normalize(params, dtype)
    if require_tpu:
        ensure_timeable()
    cands = fam.candidates(params)
    if not cands:
        raise ValueError(
            f"{fam.name}: no legal candidates at {params} — the shape "
            "is outside the fused kernel's eligibility entirely")
    default_cfg = fam.default(params)
    case = fam.make_case(params, dtype)
    ref = case.reference()

    rows = []
    for cfg in cands:
        thunk = case.make(cfg)
        ok = _numerics_ok(thunk(), ref, case.tol)
        med = measure(thunk, iters=iters, warmup=warmup,
                      name=f"tune/{fam.name}") if ok else float("inf")
        rows.append({"config": cfg, "median_s": med, "numerics_ok": ok,
                     "is_default": cfg == default_cfg})
    usable = [r for r in rows if r["numerics_ok"]]
    if not usable:
        raise RuntimeError(
            f"{fam.name}: every candidate failed the numeric cross-check "
            f"at {params} — refusing to tune (kernel bug, not a slow "
            "config)")
    best = min(usable, key=lambda r: r["median_s"])
    report = {
        "kernel": fam.name,
        "params": params,
        "dtype": dtype,
        "device_kind": _cache.device_kind(),
        "default": default_cfg,
        "best": best["config"],
        "rows": rows,
    }
    default_row = next((r for r in rows if r["is_default"]), None)
    if default_row is not None and default_row["numerics_ok"]:
        report["speedup_vs_default"] = (
            default_row["median_s"] / best["median_s"]
            if best["median_s"] > 0 else 1.0)
    if table is not None:
        table.put(fam.name, params, dtype, best["config"],
                  meta={"median_s": best["median_s"], "iters": iters,
                        "default": default_cfg})
    return report


def list_candidates(family: str, params: Dict[str, Any],
                    dtype: str) -> Dict[str, Any]:
    """The --dry-run half: enumerate legal candidates without compiling
    or timing anything (works on any backend)."""
    fam = space.get_family(family)
    params = fam.normalize(params, dtype)
    return {
        "kernel": fam.name,
        "params": params,
        "dtype": dtype,
        "default": fam.default(params),
        "candidates": fam.candidates(params),
    }
