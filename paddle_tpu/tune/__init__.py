"""paddle_tpu.tune: empirical kernel autotuner with a persistent
per-device config cache.

Why this exists: every fused Pallas kernel in the repo picks its tile
sizes from hand-derived analytic cost models (`_bblk` in
ops/bahdanau_kernels.py, `_v5e_block_sizes` in ops/flash_ops.py,
`_block_rows` in ops/fused_conv_ops.py, the measured H-windows in
ops/pallas_kernels.py). Those models encode one device generation's
measurements — the bahdanau comment itself records a 256k-vs-217k tok/s
gap found only by hand-sweeping PT_ATTN_BBLK. CLBlast (arXiv:1705.05249)
and the per-shape serving buckets in paddle_tpu.serving both apply the
same lesson: empirical per-device, per-shape search beats analytic
defaults across hardware generations, IF the search result is cached and
consulted as a first-class input to dispatch.

Module layout:

  space.py     per-kernel candidate generators. The legality predicates
               (Mosaic tile rules + the VMEM-budget models) are defined
               HERE and imported by the runtime kernels, so the tuner
               can never emit a config the runtime would reject, and the
               runtime can never accept a config the tuner can't
               enumerate.
  harness.py   the measurement loop: compile each candidate, warm up,
               median-of-k wall timing via profiler.StatSet, numeric
               cross-check against the reference lowering. REFUSES to
               time on non-TPU backends (a CPU timing would poison the
               per-device table) — lookups then fall back to analytic
               defaults deterministically.
  cache.py     the persistent JSON table keyed by (kernel,
               shape-signature, dtype, device_kind): atomic writes,
               schema versioning, corrupt-file recovery, an in-process
               LRU front.
  overrides.py the one consult point kernels call at trace time:
               forced override (programmatic or env, e.g. PT_ATTN_BBLK)
               -> tuned table -> None (analytic default). Also exports
               the fingerprint the Executor folds into its jit cache
               key, so flipping ANY kernel knob re-traces instead of
               silently reusing a stale tile choice.

CLI: `python -m paddle_tpu tune --kernel bahdanau --shape B=256,S=60,\
A=512,C=512 [--dry-run]` — see cli.py.
"""

from . import cache  # noqa: F401
from . import space  # noqa: F401
from . import overrides  # noqa: F401
from . import harness  # noqa: F401
from .cache import TunedTable, device_kind  # noqa: F401
from .harness import TuningUnavailable, tune_case  # noqa: F401
from .overrides import force, forcing, lookup  # noqa: F401
