"""paddle_tpu.tune: empirical kernel autotuner with a persistent
per-device config cache.

Why this exists: every fused Pallas kernel in the repo picks its tile
sizes from hand-derived analytic cost models (`_bblk` in
ops/bahdanau_kernels.py, `_v5e_block_sizes` in ops/flash_ops.py,
`_block_rows` in ops/fused_conv_ops.py, the measured H-windows in
ops/pallas_kernels.py). Those models encode one device generation's
measurements — the bahdanau comment itself records a 256k-vs-217k tok/s
gap found only by hand-sweeping PT_ATTN_BBLK. CLBlast (arXiv:1705.05249)
and the per-shape serving buckets in paddle_tpu.serving both apply the
same lesson: empirical per-device, per-shape search beats analytic
defaults across hardware generations, IF the search result is cached and
consulted as a first-class input to dispatch.

Module layout:

  space.py     per-kernel candidate generators. The legality predicates
               (Mosaic tile rules + the VMEM-budget models) are defined
               HERE and imported by the runtime kernels, so the tuner
               can never emit a config the runtime would reject, and the
               runtime can never accept a config the tuner can't
               enumerate.
  harness.py   the measurement loop: compile each candidate, warm up,
               median-of-k wall timing via profiler.StatSet, numeric
               cross-check against the reference lowering. REFUSES to
               time on non-TPU backends (a CPU timing would poison the
               per-device table) — lookups then fall back to analytic
               defaults deterministically. The timing oracle is
               INJECTABLE (make_oracle builds the real one), so the
               search quality is testable on recorded timings in the
               CPU suite.
  search.py    Autotuner v2's guided searcher: a lightweight cost model
               (HBM traffic + grid overhead + VMEM-pressure features
               from space.py's legality model) ranks candidates, and
               successive halving with early stop times only the
               top-ranked fraction — >= 95% of exhaustive quality at
               <= 40% of the space (tests + bench tune_search).
  cache.py     the persistent JSON table keyed by (kernel,
               shape-signature, dtype, device_kind): atomic writes,
               schema versioning, corrupt-file recovery, an in-process
               LRU front. Also the fleet EXCHANGE format: entry meta
               carries provenance (measured/interpolated) + updated_at,
               and merge_entry resolves conflicts measured-first,
               newest-second (tune export/import/merge CLI).
  overrides.py the one consult point kernels call at trace time:
               forced override (programmatic or env, e.g. PT_ATTN_BBLK)
               -> exact table (local, then the pre-tuned base table the
               package ships per device_kind under tune/tables/) ->
               nearest-shape interpolation re-validated against the
               target's legality -> None (analytic default). Records
               per-source consult counts (pt_tune_consults_total) and
               exports the fingerprint the Executor folds into its jit
               cache key, so flipping ANY kernel knob re-traces instead
               of silently reusing a stale tile choice.

CLI: `python -m paddle_tpu tune --kernel bahdanau --shape B=256,S=60,\
A=512,C=512 [--dry-run] [--search guided|exhaustive]`, plus
`tune export/import/merge` for moving tables between fleet hosts —
see cli.py.
"""

from . import cache  # noqa: F401
from . import space  # noqa: F401
from . import overrides  # noqa: F401
from . import harness  # noqa: F401
from . import search  # noqa: F401
from .cache import TunedTable, device_kind  # noqa: F401
from .harness import TuningUnavailable, make_oracle, tune_case  # noqa: F401
from .overrides import force, forcing, lookup  # noqa: F401
from .search import (SimulatedOracle, guided_search,  # noqa: F401
                     predicted_cost, rank_candidates)
