"""Program rewrite: fp matmul sites → quantized int8 ops.

In-place pass over (program, scope): weight payloads are re-stored as
int8 with an f32 per-output-channel scale var (`<w>@quant_scale`,
persistable — it travels in params.npz like any parameter), and each
eligible site becomes a quantized_mul/quantized_matmul op whose
dequantize epilogue runs inside the kernel (ops/quant_kernels.py). The
activation scale is CALIBRATED (calibrate.py absmax / 127) and baked as
a JSON-safe float attr — per-channel scale ARRAYS can't live in op
attrs (core/program._json_safe drops them from program.json), which is
exactly why weight scales are scope vars instead.

The result is deliberately a MIXED-precision program: anything the
shared policy table (amp.precision_policy — ONE table for amp and
quant) marks "high", anything without a persistable 2-D weight, and
anything whose site fails an eligibility check stays at its original
precision. QuantReport.summary() names every survivor loudly; a silent
partial quantization would make the bench's bytes-saved claim a lie.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from .. import amp
from ..core.executor import Executor, Scope, global_scope
from ..ops import quant_kernels as qk
from .calibrate import CalibrationResult, quantizable_sites

SCALE_SUFFIX = "@quant_scale"

_QUANT_OP = {"mul": "quantized_mul", "matmul": "quantized_matmul"}


class QuantReport:
    """What the converter did — and, loudly, what it did NOT."""

    def __init__(self, mode: str, quantized: List[Dict[str, Any]],
                 skipped: List[Dict[str, Any]], kept_fp_ops: int,
                 bytes_saved: int, sample_count: int,
                 accuracy_delta: Optional[float] = None):
        self.mode = mode
        self.quantized = quantized
        self.skipped = skipped
        self.kept_fp_ops = kept_fp_ops
        self.bytes_saved = bytes_saved
        self.sample_count = sample_count
        self.accuracy_delta = accuracy_delta

    def meta(self) -> Dict[str, Any]:
        """The artifact sidecar payload (io.save_inference_model adds
        the program fingerprint + scales digest at save time)."""
        return {
            "mode": self.mode,
            "sites": len(self.quantized),
            "skipped": len(self.skipped),
            "calibration_samples": self.sample_count,
            "bytes_saved": int(self.bytes_saved),
            **({"accuracy_delta": float(self.accuracy_delta)}
               if self.accuracy_delta is not None else {}),
        }

    def summary(self) -> str:
        lines = [
            f"quantized {len(self.quantized)} matmul sites to "
            f"{self.mode} ({self.bytes_saved / 1024:.1f} KiB of weight "
            f"bytes saved; calibrated on {self.sample_count} samples)"]
        for q in self.quantized:
            lines.append(
                f"  {q['op']}: {q['w']} [{q['K']}x{q['N']}] int8 "
                f"per-channel, x_scale={q['x_scale']:.3g}")
        if self.skipped:
            lines.append(
                f"  LEFT AT HIGHER PRECISION ({len(self.skipped)} "
                "candidate sites — mixed-precision program):")
            for s in self.skipped:
                lines.append(f"    {s['op']}: {s['reason']}")
        lines.append(
            f"  {self.kept_fp_ops} non-matmul ops keep their original "
            "precision (amp.precision_policy: high/follow)")
        if self.accuracy_delta is not None:
            lines.append(
                f"  accuracy check: max |quant - fp| = "
                f"{self.accuracy_delta:.4g} on the check feed")
        return "\n".join(lines)


def _site_skip_reason(site, calib: CalibrationResult,
                      quantized_layout: Dict[str, str]) -> Optional[str]:
    x, w = site["x"], site["w"]
    if x not in calib.act_ranges:
        return f"activation {x!r} has no calibration range"
    if calib.act_ranges[x] <= 0.0:
        return (f"activation {x!r} calibrated to absmax 0 (dead input "
                "on the sample feed)")
    layout = "NK" if site["transpose_w"] else "KN"
    if w in quantized_layout and quantized_layout[w] != layout:
        return (f"weight {w!r} already quantized with layout "
                f"{quantized_layout[w]} (shared across transposed "
                "sites)")
    return None


def convert(program, scope: Optional[Scope] = None,
            calib: Optional[CalibrationResult] = None,
            mode: str = "int8",
            check_feed: Optional[Dict[str, Any]] = None,
            fetch_list: Optional[List[str]] = None,
            exe: Optional[Executor] = None) -> QuantReport:
    """Rewrite `program`/`scope` IN PLACE to the quantized form.

    check_feed (optional, with fetch_list): runs the program before and
    after the rewrite on that feed and records the max output delta —
    the accuracy number meta.json and the pt_quant_accuracy_delta gauge
    report. Returns the QuantReport; raises if nothing was quantizable
    (an all-skip convert is an operator error, not a quiet no-op)."""
    if mode != "int8":
        raise ValueError(f"unsupported quant mode {mode!r} (only int8)")
    scope = scope or global_scope()
    if calib is None:
        raise ValueError("convert() needs a CalibrationResult "
                         "(quant.calibrate the sample feed first)")
    exe = exe or Executor()
    ref_outs = None
    if check_feed is not None:
        if not fetch_list:
            raise ValueError("check_feed needs fetch_list to compare on")
        ref_outs = exe.run(program, feed=dict(check_feed),
                           fetch_list=list(fetch_list), scope=scope)

    sites = quantizable_sites(program, scope)
    quantized: List[Dict[str, Any]] = []
    skipped: List[Dict[str, Any]] = []
    quantized_layout: Dict[str, str] = {}
    bytes_saved = 0
    for site in sites:
        op, block = site["op"], program.blocks[site["block"]]
        reason = _site_skip_reason(site, calib, quantized_layout)
        if reason is not None:
            skipped.append({"op": op.type, "w": site["w"],
                            "reason": reason})
            continue
        wname = site["w"]
        layout = "NK" if site["transpose_w"] else "KN"
        scale_name = wname + SCALE_SUFFIX
        if wname not in quantized_layout:
            w = np.asarray(scope.get(wname))
            orig_nbytes = w.size * w.dtype.itemsize
            if site["transpose_w"]:
                w = np.ascontiguousarray(w.T)
            wq, scale = qk.quantize_weight(w)
            scope.set(wname, wq)
            scope.set(scale_name, scale)
            wv = block.var(wname)
            wv.dtype = np.int8
            wv.shape = tuple(wq.shape)
            block.create_var(scale_name, shape=(wq.shape[1],),
                             dtype=np.float32, persistable=True)
            quantized_layout[wname] = layout
            bytes_saved += orig_nbytes - (wq.size + scale.size * 4)
        x_scale = qk.act_scale(calib.act_ranges[site["x"]])
        op.type = _QUANT_OP[op.type]
        op.inputs["Scale"] = [scale_name]
        op.attrs.pop("transpose_Y", None)
        op.attrs["x_scale"] = x_scale
        op.attrs["quant_mode"] = mode
        K, N = block.var(wname).shape
        quantized.append({"op": op.type, "x": site["x"], "w": wname,
                          "K": int(K), "N": int(N), "x_scale": x_scale})
    if not quantized:
        raise ValueError(
            "convert(): no site was quantizable — " + "; ".join(
                f"{s['op']}: {s['reason']}" for s in skipped) if skipped
            else "convert(): the program has no quantizable matmul sites")
    program.bump_version()

    kept_fp = sum(1 for b in program.blocks for o in b.ops
                  if o.type not in _QUANT_OP.values())
    accuracy_delta = None
    if ref_outs is not None:
        q_outs = exe.run(program, feed=dict(check_feed),
                         fetch_list=list(fetch_list), scope=scope)
        accuracy_delta = max(
            float(np.max(np.abs(np.asarray(a, np.float32)
                                - np.asarray(b, np.float32))))
            for a, b in zip(ref_outs, q_outs))
    report = QuantReport(mode, quantized, skipped, kept_fp, bytes_saved,
                         calib.sample_count, accuracy_delta)
    program._quant_meta = report.meta()

    from . import note_convert

    note_convert(report)
    return report
