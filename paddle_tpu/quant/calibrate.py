"""Calibration: record absmax ranges over a sample feed.

Per-tensor ranges for matmul ACTIVATION inputs (the only runtime-valued
side — it has to be observed), per-output-channel ranges for WEIGHTS
(taken at convert time straight off the parameter, no run needed).
Observation rides the executor's ordinary fetch path: the activation
var names are appended to fetch_list, so calibration exercises exactly
the compiled program serving will run — no shadow interpreter whose
numerics could drift from production's.

Determinism: absmax over a fixed sample list through a jitted program
is bit-deterministic (tier-1 pins it), so calibrating twice from the
same feed yields byte-identical scales — which is what lets the scales
digest in meta.json double as a staleness check.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import amp
from ..core.executor import Executor, Scope, global_scope


def quantizable_sites(program, scope: Optional[Scope] = None
                      ) -> List[Dict[str, Any]]:
    """The matmul sites the converter MAY rewrite: op type passes the
    shared precision policy (amp.QUANTIZABLE_OPS — the one table both
    passes read), the weight side is a persistable 2-D parameter
    present in the scope, and no transpose lands on the activation.
    Returns [{block, op_idx, op, x, w, transpose_w}]."""
    scope = scope or global_scope()
    sites = []
    for bi, block in enumerate(program.blocks):
        for oi, op in enumerate(block.ops):
            if op.type not in amp.QUANTIZABLE_OPS:
                continue
            if amp.precision_policy(op.type) != "low":
                continue  # policy table is authoritative, not op list
            xs = op.inputs.get("X", [])
            ys = op.inputs.get("Y", [])
            if len(xs) != 1 or len(ys) != 1:
                continue
            try:
                wv = block.var(ys[0])
            except KeyError:
                continue
            if not wv.persistable or not scope.has(ys[0]):
                continue  # activation×activation matmul: nothing stored
            w = np.asarray(scope.get(ys[0]))
            if w.ndim != 2:
                continue
            if op.type == "matmul" and op.attrs.get("transpose_X"):
                continue
            sites.append({
                "block": bi, "op_idx": oi, "op": op,
                "x": xs[0], "w": ys[0],
                "transpose_w": bool(op.attrs.get("transpose_Y", False)),
            })
    return sites


class CalibrationResult:
    """absmax ranges from one calibration run.

    act_ranges: activation var name -> float absmax (per-tensor);
    sample_count: how many sample feeds contributed (meta.json records
    it so an artifact calibrated on 2 samples is visibly different from
    one calibrated on 2000)."""

    def __init__(self, act_ranges: Dict[str, float], sample_count: int):
        self.act_ranges = dict(act_ranges)
        self.sample_count = int(sample_count)

    def __repr__(self):
        return (f"CalibrationResult({len(self.act_ranges)} tensors, "
                f"{self.sample_count} samples)")


def calibrate(program, samples: Sequence[Dict[str, Any]],
              scope: Optional[Scope] = None,
              exe: Optional[Executor] = None) -> CalibrationResult:
    """Run `samples` (a sequence of feed dicts) through the inference
    program and record per-tensor absmax of every quantizable site's
    activation input. The fetches ride the ordinary executor path, so
    ranges are observed on the exact compiled numerics serving uses."""
    if not samples:
        raise ValueError("calibrate() needs at least one sample feed")
    scope = scope or global_scope()
    exe = exe or Executor()
    sites = quantizable_sites(program, scope)
    act_names = sorted({s["x"] for s in sites})
    ranges: Dict[str, float] = {n: 0.0 for n in act_names}
    if act_names:
        for feed in samples:
            outs = exe.run(program, feed=dict(feed),
                           fetch_list=list(act_names), scope=scope)
            for name, val in zip(act_names, outs):
                amax = float(np.max(np.abs(np.asarray(val, np.float32))))
                if amax > ranges[name]:
                    ranges[name] = amax
    return CalibrationResult(ranges, len(samples))
