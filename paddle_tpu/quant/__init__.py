"""Post-training int8 quantization for inference artifacts.

The low-precision serving fast path (ROADMAP item 2; ISSUE 15): serving
is HBM-bandwidth-bound well below the MXU ceiling (PERF.md's MFU grid),
so the highest-leverage byte to shave is the weight panel a matmul
re-streams every request. The pass is the classic PTQ recipe:

  1. `calibrate.calibrate(program, samples)` — run a sample feed
     through the inference program, record per-tensor absmax ranges of
     every quantizable matmul's activation input (weights get
     per-output-channel ranges at convert time, straight off the
     parameter value);
  2. `convert.convert(program, scope, calib)` — rewrite in place:
     weight payloads become int8 with f32 per-channel scale vars,
     mul/matmul sites become quantized_* ops (ops/quant_kernels.py)
     with a dequantize-on-the-fly epilogue; everything without a
     quantized lowering (amp.precision_policy says "high", or no
     weight to quantize) stays at its original precision — the result
     is a MIXED program and the report says loudly what stayed fp;
  3. `io.save_inference_model` — scales + quant mode land in the
     meta.json "quant" block with a program fingerprint + scales
     digest, so a stale-scale artifact fails LOUDLY at load instead of
     serving garbage, and the artifact round-trips through the router
     fleet / mesh sharding unchanged (it's just a program + params).

Process-level quant state is exported as pt_quant_* gauges through the
unified obs registry (obs/metrics._quant_families): bytes saved, sites
quantized/skipped, and the convert-time accuracy-check delta.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from .calibrate import CalibrationResult, calibrate, quantizable_sites
from .convert import QuantReport, convert

__all__ = ["CalibrationResult", "calibrate", "quantizable_sites",
           "QuantReport", "convert", "stats", "note_convert",
           "note_serving"]

# process-level quant activity, rendered as pt_quant_* by the obs
# registry collector (obs/metrics.py). Updated by convert() in the
# converting process and by ServingEngine on loading a quantized
# artifact (so a serving replica's /metrics shows the artifact's quant
# footprint without having converted anything itself).
_STATS: Dict[str, float] = {
    "sites_quantized": 0,
    "sites_skipped": 0,
    "bytes_saved": 0,
    "accuracy_delta": 0.0,
}
_ACTIVE = False


def note_convert(report: "QuantReport") -> None:
    global _ACTIVE
    _ACTIVE = True
    _STATS["sites_quantized"] += len(report.quantized)
    _STATS["sites_skipped"] += len(report.skipped)
    _STATS["bytes_saved"] += report.bytes_saved
    if report.accuracy_delta is not None:
        _STATS["accuracy_delta"] = float(report.accuracy_delta)


def note_serving(meta: Optional[Dict[str, Any]]) -> None:
    """Fold a loaded artifact's quant block into this process's gauges
    (a serving replica advertises the quant footprint it dispatches)."""
    global _ACTIVE
    if not meta:
        return
    _ACTIVE = True
    _STATS["sites_quantized"] += int(meta.get("sites", 0))
    _STATS["bytes_saved"] += int(meta.get("bytes_saved", 0))
    if meta.get("accuracy_delta") is not None:
        _STATS["accuracy_delta"] = float(meta["accuracy_delta"])


def stats() -> Dict[str, float]:
    """Current pt_quant_* gauge values; empty dict = no quant activity
    in this process (the collector then emits nothing)."""
    return dict(_STATS) if _ACTIVE else {}


def reset_stats() -> None:
    """Test isolation."""
    global _ACTIVE
    _ACTIVE = False
    for k in _STATS:
        _STATS[k] = 0 if k != "accuracy_delta" else 0.0
