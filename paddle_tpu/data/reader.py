"""Reader combinators.

Reference: python/paddle/v2/reader/decorator.py:29-236 — a *reader* is a
zero-arg callable returning an iterable of samples; combinators wrap
readers. Full parity set: map_readers, shuffle, chain, compose, buffered,
firstn, xmap_readers (parallel map), plus batch() from v2/minibatch.py.
"""

from __future__ import annotations

import itertools
import queue
import random as _random
import threading
from typing import Callable, Iterable, List

__all__ = [
    "map_readers",
    "shuffle",
    "chain",
    "compose",
    "buffered",
    "firstn",
    "xmap_readers",
    "batch",
    "cache",
]


def map_readers(func, *readers):
    """Apply func to the sample tuples zipped from readers (decorator.py:29)."""

    def reader():
        rs = [r() for r in readers]
        for vals in zip(*rs):
            yield func(*vals)

    return reader


def shuffle(reader, buf_size: int, seed=None):
    """Buffered shuffle (decorator.py:60)."""

    def new_reader():
        rnd = _random.Random(seed)
        buf: List = []
        for e in reader():
            buf.append(e)
            if len(buf) >= buf_size:
                rnd.shuffle(buf)
                yield from buf
                buf = []
        if buf:
            rnd.shuffle(buf)
            yield from buf

    return new_reader


def chain(*readers):
    """Concatenate readers (decorator.py:89)."""

    def reader():
        return itertools.chain(*[r() for r in readers])

    return reader


def compose(*readers, check_alignment: bool = True):
    """Zip samples from several readers into combined tuples (decorator.py:128)."""

    def make_tuple(x):
        return x if isinstance(x, tuple) else (x,)

    def reader():
        rs = [r() for r in readers]
        iters = [iter(r) for r in rs]
        while True:
            outs = []
            stop = 0
            for it in iters:
                try:
                    outs.append(make_tuple(next(it)))
                except StopIteration:
                    stop += 1
                    outs.append(None)
            if stop:
                if check_alignment and stop != len(iters):
                    raise RuntimeError("readers not aligned in compose()")
                return
            yield sum(outs, ())

    return reader


def buffered(reader, size: int):
    """Read-ahead via a daemon thread (decorator.py:180) — the Python analogue

    of the reference's double-buffered DataProvider (DataProvider.h:375)."""

    end = object()

    def new_reader():
        q: queue.Queue = queue.Queue(maxsize=size)

        def fill():
            try:
                for d in reader():
                    q.put(d)
            finally:
                q.put(end)

        t = threading.Thread(target=fill, daemon=True)
        t.start()
        while True:
            e = q.get()
            if e is end:
                return
            yield e

    return new_reader


def firstn(reader, n: int):
    def new_reader():
        return itertools.islice(reader(), n)

    return new_reader


def xmap_readers(mapper, reader, process_num: int, buffer_size: int, order: bool = False):
    """Parallel map over samples with worker threads (decorator.py:236)."""

    end = object()

    def new_reader():
        in_q: queue.Queue = queue.Queue(buffer_size)
        out_q: queue.Queue = queue.Queue(buffer_size)

        errors: List[BaseException] = []

        def feed():
            try:
                for i, d in enumerate(reader()):
                    in_q.put((i, d))
            except BaseException as e:  # propagate, don't hang the consumer
                errors.append(e)
            finally:
                for _ in range(process_num):
                    in_q.put(end)

        def work():
            try:
                while True:
                    item = in_q.get()
                    if item is end:
                        return
                    i, d = item
                    out_q.put((i, mapper(d)))
            except BaseException as e:
                errors.append(e)
            finally:
                out_q.put(end)

        threading.Thread(target=feed, daemon=True).start()
        workers = [threading.Thread(target=work, daemon=True) for _ in range(process_num)]
        for w in workers:
            w.start()
        finished = 0
        if order:
            pending = {}
            want = 0
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                i, d = item
                pending[i] = d
                while want in pending:
                    yield pending.pop(want)
                    want += 1
            while want in pending:
                yield pending.pop(want)
                want += 1
        else:
            while finished < process_num:
                item = out_q.get()
                if item is end:
                    finished += 1
                    continue
                yield item[1]
        if errors:
            raise errors[0]

    return new_reader


def batch(reader, batch_size: int, drop_last: bool = False):
    """Group samples into lists (reference: python/paddle/v2/minibatch.py)."""

    def new_reader():
        buf = []
        for s in reader():
            buf.append(s)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf

    return new_reader


def cache(reader):
    """Materialize once, then replay from memory."""
    data: List = []
    filled = [False]

    def new_reader():
        if not filled[0]:
            data.extend(reader())
            filled[0] = True
        return iter(data)

    return new_reader
