"""Image IO + augmentation utilities (host side, numpy).

Reference: python/paddle/v2/image.py:111-290 (load_image, resize_short,
to_chw, center_crop, random_crop, left_right_flip, simple_transform,
load_and_transform) and python/paddle/utils/preprocess_img.py. Same API
shape; decoding uses PIL when a real file is given (the reference used
cv2), everything else is pure numpy so it runs in reader worker threads
with no framework dependency.

Images are HWC uint8/float arrays throughout; `to_chw` converts at the
end for NCHW feeds (keep HWC for `data_format="NHWC"` models — the
TPU-preferred layout).
"""

from __future__ import annotations

import os

import numpy as np

__all__ = [
    "load_image", "load_image_bytes", "resize_short", "to_chw",
    "center_crop", "random_crop", "left_right_flip", "simple_transform",
    "load_and_transform", "batch_images_from_tar", "batch_reader",
]


def load_image_bytes(data: bytes, is_color: bool = True) -> np.ndarray:
    """Decode an encoded image from bytes → HWC (or HW) uint8."""
    import io

    from PIL import Image

    im = Image.open(io.BytesIO(data))
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def load_image(file, is_color: bool = True) -> np.ndarray:
    """Load an image file → HWC (or HW for grayscale) uint8 array."""
    from PIL import Image

    im = Image.open(file)
    im = im.convert("RGB" if is_color else "L")
    return np.asarray(im)


def resize_short(im: np.ndarray, size: int) -> np.ndarray:
    """Resize so the SHORTER edge equals `size` (aspect preserved).

    Bilinear, pure numpy (reference: cv2.resize at image.py:163-189)."""
    h, w = im.shape[:2]
    if h < w:
        new_h, new_w = size, int(round(w * size / h))
    else:
        new_h, new_w = int(round(h * size / w)), size
    return _bilinear_resize(im, new_h, new_w)


def _bilinear_resize(im: np.ndarray, new_h: int, new_w: int) -> np.ndarray:
    h, w = im.shape[:2]
    if (h, w) == (new_h, new_w):
        return im
    dtype = im.dtype
    ys = (np.arange(new_h) + 0.5) * h / new_h - 0.5
    xs = (np.arange(new_w) + 0.5) * w / new_w - 0.5
    y0 = np.clip(np.floor(ys).astype(int), 0, h - 1)
    x0 = np.clip(np.floor(xs).astype(int), 0, w - 1)
    y1 = np.clip(y0 + 1, 0, h - 1)
    x1 = np.clip(x0 + 1, 0, w - 1)
    wy = np.clip(ys - y0, 0.0, 1.0)[:, None]
    wx = np.clip(xs - x0, 0.0, 1.0)[None, :]
    if im.ndim == 3:
        wy = wy[..., None]
        wx = wx[..., None]
    imf = im.astype(np.float32)
    top = imf[y0][:, x0] * (1 - wx) + imf[y0][:, x1] * wx
    bot = imf[y1][:, x0] * (1 - wx) + imf[y1][:, x1] * wx
    out = top * (1 - wy) + bot * wy
    if np.issubdtype(dtype, np.integer):
        out = np.clip(np.round(out), np.iinfo(dtype).min, np.iinfo(dtype).max)
    return out.astype(dtype)


def to_chw(im: np.ndarray, order=(2, 0, 1)) -> np.ndarray:
    """HWC → CHW (reference image.py:190)."""
    return im.transpose(order)


def center_crop(im: np.ndarray, size: int, is_color: bool = True) -> np.ndarray:
    h, w = im.shape[:2]
    h_start = (h - size) // 2
    w_start = (w - size) // 2
    return im[h_start : h_start + size, w_start : w_start + size]


def random_crop(im: np.ndarray, size: int, is_color: bool = True,
                rng: np.random.RandomState = None) -> np.ndarray:
    rng = rng or np.random
    h, w = im.shape[:2]
    h_start = rng.randint(0, h - size + 1)
    w_start = rng.randint(0, w - size + 1)
    return im[h_start : h_start + size, w_start : w_start + size]


def left_right_flip(im: np.ndarray) -> np.ndarray:
    """Horizontal mirror (reference image.py:270)."""
    return im[:, ::-1]


def simple_transform(
    im: np.ndarray,
    resize_size: int,
    crop_size: int,
    is_train: bool,
    is_color: bool = True,
    mean=None,
    rng: np.random.RandomState = None,
) -> np.ndarray:
    """The reference's standard pipeline (image.py:290-343): resize short
    edge → (train: random crop + coin-flip mirror | test: center crop) →
    CHW float32 → optional mean subtraction (scalar-per-channel or full
    image)."""
    rng = rng or np.random
    im = resize_short(im, resize_size)
    if is_train:
        im = random_crop(im, crop_size, rng=rng)
        if rng.randint(2) == 0:
            im = left_right_flip(im)
    else:
        im = center_crop(im, crop_size)
    if im.ndim == 2:
        im = im[:, :, None]
    im = to_chw(im).astype(np.float32)
    if mean is not None:
        mean = np.asarray(mean, np.float32)
        if mean.ndim == 1:
            mean = mean[:, None, None]
        im -= mean
    return im


def load_and_transform(
    filename, resize_size, crop_size, is_train, is_color=True, mean=None
) -> np.ndarray:
    return simple_transform(
        load_image(filename, is_color), resize_size, crop_size, is_train,
        is_color, mean,
    )


def batch_images_from_tar(
    data_file: str,
    dataset_name: str,
    img2label: dict,
    num_per_batch: int = 1024,
) -> str:
    """Pre-batch a tar of images into batch files + a meta list.

    Reference: python/paddle/v2/image.py:48-109 (same contract: returns
    the meta file path listing batch files, in tar order; idempotent once
    complete). Batch files are .npz holding the encoded image bytes as
    one flat uint8 buffer + offsets (NOT an object array — object arrays
    make numpy pickle internally and re-open the reference's
    pickle-on-load code-execution hole). The meta file is written LAST:
    its presence marks the batching complete, so an interrupted run
    restarts instead of returning a half-written set. Read back with
    `batch_reader(meta_file)`.
    """
    import tarfile

    batch_dir = data_file + "_batch"
    out_path = os.path.join(batch_dir, dataset_name)
    meta_file = os.path.join(batch_dir, f"{dataset_name}.txt")
    if os.path.exists(meta_file):  # completion marker, not the dir
        return meta_file
    os.makedirs(out_path, exist_ok=True)

    paths: list = []

    def dump(data, labels, file_id):
        buf = np.frombuffer(b"".join(data), dtype=np.uint8)
        offsets = np.cumsum([0] + [len(d) for d in data]).astype(np.int64)
        p = os.path.join(out_path, f"batch_{file_id}.npz")
        np.savez(p, data=buf, offsets=offsets, label=np.asarray(labels))
        paths.append(os.path.abspath(p))

    data, labels, file_id = [], [], 0
    with tarfile.open(data_file) as tf:
        for mem in tf.getmembers():
            if mem.name not in img2label:
                continue
            data.append(tf.extractfile(mem).read())
            labels.append(img2label[mem.name])
            if len(data) == num_per_batch:
                dump(data, labels, file_id)
                file_id += 1
                data, labels = [], []
    if data:
        dump(data, labels, file_id)
    # written in production order (no listdir re-scan: lexicographic
    # order would interleave batch_10 between batch_1 and batch_2) and
    # atomically — a truncated meta would otherwise read as "complete"
    import tempfile

    fd, tmp = tempfile.mkstemp(dir=batch_dir, suffix=".tmp")
    try:
        with os.fdopen(fd, "w") as meta:
            meta.write("".join(p + "\n" for p in paths))
        os.replace(tmp, meta_file)
    finally:
        if os.path.exists(tmp):
            os.remove(tmp)
    return meta_file


def batch_reader(meta_file: str, is_color: bool = True):
    """Reader over batch files produced by batch_images_from_tar:
    yields (decoded HWC image, label) samples in tar order."""

    def reader():
        with open(meta_file) as f:
            paths = [ln.strip() for ln in f if ln.strip()]
        for p in paths:
            with np.load(p) as d:  # no allow_pickle: plain arrays only
                buf, offsets = d["data"], d["offsets"]
                for j, label in enumerate(d["label"]):
                    raw = buf[offsets[j]:offsets[j + 1]].tobytes()
                    yield load_image_bytes(raw, is_color), label

    return reader
