"""MQ2007 learning-to-rank (reference: python/paddle/v2/dataset/mq2007.py).

Reference API: train(format=...)/test(format=...) with three views:
- "pointwise": (feature[46], relevance float)
- "pairwise":  (feature_hi[46], feature_lo[46]) with rel(hi) > rel(lo)
- "listwise":  (label_list, feature_matrix) per query

Synthetic data: per-query docs with a hidden linear relevance model over the
46 LETOR features (plus noise), quantized to 0/1/2 like the corpus.
"""

from __future__ import annotations

import itertools

import numpy as np

FEATURE_DIM = 46
_N_QUERIES_TRAIN, _N_QUERIES_TEST = 200, 40
_DOCS_PER_QUERY = 8


def _w():
    return np.linspace(-1, 1, FEATURE_DIM).astype(np.float32)


def _queries(n_queries, seed):
    w = _w()
    rng = np.random.RandomState(seed)
    for _ in range(n_queries):
        feats = rng.randn(_DOCS_PER_QUERY, FEATURE_DIM).astype(np.float32)
        score = feats @ w + 0.3 * rng.randn(_DOCS_PER_QUERY)
        rel = np.digitize(score, np.quantile(score, [0.5, 0.85])).astype(np.int64)
        yield rel, feats


def _reader(n_queries, seed, format):
    if format not in ("pointwise", "pairwise", "listwise"):
        raise ValueError(f"unknown format {format!r}")

    def reader():
        for rel, feats in _queries(n_queries, seed):
            if format == "pointwise":
                for r, f in zip(rel, feats):
                    yield f, float(r)
            elif format == "pairwise":
                for i, j in itertools.combinations(range(len(rel)), 2):
                    if rel[i] > rel[j]:
                        yield feats[i], feats[j]
                    elif rel[j] > rel[i]:
                        yield feats[j], feats[i]
            else:
                yield rel.tolist(), feats

    return reader


def train(format: str = "pairwise"):
    return _reader(_N_QUERIES_TRAIN, 61, format)


def test(format: str = "pairwise"):
    return _reader(_N_QUERIES_TEST, 62, format)
