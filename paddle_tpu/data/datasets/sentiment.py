"""Movie-review sentiment (reference: python/paddle/v2/dataset/sentiment.py,
NLTK movie_reviews corpus). Sample schema: (word_ids list[int], label 0/1).

Synthetic data shares the class-conditional token-distribution scheme of
imdb.py with a smaller vocabulary (the reference corpus is ~39k tokens over
2k documents; scaled down proportionally here).
"""

from __future__ import annotations

import numpy as np

_VOCAB = 2000
_N_TRAIN, _N_TEST = 1600, 400


def get_word_dict():
    return {f"w{i}": i for i in range(_VOCAB)}


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        half = _VOCAB // 2
        for _ in range(n):
            label = int(rng.randint(0, 2))
            length = rng.randint(10, 60)
            # positive docs over-sample the first vocab half 3:1
            biased = rng.rand(length) < 0.75
            ids = np.where(
                biased == (label == 0),
                rng.randint(0, half, size=length),
                rng.randint(half, _VOCAB, size=length),
            )
            yield ids.tolist(), label

    return reader


def train():
    return _reader(_N_TRAIN, 51)


def test():
    return _reader(_N_TEST, 52)


def convert(path):
    """Converts dataset to recordio shards (reference sentiment.py convert)."""
    from . import common
    common.convert(path, train, 1000, "sentiment_train")
    common.convert(path, test, 1000, "sentiment_test")
