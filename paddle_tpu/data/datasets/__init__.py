"""Dataset zoo.

Reference: python/paddle/v2/dataset/ (uci_housing, mnist, cifar, imdb,
imikolov, movielens, conll05, wmt14/16, …) which download from public
mirrors. This environment has no network egress, so each dataset module
serves deterministic synthetic data with the *same sample schema and
reader API* as the reference; when real data files exist under
$PADDLE_TPU_DATA_HOME they are used instead.
"""

import os


def data_home() -> str:
    return os.environ.get(
        "PADDLE_TPU_DATA_HOME", os.path.expanduser("~/.cache/paddle_tpu/dataset")
    )
