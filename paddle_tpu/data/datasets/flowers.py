"""Oxford 102 Flowers (reference: python/paddle/v2/dataset/flowers.py).

Sample schema: (image[3,H,W] float32, label int), 102 classes. The reference
decodes/augments JPEGs; here synthetic 3x64x64 class-conditional color
fields (same scheme as cifar.py) keep the API and let image models train.
"""

from __future__ import annotations

import numpy as np

_N_CLASSES = 102
_N_TRAIN, _N_TEST = 2040, 510
_H = _W = 64


def _synthetic(n, seed):
    rng = np.random.RandomState(4321)
    low = rng.randn(_N_CLASSES, 3, 8, 8).astype(np.float32)
    templates = low.repeat(_H // 8, axis=2).repeat(_W // 8, axis=3)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, _N_CLASSES, size=n)
    for i in range(n):
        img = templates[labels[i]] * 0.5 + 0.35 * rng.randn(3, _H, _W).astype(np.float32)
        yield 1.0 / (1.0 + np.exp(-img)), int(labels[i])


def train(mapper=None, buffered_size: int = 1024, use_xmap: bool = False):
    def reader():
        for img, lbl in _synthetic(_N_TRAIN, 71):
            yield (mapper((img, lbl)) if mapper else (img, lbl))

    return reader


def test(mapper=None, buffered_size: int = 1024, use_xmap: bool = False):
    def reader():
        for img, lbl in _synthetic(_N_TEST, 72):
            yield (mapper((img, lbl)) if mapper else (img, lbl))

    return reader


def valid(mapper=None, buffered_size: int = 1024, use_xmap: bool = False):
    def reader():
        for img, lbl in _synthetic(_N_TEST, 73):
            yield (mapper((img, lbl)) if mapper else (img, lbl))

    return reader
