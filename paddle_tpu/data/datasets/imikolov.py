"""PTB language-model dataset (reference: python/paddle/v2/dataset/imikolov.py).

Reference API: build_dict(min_word_freq) → word dict with '<unk>'/'<e>'/'<s>',
train(word_idx, n) / test(word_idx, n) yielding n-gram id tuples
(DataType.NGRAM) or full sentences (DataType.SEQ). With no egress, sentences
come from a deterministic order-1 Markov chain over the vocab, so n-gram
models (word2vec, book/04) have real mutual information to learn.
"""

from __future__ import annotations

import os

import numpy as np

from . import data_home


class DataType:
    NGRAM = 1
    SEQ = 2


_VOCAB = 2000
_N_TRAIN, _N_TEST = 3000, 300


def _real_file(name):
    p = os.path.join(data_home(), "imikolov", name)
    return p if os.path.exists(p) else None


def build_dict(min_word_freq: int = 50):
    f = _real_file("ptb.train.txt")
    if f:
        from collections import Counter

        cnt = Counter()
        with open(f) as fh:
            for line in fh:
                cnt.update(line.split())
        cnt.pop("<unk>", None)
        words = sorted(
            (w for w, c in cnt.items() if c >= min_word_freq),
            key=lambda w: (-cnt[w], w),
        )
        d = {w: i for i, w in enumerate(words)}
    else:
        d = {f"w{i}": i for i in range(_VOCAB)}
    d["<unk>"] = len(d)
    d["<s>"] = len(d)
    d["<e>"] = len(d)
    return d


def _transition_matrix(v, seed=99):
    """Sparse-ish row-stochastic matrix: each word strongly predicts a few
    successors — the structure n-gram models exploit."""
    rng = np.random.RandomState(seed)
    nxt = rng.randint(0, v, size=(v, 4))
    return nxt


def _sentences(word_idx, n_sent, seed):
    v = max(word_idx.values()) - 2  # exclude <unk>/<s>/<e>
    v = max(v, 10)
    nxt = _transition_matrix(v)
    rng = np.random.RandomState(seed)
    for _ in range(n_sent):
        length = rng.randint(5, 25)
        w = rng.randint(0, v)
        sent = [w]
        for _ in range(length - 1):
            w = nxt[w, rng.randint(0, 4)]
            sent.append(int(w))
        yield sent


def _reader(word_idx, n, data_type, is_train):
    f = _real_file("ptb.train.txt" if is_train else "ptb.valid.txt")
    s_id, e_id, unk = word_idx["<s>"], word_idx["<e>"], word_idx["<unk>"]

    def sentences():
        if f:
            with open(f) as fh:
                for line in fh:
                    yield [word_idx.get(w, unk) for w in line.split()]
        else:
            yield from _sentences(
                word_idx, _N_TRAIN if is_train else _N_TEST, 3 if is_train else 4
            )

    def reader():
        for sent in sentences():
            if data_type == DataType.SEQ:
                yield [s_id] + sent + [e_id]
            else:
                padded = [s_id] * (n - 1) + sent + [e_id]
                for i in range(n, len(padded) + 1):
                    yield tuple(padded[i - n : i])

    return reader


def train(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, True)


def test(word_idx, n, data_type=DataType.NGRAM):
    return _reader(word_idx, n, data_type, False)


def convert(path):
    """Converts dataset to recordio shards (reference imikolov.py convert)."""
    from . import common

    n = 5
    word_dict = build_dict()
    common.convert(path, train(word_dict, n), 1000, "imikolov_train")
    common.convert(path, test(word_dict, n), 1000, "imikolov_test")
