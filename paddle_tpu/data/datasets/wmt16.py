"""WMT16 en↔de (reference: python/paddle/v2/dataset/wmt16.py).

Same sample schema as wmt14 — (src_ids, trg_ids(<s>-prefixed),
trg_ids_next(<e>-suffixed)) — with configurable src/trg dict sizes.
Synthetic mapping: reversal + vocabulary permutation (see wmt14.py).
"""

from __future__ import annotations

import numpy as np

from . import wmt14


def train(src_dict_size: int, trg_dict_size: int, src_lang: str = "en"):
    return wmt14._reader(min(src_dict_size, trg_dict_size), wmt14._N_TRAIN, 41)


def test(src_dict_size: int, trg_dict_size: int, src_lang: str = "en"):
    return wmt14._reader(min(src_dict_size, trg_dict_size), wmt14._N_TEST, 42)


def get_dict(lang: str, dict_size: int, reverse: bool = False):
    d, _ = wmt14.get_dict(dict_size, reverse)
    return d


def convert(path, src_dict_size, trg_dict_size, src_lang="en"):
    """Converts dataset to recordio shards (reference wmt16.py convert)."""
    from . import common
    common.convert(
        path, train(src_dict_size=src_dict_size,
                    trg_dict_size=trg_dict_size, src_lang=src_lang),
        1000, "wmt16_train")
    common.convert(
        path, test(src_dict_size=src_dict_size,
                   trg_dict_size=trg_dict_size, src_lang=src_lang),
        1000, "wmt16_test")
