"""MovieLens-1M (reference: python/paddle/v2/dataset/movielens.py).

Reference sample schema (train()/test()):
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
 score) — the 8 feed slots of the recommender_system book model (book/05).
Helper API: max_user_id/max_movie_id/max_job_id, age_table,
movie_categories(), user_info(), movie_info().

The real ml-1m.zip ('::'-separated users.dat/movies.dat/ratings.dat,
reference movielens.py:102-163, split by random.Random(0) per rating at
test_ratio=0.1) is parsed when present under data_home()/movielens.
Otherwise users/movies get latent factors and ratings follow
score = clip(round(u·v + biases), 1..5), so the dual-tower regression model
has real signal to learn.
"""

from __future__ import annotations

import os
import random
import re
import zipfile

import numpy as np

from . import data_home

age_table = [1, 18, 25, 35, 45, 50, 56]

URL = "http://files.grouplens.org/datasets/movielens/ml-1m.zip"
MD5 = "c4d9eecfca2ab87c1945afe126590906"


def fetch():
    from .common import download

    return download(URL, "movielens", MD5)


def _real_zip():
    p = os.path.join(data_home(), "movielens", "ml-1m.zip")
    return p if os.path.exists(p) else None


_REAL_META = None


def _real_meta(zip_path):
    """Parse movies.dat/users.dat into this module's id-based schema
    (reference movielens.py:102-143 __initialize_meta_info__)."""
    global _REAL_META
    if _REAL_META is not None:
        return _REAL_META
    pattern = re.compile(r"^(.*)\((\d+)\)$")
    movies, users = {}, {}
    title_words, categories = set(), set()
    with zipfile.ZipFile(zip_path) as z:
        with z.open("ml-1m/movies.dat") as f:
            for line in f:
                mid, title, cats = line.decode("latin-1").strip().split("::")
                cats = cats.split("|")
                categories.update(cats)
                m = pattern.match(title)
                title = (m.group(1) if m else title).strip()
                movies[int(mid)] = (title, cats)
                title_words.update(w.lower() for w in title.split())
        with z.open("ml-1m/users.dat") as f:
            for line in f:
                uid, gender, age, job, _ = \
                    line.decode("latin-1").strip().split("::")
                users[int(uid)] = (0 if gender == "M" else 1,
                                   age_table.index(int(age)), int(job))
    cat_dict = {c: i for i, c in enumerate(sorted(categories))}
    title_dict = {w: i for i, w in enumerate(sorted(title_words))}
    _REAL_META = (movies, users, cat_dict, title_dict)
    return _REAL_META


def _real_reader(zip_path, is_test, rand_seed=0, test_ratio=0.1):
    """Reference movielens.py:145 __reader__ — per-rating random split."""
    def reader():
        movies, users, cat_dict, title_dict = _real_meta(zip_path)
        rand = random.Random(x=rand_seed)
        with zipfile.ZipFile(zip_path) as z, \
                z.open("ml-1m/ratings.dat") as f:
            for line in f:
                if (rand.random() < test_ratio) != is_test:
                    continue
                uid, mid, score, _ = \
                    line.decode("latin-1").strip().split("::")
                uid, mid = int(uid), int(mid)
                gender, age_id, job = users[uid]
                title, cats = movies[mid]
                yield (
                    uid, gender, age_id, job, mid,
                    [cat_dict[c] for c in cats],
                    [title_dict[w.lower()] for w in title.split()],
                    float(score),
                )

    return reader

_N_USERS = 400
_N_MOVIES = 300
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 1000
_N_TRAIN, _N_TEST = 6000, 600
_DIM = 6


def max_user_id() -> int:
    z = _real_zip()
    if z:
        _, users, _, _ = _real_meta(z)
        return max(users)
    return _N_USERS


def max_movie_id() -> int:
    z = _real_zip()
    if z:
        movies, _, _, _ = _real_meta(z)
        return max(movies)
    return _N_MOVIES


def max_job_id() -> int:
    z = _real_zip()
    if z:
        _, users, _, _ = _real_meta(z)
        return max(job for _, _, job in users.values())
    return _N_JOBS - 1


def movie_categories():
    z = _real_zip()
    if z:
        _, _, cat_dict, _ = _real_meta(z)
        return dict(cat_dict)
    return {f"genre{i}": i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    z = _real_zip()
    if z:
        _, _, _, title_dict = _real_meta(z)
        return dict(title_dict)
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def _factors():
    rng = np.random.RandomState(2024)
    u = rng.randn(_N_USERS + 1, _DIM) * 0.8
    v = rng.randn(_N_MOVIES + 1, _DIM) * 0.8
    ub = rng.randn(_N_USERS + 1) * 0.3
    vb = rng.randn(_N_MOVIES + 1) * 0.3
    genders = rng.randint(0, 2, _N_USERS + 1)
    ages = rng.randint(0, len(age_table), _N_USERS + 1)
    jobs = rng.randint(0, _N_JOBS, _N_USERS + 1)
    cats = [
        sorted(rng.choice(_N_CATEGORIES, size=rng.randint(1, 4), replace=False))
        for _ in range(_N_MOVIES + 1)
    ]
    titles = [
        list(rng.randint(0, _TITLE_VOCAB, size=rng.randint(2, 6)))
        for _ in range(_N_MOVIES + 1)
    ]
    return u, v, ub, vb, genders, ages, jobs, cats, titles


_F = None


def _get_factors():
    global _F
    if _F is None:
        _F = _factors()
    return _F


def user_info():
    z = _real_zip()
    if z:
        _, users, _, _ = _real_meta(z)
        return {uid: {"gender": g, "age": a, "job": j}
                for uid, (g, a, j) in users.items()}
    _, _, _, _, genders, ages, jobs, _, _ = _get_factors()
    return {
        i: {"gender": int(genders[i]), "age": int(ages[i]), "job": int(jobs[i])}
        for i in range(1, _N_USERS + 1)
    }


def movie_info():
    z = _real_zip()
    if z:
        movies, _, cat_dict, title_dict = _real_meta(z)
        return {mid: {"categories": [cat_dict[c] for c in cats],
                      "title": [title_dict[w.lower()] for w in t.split()]}
                for mid, (t, cats) in movies.items()}
    *_, cats, titles = _get_factors()
    return {
        i: {"categories": [int(c) for c in cats[i]], "title": [int(t) for t in titles[i]]}
        for i in range(1, _N_MOVIES + 1)
    }


def _reader(n, seed):
    u, v, ub, vb, genders, ages, jobs, cats, titles = _get_factors()

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = rng.randint(1, _N_USERS + 1)
            mid = rng.randint(1, _N_MOVIES + 1)
            raw = u[uid] @ v[mid] + ub[uid] + vb[mid] + 3.0 + 0.2 * rng.randn()
            score = float(np.clip(np.round(raw), 1, 5))
            yield (
                uid,
                int(genders[uid]),
                int(ages[uid]),
                int(jobs[uid]),
                mid,
                [int(c) for c in cats[mid]],
                [int(t) for t in titles[mid]],
                score,
            )

    return reader


def train():
    z = _real_zip()
    if z:
        return _real_reader(z, is_test=False)
    return _reader(_N_TRAIN, 11)


def test():
    z = _real_zip()
    if z:
        return _real_reader(z, is_test=True)
    return _reader(_N_TEST, 12)


def convert(path):
    """Converts dataset to recordio shards (reference movielens.py convert)."""
    from . import common

    common.convert(path, train(), 1000, "movielens_train")
    common.convert(path, test(), 1000, "movielens_test")
