"""MovieLens-1M (reference: python/paddle/v2/dataset/movielens.py).

Reference sample schema (train()/test()):
(user_id, gender_id, age_id, job_id, movie_id, category_ids, title_ids,
 score) — the 8 feed slots of the recommender_system book model (book/05).
Helper API: max_user_id/max_movie_id/max_job_id, age_table,
movie_categories(), user_info(), movie_info().

With no egress, users/movies get latent factors and ratings follow
score = clip(round(u·v + biases), 1..5), so the dual-tower regression model
has real signal to learn.
"""

from __future__ import annotations

import numpy as np

age_table = [1, 18, 25, 35, 45, 50, 56]

_N_USERS = 400
_N_MOVIES = 300
_N_JOBS = 21
_N_CATEGORIES = 18
_TITLE_VOCAB = 1000
_N_TRAIN, _N_TEST = 6000, 600
_DIM = 6


def max_user_id() -> int:
    return _N_USERS


def max_movie_id() -> int:
    return _N_MOVIES


def max_job_id() -> int:
    return _N_JOBS - 1


def movie_categories():
    return {f"genre{i}": i for i in range(_N_CATEGORIES)}


def get_movie_title_dict():
    return {f"t{i}": i for i in range(_TITLE_VOCAB)}


def _factors():
    rng = np.random.RandomState(2024)
    u = rng.randn(_N_USERS + 1, _DIM) * 0.8
    v = rng.randn(_N_MOVIES + 1, _DIM) * 0.8
    ub = rng.randn(_N_USERS + 1) * 0.3
    vb = rng.randn(_N_MOVIES + 1) * 0.3
    genders = rng.randint(0, 2, _N_USERS + 1)
    ages = rng.randint(0, len(age_table), _N_USERS + 1)
    jobs = rng.randint(0, _N_JOBS, _N_USERS + 1)
    cats = [
        sorted(rng.choice(_N_CATEGORIES, size=rng.randint(1, 4), replace=False))
        for _ in range(_N_MOVIES + 1)
    ]
    titles = [
        list(rng.randint(0, _TITLE_VOCAB, size=rng.randint(2, 6)))
        for _ in range(_N_MOVIES + 1)
    ]
    return u, v, ub, vb, genders, ages, jobs, cats, titles


_F = None


def _get_factors():
    global _F
    if _F is None:
        _F = _factors()
    return _F


def user_info():
    _, _, _, _, genders, ages, jobs, _, _ = _get_factors()
    return {
        i: {"gender": int(genders[i]), "age": int(ages[i]), "job": int(jobs[i])}
        for i in range(1, _N_USERS + 1)
    }


def movie_info():
    *_, cats, titles = _get_factors()
    return {
        i: {"categories": [int(c) for c in cats[i]], "title": [int(t) for t in titles[i]]}
        for i in range(1, _N_MOVIES + 1)
    }


def _reader(n, seed):
    u, v, ub, vb, genders, ages, jobs, cats, titles = _get_factors()

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            uid = rng.randint(1, _N_USERS + 1)
            mid = rng.randint(1, _N_MOVIES + 1)
            raw = u[uid] @ v[mid] + ub[uid] + vb[mid] + 3.0 + 0.2 * rng.randn()
            score = float(np.clip(np.round(raw), 1, 5))
            yield (
                uid,
                int(genders[uid]),
                int(ages[uid]),
                int(jobs[uid]),
                mid,
                [int(c) for c in cats[mid]],
                [int(t) for t in titles[mid]],
                score,
            )

    return reader


def train():
    return _reader(_N_TRAIN, 11)


def test():
    return _reader(_N_TEST, 12)
