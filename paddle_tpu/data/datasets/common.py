"""Dataset download machinery.

Reference: python/paddle/v2/dataset/common.py:55-100 (`md5file`,
`download(url, module_name, md5sum)` — cache under DATA_HOME/module_name,
verify checksum, re-download up to 3 times). Same contract here, built on
urllib (no requests dependency) and network-off safe: with no egress a
cached-and-verified file is returned without touching the network, and a
failed fetch raises a RuntimeError naming the cache path to pre-seed.
"""

from __future__ import annotations

import hashlib
import os
import shutil
from typing import Optional

from . import data_home

__all__ = ["md5file", "download", "convert"]


def convert(output_path, reader, line_count, name_prefix):
    """Serialize a reader's samples to recordio shard files of up to
    `line_count` records each, named `{output_path}/{name_prefix}-%05d`.

    Reference: python/paddle/v2/dataset/common.py:200 `convert` — the
    seam between the dataset zoo and the cloud data path (shards are the
    task unit the master dispatches, go/master/service.go; here
    native/master.cc + data/recordio.py master_reader). `reader` may be
    a reader function or an already-created sample iterable, as in the
    reference's per-dataset convert() callers.
    """
    import itertools

    from ..recordio import write_shard

    assert line_count >= 1
    # accept a reader fn, a reader-creator, or a sample iterable (the
    # reference's per-dataset callers pass all three styles)
    samples = reader
    while callable(samples):
        samples = samples()
    samples = iter(samples)
    os.makedirs(output_path, exist_ok=True)
    paths = []
    for idx in itertools.count():
        chunk = list(itertools.islice(samples, line_count))
        if not chunk:
            break
        path = os.path.join(output_path, f"{name_prefix}-{idx:05d}")
        write_shard(path, chunk)
        paths.append(path)
    return paths


def md5file(fname: str) -> str:
    """Reference: common.py:55 — streaming md5 of a file."""
    h = hashlib.md5()
    with open(fname, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    return h.hexdigest()


# socket timeout handed to urlopen: a stalled mirror must fail the
# attempt in seconds (and spend one of the 3 retries), never hang the
# job forever on a dead recv(). Overridable per-host via env.
DOWNLOAD_TIMEOUT_S = float(os.environ.get("PT_DOWNLOAD_TIMEOUT", "30"))


def download(url: str, module_name: str, md5sum: str,
             save_name: Optional[str] = None,
             timeout: Optional[float] = None) -> str:
    """Return the path of the cached, checksum-verified file; fetch it if
    missing. Reference: common.py:65."""
    import socket

    dirname = os.path.join(data_home(), module_name)
    os.makedirs(dirname, exist_ok=True)
    filename = os.path.join(
        dirname, save_name if save_name else url.split("/")[-1]
    )
    timeout = DOWNLOAD_TIMEOUT_S if timeout is None else float(timeout)

    retry, retry_limit, timeouts = 0, 3, 0
    while not (os.path.exists(filename) and md5file(filename) == md5sum):
        if retry == retry_limit:
            timed_out = (f" ({timeouts} of them stalled past the "
                         f"{timeout:g}s socket timeout)" if timeouts else "")
            raise RuntimeError(
                f"cannot download {url} within {retry_limit} retries"
                f"{timed_out}; if this host has no egress, pre-seed the "
                f"cache file at {filename} (md5 {md5sum})"
            )
        retry += 1
        tmp = filename + ".part"
        try:
            import urllib.request

            with urllib.request.urlopen(url, timeout=timeout) as r, \
                    open(tmp, "wb") as f:
                shutil.copyfileobj(r, f)
            os.replace(tmp, filename)
        except (socket.timeout, TimeoutError):
            # a stall counts against the same retry budget as any other
            # failure, but is reported distinctly — "mirror is slow" and
            # "mirror is wrong" need different fixes
            timeouts += 1
        except Exception as e:  # noqa: BLE001 — retry loop decides fatality
            # connect-phase timeouts surface wrapped in URLError.reason
            if isinstance(getattr(e, "reason", None),
                          (socket.timeout, TimeoutError)):
                timeouts += 1
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    return filename
