"""CoNLL-2005 semantic role labeling (reference:
python/paddle/v2/dataset/conll05.py).

Reference sample schema (test()): 9 sequence slots per (sentence, predicate)
pair — (word_ids, ctx_n2, ctx_n1, ctx_0, ctx_p1, ctx_p2, pred_ids, mark,
label_ids) — exactly the feeds of the label_semantic_roles book model
(book/07). get_dict() → (word_dict, verb_dict, label_dict); label_dict uses
the B-/I-/O tag layout the ChunkEvaluator expects.

Synthetic generation: each sentence has one predicate; tokens near the
predicate get role spans whose type depends on (token bucket, side), so the
tagger has deterministic structure to learn.
"""

from __future__ import annotations

import numpy as np

_WORD_VOCAB = 3000
_N_VERBS = 50
_N_ROLES = 4  # role types → labels B-Ai/I-Ai per type + O
_N_TRAIN, _N_TEST = 1500, 200


def word_dict():
    d = {f"w{i}": i for i in range(_WORD_VOCAB)}
    d["<unk>"] = len(d)
    return d


def verb_dict():
    return {f"v{i}": i for i in range(_N_VERBS)}


def label_dict():
    # IOB layout: B-A0=0, I-A0=1, B-A1=2, I-A1=3, ... O=2*_N_ROLES
    d = {}
    for t in range(_N_ROLES):
        d[f"B-A{t}"] = 2 * t
        d[f"I-A{t}"] = 2 * t + 1
    d["O"] = 2 * _N_ROLES
    return d


def get_dict():
    return word_dict(), verb_dict(), label_dict()


def get_embedding():
    """Reference ships a pretrained emb matrix; here a fixed random one."""
    rng = np.random.RandomState(5)
    return rng.randn(_WORD_VOCAB + 1, 32).astype(np.float32)


def _ctx(words, pred_pos, off):
    """Predicate-context word at pred_pos+off, broadcast over the sequence
    (reference conll05: ctx_n2..ctx_p2 are constant per (sentence, verb))."""
    j = min(max(pred_pos + off, 0), len(words) - 1)
    return words[j]


def _reader(n, seed):
    o_tag = 2 * _N_ROLES

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = rng.randint(6, 20)
            words = rng.randint(0, _WORD_VOCAB, size=length).tolist()
            pred_pos = rng.randint(1, length - 1)
            verb = words[pred_pos] % _N_VERBS
            labels = [o_tag] * length
            # role span left of the predicate; type from word id parity
            lstart = max(0, pred_pos - 3)
            t0 = verb % 2  # A0 or A1 — keyed to the predicate so the
            labels[lstart] = 2 * t0  # mapping generalizes to unseen words
            for k in range(lstart + 1, pred_pos):
                labels[k] = 2 * t0 + 1
            # role span right of the predicate
            rend = min(length, pred_pos + 1 + rng.randint(1, 4))
            t1 = 2 + (verb >> 1) % 2  # A2 or A3
            labels[pred_pos + 1] = 2 * t1
            for k in range(pred_pos + 2, rend):
                labels[k] = 2 * t1 + 1
            mark = [1 if k == pred_pos else 0 for k in range(length)]
            preds = [verb] * length
            yield (
                words,
                [_ctx(words, pred_pos, -2)] * length,
                [_ctx(words, pred_pos, -1)] * length,
                [_ctx(words, pred_pos, 0)] * length,
                [_ctx(words, pred_pos, 1)] * length,
                [_ctx(words, pred_pos, 2)] * length,
                preds,
                mark,
                labels,
            )

    return reader


def train():
    return _reader(_N_TRAIN, 21)


def test():
    return _reader(_N_TEST, 22)


def convert(path):
    """Converts dataset to recordio shards. The reference wrote test()
    into both prefixes because its train split was license-gated
    (conll05.py convert); here train() exists, so the train shards carry
    the actual train split."""
    from . import common

    common.convert(path, train(), 1000, "conll05_train")
    common.convert(path, test(), 1000, "conll05_test")
