"""IMDB sentiment dataset (reference: python/paddle/v2/dataset/imdb.py).

Sample schema: (word_ids list[int], label 0/1). With no egress, synthesizes
variable-length reviews from two class-conditional token distributions
(positive reviews over-sample the first vocab half), so stacked-LSTM /
conv sentiment models can learn the classes. word_dict() returns a vocab
of the same shape as the reference API.
"""

from __future__ import annotations

import glob
import os
import re

import numpy as np

from . import data_home

_VOCAB = 5147  # reference: imdb word dict ~5147 after cutoff
_N_TRAIN, _N_TEST = 2000, 400


def _real_dir():
    d = os.path.join(data_home(), "imdb", "aclImdb")
    return d if os.path.isdir(d) else None


_word_dict_cache = None


def _tokenize(text):
    return re.sub(r"[^a-z0-9 ]", " ", text.lower()).split()


def _build_real_dict(root, min_freq=30):
    from collections import Counter

    cnt = Counter()
    for path in glob.glob(os.path.join(root, "train", "*", "*.txt")):
        with open(path, errors="ignore") as f:
            cnt.update(_tokenize(f.read()))
    # strictly > like the reference's build_dict cutoff (imdb.py:66)
    words = [w for w, c in cnt.most_common() if c > min_freq]
    return {w: i for i, w in enumerate(words)}


def word_dict(min_freq=30):
    """Reference: imdb.word_dict() — token → id (strict frequency cutoff
    like the reference's build_dict(re, 150)). Uses real aclImdb data under
    data_home()/imdb/aclImdb when present, else a synthetic vocab."""
    global _word_dict_cache
    if _word_dict_cache is None:
        _word_dict_cache = {}
    if min_freq not in _word_dict_cache:
        root = _real_dir()
        _word_dict_cache[min_freq] = (
            _build_real_dict(root, min_freq) if root
            else {f"w{i}": i for i in range(_VOCAB)}
        )
    return _word_dict_cache[min_freq]


def _real_reader(split):
    root = _real_dir()
    wd = word_dict()
    unk = len(wd)

    def reader():
        for label, sub in ((1, "pos"), (0, "neg")):
            for path in sorted(glob.glob(os.path.join(root, split, sub, "*.txt"))):
                with open(path, errors="ignore") as f:
                    ids = [wd.get(w, unk) for w in _tokenize(f.read())]
                yield ids, label

    return reader


def _make(n, seed):
    rng = np.random.RandomState(seed)
    samples = []
    half = _VOCAB // 2
    for _ in range(n):
        label = int(rng.randint(0, 2))
        length = int(rng.randint(8, 120))
        # class-dependent mixture: label 1 prefers low ids, 0 prefers high
        if label == 1:
            ids = np.where(
                rng.rand(length) < 0.8,
                rng.randint(0, half, length),
                rng.randint(half, _VOCAB, length),
            )
        else:
            ids = np.where(
                rng.rand(length) < 0.8,
                rng.randint(half, _VOCAB, length),
                rng.randint(0, half, length),
            )
        samples.append((ids.astype(np.int32).tolist(), label))
    return samples


def train(word_idx=None):
    if _real_dir():
        return _real_reader("train")

    def reader():
        for ids, label in _make(_N_TRAIN, seed=0):
            yield ids, label

    return reader


def test(word_idx=None):
    if _real_dir():
        return _real_reader("test")

    def reader():
        for ids, label in _make(_N_TEST, seed=1):
            yield ids, label

    return reader


def convert(path):
    """Converts dataset to recordio shards (reference imdb.py convert)."""
    from . import common

    w = word_dict()
    common.convert(path, lambda: train(w), 1000, "imdb_train")
    common.convert(path, lambda: test(w), 1000, "imdb_test")
