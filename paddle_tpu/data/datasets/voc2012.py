"""Pascal VOC2012 segmentation (reference: python/paddle/v2/dataset/voc2012.py).

Sample schema: (image[3,H,W] float32, label_map[H,W] int32 in [0,21)) —
21 classes incl. background. Synthetic scenes place 1-3 solid-color
rectangles (class-correlated colors) on a textured background so a small
segmentation head can learn pixel classes.
"""

from __future__ import annotations

import numpy as np

N_CLASSES = 21
_H = _W = 64
_N_TRAIN, _N_TEST = 600, 120


def _scene(rng):
    img = 0.1 * rng.randn(3, _H, _W).astype(np.float32) + 0.4
    lbl = np.zeros((_H, _W), np.int32)
    colors = np.linspace(0, 1, N_CLASSES)
    for _ in range(rng.randint(1, 4)):
        c = rng.randint(1, N_CLASSES)
        h, w = rng.randint(8, 32), rng.randint(8, 32)
        y, x = rng.randint(0, _H - h), rng.randint(0, _W - w)
        img[0, y : y + h, x : x + w] = colors[c]
        img[1, y : y + h, x : x + w] = 1 - colors[c]
        img[2, y : y + h, x : x + w] = (c % 5) / 5.0
        lbl[y : y + h, x : x + w] = c
    return img, lbl


def _reader(n, seed):
    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            yield _scene(rng)

    return reader


def train():
    return _reader(_N_TRAIN, 81)


def test():
    return _reader(_N_TEST, 82)


def val():
    return _reader(_N_TEST, 83)
