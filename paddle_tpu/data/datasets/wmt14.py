"""WMT14 fr→en translation (reference: python/paddle/v2/dataset/wmt14.py).

Reference sample schema (train(dict_size)): (src_ids, trg_ids, trg_ids_next)
where trg_ids is <s>-prefixed and trg_ids_next is the shifted target ending
in <e> — the three feeds of the machine_translation book model (book/08).
Special ids follow the reference: <s>=0, <e>=1, <unk>=2.

The real wmt14.tgz (src.dict / trg.dict / train/train / test/test members,
tab-separated parallel lines — reference wmt14.py:53-110) is parsed when
present under data_home()/wmt14; otherwise synthetic generation: the
"translation" of a source sentence is its reversal with a fixed vocabulary
permutation — a deterministic mapping that a seq2seq-with-attention model
can actually learn, giving the acceptance test a convergence signal.
"""

from __future__ import annotations

import os
import tarfile

import numpy as np

from . import data_home

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2
_RESERVED = 3

_N_TRAIN, _N_TEST = 3000, 300

URL_TRAIN = ("http://paddlepaddle.cdn.bcebos.com/demo/"
             "wmt_shrinked_data/wmt14.tgz")
MD5_TRAIN = "0791583d57d5beb693b9414c5b36798c"


def _real_tar():
    p = os.path.join(data_home(), "wmt14", "wmt14.tgz")
    return p if os.path.exists(p) else None


def fetch():
    """Reference: common.download(URL_TRAIN, 'wmt14', MD5_TRAIN)."""
    from .common import download

    return download(URL_TRAIN, "wmt14", MD5_TRAIN)


def _read_real_dict(tar_path, suffix, dict_size):
    with tarfile.open(tar_path) as f:
        names = [m.name for m in f if m.name.endswith(suffix)]
        assert len(names) == 1, (suffix, names)
        out = {}
        for i, line in enumerate(f.extractfile(names[0])):
            if i >= dict_size:
                break
            out[line.strip().decode("utf-8")] = i
        return out


def _real_reader(tar_path, member_suffix, dict_size):
    """Reference: wmt14.py reader_creator — <s>/<e>-wrapped source ids,
    <s>-prefixed target, next-target ending in <e>; drop length>80."""
    # parsed once per reader creator, not once per epoch
    src_dict = _read_real_dict(tar_path, "src.dict", dict_size)
    trg_dict = _read_real_dict(tar_path, "trg.dict", dict_size)

    def reader():
        with tarfile.open(tar_path) as f:
            names = [m.name for m in f if m.name.endswith(member_suffix)]
            for name in names:
                for line in f.extractfile(name):
                    parts = line.decode("utf-8").strip().split("\t")
                    if len(parts) != 2:
                        continue
                    src_ids = [src_dict.get(w, UNK_ID)
                               for w in [START] + parts[0].split() + [END]]
                    trg_words = [trg_dict.get(w, UNK_ID)
                                 for w in parts[1].split()]
                    if len(src_ids) > 80 or len(trg_words) > 80:
                        continue
                    yield (src_ids, [trg_dict[START]] + trg_words,
                           trg_words + [trg_dict[END]])

    return reader


def _perm(dict_size, seed=17):
    rng = np.random.RandomState(seed)
    content = dict_size - _RESERVED
    return rng.permutation(content)


def _reader(dict_size, n, seed):
    perm = _perm(dict_size)
    content = dict_size - _RESERVED

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = rng.randint(3, 12)
            src = rng.randint(0, content, size=length)
            trg = perm[src[::-1]] + _RESERVED
            src = src + _RESERVED
            trg_in = [START_ID] + trg.tolist()
            trg_next = trg.tolist() + [END_ID]
            yield src.tolist(), trg_in, trg_next

    return reader


def train(dict_size: int):
    tar = _real_tar()
    if tar:
        return _real_reader(tar, "train/train", dict_size)
    return _reader(dict_size, _N_TRAIN, 31)


def test(dict_size: int):
    tar = _real_tar()
    if tar:
        return _real_reader(tar, "test/test", dict_size)
    return _reader(dict_size, _N_TEST, 32)


def get_dict(dict_size: int, reverse: bool = False):
    """Reference API: (src_dict, trg_dict)."""
    tar = _real_tar()
    if tar:
        src = _read_real_dict(tar, "src.dict", dict_size)
        trg = _read_real_dict(tar, "trg.dict", dict_size)
        if reverse:
            src = {v: k for k, v in src.items()}
            trg = {v: k for k, v in trg.items()}
        return src, trg

    def mk():
        d = {START: START_ID, END: END_ID, UNK: UNK_ID}
        for i in range(dict_size - _RESERVED):
            d[f"tok{i}"] = i + _RESERVED
        return {v: k for k, v in d.items()} if reverse else d

    return mk(), mk()


def convert(path):
    """Converts dataset to recordio shards (reference wmt14.py convert)."""
    from . import common

    dict_size = 30000
    common.convert(path, train(dict_size), 1000, "wmt14_train")
    common.convert(path, test(dict_size), 1000, "wmt14_test")
