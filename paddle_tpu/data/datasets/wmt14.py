"""WMT14 fr→en translation (reference: python/paddle/v2/dataset/wmt14.py).

Reference sample schema (train(dict_size)): (src_ids, trg_ids, trg_ids_next)
where trg_ids is <s>-prefixed and trg_ids_next is the shifted target ending
in <e> — the three feeds of the machine_translation book model (book/08).
Special ids follow the reference: <s>=0, <e>=1, <unk>=2.

Synthetic generation: the "translation" of a source sentence is its reversal
with a fixed vocabulary permutation — a deterministic mapping that a
seq2seq-with-attention model can actually learn, giving the acceptance test
a convergence signal.
"""

from __future__ import annotations

import numpy as np

START = "<s>"
END = "<e>"
UNK = "<unk>"
START_ID, END_ID, UNK_ID = 0, 1, 2
_RESERVED = 3

_N_TRAIN, _N_TEST = 3000, 300


def _perm(dict_size, seed=17):
    rng = np.random.RandomState(seed)
    content = dict_size - _RESERVED
    return rng.permutation(content)


def _reader(dict_size, n, seed):
    perm = _perm(dict_size)
    content = dict_size - _RESERVED

    def reader():
        rng = np.random.RandomState(seed)
        for _ in range(n):
            length = rng.randint(3, 12)
            src = rng.randint(0, content, size=length)
            trg = perm[src[::-1]] + _RESERVED
            src = src + _RESERVED
            trg_in = [START_ID] + trg.tolist()
            trg_next = trg.tolist() + [END_ID]
            yield src.tolist(), trg_in, trg_next

    return reader


def train(dict_size: int):
    return _reader(dict_size, _N_TRAIN, 31)


def test(dict_size: int):
    return _reader(dict_size, _N_TEST, 32)


def get_dict(dict_size: int, reverse: bool = False):
    """Reference API: (src_dict, trg_dict); synthetic vocab tokens."""
    def mk():
        d = {START: START_ID, END: END_ID, UNK: UNK_ID}
        for i in range(dict_size - _RESERVED):
            d[f"tok{i}"] = i + _RESERVED
        return {v: k for k, v in d.items()} if reverse else d

    return mk(), mk()
