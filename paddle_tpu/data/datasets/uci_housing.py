"""UCI housing dataset (reference: python/paddle/v2/dataset/uci_housing.py).

Sample schema: (features[13] float32, price[1] float32), features
standardized. The real housing.data (whitespace floats, 14 columns,
(x-avg)/(max-min) normalization, 80/20 split — reference
uci_housing.py:60-75) is parsed when present under
data_home()/uci_housing; otherwise the data is synthesized from a fixed
linear model + noise — statistically equivalent for the fit_a_line
acceptance test (book/01), which only asserts loss convergence.
"""

from __future__ import annotations

import os

import numpy as np

from . import data_home

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
    "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT",
]

_N_TRAIN, _N_TEST = 404, 102

URL = "https://archive.ics.uci.edu/ml/machine-learning-databases/housing/housing.data"
MD5 = "d4accdce7a25600298819f8e28e8d593"


def fetch():
    from .common import download

    return download(URL, "uci_housing", MD5)


def _real_file():
    p = os.path.join(data_home(), "uci_housing", "housing.data")
    return p if os.path.exists(p) else None


def _load_real(filename, feature_num=14, ratio=0.8):
    """Reference: uci_housing.py:60 load_data — (x-avg)/(max-min) per
    feature, first 80% train / rest test."""
    data = np.fromfile(filename, sep=" ")
    data = data.reshape(data.shape[0] // feature_num, feature_num)
    maxs, mins = data.max(axis=0), data.min(axis=0)
    avgs = data.mean(axis=0)
    for i in range(feature_num - 1):
        data[:, i] = (data[:, i] - avgs[i]) / (maxs[i] - mins[i])
    offset = int(data.shape[0] * ratio)
    return data[:offset].astype(np.float32), data[offset:].astype(np.float32)


def _make(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 13).astype(np.float32)
    w = np.linspace(-1.5, 1.5, 13).astype(np.float32)[:, None]
    y = x @ w + 0.3 + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y.astype(np.float32)


def _reader(is_train):
    def reader():
        f = _real_file()
        if f:
            tr, te = _load_real(f)
            rows = tr if is_train else te
            for row in rows:
                yield row[:-1], row[-1:]
            return
        x, y = _make(
            _N_TRAIN if is_train else _N_TEST, seed=0 if is_train else 1
        )
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader


def train():
    return _reader(True)


def test():
    return _reader(False)


def convert(path):
    """Converts dataset to recordio shards (reference uci_housing.py:129)."""
    from . import common

    common.convert(path, train(), 1000, "uci_housing_train")
    common.convert(path, test(), 1000, "uci_housing_test")
