"""UCI housing dataset (reference: python/paddle/v2/dataset/uci_housing.py).

Sample schema: (features[13] float32, price[1] float32), features
standardized. With no egress the data is synthesized from a fixed linear
model + noise — statistically equivalent for the fit_a_line acceptance test
(book/01), which only asserts loss convergence.
"""

from __future__ import annotations

import numpy as np

feature_names = [
    "CRIM", "ZN", "INDUS", "CHAS", "NOX", "RM", "AGE",
    "DIS", "RAD", "TAX", "PTRATIO", "B", "LSTAT",
]

_N_TRAIN, _N_TEST = 404, 102


def _make(n, seed):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 13).astype(np.float32)
    w = np.linspace(-1.5, 1.5, 13).astype(np.float32)[:, None]
    y = x @ w + 0.3 + 0.1 * rng.randn(n, 1).astype(np.float32)
    return x, y.astype(np.float32)


def train():
    def reader():
        x, y = _make(_N_TRAIN, seed=0)
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader


def test():
    def reader():
        x, y = _make(_N_TEST, seed=1)
        for i in range(x.shape[0]):
            yield x[i], y[i]

    return reader
