"""MNIST dataset (reference: python/paddle/v2/dataset/mnist.py).

Sample schema: (image[784] float32 in [-1, 1], label int). Real IDX files
are used when present under data_home()/mnist; otherwise a deterministic
synthetic digit generator produces linearly-separable-ish classes so the
recognize_digits acceptance tests (book/02) can assert convergence.
"""

from __future__ import annotations

import gzip
import os
import struct

import numpy as np

from . import data_home

_N_TRAIN, _N_TEST = 8000, 1000


def _load_idx(img_path, lbl_path):
    with gzip.open(lbl_path, "rb") as f:
        magic, n_lbl = struct.unpack(">II", f.read(8))
        if magic != 2049:
            raise ValueError(f"corrupt MNIST label file {lbl_path}: magic={magic}")
        labels = np.frombuffer(f.read(), dtype=np.uint8)
    with gzip.open(img_path, "rb") as f:
        magic, n, rows, cols = struct.unpack(">IIII", f.read(16))
        if magic != 2051:
            raise ValueError(f"corrupt MNIST image file {img_path}: magic={magic}")
        images = np.frombuffer(f.read(), dtype=np.uint8).reshape(n, rows * cols)
    if n != n_lbl or len(labels) != n:
        raise ValueError(f"MNIST image/label count mismatch: {n} vs {n_lbl}")
    images = images.astype(np.float32) / 255.0 * 2.0 - 1.0
    return images, labels.astype(np.int64)


def _synthetic(n, seed):
    """Each class = a fixed spatially-smooth 28x28 template + noise.

    Templates are low-res (7x7) random fields upsampled 4x, so they carry
    local spatial structure that conv/pool layers can exploit (white-noise
    templates would be destroyed by pooling)."""
    rng = np.random.RandomState(42)
    low = rng.randn(10, 7, 7).astype(np.float32)
    templates = low.repeat(4, axis=1).repeat(4, axis=2).reshape(10, 784)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, 10, size=n).astype(np.int64)
    images = templates[labels] * 0.6 + 0.5 * rng.randn(n, 784).astype(np.float32)
    images = np.clip(images, -1.0, 1.0)
    return images.astype(np.float32), labels


def _data(split):
    home = os.path.join(data_home(), "mnist")
    files = {
        "train": ("train-images-idx3-ubyte.gz", "train-labels-idx1-ubyte.gz"),
        "test": ("t10k-images-idx3-ubyte.gz", "t10k-labels-idx1-ubyte.gz"),
    }[split]
    paths = [os.path.join(home, f) for f in files]
    if all(os.path.exists(p) for p in paths):
        return _load_idx(*paths)
    n, seed = (_N_TRAIN, 0) if split == "train" else (_N_TEST, 1)
    return _synthetic(n, seed)


def train():
    def reader():
        images, labels = _data("train")
        for i in range(images.shape[0]):
            yield images[i], int(labels[i])

    return reader


def test():
    def reader():
        images, labels = _data("test")
        for i in range(images.shape[0]):
            yield images[i], int(labels[i])

    return reader


def convert(path):
    """Converts dataset to recordio shards (reference mnist.py convert)."""
    from . import common
    common.convert(path, train(), 1000, "mnist_train")
    common.convert(path, test(), 1000, "mnist_test")
