"""CIFAR-10/100 (reference: python/paddle/v2/dataset/cifar.py).

Sample schema: (image[3072] float32 in [0,1], label int) — 3x32x32
flattened, matching the reference's reader output. Real pickled python
batches are used when present under data_home()/cifar; otherwise a
deterministic synthetic generator produces class-conditional smooth color
fields so the image_classification acceptance tests (book/03) converge.
"""

from __future__ import annotations

import os
import pickle
import tarfile

import numpy as np

from . import data_home

_N_TRAIN, _N_TEST = 4000, 800


def _real_archive(name):
    p = os.path.join(data_home(), "cifar", name)
    return p if os.path.exists(p) else None


def _read_real(archive, is_train):
    with tarfile.open(archive) as tf:
        for member in tf.getmembers():
            base = os.path.basename(member.name)
            is_batch = (
                base.startswith("data_batch") if is_train else base == "test_batch"
            ) or (base == "train" if is_train else base == "test")
            if not is_batch:
                continue
            d = pickle.load(tf.extractfile(member), encoding="latin1")
            labels = d.get("labels", d.get("fine_labels"))
            for img, lbl in zip(d["data"], labels):
                yield img.astype(np.float32) / 255.0, int(lbl)


def _synthetic(n, n_classes, seed):
    rng = np.random.RandomState(1234 + n_classes)
    low = rng.randn(n_classes, 3, 8, 8).astype(np.float32)
    templates = low.repeat(4, axis=2).repeat(4, axis=3).reshape(n_classes, 3072)
    rng = np.random.RandomState(seed)
    labels = rng.randint(0, n_classes, size=n)
    imgs = templates[labels] * 0.5 + 0.35 * rng.randn(n, 3072).astype(np.float32)
    imgs = 1.0 / (1.0 + np.exp(-imgs))  # squash to (0,1) like real pixels
    return imgs.astype(np.float32), labels.astype(np.int64)


def _reader(n_classes, is_train):
    archive = _real_archive(
        "cifar-10-python.tar.gz" if n_classes == 10 else "cifar-100-python.tar.gz"
    )

    def reader():
        if archive:
            yield from _read_real(archive, is_train)
        else:
            n = _N_TRAIN if is_train else _N_TEST
            imgs, labels = _synthetic(n, n_classes, 7 if is_train else 8)
            for i in range(n):
                yield imgs[i], int(labels[i])

    return reader


def train10():
    return _reader(10, True)


def test10():
    return _reader(10, False)


def train100():
    return _reader(100, True)


def test100():
    return _reader(100, False)


def convert(path):
    """Converts dataset to recordio shards (reference cifar.py:132)."""
    from . import common
    common.convert(path, train100(), 1000, "cifar_train100")
    common.convert(path, test100(), 1000, "cifar_test100")
    common.convert(path, train10(), 1000, "cifar_train10")
    common.convert(path, test10(), 1000, "cifar_test10")
