"""Recordio-backed readers: sharded dataset files + fault-tolerant

dispatch. Reference: the v2 cloud data path — convert datasets to
recordio shards, the master partitions shards into tasks, trainers pull
tasks and stream records (go/master/service.go; python/paddle/v2/
master/client.py). Serialization is pickle (the reference uses its own
framing; the container format is the native recordio).
"""

from __future__ import annotations

import pickle
import time
from typing import Callable, List, Optional, Sequence

from ..native import Master, Prefetcher, RecordIOReader, RecordIOWriter

__all__ = ["dump_reader", "recordio_reader", "master_reader",
           "write_shard"]


def _dumps(sample) -> bytes:
    """The one record serialization (shared by every shard writer)."""
    return pickle.dumps(sample, pickle.HIGHEST_PROTOCOL)


def write_shard(path: str, samples) -> int:
    """Write an iterable of samples as one recordio shard; returns the
    record count. The sequential-chunk sharding of the dataset zoo's
    convert() (data/datasets/common.py) builds on this."""
    w = RecordIOWriter(path)
    n = 0
    try:
        for s in samples:
            w.write(_dumps(s))
            n += 1
    finally:
        w.close()
    return n


def dump_reader(reader: Callable, path_prefix: str, num_shards: int = 1,
                max_records_per_shard: Optional[int] = None) -> List[str]:
    """Serialize a reader's samples round-robin into recordio shards.

    Returns the shard paths (path_prefix-00000-of-00005 style)."""
    paths = [
        f"{path_prefix}-{i:05d}-of-{num_shards:05d}" for i in range(num_shards)
    ]
    writers = [RecordIOWriter(p) for p in paths]
    try:
        for i, sample in enumerate(reader()):
            if max_records_per_shard is not None and (
                i // num_shards
            ) >= max_records_per_shard:
                break
            writers[i % num_shards].write(_dumps(sample))
    finally:
        for w in writers:
            w.close()
    return paths


def recordio_reader(paths: Sequence[str], n_threads: int = 2,
                    capacity: int = 4096) -> Callable:
    """Reader over recordio shards with native async prefetch
    (DataProvider.h:292 double-buffering parity)."""

    def reader():
        with Prefetcher(paths, n_threads=n_threads, capacity=capacity) as pf:
            for rec in pf:
                yield pickle.loads(rec)

    return reader


def master_reader(master: Master, paths: Optional[Sequence[str]] = None) -> Callable:
    """Fault-tolerant reader: pulls shard tasks from the master, streams

    each shard, reports finished/failed. Re-queued tasks (from a worker
    that died mid-shard) are re-read in full — task granularity is the
    unit of at-least-once delivery, exactly the Go master's contract.

    Call once per pass; if `paths` is given they are enqueued on the
    first call (subsequent passes re-queue via master.new_pass())."""
    state = {"dataset_set": False}

    def reader():
        if paths is not None and not state["dataset_set"]:
            master.set_dataset(list(paths))
            state["dataset_set"] = True
        while True:
            task = master.get_task()
            if task is None:
                counts = master.counts()
                if counts["pending"] == 0 and counts["todo"] == 0:
                    return  # pass complete (only done/failed remain)
                time.sleep(0.05)  # a pending task must time out first
                continue
            task_id, meta = task
            try:
                with RecordIOReader(meta.decode()) as r:
                    for rec in r:
                        yield pickle.loads(rec)
            except Exception:
                master.task_failed(task_id)
                raise
            master.task_finished(task_id)

    return reader
