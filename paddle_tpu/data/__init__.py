"""Data subsystem: reader combinators, datasets, feeders.

Reference: python/paddle/v2/reader + dataset + data_feeder (SURVEY.md §2.2).
"""

from . import reader  # noqa: F401
from .feeder import DataFeeder, DevicePrefetcher  # noqa: F401
from .reader import batch, buffered, cache, chain, compose, firstn, map_readers, shuffle, xmap_readers  # noqa: F401

# recordio/master build the native .so lazily at first use; the import
# itself is always safe
from .recordio import dump_reader, master_reader, recordio_reader  # noqa: F401
