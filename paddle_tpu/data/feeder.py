"""DataFeeder: host batches → device-ready feed dicts.

Reference: python/paddle/v2/fluid/data_feeder.py and
paddle/py_paddle/dataprovider_converter.py:25-125 (dense / index /
sequence scanners building Arguments). Here dense slots stack to arrays
and lod_level=1 slots build LoDArray with *bucketed* capacity so XLA
recompiles only when a batch overflows the current bucket (the TPU answer
to the reference's no-padding variable-length batches).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.lod import LoDArray
from ..core.program import Variable


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], bucket: int = 256,
                 max_seqs: int = None):
        self.feed_list = list(feed_list)
        self.bucket = bucket
        self.max_seqs = max_seqs

    def feed(self, batch: List[Sequence]) -> Dict[str, object]:
        """batch: list of samples, each a tuple aligned with feed_list."""
        out = {}
        for slot_idx, var in enumerate(self.feed_list):
            vals = [sample[slot_idx] for sample in batch]
            if var.lod_level == 0:
                arr = np.asarray(vals, dtype=np.dtype(var.dtype))
                want = tuple(d for d in var.shape if d != -1)
                if arr.ndim == 1 and want:
                    arr = arr.reshape((len(batch),) + want)
                out[var.name] = arr
            else:
                seqs = [
                    np.asarray(v, dtype=np.dtype(var.dtype)).reshape(
                        (-1,) + tuple(d for d in var.shape[1:] if d != -1)
                    )
                    for v in vals
                ]
                out[var.name] = LoDArray.from_sequences(
                    seqs,
                    bucket=self.bucket,
                    max_seqs=self.max_seqs or len(batch),
                )
        return out
