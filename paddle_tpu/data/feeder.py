"""DataFeeder: host batches → device-ready feed dicts.

Reference: python/paddle/v2/fluid/data_feeder.py and
paddle/py_paddle/dataprovider_converter.py:25-125 (dense / index /
sequence scanners building Arguments). Here dense slots stack to arrays
and lod_level=1 slots build LoDArray with *bucketed* capacity so XLA
recompiles only when a batch overflows the current bucket (the TPU answer
to the reference's no-padding variable-length batches).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.lod import LoDArray
from ..core.program import Variable
from ..core.sparse import SparseArray


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], bucket: int = 256,
                 max_seqs: int = None):
        self.feed_list = list(feed_list)
        self.bucket = bucket
        self.max_seqs = max_seqs

    def feed(self, batch: List[Sequence]) -> Dict[str, object]:
        """batch: list of samples, each a tuple aligned with feed_list."""
        out = {}
        for slot_idx, var in enumerate(self.feed_list):
            vals = [sample[slot_idx] for sample in batch]
            if getattr(var, "sparse_format", None):
                # sparse_binary/sparse_float slots (SparseBinaryScanner /
                # SparseFloatScanner parity): each sample is a list of
                # active indices, or of (index, value) pairs
                dim = int(var.shape[-1])
                out[var.name] = SparseArray.from_batch(
                    vals, dim=dim, format=var.sparse_format,
                    bucket=self.bucket, dtype=np.dtype(var.dtype),
                )
            elif var.lod_level == 0:
                arr = np.asarray(vals, dtype=np.dtype(var.dtype))
                want = tuple(d for d in var.shape if d != -1)
                if arr.ndim == 1 and want:
                    arr = arr.reshape((len(batch),) + want)
                out[var.name] = arr
            else:
                seqs = [
                    np.asarray(v, dtype=np.dtype(var.dtype)).reshape(
                        (-1,) + tuple(d for d in var.shape[1:] if d != -1)
                    )
                    for v in vals
                ]
                out[var.name] = LoDArray.from_sequences(
                    seqs,
                    bucket=self.bucket,
                    max_seqs=self.max_seqs or len(batch),
                )
        return out


class DevicePrefetcher:
    """Async double-buffered host→device pipeline.

    Reference: DataProvider's double-buffered async loading
    (gserver/dataproviders/DataProvider.h:292,328 — background thread at
    :375 fills a queue while the trainer consumes). TPU version: a daemon
    thread walks the reader, converts batches (optionally through a
    DataFeeder) and jax.device_put's them `depth` batches ahead, so the
    h2d transfer of batch N+1 overlaps the device compute of batch N —
    the single biggest win when the host link is slow.

    Trainer.train runs its input through this by default
    (FLAGS.prefetch_to_device, depth 2) on executors that don't own
    input placement themselves; the committed arrays it yields then skip
    Executor.run's per-feed jnp.asarray normalization entirely.

    Usage::

        for feed in DevicePrefetcher(reader, feeder, depth=2):
            exe.run(prog, feed=feed, ...)
    """

    def __init__(self, reader, feeder=None, depth: int = 2, device=None):
        self.reader = reader
        self.feeder = feeder
        self.depth = max(1, int(depth))
        self.device = device

    def __iter__(self):
        import queue as _queue
        import threading

        import jax

        q: "_queue.Queue" = _queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        END, ERR = object(), object()

        def put(v):
            # an array already on the target device must pass through:
            # re-putting a committed device array round-trips its bytes
            # through the host (on the tunneled platform that is ~0.7 s
            # for a ResNet batch — measured via BENCH_OVERLAP before this
            # guard existed)
            # device=None means "the effective default device" — resolve it
            # so an array committed to a DIFFERENT local device still gets
            # placed (jax.device_put(x, None) is the identity for committed
            # arrays). Resolution handles a string jax_default_device and
            # stays process-local; multi-device (sharded) arrays pass
            # through untouched — re-placing them would gather.
            target = self.device
            if target is None:
                target = jax.config.jax_default_device
                if isinstance(target, str):
                    target = jax.local_devices(backend=target)[0]
                elif target is None:
                    target = jax.local_devices()[0]
            if isinstance(v, jax.Array) and (
                len(v.devices()) > 1 or v.devices() == {target}
            ):
                return v
            return jax.device_put(v, target)

        def produce():
            try:
                for batch in self.reader():
                    if stop.is_set():
                        return
                    feed = self.feeder.feed(batch) if self.feeder else batch
                    feed = {
                        k: jax.tree.map(put, v) for k, v in feed.items()
                    }
                    q.put(feed)
                q.put(END)
            except BaseException as e:  # surface reader errors to consumer
                q.put((ERR, e))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is END:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                    raise item[1]
                yield item
        finally:
            stop.set()
            # drain so a blocked producer can observe stop and exit
            while not q.empty():
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
