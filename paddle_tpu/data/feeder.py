"""DataFeeder: host batches → device-ready feed dicts.

Reference: python/paddle/v2/fluid/data_feeder.py and
paddle/py_paddle/dataprovider_converter.py:25-125 (dense / index /
sequence scanners building Arguments). Here dense slots stack to arrays
and lod_level=1 slots build LoDArray with *bucketed* capacity so XLA
recompiles only when a batch overflows the current bucket (the TPU answer
to the reference's no-padding variable-length batches).
"""

from __future__ import annotations

from typing import Dict, List, Sequence

import numpy as np

from ..core.lod import LoDArray
from ..core.program import Variable
from ..core.sparse import SparseArray
from ..obs import trace as obs_trace


class DataFeeder:
    def __init__(self, feed_list: Sequence[Variable], bucket: int = 256,
                 max_seqs: int = None):
        self.feed_list = list(feed_list)
        self.bucket = bucket
        self.max_seqs = max_seqs

    def feed(self, batch: List[Sequence]) -> Dict[str, object]:
        """batch: list of samples, each a tuple aligned with feed_list."""
        out = {}
        for slot_idx, var in enumerate(self.feed_list):
            vals = [sample[slot_idx] for sample in batch]
            if getattr(var, "sparse_format", None):
                # sparse_binary/sparse_float slots (SparseBinaryScanner /
                # SparseFloatScanner parity): each sample is a list of
                # active indices, or of (index, value) pairs
                dim = int(var.shape[-1])
                out[var.name] = SparseArray.from_batch(
                    vals, dim=dim, format=var.sparse_format,
                    bucket=self.bucket, dtype=np.dtype(var.dtype),
                )
            elif var.lod_level == 0:
                arr = np.asarray(vals, dtype=np.dtype(var.dtype))
                want = tuple(d for d in var.shape if d != -1)
                if arr.ndim == 1 and want:
                    arr = arr.reshape((len(batch),) + want)
                out[var.name] = arr
            else:
                seqs = [
                    np.asarray(v, dtype=np.dtype(var.dtype)).reshape(
                        (-1,) + tuple(d for d in var.shape[1:] if d != -1)
                    )
                    for v in vals
                ]
                out[var.name] = LoDArray.from_sequences(
                    seqs,
                    bucket=self.bucket,
                    max_seqs=self.max_seqs or len(batch),
                )
        return out


class FeedWindow:
    """K device-committed batches stacked along a leading window axis —
    the unit the windowed (lax.scan) training loop dispatches. `k` may be
    short of the configured window for the ragged tail of a pass (or a
    feed-signature change mid-stream); Executor.run_window compiles one
    extra program per distinct k, which the jit cache absorbs."""

    __slots__ = ("feed", "k")

    def __init__(self, feed, k: int):
        self.feed = feed
        self.k = int(k)

    def slice(self, i: int):
        """One step's feed as a window of 1 (keeps the leading axis) —
        the guard-hot fallback runs these for step-granular recovery."""
        import jax

        return {
            name: jax.tree_util.tree_map(lambda a: a[i:i + 1], v)
            for name, v in self.feed.items()
        }


def _stack_feeds(feeds):
    """Stack K same-signature feed dicts to a leading window axis. The
    leaves are already device-committed, so the stack itself is one
    dispatched device op (issued from the prefetch thread — it overlaps
    the training window in flight)."""
    import jax
    import jax.numpy as jnp

    stacked = {
        name: jax.tree_util.tree_map(
            lambda *xs: jnp.stack(xs), *(f[name] for f in feeds))
        for name in feeds[0]
    }
    return FeedWindow(stacked, len(feeds))


class DevicePrefetcher:
    """Async double-buffered host→device pipeline.

    Reference: DataProvider's double-buffered async loading
    (gserver/dataproviders/DataProvider.h:292,328 — background thread at
    :375 fills a queue while the trainer consumes). TPU version: a daemon
    thread walks the reader, converts batches (optionally through a
    DataFeeder) and jax.device_put's them `depth` batches ahead, so the
    h2d transfer of batch N+1 overlaps the device compute of batch N —
    the single biggest win when the host link is slow.

    Trainer.train runs its input through this by default
    (FLAGS.prefetch_to_device, depth 2) on executors that don't own
    input placement themselves; the committed arrays it yields then skip
    Executor.run's per-feed jnp.asarray normalization entirely.

    Usage::

        for feed in DevicePrefetcher(reader, feeder, depth=2):
            exe.run(prog, feed=feed, ...)
    """

    window = 0  # see __init__

    def __init__(self, reader, feeder=None, depth: int = 2, device=None,
                 window: int = 0):
        self.reader = reader
        self.feeder = feeder
        self.depth = max(1, int(depth))
        self.device = device
        # window > 0: group consecutive same-signature batches and yield
        # FeedWindow objects of up to `window` stacked batches instead of
        # single feed dicts (the scan-window trainer path). depth then
        # counts windows, so the effective prefetch depth in batches is
        # depth*window >= window — the "auto-raised to >= K" guarantee.
        # A signature change (e.g. a LoD bucket overflow) or the end of
        # the pass flushes a partial window.
        self.window = max(0, int(window))

    def __iter__(self):
        import queue as _queue
        import threading

        import jax

        q: "_queue.Queue" = _queue.Queue(maxsize=self.depth)
        stop = threading.Event()
        END, ERR = object(), object()

        def put(v):
            # an array already on the target device must pass through:
            # re-putting a committed device array round-trips its bytes
            # through the host (on the tunneled platform that is ~0.7 s
            # for a ResNet batch — measured via BENCH_OVERLAP before this
            # guard existed)
            # device=None means "the effective default device" — resolve it
            # so an array committed to a DIFFERENT local device still gets
            # placed (jax.device_put(x, None) is the identity for committed
            # arrays). Resolution handles a string jax_default_device and
            # stays process-local; multi-device (sharded) arrays pass
            # through untouched — re-placing them would gather.
            target = self.device
            if target is None:
                target = jax.config.jax_default_device
                if isinstance(target, str):
                    target = jax.local_devices(backend=target)[0]
                elif target is None:
                    target = jax.local_devices()[0]
            if isinstance(v, jax.Array) and (
                len(v.devices()) > 1 or v.devices() == {target}
            ):
                return v
            return jax.device_put(v, target)

        def produce():
            from ..core.executor import _feed_signature

            buf, sig = [], None
            try:
                for i, batch in enumerate(self.reader()):
                    if stop.is_set():
                        return
                    # producer-thread span: the batch index here is the
                    # SAME index the trainer's BeginIteration/step spans
                    # carry, so prefetch→enqueue latency reads straight
                    # off the exported timeline (disarmed: one bool test,
                    # zero allocations — the obs lint enforces the guard)
                    armed = obs_trace._armed
                    if armed:
                        obs_trace.set_context(batch=i)
                        obs_trace._begin("prefetch.batch", "prefetch")
                    feed = self.feeder.feed(batch) if self.feeder else batch
                    feed = {
                        k: jax.tree.map(put, v) for k, v in feed.items()
                    }
                    if armed:
                        obs_trace._end()
                    if not self.window:
                        q.put(feed)
                        continue
                    s = _feed_signature(feed)
                    if buf and s != sig:
                        # shape change mid-stream: flush the partial
                        # window so every window stays one compiled shape
                        if armed:
                            obs_trace._begin("prefetch.window", "prefetch")
                        q.put(_stack_feeds(buf))
                        if armed:
                            obs_trace._end()
                        buf = []
                    sig = s
                    buf.append(feed)
                    if len(buf) == self.window:
                        if armed:
                            obs_trace._begin("prefetch.window", "prefetch")
                        q.put(_stack_feeds(buf))
                        if armed:
                            obs_trace._end()
                        buf = []
                if buf:  # ragged tail window at pass end
                    q.put(_stack_feeds(buf))
                q.put(END)
            except BaseException as e:  # surface reader errors to consumer
                q.put((ERR, e))

        t = threading.Thread(target=produce, daemon=True)
        t.start()
        try:
            while True:
                item = q.get()
                if item is END:
                    return
                if isinstance(item, tuple) and len(item) == 2 and item[0] is ERR:
                    raise item[1]
                yield item
        finally:
            stop.set()
            # drain so a blocked producer can observe stop and exit
            while not q.empty():
                try:
                    q.get_nowait()
                except _queue.Empty:
                    break
