"""Structured span tracing: bounded per-thread rings, Chrome trace export.

The reference framework's profiling surface was Gen-1's REGISTER_TIMER
RAII macros (utils/Stat.h) and Fluid's push/pop profiler ranges — both
answer "how much time, cumulatively" but neither can answer the
questions the concurrent rebuild raises: *where did this request's
first-token latency go* across the admission queue, the prefix run and
the shared decode pool, or *why did this window's hostSync stall* while
the prefetcher and the checkpoint writer were doing what. Those need a
timeline, not a table.

Design (the `resilience.faults` contract applied to tracing):

- Disarmed (the default), every hook returns after ONE module-global
  boolean test — no allocation, no clock read, nothing observable on
  the step path. A lint test (tests/test_obs.py) enforces that call
  sites on hot loops guard kwargs-building work behind `_armed`.
- Armed (`PT_FLAGS_TRACE=<out.json>`, CLI `--trace_out`, or the scoped
  `obs.tracing()` context), spans record into BOUNDED per-thread ring
  buffers (no cross-thread contention on the record path; overflow
  drops the OLDEST events and counts them — `dropped_total()`, exported
  as the `pt_trace_dropped_total` counter — never silent truncation).
- Timestamps come from one monotonic clock (`time.perf_counter`), so
  spans across threads order correctly in the exported timeline.
- Correlation travels as a per-thread *trace context* (a plain dict):
  `set_context(step=..)` / `context(request_id=..)` attach ids that
  every subsequent span on that thread records as args. Thread
  hand-offs copy it explicitly — `get_context()` on the producer,
  `set_context(**ctx)` on the consumer — which is how request_id flows
  queue→admission→pool-step→stream and step/window ids flow
  prefetch→enqueue→hostSync→checkpoint.
- Export is Chrome trace-event JSON (one "X" complete event per span,
  "i" instants, "C" counter tracks, "M" thread-name metadata): open it
  in Perfetto / chrome://tracing. `tracing(xprof_dir=...)` brackets the
  capture inside the existing `profiler.profiler()` XProf trace so host
  spans and device kernels cover the same interval.

`profiler.StatSet.timer` integrates: while tracing is armed every timer
block (forwardBackward, hostSync, checkpointSnapshot, the serving
predict timers) also records a span, so the span vocabulary is the
timer vocabulary plus the explicitly instrumented request/pool events.
"""

from __future__ import annotations

import atexit
import collections
import contextlib
import itertools
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

from ..flags import FLAGS, define_flag

__all__ = [
    "Trace",
    "arm",
    "armed",
    "context",
    "counter",
    "disarm",
    "dropped_total",
    "get_context",
    "instant",
    "new_request_id",
    "set_context",
    "span",
    "tracing",
    "validate_chrome_trace",
]

define_flag("trace", "",
            "arm structured span tracing and export a Chrome trace-event "
            "JSON (Perfetto / chrome://tracing) to this path at process "
            "exit (env: PT_FLAGS_TRACE; CLI: --trace_out; scoped "
            "captures: paddle_tpu.obs.tracing()). Empty = tracing "
            "disarmed and every trace hook a single-boolean-test no-op")
define_flag("trace_ring", 65536,
            "per-thread trace ring capacity in events; overflow drops "
            "the oldest events and counts them in pt_trace_dropped_total")

# the fast-path gate, exactly like resilience.faults._armed: when False
# every public hook returns after one module-global boolean test
_armed = False
_trace: Optional["Trace"] = None
_lock = threading.Lock()
_dropped_closed = 0  # drops accumulated by finished capture sessions
_req_ids = itertools.count(1)


class _NullSpan:
    """Singleton no-op context manager returned by span() while
    disarmed — no per-call allocation on the disarmed path."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL = _NullSpan()


class _ThreadBuf:
    """One thread's ring: events, open-span stack, and trace context.

    Single-writer by construction (only its own thread appends), so the
    record path is lock-free; the exporter snapshots under the trace
    lock after the run quiesces."""

    __slots__ = ("tid", "name", "events", "stack", "ctx", "dropped")

    def __init__(self, ring: int):
        t = threading.current_thread()
        self.tid = t.ident or 0
        self.name = t.name
        self.events: collections.deque = collections.deque(maxlen=ring)
        self.stack: List[tuple] = []  # open spans: (name, cat, t0, args)
        self.ctx: Dict[str, Any] = {}
        self.dropped = 0

    def push(self, ev: tuple) -> None:
        if len(self.events) == self.events.maxlen:
            self.dropped += 1  # deque drops the oldest on append
        self.events.append(ev)


class _Span:
    __slots__ = ("_name", "_cat", "_args")

    def __init__(self, name, cat, args):
        self._name = name
        self._cat = cat
        self._args = args

    def __enter__(self):
        _begin(self._name, self._cat, self._args)
        return self

    def __exit__(self, *exc):
        _end()
        return False


class Trace:
    """One capture session: per-thread rings + the export machinery."""

    def __init__(self, ring_size: Optional[int] = None):
        self.ring_size = int(ring_size or FLAGS.trace_ring)
        if self.ring_size < 1:
            raise ValueError(f"ring_size must be >= 1, got {self.ring_size}")
        self.t0 = time.perf_counter()
        self._tls = threading.local()
        self._bufs: List[_ThreadBuf] = []
        self._bufs_lock = threading.Lock()

    # -- record side (called via the module-level hooks) ----------------
    def buf(self) -> _ThreadBuf:
        b = getattr(self._tls, "buf", None)
        if b is None:
            b = _ThreadBuf(self.ring_size)
            self._tls.buf = b
            with self._bufs_lock:
                self._bufs.append(b)
        return b

    # -- accounting -----------------------------------------------------
    def dropped_total(self) -> int:
        with self._bufs_lock:
            return sum(b.dropped for b in self._bufs)

    def event_count(self) -> int:
        with self._bufs_lock:
            return sum(len(b.events) for b in self._bufs)

    # -- export ---------------------------------------------------------
    def to_chrome(self) -> Dict[str, Any]:
        """Chrome trace-event JSON object (the "JSON Object Format":
        {"traceEvents": [...]}). Open spans on any thread are closed at
        export time so a mid-run snapshot still validates."""
        pid = os.getpid()
        now = time.perf_counter()
        events: List[Dict[str, Any]] = []
        with self._bufs_lock:
            bufs = list(self._bufs)
        for b in bufs:
            events.append({
                "ph": "M", "name": "thread_name", "pid": pid,
                "tid": b.tid, "args": {"name": b.name},
            })
            for ev in list(b.events):
                events.append(self._event_json(ev, pid, b.tid))
            # spans still open (e.g. export inside the traced region):
            # close them at "now" so the JSON stays schema-valid
            for name, cat, t0, args in b.stack:
                events.append(self._event_json(
                    ("X", name, cat, t0, now - t0, dict(b.ctx, **(args or {}))),
                    pid, b.tid))
        return {
            "traceEvents": events,
            "displayTimeUnit": "ms",
            "otherData": {"dropped_events": self.dropped_total()},
        }

    def _event_json(self, ev: tuple, pid: int, tid: int) -> Dict[str, Any]:
        ph = ev[0]
        us = 1e6
        if ph == "X":
            _, name, cat, t0, dur, args = ev
            out = {"ph": "X", "name": name, "cat": cat, "pid": pid,
                   "tid": tid, "ts": (t0 - self.t0) * us,
                   "dur": max(0.0, dur) * us}
            if args:
                out["args"] = args
            return out
        if ph == "i":
            _, name, cat, t, args = ev
            out = {"ph": "i", "name": name, "cat": cat, "pid": pid,
                   "tid": tid, "ts": (t - self.t0) * us, "s": "t"}
            if args:
                out["args"] = args
            return out
        # counter track
        _, name, t, value = ev
        return {"ph": "C", "name": name, "pid": pid, "tid": tid,
                "ts": (t - self.t0) * us, "args": {"value": value}}

    def export(self, path: str) -> str:
        """Write the Chrome trace JSON; returns the path."""
        doc = self.to_chrome()
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(doc, f)
        os.replace(tmp, path)
        return path


# -- module-level hooks (the instrumented call sites) -----------------------

def armed() -> bool:
    return _armed


def arm(out: Optional[str] = None,
        ring_size: Optional[int] = None) -> Trace:
    """Start a capture session (idempotent while one is active). `out`
    only records the default export path used by disarm()/atexit."""
    global _armed, _trace
    with _lock:
        if _trace is None:
            _trace = Trace(ring_size=ring_size)
            _trace.out = out  # type: ignore[attr-defined]
            _armed = True
        elif out:
            _trace.out = out  # type: ignore[attr-defined]
        return _trace


def disarm(export: bool = True) -> Optional[Trace]:
    """End the capture session; export to its recorded path (if any)
    and return the Trace for programmatic inspection."""
    global _armed, _trace, _dropped_closed
    with _lock:
        tr, _trace = _trace, None
        _armed = False
    if tr is not None:
        _dropped_closed += tr.dropped_total()
        out = getattr(tr, "out", None)
        if export and out:
            tr.export(out)
    return tr


def dropped_total() -> int:
    """Events dropped to ring overflow, across all capture sessions of
    this process (monotonic; the pt_trace_dropped_total counter)."""
    tr = _trace
    return _dropped_closed + (tr.dropped_total() if tr is not None else 0)


@contextlib.contextmanager
def tracing(out: Optional[str] = None, ring_size: Optional[int] = None,
            xprof_dir: Optional[str] = None):
    """Scoped capture: arm, yield the Trace, export+disarm on exit.

    xprof_dir brackets the capture in the existing profiler.profiler()
    XProf trace, so host spans and device kernels are captured over the
    same interval (correlate the two timelines by wall offset)."""
    tr = arm(out=out, ring_size=ring_size)
    stack = contextlib.ExitStack()
    if xprof_dir:
        from .. import profiler as _profiler

        stack.enter_context(_profiler.profiler(xprof_dir))
    try:
        with stack:
            yield tr
    finally:
        disarm(export=True)


def _begin(name: str, cat: str = "host",
           args: Optional[Dict[str, Any]] = None) -> None:
    tr = _trace
    if tr is None:
        return
    tr.buf().stack.append((name, cat, time.perf_counter(), args))


def _end() -> None:
    tr = _trace
    if tr is None:
        return
    b = tr.buf()
    if not b.stack:
        return  # span begun before arm / ended twice: drop, don't crash
    name, cat, t0, args = b.stack.pop()
    t1 = time.perf_counter()
    merged = dict(b.ctx)
    if args:
        merged.update(args)
    b.push(("X", name, cat, t0, t1 - t0, merged or None))


def span(name: str, cat: str = "host", **args):
    """Context manager recording one span. Disarmed: returns the no-op
    singleton. (Building `args` still costs a dict at the call site —
    hot loops must guard with `if trace.armed():`, see the lint test.)"""
    if not _armed:
        return _NULL
    return _Span(name, cat, args)


def instant(name: str, cat: str = "host", **args) -> None:
    """Point event (phase "i")."""
    if not _armed:
        return
    tr = _trace
    if tr is None:
        return
    b = tr.buf()
    merged = dict(b.ctx)
    if args:
        merged.update(args)
    b.push(("i", name, cat, time.perf_counter(), merged or None))


def counter(name: str, value: float) -> None:
    """Counter-track sample (phase "C"): queue depth, slot occupancy."""
    if not _armed:
        return
    tr = _trace
    if tr is None:
        return
    tr.buf().push(("C", name, time.perf_counter(), float(value)))


def set_context(**ids: Any) -> None:
    """Merge correlation ids into this thread's trace context; every
    subsequent span/instant on this thread records them as args.
    A None value removes the key."""
    if not _armed:
        return
    tr = _trace
    if tr is None:
        return
    ctx = tr.buf().ctx
    for k, v in ids.items():
        if v is None:
            ctx.pop(k, None)
        else:
            ctx[k] = v


def get_context() -> Dict[str, Any]:
    """Snapshot of this thread's trace context (for explicit hand-off
    to another thread); {} while disarmed."""
    if not _armed:
        return {}
    tr = _trace
    if tr is None:
        return {}
    return dict(tr.buf().ctx)


@contextlib.contextmanager
def context(**ids: Any):
    """Scoped set_context: sets ids on entry, restores the previous
    values on exit (worker loops that serve many requests)."""
    if not _armed:
        yield
        return
    tr = _trace
    if tr is None:
        yield
        return
    ctx = tr.buf().ctx
    saved = {k: ctx.get(k, _MISSING) for k in ids}
    set_context(**ids)
    try:
        yield
    finally:
        buf_ctx = tr.buf().ctx
        for k, v in saved.items():
            if v is _MISSING:
                buf_ctx.pop(k, None)
            else:
                buf_ctx[k] = v


_MISSING = object()


def new_request_id(prefix: str = "req") -> str:
    """Process-unique request id ("req-17"): assigned at admission so
    every span a request touches — across threads — carries one key."""
    return f"{prefix}-{next(_req_ids)}"


# -- schema ------------------------------------------------------------------

_PHASES = {"X", "i", "C", "M"}


def validate_chrome_trace(doc: Any) -> List[str]:
    """Validate a loaded Chrome trace-event JSON object against the
    subset of the trace-event format this exporter emits. Returns a
    list of problems (empty = valid). Used by the test suite's
    schema check and by `tracing()` consumers that want a cheap
    sanity gate before shipping a trace somewhere."""
    problems: List[str] = []
    if not isinstance(doc, dict) or "traceEvents" not in doc:
        return ["top level must be an object with a traceEvents list"]
    evs = doc["traceEvents"]
    if not isinstance(evs, list):
        return ["traceEvents must be a list"]
    for i, ev in enumerate(evs):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            problems.append(f"{where}: not an object")
            continue
        ph = ev.get("ph")
        if ph not in _PHASES:
            problems.append(f"{where}: bad ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"{where}: missing name")
        if not isinstance(ev.get("pid"), int) \
                or not isinstance(ev.get("tid"), int):
            problems.append(f"{where}: pid/tid must be ints")
        if ph != "M":
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or ts < 0:
                problems.append(f"{where}: bad ts {ts!r}")
        if ph == "X":
            dur = ev.get("dur")
            if not isinstance(dur, (int, float)) or dur < 0:
                problems.append(f"{where}: bad dur {dur!r}")
        if "args" in ev and not isinstance(ev["args"], dict):
            problems.append(f"{where}: args must be an object")
    return problems


# -- env-seeded arming (subprocesses traced from birth, like faults) --------

if FLAGS.trace:
    arm(out=FLAGS.trace)
    atexit.register(lambda: disarm(export=True))
