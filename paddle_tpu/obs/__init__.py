"""paddle_tpu.obs: run-wide observability.

The layer every scale-out PR instruments instead of growing one-off
counters (ISSUE 8). Three pieces:

- `trace`     — structured span tracing: bounded per-thread ring
                buffers, zero-cost disarmed (the resilience.faults
                contract), correlation ids propagated across thread
                hand-offs, Chrome trace-event JSON export for
                Perfetto / chrome://tracing, optional XProf bracketing
                so host spans and device kernels share an interval.
- `metrics`   — ONE process-wide MetricsRegistry unifying the global
                profiler.StatSet, trainer dispatch/sync/checkpoint/
                guard counters, fault-registry hit/fire counts, and
                the serving histograms/gauges behind one compliant
                Prometheus text renderer; serving `/metrics` is a view
                of it, training runs log/dump the same surface.
- `promparse` — a minimal Prometheus text parser: the smoke test that
                proves the renderer's output round-trips, and the
                `paddle_tpu stats` pretty-printer.

Quick start::

    from paddle_tpu import obs

    with obs.tracing("/tmp/run.trace.json"):
        trainer.train(...)            # spans land per thread
    # open the JSON in https://ui.perfetto.dev

    print(obs.registry().render())    # the unified Prometheus text
"""

from . import metrics  # noqa: F401
from . import promparse  # noqa: F401
from . import trace  # noqa: F401
from .metrics import MetricsRegistry, registry  # noqa: F401
from .trace import Trace, span, tracing, validate_chrome_trace  # noqa: F401

__all__ = [
    "MetricsRegistry",
    "Trace",
    "metrics",
    "promparse",
    "registry",
    "span",
    "trace",
    "tracing",
    "validate_chrome_trace",
]
