"""Minimal Prometheus text-exposition parser (the CI smoke-test half).

A renderer is only as trustworthy as something that parses it back:
this module is the consumer side of `obs.metrics` — a small, strict
parser for the text exposition format (version 0.0.4) used by the
tier-1 smoke test (scrape `/metrics` twice, assert every family parses
and every counter is monotonic) and by `paddle_tpu stats` to pretty-
print a scrape. Deliberately dependency-free and narrower than the
official client: exactly the grammar the unified renderer emits —
`# HELP`/`# TYPE` comments, optional `{label="value"}` sets with
escaped values, float samples including +Inf/-Inf/NaN.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

__all__ = ["Family", "ParseError", "parse_text"]

_TYPES = {"counter", "gauge", "histogram", "summary", "untyped"}


class ParseError(ValueError):
    """A line did not parse as Prometheus text exposition."""

    def __init__(self, lineno: int, line: str, why: str):
        super().__init__(f"line {lineno}: {why}: {line!r}")
        self.lineno = lineno
        self.line = line


class Family:
    """One metric family: its declared type/help plus every sample that
    belongs to it (for histograms that includes the `_bucket`/`_sum`/
    `_count` series)."""

    def __init__(self, name: str, type: str = "untyped", help: str = ""):
        self.name = name
        self.type = type
        self.help = help
        # [(sample_name, labels, value)]
        self.samples: List[Tuple[str, Dict[str, str], float]] = []

    def value(self, labels: Optional[Dict[str, str]] = None) -> float:
        """The single sample matching `labels` (exact match; {}/None for
        the unlabeled series). Raises KeyError when absent."""
        want = dict(labels or {})
        for name, lb, v in self.samples:
            if name == self.name and lb == want:
                return v
        raise KeyError(f"{self.name}{want}")

    def __repr__(self):
        return (f"Family({self.name!r}, type={self.type!r}, "
                f"samples={len(self.samples)})")


def _parse_value(tok: str, lineno: int, line: str) -> float:
    t = tok.strip()
    if t in ("+Inf", "Inf"):
        return float("inf")
    if t == "-Inf":
        return float("-inf")
    if t == "NaN":
        return float("nan")
    try:
        return float(t)
    except ValueError:
        raise ParseError(lineno, line, f"bad sample value {tok!r}") from None


def _parse_labels(body: str, lineno: int, line: str) -> Dict[str, str]:
    """body is the text between {{ and }} — label pairs with escaped
    values: name="va\\"lue",other="x"."""
    labels: Dict[str, str] = {}
    i, n = 0, len(body)
    while i < n:
        eq = body.find("=", i)
        if eq < 0:
            raise ParseError(lineno, line, "label without '='")
        name = body[i:eq].strip().lstrip(",").strip()
        if not name or not all(c.isalnum() or c == "_" for c in name):
            raise ParseError(lineno, line, f"bad label name {name!r}")
        if eq + 1 >= n or body[eq + 1] != '"':
            raise ParseError(lineno, line, "label value must be quoted")
        j = eq + 2
        out = []
        while j < n:
            c = body[j]
            if c == "\\":
                if j + 1 >= n:
                    raise ParseError(lineno, line, "dangling escape")
                nxt = body[j + 1]
                out.append({"\\": "\\", '"': '"', "n": "\n"}.get(nxt))
                if out[-1] is None:
                    raise ParseError(lineno, line,
                                     f"bad escape \\{nxt} in label value")
                j += 2
                continue
            if c == '"':
                break
            out.append(c)
            j += 1
        else:
            raise ParseError(lineno, line, "unterminated label value")
        labels[name] = "".join(out)
        i = j + 1
        if i < n and body[i] == ",":
            i += 1
    return labels


def _family_of(sample_name: str, declared: Dict[str, Family]) -> str:
    """Map a sample to its family: exact name, or the histogram series
    suffixes of a declared histogram family."""
    if sample_name in declared:
        return sample_name
    for suffix in ("_bucket", "_sum", "_count"):
        if sample_name.endswith(suffix):
            base = sample_name[: -len(suffix)]
            fam = declared.get(base)
            if fam is not None and fam.type in ("histogram", "summary"):
                return base
    return sample_name


def parse_text(text: str) -> Dict[str, Family]:
    """Parse one exposition into {family_name: Family}. Strict: any
    malformed line raises ParseError; a family re-declared with a
    DIFFERENT type raises too (duplicate TYPE lines are the renderer
    bug the smoke test exists to catch)."""
    families: Dict[str, Family] = {}

    def fam(name: str) -> Family:
        f = families.get(name)
        if f is None:
            f = families[name] = Family(name)
        return f

    for lineno, raw in enumerate(text.splitlines(), 1):
        line = raw.strip()
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 3 and parts[1] in ("HELP", "TYPE"):
                name = parts[2]
                if parts[1] == "TYPE":
                    typ = parts[3].strip() if len(parts) > 3 else ""
                    if typ not in _TYPES:
                        raise ParseError(lineno, raw,
                                         f"unknown metric type {typ!r}")
                    f = fam(name)
                    if f.type not in ("untyped", typ):
                        raise ParseError(
                            lineno, raw,
                            f"family {name} re-declared as {typ} "
                            f"(was {f.type})")
                    f.type = typ
                else:
                    fam(name).help = parts[3] if len(parts) > 3 else ""
            continue  # other comments are legal and ignored
        # sample line: name[{labels}] value
        brace = line.find("{")
        if brace >= 0:
            close = line.rfind("}")
            if close < brace:
                raise ParseError(lineno, raw, "unbalanced braces")
            name = line[:brace].strip()
            labels = _parse_labels(line[brace + 1:close], lineno, raw)
            rest = line[close + 1:]
        else:
            toks = line.split(None, 1)
            if len(toks) != 2:
                raise ParseError(lineno, raw, "sample without value")
            name, rest = toks
            labels = {}
        if not name or not (name[0].isalpha() or name[0] == "_"):
            raise ParseError(lineno, raw, f"bad metric name {name!r}")
        if not all(c.isalnum() or c in "_:" for c in name):
            raise ParseError(lineno, raw, f"bad metric name {name!r}")
        value = _parse_value(rest, lineno, raw)
        fname = _family_of(name, families)
        families.setdefault(fname, Family(fname)).samples.append(
            (name, labels, value))
    return families
