"""One process-wide metrics registry behind one Prometheus renderer.

Before this module the rebuild's accounting was fragmented exactly the
way the reference's never was (Gen-1 had ONE global StatSet table):
serving histograms lived in `serving/metrics.py`, the trainer counted
dispatches/syncs on itself, the checkpoint writer and StepGuard counted
privately, and the fault registry kept its own hit/fire dict — four
surfaces, one of them scrapeable. This registry unifies them:

- histograms / counters / gauges live in ONE process-wide store
  (`registry()`); the serving `MetricSet` is now a namespace *view*
  over it, so the HTTP `/metrics` endpoint scrapes the same families a
  training run logs and `paddle_tpu stats` dumps;
- external accounting joins at render time through collectors: the
  global `profiler.StatSet` timers (count/total/median), the fault
  registry's per-point hit/fire counts (labeled series), the active
  trace session's dropped-event counter;
- the renderer is Prometheus-text-format compliant: `# HELP`/`# TYPE`
  exactly once per family, label values escaped, and components
  pre-register (declare) their counters so scrapers never see a
  missing series before the first request.

Thread-safe throughout (HTTP scrape threads vs batcher/scheduler/
trainer writers); no JAX anywhere in this module.
"""

from __future__ import annotations

import threading
from typing import (Any, Callable, Dict, List, Optional, Sequence,
                    Tuple)

__all__ = [
    "Histogram",
    "MetricsRegistry",
    "registry",
]

# seconds; spans sub-ms CPU fc models to multi-second cold compiles
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

_LabelKey = Tuple[Tuple[str, str], ...]


def _fmt(v: float) -> str:
    # prometheus floats: integral values without the trailing .0 noise
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)


def _escape_label(v: Any) -> str:
    """Prometheus text-format label-value escaping: backslash, double
    quote, and newline must be escaped or the exposition is unparsable."""
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _label_key(labels: Optional[Dict[str, Any]]) -> _LabelKey:
    if not labels:
        return ()
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _render_labels(key: _LabelKey) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"' for k, v in key)
    return "{" + inner + "}"


class Histogram:
    """Cumulative-bucket histogram (Prometheus `histogram` type).

    Quantiles are estimated from the bucket counts (each returns the
    upper bound of the bucket containing the quantile — the standard
    `histogram_quantile` resolution, good enough for p50/p95/p99
    dashboards without keeping samples)."""

    def __init__(self, name: str, buckets: Sequence[float],
                 help: str = ""):
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding quantile q in [0, 1];
        0.0 when empty, the largest finite bound for the +Inf bucket."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            cum = 0
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                if cum >= target:
                    return b
            return self.bounds[-1] if self.bounds else 0.0

    def render(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            cum = 0
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                lines.append(f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}')
            cum += self.counts[-1]
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{self.name}_sum {_fmt(self.sum)}")
            lines.append(f"{self.name}_count {self.count}")
        # convenience quantile gauges so dashboards don't need
        # histogram_quantile(); same data, pre-reduced
        for q, label in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
            lines.append(f"# TYPE {self.name}_{label} gauge")
            lines.append(f"{self.name}_{label} {_fmt(self.percentile(q))}")
        return lines


# a collector contributes families at render time:
#   () -> [(family_name, type, help, [(labels_dict_or_None, value)])]
_Collector = Callable[[], List[Tuple[str, str, str,
                                     List[Tuple[Optional[Dict], float]]]]]


class MetricsRegistry:
    """Histograms, counters (optionally labeled), gauge callables, stat
    sets, and render-time collectors behind one compliant renderer.

    Names here are FULL metric names — namespacing is the caller's job
    (the serving `MetricSet` view prepends its `ptserving_` prefix;
    runtime families use `pt_`)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._histograms: Dict[str, Histogram] = {}
        # family -> labelkey -> value; () is the unlabeled series
        self._counters: Dict[str, Dict[_LabelKey, float]] = {}
        self._help: Dict[str, str] = {}
        self._gauges: Dict[str, Tuple[Callable[[], Any], str]] = {}
        self._stat_sets: List[Tuple[str, Any]] = []  # (prefix, StatSet)
        self._collectors: List[_Collector] = []

    # -- registration ---------------------------------------------------
    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  help: str = "") -> Histogram:
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name, buckets, help)
            return h

    def declare_counter(self, name: str, help: str = "",
                        labels: Optional[Dict[str, Any]] = None) -> None:
        """Pre-register a counter at 0 so the series exists on the very
        first scrape (components declare their counters at construction
        — a scraper must never see a family appear mid-flight)."""
        key = _label_key(labels)
        with self._lock:
            fam = self._counters.setdefault(name, {})
            fam.setdefault(key, 0.0)
            if help:
                self._help.setdefault(name, help)

    def counter_inc(self, name: str, by: float = 1.0, help: str = "",
                    labels: Optional[Dict[str, Any]] = None) -> None:
        key = _label_key(labels)
        with self._lock:
            fam = self._counters.setdefault(name, {})
            fam[key] = fam.get(key, 0.0) + by
            if help:
                self._help.setdefault(name, help)

    def counter_value(self, name: str,
                      labels: Optional[Dict[str, Any]] = None) -> float:
        with self._lock:
            return self._counters.get(name, {}).get(_label_key(labels), 0.0)

    def gauge(self, name: str, fn: Callable[[], Any],
              help: str = "") -> None:
        """Gauges are callables evaluated at scrape time — the
        instrumented component owns the value, the registry only reads
        it. Registering an existing name replaces it (a rebuilt trainer
        or engine takes the series over). A callable returning None
        skips the series for that scrape (e.g. a dead weakref)."""
        with self._lock:
            self._gauges[name] = (fn, help)

    def attach_stat_set(self, stat_set, prefix: str = "pt_timer_") -> None:
        """Render a profiler.StatSet's timers as counter pairs
        `<prefix><name>_seconds_total` / `<prefix><name>_count` (plus a
        `_seconds_median` gauge when the set retains samples)."""
        with self._lock:
            for p, s in self._stat_sets:
                if p == prefix and s is stat_set:
                    return
            self._stat_sets.append((prefix, stat_set))

    def add_collector(self, fn: _Collector) -> None:
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def remove_collector(self, fn: _Collector) -> None:
        """Detach a render-time collector (a closed Router removes its
        fleet families so a long-lived process doesn't scrape ghosts)."""
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def remove_series(self, name: str,
                      labels: Optional[Dict[str, Any]] = None) -> bool:
        """Drop ONE labeled series from a counter family (the family
        stays as long as any series remains). This is the retirement
        half of the per-entity counter lifecycle: a replica that is
        deliberately scaled down or rolled away takes its
        `{replica="..."}` series with it, so a long-lived router's
        scrape surface tracks the live fleet instead of accreting dead
        series forever. Counters for FAILED replicas are kept by their
        owners (failure history is evidence; see Router.remove_replica).
        Returns True when the series existed."""
        key = _label_key(labels)
        with self._lock:
            fam = self._counters.get(name)
            if fam is None or key not in fam:
                return False
            del fam[key]
            if not fam:
                del self._counters[name]
                self._help.pop(name, None)
            return True

    def reset_metrics(self) -> None:
        """Drop all registered series (test isolation via pt.reset());
        collectors stay — they read external module state that owns its
        own reset story (faults.reset, trace.disarm)."""
        with self._lock:
            self._histograms.clear()
            self._counters.clear()
            self._help.clear()
            self._gauges.clear()
            self._stat_sets.clear()

    # -- export ---------------------------------------------------------
    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            hists = list(self._histograms.values())
            counters = sorted((n, dict(series))
                              for n, series in self._counters.items())
            helps = dict(self._help)
            gauges = sorted(self._gauges.items())
            stat_sets = list(self._stat_sets)
            collectors = list(self._collectors)
        for h in hists:
            lines.extend(h.render())
        for name, series in counters:
            self._family(lines, name, "counter", helps.get(name, ""),
                         [(k, v) for k, v in sorted(series.items())])
        for name, (fn, help) in gauges:
            try:
                v = fn()
            except Exception:
                v = float("nan")
            if v is None:
                continue  # dead source: skip the series this scrape
            self._family(lines, name, "gauge", help, [((), float(v))])
        for prefix, ss in stat_sets:
            lines.extend(self._render_stat_set(prefix, ss))
        for coll in collectors:
            try:
                fams = coll()
            except Exception:
                continue  # a broken collector must not break the scrape
            for name, typ, help, samples in fams:
                self._family(
                    lines, name, typ, help,
                    [(_label_key(lb), float(v)) for lb, v in samples])
        return "\n".join(lines) + "\n"

    @staticmethod
    def _family(lines: List[str], name: str, typ: str, help: str,
                samples: List[Tuple[_LabelKey, float]]) -> None:
        """One family: HELP/TYPE exactly once, then every series."""
        if help:
            lines.append(f"# HELP {name} {help}")
        lines.append(f"# TYPE {name} {typ}")
        for key, v in samples:
            lines.append(f"{name}{_render_labels(key)} {_fmt(v)}")

    def _render_stat_set(self, prefix: str, ss) -> List[str]:
        lines: List[str] = []
        for name, s in sorted(ss.as_dict().items()):
            metric = f"{prefix}{_sanitize(name)}"
            self._family(lines, f"{metric}_seconds_total", "counter", "",
                         [((), s["total"])])
            self._family(lines, f"{metric}_count", "counter", "",
                         [((), s["count"])])
            if "median" in s:
                self._family(lines, f"{metric}_seconds_median", "gauge",
                             "", [((), s["median"])])
        return lines


# -- the process-wide registry ----------------------------------------------

_REGISTRY = MetricsRegistry()


def registry() -> MetricsRegistry:
    """THE process-wide registry. Serving metric sets, the trainer's
    counters, and the runtime collectors all land here; /metrics, the
    periodic training stats line, and `paddle_tpu stats` render it."""
    return _REGISTRY


# -- built-in runtime collectors --------------------------------------------

def _faults_families():
    import sys

    faults = sys.modules.get("paddle_tpu.resilience.faults")
    if faults is None:
        return []
    st = faults.stats()
    if not st:
        return []
    return [
        ("pt_fault_hits_total", "counter",
         "fault-point hits (resilience.faults)",
         [({"point": p}, d["hits"]) for p, d in st.items()]),
        ("pt_fault_fired_total", "counter",
         "fault-point triggers (resilience.faults)",
         [({"point": p}, d["fired"]) for p, d in st.items()]),
    ]


def _trace_families():
    from . import trace

    return [
        ("pt_trace_dropped_total", "counter",
         "trace events dropped to ring-buffer overflow (obs.trace)",
         [(None, trace.dropped_total())]),
        ("pt_trace_armed", "gauge",
         "1 while a span-tracing capture session is active",
         [(None, 1.0 if trace.armed() else 0.0)]),
    ]


def _tune_families():
    """Tuned-coverage of the live process: per-source consult counts
    from the autotuner's one lookup point (tune/overrides.py). Every
    source label renders from the first scrape (0 included), so
    `paddle_tpu stats` on a fresh process already shows the full
    forced/env/table/interpolated/analytic surface — the ratio of
    table+interpolated to analytic IS the tuned-coverage number."""
    import sys

    overrides = sys.modules.get("paddle_tpu.tune.overrides")
    if overrides is None:
        return []
    st = overrides.consult_stats()
    return [
        ("pt_tune_consults_total", "counter",
         "tuned-config consults by provenance (tune/overrides.lookup)",
         [({"source": s}, float(v)) for s, v in sorted(st.items())]),
    ]


def _quant_families():
    """The int8 serving fast path's footprint (paddle_tpu.quant): how
    many matmul sites run quantized, the weight bytes that stopped
    streaming per request, and the convert-time accuracy-check delta.
    Emits nothing until the process converts or loads a quantized
    artifact — an fp-only process's scrape stays quant-silent."""
    import sys

    quant = sys.modules.get("paddle_tpu.quant")
    if quant is None:
        return []
    st = quant.stats()
    if not st:
        return []
    return [
        ("pt_quant_sites_quantized", "gauge",
         "matmul sites running the int8 quantized kernel (quant/)",
         [(None, float(st["sites_quantized"]))]),
        ("pt_quant_sites_skipped", "gauge",
         "candidate sites left at higher precision by the converter",
         [(None, float(st["sites_skipped"]))]),
        ("pt_quant_bytes_saved", "gauge",
         "weight bytes removed from the per-request HBM stream by int8 "
         "storage (vs the original parameter dtype)",
         [(None, float(st["bytes_saved"]))]),
        ("pt_quant_accuracy_delta", "gauge",
         "max |quantized - fp| output delta on the convert check feed",
         [(None, float(st["accuracy_delta"]))]),
    ]


def _statset_families():
    """The global StatSet rides the unified render even though it is
    not attach_stat_set'ed (reset_metrics would drop the attachment;
    the global table must always be scrapeable)."""
    import sys

    profiler = sys.modules.get("paddle_tpu.profiler")
    if profiler is None:
        return []
    out = []
    for name, s in sorted(profiler.global_stat_set().as_dict().items()):
        metric = f"pt_timer_{_sanitize(name)}"
        out.append((f"{metric}_seconds_total", "counter", "",
                    [(None, s["total"])]))
        out.append((f"{metric}_count", "counter", "", [(None, s["count"])]))
        if "median" in s:
            out.append((f"{metric}_seconds_median", "gauge", "",
                        [(None, s["median"])]))
    return out


_REGISTRY.add_collector(_faults_families)
_REGISTRY.add_collector(_trace_families)
_REGISTRY.add_collector(_tune_families)
_REGISTRY.add_collector(_quant_families)
_REGISTRY.add_collector(_statset_families)
