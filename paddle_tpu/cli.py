"""Command-line driver: `python -m paddle_tpu <command> ...`.

Reference: the `paddle` shell wrapper (paddle/scripts/submit_local.sh.in:6-7,
177-180 — `paddle train / merge_model / pserver2 ...`) and the trainer
binary's flag-driven main (paddle/trainer/TrainerMain.cpp:32). The
"config is a program" philosophy carries over: the --config argument is a
Python file that builds the model on the default programs and exposes

    def get_model() -> dict:
        return {
            "cost": <loss Variable>,
            "reader": <callable yielding batches>,
            "feed_order": [<data Variables>],        # optional if reader
                                                     # yields feed dicts
            "metrics": {"name": Variable, ...},      # optional
            "num_passes": int,                        # optional default 1
        }

Commands:
  train       --config M.py [--num_passes N] [--save_dir D]
              [--mesh dp2,pp2] [--microbatches M] [--pipeline_stages K]
              [flags...]
              --mesh trains over a device mesh (axes dp/mp/sp/pp). A
              pp axis — or --pipeline_stages K — selects the
              micro-batch pipeline executor (paddle_tpu/pipeline):
              the program is cut into K stages (stage_boundary()
              markers or auto-balanced), each step drives
              --microbatches M slices through the GPipe tick grid
              (default M = 2K; bubble fraction (K-1)/(M+K-1)). A
              dp/mp-only mesh selects the ParallelExecutor.
              notable flags for the pipelined loop (README "Training"):
              --prefetch_to_device N  DevicePrefetcher queue depth
                                      (default 2; 0 disables)
              --sync_every N          host-sync cadence of the async step
                                      loop (default: follow --log_period;
                                      1 = fully synchronous legacy loop;
                                      env PT_FLAGS_SYNC_EVERY)
              --scan_window K         fuse K steps into ONE compiled
                                      lax.scan window: 1 host dispatch
                                      per K steps, syncs at window edges
                                      only (default 0 = per-step loop;
                                      env PT_FLAGS_SCAN_WINDOW; single-
                                      device executors only)
              --log_period N          print cost every N batches (reading
                                      the lazy cost is itself a sync)
              observability (README "Observability"):
              --trace_out PATH        arm span tracing; export a Chrome
                                      trace-event JSON (Perfetto) at exit
                                      (env PT_FLAGS_TRACE)
              --stats_period N        log a runtime-stats line every N
                                      steps (paddle_tpu.stats logger)
              --dump_stats            print the unified metrics registry
                                      + timer table at exit
  merge_model --model_dir D --out O   (MergeModel.cpp parity: checkpoint
                                       params -> single deployable dir)
  serve       --model_dir D [--model name=dir ...] [--host H] [--port P]
              [--max_batch_size N] [--max_wait_ms M] [--max_queue Q]
              [--timeout_ms T] [--seq_len_buckets 64,128,...] [--warmup 0|1]
              [--max_slots S] [--gen_queue Q] [--gen_timeout_ms T]
              [--prefix_cache_mb MB [--prefix_quant int8]]
              [--draft_model D [--draft_k K]]
              [--mesh dp1,mp2] [--drain_s S] [--quant int8]
              [--slo model=interactive|batch ...]
              [--replicas N [--standby K] [--probe_interval_ms P]
               [--autoscale --min_replicas A --max_replicas B
                --cooldown_s C]]
              [--disaggregate --prefill_replicas N --decode_replicas M
               [--handoff_quant int8]]
              batching HTTP inference server over saved inference
              models (paddle_tpu.serving): /predict, /healthz, /metrics
              — generation models additionally serve /generate
              (continuous batching over S decode slots, NDJSON
              streaming with "stream": true).
              --mesh runs the replica sharded over a device mesh (the
              artifact's sharding sidecar places params; README
              "Scale-out serving"); SIGTERM drains in-flight work for
              up to --drain_s seconds before exit.
              --replicas N turns this process into a ROUTER that
              pre-forks N replica serve processes (plus --standby
              warmed spares), join-shortest-queue balances /predict
              and /generate over them (streaming passes through),
              retries shed/503s on another replica, circuit-breaks and
              replaces dead replicas (paddle_tpu.serving.router).
              --slo model=batch marks a model's traffic as the
              sheddable tier: at queue pressure batch requests shed
              strictly before interactive ones ever queue behind them,
              and the router JSQ-scores picks per class
              (paddle_tpu.fleetctl.tenancy; a request may self-demote
              via X-PT-SLO-Class or "slo" in the body).
              --autoscale arms the control loop: warm standbys are
              promoted under sustained queue/occupancy pressure and
              idle replicas drained + retired, between --min_replicas
              and --max_replicas, with --cooldown_s between actions
              (paddle_tpu.fleetctl.autoscaler; watch /admin/fleet)
              --disaggregate splits the fleet into N PREFILL replicas
              (prefix program only) and M DECODE replicas (slot pool):
              /generate runs the prefix on a prefill replica, ships
              the decode boot state as a handoff payload (bit-
              identical admission; --handoff_quant int8 halves the
              bytes) and streams tokens from a decode replica; with
              --autoscale each class scales on its own signal
              (paddle_tpu.serving.disagg)
  fleetctl    rollout --router URL --model_dir D [--model NAME]
              | status --router URL
              control-plane client for a serve --replicas router:
              rollout = zero-downtime version flip (warm new artifact
              in fresh replicas, verify the program fingerprint from
              meta.json on /healthz, atomically flip the router, drain
              the old version); status = router + fleet + autoscaler
              state in one JSON doc (GET /admin/fleet)
              --quant int8 asserts the artifact is a quantized one
              (see `quant` below) and serves its low-precision fast
              path; an fp artifact fails loudly instead of silently
              serving at fp cost
  quant       --model_dir D --out O [--samples N] [--mode int8]
              [--no-check]
              post-training int8 quantization of a saved inference
              artifact (paddle_tpu.quant): calibrates activation
              ranges on N deterministic synthetic samples drawn from
              the artifact's feed specs (default 8), rewrites matmul
              sites to int8 kernels with per-channel weight scales,
              prints the loud mixed-precision report, and saves the
              converted artifact to O (meta.json carries the quant
              block: mode, scales digest, calibration sample count —
              stale-scale artifacts fail at load). --no-check skips
              the fp-vs-quant output-delta check run
  route--replica http://host:port [--replica ...] [--host H]
              [--port P] [--probe_interval_ms P] [--request_timeout_ms T]
              stand-alone router over ALREADY-RUNNING replica servers
              (the cross-host deployment: one route process in front
              of serve processes on other machines)
  tune        --kernel K --shape k=v,k=v [--shape ...]
              [--dtype bf16|f32|int8]
              [--dry-run] [--cache PATH] [--iters N] [--warmup N]
              [--search guided|exhaustive] [--budget FRAC] [--mesh dp4]
              | --config M.py [--dry-run ...]
              empirical kernel autotuner (paddle_tpu.tune): search legal
              configs for a named kernel family over a shape grid (or
              every tunable site of a model config), write the winners
              to the persistent per-device table, print a before/after
              report. --search guided (default) cost-model-ranks the
              space and times only the top --budget fraction (0.4) with
              successive-halving early stop; exhaustive is the v1 full
              sweep. --mesh dp4 keys the --config sweep on PER-SHARD
              shapes (what the kernels dispatch under a mesh). --dry-run
              lists candidates without timing (works on any backend;
              real timing requires TPU).
              Kernels: bahdanau (B,S,A,C), flash (Tq,Tk), conv
              (n,cin,cout), lstm/gru (B,H), quant (M,K,N — int8).
  tune export --out FILE [--cache PATH]
  tune import FILE [FILE...] [--cache PATH]
  tune merge  --out FILE IN1 [IN2...]
              fleet-shared tuning database plumbing: export snapshots
              the local table, import merges colleagues' tables into it,
              merge aggregates N tables into a new file — conflicts
              resolve measured-beats-interpolated then newest-wins, and
              schema-version mismatches are loud errors. Pre-tuned
              tables shipped under paddle_tpu/tune/tables/ are
              auto-consulted beneath the local table (README
              "Autotuning").
  stats       --url http://host:port | --file exposition.txt [--raw 1]
              scrape (or read) a Prometheus /metrics exposition, parse
              it with the paddle_tpu.obs.promparse grammar, and print a
              per-family summary — the CLI view of the unified metrics
              registry a serving process exposes and a training run
              dumps at exit (--dump_stats)
  flags       print the flag registry
  version     print the version
"""

from __future__ import annotations

import os
import runpy
import sys

from .flags import FLAGS, flags_help, parse_flags


def _load_config(path: str) -> dict:
    ns = runpy.run_path(path)
    if "get_model" not in ns:
        raise SystemExit(f"config {path!r} must define get_model()")
    model = ns["get_model"]()
    if "cost" not in model or "reader" not in model:
        raise SystemExit("get_model() must return at least cost and reader")
    return model


def _cmd_train(argv) -> int:
    import numpy as np

    from .trainer import CheckpointConfig, Trainer

    train_opts = ("config", "num_passes", "save_dir", "trace_out", "mesh")
    cfg = {}
    rest = []
    i = 0
    while i < len(argv):
        a = argv[i]
        name, eq, val = a.partition("=") if a.startswith("--") else ("", "", "")
        name = name[2:].replace("-", "_")  # same normalization as parse_flags
        if name in train_opts:
            # both '--config x' and '--config=x' forms; must be consumed
            # BEFORE parse_flags (save_dir is also a registry flag and
            # would otherwise be swallowed there, silently disabling the
            # checkpoint dir)
            if eq:
                cfg[name] = val
                i += 1
            elif i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                cfg[name] = argv[i + 1]
                i += 2
            else:
                raise SystemExit(f"flag --{name} requires a value")
        else:
            rest.append(a)
            i += 1
    try:
        leftover = parse_flags(rest)
    except ValueError as e:
        raise SystemExit(str(e)) from None
    bad = [a for a in leftover if a.startswith("--")]
    if bad:
        # gflags parity: the reference errors on unknown flags rather than
        # silently training with defaults (a typo'd --log_perod=10 must
        # not be ignored). A known flag lands here too when its value is
        # missing — tell those two cases apart.
        from .flags import _REGISTRY

        msgs = []
        for a in bad:
            fname = a[2:].split("=", 1)[0].replace("-", "_")
            if fname in _REGISTRY:
                msgs.append(f"flag --{fname} requires a value")
            else:
                msgs.append(f"unknown flag: {a}")
        raise SystemExit("\n".join(msgs) + f"\n{flags_help()}")
    if "config" not in cfg:
        raise SystemExit("train requires --config <model.py>")
    from .obs import trace as obs_trace

    if cfg.get("trace_out"):
        # arm before the model builds so warmup/compile spans are in the
        # capture too; exported in the finally below (and idempotently
        # by the atexit hook if the env flag armed it first)
        obs_trace.arm(out=cfg["trace_out"])
    model = _load_config(cfg["config"])
    if FLAGS.stats_period:
        # the trainer emits the periodic runtime-stats line through the
        # paddle_tpu.stats logger; a CLI run that asked for it must see
        # it without configuring logging first
        import logging

        slog = logging.getLogger("paddle_tpu.stats")
        if not slog.handlers:
            h = logging.StreamHandler()
            h.setFormatter(logging.Formatter("%(name)s: %(message)s"))
            slog.addHandler(h)
            slog.setLevel(logging.INFO)
    num_passes = int(cfg.get("num_passes", model.get("num_passes", 1)))
    # checkpointing (and its auto-resume) only when the user asks for it:
    # a default dir would make a rerun of a finished job silently resume
    # past the last pass and train nothing
    save_dir = cfg.get("save_dir", "")
    ckpt = CheckpointConfig(checkpoint_dir=save_dir) if save_dir else None
    executor = None
    mesh_spec = cfg.get("mesh", "")
    if mesh_spec or FLAGS.pipeline_stages or FLAGS.microbatches:
        # --mesh dp2,pp2 trains over a device mesh; a pp axis (or
        # --pipeline_stages) selects the micro-batch pipeline executor,
        # a dp/mp-only mesh the ParallelExecutor
        mesh = None
        pp_size = 1
        if mesh_spec:
            from .parallel.mesh import mesh_from_spec, parse_mesh_spec

            try:
                pp_size = dict(parse_mesh_spec(mesh_spec)).get("pp", 1)
                mesh = mesh_from_spec(mesh_spec)
            except ValueError as e:
                raise SystemExit(f"--mesh {mesh_spec}: {e}") from None
        stages = int(FLAGS.pipeline_stages) or pp_size
        if stages > 1 or FLAGS.microbatches:
            from .pipeline import PipelineExecutor

            stages = max(stages, 1)
            executor = PipelineExecutor(
                num_stages=stages,
                num_microbatches=int(FLAGS.microbatches) or 2 * stages,
                mesh=mesh,
            )
        elif mesh is not None:
            from .parallel import ParallelExecutor

            executor = ParallelExecutor(mesh)
    trainer = Trainer(cost=model["cost"], checkpoint_config=ckpt,
                      executor=executor)

    def log_handler(event):
        from .trainer import EndIteration, EndPass

        if isinstance(event, EndIteration):
            if event.batch_id % FLAGS.log_period == 0:
                ms = ", ".join(f"{k}={v:.5g}" for k, v in event.metrics.items())
                print(f"pass {event.pass_id} batch {event.batch_id} "
                      f"cost={event.cost:.6g}" + (f" {ms}" if ms else ""))
        elif isinstance(event, EndPass):
            ms = ", ".join(f"{k}={v:.5g}" for k, v in event.metrics.items())
            print(f"Pass {event.pass_id} done: {ms}")

    from .resilience import PREEMPT_EXIT_CODE, PreemptedError

    def finish():
        # dump-at-exit observability: export the trace capture (if any)
        # and print the same unified metrics surface a serving process
        # exposes on /metrics
        if obs_trace.armed():
            tr = obs_trace.disarm(export=True)
            out = getattr(tr, "out", None) if tr is not None else None
            if out:
                print(f"trace written to {out} ({tr.event_count()} "
                      f"events, {tr.dropped_total()} dropped)", flush=True)
        if FLAGS.dump_stats:
            from . import profiler
            from .obs import metrics as obs_metrics

            profiler.global_stat_set().print_all_status()
            print(obs_metrics.registry().render(), end="")

    try:
        metrics = trainer.train(
            model["reader"],
            num_passes=num_passes,
            feed_order=model.get("feed_order"),
            fetch_metrics=model.get("metrics"),
            event_handler=log_handler,
        )
    except PreemptedError as e:
        # EX_TEMPFAIL: the scheduler should reschedule this job; a rerun
        # with the same --save_dir resumes from the emergency checkpoint
        print(f"preempted: {e}", flush=True)
        finish()
        return PREEMPT_EXIT_CODE
    print("final:", {k: round(float(v), 6) for k, v in metrics.items()})
    finish()
    return 0


def _cmd_merge_model(argv) -> int:
    """Checkpoint/params dir → single deployable inference dir."""
    args = dict(zip(argv[::2], argv[1::2]))
    model_dir = args.get("--model_dir")
    out = args.get("--out")
    config = args.get("--config")
    if not (model_dir and out and config):
        raise SystemExit(
            "merge_model requires --config <infer_model.py> --model_dir "
            "<params> --out <dir>; the config must define get_inference() "
            "returning (feed_names, fetch_vars)")
    import paddle_tpu as pt

    ns = runpy.run_path(config)
    if "get_inference" not in ns:
        raise SystemExit("config must define get_inference()")
    feed_names, fetch_vars = ns["get_inference"]()
    # accept either a plain params dir (save_params) or a trainer
    # checkpoint dir (pick the latest serial)
    if pt.io.get_latest_checkpoint_serial(model_dir) >= 0:
        pt.io.load_checkpoint(model_dir)
    else:
        pt.io.load_params(model_dir)
    pt.io.save_inference_model(out, feed_names, fetch_vars)
    print(f"merged model written to {out}")
    return 0


def _parse_kv(argv, known):
    """--k v / --k=v option parsing (list-valued keys may repeat)."""
    opts: dict = {}
    i = 0
    while i < len(argv):
        a = argv[i]
        if not a.startswith("--"):
            raise SystemExit(f"unexpected argument {a!r}")
        name, eq, val = a.partition("=")
        name = name[2:].replace("-", "_")
        if name not in known:
            raise SystemExit(f"unknown option --{name}")
        if known[name] is bool:
            # bare flag: --autoscale (or explicit --autoscale=0)
            opts[name] = val if eq else "1"
            i += 1
            continue
        if not eq:
            if i + 1 >= len(argv):
                raise SystemExit(f"option --{name} requires a value")
            val = argv[i + 1]
            i += 1
        if known[name] is list:
            opts.setdefault(name, []).append(val)
        else:
            opts[name] = val
        i += 1
    return opts


def _model_is_generative(model_dir: str) -> bool:
    """Cheap pre-load check: does the artifact's meta.json carry the
    generation sidecar (io.save_inference_model on a beam-search
    model)? Decides whether serve passes continuous-batching knobs."""
    import json as _json
    import os as _os

    try:
        with open(_os.path.join(model_dir, "meta.json")) as f:
            return bool(_json.load(f).get("generation"))
    except (OSError, ValueError):
        return False


_SERVE_KNOWN = {
    "model_dir": str, "model": list, "host": str, "port": str,
    "max_batch_size": str, "max_wait_ms": str, "max_queue": str,
    "timeout_ms": str, "seq_len_buckets": str, "warmup": str,
    "max_slots": str, "gen_queue": str, "gen_timeout_ms": str,
    # generation serving v3: device-resident prefix cache +
    # speculative decoding (forwarded to replica children so a fleet
    # caches/drafts identically on every replica)
    "prefix_cache_mb": str, "prefix_quant": str,
    "draft_model": str, "draft_k": str,
    "trace_out": str, "mesh": str, "drain_s": str, "quant": str,
    # multi-tenancy: per-model SLO class specs (model=interactive|batch);
    # forwarded to replica children so admission tiers match the
    # router's per-class picks
    "slo": list,
    # fleet mode (router + replica processes); NOT forwarded to the
    # replica children
    "replicas": str, "standby": str, "probe_interval_ms": str,
    # fleet control plane (fleetctl.autoscaler): warm-standby
    # promotion under pressure, drain-and-retire when idle
    "autoscale": bool, "min_replicas": str, "max_replicas": str,
    "cooldown_s": str,
    # disaggregated serving (serving/disagg): phase-specialized
    # replica classes with device-state handoff
    "disaggregate": bool, "prefill_replicas": str,
    "decode_replicas": str, "handoff_quant": str,
}
_FLEET_ONLY = ("replicas", "standby", "probe_interval_ms", "host",
               "port", "trace_out", "autoscale", "min_replicas",
               "max_replicas", "cooldown_s", "disaggregate",
               "prefill_replicas", "decode_replicas", "handoff_quant")


def _cmd_serve(argv) -> int:
    """Batching inference server over saved inference models. With
    --replicas N this process becomes a ROUTER: it pre-forks N replica
    serve processes (plus --standby warm spares), load-balances
    /predict and /generate across them join-shortest-queue, and
    fails over on replica death (serving/router.py)."""
    from .serving import BucketPolicy, ModelRegistry, make_server

    opts = _parse_kv(argv, _SERVE_KNOWN)
    if (int(opts.get("replicas", 0) or 0) > 0
            or opts.get("disaggregate", "0")
            not in ("0", "false", "no", "")):
        return _serve_fleet(opts)
    if opts.get("trace_out"):
        from .obs import trace as obs_trace

        obs_trace.arm(out=opts["trace_out"])
        print(f"span tracing armed; Chrome trace JSON will be written "
              f"to {opts['trace_out']} at shutdown", flush=True)
    models = {}
    if "model_dir" in opts:
        models["default"] = opts["model_dir"]
    for spec in opts.get("model", []):
        name, eq, d = spec.partition("=")
        if not eq:
            raise SystemExit(
                f"--model needs name=dir, got {spec!r}")
        models[name] = d
    if not models:
        raise SystemExit("serve requires --model_dir <dir> or at least "
                         "one --model name=dir")
    mesh = None
    if opts.get("mesh"):
        # mesh-sharded replica: ONE model served across chips — params
        # carrying the artifact's sharding sidecar land sharded, the
        # HTTP surface is unchanged (README "Scale-out serving")
        from .parallel.mesh import mesh_from_spec

        mesh = mesh_from_spec(opts["mesh"])
    policy = BucketPolicy(
        max_batch_size=int(opts.get("max_batch_size", 64)),
        seq_len_buckets=tuple(
            int(t) for t in opts.get("seq_len_buckets", "").split(",")
            if t.strip()),
    )
    # continuous-batching knobs for generation models (ignored — and
    # rejected by the registry — for feed-forward ones)
    scheduler_kw = {
        "max_slots": int(opts.get("max_slots", 8)),
        "max_queue": int(opts.get("gen_queue", 64)),
        "timeout_ms": float(opts.get("gen_timeout_ms", 30000.0)),
    }
    # serving v3 knobs stay absent unless asked for, so the scheduler's
    # defaults (cache off, no draft) govern and old artifacts' sidecar
    # draft models still auto-apply
    if opts.get("prefix_cache_mb"):
        scheduler_kw["prefix_cache_mb"] = float(opts["prefix_cache_mb"])
    if opts.get("prefix_quant"):
        scheduler_kw["prefix_cache_quant"] = opts["prefix_quant"]
    if opts.get("draft_model"):
        scheduler_kw["draft_model"] = opts["draft_model"]
    if opts.get("draft_k"):
        scheduler_kw["draft_k"] = int(opts["draft_k"])
    from .fleetctl.tenancy import SLOPolicy

    registry = ModelRegistry(
        slo_policy=SLOPolicy.from_specs(opts.get("slo", [])))
    for name, d in models.items():
        engine, _ = registry.add(
            name, model_dir=d, policy=policy, mesh=mesh,
            quantize=opts.get("quant") or None,
            max_wait_ms=float(opts.get("max_wait_ms", 5.0)),
            max_queue=int(opts.get("max_queue", 256)),
            timeout_ms=float(opts.get("timeout_ms", 2000.0)),
            scheduler_kw=(scheduler_kw
                          if _model_is_generative(d) else None),
        )
        if opts.get("warmup", "1") not in ("0", "false", "no"):
            n = engine.warmup()
            print(f"model {name!r}: warmed {n} bucket programs",
                  flush=True)
        if engine.generation_spec() is not None:
            spec = engine.generation_spec()
            print(f"model {name!r}: generation serving on /generate/"
                  f"{name} (beam_size={spec.beam_size} "
                  f"max_len={spec.max_len} "
                  f"slots={scheduler_kw['max_slots']})", flush=True)
    server = make_server(registry, host=opts.get("host", "127.0.0.1"),
                         port=int(opts.get("port", 8866)))
    registry.start()
    # SIGTERM = graceful shutdown (the replica half of the router's
    # failover contract, mirroring the trainer's preemption drain):
    # stop accepting, then DRAIN in-flight work — queued predicts and
    # running generation streams finish (bounded by --drain_s) before
    # the process exits, so a router-managed replica being descheduled
    # never tears a client's stream mid-token.
    import signal
    import threading

    term = {"signaled": False}

    def _on_term(signum, frame):
        term["signaled"] = True
        threading.Thread(target=server.shutdown, daemon=True).start()

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except ValueError:
        pass  # not the main thread (embedded use): caller owns signals
    print(f"serving {registry.names()} on "
          f"http://{server.server_address[0]}:{server.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        drain_s = (float(opts.get("drain_s", 30.0))
                   if term["signaled"] else 0.0)
        if drain_s:
            print(f"SIGTERM: draining in-flight work "
                  f"(up to {drain_s:g}s)", flush=True)
        registry.stop(drain_s=drain_s)
        if drain_s:
            # the scheduler/batcher have delivered every result; give
            # in-flight (daemon) handler threads a beat to flush their
            # final chunks down the socket before the interpreter exits
            import time as _time

            _time.sleep(0.5)
            print("drained; exiting", flush=True)
        server.server_close()
        from .obs import trace as obs_trace

        if obs_trace.armed():
            tr = obs_trace.disarm(export=True)
            out = getattr(tr, "out", None) if tr is not None else None
            if out:
                print(f"trace written to {out}", flush=True)
    return 0


def _serve_fleet(opts) -> int:
    """serve --replicas N: router + pre-forked replica fleet."""
    from .fleetctl.tenancy import SLOPolicy
    from .serving.router import Fleet, Router, make_router_server, \
        replica_spawner

    # child argv = every serving option EXCEPT the fleet-only ones;
    # children bind port 0 on loopback and print their URL
    if not opts.get("model_dir") and not opts.get("model"):
        raise SystemExit("serve requires --model_dir <dir> or at "
                         "least one --model name=dir")
    child_args = []
    for k, v in opts.items():
        if k in _FLEET_ONLY:
            continue
        if isinstance(v, list):
            child_args.extend(f"--{k}={x}" for x in v)
        else:
            child_args.append(f"--{k}={v}")
    disagg_on = (opts.get("disaggregate", "0")
                 not in ("0", "false", "no", ""))
    standby = int(opts.get("standby", 0))
    router = Router(
        probe_interval_s=float(opts.get("probe_interval_ms", 500)) / 1e3,
        slo_policy=SLOPolicy.from_specs(opts.get("slo", [])))
    if disagg_on:
        # disaggregated topology: two replica classes behind one
        # router, /generate phase-split through a DisaggDispatcher
        from .serving.disagg import DisaggFleet

        npf = int(opts.get("prefill_replicas", 1))
        ndec = int(opts.get("decode_replicas", 1))
        n = npf + ndec
        fleet = DisaggFleet(replica_spawner(child_args),
                            prefill_replicas=npf,
                            decode_replicas=ndec,
                            standby=standby, router=router)
    else:
        n = int(opts["replicas"])
        fleet = Fleet(replica_spawner(child_args), replicas=n,
                      standby=standby, router=router)

    # rollout hook: model_dir -> spawn_fn serving THAT artifact with
    # this fleet's serve flags (fleetctl rollout warms the new version
    # through it, then repoints standby respawns)
    def _spawn_template(model_dir):
        args = [a for a in child_args
                if not a.startswith(("--model_dir=", "--model="))]
        args.append(f"--model_dir={model_dir}")
        return replica_spawner(args)

    fleet.spawn_template = _spawn_template
    print(f"spawning {n} replica(s)"
          + (f" + {standby} warm standby" if standby else "")
          + " ...", flush=True)
    fleet.start()
    for r in router.replicas():
        print(f"  replica {r.name}: {r.url}"
              + (f" [{r.phase}]" if r.phase else ""), flush=True)
    scaler = None
    if opts.get("autoscale", "0") not in ("0", "false", "no", ""):
        if disagg_on:
            from .serving.disagg import make_phase_autoscalers

            scaler = make_phase_autoscalers(fleet).start()
            print("phase autoscalers armed: prefill scales on queue "
                  "age/depth, decode on slot occupancy", flush=True)
        else:
            from .fleetctl import Autoscaler, AutoscalerConfig

            cfg = AutoscalerConfig(
                min_replicas=int(opts.get("min_replicas", 1)),
                max_replicas=int(opts.get("max_replicas",
                                          max(n, 1) + max(standby, 1))),
                cooldown_s=float(opts.get("cooldown_s", 3.0)))
            scaler = Autoscaler(fleet, cfg).start()
            print(f"autoscaler armed: {cfg.min_replicas}.."
                  f"{cfg.max_replicas} replicas, "
                  f"cooldown {cfg.cooldown_s:g}s", flush=True)
    dispatcher = None
    if disagg_on:
        from .serving.disagg import DisaggDispatcher

        dispatcher = DisaggDispatcher(
            router, quant=opts.get("handoff_quant") or None)
        print("disaggregated dispatch armed: /generate phase-splits "
              "prefill -> handoff -> decode"
              + (f" (handoff quant {opts['handoff_quant']})"
                 if opts.get("handoff_quant") else ""), flush=True)
    server = make_router_server(
        router, host=opts.get("host", "127.0.0.1"),
        port=int(opts.get("port", 8866)),
        fleet=fleet, autoscaler=scaler, disagg=dispatcher)
    server.serve_background()

    import signal
    import threading

    stop = threading.Event()
    for sig in (signal.SIGTERM, signal.SIGINT):
        try:
            signal.signal(sig, lambda s, f: stop.set())
        except ValueError:
            pass
    print(f"routing /predict and /generate for {n} replica(s) on "
          f"http://{server.server_address[0]}:{server.port}", flush=True)
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    print("stopping fleet (graceful: replicas drain in-flight work)",
          flush=True)
    if scaler is not None:
        scaler.stop()
    server.shutdown()
    fleet.stop(graceful=True)
    server.server_close()
    return 0


def _cmd_fleetctl(argv) -> int:
    """Control-plane client for a running fleet router: `rollout`
    POSTs /admin/rollout (zero-downtime version flip), `status` GETs
    /admin/fleet (router health + fleet + autoscaler in one doc)."""
    import json as _json
    import urllib.error
    import urllib.request

    if not argv or argv[0] in ("-h", "--help"):
        raise SystemExit(
            "usage: fleetctl rollout --router URL --model_dir D "
            "[--model NAME]\n       fleetctl status --router URL")
    verb, rest = argv[0], argv[1:]
    known = {"router": str, "model_dir": str, "model": str,
             "drain_timeout_s": str}
    opts = _parse_kv(rest, known)
    url = (opts.get("router") or "http://127.0.0.1:8866").rstrip("/")
    try:
        if verb == "status":
            with urllib.request.urlopen(url + "/admin/fleet",
                                        timeout=10.0) as f:
                payload = _json.load(f)
        elif verb == "rollout":
            if not opts.get("model_dir"):
                raise SystemExit("fleetctl rollout requires "
                                 "--model_dir <new artifact dir>")
            body = {"model_dir": opts["model_dir"],
                    "model": opts.get("model", "default")}
            if opts.get("drain_timeout_s"):
                body["drain_timeout_s"] = float(opts["drain_timeout_s"])
            req = urllib.request.Request(
                url + "/admin/rollout",
                data=_json.dumps(body).encode(),
                headers={"Content-Type": "application/json"})
            # rollout blocks through warm+verify+flip+drain; size the
            # client timeout for a model load, not a ping
            with urllib.request.urlopen(req, timeout=600.0) as f:
                payload = _json.load(f)
        else:
            raise SystemExit(
                f"unknown fleetctl verb {verb!r}; try: rollout, status")
    except urllib.error.HTTPError as e:
        try:
            detail = _json.load(e).get("error", "")
        except Exception:
            detail = ""
        print(f"fleetctl {verb} failed: HTTP {e.code} {detail}",
              file=sys.stderr)
        return 1
    except urllib.error.URLError as e:
        print(f"cannot reach router at {url}: {e.reason}",
              file=sys.stderr)
        return 1
    print(_json.dumps(payload, indent=2, sort_keys=True))
    return 0


def _cmd_route(argv) -> int:
    """Stand-alone router over ALREADY-RUNNING replicas (spawned by
    `serve` on other hosts/ports, or by an external scheduler)."""
    from .serving.router import Router, make_router_server

    known = {"replica": list, "host": str, "port": str,
             "probe_interval_ms": str, "request_timeout_ms": str}
    opts = _parse_kv(argv, known)
    urls = opts.get("replica", [])
    if not urls:
        raise SystemExit("route requires at least one "
                         "--replica http://host:port")
    router = Router(
        replicas=urls,
        probe_interval_s=float(opts.get("probe_interval_ms", 500)) / 1e3,
        request_timeout_s=float(
            opts.get("request_timeout_ms", 120000)) / 1e3)
    server = make_router_server(
        router, host=opts.get("host", "127.0.0.1"),
        port=int(opts.get("port", 8866)))
    router.start()
    print(f"routing {len(urls)} replica(s) on "
          f"http://{server.server_address[0]}:{server.port}", flush=True)
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        pass
    finally:
        router.close()
        server.server_close()
    return 0


_DTYPE_ALIASES = {"bf16": "bfloat16", "bfloat16": "bfloat16",
                  "f32": "float32", "fp32": "float32",
                  "float32": "float32", "int8": "int8", "i8": "int8"}


def _fmt_cfg(cfg) -> str:
    if cfg is None:
        return "<none>"
    return ",".join(f"{k}={v}" for k, v in sorted(cfg.items()))


def _cmd_tune_export(argv) -> int:
    """`tune export --out FILE [--cache PATH]`: snapshot the local
    table into a shareable file (the fleet exchange format — same
    schema the runtime reads, so export/import round-trips
    bit-identically)."""
    from .tune import cache as tune_cache

    opts = _parse_kv(argv, {"out": str, "cache": str})
    if "out" not in opts:
        raise SystemExit("tune export requires --out FILE")
    src = opts.get("cache") or tune_cache.default_path()
    table = tune_cache.TunedTable(src)
    table.save(opts["out"])
    print(f"exported {len(table)} entries from {src} to {opts['out']} "
          f"(fingerprint {table.fingerprint()})")
    return 0


def _split_positional(argv, known):
    """(positional files, option dict) from an argv mixing both —
    `--k v` / `--k=v` options consumed pairwise, the rest positional."""
    files, opt_argv, i = [], [], 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--"):
            opt_argv.append(a)
            if "=" not in a and i + 1 < len(argv):
                opt_argv.append(argv[i + 1])
                i += 1
        else:
            files.append(a)
        i += 1
    return files, _parse_kv(opt_argv, known)


def _cmd_tune_import(argv) -> int:
    """`tune import FILE [FILE...] [--cache PATH]`: merge tables from
    fleet colleagues into the local table. Conflicts resolve
    measured-beats-interpolated, then newest-wins (cache.merge_entry);
    a schema-version mismatch is a loud error, not a silent skip."""
    from .tune import cache as tune_cache
    from .tune import overrides as tune_overrides

    files, opts = _split_positional(argv, {"cache": str})
    if not files:
        raise SystemExit("tune import requires at least one table FILE")
    dst_path = opts.get("cache") or tune_cache.default_path()
    dst = tune_cache.TunedTable(dst_path)
    for f in files:
        try:
            src = tune_cache.load_strict(f)
        except tune_cache.TableFormatError as e:
            raise SystemExit(str(e)) from None
        st = dst.merge_from(src)
        print(f"{f}: +{st['added']} added, {st['replaced']} replaced, "
              f"{st['kept']} kept (local won)")
    dst.save(dst_path)
    tune_overrides.reload_table()  # a live import must be visible
    print(f"local table {dst_path}: {len(dst)} entries "
          f"(fingerprint {dst.fingerprint()})")
    return 0


def _cmd_tune_merge(argv) -> int:
    """`tune merge --out FILE IN1 IN2 ...`: merge N tables into a new
    file without touching the local table (the fleet-aggregation step:
    every host exports, one job merges, the result ships as the next
    base table)."""
    from .tune import cache as tune_cache

    files, opts = _split_positional(argv, {"out": str})
    if "out" not in opts or len(files) < 1:
        raise SystemExit("tune merge requires --out FILE and at least "
                         "one input table")
    out = tune_cache.TunedTable(opts["out"], autoload=False)
    for f in files:
        try:
            src = tune_cache.load_strict(f)
        except tune_cache.TableFormatError as e:
            raise SystemExit(str(e)) from None
        st = out.merge_from(src)
        print(f"{f}: +{st['added']} added, {st['replaced']} replaced, "
              f"{st['kept']} kept")
    out.save(opts["out"])
    print(f"merged {len(files)} tables -> {opts['out']} "
          f"({len(out)} entries, fingerprint {out.fingerprint()})")
    return 0


def _cmd_tune(argv) -> int:
    """Empirical kernel autotuner front-end (paddle_tpu.tune)."""
    from .tune import cache as tune_cache
    from .tune import harness, space

    if argv and argv[0] in ("export", "import", "merge"):
        return {"export": _cmd_tune_export,
                "import": _cmd_tune_import,
                "merge": _cmd_tune_merge}[argv[0]](argv[1:])

    dry = False
    rest = []
    for a in argv:
        if a in ("--dry-run", "--dry_run"):
            dry = True
        else:
            rest.append(a)
    known = {"kernel": str, "shape": list, "dtype": str, "cache": str,
             "iters": str, "warmup": str, "config": str, "search": str,
             "budget": str, "mesh": str}
    opts = _parse_kv(rest, known)
    mode = opts.get("search", "guided")
    if mode not in ("guided", "exhaustive"):
        raise SystemExit(f"--search must be guided or exhaustive, got "
                         f"{mode!r}")
    budget = float(opts.get("budget", 0.4))
    dp = 1
    if "mesh" in opts:
        from .parallel.mesh import parse_mesh_spec

        dp = dict(parse_mesh_spec(opts["mesh"])).get("dp", 1)
    dtype = _DTYPE_ALIASES.get(opts.get("dtype", "bf16"))
    if dtype is None:
        raise SystemExit(f"--dtype must be bf16, f32 or int8, got "
                         f"{opts['dtype']!r}")

    cases = []
    if "config" in opts:
        # model sweep: build the model's program, scan it for tunable
        # kernel sites with concrete shapes — at the PER-SHARD batch
        # when --mesh declares the dp degree the model will run under
        _load_config(opts["config"])
        sites = space.cases_from_program(dp=dp)
        if not sites:
            print("no tunable kernel sites with concrete shapes found "
                  "in the model program")
        cases.extend(
            {"family": s["family"], "params": s["params"],
             "dtype": s["dtype"]} for s in sites)
    if "kernel" in opts:
        shapes = opts.get("shape", [])
        if not shapes:
            raise SystemExit("tune --kernel requires at least one "
                             "--shape k=v,k=v (e.g. --shape "
                             "B=256,S=60,A=512,C=512)")
        try:
            fam = space.get_family(opts["kernel"])
        except KeyError as e:
            raise SystemExit(str(e)) from None
        for spec in shapes:
            try:
                params = {k: int(v) for k, _, v in
                          (kv.partition("=") for kv in spec.split(","))}
            except ValueError:
                raise SystemExit(
                    f"bad --shape {spec!r}: expected k=v,k=v with "
                    "integer values") from None
            # user-facing bahdanau shapes take the raw source length S;
            # the kernels run over S padded (the signature's Sp)
            if fam.name == "bahdanau_attention" and "S" in params \
                    and "Sp" not in params:
                params["Sp"] = space.pad_s(params.pop("S"))
            cases.append({"family": fam.name, "params": params,
                          "dtype": dtype})
    if not cases:
        raise SystemExit("tune requires --kernel <family> --shape ... "
                         "and/or --config <model.py>")

    if dry:
        for c in cases:
            try:
                info = harness.list_candidates(c["family"], c["params"],
                                               c["dtype"])
            except (ValueError, KeyError) as e:
                print(f"{c['family']}: {e}")
                continue
            sig = tune_cache.make_sig(info["params"])
            print(f"kernel {info['kernel']}  {sig}  dtype={c['dtype']}")
            print(f"  analytic default: {_fmt_cfg(info['default'])}")
            print(f"  {len(info['candidates'])} legal candidates:")
            for cfg in info["candidates"]:
                mark = "   (analytic default)" \
                    if cfg == info["default"] else ""
                print(f"    {_fmt_cfg(cfg)}{mark}")
        return 0

    try:
        harness.ensure_timeable()
    except harness.TuningUnavailable as e:
        raise SystemExit(str(e)) from None
    path = opts.get("cache") or tune_cache.default_path()
    table = tune_cache.TunedTable(path)  # merge into any existing table
    iters = int(opts.get("iters", 7))
    warmup = int(opts.get("warmup", 2))
    for c in cases:
        try:
            rep = harness.tune_case(c["family"], c["params"], c["dtype"],
                                    table=table, iters=iters,
                                    warmup=warmup, mode=mode,
                                    budget_fraction=budget)
        except (NotImplementedError, ValueError) as e:
            print(f"{c['family']}: skipped — {e}")
            continue
        sig = tune_cache.make_sig(rep["params"])
        print(f"kernel {rep['kernel']}  {sig}  dtype={c['dtype']}  "
              f"device={rep['device_kind']}")
        for r in rep["rows"]:
            if not r.get("timed", True):
                t = "   (pruned by cost model)"
            elif not r["numerics_ok"]:
                t = "   FAILED numerics"
            else:
                t = f"{r['median_s'] * 1e3:10.3f} ms"
            marks = ("   (default)" if r["is_default"] else "") + \
                    ("   <- best" if r["config"] == rep["best"] else "")
            print(f"    {_fmt_cfg(r['config']):<28}{t}{marks}")
        s = rep.get("search", {})
        if s.get("mode") == "guided":
            print(f"  guided search timed {s['timed']}/{s['candidates']} "
                  f"candidates ({s['timed_fraction']:.0%})"
                  + (" — stopped early (leader stable)"
                     if s.get("stopped_early") else ""))
        if "speedup_vs_default" in rep:
            print(f"  best {_fmt_cfg(rep['best'])}: "
                  f"{rep['speedup_vs_default']:.3f}x vs analytic default")
    table.save(path)
    print(f"tuned table written to {path} "
          f"({len(table)} entries, fingerprint {table.fingerprint()})")
    return 0


def _synthetic_samples(feed_specs, feed_names, n, batch=4):
    """Deterministic calibration feeds from an artifact's feed specs:
    seed-0 standard-normal floats / small-range ints, -1 dims pinned to
    the calibration batch (dim 0) or 8 (inner dims). Synthetic ranges
    are a stand-in for real traffic — good enough for the smoke path;
    production calibration should feed recorded samples through
    quant.calibrate directly."""
    import numpy as np

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(n):
        feed = {}
        for name in feed_names:
            spec = (feed_specs or {}).get(name)
            if spec is None:
                raise SystemExit(
                    f"feed {name!r} has no shape/dtype spec in meta.json "
                    "(pre-serving artifact?); re-export the model or "
                    "calibrate programmatically via paddle_tpu.quant")
            shape = [batch if i == 0 and d == -1 else (8 if d == -1 else d)
                     for i, d in enumerate(spec["shape"])]
            dtype = np.dtype(spec["dtype"])
            if dtype.kind in "iu":
                feed[name] = rng.randint(0, 8, size=shape).astype(dtype)
            else:
                feed[name] = rng.standard_normal(shape).astype(dtype)
        samples.append(feed)
    return samples


def _cmd_quant(argv) -> int:
    """Post-training int8 quantization of a saved inference artifact:
    load → calibrate activation ranges on deterministic synthetic
    samples → rewrite matmul sites to quantized kernels → save the
    converted artifact (with the quant sidecar io.py validates at
    load). The loud mixed-precision report goes to stdout."""
    from . import io as pt_io
    from . import quant
    from .core.executor import Executor, Scope

    no_check = False
    argv = list(argv)
    while "--no-check" in argv or "--no_check" in argv:
        argv.remove("--no-check" if "--no-check" in argv
                    else "--no_check")
        no_check = True
    known = {"model_dir": str, "out": str, "samples": str, "mode": str}
    opts = _parse_kv(argv, known)
    model_dir, out = opts.get("model_dir"), opts.get("out")
    if not (model_dir and out):
        raise SystemExit("quant requires --model_dir <dir> --out <dir>")
    mode = opts.get("mode", "int8")
    n_samples = int(opts.get("samples", 8))
    scope = Scope()
    exe = Executor()
    program, feed_names, fetch_names = pt_io.load_inference_model(
        model_dir, scope=scope)
    if getattr(program, "_quant_meta", None):
        raise SystemExit(f"{model_dir} is already quantized "
                         f"({program._quant_meta.get('mode')})")
    samples = _synthetic_samples(getattr(program, "_serving_meta", None),
                                 feed_names, n_samples)
    calib = quant.calibrate(program, samples, scope=scope, exe=exe)
    check = None if no_check else samples[0]
    try:
        report = quant.convert(
            program, scope=scope, calib=calib, mode=mode,
            check_feed=check, fetch_list=fetch_names if check else None,
            exe=exe)
    except ValueError as e:
        raise SystemExit(str(e))
    print(report.summary())
    pt_io.save_inference_model(out, feed_names, fetch_names,
                               main_program=program, scope=scope)
    print(f"quantized model written to {out}")
    return 0


def _cmd_stats(argv) -> int:
    """Scrape/parse a Prometheus exposition and print a summary: the
    consumer side of the unified metrics registry (obs.promparse is the
    same parser the tier-1 smoke test validates the renderer with)."""
    from .obs import promparse

    known = {"url": str, "file": str, "raw": str}
    opts = _parse_kv(argv, known)
    if "url" in opts:
        import urllib.request

        url = opts["url"]
        if not url.rstrip("/").endswith("/metrics"):
            url = url.rstrip("/") + "/metrics"
        with urllib.request.urlopen(url, timeout=10) as r:
            text = r.read().decode()
    elif "file" in opts:
        with open(opts["file"]) as f:
            text = f.read()
    else:
        raise SystemExit(
            "stats requires --url http://host:port (a serving process's "
            "/metrics) or --file <exposition.txt>")
    try:
        families = promparse.parse_text(text)
    except promparse.ParseError as e:
        raise SystemExit(f"exposition did not parse: {e}") from None
    if opts.get("raw") in ("1", "true", "yes"):
        print(text, end="")
        return 0
    print(f"{'family':<48}{'type':>10}{'series':>8}{'value':>14}")
    for name in sorted(families):
        f = families[name]
        if f.type == "histogram":
            count = sum(v for n, _, v in f.samples
                        if n == f"{name}_count")
            total = sum(v for n, _, v in f.samples if n == f"{name}_sum")
            val = f"n={int(count)} sum={total:.4g}"
        elif len(f.samples) == 1:
            val = f"{f.samples[0][2]:.6g}"
        else:
            val = f"{len(f.samples)} series"
        print(f"{name:<48}{f.type:>10}{len(f.samples):>8}{val:>14}")
        if f.type not in ("histogram",) and 1 < len(f.samples) <= 8:
            for sname, labels, v in f.samples:
                lb = ",".join(f"{k}={x}" for k, x in sorted(labels.items()))
                print(f"    {sname}{{{lb}}} {v:.6g}")
    if "pt_tune_consults_total" in families:
        # tuned-coverage one-liner (the autotuner's provenance counters):
        # of the consults the table COULD have answered (forced/env are
        # operator overrides, not coverage), how many did it?
        src = {lb.get("source"): v for _, lb, v in
               families["pt_tune_consults_total"].samples}
        covered = src.get("table", 0) + src.get("interpolated", 0)
        total = covered + src.get("analytic", 0)
        if total:
            print(f"tuned coverage: {covered / total:.0%} of "
                  f"{int(total)} kernel consults "
                  f"({int(src.get('table', 0))} exact, "
                  f"{int(src.get('interpolated', 0))} interpolated, "
                  f"{int(src.get('analytic', 0))} analytic)")
    print(f"{len(families)} families parsed OK")
    return 0


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    if not argv or argv[0] in ("-h", "--help", "help"):
        print(__doc__)
        return 0
    cmd, rest = argv[0], argv[1:]
    if cmd == "train":
        return _cmd_train(rest)
    if cmd == "merge_model":
        return _cmd_merge_model(rest)
    if cmd == "serve":
        return _cmd_serve(rest)
    if cmd == "route":
        return _cmd_route(rest)
    if cmd == "fleetctl":
        return _cmd_fleetctl(rest)
    if cmd == "tune":
        return _cmd_tune(rest)
    if cmd == "quant":
        return _cmd_quant(rest)
    if cmd == "stats":
        return _cmd_stats(rest)
    if cmd == "flags":
        print(flags_help())
        return 0
    if cmd == "version":
        from .version import full_version

        print(full_version)
        return 0
    raise SystemExit(f"unknown command {cmd!r}; try: train, merge_model, "
                     "serve, route, fleetctl, tune, quant, stats, flags, "
                     "version")


if __name__ == "__main__":
    sys.exit(main())
