"""Automatic mixed precision (bf16 compute, f32 master weights).

TPU analogue of the reference's half-precision support
(paddle/math/float16.h:70 and the fp16 GEMM paths in paddle/cuda): on the
MXU the fast matmul/conv datatype is bfloat16, which — unlike fp16 — keeps
fp32's exponent range, so no loss scaling is needed.

Design: parameters, optimizer state, and reductions stay float32; only the
*inputs* to MXU ops (mul/matmul/conv*) are cast to the amp dtype, with
float32 accumulation (`preferred_element_type`). Enabled per-Program via
`Program.set_amp("bfloat16")` after building it, or the `pt.amp_guard()`
context around the *run* calls; the executor reads the setting at run time
and threads it into the traced env under `@AMP@`, where kernels pick it up
via `cast_inputs`.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

AMP_KEY = "@AMP@"


def cast_inputs(ctx, *arrays):
    """Cast float32 arrays to the program's amp dtype (no-op otherwise)."""
    dtype = ctx.env.get(AMP_KEY)
    out = []
    for a in arrays:
        if (
            dtype is not None
            and hasattr(a, "dtype")
            and a.dtype == jnp.float32
        ):
            a = a.astype(dtype)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16", main_program=None):
    """Enable amp on the current (or given) main program for the block.

    The flag is read at *run* time (the executor threads it into the traced
    env per compile), so wrap the `exe.run(...)` calls — or simply call
    `program.set_amp(...)` once after building. Wrapping only the layer-
    construction code would be a no-op: the guard restores the previous
    setting on exit, before any run happens."""
    from .core.program import default_main_program

    prog = main_program or default_main_program()
    prev = prog.amp_dtype
    prog.set_amp(dtype)
    try:
        yield
    finally:
        prog.set_amp(prev)
