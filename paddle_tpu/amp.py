"""Automatic mixed precision (bf16 compute AND activations, f32 masters).

TPU analogue of the reference's half-precision support
(paddle/math/float16.h:70 and the fp16 GEMM paths in paddle/cuda): on the
MXU the fast matmul/conv datatype is bfloat16, which — unlike fp16 — keeps
fp32's exponent range, so no loss scaling is needed.

Design (v5e roofline-driven — see PERF.md): parameters, optimizer state,
batch-norm statistics and losses stay float32; MXU op *inputs* are cast to
the amp dtype AND their outputs stay in the amp dtype, so activations flow
through the network at 2 bytes/element. ResNet-scale models are
HBM-bandwidth-bound on TPU, so halving activation traffic — not the MXU
math itself — is most of AMP's win; casting each op's result back to f32
(the previous design) forfeited it. Where f32 masters meet bf16 activations
in an elementwise op (bias adds), the f32 side casts DOWN (`harmonize`),
overriding numpy's promote-to-f32 rule. Numerically-sensitive kernels
(batch_norm stats, softmax/log, losses) upcast internally and emit f32.

Enabled per-Program via `Program.set_amp("bfloat16")` after building it, or
the `pt.amp_guard()` context around the *run* calls; the executor reads the
setting at run time and threads it into the traced env under `@AMP@`,
where kernels pick it up via `cast_inputs`/`harmonize`.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

AMP_KEY = "@AMP@"

# --------------------------------------------------------------------------
# The dtype-policy table: which op families may drop precision.
#
# ONE place for the "may this site compute below f32?" judgment, consulted
# by BOTH precision passes — amp (bf16 compute, cast_inputs below) and the
# post-training int8 converter (quant/convert.py). Before this table the
# policy lived implicitly in which kernels called cast_inputs, and the
# quant pass would have had to re-derive (and could silently drift from)
# the batch_norm/softmax exclusions. Now a site is:
#
#   "low"    — MXU-bound, numerically tolerant: amp casts its inputs down,
#              and the quant converter may rewrite it to an int8 kernel
#              when it carries a persistable weight (LOW_PRECISION_OPS ∩
#              QUANTIZABLE_OPS);
#   "high"   — numerically sensitive (stats, exps/logs, losses): the
#              kernel upcasts internally, cast_inputs is a no-op even if
#              called, and the quant converter must leave it alone;
#   "follow" — dtype-transparent (elementwise glue, reshapes): follows
#              whatever dtype its inputs already carry via harmonize.
# --------------------------------------------------------------------------

# MXU ops whose kernels call cast_inputs: inputs drop to the amp dtype.
LOW_PRECISION_OPS = frozenset({
    "mul", "matmul", "conv2d", "conv2d_transpose", "fused_conv_bn",
    "flash_attention", "lookup_table",
})

# The subset of low-precision sites the int8 converter may rewrite: dense
# weight-carrying GEMMs with a quantized lowering (ops/quant_kernels.py).
# conv2d lowers through im2col+mul in this runtime, so the mul sites are
# the conv sites too; fused_conv_bn folds BN stats and must stay fp.
QUANTIZABLE_OPS = frozenset({"mul", "matmul"})

# Numerically sensitive: upcast internally, emit f32, never quantized.
# batch_norm/softmax live HERE and only here — amp and quant both read
# this set, so the exclusions cannot drift between the two passes.
HIGH_PRECISION_OPS = frozenset({
    "batch_norm", "layer_norm", "softmax", "log_softmax",
    "cross_entropy", "softmax_with_cross_entropy", "mean",
    "reduce_mean", "huber_loss", "smooth_l1", "squared_l2_norm",
    "l2_normalize", "exp", "log",
})


def precision_policy(op_type: str) -> str:
    """'low' | 'high' | 'follow' for one op type (see table above)."""
    if op_type in HIGH_PRECISION_OPS:
        return "high"
    if op_type in LOW_PRECISION_OPS:
        return "low"
    return "follow"


def cast_inputs(ctx, *arrays):
    """Cast float32 arrays to the program's amp dtype (no-op otherwise).

    Consults precision_policy: a kernel on the HIGH_PRECISION list gets
    its inputs back untouched even if it (mistakenly) calls this — the
    exclusion table, not the call site, decides who drops precision."""
    dtype = ctx.env.get(AMP_KEY)
    op = getattr(ctx, "op", None)
    if dtype is not None and op is not None \
            and precision_policy(op.type) == "high":
        dtype = None
    out = []
    for a in arrays:
        if (
            dtype is not None
            and hasattr(a, "dtype")
            and a.dtype == jnp.float32
        ):
            a = a.astype(dtype)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def harmonize(ctx, x, y):
    """AMP meeting rule for binary elementwise ops: when an f32 array (a
    master-weight bias/scale) meets an amp-dtype activation, cast the f32
    side DOWN instead of numpy-promoting the activation up — otherwise one
    bias add re-materializes the whole activation at 4 bytes/element."""
    dtype = ctx.env.get(AMP_KEY)
    if dtype is None:
        return x, y
    amp_dt = jnp.dtype(dtype)
    dx = getattr(x, "dtype", None)
    dy = getattr(y, "dtype", None)
    if dx == amp_dt and dy == jnp.float32:
        y = y.astype(amp_dt)
    elif dy == amp_dt and dx == jnp.float32:
        x = x.astype(amp_dt)
    return x, y


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16", main_program=None):
    """Enable amp on the current (or given) main program for the block.

    The flag is read at *run* time (the executor threads it into the traced
    env per compile), so wrap the `exe.run(...)` calls — or simply call
    `program.set_amp(...)` once after building. Wrapping only the layer-
    construction code would be a no-op: the guard restores the previous
    setting on exit, before any run happens."""
    from .core.program import default_main_program

    prog = main_program or default_main_program()
    prev = prog.amp_dtype
    prog.set_amp(dtype)
    try:
        yield
    finally:
        prog.set_amp(prev)
