"""Automatic mixed precision (bf16 compute AND activations, f32 masters).

TPU analogue of the reference's half-precision support
(paddle/math/float16.h:70 and the fp16 GEMM paths in paddle/cuda): on the
MXU the fast matmul/conv datatype is bfloat16, which — unlike fp16 — keeps
fp32's exponent range, so no loss scaling is needed.

Design (v5e roofline-driven — see PERF.md): parameters, optimizer state,
batch-norm statistics and losses stay float32; MXU op *inputs* are cast to
the amp dtype AND their outputs stay in the amp dtype, so activations flow
through the network at 2 bytes/element. ResNet-scale models are
HBM-bandwidth-bound on TPU, so halving activation traffic — not the MXU
math itself — is most of AMP's win; casting each op's result back to f32
(the previous design) forfeited it. Where f32 masters meet bf16 activations
in an elementwise op (bias adds), the f32 side casts DOWN (`harmonize`),
overriding numpy's promote-to-f32 rule. Numerically-sensitive kernels
(batch_norm stats, softmax/log, losses) upcast internally and emit f32.

Enabled per-Program via `Program.set_amp("bfloat16")` after building it, or
the `pt.amp_guard()` context around the *run* calls; the executor reads the
setting at run time and threads it into the traced env under `@AMP@`,
where kernels pick it up via `cast_inputs`/`harmonize`.
"""

from __future__ import annotations

import contextlib

import jax.numpy as jnp

AMP_KEY = "@AMP@"


def cast_inputs(ctx, *arrays):
    """Cast float32 arrays to the program's amp dtype (no-op otherwise)."""
    dtype = ctx.env.get(AMP_KEY)
    out = []
    for a in arrays:
        if (
            dtype is not None
            and hasattr(a, "dtype")
            and a.dtype == jnp.float32
        ):
            a = a.astype(dtype)
        out.append(a)
    return out[0] if len(out) == 1 else tuple(out)


def harmonize(ctx, x, y):
    """AMP meeting rule for binary elementwise ops: when an f32 array (a
    master-weight bias/scale) meets an amp-dtype activation, cast the f32
    side DOWN instead of numpy-promoting the activation up — otherwise one
    bias add re-materializes the whole activation at 4 bytes/element."""
    dtype = ctx.env.get(AMP_KEY)
    if dtype is None:
        return x, y
    amp_dt = jnp.dtype(dtype)
    dx = getattr(x, "dtype", None)
    dy = getattr(y, "dtype", None)
    if dx == amp_dt and dy == jnp.float32:
        y = y.astype(amp_dt)
    elif dy == amp_dt and dx == jnp.float32:
        x = x.astype(amp_dt)
    return x, y


@contextlib.contextmanager
def amp_guard(dtype: str = "bfloat16", main_program=None):
    """Enable amp on the current (or given) main program for the block.

    The flag is read at *run* time (the executor threads it into the traced
    env per compile), so wrap the `exe.run(...)` calls — or simply call
    `program.set_amp(...)` once after building. Wrapping only the layer-
    construction code would be a no-op: the guard restores the previous
    setting on exit, before any run happens."""
    from .core.program import default_main_program

    prog = main_program or default_main_program()
    prev = prog.amp_dtype
    prog.set_amp(dtype)
    try:
        yield
    finally:
        prog.set_amp(prev)
