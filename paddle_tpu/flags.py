"""Global flag registry (gflags parity).

Reference: paddle/utils/Flags.cpp:18-110 defines ~40 gflags consumed across
the runtime (use_gpu, trainer_count, beam_size, check_nan_inf behavior via
FLAGS_check_nan_inf in fluid executor.cc:60-72, log_period, ...). Here:
a typed registry with env-var overrides (`PT_FLAGS_<NAME>`) and an argv
parser, read through the `FLAGS` namespace object.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

_REGISTRY: Dict[str, dict] = {}


class _Flags:
    """Attribute access over the registry: `FLAGS.check_nan_inf`."""

    def __getattr__(self, name: str):
        try:
            return _REGISTRY[name]["value"]
        except KeyError:
            raise AttributeError(f"undefined flag {name!r}") from None

    def __setattr__(self, name: str, value):
        if name not in _REGISTRY:
            raise AttributeError(f"undefined flag {name!r}")
        _REGISTRY[name]["value"] = _coerce(value, _REGISTRY[name]["default"])


FLAGS = _Flags()


def _coerce(value, default):
    if isinstance(default, bool):
        if isinstance(value, str):
            return value.lower() in ("1", "true", "yes", "on")
        return bool(value)
    if default is None:
        return value
    return type(default)(value)


def define_flag(name: str, default, help: str = "") -> None:
    """Register a flag; env var PT_FLAGS_<NAME> overrides the default."""
    value = default
    env = os.environ.get(f"PT_FLAGS_{name.upper()}")
    if env is not None:
        value = _coerce(env, default)
    _REGISTRY[name] = {"default": default, "value": value, "help": help}


def parse_flags(argv: Optional[List[str]] = None) -> List[str]:
    """Parse --name=value / --name value pairs; returns unconsumed args."""
    argv = list(argv or [])
    rest: List[str] = []
    i = 0
    while i < len(argv):
        a = argv[i]
        if a.startswith("--") and "=" in a:
            name, val = a[2:].split("=", 1)
            name = name.replace("-", "_")
            if name in _REGISTRY:
                _set_parsed(name, val)
            else:
                rest.append(a)
            i += 1
            continue
        name = a[2:].replace("-", "_") if a.startswith("--") else None
        if name in _REGISTRY:
            if isinstance(_REGISTRY[name]["default"], bool):
                # gflags semantics: a bare boolean flag means True; never
                # consume the next token as its value
                setattr(FLAGS, name, True)
            elif i + 1 < len(argv) and not argv[i + 1].startswith("--"):
                _set_parsed(name, argv[i + 1])
                i += 1
            else:
                # no value available (end of argv, or the next token is
                # itself a flag) — leave it for the caller to reject
                rest.append(a)
        else:
            rest.append(a)
        i += 1
    return rest


def _set_parsed(name: str, val: str) -> None:
    """setattr with a flag-parse error message instead of a bare
    coercion ValueError."""
    try:
        setattr(FLAGS, name, val)
    except (TypeError, ValueError) as e:
        raise ValueError(
            f"invalid value {val!r} for flag --{name}: {e}"
        ) from None


def flags_help() -> str:
    lines = []
    for name in sorted(_REGISTRY):
        f = _REGISTRY[name]
        lines.append(f"--{name} (default {f['default']!r}): {f['help']}")
    return "\n".join(lines)


# -- core flags (the subset of Flags.cpp that survives the TPU redesign) ----
define_flag("check_nan_inf", False,
            "after each executor run, verify all persistable outputs are "
            "finite (reference: FLAGS_check_nan_inf, fluid executor.cc:60)")
define_flag("seed", 0, "global random seed (0 = nondeterministic)")
define_flag("step_guard", False,
            "trainer: enable the resilience.StepGuard default policy — "
            "skip non-finite steps, roll back to the last checkpoint "
            "after 3 consecutive, reduced-LR cool-down (the production "
            "counterpart of check_nan_inf's debug abort; README 'Fault "
            "tolerance')")
define_flag("log_period", 100, "trainer: log every N batches")
define_flag("sync_every", 0,
            "trainer: host-sync cadence of the pipelined step loop — "
            "materialize the on-device cost/metric accumulator every N "
            "steps (env: PT_FLAGS_SYNC_EVERY). 1 = the fully synchronous "
            "legacy loop (every step fences XLA's async dispatch queue); "
            "0 = auto: follow log_period, except a StepGuard-armed run "
            "keeps the exact per-step check unless a cadence is set "
            "explicitly (PERF.md 'Async dispatch and the host-sync "
            "budget')")
define_flag("scan_window", 0,
            "trainer: fuse K training steps into ONE jitted lax.scan "
            "program over a device-resident window of K stacked batches "
            "(env: PT_FLAGS_SCAN_WINDOW, CLI --scan_window). One host "
            "dispatch per window instead of K — removes, not just hides, "
            "the per-step dispatch floor PERF.md measures; the on-device "
            "metric accumulator and non-finite counter ride inside the "
            "scan carry and sync only at window edges. 0 = off (the "
            "per-step pipelined loop); requires an executor with "
            "scan_window_supported (the mesh ParallelExecutor is not, "
            "yet). Checkpoint cadence and StepGuard detection quantize "
            "to window boundaries (PERF.md 'Breaking the dispatch "
            "floor')")
define_flag("microbatches", 0,
            "pipeline executor: micro-batches M per global batch (CLI "
            "--microbatches, env: PT_FLAGS_MICROBATCHES). Each step "
            "splits the batch into M slices driven through the K-stage "
            "GPipe tick grid (paddle_tpu/pipeline); bubble fraction is "
            "(K-1)/(M+K-1), so more micro-batches amortize the "
            "fill/drain ticks. 0 = default 2x the stage count")
define_flag("pipeline_stages", 0,
            "pipeline executor: stage count K for `train --mesh` runs "
            "(CLI --pipeline_stages, env: PT_FLAGS_PIPELINE_STAGES). "
            "0 = follow the mesh's pp axis size (meshless: no "
            "pipelining). Must be a multiple of the pp axis; the "
            "program is cut at stage_boundary() markers when their "
            "count matches K-1, else auto-balanced by op cost")
define_flag("prefetch_to_device", 2,
            "trainer: default DevicePrefetcher queue depth — batch N+1's "
            "host->device transfer overlaps batch N's compute "
            "(DataProvider.h:375 double-buffer parity). 0 disables; "
            "Trainer.train(prefetch_to_device=...) overrides per run. "
            "Executors that own input placement (ParallelExecutor) "
            "ignore the default")
define_flag("show_param_stats_period", 0,
            "trainer: dump per-parameter value/gradient stats every N "
            "batches (reference: TrainerInternal.cpp:81-109); 0 = off")
define_flag("beam_size", 7, "default beam width for beam-search decode")
define_flag("save_dir", "./output",
            "conventional checkpoint directory; checkpointing itself is "
            "enabled per-run (CLI: train --save_dir; API: "
            "Trainer(checkpoint_config=...))")
define_flag("stats_period", 0,
            "trainer: emit a one-line runtime-stats log (step, "
            "dispatches, syncs, checkpoint commits, guard skips, trace "
            "drops — the paddle_tpu.stats logger) every N steps; the "
            "training-side view of the unified metrics registry that "
            "serving exposes on /metrics. 0 = off")
define_flag("dump_stats", False,
            "CLI train: print the unified metrics registry (Prometheus "
            "text) and the global timer table at exit — the dump-at-exit "
            "counterpart of scraping a serving process's /metrics")
define_flag("enable_timers", False,
            "accumulate REGISTER_TIMER-style stat timers "
            "(reference: utils/Stat.h, WITH_TIMER)")
define_flag("use_fused_rnn", True,
            "use pallas fused LSTM/GRU sequence kernels when shapes are "
            "eligible and the backend is TPU (reference: "
            "hl_lstm_parallel_forward fused CUDA kernels, "
            "cuda/include/hl_lstm.h:42). On by default: measured on v5e "
            "the fused train recurrence beats lax.scan 1.1-1.5x across "
            "T/B/H/dtype (benchmarks/lstm_kernel_microbench.json; round-1's "
            "contrary measurement was an artifact of the tunnel's d2h "
            "readback latency, see PERF.md)")
define_flag("fused_rnn_interpret", False,
            "testing only: allow the fused RNN kernels in pallas interpret "
            "mode on non-TPU backends")
define_flag("use_fused_conv", True,
            "build conv+BN+ReLU towers through the fused raw-stats protocol "
            "(pallas 1x1-conv kernels with BN prologue/epilogue — the "
            "reference's cuDNN fused-conv analogue, "
            "gserver/layers/CudnnConvBaseLayer.cpp); ineligible shapes and "
            "non-TPU backends fall back to identical-semantics jnp inside "
            "the same ops")
define_flag("fused_conv_dot_max_n", 0,
            "run the protocol's 1x1 convs as 2-D matmuls (dot or pallas "
            "per fused_conv_pallas) when rows N <= this. Default 0 (always "
            "the 4-D conv_general formulation): measured in-model on v5e "
            "(experiments/exp_dotstage.py) every threshold LOSES — dots in "
            "a conv tower force relayouts that outweigh the dot's "
            "isolated-chain win (exp_protomicro.py)")
define_flag("fused_conv_pallas", False,
            "use the hand-written Pallas fused kernel for eligible 2-D "
            "dispatches (requires fused_conv_dot_max_n > 0). Off by "
            "default: measured slower than XLA's own fusion of the same "
            "raw-stats formulation at every ResNet stage shape "
            "(experiments/exp_protomicro.py; see PERF.md round 4)")
define_flag("fused_conv_interpret", False,
            "testing only: allow the fused conv kernels in pallas interpret "
            "mode on non-TPU backends")
define_flag("use_fused_attention", True,
            "use the fused Bahdanau attention decoder kernels when shapes "
            "are eligible and the backend is TPU (ops/bahdanau_kernels.py "
            "— the hand-written-fused-kernel philosophy of the reference's "
            "hl_lstm.h:42 applied to the NMT decoder scan, 51% of that "
            "step)")
define_flag("fused_attention_interpret", False,
            "testing only: allow the fused attention decoder kernels in "
            "pallas interpret mode on non-TPU backends")
define_flag("fused_attention_seq_fwd", False,
            "run the fused decoder's FORWARD as one whole-sequence pallas "
            "kernel (grid (T, batch-tiles), hidden state in VMEM scratch "
            "— the fused-LSTM pattern extended with the attention "
            "prologue) instead of a per-step kernel inside lax.scan. "
            "Off by default: measured exactly neutral at the NMT config "
            "(256.1 vs 256.2k tok/s bs256 — the scan's per-step cost is "
            "device-side loop overhead that the kernel's T x batch-tile "
            "grid floor matches); kept tested for parts where dispatch "
            "economics differ")
define_flag("fused_attention_seq_bwd", False,
            "run the fused decoder's BACKWARD as one whole-sequence "
            "pallas kernel (grid (batch-tiles, T) walking timesteps "
            "newest-first, dh carry + d(enc_proj)/d(v) accumulators in "
            "f32 VMEM scratch) instead of a reverse lax.scan of per-step "
            "kernels + a separate phase-2 accumulation kernel. Off by "
            "default: measured 0.963x at the NMT config bf16 bs128 AND "
            "bs256 (310->299k, 316->305k tok/s, experiments/"
            "exp_megabwd.py) — it eliminates T per-step dispatches + the "
            "phase-2 dispatch + the [T,B,Sp] dsc HBM round-trip, but "
            "runs the GRU-cell backward matmuls at the 8-row batch tile "
            "(MXU ~8/128 utilized) where the scan path runs them at the "
            "full batch; the dispatch savings don't cover that. Kept "
            "parity-tested both ways (more accurate than the scan path "
            "vs f64 ground truth; see PERF.md round 5)")
define_flag("stacked_lstm_single_scan", False,
            "run the N-layer stacked_lstm op as ONE all-layers masked "
            "scan (the stacked_lstm2 lever generalized). Off by "
            "default: the book's [4H,4H] inter-layer concat-fc "
            "sequentializes in-scan where the default layer-by-layer "
            "formulation runs it as one [T*B,4H] batched matmul, and "
            "measured at the book config (hid=128 bs128, experiments/"
            "exp_stacked_book.py) neither formulation separates from "
            "the noise floor (0.79x-1.30x across identical runs — "
            "benchmarks/stacked_book.json), so the batched default "
            "stands on the structural argument")
define_flag("use_tuned_table", True,
            "consult the persistent tuned-config table (paddle_tpu.tune, "
            "`paddle_tpu tune`) for kernel tile/block choices before the "
            "analytic defaults. Lookups are keyed by device_kind, so a "
            "machine without tuned entries (or any non-TPU backend) "
            "deterministically falls back to the analytic models; set 0 "
            "to ignore tables entirely (A/B escape hatch)")
define_flag("tune_interpolate", True,
            "on a tuned-table miss, fall through to the nearest tuned "
            "entry for the same kernel/dtype/device by log-space shape "
            "distance (Autotuner v2 shape interpolation), re-validated "
            "against the target shape's legality model before use; the "
            "consult is recorded as source=interpolated in "
            "pt_tune_consults_total. Set 0 to restrict lookups to exact "
            "shape signatures (A/B escape hatch)")
define_flag("bn_bf16_stats", True,
            "batch_norm stats: square in the io dtype with f32 reduction "
            "accumulation instead of upcasting the activation first. "
            "Default on: +3% ResNet-50 img/s at bs128, +1.5% at bs256, "
            "neutral at bs512, same-process A/B (PERF.md r4, "
            "experiments/exp_bnbatch.py); set 0 to restore full-f32 "
            "stats math")
