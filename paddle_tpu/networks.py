"""Composite network builders.

Reference: python/paddle/trainer_config_helpers/networks.py — pre-assembled
building blocks (simple_img_conv_pool, img_conv_group, sequence_conv_pool,
text_conv_pool, simple_lstm, bidirectional_lstm, simple_gru) and fluid
nets.py (simple_img_conv_pool, img_conv_group, sequence_conv_pool,
glu, scaled_dot_product_attention).
"""

from __future__ import annotations

from typing import Optional, Sequence

from . import layers

__all__ = [
    "simple_img_conv_pool",
    "img_conv_group",
    "sequence_conv_pool",
    "text_conv_pool",
    "simple_lstm",
    "simple_gru",
    "bidirectional_lstm",
    "bidirectional_gru",
    "glu",
]


def simple_img_conv_pool(input, num_filters, filter_size, pool_size,
                         pool_stride=None, act="relu", pool_type="max",
                         param_attr=None, bias_attr=None):
    """conv2d + pool2d (reference networks.py simple_img_conv_pool /
    fluid nets.py:~27)."""
    conv = layers.conv2d(
        input, num_filters=num_filters, filter_size=filter_size, act=act,
        param_attr=param_attr, bias_attr=bias_attr,
    )
    return layers.pool2d(conv, pool_size=pool_size, pool_type=pool_type,
                         pool_stride=pool_stride or pool_size)


def img_conv_group(input, conv_num_filter: Sequence[int], conv_filter_size=3,
                   conv_act="relu", conv_with_batchnorm=False,
                   conv_batchnorm_drop_rate=0.0, pool_size=2, pool_stride=2,
                   pool_type="max", is_test=False):
    """Stacked conv(+bn+dropout) block followed by one pool — the VGG
    building block (reference networks.py img_conv_group / fluid nets.py)."""
    tmp = input
    n = len(conv_num_filter)
    for i, nf in enumerate(conv_num_filter):
        tmp = layers.conv2d(
            tmp, num_filters=nf, filter_size=conv_filter_size, padding=1,
            act=None if conv_with_batchnorm else conv_act,
        )
        if conv_with_batchnorm:
            tmp = layers.batch_norm(tmp, act=conv_act, is_test=is_test)
            if conv_batchnorm_drop_rate and i != n - 1:
                tmp = layers.dropout(tmp, conv_batchnorm_drop_rate,
                                     is_test=is_test)
    return layers.pool2d(tmp, pool_size=pool_size, pool_stride=pool_stride,
                         pool_type=pool_type)


def sequence_conv_pool(input, num_filters, filter_size, act="tanh",
                       pool_type="max", param_attr=None):
    """sequence_conv + sequence_pool (reference networks.py
    sequence_conv_pool — the text-conv recipe)."""
    conv = layers.sequence_conv(input, num_filters=num_filters,
                                filter_size=filter_size, act=act,
                                param_attr=param_attr)
    return layers.sequence_pool(conv, pool_type)


text_conv_pool = sequence_conv_pool


def simple_lstm(input, size, reverse=False, act="tanh", gate_act="sigmoid"):
    """fc projection + dynamic_lstm (reference networks.py simple_lstm:
    mixed full_matrix_projection feeding lstmemory)."""
    proj = layers.fc(input, size=size * 4, bias_attr=False)
    return layers.dynamic_lstm(proj, size=size * 4, is_reverse=reverse,
                               candidate_activation=act,
                               gate_activation=gate_act)


def simple_gru(input, size, reverse=False, act="tanh", gate_act="sigmoid"):
    proj = layers.fc(input, size=size * 3, bias_attr=False)
    return layers.dynamic_gru(proj, size=size, is_reverse=reverse,
                              candidate_activation=act,
                              gate_activation=gate_act)


def bidirectional_lstm(input, size, return_unit=False, act="tanh"):
    """Forward + backward simple_lstm (reference networks.py
    bidirectional_lstm): returns the per-token concat, or the [fwd, bwd]
    unit outputs unconcatenated when return_unit=True."""
    fwd = simple_lstm(input, size, reverse=False, act=act)
    bwd = simple_lstm(input, size, reverse=True, act=act)
    if return_unit:
        return [fwd, bwd]
    return layers.sequence_concat([fwd, bwd])


def bidirectional_gru(input, size, act="tanh"):
    fwd = simple_gru(input, size, reverse=False, act=act)
    bwd = simple_gru(input, size, reverse=True, act=act)
    return layers.sequence_concat([fwd, bwd])


def glu(input, dim=-1):
    """Gated linear unit: split in two along dim, a * sigmoid(b) (fluid
    nets.py glu)."""
    a, b = layers.split(input, 2, dim=dim)
    return layers.elementwise_mul(a, layers.sigmoid(b))
