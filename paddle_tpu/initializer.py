"""Parameter initializers.

Reference: python/paddle/v2/fluid/initializer.py (Constant/Uniform/Normal/
Xavier/MSRA) — each appends an init op to the *startup program*, executed
once by the Executor before training. The same pattern is kept: an
Initializer instance, given a parameter Variable, appends the matching
random/fill op to the startup program's block 0.
"""

from __future__ import annotations

import math

import numpy as np

from .core.program import Program, Variable, default_startup_program


class Initializer:
    def __call__(self, var: Variable, startup: Program = None):
        raise NotImplementedError


class ConstantInitializer(Initializer):
    def __init__(self, value: float = 0.0):
        self.value = value

    def __call__(self, var, startup=None):
        startup = startup or default_startup_program()
        b = startup.global_block()
        b.create_var(var.name, var.shape, var.dtype, persistable=True)
        b.append_op(
            "fill_constant",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "value": self.value,
                   "dtype": np.dtype(var.dtype).name},
        )


class UniformInitializer(Initializer):
    def __init__(self, low=-1.0, high=1.0, seed=0):
        self.low, self.high, self.seed = low, high, seed

    def __call__(self, var, startup=None):
        startup = startup or default_startup_program()
        b = startup.global_block()
        b.create_var(var.name, var.shape, var.dtype, persistable=True)
        b.append_op(
            "uniform_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "min": self.low, "max": self.high,
                   "dtype": np.dtype(var.dtype).name},
        )


class NormalInitializer(Initializer):
    def __init__(self, loc=0.0, scale=1.0, seed=0):
        self.loc, self.scale, self.seed = loc, scale, seed

    def __call__(self, var, startup=None):
        startup = startup or default_startup_program()
        b = startup.global_block()
        b.create_var(var.name, var.shape, var.dtype, persistable=True)
        b.append_op(
            "gaussian_random",
            outputs={"Out": [var.name]},
            attrs={"shape": list(var.shape), "mean": self.loc, "std": self.scale,
                   "dtype": np.dtype(var.dtype).name},
        )


def _fan_in_out(var: Variable):
    shape = var.shape
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    recept = int(np.prod(shape[2:]))
    return shape[1] * recept, shape[0] * recept


class XavierInitializer(Initializer):
    """Reference: fluid initializer.py XavierInitializer (Glorot)."""

    def __init__(self, uniform=True, fan_in=None, fan_out=None, seed=0):
        self.uniform, self.fan_in, self.fan_out = uniform, fan_in, fan_out

    def __call__(self, var, startup=None):
        fi, fo = _fan_in_out(var)
        fi = self.fan_in or fi
        fo = self.fan_out or fo
        if self.uniform:
            limit = math.sqrt(6.0 / (fi + fo))
            UniformInitializer(-limit, limit)(var, startup)
        else:
            std = math.sqrt(2.0 / (fi + fo))
            NormalInitializer(0.0, std)(var, startup)


class MSRAInitializer(Initializer):
    """Reference: fluid initializer.py MSRAInitializer (He)."""

    def __init__(self, uniform=True, fan_in=None, seed=0):
        self.uniform, self.fan_in = uniform, fan_in

    def __call__(self, var, startup=None):
        fi, _ = _fan_in_out(var)
        fi = self.fan_in or fi
        if self.uniform:
            limit = math.sqrt(6.0 / fi)
            UniformInitializer(-limit, limit)(var, startup)
        else:
            NormalInitializer(0.0, math.sqrt(2.0 / fi))(var, startup)


Constant = ConstantInitializer
Uniform = UniformInitializer
Normal = NormalInitializer
Xavier = XavierInitializer
MSRA = MSRAInitializer
