"""ParamAttr: per-parameter configuration.

Reference: python/paddle/v2/fluid/param_attr.py — name, initializer,
learning_rate multiplier, regularizer, trainable, gradient clip; same fields
here, consumed by LayerHelper.create_parameter (layers/helper.py).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Optional


@dataclass
class ParamAttr:
    name: Optional[str] = None
    initializer: Any = None
    learning_rate: float = 1.0
    regularizer: Any = None
    trainable: bool = True
    gradient_clip: Any = None

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        if arg is False:
            return False  # explicit "no parameter" (e.g. bias_attr=False)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")
