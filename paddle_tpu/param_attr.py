"""ParamAttr: per-parameter configuration.

Reference: python/paddle/v2/fluid/param_attr.py — name, initializer,
learning_rate multiplier, regularizer, trainable, gradient clip; same fields
here, consumed by LayerHelper.create_parameter (layers/helper.py). The
`update_hooks` field carries the Gen-1 ParameterAttribute(update_hooks=...)
seam (trainer_config_helpers/attrs.py HookAttribute →
paddle/parameter/ParameterUpdaterHook.cpp).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional


@dataclass
class StaticPruningHook:
    """Mask-based static sparsity maintained across optimizer updates.

    Reference: paddle/parameter/ParameterUpdaterHook.cpp:39
    (StaticPruningHook: `generateMask` sorts |w| at init time and zeroes
    the smallest `sparsity_ratio` fraction; `update()` re-applies the mask
    after every optimizer step so pruned weights stay zero). TPU design:
    the mask is a persistable `<param>@PRUNE_MASK` variable computed by a
    startup-program op from the freshly initialized weights, and an
    `apply_mask` op appended to the optimizer slice multiplies it back in
    each step — everything stays inside the jitted train step.
    """

    sparsity_ratio: float = 0.8

    def mask_name(self, param) -> str:
        return f"{param.name}@PRUNE_MASK"

    def append_startup(self, param, main_block, startup_program) -> None:
        """Create the mask variable and its init op (runs after the
        param's initializer op in the startup program)."""
        mask = main_block.create_var(
            self.mask_name(param), tuple(param.shape), param.dtype,
            persistable=True,
        )
        sb = startup_program.global_block()
        sb.create_var(mask.name, tuple(param.shape), param.dtype,
                      persistable=True)
        sb.append_op(
            "prune_mask_init",
            inputs={"Param": [param.name]},
            outputs={"Out": [mask.name]},
            attrs={"sparsity_ratio": float(self.sparsity_ratio)},
        )
        # Reference StaticPruningHook::init masks the param immediately
        # after generateMask (paraVec->dotMul(maskVec_)); without this the
        # first forward runs unpruned until the first optimizer step.
        sb.append_op(
            "apply_mask",
            inputs={"Param": [param.name], "Mask": [mask.name]},
            outputs={"ParamOut": [param.name]},
        )

    def append_update(self, helper, param) -> None:
        mask = helper.main_program.global_block().var(self.mask_name(param))
        helper.append_op(
            type="apply_mask",
            inputs={"Param": [param], "Mask": [mask]},
            outputs={"ParamOut": [param]},
        )


@dataclass
class ParamAttr:
    name: Optional[str] = None
    initializer: Any = None
    learning_rate: float = 1.0
    regularizer: Any = None
    trainable: bool = True
    gradient_clip: Any = None
    update_hooks: Optional[List[Any]] = None

    @staticmethod
    def derive(attr, base_default: str, suffix: str):
        """Per-weight attr for multi-parameter layers (MHA projections,
        stacked_lstm2 weights): keep every field of a caller-supplied
        attr but derive a distinct `{base}.{suffix}` name — passing the
        attr through unchanged would tie the weights into ONE shared
        parameter. attr=None derives from `base_default`; attr=False
        passes through (explicit "no parameter")."""
        import dataclasses

        if attr is None:
            return ParamAttr(name=f"{base_default}.{suffix}")
        if attr is False:
            return False
        attr = ParamAttr.to_attr(attr)
        base = attr.name or base_default
        return dataclasses.replace(attr, name=f"{base}.{suffix}")

    @staticmethod
    def to_attr(arg) -> "ParamAttr":
        if arg is None:
            return ParamAttr()
        if isinstance(arg, ParamAttr):
            return arg
        if isinstance(arg, str):
            return ParamAttr(name=arg)
        if isinstance(arg, (list, tuple)):
            return [ParamAttr.to_attr(a) for a in arg]
        if arg is False:
            return False  # explicit "no parameter" (e.g. bias_attr=False)
        raise TypeError(f"cannot convert {arg!r} to ParamAttr")
