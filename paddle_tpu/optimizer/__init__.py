"""Optimizer front-end: builds backward + update ops into the program.

Reference: python/paddle/v2/fluid/optimizer.py — Optimizer.minimize(:204)
appends backward ops then per-parameter update ops, managing accumulator
state; subclasses SGD/Momentum/Adagrad/Adam/Adamax/DecayedAdagrad (:228-528).
Gen-1 equivalents: paddle/parameter/FirstOrderOptimizer.h (9 optimizer
classes), OptimizerWithGradientClipping (:346), AverageOptimizer
(AverageOptimizer.h) and LearningRateScheduler (LearningRateScheduler.cpp).

All of those capabilities live here: 9+ optimizers, L1/L2 regularization
(regularizer.py), value/norm/global-norm gradient clipping, LR schedules,
and ModelAverage. State (moments, lr, step) is made of persistable vars so
checkpointing captures the full training state.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.backward import append_backward
from ..core.program import (
    Variable,
    default_main_program,
    default_startup_program,
    unique_name,
)
from ..initializer import ConstantInitializer
from ..layers.helper import LayerHelper

__all__ = [
    "SGD",
    "Momentum",
    "Adagrad",
    "Adadelta",
    "RMSProp",
    "DecayedAdagrad",
    "Adam",
    "Adamax",
    "Ftrl",
    "SGDOptimizer",
    "MomentumOptimizer",
    "AdagradOptimizer",
    "AdadeltaOptimizer",
    "RMSPropOptimizer",
    "DecayedAdagradOptimizer",
    "AdamOptimizer",
    "AdamaxOptimizer",
    "FtrlOptimizer",
    "GradientClipByValue",
    "GradientClipByNorm",
    "GradientClipByGlobalNorm",
    "ExponentialDecay",
    "NaturalExpDecay",
    "InverseTimeDecay",
    "PolynomialDecay",
    "PiecewiseDecay",
    "ModelAverage",
]


# ---------------------------------------------------------- LR schedules ---
class LRSchedule:
    """Reference: Gen-1 LearningRateScheduler.cpp policies ('exp', 'poly',

    'discexp', 'linear', 'pass_manual') and fluid learning-rate decay."""

    def __call__(self, step, base_lr):
        raise NotImplementedError


class ExponentialDecay(LRSchedule):
    def __init__(self, decay_steps, decay_rate, staircase=False):
        self.decay_steps, self.decay_rate, self.staircase = (
            decay_steps,
            decay_rate,
            staircase,
        )

    def __call__(self, step, base_lr):
        import jax.numpy as jnp

        p = step / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return base_lr * jnp.power(self.decay_rate, p)


class NaturalExpDecay(LRSchedule):
    def __init__(self, decay_steps, decay_rate, staircase=False):
        self.decay_steps, self.decay_rate, self.staircase = (
            decay_steps,
            decay_rate,
            staircase,
        )

    def __call__(self, step, base_lr):
        import jax.numpy as jnp

        p = step / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return base_lr * jnp.exp(-self.decay_rate * p)


class InverseTimeDecay(LRSchedule):
    def __init__(self, decay_steps, decay_rate, staircase=False):
        self.decay_steps, self.decay_rate, self.staircase = (
            decay_steps,
            decay_rate,
            staircase,
        )

    def __call__(self, step, base_lr):
        import jax.numpy as jnp

        p = step / self.decay_steps
        if self.staircase:
            p = jnp.floor(p)
        return base_lr / (1.0 + self.decay_rate * p)


class PolynomialDecay(LRSchedule):
    def __init__(self, decay_steps, end_learning_rate=1e-4, power=1.0, cycle=False):
        self.decay_steps = decay_steps
        self.end_lr = end_learning_rate
        self.power = power
        self.cycle = cycle

    def __call__(self, step, base_lr):
        import jax.numpy as jnp

        if self.cycle:
            div = jnp.maximum(jnp.ceil(step / self.decay_steps), 1.0)
            decay_steps = div * self.decay_steps
        else:
            decay_steps = self.decay_steps
            step = jnp.minimum(step, decay_steps)
        frac = jnp.power(1.0 - step / decay_steps, self.power)
        return (base_lr - self.end_lr) * frac + self.end_lr


class PiecewiseDecay(LRSchedule):
    def __init__(self, boundaries: Sequence[int], values: Sequence[float]):
        assert len(values) == len(boundaries) + 1
        self.boundaries, self.values = list(boundaries), list(values)

    def __call__(self, step, base_lr):
        import jax.numpy as jnp

        lr = jnp.asarray(self.values[-1], jnp.float32)
        for b, v in zip(reversed(self.boundaries), reversed(self.values[:-1])):
            lr = jnp.where(step < b, v, lr)
        return lr


# ------------------------------------------------------ gradient clipping --
class GradientClipByValue:
    """Reference: fluid clip.py ClipByValue."""

    def __init__(self, max, min=None):
        self.max = max
        self.min = -max if min is None else min

    def apply_one(self, helper: LayerHelper, param, grad):
        out = helper.create_tmp_variable(grad.dtype, grad.shape)
        helper.append_op(
            type="clip", inputs={"X": [grad]}, outputs={"Out": [out]},
            attrs={"min": self.min, "max": self.max},
        )
        return out


class GradientClipByNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def apply_one(self, helper, param, grad):
        out = helper.create_tmp_variable(grad.dtype, grad.shape)
        helper.append_op(
            type="clip_by_norm", inputs={"X": [grad]}, outputs={"Out": [out]},
            attrs={"max_norm": self.clip_norm},
        )
        return out


class GradientClipByGlobalNorm:
    def __init__(self, clip_norm):
        self.clip_norm = clip_norm

    def apply_all(self, helper, params_grads):
        grads = [g for _, g in params_grads]
        outs = [helper.create_tmp_variable(g.dtype, g.shape) for g in grads]
        helper.append_op(
            type="clip_by_global_norm",
            inputs={"X": grads},
            outputs={"Out": outs},
            attrs={"max_global_norm": self.clip_norm},
        )
        return [(p, o) for (p, _), o in zip(params_grads, outs)]


# -------------------------------------------------------------- Optimizer --
class Optimizer:
    op_type: str = ""

    def __init__(
        self,
        learning_rate: float = 0.001,
        regularization=None,
        grad_clip=None,
        lr_schedule: Optional[LRSchedule] = None,
        name: Optional[str] = None,
    ):
        self.base_lr = learning_rate
        self.regularization = regularization
        self.grad_clip = grad_clip
        self.lr_schedule = lr_schedule
        self.name = name or unique_name(self.op_type or "opt")
        self._accumulators: Dict[str, Dict[str, Variable]] = {}

    # -- state helpers ---------------------------------------------------
    def _add_accumulator(self, helper, name, param, fill=0.0, shape=None):
        acc_name = f"{self.name}.{name}.{param.name}"
        shape = shape if shape is not None else param.shape
        acc = helper.main_program.global_block().create_var(
            acc_name, tuple(shape), param.dtype, persistable=True
        )
        # marks the var as shardable optimizer state (ZeRO-style, see
        # parallel/data_parallel.py shard_optimizer_state)
        acc.is_optimizer_state = True
        # a param with its own sharding (e.g. mp-sharded embedding) passes
        # it to same-shaped accumulators — state stays co-located with the
        # param instead of being re-sharded over dp every step
        pspec = getattr(param, "sharding", None)
        if pspec is not None and tuple(shape) == tuple(param.shape):
            acc.sharding = pspec
        ConstantInitializer(fill)(acc, helper.startup_program)
        self._accumulators.setdefault(name, {})[param.name] = acc
        return acc

    def _lr_var(self, helper) -> Variable:
        """Create the (possibly scheduled) learning-rate variable + step."""
        block = helper.main_program.global_block()
        if self.lr_schedule is not None:
            step = block.create_var(
                f"{self.name}.step", (), np.float32, persistable=True
            )
            ConstantInitializer(0.0)(step, helper.startup_program)
            helper.append_op(
                type="increment", inputs={"X": [step]},
                outputs={"Out": [step]}, attrs={"step": 1.0},
            )
            sched_lr = helper.create_tmp_variable(np.float32, ())
            helper.append_op(
                type="lr_schedule",
                inputs={"Step": [step]},
                outputs={"Out": [sched_lr]},
                attrs={"schedule": self.lr_schedule, "base_lr": self.base_lr},
            )
            return sched_lr
        lr = block.create_var(f"{self.name}.lr", (), np.float32, persistable=True)
        ConstantInitializer(self.base_lr)(lr, helper.startup_program)
        return lr

    # -- per-optimizer hooks ---------------------------------------------
    def _create_accumulators(self, helper, params):
        pass

    def _append_update_op(self, helper, param, grad, lr):
        raise NotImplementedError

    # -- main entry -------------------------------------------------------
    def minimize(
        self,
        loss: Variable,
        startup_program=None,
        parameter_list=None,
        no_grad_set=None,
    ) -> List[Tuple[Variable, Variable]]:
        helper = LayerHelper(
            self.name,
            main_program=loss.block.program,
            startup_program=startup_program or default_startup_program(),
        )
        block = loss.block.program.global_block()
        opt_pass_start = len(block.ops)
        params_grads = append_backward(loss, parameter_list, no_grad_set)

        # regularization: grad += decay(param)  (fluid regularizer.py).
        # sparse_update params skip it: decay over the whole table would
        # densify the SelectedRows grad and defeat the row-wise update
        # (the reference's sparse remote updater likewise applies no decay
        # trainer-side — RemoteParameterUpdater.h:265)
        new_pg = []
        for p, g in params_grads:
            reg = p.regularizer or self.regularization
            if reg is not None and not getattr(p, "sparse_update", False):
                g = reg.append_decay(p, g)
            new_pg.append((p, g))
        params_grads = new_pg

        # clipping (fluid clip.py; Gen-1 OptimizerWithGradientClipping).
        # sparse_update grads pass through unclipped (same densification
        # rationale as regularization above)
        def _dense_pg():
            return [pg for pg in params_grads
                    if not getattr(pg[0], "sparse_update", False)]

        def _sparse_pg():
            return [pg for pg in params_grads
                    if getattr(pg[0], "sparse_update", False)]

        if isinstance(self.grad_clip, GradientClipByGlobalNorm):
            params_grads = (
                self.grad_clip.apply_all(helper, _dense_pg()) + _sparse_pg()
            )
        elif self.grad_clip is not None:
            params_grads = [
                (p, g) if getattr(p, "sparse_update", False)
                else (p, self.grad_clip.apply_one(helper, p, g))
                for p, g in params_grads
            ]
        else:
            pg2 = []
            for p, g in params_grads:
                if p.grad_clip is not None and \
                        not getattr(p, "sparse_update", False):
                    if isinstance(p.grad_clip, GradientClipByGlobalNorm):
                        raise ValueError(
                            "per-param global-norm clip unsupported; set it on the optimizer"
                        )
                    g = p.grad_clip.apply_one(helper, p, g)
                pg2.append((p, g))
            params_grads = pg2

        lr = self._lr_var(helper)
        self._create_accumulators(helper, [p for p, _ in params_grads])
        for p, g in params_grads:
            plr = lr
            mult = p.optimize_attr.get("learning_rate", 1.0)
            if mult != 1.0:
                plr = helper.create_tmp_variable(np.float32, ())
                helper.append_op(
                    type="scale", inputs={"X": [lr]}, outputs={"Out": [plr]},
                    attrs={"scale": mult},
                )
            self._append_update_op(helper, p, g, plr)
            # ParameterUpdaterHook (Gen-1 update_hooks, e.g. static
            # pruning): runs after the update so masked weights stay
            # masked whatever the optimizer wrote
            for hook in getattr(p, "update_hooks", None) or []:
                hook.append_update(helper, p)
        # mark the backward+update slice so io._prune_for_inference and
        # Program test-clones can drop it wholesale (fluid marks these with
        # op_role=Optimize; same idea)
        for op in block.ops[opt_pass_start:]:
            op.attrs["is_optimizer_op"] = True
        return params_grads


class SGDOptimizer(Optimizer):
    op_type = "sgd"

    def _append_update_op(self, helper, param, grad, lr):
        helper.append_op(
            type="sgd",
            inputs={"Param": [param], "Grad": [grad], "LearningRate": [lr]},
            outputs={"ParamOut": [param]},
        )


class MomentumOptimizer(Optimizer):
    op_type = "momentum"

    def __init__(self, learning_rate=0.001, momentum=0.9, use_nesterov=False, **kw):
        super().__init__(learning_rate, **kw)
        self.momentum = momentum
        self.use_nesterov = use_nesterov

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "velocity", p)

    def _append_update_op(self, helper, param, grad, lr):
        v = self._accumulators["velocity"][param.name]
        helper.append_op(
            type="momentum",
            inputs={"Param": [param], "Grad": [grad], "Velocity": [v],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [param], "VelocityOut": [v]},
            attrs={"mu": self.momentum, "use_nesterov": self.use_nesterov},
        )


class AdagradOptimizer(Optimizer):
    op_type = "adagrad"

    def __init__(self, learning_rate=0.001, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.epsilon = epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "moment", p)

    def _append_update_op(self, helper, param, grad, lr):
        m = self._accumulators["moment"][param.name]
        helper.append_op(
            type="adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [param], "MomentOut": [m]},
            attrs={"epsilon": self.epsilon},
        )


class AdadeltaOptimizer(Optimizer):
    op_type = "adadelta"

    def __init__(self, learning_rate=1.0, rho=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.rho, self.epsilon = rho, epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "avg_squared_grad", p)
            self._add_accumulator(helper, "avg_squared_update", p)

    def _append_update_op(self, helper, param, grad, lr):
        g2 = self._accumulators["avg_squared_grad"][param.name]
        u2 = self._accumulators["avg_squared_update"][param.name]
        helper.append_op(
            type="adadelta",
            inputs={"Param": [param], "Grad": [grad],
                    "AvgSquaredGrad": [g2], "AvgSquaredUpdate": [u2],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [param]},
            attrs={"rho": self.rho, "epsilon": self.epsilon},
        )


class RMSPropOptimizer(Optimizer):
    op_type = "rmsprop"

    def __init__(self, learning_rate=0.001, decay=0.95, momentum=0.0, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.momentum, self.epsilon = decay, momentum, epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "mean_square", p)
            self._add_accumulator(helper, "moment", p)

    def _append_update_op(self, helper, param, grad, lr):
        ms = self._accumulators["mean_square"][param.name]
        mom = self._accumulators["moment"][param.name]
        helper.append_op(
            type="rmsprop",
            inputs={"Param": [param], "Grad": [grad], "MeanSquare": [ms],
                    "Moment": [mom], "LearningRate": [lr]},
            outputs={"ParamOut": [param]},
            attrs={"decay": self.decay, "momentum": self.momentum,
                   "epsilon": self.epsilon},
        )


class DecayedAdagradOptimizer(Optimizer):
    op_type = "decayed_adagrad"

    def __init__(self, learning_rate=0.001, decay=0.95, epsilon=1e-6, **kw):
        super().__init__(learning_rate, **kw)
        self.decay, self.epsilon = decay, epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "moment", p)

    def _append_update_op(self, helper, param, grad, lr):
        m = self._accumulators["moment"][param.name]
        helper.append_op(
            type="decayed_adagrad",
            inputs={"Param": [param], "Grad": [grad], "Moment": [m],
                    "LearningRate": [lr]},
            outputs={"ParamOut": [param]},
            attrs={"decay": self.decay, "epsilon": self.epsilon},
        )


class AdamOptimizer(Optimizer):
    op_type = "adam"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "moment1", p)
            self._add_accumulator(helper, "moment2", p)
            self._add_accumulator(helper, "beta1_pow", p, fill=self.beta1, shape=())
            self._add_accumulator(helper, "beta2_pow", p, fill=self.beta2, shape=())

    def _append_update_op(self, helper, param, grad, lr):
        a = self._accumulators
        helper.append_op(
            type="adam",
            inputs={
                "Param": [param], "Grad": [grad], "LearningRate": [lr],
                "Moment1": [a["moment1"][param.name]],
                "Moment2": [a["moment2"][param.name]],
                "Beta1Pow": [a["beta1_pow"][param.name]],
                "Beta2Pow": [a["beta2_pow"][param.name]],
            },
            outputs={"ParamOut": [param]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon},
        )


class AdamaxOptimizer(Optimizer):
    op_type = "adamax"

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8, **kw):
        super().__init__(learning_rate, **kw)
        self.beta1, self.beta2, self.epsilon = beta1, beta2, epsilon

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "moment", p)
            self._add_accumulator(helper, "inf_norm", p)
            self._add_accumulator(helper, "beta1_pow", p, fill=self.beta1, shape=())

    def _append_update_op(self, helper, param, grad, lr):
        a = self._accumulators
        helper.append_op(
            type="adamax",
            inputs={
                "Param": [param], "Grad": [grad], "LearningRate": [lr],
                "Moment": [a["moment"][param.name]],
                "InfNorm": [a["inf_norm"][param.name]],
                "Beta1Pow": [a["beta1_pow"][param.name]],
            },
            outputs={"ParamOut": [param]},
            attrs={"beta1": self.beta1, "beta2": self.beta2,
                   "epsilon": self.epsilon},
        )


class FtrlOptimizer(Optimizer):
    op_type = "ftrl"

    def __init__(self, learning_rate=0.001, l1=0.0, l2=0.0, lr_power=-0.5, **kw):
        super().__init__(learning_rate, **kw)
        self.l1, self.l2, self.lr_power = l1, l2, lr_power

    def _create_accumulators(self, helper, params):
        for p in params:
            self._add_accumulator(helper, "squared", p)
            self._add_accumulator(helper, "linear", p)

    def _append_update_op(self, helper, param, grad, lr):
        a = self._accumulators
        helper.append_op(
            type="ftrl",
            inputs={
                "Param": [param], "Grad": [grad], "LearningRate": [lr],
                "SquaredAccumulator": [a["squared"][param.name]],
                "LinearAccumulator": [a["linear"][param.name]],
            },
            outputs={"ParamOut": [param]},
            attrs={"l1": self.l1, "l2": self.l2, "lr_power": self.lr_power},
        )


# -------------------------------------------------------- model averaging --
class ModelAverage:
    """Parameter averaging (reference: paddle/parameter/AverageOptimizer.h;

    v1 trainer_config_helpers optimizers.py ModelAverage). Keeps a sliding
    window of parameter values via a restarting accumulator: the window
    length is clamp(average_window_rate * num_updates, min_average_window,
    max_average_window), matching the reference's semantics. `apply()`
    swaps averaged values in, `restore()` swaps them back — for eval."""

    def __init__(
        self,
        average_window_rate: float = 0.15,
        min_average_window: int = 10000,
        max_average_window: int = 10**9,
        program=None,
    ):
        self.program = program or default_main_program()
        helper = LayerHelper("model_average", main_program=self.program)
        self.pairs = []
        attrs = {
            "average_window": average_window_rate,
            "min_average_window": min_average_window,
            "max_average_window": max_average_window,
        }
        for p in self.program.parameters():
            gb = self.program.global_block()
            s = gb.create_var(f"@AVG@.{p.name}", p.shape, p.dtype, persistable=True)
            ConstantInitializer(0.0)(s, helper.startup_program)
            n = gb.create_var(f"@AVG_N@.{p.name}", (), np.float32, persistable=True)
            ConstantInitializer(0.0)(n, helper.startup_program)
            t = gb.create_var(f"@AVG_T@.{p.name}", (), np.float32, persistable=True)
            ConstantInitializer(0.0)(t, helper.startup_program)
            helper.append_op(
                type="average_accumulate",
                inputs={"Param": [p], "Sum": [s], "Count": [n], "Total": [t]},
                outputs={},
                attrs=attrs,
            )
            self.pairs.append((p, s, n))

    def apply(self, executor, scope=None):
        from ..core.executor import global_scope

        scope = scope or global_scope()
        self._backup = {}
        for p, s, n in self.pairs:
            self._backup[p.name] = scope.get(p.name)
            cnt = max(float(np.asarray(scope.get(n.name))), 1.0)
            scope.set(p.name, np.asarray(scope.get(s.name)) / cnt)

    def restore(self, executor, scope=None):
        from ..core.executor import global_scope

        scope = scope or global_scope()
        for name, val in self._backup.items():
            scope.set(name, val)


# convenient aliases (v2 API names)
SGD = SGDOptimizer
Momentum = MomentumOptimizer
Adagrad = AdagradOptimizer
Adadelta = AdadeltaOptimizer
RMSProp = RMSPropOptimizer
DecayedAdagrad = DecayedAdagradOptimizer
Adam = AdamOptimizer
Adamax = AdamaxOptimizer
Ftrl = FtrlOptimizer
