"""Optimizer update op kernels.

Reference: paddle/operators/{sgd_op,momentum_op,adagrad_op,adadelta_op,
rmsprop_op,decayed_adagrad_op,adam_op,adamax_op,ftrl_op,proximal_gd_op,
proximal_adagrad_op}.cc — the 10+ Fluid optimizer ops — and the Gen-1
equivalents in paddle/parameter/FirstOrderOptimizer.h:24-346. Update math
follows the reference's kernels exactly; each op updates the parameter (and
its moment persistables) in place in the env, so the new values flow back to
the Scope after the jitted step.
"""

from __future__ import annotations

import jax.numpy as jnp

from ..core.registry import register_op
from ..core.sparse import SelectedRows


def _write(ctx, slot_in, value):
    """Write back through an in/out slot pair (ParamOut etc.)."""
    name = ctx.op.inputs[slot_in][0]
    ctx.env[name] = value
    out_slot = slot_in + "Out"
    if ctx.has_output(out_slot):
        ctx.set_output(out_slot, value)


def _lr(ctx):
    lr = ctx.input("LearningRate")
    return jnp.reshape(lr, ()) if hasattr(lr, "shape") else lr


@register_op("sgd")
def sgd_kernel(ctx):
    """Reference: sgd_op.cc — p -= lr * g. SelectedRows grads (embedding
    is_sparse) apply as a row-wise scatter-add, touching only gathered rows
    (sgd_op.cc's SelectedRows branch / SparseRowMatrix sgdUpdate)."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    if isinstance(g, SelectedRows):
        # duplicate rows accumulate — scatter-add is linear, no dedup needed
        _write(ctx, "Param",
               p.at[g.rows].add(-_lr(ctx) * g.values, mode="drop"))
        return
    _write(ctx, "Param", p - _lr(ctx) * g)


@register_op("momentum")
def momentum_kernel(ctx):
    """Reference: momentum_op.cc — supports use_nesterov."""
    p, g, v = ctx.input("Param"), ctx.input("Grad"), ctx.input("Velocity")
    mu = ctx.attr("mu", 0.9)
    lr = _lr(ctx)
    if isinstance(g, SelectedRows):
        # lazy momentum: decay + step only on touched rows
        rows, vals = g.dedup()
        v_rows = mu * v[rows] + vals
        if ctx.attr("use_nesterov", False):
            step = -(vals + mu * v_rows) * lr
        else:
            step = -lr * v_rows
        _write(ctx, "Velocity", v.at[rows].set(v_rows, mode="drop"))
        _write(ctx, "Param", p.at[rows].add(step, mode="drop"))
        return
    v_new = mu * v + g
    if ctx.attr("use_nesterov", False):
        p_new = p - (g + mu * v_new) * lr
    else:
        p_new = p - lr * v_new
    _write(ctx, "Velocity", v_new)
    _write(ctx, "Param", p_new)


@register_op("adagrad")
def adagrad_kernel(ctx):
    """Reference: adagrad_op.cc — moment += g²; p -= lr*g/(√moment+ε).

    SelectedRows grads: lazy row-wise update (adagrad_op.cc SelectedRows
    branch merges duplicate rows first — dedup() here; untouched rows'
    moments stay untouched, matching the reference's sparse semantics)."""
    p, g, m = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    eps = ctx.attr("epsilon", 1e-6)
    if isinstance(g, SelectedRows):
        rows, vals = g.dedup()
        m_rows = m[rows] + jnp.square(vals)
        upd = -_lr(ctx) * vals / (jnp.sqrt(m_rows) + eps)
        _write(ctx, "Moment", m.at[rows].set(m_rows, mode="drop"))
        _write(ctx, "Param", p.at[rows].add(upd, mode="drop"))
        return
    m_new = m + jnp.square(g)
    p_new = p - _lr(ctx) * g / (jnp.sqrt(m_new) + eps)
    _write(ctx, "Moment", m_new)
    _write(ctx, "Param", p_new)


@register_op("adadelta")
def adadelta_kernel(ctx):
    """Reference: adadelta_op.cc."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    avg_sq_g = ctx.input("AvgSquaredGrad")
    avg_sq_u = ctx.input("AvgSquaredUpdate")
    rho = ctx.attr("rho", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    g2 = rho * avg_sq_g + (1 - rho) * jnp.square(g)
    update = -jnp.sqrt((avg_sq_u + eps) / (g2 + eps)) * g
    u2 = rho * avg_sq_u + (1 - rho) * jnp.square(update)
    _write(ctx, "AvgSquaredGrad", g2)
    _write(ctx, "AvgSquaredUpdate", u2)
    _write(ctx, "Param", p + update)


@register_op("rmsprop")
def rmsprop_kernel(ctx):
    """Reference: rmsprop_op.cc — with momentum term."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    ms, mom = ctx.input("MeanSquare"), ctx.input("Moment")
    rho = ctx.attr("decay", 0.9)
    mu = ctx.attr("momentum", 0.0)
    eps = ctx.attr("epsilon", 1e-6)
    lr = _lr(ctx)
    ms_new = rho * ms + (1 - rho) * jnp.square(g)
    mom_new = mu * mom + lr * g / jnp.sqrt(ms_new + eps)
    _write(ctx, "MeanSquare", ms_new)
    _write(ctx, "Moment", mom_new)
    _write(ctx, "Param", p - mom_new)


@register_op("decayed_adagrad")
def decayed_adagrad_kernel(ctx):
    """Reference: decayed_adagrad_op.cc."""
    p, g, m = ctx.input("Param"), ctx.input("Grad"), ctx.input("Moment")
    decay = ctx.attr("decay", 0.95)
    eps = ctx.attr("epsilon", 1e-6)
    m_new = decay * m + (1 - decay) * jnp.square(g)
    _write(ctx, "Moment", m_new)
    _write(ctx, "Param", p - _lr(ctx) * g / (jnp.sqrt(m_new) + eps))


@register_op("adam")
def adam_kernel(ctx):
    """Reference: adam_op.cc — bias-corrected via Beta1Pow/Beta2Pow state."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    m1, m2 = ctx.input("Moment1"), ctx.input("Moment2")
    b1p, b2p = ctx.input("Beta1Pow"), ctx.input("Beta2Pow")
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    if isinstance(g, SelectedRows):
        # lazy adam (adam_op.cc SelectedRows branch): moments and step only
        # on touched rows; Beta*Pow still advance globally per step
        rows, vals = g.dedup()
        m1r = b1 * m1[rows] + (1 - b1) * vals
        m2r = b2 * m2[rows] + (1 - b2) * jnp.square(vals)
        lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
        step = -lr_t * m1r / (jnp.sqrt(m2r) + eps)
        _write(ctx, "Moment1", m1.at[rows].set(m1r, mode="drop"))
        _write(ctx, "Moment2", m2.at[rows].set(m2r, mode="drop"))
        _write(ctx, "Beta1Pow", b1p * b1)
        _write(ctx, "Beta2Pow", b2p * b2)
        _write(ctx, "Param", p.at[rows].add(step, mode="drop"))
        return
    m1n = b1 * m1 + (1 - b1) * g
    m2n = b2 * m2 + (1 - b2) * jnp.square(g)
    lr_t = lr * jnp.sqrt(1 - b2p) / (1 - b1p)
    p_new = p - lr_t * m1n / (jnp.sqrt(m2n) + eps)
    _write(ctx, "Moment1", m1n)
    _write(ctx, "Moment2", m2n)
    _write(ctx, "Beta1Pow", b1p * b1)
    _write(ctx, "Beta2Pow", b2p * b2)
    _write(ctx, "Param", p_new)


@register_op("adamax")
def adamax_kernel(ctx):
    """Reference: adamax_op.cc."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    m, inf = ctx.input("Moment"), ctx.input("InfNorm")
    b1p = ctx.input("Beta1Pow")
    b1 = ctx.attr("beta1", 0.9)
    b2 = ctx.attr("beta2", 0.999)
    eps = ctx.attr("epsilon", 1e-8)
    lr = _lr(ctx)
    m_new = b1 * m + (1 - b1) * g
    inf_new = jnp.maximum(b2 * inf, jnp.abs(g) + eps)
    p_new = p - (lr / (1 - b1p)) * m_new / inf_new
    _write(ctx, "Moment", m_new)
    _write(ctx, "InfNorm", inf_new)
    _write(ctx, "Beta1Pow", b1p * b1)
    _write(ctx, "Param", p_new)


@register_op("ftrl")
def ftrl_kernel(ctx):
    """Reference: ftrl_op.cc."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    sq, lin = ctx.input("SquaredAccumulator"), ctx.input("LinearAccumulator")
    l1 = ctx.attr("l1", 0.0)
    l2 = ctx.attr("l2", 0.0)
    lr_power = ctx.attr("lr_power", -0.5)
    lr = _lr(ctx)
    new_sq = sq + jnp.square(g)
    if lr_power == -0.5:
        sigma = (jnp.sqrt(new_sq) - jnp.sqrt(sq)) / lr
    else:
        sigma = (jnp.power(new_sq, -lr_power) - jnp.power(sq, -lr_power)) / lr
    new_lin = lin + g - sigma * p
    if lr_power == -0.5:
        denom = jnp.sqrt(new_sq) / lr + 2 * l2
    else:
        denom = jnp.power(new_sq, -lr_power) / lr + 2 * l2
    pre_shrink = (l1 * jnp.sign(new_lin) - new_lin) / denom
    p_new = jnp.where(jnp.abs(new_lin) > l1, pre_shrink, 0.0)
    _write(ctx, "SquaredAccumulator", new_sq)
    _write(ctx, "LinearAccumulator", new_lin)
    _write(ctx, "Param", p_new)


@register_op("average_accumulate")
def average_accumulate_kernel(ctx):
    """Sliding-window parameter accumulation for ModelAverage.

    Reference: paddle/parameter/AverageOptimizer.h — the accumulator
    restarts once the window (clamp(rate * num_updates, min_window,
    max_window)) is exceeded, so apply() averages only recent values."""
    p = ctx.input("Param")
    s, n, t = ctx.input("Sum"), ctx.input("Count"), ctx.input("Total")
    rate = ctx.attr("average_window", 0.15)
    min_w = ctx.attr("min_average_window", 10000)
    max_w = ctx.attr("max_average_window", 10**9)
    t_new = t + 1.0
    window = jnp.clip(rate * t_new, min_w, max_w)
    restart = (n + 1.0) > window
    s_new = jnp.where(restart, p, s + p)
    n_new = jnp.where(restart, 1.0, n + 1.0)
    ctx.env[ctx.op.inputs["Sum"][0]] = s_new
    ctx.env[ctx.op.inputs["Count"][0]] = n_new
    ctx.env[ctx.op.inputs["Total"][0]] = t_new


@register_op("lr_schedule")
def lr_schedule_kernel(ctx):
    """Computes the scheduled learning rate from the global step.

    Reference: Gen-1 LearningRateScheduler.cpp policies; fluid lr decay.
    The `schedule` attr is an optimizer.LRSchedule instance applied at
    trace time — the schedule math becomes part of the XLA program."""
    step = ctx.input("Step")
    sched = ctx.attr("schedule")
    ctx.set_output("Out", sched(step, ctx.attr("base_lr")))


@register_op("proximal_gd")
def proximal_gd_kernel(ctx):
    """Reference: proximal_gd_op.cc — l1/l2-regularized SGD step."""
    p, g = ctx.input("Param"), ctx.input("Grad")
    l1, l2 = ctx.attr("l1", 0.0), ctx.attr("l2", 0.0)
    lr = _lr(ctx)
    prox = p - lr * g
    p_new = (
        jnp.sign(prox) * jnp.maximum(jnp.abs(prox) - lr * l1, 0.0)
        / (1.0 + lr * l2)
    )
    _write(ctx, "Param", p_new)


@register_op("prune_mask_init")
def prune_mask_init_kernel(ctx):
    """Reference: ParameterUpdaterHook.cpp:105 StaticPruningHook::
    generateMask — sort |w|, zero the smallest sparsity_ratio fraction.
    Runs once in the startup program, after the param's initializer."""
    w = ctx.input("Param")
    ratio = float(ctx.attr("sparsity_ratio", 0.8))
    flat = jnp.abs(w).reshape(-1)
    k = int(round(ratio * flat.size))
    if k <= 0:
        ctx.set_output("Out", jnp.ones_like(w))
        return
    # Exactly-k selection by sorted index (the reference partial_sorts
    # indices): a |w| > threshold compare would also prune every value
    # tied at the threshold — a constant-magnitude init would mask to
    # all-zero.
    order = jnp.argsort(flat)
    mask = jnp.ones(flat.shape, w.dtype).at[order[:k]].set(0)
    ctx.set_output("Out", mask.reshape(w.shape))


@register_op("apply_mask")
def apply_mask_kernel(ctx):
    """Reference: ParameterUpdaterHook.cpp:86 StaticPruningHook::update —
    re-apply the static mask after every optimizer step."""
    p, m = ctx.input("Param"), ctx.input("Mask")
    _write(ctx, "Param", p * m)
