"""Fused 1x1-conv + BatchNorm Pallas ops (the cuDNN-fused-path analogue).

Reference: the reference never runs its conv hot path as naive composed
ops — conv layers go through cuDNN's fused machinery
(paddle/gserver/layers/CudnnConvBaseLayer.cpp, paddle/cuda/src/
hl_cuda_cudnn.cc). On TPU the XLA formulation of train-mode BN is
irreducibly extra HBM passes over the conv output (stats reduce +
normalize read/write — measured at ~34% of the ResNet-50 step, PERF.md),
so the fused path here rewrites each eligible 1x1 conv as a Pallas
matmul kernel that
  - applies the PREVIOUS BN (normalize+scale+shift+ReLU) in its prologue,
    consuming the raw (pre-BN) activation straight from HBM, and
  - accumulates this conv's OWN output per-channel sum/sumsq in its
    epilogue (VMEM f32 accumulators across row tiles),
so each activation is read once and written once — BN statistics come out
of the conv for free, and the normalize of layer k happens inside layer
k+1's operand read. Op-level protocol (see layers/nn.py fused_conv_bn /
bn_apply / bn_stats and models/image.py _bottleneck):

  raw_k, mean_k, inv_k = fused_conv_bn(raw_{k-1}, stats_{k-1}, W_k)
  ...consumers of the normalized activation call bn_apply (one fused
  XLA elementwise pass) or feed the raw+stats pair to the next fused op.

Training: pallas_call has no automatic VJP, so the fused forward is a
jax.custom_vjp whose backward is the standard conv+BN-prologue chain
composed from XLA matmuls and (fused-by-XLA) elementwise/reduce passes —
recomputing the prologue from the saved raw input instead of saving the
normalized activation (remat: one VPU pass buys an HBM tensor).

Eligibility mirrors the fused-RNN dispatch (pallas_kernels.py): TPU
backend (or the interpret test flag), bf16/f32 io, channels that tile the
128-wide lanes, rows divisible into MXU-sized blocks, and a VMEM model
that keeps the working set under the scoped budget. Ineligible shapes run
an identical-semantics jnp fallback (same raw+stats dataflow).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .. import amp
from ..core.registry import register_op


def _block_rows(n: int, cin: int, cout: int, itemsize: int) -> int:
    """Row block for the fused kernel. Legality (divides n, tiles the
    8-row sublane, working set — x/y blocks double-buffered by the
    pipeline machinery, full weight panel, f32 accumulators — under the
    VMEM budget) lives in tune/space.py `conv_rows_legal`, shared with
    the autotuner's candidate generator. Consult order: forced/tuned
    override for this (n, cin, cout, dtype, device) -> the analytic
    default (largest legal block <= 1024). Returns 0 when no eligible
    block exists."""
    from ..tune import overrides as tune_overrides
    from ..tune.cache import ITEMSIZE_DTYPE
    from ..tune.space import CONV_ROW_BLOCKS, conv_rows_legal

    ov = tune_overrides.lookup(
        "fused_conv", {"n": n, "cin": cin, "cout": cout},
        ITEMSIZE_DTYPE.get(itemsize, f"itemsize{itemsize}"))
    if ov is not None:
        b = int(ov.config.get("block_rows", 0))
        if b and conv_rows_legal(b, n, cin, cout, itemsize):
            return b
        if ov.source in ("forced", "env"):
            import warnings

            warnings.warn(
                f"forced fused-conv block_rows={b} fails eligibility at "
                f"n={n} cin={cin} cout={cout}; fused conv kernel "
                f"DISABLED for this shape", stacklevel=2)
            return 0
    for b in CONV_ROW_BLOCKS:
        if conv_rows_legal(b, n, cin, cout, itemsize):
            return b
    return 0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _backend_ok() -> bool:
    from .pallas_kernels import backend_ok

    return backend_ok("fused_conv_interpret")


def fused_conv_eligible(n: int, cin: int, cout: int, dtype) -> bool:
    itemsize = jnp.dtype(dtype).itemsize
    return (
        dtype in (jnp.bfloat16, jnp.float32)
        and cin % 128 == 0
        and cout % 128 == 0
        and _block_rows(n, cin, cout, itemsize) > 0
        and _backend_ok()
    )


# ------------------------------------------------------------- the kernel --
def _fused_kernel(x_ref, w_ref, pm_ref, pi_ref, ps_ref, pb_ref,
                  y_ref, s_ref, sq_ref, acc_s, acc_q,
                  *, prologue: bool, prologue_relu: bool):
    i = pl.program_id(0)

    @pl.when(i == 0)
    def _():
        acc_s[:] = jnp.zeros_like(acc_s)
        acc_q[:] = jnp.zeros_like(acc_q)

    x = x_ref[:]
    if prologue:
        xh = (x.astype(jnp.float32) - pm_ref[:]) * (pi_ref[:] * ps_ref[:]) \
            + pb_ref[:]
        if prologue_relu:
            xh = jnp.maximum(xh, 0.0)
        xn = xh.astype(x.dtype)
    else:
        xn = x
    y = jnp.dot(xn, w_ref[:], preferred_element_type=jnp.float32)
    yq = y.astype(y_ref.dtype)
    y_ref[:] = yq
    # stats from the QUANTIZED output (what consumers read back from HBM)
    # so the fused formulation matches batch_norm's stats-of-stored-y
    yf = yq.astype(jnp.float32)
    acc_s[:] = acc_s[:] + jnp.sum(yf, axis=0, keepdims=True)
    acc_q[:] = acc_q[:] + jnp.sum(yf * yf, axis=0, keepdims=True)

    @pl.when(i == pl.num_programs(0) - 1)
    def _():
        s_ref[:] = acc_s[:]
        sq_ref[:] = acc_q[:]


def _pallas_fwd(x, w, pm, pi, ps, pb, prologue, prologue_relu, interpret):
    n, cin = x.shape
    cout = w.shape[1]
    b = _block_rows(n, cin, cout, x.dtype.itemsize)
    y, s, sq = pl.pallas_call(
        functools.partial(_fused_kernel, prologue=prologue,
                          prologue_relu=prologue_relu),
        grid=(n // b,),
        in_specs=[
            pl.BlockSpec((b, cin), lambda i: (i, 0)),
            pl.BlockSpec((cin, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
            pl.BlockSpec((1, cin), lambda i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((b, cout), lambda i: (i, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
            pl.BlockSpec((1, cout), lambda i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((n, cout), x.dtype),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
            jax.ShapeDtypeStruct((1, cout), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((1, cout), jnp.float32),
            pltpu.VMEM((1, cout), jnp.float32),
        ],
        interpret=interpret,
    )(x, w, pm.reshape(1, -1), pi.reshape(1, -1), ps.reshape(1, -1),
      pb.reshape(1, -1))
    return y, s.reshape(-1), sq.reshape(-1)


@functools.lru_cache(maxsize=None)
def _fused_fn(prologue: bool, prologue_relu: bool, interpret: bool):
    """custom_vjp'd fused unit: (x_raw, w[Cin,Cout], prev-BN mean/inv/
    scale/bias) -> (y_raw, sum_y, sqsum_y). Static config via closure."""

    @jax.custom_vjp
    def f(x, w, pm, pi, ps, pb):
        return _pallas_fwd(x, w, pm, pi, ps, pb, prologue, prologue_relu,
                           interpret)

    def fwd(x, w, pm, pi, ps, pb):
        y, s, sq = _pallas_fwd(x, w, pm, pi, ps, pb, prologue,
                               prologue_relu, interpret)
        # y rides along as a residual by reference — no extra HBM copy
        return (y, s, sq), (x, w, pm, pi, ps, pb, y)

    def bwd(res, cts):
        # dtype discipline mirrors amp.py: every [N, C]-sized intermediate
        # stays in the io dtype (an f32 materialization of one stage-2
        # tensor is 400+ MB of HBM traffic); f32 lives only in [C]-sized
        # vectors and matmul-internal accumulation
        x, w, pm, pi, ps, pb, y = res
        dy, ds, dsq = cts
        dt = x.dtype
        # stats outputs fold into an effective dy: d(sum)->+ds,
        # d(sqsum)->+2*y*dsq (one fused elementwise pass over y, dy)
        dy_c = (dy + ds.astype(dt) + (2.0 * dsq).astype(dt) * y).astype(dt)
        if prologue:
            g = pi * ps  # [Cin] f32
            # recompute the prologue in f32, as the forward kernel does,
            # so the ReLU mask `xh > 0` cannot disagree with the forward
            # near zero (a bf16 recompute flips borderline signs and
            # takes dx/dw at slightly different activations — ADVICE r4);
            # XLA fuses this elementwise chain into its consumers, so no
            # f32 [N, Cin] tensor is materialized to HBM
            xh32 = x.astype(jnp.float32) * g + (pb - pm * g)
            xh = xh32.astype(dt)
            if prologue_relu:
                pos = xh32 > 0
                xn_c = jnp.where(pos, xh, jnp.zeros((), dt))
            else:
                xn_c = xh
        else:
            xn_c = x
        dw = jnp.dot(xn_c.T, dy_c).astype(w.dtype)
        dxn = jnp.dot(dy_c, w.T)
        if prologue:
            dxh = jnp.where(pos, dxn, jnp.zeros((), dt)) \
                if prologue_relu else dxn
            dx = (dxh * g.astype(dt)).astype(dt)
            # the two per-channel reductions (XLA fuses both into one
            # pass over dxh, x); every prologue-param grad derives.
            # f32 accumulation: the reduce is over N ~ 1e5 rows
            dxh32 = dxh.astype(jnp.float32)
            r0 = jnp.sum(dxh32, axis=0)                             # [Cin]
            r1 = jnp.sum(dxh32 * x.astype(jnp.float32), axis=0)     # [Cin]
            rc = r1 - pm * r0  # sum(dxh * (x - pm)) without centering x
            dpm = -r0 * g
            dpi = rc * ps
            dps = rc * pi
            dpb = r0
        else:
            dx = dxn.astype(dt)
            dpm = jnp.zeros_like(pm)
            dpi = jnp.zeros_like(pi)
            dps = jnp.zeros_like(ps)
            dpb = jnp.zeros_like(pb)
        return dx, dw, dpm, dpi, dps, dpb

    f.defvjp(fwd, bwd)
    return f


def _prologue(x, pm, pi, ps, pb, prologue, prologue_relu):
    """The previous BN's normalize(+ReLU) in f32, quantized back to the
    io dtype — the one definition shared by the 2-D and 4-D fallbacks
    (the Pallas kernel implements the same math tile-locally). [C]-vector
    params broadcast over any leading rank."""
    if not prologue:
        return x
    xh = (x.astype(jnp.float32) - pm) * (pi * ps) + pb
    if prologue_relu:
        xh = jnp.maximum(xh, 0.0)
    return xh.astype(x.dtype)


def _jnp_fused(x, w, pm, pi, ps, pb, prologue, prologue_relu):
    """Identical-semantics fallback for ineligible shapes/backends.
    bf16 io end-to-end like conv2d_kernel under amp (the MXU accumulates
    f32 internally either way); f32 only in [C]-vectors and the stats
    reduction."""
    xn = _prologue(x, pm, pi, ps, pb, prologue, prologue_relu)
    acc = jnp.float32 if x.dtype == jnp.float32 else None
    y = jnp.dot(xn, w, preferred_element_type=acc).astype(x.dtype)
    return (y,) + _sum_sq(y, axis=0)


def _sum_sq(y, axis):
    """Per-channel sum / sum-of-squares with f32 accumulation; the
    bn_bf16_stats flag squares in the io dtype instead of upcasting
    first (escape-route knob, PERF.md r4) — one definition for every
    stats site."""
    from ..flags import FLAGS

    if FLAGS.bn_bf16_stats:
        return (jnp.sum(y, axis=axis, dtype=jnp.float32),
                jnp.sum(y * y, axis=axis, dtype=jnp.float32))
    yf = y.astype(jnp.float32)
    return jnp.sum(yf, axis=axis), jnp.sum(yf * yf, axis=axis)


def _jnp_fused4(x4, w, pm, pi, ps, pb, prologue, prologue_relu):
    """4-D (NHWC) fallback: same math as _jnp_fused but the matmul runs
    as a 1x1 conv_general_dilated on the un-reshaped activation, keeping
    XLA's conv layout assignment intact between neighboring 3x3 convs
    (a 2-D dot in the middle of a conv tower forces relayouts)."""
    xn = _prologue(x4, pm, pi, ps, pb, prologue, prologue_relu)
    acc = jnp.float32 if x4.dtype == jnp.float32 else None
    y = jax.lax.conv_general_dilated(
        xn, w[None, None], (1, 1), [(0, 0), (0, 0)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"),
        preferred_element_type=acc,
    ).astype(x4.dtype)
    return (y,) + _sum_sq(y, axis=(0, 1, 2))


def fused_matmul_bn(x, w, pm=None, pi=None, ps=None, pb=None,
                    prologue_relu=True):
    """Public fused unit on 2-D operands; dispatches Pallas vs jnp."""
    prologue = pm is not None
    if not prologue:
        c = x.shape[1]
        pm = jnp.zeros((c,), jnp.float32)
        pi = jnp.ones((c,), jnp.float32)
        ps = jnp.ones((c,), jnp.float32)
        pb = jnp.zeros((c,), jnp.float32)
    n, cin = x.shape
    cout = w.shape[1]
    if fused_conv_eligible(n, cin, cout, x.dtype):
        f = _fused_fn(prologue, bool(prologue_relu), _interpret())
        return f(x, w, pm, pi, ps, pb)
    return _jnp_fused(x, w, pm, pi, ps, pb, prologue, bool(prologue_relu))


# -------------------------------------------------------------------- ops --
def _stats_to_mean_inv(s, sq, n, eps):
    mean = s / n
    var = jnp.maximum(sq / n - mean * mean, 0.0)
    return mean, var, jax.lax.rsqrt(var + eps)


def _update_running(ctx, bmean, bvar):
    momentum = ctx.attr("momentum", 0.9)
    mean_v, var_v = ctx.input("Mean"), ctx.input("Variance")
    ctx.env[ctx.op.inputs["Mean"][0]] = (
        momentum * mean_v + (1 - momentum) * bmean)
    ctx.env[ctx.op.inputs["Variance"][0]] = (
        momentum * var_v + (1 - momentum) * bvar)


@register_op("fused_conv_bn")
def fused_conv_bn_kernel(ctx):
    """1x1 conv (NHWC, optional spatial-subsample stride) with fused
    previous-BN prologue and own-BN stats epilogue. Outputs the RAW conv
    result plus its batch mean/inv; consumers apply the normalize
    (bn_apply) or fuse it into their own prologue."""
    x = ctx.input("X")          # [B, H, W, Cin] NHWC
    w = ctx.input("Filter")     # [Cout, Cin, 1, 1] OIHW (checkpoint shape)
    stride = int(ctx.attr("stride", 1))
    eps = ctx.attr("epsilon", 1e-5)
    if stride > 1:
        # a stride-s 1x1 conv only reads every s-th pixel: subsample
        # FIRST so the prologue/matmul touch a quarter of the rows
        x = x[:, ::stride, ::stride, :]
    b, h, wd, cin = x.shape
    cout = w.shape[0]
    w2 = jnp.transpose(w.reshape(cout, cin))  # [Cin, Cout]
    xc, wc = amp.cast_inputs(ctx, x, w2)
    wc = wc.astype(xc.dtype)
    n = b * h * wd
    prologue = ctx.has_input("XMean")
    prologue_relu = ctx.attr("prologue_act", None) == "relu"
    if prologue:
        pm, pi = ctx.input("XMean"), ctx.input("XInv")
        ps, pb = ctx.input("XScale"), ctx.input("XBias")
    else:
        pm = pi = ps = pb = None
    from ..flags import FLAGS

    dot_max_n = FLAGS.fused_conv_dot_max_n
    use_pallas = FLAGS.fused_conv_pallas or FLAGS.fused_conv_interpret
    from .mesh_dispatch import current as _active_mesh

    if _active_mesh() is not None and _active_mesh().dp > 1:
        # mesh policy (ops/mesh_dispatch.py): a bare pallas_call cannot
        # be GSPMD-partitioned. This opt-in kernel (measured slower than
        # XLA's fusion anyway — PERF.md r4) is not shard_map-wrapped;
        # under a mesh it falls back to the identical-semantics jnp
        # formulation, which GSPMD partitions natively
        use_pallas = False
    if n <= dot_max_n and fused_conv_eligible(n, cin, cout, xc.dtype):
        if use_pallas:
            y2, s, sq = fused_matmul_bn(
                xc.reshape(-1, cin), wc, pm, pi, ps, pb,
                prologue_relu=prologue_relu)
        else:
            y2, s, sq = _jnp_fused(xc.reshape(-1, cin), wc, pm, pi, ps, pb,
                                   prologue, prologue_relu)
        y = y2.reshape(b, h, wd, cout)
    else:
        y, s, sq = _jnp_fused4(xc, wc, pm, pi, ps, pb, prologue,
                               prologue_relu)
    bmean, bvar, binv = _stats_to_mean_inv(s, sq, float(n), eps)
    _update_running(ctx, bmean, bvar)
    ctx.set_output("Out", y)
    ctx.set_output("BatchMean", bmean)
    ctx.set_output("BatchInv", binv)


@register_op("bn_stats")
def bn_stats_kernel(ctx):
    """Stats-only half of batch_norm (NHWC): one reduce pass emitting
    batch mean/inv + the running-stat update; the normalize is applied
    by the consumer (bn_apply or a fused_conv_bn prologue)."""
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    s, sq = _sum_sq(x, axis=(0, 1, 2))
    n = float(x.size // x.shape[-1])
    bmean, bvar, binv = _stats_to_mean_inv(s, sq, n, eps)
    _update_running(ctx, bmean, bvar)
    ctx.set_output("BatchMean", bmean)
    ctx.set_output("BatchInv", binv)


@register_op("bn_apply")
def bn_apply_kernel(ctx):
    """Normalize+scale+shift (+act) of a raw activation given its stats —
    one XLA elementwise pass, fusable with adjacent adds/relus."""
    x = ctx.input("X")
    m, iv = ctx.input("Mean"), ctx.input("Inv")
    s, b = ctx.input("Scale"), ctx.input("Bias")
    y = (x.astype(jnp.float32) - m) * (iv * s) + b
    if ctx.attr("act", None) == "relu":
        y = jnp.maximum(y, 0.0)
    ctx.set_output("Out", y.astype(x.dtype))
