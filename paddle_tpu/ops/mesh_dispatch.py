"""Explicit batch-sharding policy for the fused Pallas kernels.

THE pallas-under-GSPMD rule for this framework: a Mosaic `pallas_call`
is an opaque custom call to the XLA SPMD partitioner — it cannot be
automatically partitioned, and relying on the partitioner means either
a lowering error or a silent full replication (all-gather the batch,
run the whole kernel on every device) the day a mesh appears. So under
a `ParallelExecutor` mesh every fused-kernel dispatch is wrapped in
`jax.shard_map` over the data-parallel axis — jax's own documented
pattern for pallas + sharding:

- batch-sharded operands in, batch-sharded activations out;
- weights replicated in; their cotangents are per-shard partial sums,
  so the custom-VJP backwards `psum` them over the dp axis (shard_map
  runs with check_vma off — pallas calls don't carry replication
  rules — which means NO automatic cotangent psum: each kernel
  family's bwd does it explicitly, keyed by the `axis` parameter);
- eligibility is evaluated at the PER-SHARD batch (`local_batch`):
  what the kernel actually sees inside shard_map. Non-divisible or
  ineligible-at-local-batch configs fall back to the XLA scan
  formulations, which GSPMD partitions natively.

The executor threads the active mesh here via `active_mesh(...)` around
its trace (`core/executor.py` / `parallel/data_parallel.py`); op
kernels consult `current()`/`local_batch()` at trace time, exactly like
the FLAGS-based dispatch they sit next to.

Covered families: the fused LSTM/GRU kernels (pallas_kernels.py), the
fused Bahdanau decoder (bahdanau_kernels.py), and flash attention
(flash_ops.py — wrapped over dp only; it has no weight operands, so no
cotangent psums, and under an mp axis the wrap replicates heads — a
GSPMD-inserted reshard; head-sharding inside the wrap is a named
multi-chip lever). The opt-in fused-conv pallas kernel
(fused_conv_ops.py, measured-off by default) is NOT wrapped: under a
mesh it falls back to its identical-semantics jnp formulation.

Reference counterpart: MultiGradientMachine ran one replica per GPU
and ring-reduced gradients (gserver/gradientmachines/
MultiGradientMachine.h:63-110) — shard_map over dp + psum'd weight
cotangents is that same contract, expressed inside one SPMD program.
"""

from __future__ import annotations

import contextlib
import contextvars
from typing import NamedTuple, Optional

import jax
from jax.sharding import Mesh, PartitionSpec as P


class ActiveMesh(NamedTuple):
    mesh: Mesh
    batch_axis: str

    @property
    def dp(self) -> int:
        return self.mesh.shape[self.batch_axis]


_ACTIVE: contextvars.ContextVar[Optional[ActiveMesh]] = \
    contextvars.ContextVar("pt_active_mesh", default=None)


@contextlib.contextmanager
def active_mesh(mesh: Mesh, batch_axis: str):
    """Executor hook: declares the mesh the current trace runs under."""
    tok = _ACTIVE.set(ActiveMesh(mesh, batch_axis))
    try:
        yield
    finally:
        _ACTIVE.reset(tok)


def current() -> Optional[ActiveMesh]:
    return _ACTIVE.get()


@contextlib.contextmanager
def no_mesh():
    """Clear the active-mesh context for code that is ALREADY running
    per-shard (inside its own shard_map): eligibility there must see
    the true local shapes, not divide them by dp a second time, and a
    nested shard_batch wrap would be an error. parallel/ring_attention
    brackets its per-shard inner attention with this."""
    tok = _ACTIVE.set(None)
    try:
        yield
    finally:
        _ACTIVE.reset(tok)


def dp_size() -> int:
    am = _ACTIVE.get()
    return am.dp if am is not None else 1


def local_batch(B: int) -> int:
    """The batch each kernel instance sees: B under no mesh, B/dp under
    a mesh, 0 (= every eligibility check fails -> scan fallback) when
    the dp axis does not divide the batch."""
    am = _ACTIVE.get()
    if am is None or am.dp == 1:
        return B
    return B // am.dp if B % am.dp == 0 else 0


def shard_batch(fn, batch_dims, out_dims, out_tree=None):
    """Wrap `fn` in shard_map over the active dp axis (identity without
    a mesh). `batch_dims[i]` is the batch dimension of positional arg i
    (None = replicated, e.g. weights); `out_dims` gives each flattened
    output's (batch_dim, ndim) — callers know their output ranks
    statically. `out_tree` (a treedef from jax.tree.structure on an
    example output) restores structure; None = single array output. The
    wrapped fn's custom-VJP backward must psum replicated-input
    cotangents itself (see module docstring)."""
    am = _ACTIVE.get()
    if am is None or am.dp == 1:
        return fn
    ax = am.batch_axis

    def spec(d, ndim):
        if d is None:
            return P()
        return P(*(ax if i == d else None for i in range(ndim)))

    out_flat = [spec(d, nd) for d, nd in out_dims]
    out_specs = (out_flat[0] if out_tree is None
                 else jax.tree.unflatten(out_tree, out_flat))

    def wrapped(*args):
        in_specs = tuple(
            spec(d, arg.ndim) for arg, d in zip(args, batch_dims))
        return jax.shard_map(
            fn, mesh=am.mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=False)(*args)

    return wrapped
