"""Sequence (ragged/LoD) op kernels.

Reference coverage: paddle/operators/{sequence_pool_op,sequence_softmax_op,
sequence_expand_op,sequence_concat_op,sequence_slice_op,sequence_conv_op}.cc,
Gen-1 gserver/layers/{SequencePoolLayer,ExpandLayer}.cpp, and the segment
machinery in paddle/cuda/src/hl_cuda_sequence.cu. All operate on LoDArray
(core/lod.py): segment reductions over `seq_ids` — the TPU-native encoding
of the reference's no-padding sequenceStartPositions design
(parameter/Argument.h:84-90).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


def _segment_max_ids(x: LoDArray):
    return jnp.where(x.seq_ids >= 0, x.seq_ids, x.max_seqs)


def segment_reduce(x: LoDArray, mode: str):
    """[capacity, ...] → [max_seqs, ...] per-sequence reduction."""
    ids = _segment_max_ids(x)
    num = x.max_seqs
    if mode == "sum":
        return jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
    if mode == "average":
        s = jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
        cnt = jnp.maximum(x.lengths, 1).astype(s.dtype)
        return s / cnt.reshape((-1,) + (1,) * (s.ndim - 1))
    if mode == "sqrt":
        s = jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
        cnt = jnp.maximum(x.lengths, 1).astype(s.dtype)
        return s / jnp.sqrt(cnt).reshape((-1,) + (1,) * (s.ndim - 1))
    if mode == "max":
        return jax.ops.segment_max(x.data, ids, num_segments=num + 1)[:num]
    if mode == "min":
        return jax.ops.segment_min(x.data, ids, num_segments=num + 1)[:num]
    if mode == "last":
        idx = jnp.clip(x.offsets[1:] - 1, 0, x.capacity - 1)
        return jnp.take(x.data, idx, axis=0)
    if mode == "first":
        idx = jnp.clip(x.offsets[:-1], 0, x.capacity - 1)
        return jnp.take(x.data, idx, axis=0)
    raise NotImplementedError(f"sequence_pool mode {mode!r}")


@register_op("sequence_pool")
def sequence_pool_kernel(ctx):
    """Reference: sequence_pool_op.cc / gserver SequencePoolLayer.cpp —

    modes: average, sum, sqrt, max, last, first."""
    x = ctx.input("X")
    mode = ctx.attr("pooltype", "sum").lower()
    out = segment_reduce(x, mode)
    # zero out absent sequences
    valid = (jnp.arange(x.max_seqs) < x.num_seqs).reshape(
        (-1,) + (1,) * (out.ndim - 1)
    )
    ctx.set_output("Out", jnp.where(valid, out, 0.0))


def sequence_softmax_impl(x: LoDArray) -> LoDArray:
    """Softmax within each sequence (reference: sequence_softmax_op.cc,

    Gen-1 sequence_softmax activation). x.data: [capacity] or [capacity, 1].
    """
    data = x.data
    squeeze = False
    if data.ndim == 2 and data.shape[1] == 1:
        data = data[:, 0]
        squeeze = True
    ids = _segment_max_ids(x)
    num = x.max_seqs
    data = jnp.where(x.token_mask, data, -jnp.inf)
    seg_max = jax.ops.segment_max(data, ids, num_segments=num + 1)
    shifted = data - jnp.take(seg_max, ids)
    e = jnp.where(x.token_mask, jnp.exp(shifted), 0.0)
    seg_sum = jax.ops.segment_sum(e, ids, num_segments=num + 1)
    out = e / jnp.maximum(jnp.take(seg_sum, ids), 1e-20)
    if squeeze:
        out = out[:, None]
    return x.with_data(out)


@register_op("sequence_softmax")
def sequence_softmax_kernel(ctx):
    ctx.set_output("Out", sequence_softmax_impl(ctx.input("X")))


@register_op("sequence_expand")
def sequence_expand_kernel(ctx):
    """Reference: sequence_expand_op.cc / gserver ExpandLayer.cpp — broadcast

    per-sequence rows of X across the tokens of Y's sequences."""
    x = ctx.input("X")  # dense [max_seqs, ...] or LoDArray
    y = ctx.input("Y")  # LoDArray giving the target lod
    rows = x.data if isinstance(x, LoDArray) else x
    ids = jnp.clip(y.seq_ids, 0, rows.shape[0] - 1)
    out = jnp.take(rows, ids, axis=0)
    out = jnp.where(
        y.token_mask.reshape((-1,) + (1,) * (out.ndim - 1)), out, 0.0
    )
    ctx.set_output("Out", y.with_data(out))


@register_op("sequence_concat")
def sequence_concat_kernel(ctx):
    """Reference: sequence_concat_op.cc — feature-axis concat of LoD inputs

    with identical lod (axis=1)."""
    xs = ctx.inputs("X")
    datas = [x.data for x in xs]
    ctx.set_output("Out", xs[0].with_data(jnp.concatenate(datas, axis=-1)))


@register_op("sequence_first_step")
def sequence_first_step_kernel(ctx):
    ctx.set_output("Out", segment_reduce(ctx.input("X"), "first"))


@register_op("sequence_last_step")
def sequence_last_step_kernel(ctx):
    ctx.set_output("Out", segment_reduce(ctx.input("X"), "last"))


# ---------------------------------------------------------------------------
# Widened sequence set: slice/reshape/reverse/kmax/sub_nested/featmap/eos/conv
# Reference: gserver/layers/{SequenceSliceLayer,SequenceReshapeLayer,
# KmaxSeqScoreLayer,SubNestedSequenceLayer,FeatureMapExpandLayer,
# EosIdCheckLayer,ContextProjection}.cpp and operators/{sequence_slice_op,
# sequence_conv_op}.cc.
# ---------------------------------------------------------------------------
def _out_seq_structure(new_lengths, capacity):
    """Build (seq_ids, offsets, total) for a new ragged layout given
    per-sequence lengths (static capacity)."""
    new_offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(new_lengths).astype(jnp.int32)]
    )
    total = new_offsets[-1]
    pos = jnp.arange(capacity)
    ids = (pos[:, None] >= new_offsets[None, 1:]).sum(-1).astype(jnp.int32)
    ids = jnp.where(pos < total, ids, -1)
    return ids, new_offsets, total


@register_op("sequence_slice")
def sequence_slice_kernel(ctx):
    """SequenceSliceLayer: take [offset, offset+length) of each sequence."""
    x = ctx.input("X")
    off = ctx.input("Offset")
    length = ctx.input("Length")
    off = (off.data if isinstance(off, LoDArray) else off).reshape(-1).astype(jnp.int32)
    length = (length.data if isinstance(length, LoDArray) else length).reshape(-1).astype(jnp.int32)

    def _fit(v):  # pad/trim to the LoD's (possibly bucketed) max_seqs
        if v.shape[0] < x.max_seqs:
            return jnp.pad(v, (0, x.max_seqs - v.shape[0]))
        return v[: x.max_seqs]

    off = _fit(off)
    length = _fit(length)
    new_len = jnp.clip(jnp.minimum(length, x.lengths - off), 0, None)
    new_len = new_len * (jnp.arange(x.max_seqs) < x.num_seqs)
    ids, new_offsets, _ = _out_seq_structure(new_len, x.capacity)
    sid = jnp.clip(ids, 0, x.max_seqs - 1)
    local = jnp.arange(x.capacity) - new_offsets[sid]
    src = jnp.clip(x.offsets[sid] + off[sid] + local, 0, x.capacity - 1)
    data = jnp.where(
        (ids >= 0).reshape((-1,) + (1,) * (x.data.ndim - 1)),
        x.data[src],
        0,
    )
    ctx.set_output("Out", LoDArray(data, ids, new_len, x.num_seqs))


@register_op("sequence_reshape")
def sequence_reshape_kernel(ctx):
    """SequenceReshapeLayer: refactor feature dim; seq lengths scale by
    d/new_dim (reference requires divisibility)."""
    x = ctx.input("X")
    new_dim = ctx.attr("new_dim")
    d = x.data.shape[-1]
    cap = x.capacity
    new_cap = cap * d // new_dim
    data = x.data.reshape(new_cap, new_dim)
    new_len = (x.lengths * d) // new_dim
    ids, _, _ = _out_seq_structure(new_len, new_cap)
    ctx.set_output("Out", LoDArray(data, ids, new_len, x.num_seqs))


@register_op("sequence_reverse")
def sequence_reverse_kernel(ctx):
    x = ctx.input("X")
    pos = jnp.arange(x.capacity)
    sid = jnp.clip(jnp.where(x.seq_ids >= 0, x.seq_ids, 0), 0, x.max_seqs - 1)
    local = pos - x.offsets[sid]
    src = jnp.clip(x.offsets[sid] + x.lengths[sid] - 1 - local, 0, x.capacity - 1)
    data = jnp.where(
        (x.seq_ids >= 0).reshape((-1,) + (1,) * (x.data.ndim - 1)),
        x.data[src],
        0,
    )
    ctx.set_output("Out", x.with_data(data))


@register_op("kmax_seq_score")
def kmax_seq_score_kernel(ctx):
    """KmaxSeqScoreLayer: top-k scores per sequence → within-sequence
    indices, padded with -1 (dense [max_seqs, k] output)."""
    x = ctx.input("X")
    k = ctx.attr("beam_size", 1)
    scores = x.data.reshape(x.capacity)
    dense, valid = x.with_data(scores).to_batch(time_major=False)  # [B, T]
    masked = jnp.where(valid, dense, -jnp.inf)
    _, idx = jax.lax.top_k(masked, k)
    in_range = jnp.take_along_axis(valid, idx, axis=-1)
    ctx.set_output("Out", jnp.where(in_range, idx, -1).astype(jnp.int32))


@register_op("sub_nested_seq")
def sub_nested_seq_kernel(ctx):
    """SubNestedSequenceLayer: from a nested (2-level) sequence, select
    sub-sequences by global sub-sequence index; emit a level-1 LoD batch.
    Selection: dense int [num_sel] (global sub-seq ids, -1 = pad)."""
    x = ctx.input("X")
    sel = ctx.input("Selection")
    sel = (sel.data if isinstance(sel, LoDArray) else sel).reshape(-1).astype(jnp.int32)
    if x.sub_seq_ids is None:
        raise ValueError("sub_nested_seq requires a 2-level LoDArray input")
    n_sel = sel.shape[0]
    sub_ids = x.sub_seq_ids
    # per-subsequence lengths/offsets over the flat buffer
    n_subs = x.capacity  # upper bound on distinct sub ids
    ones = (sub_ids >= 0).astype(jnp.int32)
    sub_len = jax.ops.segment_sum(
        ones, jnp.where(sub_ids >= 0, sub_ids, n_subs), num_segments=n_subs + 1
    )[:-1]
    sub_off = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(sub_len).astype(jnp.int32)]
    )
    sel_valid = sel >= 0
    sel_safe = jnp.where(sel_valid, sel, 0)
    new_len = jnp.where(sel_valid, sub_len[sel_safe], 0)
    ids, new_offsets, _ = _out_seq_structure(new_len, x.capacity)
    sid = jnp.clip(ids, 0, n_sel - 1)
    local = jnp.arange(x.capacity) - new_offsets[sid]
    src = jnp.clip(sub_off[sel_safe[sid]] + local, 0, x.capacity - 1)
    data = jnp.where(
        (ids >= 0).reshape((-1,) + (1,) * (x.data.ndim - 1)),
        x.data[src],
        0,
    )
    num = jnp.sum(sel_valid.astype(jnp.int32))
    ctx.set_output("Out", LoDArray(data, ids, new_len, num))


@register_op("featmap_expand")
def featmap_expand_kernel(ctx):
    """FeatureMapExpandLayer: tile each token's feature num_filters times
    ([cap, D] → [cap, num_filters*D]; as_row_vector=False repeats
    per-element instead)."""
    x = ctx.input("X")
    n = ctx.attr("num_filters")
    as_row = ctx.attr("as_row_vector", True)
    d = x.data
    if as_row:
        out = jnp.tile(d, (1, n))
    else:
        out = jnp.repeat(d, n, axis=-1)
    ctx.set_output("Out", x.with_data(out))


@register_op("eos_id")
def eos_id_kernel(ctx):
    """EosIdCheckLayer: 1 where the token id equals eos_id."""
    x = ctx.input("X")
    eos = ctx.attr("eos_id")
    d = x.data if isinstance(x, LoDArray) else x
    out = (d.reshape(d.shape[0], -1)[:, :1] == eos).astype(jnp.float32)
    if isinstance(x, LoDArray):
        ctx.set_output("Out", x.with_data(out))
    else:
        ctx.set_output("Out", out)


@register_op("sequence_conv")
def sequence_conv_kernel(ctx):
    """Context-window convolution over a ragged batch: out[t] =
    concat_{i<L} x[t + start + i] @ Filter, windows clipped at sequence
    boundaries (reference ContextProjection + sequence_conv_op.cc; the SRL
    and text-conv models build on this)."""
    x = ctx.input("X")
    w = ctx.input("Filter")
    w = w.data if isinstance(w, LoDArray) else w
    length = ctx.attr("context_length")
    start = ctx.attr("context_start", -(length // 2))
    cap = x.capacity
    d = x.data
    pos = jnp.arange(cap)
    cols = []
    for i in range(length):
        shift = start + i
        src = jnp.clip(pos + shift, 0, cap - 1)
        same = jnp.where(
            (pos + shift >= 0) & (pos + shift < cap),
            x.seq_ids[src] == x.seq_ids,
            False,
        )
        cols.append(
            jnp.where(same.reshape(-1, 1), d[src], 0.0)
        )
    ctx_feat = jnp.concatenate(cols, axis=-1)  # [cap, L*D]
    out = jnp.dot(ctx_feat, w, preferred_element_type=jnp.float32)
    if ctx.has_input("Bias"):
        b = ctx.input("Bias")
        out = out + (b.data if isinstance(b, LoDArray) else b).reshape(1, -1)
    # keep padding slots zero (the buffer-wide invariant all LoD ops hold)
    out = jnp.where(x.token_mask.reshape(-1, 1), out, 0.0)
    ctx.set_output("Out", x.with_data(out))
