"""Sequence (ragged/LoD) op kernels.

Reference coverage: paddle/operators/{sequence_pool_op,sequence_softmax_op,
sequence_expand_op,sequence_concat_op,sequence_slice_op,sequence_conv_op}.cc,
Gen-1 gserver/layers/{SequencePoolLayer,ExpandLayer}.cpp, and the segment
machinery in paddle/cuda/src/hl_cuda_sequence.cu. All operate on LoDArray
(core/lod.py): segment reductions over `seq_ids` — the TPU-native encoding
of the reference's no-padding sequenceStartPositions design
(parameter/Argument.h:84-90).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


def _segment_max_ids(x: LoDArray):
    return jnp.where(x.seq_ids >= 0, x.seq_ids, x.max_seqs)


def segment_reduce(x: LoDArray, mode: str):
    """[capacity, ...] → [max_seqs, ...] per-sequence reduction."""
    ids = _segment_max_ids(x)
    num = x.max_seqs
    if mode == "sum":
        return jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
    if mode == "average":
        s = jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
        cnt = jnp.maximum(x.lengths, 1).astype(s.dtype)
        return s / cnt.reshape((-1,) + (1,) * (s.ndim - 1))
    if mode == "sqrt":
        s = jax.ops.segment_sum(x.data, ids, num_segments=num + 1)[:num]
        cnt = jnp.maximum(x.lengths, 1).astype(s.dtype)
        return s / jnp.sqrt(cnt).reshape((-1,) + (1,) * (s.ndim - 1))
    if mode == "max":
        return jax.ops.segment_max(x.data, ids, num_segments=num + 1)[:num]
    if mode == "min":
        return jax.ops.segment_min(x.data, ids, num_segments=num + 1)[:num]
    if mode == "last":
        idx = jnp.clip(x.offsets[1:] - 1, 0, x.capacity - 1)
        return jnp.take(x.data, idx, axis=0)
    if mode == "first":
        idx = jnp.clip(x.offsets[:-1], 0, x.capacity - 1)
        return jnp.take(x.data, idx, axis=0)
    raise NotImplementedError(f"sequence_pool mode {mode!r}")


@register_op("sequence_pool")
def sequence_pool_kernel(ctx):
    """Reference: sequence_pool_op.cc / gserver SequencePoolLayer.cpp —

    modes: average, sum, sqrt, max, last, first."""
    x = ctx.input("X")
    mode = ctx.attr("pooltype", "sum").lower()
    out = segment_reduce(x, mode)
    # zero out absent sequences
    valid = (jnp.arange(x.max_seqs) < x.num_seqs).reshape(
        (-1,) + (1,) * (out.ndim - 1)
    )
    ctx.set_output("Out", jnp.where(valid, out, 0.0))


def sequence_softmax_impl(x: LoDArray) -> LoDArray:
    """Softmax within each sequence (reference: sequence_softmax_op.cc,

    Gen-1 sequence_softmax activation). x.data: [capacity] or [capacity, 1].
    """
    data = x.data
    squeeze = False
    if data.ndim == 2 and data.shape[1] == 1:
        data = data[:, 0]
        squeeze = True
    ids = _segment_max_ids(x)
    num = x.max_seqs
    data = jnp.where(x.token_mask, data, -jnp.inf)
    seg_max = jax.ops.segment_max(data, ids, num_segments=num + 1)
    shifted = data - jnp.take(seg_max, ids)
    e = jnp.where(x.token_mask, jnp.exp(shifted), 0.0)
    seg_sum = jax.ops.segment_sum(e, ids, num_segments=num + 1)
    out = e / jnp.maximum(jnp.take(seg_sum, ids), 1e-20)
    if squeeze:
        out = out[:, None]
    return x.with_data(out)


@register_op("sequence_softmax")
def sequence_softmax_kernel(ctx):
    ctx.set_output("Out", sequence_softmax_impl(ctx.input("X")))


@register_op("sequence_expand")
def sequence_expand_kernel(ctx):
    """Reference: sequence_expand_op.cc / gserver ExpandLayer.cpp — broadcast

    per-sequence rows of X across the tokens of Y's sequences."""
    x = ctx.input("X")  # dense [max_seqs, ...] or LoDArray
    y = ctx.input("Y")  # LoDArray giving the target lod
    rows = x.data if isinstance(x, LoDArray) else x
    ids = jnp.clip(y.seq_ids, 0, rows.shape[0] - 1)
    out = jnp.take(rows, ids, axis=0)
    out = jnp.where(
        y.token_mask.reshape((-1,) + (1,) * (out.ndim - 1)), out, 0.0
    )
    ctx.set_output("Out", y.with_data(out))


@register_op("sequence_concat")
def sequence_concat_kernel(ctx):
    """Reference: sequence_concat_op.cc — feature-axis concat of LoD inputs

    with identical lod (axis=1)."""
    xs = ctx.inputs("X")
    datas = [x.data for x in xs]
    ctx.set_output("Out", xs[0].with_data(jnp.concatenate(datas, axis=-1)))


@register_op("sequence_first_step")
def sequence_first_step_kernel(ctx):
    ctx.set_output("Out", segment_reduce(ctx.input("X"), "first"))


@register_op("sequence_last_step")
def sequence_last_step_kernel(ctx):
    ctx.set_output("Out", segment_reduce(ctx.input("X"), "last"))
