"""Math / tensor-manipulation op kernels.

Reference coverage: paddle/operators/{mul_op,matmul_op,elementwise_*_op,
scale_op,sum_op,mean_op,reduce_op,reshape_op,transpose_op,concat_op,
split_op,clip_op,cast_op,top_k_op,fill_constant_op,uniform_random_op,
gaussian_random_op,lookup_table_op,squared_l2_norm_op,...}.cc and the
paddle/math Matrix::mul / BaseMatrix template kernels they sit on. All are
direct jnp/lax calls — matmuls land on the MXU, elementwise on the VPU,
everything fuses under the whole-program jit.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .. import amp
from ..core.lod import LoDArray
from ..core.registry import register_op
from ..core.sparse import SparseArray


def _data(x):
    return x.data if isinstance(x, LoDArray) else x


def _like(x, data):
    return x.with_data(data) if isinstance(x, LoDArray) else data


# ---------------------------------------------------------------- matmul ---
@register_op("mul")
def mul_kernel(ctx):
    """Reference: paddle/operators/mul_op.cc — flattens X to 2-D by

    x_num_col_dims then GEMM (math/math_function matmul → cuBLAS; here MXU).
    """
    x_in = ctx.input("X")
    if isinstance(x_in, SparseArray):
        # sparse × dense (reference: CpuSparseMatrix::mul, sparse input
        # slots feeding an FC): gather + weighted segment-sum — never
        # densifies the [N, dim] input; output stays at the compute dtype
        # (bf16 under amp, like every other MXU kernel)
        w = amp.cast_inputs(ctx, ctx.input("Y"))
        ctx.set_output("Out", x_in.matmul(w))
        return
    x, y = _data(x_in), _data(ctx.input("Y"))
    xd = ctx.attr("x_num_col_dims", 1)
    yd = ctx.attr("y_num_col_dims", 1)
    xs, ys = x.shape, y.shape
    x2 = x.reshape((int(np.prod(xs[:xd])), -1)) if x.ndim > 2 or xd != 1 else x
    y2 = y.reshape((int(np.prod(ys[:yd])), -1)) if y.ndim > 2 or yd != 1 else y
    x2, y2 = amp.cast_inputs(ctx, x2, y2)
    # f32 MXU accumulation; the result is then stored at the compute dtype
    # (bf16 under amp — activations stay 2 B/elem, see amp.py)
    out = jnp.dot(x2, y2, preferred_element_type=jnp.float32).astype(x2.dtype)
    # restore leading dims: out shape is xs[:xd] + ys[yd:] (mul_op.cc InferShape)
    out_shape = tuple(xs[:xd]) + tuple(ys[yd:])
    if out.shape != out_shape:
        out = out.reshape(out_shape)
    ctx.set_output("Out", _like(x_in, out))


@register_op("matmul")
def matmul_kernel(ctx):
    """Reference: paddle/operators/matmul_op.cc — batched matmul with

    transpose flags."""
    x, y = _data(ctx.input("X")), _data(ctx.input("Y"))
    if ctx.attr("transpose_X", False):
        x = jnp.swapaxes(x, -1, -2)
    if ctx.attr("transpose_Y", False):
        y = jnp.swapaxes(y, -1, -2)
    x, y = amp.cast_inputs(ctx, x, y)
    out = jnp.matmul(x, y, preferred_element_type=jnp.float32).astype(x.dtype)
    ctx.set_output("Out", out)


# ----------------------------------------------------------- elementwise ---
def _broadcast_y(x, y, axis):
    """Reference elementwise broadcast rule (elementwise_op_function.h):

    y's shape must match a contiguous slice of x's starting at `axis`."""
    if y.ndim == x.ndim:
        return y
    if axis is None or axis == -1:
        axis = x.ndim - y.ndim
    new_shape = (1,) * axis + y.shape + (1,) * (x.ndim - axis - y.ndim)
    return y.reshape(new_shape)


def _make_elementwise(name, fn):
    def kernel(ctx):
        x, y = ctx.input("X"), ctx.input("Y")
        xd, yd = _data(x), _data(y)
        yd = _broadcast_y(xd, yd, ctx.attr("axis", -1))
        # under amp, f32 masters (biases/scales) cast DOWN to meet bf16
        # activations instead of promoting the activation up (amp.py)
        xd, yd = amp.harmonize(ctx, xd, yd)
        ctx.set_output("Out", _like(x, fn(xd, yd)))

    register_op(name)(kernel)


_make_elementwise("elementwise_add", jnp.add)
_make_elementwise("elementwise_sub", jnp.subtract)
_make_elementwise("elementwise_mul", jnp.multiply)
_make_elementwise("elementwise_div", jnp.divide)
_make_elementwise("elementwise_max", jnp.maximum)
_make_elementwise("elementwise_min", jnp.minimum)
_make_elementwise("elementwise_pow", jnp.power)


# ------------------------------------------------------------- reductions --
@register_op("mean")
def mean_kernel(ctx):
    x = _data(ctx.input("X"))
    # loss-style reduction: accumulate + emit f32 for any reduced-precision
    # float input (bf16/f16 — amp or not)
    if jnp.issubdtype(x.dtype, jnp.floating) and x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
    ctx.set_output("Out", jnp.mean(x))


@register_op("sum")
def sum_kernel(ctx):
    """Reference: paddle/operators/sum_op.cc — adds N input tensors."""
    xs = ctx.inputs("X")
    out = functools.reduce(jnp.add, [_data(x) for x in xs])
    ctx.set_output("Out", _like(xs[0], out))


def _make_reduce(name, fn):
    def kernel(ctx):
        x = _data(ctx.input("X"))
        dim = ctx.attr("dim", 0)
        keep = ctx.attr("keep_dim", False)
        if ctx.attr("reduce_all", False):
            dim = None
        ctx.set_output("Out", fn(x, axis=dim, keepdims=keep))

    register_op(name)(kernel)


_make_reduce("reduce_sum", jnp.sum)
_make_reduce("reduce_mean", jnp.mean)
_make_reduce("reduce_max", jnp.max)
_make_reduce("reduce_min", jnp.min)


# ----------------------------------------------------------- shape manip ---
@register_op("reshape")
def reshape_kernel(ctx):
    x = _data(ctx.input("X"))
    shape = list(ctx.attr("shape"))
    ctx.set_output("Out", x.reshape(shape))


@register_op("transpose")
def transpose_kernel(ctx):
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.transpose(x, ctx.attr("axis")))


@register_op("concat")
def concat_kernel(ctx):
    xs = [_data(x) for x in ctx.inputs("X")]
    ctx.set_output("Out", jnp.concatenate(xs, axis=ctx.attr("axis", 0)))


@register_op("split")
def split_kernel(ctx):
    x = _data(ctx.input("X"))
    axis = ctx.attr("axis", 0)
    num = ctx.attr("num", 0)
    sections = ctx.attr("sections")
    if sections:
        idx = np.cumsum(sections)[:-1].tolist()
        parts = jnp.split(x, idx, axis=axis)
    else:
        parts = jnp.split(x, num, axis=axis)
    for i, p in enumerate(parts):
        ctx.set_output("Out", p, idx=i)


@register_op("expand")
def expand_kernel(ctx):
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.tile(x, ctx.attr("expand_times")))


@register_op("slice")
def slice_kernel(ctx):
    x = _data(ctx.input("X"))
    axes = ctx.attr("axes")
    starts, ends = ctx.attr("starts"), ctx.attr("ends")
    idx = [slice(None)] * x.ndim
    for ax, s, e in zip(axes, starts, ends):
        idx[ax] = slice(s, e)
    ctx.set_output("Out", x[tuple(idx)])


# ----------------------------------------------------------------- misc ----
@register_op("scale")
def scale_kernel(ctx):
    x = ctx.input("X")
    s = ctx.attr("scale", 1.0)
    b = ctx.attr("bias", 0.0)
    ctx.set_output("Out", _like(x, _data(x) * s + b))


@register_op("clip")
def clip_kernel(ctx):
    x = ctx.input("X")
    ctx.set_output(
        "Out", _like(x, jnp.clip(_data(x), ctx.attr("min"), ctx.attr("max")))
    )


@register_op("cast")
def cast_kernel(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", _like(x, _data(x).astype(np.dtype(ctx.attr("dtype")))))


@register_op("sign")
def sign_kernel(ctx):
    x = ctx.input("X")
    ctx.set_output("Out", _like(x, jnp.sign(_data(x))))


@register_op("clip_by_norm")
def clip_by_norm_kernel(ctx):
    """Reference: paddle/operators/clip_by_norm_op.cc."""
    x = _data(ctx.input("X"))
    max_norm = ctx.attr("max_norm")
    norm = jnp.sqrt(jnp.sum(jnp.square(x)))
    scale = jnp.minimum(max_norm / jnp.maximum(norm, 1e-12), 1.0)
    ctx.set_output("Out", x * scale)


@register_op("clip_by_global_norm")
def clip_by_global_norm_kernel(ctx):
    """Variadic: clips all X[i] by their joint L2 norm (reference semantics:

    fluid clip.py GradientClipByGlobalNorm)."""
    xs = [_data(x) for x in ctx.inputs("X")]
    max_norm = ctx.attr("max_global_norm")
    gnorm = jnp.sqrt(sum(jnp.sum(jnp.square(x)) for x in xs))
    scale = jnp.minimum(max_norm / jnp.maximum(gnorm, 1e-12), 1.0)
    for i, x in enumerate(xs):
        ctx.set_output("Out", x * scale, idx=i)


@register_op("squared_l2_norm")
def squared_l2_norm_kernel(ctx):
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.sum(jnp.square(x)))


@register_op("top_k")
def top_k_kernel(ctx):
    """Reference: paddle/operators/top_k_op.cc, cuda/src/hl_top_k.cu."""
    x = _data(ctx.input("X"))
    k = ctx.attr("k", 1)
    vals, idxs = jax.lax.top_k(x, k)
    ctx.set_output("Out", vals)
    ctx.set_output("Indices", idxs.astype(jnp.int32))


@register_op("lookup_table")
def lookup_table_kernel(ctx):
    """Reference: paddle/operators/lookup_table_op.cc — embedding gather.

    When the table is marked sparse_update (embedding is_sparse=True) and an
    autodiff trace is active, the gather routes through the SparseGradTape
    so the table's gradient stays SelectedRows (rows+values), never dense —
    framework/selected_rows.h parity, see core/sparse.py."""
    w = ctx.input("W")
    ids = ctx.input("Ids")
    ids_data = _data(ids)
    if ids_data.ndim > 1 and ids_data.shape[-1] == 1:
        ids_data = ids_data[..., 0]
    tape = ctx.env.get("@SPARSE_TAPE@")
    wname = ctx.op.inputs["W"][0]
    if tape is not None and tape.wants(wname):
        gathered = jnp.take(jax.lax.stop_gradient(w), ids_data, axis=0)
        out = gathered + tape.next_slot(gathered)
        rows = ids_data.astype(jnp.int32)
        if isinstance(ids, LoDArray):
            # padding tokens must not touch row 0: point them out of range
            # so the row-wise optimizer update drops them
            rows = jnp.where(ids.seq_ids >= 0, rows, w.shape[0])
        tape.record_site(wname, rows)
    else:
        out = jnp.take(w, ids_data, axis=0)
    if ctx.attr("padding_idx") is not None:
        pad = ctx.attr("padding_idx")
        out = jnp.where((ids_data == pad)[..., None], 0.0, out)
    ctx.set_output("Out", _like(ids, out))


@register_op("fill_constant")
def fill_constant_kernel(ctx):
    shape = ctx.attr("shape")
    value = ctx.attr("value", 0.0)
    dtype = np.dtype(ctx.attr("dtype", "float32"))
    ctx.set_output("Out", jnp.full(shape, value, dtype=dtype))


@register_op("assign")
def assign_kernel(ctx):
    ctx.set_output("Out", ctx.input("X"))


@register_op("increment")
def increment_kernel(ctx):
    x_in = ctx.input("X")
    x = _data(x_in)
    # cast the step to x's dtype: int counters must stay ints
    out = x + jnp.asarray(ctx.attr("step", 1.0), dtype=x.dtype)
    ctx.set_output("Out", _like(x_in, out))


@register_op("argmax")
def argmax_kernel(ctx):
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.argmax(x, axis=ctx.attr("axis", -1)).astype(jnp.int32))


# ------------------------------------------------------------ initializers -
@register_op("uniform_random")
def uniform_random_kernel(ctx):
    """Reference: paddle/operators/uniform_random_op.cc."""
    shape = ctx.attr("shape")
    lo, hi = ctx.attr("min", -1.0), ctx.attr("max", 1.0)
    out = jax.random.uniform(
        ctx.rng(), shape, minval=lo, maxval=hi, dtype=jnp.float32
    )
    ctx.set_output("Out", out.astype(np.dtype(ctx.attr("dtype", "float32"))))


@register_op("gaussian_random")
def gaussian_random_kernel(ctx):
    shape = ctx.attr("shape")
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.normal(ctx.rng(), shape, dtype=jnp.float32)
    ctx.set_output("Out", out.astype(np.dtype(ctx.attr("dtype", "float32"))))


@register_op("truncated_gaussian_random")
def truncated_gaussian_random_kernel(ctx):
    shape = ctx.attr("shape")
    mean, std = ctx.attr("mean", 0.0), ctx.attr("std", 1.0)
    out = mean + std * jax.random.truncated_normal(
        ctx.rng(), -2.0, 2.0, shape, dtype=jnp.float32
    )
    ctx.set_output("Out", out.astype(np.dtype(ctx.attr("dtype", "float32"))))
