"""Op kernel library. Importing this package registers all kernels.

Reference: paddle/operators/ — 191 op families registered via REGISTER_OP
(framework/op_registry.h:148). Here each submodule registers pure-JAX
kernels with core.registry; gradients are derived by jax.grad over the
traced program instead of hand-written grad kernels.
"""

from . import activation_ops  # noqa: F401
from . import attention_ops  # noqa: F401
from . import control_flow_ops  # noqa: F401
from . import cost_ops  # noqa: F401
from . import crf_ops  # noqa: F401
from . import misc_ops  # noqa: F401
from . import ctc_ops  # noqa: F401
from . import detection_ops  # noqa: F401
from . import generation_ops  # noqa: F401
from . import math_ops  # noqa: F401
from . import nn_ops  # noqa: F401
from . import flash_ops  # noqa: F401
from . import fused_conv_ops  # noqa: F401
from . import optimizer_ops  # noqa: F401
from . import quant_kernels  # noqa: F401
from . import recurrent_ops  # noqa: F401
from . import rnn_ops  # noqa: F401
from . import sequence_ops  # noqa: F401
from ..core.registry import registered_ops  # noqa: F401
