"""beam_search_group op kernel: generic jitted beam-search generation.

Reference: RecurrentGradientMachine::beamSearch
(RecurrentGradientMachine.h:309) — per-step: run the frame net on every
live hypothesis, expand by the vocabulary, prune to the beam width
(hl_top_k.cu), freeze finished hypotheses; then decode by backtracking.
Fluid equivalents: beam_search_op.cc / beam_search_decode_op.cc.

The step network is a traced program sub-block (the generic analogue of
the frame net), run on the flattened [B*K, ...] beam batch each scan step.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from . import beam_common


def _tile_beam(x, K):
    """[B, ...] -> [B*K, ...] (repeat each example K times)."""
    return jnp.repeat(x, K, axis=0)


@register_op("beam_search_group")
def beam_search_group_kernel(ctx):
    boots = ctx.inputs("Boot")
    per_example_vals = ctx.inputs("PerExample")
    K = ctx.attr("beam_size", 4)
    T = ctx.attr("max_len", 32)
    bos = ctx.attr("bos_id", 0)
    eos = ctx.attr("eos_id", 1)
    norm_by_len = ctx.attr("length_normalize", False)
    prev_inner = ctx.attr("prev_inner")
    mem_inner = list(ctx.attr("mem_inner"))
    mem_update = list(ctx.attr("mem_update"))
    per_example = list(ctx.attr("per_example"))
    logits_inner = ctx.attr("logits_inner")

    if not boots:
        raise ValueError("beam_search_group needs at least one booted memory")
    b0 = boots[0]
    b0 = b0.data if isinstance(b0, LoDArray) else b0
    B = b0.shape[0]

    block = ctx.executor.program.blocks[ctx.attr("sub_block")]
    outer_env = dict(ctx.env)
    # per-decode RNG stream (same per-frame freshness recurrent_ops gives):
    # consume one outer counter, fold the step index in inside the scan
    base_key = jax.random.fold_in(
        outer_env["@RNG@"], outer_env.get("@RNG_COUNTER@", 0)
    )
    ctx.env["@RNG_COUNTER@"] = outer_env.get("@RNG_COUNTER@", 0) + 1
    # shadow per-example closure tensors with their beam-tiled versions
    for name, v in zip(per_example, per_example_vals):
        v = v.data if isinstance(v, LoDArray) else v
        outer_env[name] = _tile_beam(v, K)

    mems0 = []
    for bv in boots:
        bv = bv.data if isinstance(bv, LoDArray) else bv
        mems0.append(jnp.broadcast_to(bv[:, None], (B, K) + bv.shape[1:]))

    tokens = jnp.full((B, K), bos, jnp.int32)
    scores = beam_common.init_scores(B, K)
    finished = jnp.zeros((B, K), bool)

    def step(carry, t):
        mems, tok, sc, fin = carry
        env = dict(outer_env)
        env["@RNG@"] = jax.random.fold_in(base_key, t)
        env["@RNG_COUNTER@"] = 0
        env[prev_inner] = tok.reshape(B * K)
        for name, m in zip(mem_inner, mems):
            env[name] = m.reshape((B * K,) + m.shape[2:])
        ctx.executor.run_ops(block.ops, env, dict(env), block)
        logits = env[logits_inner]
        V = logits.shape[-1]
        logits = logits.reshape(B, K, V).astype(jnp.float32)
        new_mems = tuple(
            jnp.where(
                fin.reshape(B, K, *([1] * (m.ndim - 2))),
                m,
                env[u].reshape(m.shape),
            )
            for u, m in zip(mem_update, mems)
        )
        logp = jax.nn.log_softmax(logits, axis=-1)
        logp = beam_common.freeze_finished(logp, fin, eos)
        top_sc, parent, new_tok = beam_common.expand_prune(sc, logp, K)
        sel_mems = tuple(
            jnp.take_along_axis(
                m, parent.reshape(B, K, *([1] * (m.ndim - 2))), axis=1
            )
            for m in new_mems
        )
        fin_sel = jnp.take_along_axis(fin, parent, axis=1)
        new_fin = fin_sel | (new_tok == eos)
        return (sel_mems, new_tok, top_sc, new_fin), (parent, new_tok)

    (_, _, final_scores, _), (parents, toks) = jax.lax.scan(
        step, (tuple(mems0), tokens, scores, finished),
        jnp.arange(T, dtype=jnp.int32),
    )

    ids = beam_common.backtrack(parents, toks, B, K)
    ids, out_scores, lengths = beam_common.finalize(
        ids, final_scores, eos, T, norm_by_len
    )

    ctx.set_output("Ids", ids)
    ctx.set_output("Scores", out_scores)
    ctx.set_output("Lengths", lengths)
