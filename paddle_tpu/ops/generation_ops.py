"""beam_search_group op kernel: generic jitted beam-search generation.

Reference: RecurrentGradientMachine::beamSearch
(RecurrentGradientMachine.h:309) — per-step: run the frame net on every
live hypothesis, expand by the vocabulary, prune to the beam width
(hl_top_k.cu), freeze finished hypotheses; then decode by backtracking.
Fluid equivalents: beam_search_op.cc / beam_search_decode_op.cc.

The step network is a traced program sub-block (the generic analogue of
the frame net), run on the flattened [B*K, ...] beam batch each scan step.

The single decode step is factored out as `beam_step` with an explicit
carried-state contract so TWO consumers compile the SAME math:

- the `beam_search_group` kernel wraps it in a fixed-length lax.scan over
  the whole request batch (batch-mode decode: every request rides the
  scan for max_len steps regardless of when its beams finish);
- `serving/scheduler.py` wraps it with slot masking into a pool step for
  continuous batching (one step over a fixed pool of decode slots, new
  requests admitted into slots freed by early-finishing ones).

Sharing the step function is what makes the continuous scheduler's
bit-identical-to-batch-mode guarantee testable rather than aspirational:
the per-slot computation of a pool step IS the per-example computation of
a scan step (every op in the step sub-block, plus log_softmax/top_k
pruning, is independent along the example axis).
"""

from __future__ import annotations

from typing import Any, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from . import beam_common

__all__ = [
    "GenSpec",
    "DecodeState",
    "beam_step",
    "greedy_step",
    "find_generation_op",
    "gen_spec_from_op",
]


class GenSpec(NamedTuple):
    """Static description of one beam_search_group op — everything a
    consumer needs to trace the step sub-block outside the op kernel."""

    beam_size: int
    max_len: int
    bos_id: int
    eos_id: int
    length_normalize: bool
    sub_block: int
    prev_inner: str
    mem_inner: Tuple[str, ...]
    mem_update: Tuple[str, ...]
    per_example: Tuple[str, ...]  # inner names the step body reads
    logits_inner: str
    boot_names: Tuple[str, ...]  # block-0 vars booting each memory
    per_example_names: Tuple[str, ...]  # block-0 vars tiled to the beam
    out_names: Tuple[str, str, str]  # (Ids, Scores, Lengths) var names


class DecodeState(NamedTuple):
    """Device-resident decode pool state — the carried-state pytree of
    continuous batching. Leading axis S = number of slots; each slot is
    one request example with K live hypotheses.

    `parents`/`trellis_tok` are the (parent, token) trellis written one
    column per step; a retiring slot is backtracked over its own
    `step[s]` columns only, so stale columns from a previous occupant
    are never read."""

    mems: Tuple[jnp.ndarray, ...]  # each [S, K, ...]
    tok: jnp.ndarray  # [S, K] int32 — token emitted at the last step
    scores: jnp.ndarray  # [S, K] float32 cumulative log-probs
    fin: jnp.ndarray  # [S, K] bool
    step: jnp.ndarray  # [S] int32 — decode position per slot
    parents: jnp.ndarray  # [S, K, T] int32 trellis
    trellis_tok: jnp.ndarray  # [S, K, T] int32 trellis
    pe: Tuple[jnp.ndarray, ...]  # per-example tensors, each [S*K, ...]


def find_generation_op(program):
    """The block-0 beam_search_group op, or None (non-generative model)."""
    for op in program.global_block().ops:
        if op.type == "beam_search_group":
            return op
    return None


def gen_spec_from_op(op) -> GenSpec:
    return GenSpec(
        beam_size=int(op.attrs.get("beam_size", 4)),
        max_len=int(op.attrs.get("max_len", 32)),
        bos_id=int(op.attrs.get("bos_id", 0)),
        eos_id=int(op.attrs.get("eos_id", 1)),
        length_normalize=bool(op.attrs.get("length_normalize", False)),
        sub_block=int(op.attrs["sub_block"]),
        prev_inner=op.attrs["prev_inner"],
        mem_inner=tuple(op.attrs.get("mem_inner", ())),
        mem_update=tuple(op.attrs.get("mem_update", ())),
        per_example=tuple(op.attrs.get("per_example", ())),
        logits_inner=op.attrs["logits_inner"],
        boot_names=tuple(op.inputs.get("Boot", [])),
        per_example_names=tuple(op.inputs.get("PerExample", [])),
        out_names=(
            op.outputs["Ids"][0],
            op.outputs["Scores"][0],
            op.outputs["Lengths"][0],
        ),
    )


def _tile_beam(x, K):
    """[B, ...] -> [B*K, ...] (repeat each example K times)."""
    return jnp.repeat(x, K, axis=0)


def beam_step(runner, block, spec: GenSpec, env: Dict[str, Any],
              mems, tok, sc, fin):
    """ONE beam-search decode step over a [B, K] hypothesis batch.

    `env` must already hold everything the step sub-block closes over:
    parameters, per-example tensors tiled to [B*K, ...] under
    `spec.per_example` names, plus @RNG@/@RNG_COUNTER@/@AMP@. It is
    mutated (the sub-block ops write into it) — pass a per-step copy.

    Returns (new_mems, new_tok, new_sc, new_fin, parent): the carried
    state after expand/prune plus the parent pointers for the trellis.
    """
    B, K = tok.shape
    env[spec.prev_inner] = tok.reshape(B * K)
    for name, m in zip(spec.mem_inner, mems):
        env[name] = m.reshape((B * K,) + m.shape[2:])
    runner.run_ops(block.ops, env, dict(env), block)
    logits = env[spec.logits_inner]
    V = logits.shape[-1]
    logits = logits.reshape(B, K, V).astype(jnp.float32)
    new_mems = tuple(
        jnp.where(
            fin.reshape(B, K, *([1] * (m.ndim - 2))),
            m,
            env[u].reshape(m.shape),
        )
        for u, m in zip(spec.mem_update, mems)
    )
    logp = jax.nn.log_softmax(logits, axis=-1)
    logp = beam_common.freeze_finished(logp, fin, spec.eos_id)
    top_sc, parent, new_tok = beam_common.expand_prune(sc, logp, K)
    sel_mems = tuple(
        jnp.take_along_axis(
            m, parent.reshape(B, K, *([1] * (m.ndim - 2))), axis=1
        )
        for m in new_mems
    )
    fin_sel = jnp.take_along_axis(fin, parent, axis=1)
    new_fin = fin_sel | (new_tok == spec.eos_id)
    return sel_mems, new_tok, top_sc, new_fin, parent


def greedy_step(runner, block, spec: GenSpec, env: Dict[str, Any],
                mems, tok):
    """ONE greedy (single-hypothesis) decode step over a [B] batch —
    the DRAFT side of speculative decoding (serving/scheduler.py).

    Same step sub-block contract as `beam_step` with K = 1 and no
    beam bookkeeping: `env` must hold parameters, per-example tensors
    at [B, ...] under `spec.per_example` names, and @RNG@/@AMP@; it is
    mutated. `mems` are [B, ...] (no beam axis), `tok` is [B] int32.
    Returns (new_mems, new_tok) where new_tok is the argmax of the step
    logits — a proposal the TARGET model verifies with full `beam_step`
    math, so draft quality only moves the accept rate, never the
    output (verification is exact)."""
    env[spec.prev_inner] = tok
    for name, m in zip(spec.mem_inner, mems):
        env[name] = m
    runner.run_ops(block.ops, env, dict(env), block)
    logits = env[spec.logits_inner].astype(jnp.float32)
    new_mems = tuple(
        env[u].reshape(m.shape) for u, m in zip(spec.mem_update, mems))
    new_tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return new_mems, new_tok


@register_op("beam_search_group")
def beam_search_group_kernel(ctx):
    boots = ctx.inputs("Boot")
    per_example_vals = ctx.inputs("PerExample")
    spec = gen_spec_from_op(ctx.op)
    K, T = spec.beam_size, spec.max_len

    if not boots:
        raise ValueError("beam_search_group needs at least one booted memory")
    b0 = boots[0]
    b0 = b0.data if isinstance(b0, LoDArray) else b0
    B = b0.shape[0]

    block = ctx.executor.program.blocks[spec.sub_block]
    outer_env = dict(ctx.env)
    # per-decode RNG stream (same per-frame freshness recurrent_ops gives):
    # consume one outer counter, fold the step index in inside the scan
    base_key = jax.random.fold_in(
        outer_env["@RNG@"], outer_env.get("@RNG_COUNTER@", 0)
    )
    ctx.env["@RNG_COUNTER@"] = outer_env.get("@RNG_COUNTER@", 0) + 1
    # shadow per-example closure tensors with their beam-tiled versions
    for name, v in zip(spec.per_example, per_example_vals):
        v = v.data if isinstance(v, LoDArray) else v
        outer_env[name] = _tile_beam(v, K)

    mems0 = []
    for bv in boots:
        bv = bv.data if isinstance(bv, LoDArray) else bv
        mems0.append(jnp.broadcast_to(bv[:, None], (B, K) + bv.shape[1:]))

    tokens = jnp.full((B, K), spec.bos_id, jnp.int32)
    scores = beam_common.init_scores(B, K)
    finished = jnp.zeros((B, K), bool)

    def step(carry, t):
        mems, tok, sc, fin = carry
        env = dict(outer_env)
        env["@RNG@"] = jax.random.fold_in(base_key, t)
        env["@RNG_COUNTER@"] = 0
        sel_mems, new_tok, top_sc, new_fin, parent = beam_step(
            ctx.executor, block, spec, env, mems, tok, sc, fin
        )
        return (sel_mems, new_tok, top_sc, new_fin), (parent, new_tok)

    (_, _, final_scores, _), (parents, toks) = jax.lax.scan(
        step, (tuple(mems0), tokens, scores, finished),
        jnp.arange(T, dtype=jnp.int32),
    )

    ids = beam_common.backtrack(parents, toks, B, K)
    ids, out_scores, lengths = beam_common.finalize(
        ids, final_scores, spec.eos_id, T, spec.length_normalize
    )

    ctx.set_output("Ids", ids)
    ctx.set_output("Scores", out_scores)
    ctx.set_output("Lengths", lengths)
