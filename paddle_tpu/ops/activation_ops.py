"""Activation op kernels.

Reference: paddle/gserver/activations/ActivationFunction.cpp (15 Gen-1
activation types via BEGIN_DEFINE_ACTIVATION) and
paddle/operators/activation_op.cc (28 Fluid activation ops: sigmoid,
logsigmoid, exp, relu, tanh, tanh_shrink, softshrink, sqrt, abs, ceil,
floor, round, reciprocal, log, square, softplus, softsign, brelu,
leaky_relu, soft_relu, elu, relu6, pow, stanh, hard_shrink,
thresholded_relu, hard_sigmoid, swish). All map to jnp/jax.nn primitives;
XLA fuses them into neighbouring matmuls so no custom kernels are needed —
this is exactly the elementwise-fusion case the MXU pipeline handles free.

Gradients come from jax.grad (core/executor.py); no backward kernels.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op

# name -> fn(x, attr) ; attrs carry the reference's default thresholds
_ACTIVATIONS = {
    "identity": lambda x, a: x,
    "linear": lambda x, a: x,
    "sigmoid": lambda x, a: jax.nn.sigmoid(x),
    "logsigmoid": lambda x, a: jax.nn.log_sigmoid(x),
    "exp": lambda x, a: jnp.exp(x),
    "exponential": lambda x, a: jnp.exp(x),
    "relu": lambda x, a: jax.nn.relu(x),
    "tanh": lambda x, a: jnp.tanh(x),
    "tanh_shrink": lambda x, a: x - jnp.tanh(x),
    "softshrink": lambda x, a: jnp.sign(x)
    * jnp.maximum(jnp.abs(x) - a.get("lambda", 0.5), 0.0),
    "sqrt": lambda x, a: jnp.sqrt(x),
    "abs": lambda x, a: jnp.abs(x),
    "ceil": lambda x, a: jnp.ceil(x),
    "floor": lambda x, a: jnp.floor(x),
    "round": lambda x, a: jnp.round(x),
    "reciprocal": lambda x, a: 1.0 / x,
    "log": lambda x, a: jnp.log(x),
    "square": lambda x, a: jnp.square(x),
    "softplus": lambda x, a: jax.nn.softplus(x),
    "softsign": lambda x, a: jax.nn.soft_sign(x),
    # brelu: clipped relu, reference default t_min=0, t_max=24
    "brelu": lambda x, a: jnp.clip(x, a.get("t_min", 0.0), a.get("t_max", 24.0)),
    "leaky_relu": lambda x, a: jax.nn.leaky_relu(x, a.get("alpha", 0.02)),
    # soft_relu: ln(1+e^clip(x)) with threshold 40 (activation_op.cc SoftRelu)
    "soft_relu": lambda x, a: jnp.log1p(
        jnp.exp(jnp.clip(x, -a.get("threshold", 40.0), a.get("threshold", 40.0)))
    ),
    "softrelu": lambda x, a: jnp.log1p(
        jnp.exp(jnp.clip(x, -40.0, 40.0))
    ),
    "elu": lambda x, a: jax.nn.elu(x, a.get("alpha", 1.0)),
    "relu6": lambda x, a: jnp.clip(x, 0.0, a.get("threshold", 6.0)),
    "pow": lambda x, a: jnp.power(x, a.get("factor", 1.0)),
    # stanh: a*tanh(b*x), reference defaults a=1.7159, b=2/3
    "stanh": lambda x, a: a.get("scale_a", 1.7159)
    * jnp.tanh(a.get("scale_b", 2.0 / 3.0) * x),
    "hard_shrink": lambda x, a: jnp.where(
        jnp.abs(x) > a.get("threshold", 0.5), x, 0.0
    ),
    "thresholded_relu": lambda x, a: jnp.where(
        x > a.get("threshold", 1.0), x, 0.0
    ),
    "hard_sigmoid": lambda x, a: jnp.clip(
        a.get("slope", 0.2) * x + a.get("offset", 0.5), 0.0, 1.0
    ),
    "swish": lambda x, a: x * jax.nn.sigmoid(a.get("beta", 1.0) * x),
    # gelu (tanh approximation, the transformer default; beyond the
    # reference's 2017 set — added with models/transformer.py)
    "gelu": lambda x, a: jax.nn.gelu(x, approximate=True),
}


def apply_activation(x, act: str, attrs=None):
    """Apply a named activation to an array or LoDArray."""
    if act is None:
        return x
    attrs = attrs or {}
    if act == "softmax":
        fn = lambda v: jax.nn.softmax(v, axis=-1)
    elif act == "sequence_softmax":
        from .sequence_ops import sequence_softmax_impl

        return sequence_softmax_impl(x)
    else:
        try:
            fn = lambda v, _f=_ACTIVATIONS[act]: _f(v, attrs)
        except KeyError:
            raise NotImplementedError(f"unknown activation {act!r}") from None
    if isinstance(x, LoDArray):
        return x.with_data(fn(x.data))
    return fn(x)


def _make_kernel(name):
    def kernel(ctx):
        x = ctx.input("X")
        ctx.set_output("Out", apply_activation(x, name, ctx.op.attrs))

    return kernel


for _name in list(_ACTIVATIONS) + ["softmax_activation"]:
    register_op(_name if _name != "softmax_activation" else "softmax_activation")(
        _make_kernel(_name if _name != "softmax_activation" else "softmax")
    )


@register_op("softmax")
def softmax_kernel(ctx):
    """Reference: paddle/operators/softmax_op.cc — softmax over last dim."""
    ctx.set_output("Out", apply_activation(ctx.input("X"), "softmax"))
