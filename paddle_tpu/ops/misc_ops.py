"""Tensor-manipulation op kernels widening parity with the reference layer set.

Reference coverage (Gen-1 gserver/layers + Fluid operators):
  gather/scatter            paddle/operators/{gather_op,scatter_op}.cc
  one_hot                   paddle/operators/one_hot_op (post-ref; Gen-1 uses
                            sparse index inputs for the same purpose)
  pad / crop                gserver/layers/{PadLayer,CropLayer}.cpp,
                            operators/{pad_op,crop_op}.cc
  multiplex                 gserver/layers/MultiplexLayer.cpp,
                            operators/multiplex_op.cc
  maxout                    gserver/layers/MaxOutLayer.cpp,
                            operators/math/maxouting.cc
  prelu                     gserver/layers/PReluLayer.cpp (prelu registry)
  cos_sim                   gserver/layers/CosSimLayer.cpp (cos),
                            operators/cos_sim_op.cc
  dot_prod / out_prod       gserver/layers/{DotProdLayer,OuterProdLayer}.cpp
  l2_distance / row_l2_norm gserver/layers/{L2DistanceLayer,RowL2NormLayer}.cpp
  interpolation             gserver/layers/InterpolationLayer.cpp
  power / scaling           gserver/layers/{PowerLayer,ScalingLayer}.cpp
  slope_intercept           gserver/layers/SlopeInterceptLayer.cpp
  sum_to_one_norm           gserver/layers/SumToOneNormLayer.cpp
  convex_comb               gserver/layers/ConvexCombinationLayer.cpp (cos_vm
                            family sibling)
  scale_shift               gserver/layers/ScaleShiftLayer.cpp
  scale_sub_region          gserver/layers/ScaleSubRegionLayer.cpp
  bilinear_interp           gserver/layers/BilinearInterpLayer.cpp,
                            operators/bilinear_interp_op (resize)
  rotate / switch_order     gserver/layers/{RotateLayer,SwitchOrderLayer}.cpp
  im2sequence (blockexpand) gserver/layers/BlockExpandLayer.cpp
  row_conv                  gserver/layers/RowConvLayer.cpp,
                            operators/row_conv_op.cc (lookahead conv)
  conv_shift                gserver/layers/ConvShiftLayer.cpp (circular conv)
  sampling_id               gserver/layers/SamplingIdLayer.cpp
  factorization_machine     gserver/layers/FactorizationMachineLayer.cpp
  tensor (bilinear product) gserver/layers/TensorLayer.cpp
  conv3d / pool3d           gserver/layers/{Conv3DLayer,Pool3DLayer}.cpp
  roi_pool                  gserver/layers/ROIPoolLayer.cpp
  spp                       gserver/layers/SpatialPyramidPoolLayer.cpp

All kernels are pure jnp/lax; gradients come from jax.grad over the traced
program. Gather/scatter stay static-shaped (TPU requirement).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from .. import amp
from ..core.lod import LoDArray
from ..core.registry import register_op


def _data(x):
    return x.data if isinstance(x, LoDArray) else x


def _like(x, data):
    return x.with_data(data) if isinstance(x, LoDArray) else data


# ------------------------------------------------------- gather / scatter ---
@register_op("gather")
def gather_kernel(ctx):
    x = _data(ctx.input("X"))
    idx = _data(ctx.input("Index")).reshape(-1).astype(jnp.int32)
    ctx.set_output("Out", jnp.take(x, idx, axis=0))


@register_op("scatter")
def scatter_kernel(ctx):
    """Reference scatter_op.cc: Out = X; Out[Index] op= Updates (overwrite or
    add)."""
    x = _data(ctx.input("X"))
    idx = _data(ctx.input("Index")).reshape(-1).astype(jnp.int32)
    upd = _data(ctx.input("Updates"))
    if ctx.attr("overwrite", True):
        out = x.at[idx].set(upd)
    else:
        out = x.at[idx].add(upd)
    ctx.set_output("Out", out)


@register_op("one_hot")
def one_hot_kernel(ctx):
    x = _data(ctx.input("X")).reshape(-1).astype(jnp.int32)
    depth = ctx.attr("depth")
    ctx.set_output("Out", jax.nn.one_hot(x, depth, dtype=jnp.float32))


# ------------------------------------------------------------- pad / crop ---
@register_op("pad")
def pad_kernel(ctx):
    """paddings attr: flat [lo0, hi0, lo1, hi1, ...] per the reference."""
    x = _data(ctx.input("X"))
    p = ctx.attr("paddings")
    val = ctx.attr("pad_value", 0.0)
    cfg = [(int(p[2 * i]), int(p[2 * i + 1])) for i in range(x.ndim)]
    ctx.set_output("Out", jnp.pad(x, cfg, constant_values=val))


@register_op("crop")
def crop_kernel(ctx):
    x = _data(ctx.input("X"))
    offsets = ctx.attr("offsets")
    shape = ctx.attr("shape")
    ctx.set_output(
        "Out", jax.lax.dynamic_slice(x, [int(o) for o in offsets], [int(s) for s in shape])
    )


@register_op("multiplex")
def multiplex_kernel(ctx):
    """Row-wise select among N inputs by per-row index."""
    ids = _data(ctx.input("Ids")).reshape(-1).astype(jnp.int32)
    xs = jnp.stack([_data(x) for x in ctx.inputs("X")], axis=0)  # [n, rows, d]
    rows = jnp.arange(xs.shape[1])
    ctx.set_output("Out", xs[ids, rows])


# ------------------------------------------------------- simple transforms --
@register_op("maxout")
def maxout_kernel(ctx):
    """[N, C, H, W] → [N, C/groups, H, W], max over each group of channels."""
    x = _data(ctx.input("X"))
    g = ctx.attr("groups")
    n, c, h, w = x.shape
    ctx.set_output("Out", x.reshape(n, c // g, g, h, w).max(axis=2))


@register_op("prelu")
def prelu_kernel(ctx):
    x = _data(ctx.input("X"))
    alpha = _data(ctx.input("Alpha"))
    mode = ctx.attr("mode", "all")
    if mode == "channel" and x.ndim == 4:
        alpha = alpha.reshape(1, -1, 1, 1)
    ctx.set_output("Out", jnp.where(x > 0, x, alpha * x))


@register_op("cos_sim")
def cos_sim_kernel(ctx):
    """Row-wise cosine similarity, scaled (reference CosSimLayer scale)."""
    x = _data(ctx.input("X"))
    y = _data(ctx.input("Y"))
    scale = ctx.attr("scale", 1.0)
    eps = 1e-8
    num = jnp.sum(x * y, axis=-1, keepdims=True)
    den = jnp.linalg.norm(x, axis=-1, keepdims=True) * jnp.linalg.norm(
        y, axis=-1, keepdims=True
    )
    ctx.set_output("Out", _like(ctx.input("X"), scale * num / jnp.maximum(den, eps)))


@register_op("dot_prod")
def dot_prod_kernel(ctx):
    x, y = _data(ctx.input("X")), _data(ctx.input("Y"))
    ctx.set_output("Out", _like(ctx.input("X"), jnp.sum(x * y, axis=-1, keepdims=True)))


@register_op("out_prod")
def out_prod_kernel(ctx):
    x, y = _data(ctx.input("X")), _data(ctx.input("Y"))
    ctx.set_output("Out", (x[:, :, None] * y[:, None, :]).reshape(x.shape[0], -1))


@register_op("l2_distance")
def l2_distance_kernel(ctx):
    x, y = _data(ctx.input("X")), _data(ctx.input("Y"))
    d = x - y
    ctx.set_output("Out", _like(ctx.input("X"), jnp.sqrt(jnp.sum(d * d, axis=-1, keepdims=True) + 1e-12)))


@register_op("row_l2_norm")
def row_l2_norm_kernel(ctx):
    x = _data(ctx.input("X"))
    n = jnp.linalg.norm(x, axis=-1, keepdims=True)
    ctx.set_output("Out", _like(ctx.input("X"), x / jnp.maximum(n, 1e-12)))


@register_op("interpolation")
def interpolation_kernel(ctx):
    """out = w*x + (1-w)*y, w a per-row scalar (InterpolationLayer.cpp)."""
    w = _data(ctx.input("W"))
    x = _data(ctx.input("X"))
    y = _data(ctx.input("Y"))
    w = w.reshape(-1, 1)
    ctx.set_output("Out", _like(ctx.input("X"), w * x + (1.0 - w) * y))


@register_op("power")
def power_kernel(ctx):
    """out = x ^ w, w per-row scalar (PowerLayer.cpp)."""
    w = _data(ctx.input("W")).reshape(-1, 1)
    x = _data(ctx.input("X"))
    ctx.set_output("Out", _like(ctx.input("X"), jnp.power(x, w)))


@register_op("scaling")
def scaling_kernel(ctx):
    """out = w * x row-wise, w per-row scalar (ScalingLayer.cpp)."""
    w = _data(ctx.input("W")).reshape(-1, 1)
    x = _data(ctx.input("X"))
    ctx.set_output("Out", _like(ctx.input("X"), w * x))


@register_op("slope_intercept")
def slope_intercept_kernel(ctx):
    x = _data(ctx.input("X"))
    ctx.set_output(
        "Out", _like(ctx.input("X"), ctx.attr("slope", 1.0) * x + ctx.attr("intercept", 0.0))
    )


@register_op("sum_to_one_norm")
def sum_to_one_norm_kernel(ctx):
    x = _data(ctx.input("X"))
    s = jnp.sum(x, axis=-1, keepdims=True)
    ctx.set_output("Out", _like(ctx.input("X"), x / jnp.where(jnp.abs(s) < 1e-12, 1.0, s)))


@register_op("convex_comb")
def convex_comb_kernel(ctx):
    """ConvexCombinationLayer: weights [N, K], X [N, K*D] → sum_k w_k x_k."""
    w = _data(ctx.input("W"))
    x = _data(ctx.input("X"))
    n, k = w.shape
    d = x.shape[1] // k
    ctx.set_output("Out", jnp.einsum("nk,nkd->nd", w, x.reshape(n, k, d)))


@register_op("scale_shift")
def scale_shift_kernel(ctx):
    x = _data(ctx.input("X"))
    out = x * _data(ctx.input("Scale")).reshape(())
    if ctx.has_input("Bias"):
        out = out + _data(ctx.input("Bias")).reshape(())
    ctx.set_output("Out", out)


@register_op("scale_sub_region")
def scale_sub_region_kernel(ctx):
    """Scale a [c0:c1, h0:h1, w0:w1] sub-box of NCHW input (1-based incl.
    indices attr, per reference ScaleSubRegionLayer)."""
    x = _data(ctx.input("X"))
    c0, c1, h0, h1, w0, w1 = [int(v) for v in ctx.attr("indices")]
    scale = ctx.attr("scale", 1.0)
    mask = np.zeros(x.shape[1:], np.float32)
    mask[c0 - 1 : c1, h0 - 1 : h1, w0 - 1 : w1] = 1.0
    m = jnp.asarray(mask)[None]
    ctx.set_output("Out", x * (1.0 - m) + x * m * scale)


@register_op("rotate")
def rotate_kernel(ctx):
    """90-degree CCW rotation of the HxW planes (RotateLayer.cpp)."""
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.rot90(x, k=1, axes=(-2, -1)))


@register_op("switch_order")
def switch_order_kernel(ctx):
    """NCHW → NHWC reorder (SwitchOrderLayer.cpp)."""
    x = _data(ctx.input("X"))
    ctx.set_output("Out", jnp.transpose(x, (0, 2, 3, 1)))


# ---------------------------------------------------------- interpolation ---
@register_op("bilinear_interp")
def bilinear_interp_kernel(ctx):
    """NCHW bilinear resize with align_corners=True semantics, matching the
    reference BilinearInterpLayer ratio = (in-1)/(out-1)."""
    x = _data(ctx.input("X"))
    oh, ow = ctx.attr("out_h"), ctx.attr("out_w")
    n, c, h, w = x.shape
    ry = (h - 1) / (oh - 1) if oh > 1 else 0.0
    rx = (w - 1) / (ow - 1) if ow > 1 else 0.0
    ys = jnp.arange(oh) * ry
    xs = jnp.arange(ow) * rx
    y0 = jnp.floor(ys).astype(jnp.int32)
    x0 = jnp.floor(xs).astype(jnp.int32)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (ys - y0)[None, None, :, None]
    wx = (xs - x0)[None, None, None, :]
    g = lambda yy, xx: x[:, :, yy, :][:, :, :, xx]
    out = (
        g(y0, x0) * (1 - wy) * (1 - wx)
        + g(y1, x0) * wy * (1 - wx)
        + g(y0, x1) * (1 - wy) * wx
        + g(y1, x1) * wy * wx
    )
    ctx.set_output("Out", out)


# ------------------------------------------------------------ conv family ---
@register_op("im2sequence")
def im2sequence_kernel(ctx):
    """BlockExpandLayer: extract conv-style patches, one sequence step per
    patch position (reference gserver/layers/BlockExpandLayer.cpp). Dense
    output [N, outH*outW, C*kh*kw]."""
    x = _data(ctx.input("X"))
    kh, kw = ctx.attr("block_y"), ctx.attr("block_x")
    sh, sw = ctx.attr("stride_y", 1), ctx.attr("stride_x", 1)
    ph, pw = ctx.attr("padding_y", 0), ctx.attr("padding_x", 0)
    patches = jax.lax.conv_general_dilated_patches(
        x,
        filter_shape=(kh, kw),
        window_strides=(sh, sw),
        padding=[(ph, ph), (pw, pw)],
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
    )  # [N, C*kh*kw, outH, outW]
    n, ckk, oh, ow = patches.shape
    ctx.set_output("Out", patches.reshape(n, ckk, oh * ow).transpose(0, 2, 1))


@register_op("row_conv")
def row_conv_kernel(ctx):
    """Lookahead row convolution (DeepSpeech2): out[t] = sum_{i<k} x[t+i] *
    w[i], per feature. Dense X: [N, T, D]. LoD X: flat [capacity, D] —
    the window is masked so it never crosses a sequence boundary
    (reference RowConvLayer walks each sequence separately)."""
    x_in = ctx.input("X")
    w = _data(ctx.input("Filter"))
    k = w.shape[0]
    if isinstance(x_in, LoDArray):
        x = x_in.data  # [capacity, D]
        ids = x_in.seq_ids
        cap = x.shape[0]
        xp = jnp.pad(x, ((0, k - 1), (0, 0)))
        idp = jnp.pad(ids, (0, k - 1), constant_values=-2)
        out = jnp.zeros_like(x)
        for i in range(k):  # k is small + static: unrolled, fuses on VPU
            same = (idp[i : i + cap] == ids)[:, None].astype(x.dtype)
            out = out + xp[i : i + cap, :] * same * w[i][None, :]
        ctx.set_output("Out", x_in.with_data(out))
        return
    x = x_in
    t = x.shape[1]
    xp = jnp.pad(x, ((0, 0), (0, k - 1), (0, 0)))
    out = jnp.zeros_like(x)
    for i in range(k):
        out = out + xp[:, i : i + t, :] * w[i][None, None, :]
    ctx.set_output("Out", out)


@register_op("conv_shift")
def conv_shift_kernel(ctx):
    """Circular convolution (ConvShiftLayer.cpp): X [N,D], Y [N,K] (K odd),
    out[n,d] = sum_j Y[n,j] * X[n, (d + j - K//2) mod D]."""
    x = _data(ctx.input("X"))
    y = _data(ctx.input("Y"))
    k = y.shape[1]
    half = k // 2
    out = jnp.zeros_like(x)
    for j in range(k):
        out = out + y[:, j : j + 1] * jnp.roll(x, half - j, axis=1)
    ctx.set_output("Out", out)


# ----------------------------------------------------------------- random ---
@register_op("sampling_id")
def sampling_id_kernel(ctx):
    """Sample one column index per row from a probability matrix."""
    x = _data(ctx.input("X"))
    ids = jax.random.categorical(ctx.rng(), jnp.log(jnp.maximum(x, 1e-20)), axis=-1)
    ctx.set_output("Out", ids.astype(jnp.int32))


# --------------------------------------------------------------- factored ---
@register_op("factorization_machine")
def factorization_machine_kernel(ctx):
    """2nd-order FM term: 0.5 * sum((xV)^2 - (x^2)(V^2), axis=1)."""
    x = _data(ctx.input("X"))
    v = _data(ctx.input("Factor"))
    xv = jnp.dot(x, v, preferred_element_type=jnp.float32)
    x2v2 = jnp.dot(x * x, v * v, preferred_element_type=jnp.float32)
    ctx.set_output("Out", 0.5 * jnp.sum(xv * xv - x2v2, axis=-1, keepdims=True))


@register_op("bilinear_tensor_product")
def bilinear_tensor_product_kernel(ctx):
    """TensorLayer: out[n,k] = x[n] @ W_k @ y[n] (+ bias)."""
    x = _data(ctx.input("X"))
    y = _data(ctx.input("Y"))
    w = _data(ctx.input("Weight"))  # [K, Dx, Dy]
    out = jnp.einsum("nd,kde,ne->nk", x, w, y)
    if ctx.has_input("Bias"):
        out = out + _data(ctx.input("Bias")).reshape(1, -1)
    ctx.set_output("Out", out)


@register_op("selective_fc")
def selective_fc_kernel(ctx):
    """SelectiveFullyConnectedLayer: fc whose output is masked to a selected
    subset of columns per row (dense mask form — TPU-static)."""
    x = _data(ctx.input("X"))
    w = _data(ctx.input("W"))  # [D, C]
    out = jnp.dot(x, w, preferred_element_type=jnp.float32)
    if ctx.has_input("Bias"):
        out = out + _data(ctx.input("Bias")).reshape(1, -1)
    if ctx.has_input("Mask"):
        out = out * _data(ctx.input("Mask"))
    ctx.set_output("Out", out)


# ---------------------------------------------------------------- 3-D ops ---
@register_op("conv3d")
def conv3d_kernel(ctx):
    """Reference: gserver/layers/Conv3DLayer.cpp. NCDHW layout."""
    x = _data(ctx.input("Input"))
    w = _data(ctx.input("Filter"))  # [out_c, in_c/groups, kd, kh, kw]
    stride = tuple(ctx.attr("strides", (1, 1, 1)))
    pad = tuple(ctx.attr("paddings", (0, 0, 0)))
    groups = ctx.attr("groups", 1)
    dtype = x.dtype
    xc, wc = amp.cast_inputs(ctx, x, w)
    acc = jnp.float32 if xc.dtype == jnp.float32 else None
    out = jax.lax.conv_general_dilated(
        xc,
        wc,
        window_strides=stride,
        padding=[(p, p) for p in pad],
        feature_group_count=groups,
        dimension_numbers=("NCDHW", "OIDHW", "NCDHW"),
        preferred_element_type=acc,
    ).astype(dtype)
    if ctx.has_input("Bias"):
        out = out + _data(ctx.input("Bias")).reshape((1, -1, 1, 1, 1))
    ctx.set_output("Output", out)


@register_op("pool3d")
def pool3d_kernel(ctx):
    x = _data(ctx.input("X"))
    ptype = ctx.attr("pooling_type", "max")
    ks = tuple(ctx.attr("ksize"))
    stride = tuple(ctx.attr("strides", ks))
    pad = tuple(ctx.attr("paddings", (0, 0, 0)))
    dims = (1, 1) + ks
    strides = (1, 1) + stride
    pads = ((0, 0), (0, 0)) + tuple((p, p) for p in pad)
    if ptype == "max":
        out = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, dims, strides, pads)
    else:
        s = jax.lax.reduce_window(x, 0.0, jax.lax.add, dims, strides, pads)
        cnt = jax.lax.reduce_window(
            jnp.ones_like(x), 0.0, jax.lax.add, dims, strides, pads
        )
        out = s / cnt
    ctx.set_output("Out", out)


# ------------------------------------------------------------- roi  / spp ---
@register_op("roi_pool")
def roi_pool_kernel(ctx):
    """ROIPoolLayer: max-pool each ROI box into a fixed [ph, pw] grid.
    Rois: [R, 5] = (batch_idx, x1, y1, x2, y2) in input-image coords."""
    x = _data(ctx.input("X"))  # [N, C, H, W]
    rois = _data(ctx.input("ROIs"))
    ph, pw = ctx.attr("pooled_height"), ctx.attr("pooled_width")
    scale = ctx.attr("spatial_scale", 1.0)
    n, c, h, w = x.shape

    def pool_one(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1] * scale).astype(jnp.int32)
        y1 = jnp.round(roi[2] * scale).astype(jnp.int32)
        x2 = jnp.round(roi[3] * scale).astype(jnp.int32)
        y2 = jnp.round(roi[4] * scale).astype(jnp.int32)
        rh = jnp.maximum(y2 - y1 + 1, 1)
        rw = jnp.maximum(x2 - x1 + 1, 1)
        img = x[b]  # [C, H, W]
        ys = jnp.arange(h)
        xs = jnp.arange(w)
        # floor/ceil per-bin windows — may overlap, exactly as the
        # reference ROIPoolLayer computes hstart/hend (floor(b*rh/ph),
        # ceil((b+1)*rh/ph)); membership per (bin, pixel)
        binr = jnp.arange(ph)
        binc = jnp.arange(pw)
        y_start = y1 + (binr * rh) // ph  # [ph]
        y_end = y1 + -((-(binr + 1) * rh) // ph)
        x_start = x1 + (binc * rw) // pw
        x_end = x1 + -((-(binc + 1) * rw) // pw)
        in_box_y = (ys >= y1) & (ys <= y2)
        in_box_x = (xs >= x1) & (xs <= x2)
        onehot_y = (
            (ys[None, :] >= y_start[:, None])
            & (ys[None, :] < y_end[:, None])
            & in_box_y[None, :]
        ).astype(x.dtype)
        onehot_x = (
            (xs[None, :] >= x_start[:, None])
            & (xs[None, :] < x_end[:, None])
            & in_box_x[None, :]
        ).astype(x.dtype)
        # max over pixels mapped to each bin; mask [ph,pw,1,H,W] + img
        # [C,H,W] broadcast to [ph,pw,C,H,W]
        in_bin = onehot_y[:, None, None, :, None] * onehot_x[None, :, None, None, :]
        masked = img + jnp.where(in_bin > 0, 0.0, -jnp.inf)
        pooled = jnp.max(masked, axis=(-2, -1))  # [ph, pw, C]
        # empty bins (ROI smaller than the grid) emit 0, matching the
        # reference ROIPoolLayer's zero-initialized output buffer
        pooled = jnp.where(jnp.isfinite(pooled), pooled, 0.0)
        return pooled.transpose(2, 0, 1)  # [C, ph, pw]

    out = jax.vmap(pool_one)(rois)
    ctx.set_output("Out", out)


@register_op("spp")
def spp_kernel(ctx):
    """Spatial pyramid pooling: concat pooled [2^l x 2^l] grids for l <
    pyramid_height (SpatialPyramidPoolLayer.cpp)."""
    x = _data(ctx.input("X"))
    levels = ctx.attr("pyramid_height", 3)
    ptype = ctx.attr("pooling_type", "max")
    n, c, h, w = x.shape
    outs = []
    for l in range(levels):
        bins = 2**l
        # per-bin floor/ceil windows as the reference computes start/end
        # indices — every bin covers >=1 real pixel, no padding involved
        def edges(dim):
            lo = [min((i * dim) // bins, dim - 1) for i in range(bins)]
            hi = [max(-(-((i + 1) * dim) // bins), lo[i] + 1) for i in range(bins)]
            return lo, [min(v, dim) if v > lo[i] else lo[i] + 1 for i, v in enumerate(hi)]

        ylo, yhi = edges(h)
        xlo, xhi = edges(w)
        for by in range(bins):
            for bx in range(bins):
                win = x[:, :, ylo[by] : yhi[by], xlo[bx] : xhi[bx]]
                outs.append(
                    win.max(axis=(2, 3)) if ptype == "max" else win.mean(axis=(2, 3))
                )
    ctx.set_output("Out", jnp.concatenate(outs, axis=1))
