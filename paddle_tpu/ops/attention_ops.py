"""Attention decoder + beam-search generation kernels.

Reference: the RecurrentGradientMachine (gserver/gradientmachines/
RecurrentGradientMachine.h:32 — per-timestep frames :428, memory links :342,
generateSequence :307, beamSearch :309) running the v2 book's
`simple_attention` recurrent group (trainer_config_helpers/networks.py),
and the Fluid counterparts beam_search_op.cc / beam_search_decode_op.cc.

TPU design: the reference clones a sub-network per timestep and walks
frames imperatively; here the whole decoder is ONE `lax.scan` whose body
fuses the attention score matmul, the masked softmax over source tokens,
the context reduction, and the GRU cell — XLA keeps the per-step state
(beam hypotheses, finished masks) resident on-chip. Beam search runs with
static shapes: a fixed `max_len` step count, `[B, K]` beam state, and a
`(parent, token)` trellis that is backtracked with a second scan — the
dynamic-length output of the reference becomes fixed-max-len + per-beam
length, which a host-side helper trims at EOS.

Attention is Bahdanau-style (the v2 book's simple_attention):
    score(s_j, h) = v · tanh(enc_proj_j + W_dec h)
with enc_proj precomputed once per batch ([B, S, A]) so each decode step
costs one [B, A]·[A] broadcast plus the softmax-weighted context sum.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from . import beam_common
from .rnn_ops import gru_cell


def _attention(h, enc, enc_proj, enc_mask, w_dec, v_att):
    """Bahdanau attention: h [B, H] or [B, K, H] → context [.., C].

    enc [B, S, C], enc_proj [B, S, A] (precomputed enc @ WaEnc),
    enc_mask [B, S]; score(s_j, h) = v · tanh(enc_proj_j + W_dec h)."""
    dec_proj = jnp.dot(h, w_dec, preferred_element_type=jnp.float32).astype(h.dtype)
    if h.ndim == 2:
        t = jnp.tanh(enc_proj + dec_proj[:, None, :])  # [B, S, A]
        scores = jnp.dot(t, v_att, preferred_element_type=jnp.float32).astype(h.dtype)
        scores = jnp.where(enc_mask, scores, -1e9)
        alpha = jax.nn.softmax(scores, axis=-1)  # [B, S]
        return jnp.einsum("bs,bsc->bc", alpha, enc)
    # beam case [B, K, H]
    t = jnp.tanh(enc_proj[:, None] + dec_proj[:, :, None, :])  # [B, K, S, A]
    scores = jnp.dot(t, v_att, preferred_element_type=jnp.float32).astype(h.dtype)
    scores = jnp.where(enc_mask[:, None], scores, -1e9)
    alpha = jax.nn.softmax(scores, axis=-1)  # [B, K, S]
    return jnp.einsum("bks,bsc->bkc", alpha, enc)


@register_op("attention_gru_decoder")
def attention_gru_decoder_kernel(ctx):
    """Training-time attention decoder (teacher forcing).

    Inputs:
      EncState  LoDArray [.., C]   encoder outputs over source tokens
      TrgEmb    LoDArray [.., E]   target-side input embeddings
      H0        [B, H]             decoder boot state
      WaEnc [C, A], WaDec [H, A], Va [A]        attention params
      Wx [(E+C), 3H], Wh [H, 3H], Bias [3H]     GRU params
    Output: Hidden LoDArray [.., H] aligned with TrgEmb's lod.
    """
    enc_l: LoDArray = ctx.input("EncState")
    trg_l: LoDArray = ctx.input("TrgEmb")
    h0 = ctx.input("H0")
    wa_enc, wa_dec, v_att = ctx.input("WaEnc"), ctx.input("WaDec"), ctx.input("Va")
    wx, wh = ctx.input("Wx"), ctx.input("Wh")
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None

    src_len = ctx.attr("src_max_len") or enc_l.capacity
    trg_len = ctx.attr("trg_max_len") or trg_l.capacity
    enc_b, enc_mask = enc_l.to_batch(max_len=src_len, time_major=False)  # [B,S,C]
    trg_b, trg_mask = trg_l.to_batch(max_len=trg_len)  # [T,B,E]
    # the decoder is matmul-heavy, so its inputs cast to the amp dtype
    # like fc's do (amp.py design: MXU op inputs cast down, activations
    # flow at 2 bytes). trg_emb arrives f32 straight from the embedding
    # gather — without this cast it silently pinned the WHOLE decoder
    # (and the fused kernels' [B,S,A] streams) to f32 under AMP
    from .. import amp

    trg_b = amp.cast_inputs(ctx, trg_b)
    # uniform compute dtype under amp: f32 master params cast down to the
    # activation dtype so the scan carry dtype is stable (see rnn_ops)
    dt = trg_b.dtype
    wa_enc, wa_dec, v_att = (w.astype(dt) for w in (wa_enc, wa_dec, v_att))
    wx, wh = wx.astype(dt), wh.astype(dt)
    bias = None if bias is None else bias.astype(dt)
    h0 = h0.astype(dt)
    enc_b = enc_b.astype(dt)
    enc_proj = jnp.dot(
        enc_b, wa_enc, preferred_element_type=jnp.float32
    ).astype(dt)  # [B, S, A]

    from .bahdanau_kernels import (fused_attention_decoder,
                                   fused_decoder_eligible)
    from .mesh_dispatch import local_batch

    B, S, A = enc_proj.shape
    # under a mesh the kernels run per-shard (shard_map): eligibility is
    # judged at the batch each shard actually sees
    if fused_decoder_eligible(local_batch(B), S, A, enc_b.shape[-1],
                              enc_b.dtype):
        # fused path: score+softmax+context in VMEM, whole-scan custom
        # VJP (bahdanau_kernels.py) — never materializes [B, S, A]
        h_seq = fused_attention_decoder(
            enc_b, enc_proj, enc_mask, trg_b, trg_mask, h0,
            wa_dec, v_att, wx, wh, bias)
        ctx.set_output("Hidden", LoDArray.from_batch(h_seq, trg_mask, trg_l))
        return

    def step(h_prev, inp):
        x_t, m_t = inp  # [B, E], [B]
        ctxv = _attention(h_prev, enc_b, enc_proj, enc_mask, wa_dec, v_att)
        xin = jnp.concatenate([x_t, ctxv], axis=-1)  # [B, E+C]
        xp = jnp.dot(xin, wx, preferred_element_type=jnp.float32).astype(x_t.dtype)
        if bias is not None:
            xp = xp + bias
        h = gru_cell(xp, h_prev, wh, jax.nn.sigmoid, jnp.tanh)
        m = m_t[:, None].astype(h.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    _, h_seq = jax.lax.scan(step, h0, (trg_b, trg_mask))
    ctx.set_output("Hidden", LoDArray.from_batch(h_seq, trg_mask, trg_l))


@register_op("attention_gru_beam_search")
def attention_gru_beam_search_kernel(ctx):
    """Jitted beam-search generation (reference:

    RecurrentGradientMachine::beamSearch :309 + hl_top_k.cu top-k expand,
    Fluid beam_search_op.cc). Static [B, K] beam state, `max_len` scan
    steps, backtrack scan at the end.

    Inputs: EncState (LoDArray), H0, attention+GRU params as in
    attention_gru_decoder, Embedding [V, E] target table, WOut [H, V],
    BOut [V]. Attrs: beam_size, max_len, bos_id, eos_id.
    Outputs: Ids [B, K, T] int32, Scores [B, K] (total log-prob, best
    first), Lengths [B, K] int32 (tokens before/including EOS).
    """
    enc_l: LoDArray = ctx.input("EncState")
    h0 = ctx.input("H0")  # [B, H]
    wa_enc, wa_dec, v_att = ctx.input("WaEnc"), ctx.input("WaDec"), ctx.input("Va")
    wx, wh = ctx.input("Wx"), ctx.input("Wh")
    bias = ctx.input("Bias") if ctx.has_input("Bias") else None
    emb = ctx.input("Embedding")  # [V, E]
    w_out, b_out = ctx.input("WOut"), ctx.input("BOut")

    K = ctx.attr("beam_size", 4)
    T = ctx.attr("max_len", 32)
    bos = ctx.attr("bos_id", 0)
    eos = ctx.attr("eos_id", 1)
    src_len = ctx.attr("src_max_len") or enc_l.capacity
    norm_by_len = ctx.attr("length_normalize", False)

    enc_b, enc_mask = enc_l.to_batch(max_len=src_len, time_major=False)
    dt = enc_b.dtype  # uniform dtype under amp (see attention_gru_decoder)
    wa_enc, wa_dec, v_att = (w.astype(dt) for w in (wa_enc, wa_dec, v_att))
    wx, wh = wx.astype(dt), wh.astype(dt)
    bias = None if bias is None else bias.astype(dt)
    emb, w_out, b_out = emb.astype(dt), w_out.astype(dt), b_out.astype(dt)
    h0 = h0.astype(dt)
    enc_proj = jnp.dot(
        enc_b, wa_enc, preferred_element_type=jnp.float32
    ).astype(enc_b.dtype)
    B = enc_b.shape[0]
    V = emb.shape[0]

    h_beams = jnp.broadcast_to(h0[:, None], (B, K, h0.shape[-1]))
    tokens = jnp.full((B, K), bos, jnp.int32)
    scores = beam_common.init_scores(B, K, enc_b.dtype)
    finished = jnp.zeros((B, K), bool)

    def step(carry, _):
        h, tok, sc, fin = carry
        x = emb[tok]  # [B, K, E]
        ctxv = _attention(h, enc_b, enc_proj, enc_mask, wa_dec, v_att)
        xin = jnp.concatenate([x, ctxv], axis=-1)
        xp = jnp.dot(xin, wx, preferred_element_type=jnp.float32).astype(x.dtype)
        if bias is not None:
            xp = xp + bias
        h_new = gru_cell(xp, h, wh, jax.nn.sigmoid, jnp.tanh)
        h_new = jnp.where(fin[..., None], h, h_new)
        logits = jnp.dot(
            h_new, w_out, preferred_element_type=jnp.float32
        ).astype(h.dtype) + b_out  # [B, K, V]
        logp = jax.nn.log_softmax(logits, axis=-1)
        logp = beam_common.freeze_finished(logp, fin, eos)
        top_sc, parent, new_tok = beam_common.expand_prune(sc, logp, K)
        h_sel = jnp.take_along_axis(h_new, parent[..., None], axis=1)
        fin_sel = jnp.take_along_axis(fin, parent, axis=1)
        new_fin = fin_sel | (new_tok == eos)
        return (h_sel, new_tok, top_sc, new_fin), (parent, new_tok)

    (_, _, final_scores, _), (parents, toks) = jax.lax.scan(
        step, (h_beams, tokens, scores, finished), None, length=T
    )
    ids = beam_common.backtrack(parents, toks, B, K)
    ids, out_scores, lengths = beam_common.finalize(
        ids, final_scores, eos, T, norm_by_len
    )

    ctx.set_output("Ids", ids)
    ctx.set_output("Scores", out_scores)
    if ctx.has_output("Lengths"):
        ctx.set_output("Lengths", lengths)
