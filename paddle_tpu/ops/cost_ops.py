"""Cost-layer op kernels completing the reference's cost family.

Reference: paddle/gserver/layers/CostLayer.cpp registers ~12 cost layers
(multi_class_cross_entropy :~60, multi_class_cross_entropy_with_selfnorm
:105, soft_binary_class_cross_entropy :149, square_error :176, smooth_l1
:199, rank_cost (RankingCost) :~250, lambda_cost :347, multi_binary_label_
cross_entropy :524, huber_regression :600, huber_classification :663,
sum_cost :746), plus NCELayer.cpp and HierarchicalSigmoidLayer.cpp for the
sampled / tree-factorized softmax alternatives. Fluid analogues:
operators/{sigmoid_cross_entropy_with_logits_op,smooth_l1_loss_op,
rank_loss_op,margin_rank_loss_op,huber_loss_op}.cc.

cross_entropy / softmax_with_cross_entropy / square_error / huber_loss live
in nn_ops.py; this module adds the rest. Gradients via jax.grad.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDArray
from ..core.registry import register_op


def _data(x):
    return x.data if isinstance(x, LoDArray) else x


@register_op("sigmoid_cross_entropy_with_logits")
def sigmoid_ce_logits_kernel(ctx):
    x = _data(ctx.input("X"))
    label = _data(ctx.input("Label")).astype(x.dtype)
    # numerically-stable BCE-with-logits: softplus(x) - label*x
    ctx.set_output("Out", jax.nn.softplus(x) - label * x)


@register_op("binary_cross_entropy")
def binary_ce_kernel(ctx):
    """Probability-space BCE — covers soft_binary_class_cross_entropy and
    (with multi-hot labels) multi_binary_label_cross_entropy."""
    p = jnp.clip(_data(ctx.input("X")), 1e-7, 1.0 - 1e-7)
    label = _data(ctx.input("Label")).astype(p.dtype)
    out = -(label * jnp.log(p) + (1.0 - label) * jnp.log1p(-p))
    ctx.set_output("Out", out)


@register_op("cross_entropy_with_selfnorm")
def ce_selfnorm_kernel(ctx):
    """CE on unnormalized softmax plus alpha * log(Z)^2 self-norm penalty
    (CostLayer.cpp:105)."""
    x = _data(ctx.input("X"))  # probabilities-ish (unnormalized ok)
    label = _data(ctx.input("Label")).reshape(-1).astype(jnp.int32)
    alpha = ctx.attr("softmax_selfnorm_alpha", 0.1)
    z = jnp.sum(x, axis=-1)
    p = jnp.take_along_axis(x, label[:, None], axis=-1)[:, 0] / z
    out = -jnp.log(jnp.maximum(p, 1e-20)) + alpha * jnp.square(jnp.log(z))
    ctx.set_output("Out", out[:, None])


@register_op("smooth_l1")
def smooth_l1_kernel(ctx):
    """SmoothL1CostLayer / smooth_l1_loss_op: 0.5 d^2 (|d|<sigma) else
    |d| - 0.5, with inside/outside weights (Fluid) optional."""
    x = _data(ctx.input("X"))
    y = _data(ctx.input("Y"))
    sigma = ctx.attr("sigma", 1.0)
    d = x - y
    if ctx.has_input("InsideWeight"):
        d = d * _data(ctx.input("InsideWeight"))
    a = jnp.abs(d)
    s2 = sigma * sigma
    loss = jnp.where(a < 1.0 / s2, 0.5 * d * d * s2, a - 0.5 / s2)
    if ctx.has_input("OutsideWeight"):
        loss = loss * _data(ctx.input("OutsideWeight"))
    ctx.set_output("Out", jnp.sum(loss, axis=-1, keepdims=True))


@register_op("rank_cost")
def rank_cost_kernel(ctx):
    """RankingCost: pairwise logistic loss on score difference.
    C = (1-label)*o - log(sigmoid(-o)) form; label in {0, 0.5, 1}."""
    left = _data(ctx.input("Left")).reshape(-1)
    right = _data(ctx.input("Right")).reshape(-1)
    label = _data(ctx.input("Label")).reshape(-1).astype(left.dtype)
    o = left - right
    out = jax.nn.softplus(o) - label * o
    ctx.set_output("Out", out[:, None])


@register_op("margin_rank_loss")
def margin_rank_loss_kernel(ctx):
    """margin_rank_loss_op: max(0, -label*(x1-x2) + margin)."""
    x1 = _data(ctx.input("X1")).reshape(-1)
    x2 = _data(ctx.input("X2")).reshape(-1)
    label = _data(ctx.input("Label")).reshape(-1).astype(x1.dtype)
    margin = ctx.attr("margin", 0.0)
    ctx.set_output("Out", jnp.maximum(0.0, -label * (x1 - x2) + margin)[:, None])


@register_op("huber_classification")
def huber_classification_kernel(ctx):
    """HuberTwoClassification (CostLayer.cpp:663): labels {0,1}→y∈{-1,1};
    loss 0 if y*x>1, (1-y*x)^2 if -1<=y*x<=1, -4*y*x otherwise."""
    x = _data(ctx.input("X")).reshape(-1)
    label = _data(ctx.input("Label")).reshape(-1).astype(x.dtype)
    y = 2.0 * label - 1.0
    a = y * x
    out = jnp.where(a < -1.0, -4.0 * a, jnp.where(a < 1.0, jnp.square(1.0 - a), 0.0))
    ctx.set_output("Out", out[:, None])


@register_op("sum_cost")
def sum_cost_kernel(ctx):
    ctx.set_output("Out", jnp.sum(_data(ctx.input("X"))))


@register_op("lambda_cost")
def lambda_cost_kernel(ctx):
    """LambdaCost (CostLayer.cpp:347): listwise LambdaRank cost. The
    reference walks each ragged list; TPU-statically we take the padded
    list-wise form: Score/Label [L, S], Mask [L, S] (1=real). Forward cost
    is the negative truncated NDCG per list (as in the reference, which
    reports -NDCG as the cost and uses lambda gradients; here jax.grad of
    a smooth surrogate is used instead: we emit -NDCG computed with
    softmax-weighted soft ranks so it is differentiable)."""
    score = _data(ctx.input("Score"))
    label = _data(ctx.input("Label")).astype(score.dtype)
    mask = _data(ctx.input("Mask")) if ctx.has_input("Mask") else jnp.ones_like(score)
    ndcg_num = ctx.attr("NDCG_num", 5)
    # soft rank r_i = 1 + sum_j sigmoid(s_j - s_i) over real entries
    diff = (score[:, None, :] - score[:, :, None]) * 10.0
    soft_gt = jax.nn.sigmoid(diff) * mask[:, None, :]
    soft_rank = 1.0 + jnp.sum(soft_gt, axis=-1) - jax.nn.sigmoid(jnp.zeros(()))
    gain = (jnp.exp2(label) - 1.0) * mask
    disc = 1.0 / jnp.log2(1.0 + soft_rank)
    trunc = jax.nn.sigmoid((ndcg_num - soft_rank + 0.5) * 10.0)
    dcg = jnp.sum(gain * disc * trunc, axis=-1)
    # ideal DCG from hard-sorted gains (padded entries have gain 0)
    sorted_gain = jnp.sort(gain, axis=-1)[:, ::-1]
    pos = jnp.arange(score.shape[1], dtype=score.dtype)
    ideal_disc = jnp.where(pos < ndcg_num, 1.0 / jnp.log2(2.0 + pos), 0.0)
    idcg = jnp.sum(sorted_gain * ideal_disc[None, :], axis=-1)
    ndcg = dcg / jnp.maximum(idcg, 1e-12)
    ctx.set_output("Out", -ndcg[:, None])


# ----------------------------------------------------------- sampled/tree ---
@register_op("nce")
def nce_kernel(ctx):
    """NCELayer.cpp / operators/nce_op.cc: noise-contrastive estimation with
    uniform noise. Per row: BCE-with-logits on the true class (target 1) and
    num_neg sampled classes (target 0), logits shifted by log(k*q)."""
    x = _data(ctx.input("Input"))  # [N, D]
    w = _data(ctx.input("Weight"))  # [C, D]
    label = _data(ctx.input("Label")).reshape(-1).astype(jnp.int32)
    num_neg = ctx.attr("num_neg_samples", 10)
    num_classes = w.shape[0]
    n = x.shape[0]
    neg = jax.random.randint(ctx.rng(), (n, num_neg), 0, num_classes)
    log_kq = jnp.log(jnp.asarray(num_neg / num_classes, x.dtype))

    def logit(ids):  # ids [N, K] → [N, K]
        wk = w[ids]  # [N, K, D]
        s = jnp.einsum("nd,nkd->nk", x, wk)
        if ctx.has_input("Bias"):
            s = s + _data(ctx.input("Bias")).reshape(-1)[ids]
        return s - log_kq

    s_pos = logit(label[:, None])  # [N, 1]
    s_neg = logit(neg)  # [N, num_neg]
    loss = jax.nn.softplus(-s_pos)[:, 0] + jnp.sum(jax.nn.softplus(s_neg), axis=-1)
    ctx.set_output("Cost", loss[:, None])


@functools.lru_cache(maxsize=None)
def _hsigmoid_tables(num_classes: int):
    """Per-class path tables for a complete binary tree in heap layout:
    leaf code = class + num_classes; ancestors = code >> t. Matches the
    reference CodeTable/SimpleCode scheme (paddle/math/MathUtils +
    HierarchicalSigmoidLayer.cpp)."""
    max_depth = int(np.floor(np.log2(2 * num_classes - 1)))
    nodes = np.zeros((num_classes, max_depth), np.int32)
    bits = np.zeros((num_classes, max_depth), np.float32)
    valid = np.zeros((num_classes, max_depth), np.float32)
    for c in range(num_classes):
        code = c + num_classes
        depth = code.bit_length() - 1
        for j in range(depth):
            nodes[c, j] = (code >> (depth - j)) - 1  # internal node param row
            bits[c, j] = (code >> (depth - 1 - j)) & 1
            valid[c, j] = 1.0
    return nodes, bits, valid


@register_op("hsigmoid")
def hsigmoid_kernel(ctx):
    """HierarchicalSigmoidLayer.cpp: binary-tree factorized softmax;
    num_classes-1 internal nodes each with a weight row; loss is the sum of
    BCE-with-logits along the root→leaf path."""
    x = _data(ctx.input("X"))  # [N, D]
    w = _data(ctx.input("W"))  # [C-1, D]
    label = _data(ctx.input("Label")).reshape(-1).astype(jnp.int32)
    num_classes = ctx.attr("num_classes")
    nodes_t, bits_t, valid_t = _hsigmoid_tables(num_classes)
    nodes = jnp.asarray(nodes_t)[label]  # [N, depth]
    bits = jnp.asarray(bits_t)[label]
    valid = jnp.asarray(valid_t)[label]
    wn = w[nodes]  # [N, depth, D]
    s = jnp.einsum("nd,njd->nj", x, wn)
    if ctx.has_input("Bias"):
        s = s + _data(ctx.input("Bias")).reshape(-1)[nodes]
    loss = (jax.nn.softplus(s) - bits * s) * valid
    ctx.set_output("Cost", jnp.sum(loss, axis=-1, keepdims=True))
