"""while_loop / cond op kernels + compare ops.

Reference: paddle/operators/while_op.cc (Executor re-runs the sub-block
while the cond var holds), conditional_block_op.cc, and the compare ops
(less_than/greater_than/equal — operators/compare_op.cc). Sub-blocks are
traced into jax.lax.while_loop / jax.lax.cond — compiled control flow
with no host round-trip per iteration.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


@register_op("while_loop")
def while_loop_kernel(ctx):
    """NOTE on training: jax.lax.while_loop is forward-only — reverse-mode

    differentiation through a While raises. This matches TPU reality
    (unbounded loops can't be rematerialized); for trainable recurrences
    use recurrent_group (bounded lax.scan), the same way the reference's
    trainable dynamic RNNs layer on top of while_op via the RNN memory
    machinery rather than raw while backward."""
    from .recurrent_ops import _group_rng

    carried0 = ctx.inputs("Carried")
    carried_names = list(ctx.attr("carried"))
    update_names = list(ctx.attr("updates"))
    block = ctx.executor.program.blocks[ctx.attr("sub_block")]
    outer_env = dict(ctx.env)
    base_key = _group_rng(ctx, outer_env)
    cond_name = ctx.op.inputs["Cond"][0]
    cond_pos = carried_names.index(cond_name)

    def cond_fun(carry):
        it, vals = carry
        return jnp.reshape(vals[cond_pos], ()).astype(bool)

    def body_fun(carry):
        it, vals = carry
        env = dict(outer_env)
        # fresh randomness per iteration (dropout etc.)
        env["@RNG@"] = jax.random.fold_in(base_key, it)
        env["@RNG_COUNTER@"] = 0
        for name, v in zip(carried_names, vals):
            env[name] = v
        ctx.executor.run_ops(block.ops, env, dict(env), block)
        return it + 1, tuple(env[u] for u in update_names)

    # entry condition False -> zero iterations, finals = entry values
    _, final = jax.lax.while_loop(
        cond_fun, body_fun, (jnp.asarray(0, jnp.int32), tuple(carried0))
    )
    for i, v in enumerate(final):
        ctx.set_output("Out", v, i)


@register_op("cond")
def cond_kernel(ctx):
    from .recurrent_ops import _group_rng

    pred = jnp.reshape(ctx.input("Pred"), ()).astype(bool)
    outer_env = dict(ctx.env)
    base_key = _group_rng(ctx, outer_env)
    prog = ctx.executor.program

    def branch(block_idx, out_names):
        block = prog.blocks[block_idx]

        def run(_):
            env = dict(outer_env)
            env["@RNG@"] = base_key
            env["@RNG_COUNTER@"] = 0
            ctx.executor.run_ops(block.ops, env, dict(env), block)
            return tuple(env[n] for n in out_names)

        return run

    outs = jax.lax.cond(
        pred,
        branch(ctx.attr("true_block"), list(ctx.attr("true_outs"))),
        branch(ctx.attr("false_block"), list(ctx.attr("false_outs"))),
        operand=None,
    )
    for i, v in enumerate(outs):
        ctx.set_output("Out", v, i)


# ------------------------------------------------------------- compares ---
def _data(x):
    return x.data if isinstance(x, LoDArray) else x


def _like(x, data):
    return x.with_data(data) if isinstance(x, LoDArray) else data


def _compare(name, fn):
    @register_op(name)
    def kernel(ctx):  # noqa: F811 — one kernel per registered name
        x_in = ctx.input("X")
        x, y = _data(x_in), _data(ctx.input("Y"))
        ctx.set_output("Out", _like(x_in, fn(x, y)))

    return kernel


_compare("less_than", lambda x, y: x < y)
_compare("less_equal", lambda x, y: x <= y)
_compare("greater_than", lambda x, y: x > y)
_compare("greater_equal", lambda x, y: x >= y)
_compare("equal", lambda x, y: x == y)
_compare("not_equal", lambda x, y: x != y)


@register_op("logical_and")
def logical_and_kernel(ctx):
    x_in = ctx.input("X")
    ctx.set_output(
        "Out",
        _like(x_in, jnp.logical_and(_data(x_in), _data(ctx.input("Y")))),
    )


@register_op("logical_not")
def logical_not_kernel(ctx):
    x_in = ctx.input("X")
    ctx.set_output("Out", _like(x_in, jnp.logical_not(_data(x_in))))
