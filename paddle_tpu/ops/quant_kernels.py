"""Quantized-matmul op kernels: int8×int8→int32 tiled GEMM + dequant.

The low-precision serving fast path (ROADMAP item 2): serving is
bandwidth-bound well below the MXU ceiling, so the win is BYTES — int8
weights stream at 1 B/elem (vs 2 bf16 / 4 f32) and the activation side
quantizes on the fly against a CALIBRATED per-tensor scale, so the MXU
sees an int8×int8 contraction accumulating in int32 with the dequantize
epilogue (`acc * (sx * sw[col])`) fused into the same kernel.

Two lowerings, one legality model:

- `_quant_matmul_pallas`: the TPU Pallas kernel — (block_m, block_n)
  output tiles over a full-K panel, int8 io tiles, int32 accumulator,
  per-column f32 scale epilogue. Tile legality (int8's (32, 128)
  minimum tile, divide-the-array, VMEM working set) lives in
  tune/space.py `quant_matmul_*` — shared with the autotuner, so tuned
  int8 is just another autotuner column next to tuned bf16;
- `_quant_matmul_ref`: the jnp reference (CPU/correctness) — an exact
  int32 contraction via dot_general, bit-identical math to the tile
  kernel since integer adds are associative (no float reorder hazard).

The dispatch consults tune/overrides.lookup exactly like the other
fused kernels (one consult point, provenance counted), and is a HOT
PATH under the zero-cost lint (tests/test_quant.py): no per-call scale
recomputation, no host syncs — scales arrive as traced arrays/attrs
computed once at convert time (quant/convert.py).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.registry import register_op

INT8_MAX = 127.0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


# ------------------------------------------------------------ lowerings --
def _quant_matmul_ref(xq, wq):
    """Reference int8×int8→int32 contraction (exact; any backend)."""
    return jax.lax.dot_general(
        xq, wq, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


def _qmm_kernel(x_ref, w_ref, out_ref):
    out_ref[:, :] = jax.lax.dot_general(
        x_ref[:, :], w_ref[:, :], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32)


@functools.partial(jax.jit, static_argnums=(2, 3))
def _quant_matmul_pallas(xq, wq, block_m: int, block_n: int):
    """Tiled int8 GEMM: grid over (M/block_m, N/block_n) output tiles,
    each tile contracting a full-K int8 panel into an int32 block."""
    from jax.experimental import pallas as pl

    M, K = xq.shape
    _, N = wq.shape
    grid = (M // block_m, N // block_n)
    return pl.pallas_call(
        _qmm_kernel,
        out_shape=jax.ShapeDtypeStruct((M, N), jnp.int32),
        grid=grid,
        in_specs=[
            pl.BlockSpec((block_m, K), lambda i, j: (i, 0)),
            pl.BlockSpec((K, block_n), lambda i, j: (0, j)),
        ],
        out_specs=pl.BlockSpec((block_m, block_n),
                               lambda i, j: (i, j)),
        interpret=_interpret(),
    )(xq, wq)


def quant_matmul(xq, wq):
    """int8 [M, K] × int8 [K, N] → int32 [M, N], tuned-tile dispatch.

    One overrides.lookup consult per TRACE (the jit cache makes it
    per-shape, not per-call); an illegal/absent config falls back to
    the analytic default, and a shape outside the family's eligibility
    entirely falls back to the reference contraction (XLA handles it)."""
    from ..tune import overrides, space

    M, K = xq.shape
    _, N = wq.shape
    params = {"M": int(M), "K": int(K), "N": int(N)}
    ov = overrides.lookup("quant_matmul", params, "int8")
    cfg = ov.config if ov is not None else None
    if cfg is None:
        cfg = space.quant_matmul_default(
            dict(params, dtype="int8"))
    if cfg is None:
        return _quant_matmul_ref(xq, wq)
    return _quant_matmul_pallas(xq, wq, int(cfg["block_m"]),
                                int(cfg["block_n"]))


# ---------------------------------------------------------------- ops ----
def _dequant_epilogue(acc, x_scale, w_scale, out_dtype):
    """acc int32 [M, N] → float [M, N]: one fused scale per column."""
    return (acc.astype(jnp.float32)
            * (x_scale * w_scale)[None, :]).astype(out_dtype)


def _quantize_act(x, x_scale):
    """Activation fake-int8: round/clip against the CALIBRATED scale
    (an attr baked at convert time — never recomputed per call)."""
    xf = x.astype(jnp.float32)
    return jnp.clip(jnp.round(xf / x_scale), -INT8_MAX,
                    INT8_MAX).astype(jnp.int8)


@register_op("quantized_mul")
def quantized_mul_kernel(ctx):
    """The int8 rewrite of `mul` (quant/convert.py): X stays a float
    activation and quantizes on the fly against the calibration-time
    `x_scale` attr; Y is the int8 weight payload; Scale is the
    per-output-channel f32 weight scale var. Emits the compute dtype
    (bf16 under amp, f32 otherwise) so downstream unquantized ops see
    exactly what the fp program would hand them.

    HOT PATH (zero-cost lint): every scale here is a traced array or a
    python float attr — no absmax recomputation, no numpy, no .item().
    """
    from .. import amp

    x = ctx.input("X")
    wq = ctx.input("Y")
    w_scale = ctx.input("Scale")
    x_scale = ctx.attr("x_scale", 1.0)
    xd = ctx.attr("x_num_col_dims", 1)
    xs = x.shape
    x2 = x.reshape((int(np.prod(xs[:xd])), -1)) \
        if x.ndim > 2 or xd != 1 else x
    xq = _quantize_act(x2, x_scale)
    acc = quant_matmul(xq, wq)
    amp_dt = ctx.env.get(amp.AMP_KEY)
    out_dtype = jnp.dtype(amp_dt) if amp_dt is not None else jnp.float32
    out = _dequant_epilogue(acc, jnp.float32(x_scale), w_scale, out_dtype)
    out_shape = tuple(xs[:xd]) + (wq.shape[1],)
    if out.shape != out_shape:
        out = out.reshape(out_shape)
    ctx.set_output("Out", out)


@register_op("quantized_matmul")
def quantized_matmul_kernel(ctx):
    """The int8 rewrite of 2-D `matmul` sites whose Y is a persistable
    weight (transpose handled at convert time by transposing the stored
    int8 payload, so the runtime contraction is always [M,K]x[K,N])."""
    from .. import amp

    x = ctx.input("X")
    wq = ctx.input("Y")
    w_scale = ctx.input("Scale")
    x_scale = ctx.attr("x_scale", 1.0)
    xq = _quantize_act(x, x_scale)
    acc = quant_matmul(xq, wq)
    amp_dt = ctx.env.get(amp.AMP_KEY)
    out_dtype = jnp.dtype(amp_dt) if amp_dt is not None else jnp.float32
    ctx.set_output("Out", _dequant_epilogue(
        acc, jnp.float32(x_scale), w_scale, out_dtype))


# ------------------------------------------------- convert-time helpers --
def quantize_weight(w: np.ndarray):
    """Per-output-channel symmetric int8 quantization of a [K, N]
    weight: returns (int8 payload, f32 per-column scale [N]). Runs ONCE
    at convert time (quant/convert.py) — never on the dispatch path."""
    w = np.asarray(w, np.float32)
    absmax = np.max(np.abs(w), axis=0)
    scale = np.where(absmax > 0, absmax / INT8_MAX, 1.0).astype(np.float32)
    q = np.clip(np.round(w / scale[None, :]), -INT8_MAX,
                INT8_MAX).astype(np.int8)
    return q, scale


def act_scale(absmax: float) -> float:
    """Calibrated activation scale from a recorded absmax range."""
    return float(absmax) / INT8_MAX if absmax > 0 else 1.0
