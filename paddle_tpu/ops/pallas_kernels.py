"""Pallas fused recurrent kernels.

Reference: the hand-written fused CUDA recurrences —
`hl_lstm_parallel_forward` (cuda/include/hl_lstm.h:42, hl_gpu_lstm.cuh) and
the GRU equivalents (hl_gpu_gru.cuh) — which keep the recurrent state in
registers/shared memory and run the whole sequence in one kernel launch.

TPU design: one pallas_call with `grid=(T,)`; the TPU grid runs
sequentially, so the hidden/cell state lives in VMEM scratch across grid
steps while each timestep's pre-projected input block is pipelined in from
HBM automatically by the BlockSpec machinery (double-buffered DMA). The
per-step h @ W_rec hits the MXU; all gate math fuses on the VPU; the only
HBM traffic is the x block in and the h block out — the same
bandwidth-optimality argument as the reference's fused kernels.

Training: `pallas_call` has no automatic VJP, so the fused forward is
wrapped in `jax.custom_vjp` whose backward re-runs the plain `lax.scan`
formulation under `jax.vjp` (rematerialized backward — same FLOPs as a
saved-activation backward plus one forward, no extra HBM residency).

Eligibility (else callers fall back to the scan): sigmoid/tanh gates, no
peepholes, B multiple of 8, H multiple of 128 (f32 tile constraints).
Non-TPU backends run the kernel in interpret mode (tests on CPU exercise
the same code path).
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from . import mesh_dispatch


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def backend_ok(interpret_flag: str) -> bool:
    """Shared dispatch gate for every fused-kernel family (RNN, conv,
    attention): interpret mode exists for tests; production dispatch must
    not send CPU/GPU users through the pure-Python interpreter when the
    XLA formulation is sitting right there. `interpret_flag` names that
    family's test-override flag."""
    from ..flags import FLAGS

    return jax.default_backend() == "tpu" or getattr(FLAGS, interpret_flag)


def _backend_ok() -> bool:
    return backend_ok("fused_rnn_interpret")


# The backward kernel's VMEM working set must fit the 16M scoped budget;
# the model below reproduces every measured compile outcome: LSTM bf16
# H=1280 B=128 → 18.7M predicted vs 18.75M in the observed train-graph
# overflow; GRU f32 H=1280 B=128 → 25.6M vs observed 25.0M overflow;
# LSTM bf16 H=1280 B=256 → 24.2M vs the microbench fused_error row;
# GRU bf16 H=1280 B=128 → 14.7M, compiles and wins 1.88x
# (benchmarks/rnn_kernel_microbench.json). The budget keeps a 1M safety
# margin below the hardware's 16M: LSTM bf16 H=1280 B=64 models at 15.9M
# and was observed BOTH compiling (152k tok/s) and overflowing by 824K
# on different compiles of the same graph — borderline configs flip with
# the compiler's scratch scheduling, so they stay on the scan.
_VMEM_BUDGET = 15 * 1024 * 1024


def _bwd_vmem_bytes(B: int, H: int, G: int, itemsize: int,
                    dw_max_h: int) -> int:
    """G = gates per cell (4 LSTM, 3 GRU); itemsize = io dtype bytes;
    dw_max_h = that cell's fused-dW threshold (the model must track the
    kernel's actual fuse decision)."""
    weight_block = G * H * H * itemsize
    io_blocks = 2 * (G + 3) * B * H * itemsize  # double-buffered streams
    carries = 3 * B * H * itemsize
    dw_acc = 4 * G * H * H if H <= dw_max_h else 0  # f32 accumulator
    return weight_block + io_blocks + carries + dw_acc


def _tuned_fused(kind: str, B: int, H: int, itemsize: int):
    """Tuned/forced fused-vs-scan decision from the override registry
    (None = no entry -> the measured-window analytic default applies).
    The fused-RNN kernels have no free tile parameter — their empirical
    knob is the dispatch itself, so the tuner records {"fused": bool}
    per (B, H, dtype, device)."""
    from ..tune import overrides as tune_overrides
    from ..tune.cache import ITEMSIZE_DTYPE

    ov = tune_overrides.lookup(
        f"fused_{kind}", {"B": B, "H": H},
        ITEMSIZE_DTYPE.get(itemsize, f"itemsize{itemsize}"))
    if ov is None or "fused" not in ov.config:
        return None
    return bool(ov.config["fused"])


def lstm_supported(B: int, H: int, gate_act, cell_act, cand_act, peep,
                   itemsize: int = 2) -> bool:
    # hard legality first (tile alignment, gate forms, VMEM model) —
    # no override can force an illegal config through
    if not (
        peep is None
        and gate_act == "sigmoid"
        and cell_act == "tanh"
        and cand_act == "tanh"
        and B >= 8 and B % 8 == 0
        and H % 128 == 0
        and _bwd_vmem_bytes(B, H, 4, itemsize,
                            _LSTM_FUSED_DW_MAX_H) <= _VMEM_BUDGET
        and _backend_ok()
    ):
        return False
    tuned = _tuned_fused("lstm", B, H, itemsize)
    if tuned is not None:
        return tuned
    # measured window (benchmarks/rnn_kernel_microbench.json, round 3
    # with the outer-einsum dW past H=640): 1.02x at H=512, 1.45x at
    # 768, 1.60x at 1024, 1.13x at 1280 — the reference's largest
    # published config (benchmark/README.md:129-136) now eligible at
    # bf16; H=256 still loses (0.86x, r2 data): the per-step matmul
    # is too small to amortize the kernel's fixed work
    return 384 <= H <= 1280


def gru_supported(B: int, H: int, gate_act, cand_act,
                  itemsize: int = 2) -> bool:
    if not (
        gate_act == "sigmoid"
        and cand_act == "tanh"
        and B >= 8 and B % 8 == 0
        and H % 128 == 0
        and _bwd_vmem_bytes(B, H, 3, itemsize,
                            _GRU_FUSED_DW_MAX_H) <= _VMEM_BUDGET
        and _backend_ok()
    ):
        return False
    tuned = _tuned_fused("gru", B, H, itemsize)
    if tuned is not None:
        return tuned
    # measured window (benchmarks/rnn_kernel_microbench.json, round 3
    # with the hand-written reverse-time backward kernel replacing the
    # scan-replay VJP): 1.18x at H=128, 1.06x at 256, 1.72x at 512
    # (the NMT config), 1.70x at 640, 1.24x at 768, 1.61x at 1024,
    # 1.88x at 1280. H=384 alone dips to 0.86x (3H=1152 tiles badly
    # against the 512-lane MXU pass) and stays on the scan
    return 128 <= H <= 1280 and H != 384


# ------------------------------------------------------------------ LSTM ---
def _lstm_kernel(
    x_ref, m_ref, w_ref, h_seq_ref, c_seq_ref, hT_ref, cT_ref, h_s, c_s
):
    """One timestep per grid step; h/c persist in VMEM scratch. c_seq is

    emitted as a residual for the hand-written backward kernel."""
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = jnp.zeros_like(h_s)
        c_s[:] = jnp.zeros_like(c_s)

    h_prev = h_s[:]
    c_prev = c_s[:].astype(jnp.float32)
    # gate math in f32 on the VPU regardless of io dtype (also works
    # around Mosaic's refusal to broadcast an f32 scalar into a bf16
    # vector inside sigmoid); the MXU matmul accumulates f32 anyway
    gates = x_ref[0].astype(jnp.float32) + jnp.dot(
        h_prev, w_ref[:], preferred_element_type=jnp.float32
    )
    H = h_prev.shape[-1]
    i = jax.nn.sigmoid(gates[:, :H])
    f = jax.nn.sigmoid(gates[:, H : 2 * H])
    g = jnp.tanh(gates[:, 2 * H : 3 * H])
    o = jax.nn.sigmoid(gates[:, 3 * H :])
    c = f * c_prev + i * g
    h = o * jnp.tanh(c)
    m = m_ref[0, 0][:, None]
    h = m * h + (1 - m) * h_prev.astype(jnp.float32)
    c = m * c + (1 - m) * c_prev
    dt = h_s.dtype
    h_s[:] = h.astype(dt)
    c_s[:] = c.astype(dt)
    h_seq_ref[0] = h.astype(dt)
    c_seq_ref[0] = c.astype(dt)

    @pl.when(t == pl.num_programs(0) - 1)
    def _():
        hT_ref[:] = h.astype(dt)
        cT_ref[:] = c.astype(dt)


def _lstm_pallas_raw(x_tbh, mask, w_rec):
    T, B, H4 = x_tbh.shape
    H = H4 // 4
    dt = x_tbh.dtype
    return pl.pallas_call(
        _lstm_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), lambda t: (t, 0, 0)),
            # mask rides as [T, 1, B]: a (1, 1, B) block satisfies the
            # (sublane, lane) tiling rule for any B (dims equal the array's)
            pl.BlockSpec((1, 1, B), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        ],
        scratch_shapes=[
            pltpu.VMEM((B, H), dt),
            pltpu.VMEM((B, H), dt),
        ],
        interpret=_interpret(),
    )(x_tbh, mask.astype(jnp.float32).reshape(T, 1, B), w_rec)


def _lstm_bwd_kernel(
    gates_ref,  # (1, B, 4H) pre-activation gates at t
    cprev_ref,  # (1, B, H) c_{t-1}
    hprev_ref,  # (1, B, H) h_{t-1}
    dh_seq_ref,  # (1, B, H) output cotangent at t
    m_ref,  # (1, 1, B)
    w_ref,  # (H, 4H)
    dhT_ref,  # (B, H) cotangent of final h
    dcT_ref,  # (B, H) cotangent of final c
    dx_ref,  # out (1, B, 4H)
    dw_ref,  # out (H, 4H) — absent when accumulate_dw=False
    dh_s,  # scratch (B, H): dL/dh_t carry
    dc_s,  # scratch (B, H): dL/dc_t carry
    dw_s,  # scratch (H, 4H) f32 accumulator — absent when accumulate_dw=False
    *,
    accumulate_dw: bool = True,
):
    """Reverse-time step: t = T-1-s via the index maps. Gates are

    recomputed OUTSIDE in one batched matmul (h_seq is saved, so gate
    pre-activations have no sequential dependency); only the dh/dc carry
    is sequential here.

    accumulate_dw=False drops the in-VMEM [H, 4H] f32 dW accumulator (16H²
    bytes — past H=640 it evicts everything else); dW is then one batched
    einsum over the emitted dgates OUTSIDE the kernel, which only costs one
    extra HBM read of dx. That lifts the eligibility window to the
    reference's largest published config (H=1280,
    /root/reference/benchmark/README.md:129-136)."""
    s = pl.program_id(0)

    @pl.when(s == 0)
    def _():
        dh_s[:] = dhT_ref[:]
        dc_s[:] = dcT_ref[:]
        if accumulate_dw:
            dw_s[:] = jnp.zeros_like(dw_s)

    # all gate/cotangent math in f32 (see _lstm_kernel's dtype note)
    gates = gates_ref[0].astype(jnp.float32)
    H = dh_s.shape[-1]
    i = jax.nn.sigmoid(gates[:, :H])
    f = jax.nn.sigmoid(gates[:, H : 2 * H])
    g = jnp.tanh(gates[:, 2 * H : 3 * H])
    o = jax.nn.sigmoid(gates[:, 3 * H :])
    c_prev = cprev_ref[0].astype(jnp.float32)
    h_prev = hprev_ref[0]
    m = m_ref[0, 0][:, None]

    c_raw = f * c_prev + i * g
    tc = jnp.tanh(c_raw)

    dh_total = dh_seq_ref[0].astype(jnp.float32) + dh_s[:].astype(jnp.float32)
    dc_total = dc_s[:].astype(jnp.float32)
    dh_raw = m * dh_total
    dc_raw = m * dc_total + dh_raw * o * (1 - tc * tc)
    do_a = dh_raw * tc * o * (1 - o)
    di_a = dc_raw * g * i * (1 - i)
    df_a = dc_raw * c_prev * f * (1 - f)
    dg_a = dc_raw * i * (1 - g * g)
    dgates = jnp.concatenate([di_a, df_a, dg_a, do_a], axis=1)

    dt = dx_ref.dtype
    dx_ref[0] = dgates.astype(dt)
    dh_s[:] = (
        jnp.dot(
            dgates.astype(dt), w_ref[:].T,
            preferred_element_type=jnp.float32,
        )
        + (1 - m) * dh_total
    ).astype(dh_s.dtype)
    dc_s[:] = (dc_raw * f + (1 - m) * dc_total).astype(dc_s.dtype)
    if accumulate_dw:
        dw_s[:] = dw_s[:] + jnp.dot(
            h_prev.T, dgates.astype(dt), preferred_element_type=jnp.float32
        )

        @pl.when(s == pl.num_programs(0) - 1)
        def _():
            dw_ref[:] = dw_s[:].astype(dw_ref.dtype)


def _lstm_bwd_kernel_nodw(
    gates_ref, cprev_ref, hprev_ref, dh_seq_ref, m_ref, w_ref, dhT_ref,
    dcT_ref, dx_ref, dh_s, dc_s,
):
    """Positional-signature adapter: without the dW output/scratch, pallas
    hands the kernel one fewer ref in each group."""
    _lstm_bwd_kernel(
        gates_ref, cprev_ref, hprev_ref, dh_seq_ref, m_ref, w_ref, dhT_ref,
        dcT_ref, dx_ref, None, dh_s, dc_s, None, accumulate_dw=False,
    )


# past this hidden size the [H, 4H] f32 dW accumulator (16H² bytes) no
# longer fits VMEM next to the weight and io blocks; switch to the outer
# batched-einsum dW (see _lstm_bwd_kernel docstring)
_LSTM_FUSED_DW_MAX_H = 640


def _lstm_bwd_pallas(x_tbh, mask, w_rec, h_seq, c_seq, dh_seq, dhT, dcT):
    T, B, H4 = x_tbh.shape
    H = H4 // 4
    dt = x_tbh.dtype
    zeros = jnp.zeros((1, B, H), dt)
    h_prev_seq = jnp.concatenate([zeros, h_seq[:-1]], axis=0)
    c_prev_seq = jnp.concatenate([zeros, c_seq[:-1]], axis=0)
    # all gate pre-activations in ONE batched matmul — no recurrence
    gates_pre = x_tbh + jnp.einsum(
        "tbh,hk->tbk", h_prev_seq, w_rec,
        preferred_element_type=jnp.float32,
    ).astype(dt)
    fuse_dw = H <= _LSTM_FUSED_DW_MAX_H
    rev = lambda t: (T - 1 - t, 0, 0)  # noqa: E731 — reverse-time index map
    out_specs = [pl.BlockSpec((1, B, H4), rev)]
    out_shape = [jax.ShapeDtypeStruct((T, B, H4), dt)]
    scratch = [pltpu.VMEM((B, H), dt), pltpu.VMEM((B, H), dt)]
    if fuse_dw:
        out_specs.append(pl.BlockSpec((H, H4), lambda t: (0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((H, H4), dt))
        scratch.append(pltpu.VMEM((H, H4), jnp.float32))
    outs = pl.pallas_call(
        _lstm_bwd_kernel if fuse_dw else _lstm_bwd_kernel_nodw,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H4), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, 1, B), rev),
            pl.BlockSpec((H, H4), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(
        gates_pre,
        c_prev_seq,
        h_prev_seq,
        dh_seq,
        mask.astype(jnp.float32).reshape(T, 1, B),
        w_rec,
        dhT,
        dcT,
    )
    if fuse_dw:
        dx, dw = outs
    else:
        (dx,) = outs if isinstance(outs, (list, tuple)) else (outs,)
        dw = jnp.einsum(
            "tbh,tbk->hk", h_prev_seq, dx,
            preferred_element_type=jnp.float32,
        ).astype(dt)
    return dx, dw


def lstm_fused(x_tbh, mask, w_rec, bias=None, reverse=False):
    """Fused LSTM over the whole sequence (zero-boot, sigmoid/tanh).

    Mirrors lstm_scan's signature subset: optional pre-gate bias and
    time reversal (flip in, flip the emitted sequence back). Under an
    active mesh the call is shard_map'd over the dp axis (mesh_dispatch
    policy): batch-sharded x/mask, replicated weight, per-shard kernel
    at the local batch, dW psum'd in the backward."""
    if bias is not None:
        # master-weight bias casts DOWN to the activation dtype (amp):
        # promoting x to f32 here would double the whole sequence's HBM
        # traffic through the kernel
        x_tbh = x_tbh + bias.astype(x_tbh.dtype)
    # f32 master weight likewise meets the activation dtype at the kernel
    # boundary; the cast's transpose restores an f32 dW for the optimizer
    w_rec = w_rec.astype(x_tbh.dtype)
    am = mesh_dispatch.current()
    # axis only when shard_batch will actually wrap (dp > 1): a dp=1
    # mesh runs unwrapped, where a psum over the axis name is unbound
    core = _lstm_core(am.batch_axis if am and am.dp > 1 else None)
    # outputs (h_seq [T,B,H], (h_T [B,H], c_T [B,H]))
    call = mesh_dispatch.shard_batch(
        core, (1, 1, None), ((1, 3), (0, 2), (0, 2)),
        out_tree=_RNN_LSTM_OUT_TREE)
    if reverse:
        h_seq, last = call(x_tbh[::-1], mask[::-1], w_rec)
        return h_seq[::-1], last
    return call(x_tbh, mask, w_rec)


_RNN_LSTM_OUT_TREE = jax.tree.structure((0, (0, 0)))
_RNN_GRU_OUT_TREE = jax.tree.structure((0, 0))


@functools.lru_cache(maxsize=None)
def _lstm_core(axis):
    """custom-VJP fused LSTM; `axis` names the dp shard_map axis (None =
    unsharded). The weight cotangent is a per-shard partial sum, so the
    backward psums it over `axis` — shard_map runs with check_vma off
    (pallas calls carry no replication rule), which disables the
    automatic cotangent psum for replicated inputs."""

    @jax.custom_vjp
    def core(x_tbh, mask, w_rec):
        h_seq, _c_seq, h_T, c_T = _lstm_pallas_raw(x_tbh, mask, w_rec)
        return h_seq, (h_T, c_T)

    def fwd(x_tbh, mask, w_rec):
        h_seq, c_seq, h_T, c_T = _lstm_pallas_raw(x_tbh, mask, w_rec)
        return (h_seq, (h_T, c_T)), (x_tbh, mask, w_rec, h_seq, c_seq)

    def bwd(res, ct):
        x_tbh, mask, w_rec, h_seq, c_seq = res
        dh_seq, (dhT, dcT) = ct
        dx, dw = _lstm_bwd_pallas(
            x_tbh, mask, w_rec, h_seq, c_seq, dh_seq, dhT, dcT
        )
        if axis is not None:
            dw = jax.lax.psum(dw, axis)
        return dx, None, dw

    core.defvjp(fwd, bwd)
    return core


# ------------------------------------------------------------------- GRU ---
def _gru_kernel(x_ref, m_ref, w_ref, h_seq_ref, hT_ref, h_s):
    t = pl.program_id(0)

    @pl.when(t == 0)
    def _():
        h_s[:] = jnp.zeros_like(h_s)

    h_prev = h_s[:]
    H = h_prev.shape[-1]
    xp = x_ref[0].astype(jnp.float32)  # gate math in f32 (see _lstm_kernel)
    w_ur = w_ref[:, : 2 * H]
    w_c = w_ref[:, 2 * H :]
    ur = jax.nn.sigmoid(
        xp[:, : 2 * H]
        + jnp.dot(h_prev, w_ur, preferred_element_type=jnp.float32)
    )
    u, r = ur[:, :H], ur[:, H:]
    c = jnp.tanh(
        xp[:, 2 * H :]
        + jnp.dot(
            (r * h_prev.astype(jnp.float32)).astype(h_prev.dtype), w_c,
            preferred_element_type=jnp.float32,
        )
    )
    h = (1 - u) * h_prev.astype(jnp.float32) + u * c
    m = m_ref[0, 0][:, None]
    h = m * h + (1 - m) * h_prev.astype(jnp.float32)
    dt = h_s.dtype
    h_s[:] = h.astype(dt)
    h_seq_ref[0] = h.astype(dt)

    @pl.when(t == pl.num_programs(0) - 1)
    def _():
        hT_ref[:] = h.astype(dt)


def _gru_pallas_raw(x_tbh, mask, w_rec):
    T, B, H3 = x_tbh.shape
    H = H3 // 3
    dt = x_tbh.dtype
    return pl.pallas_call(
        _gru_kernel,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, H3), lambda t: (t, 0, 0)),
            pl.BlockSpec((1, 1, B), lambda t: (t, 0, 0)),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, B, H), lambda t: (t, 0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), dt),
            jax.ShapeDtypeStruct((B, H), dt),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), dt)],
        interpret=_interpret(),
    )(x_tbh, mask.astype(jnp.float32).reshape(T, 1, B), w_rec)


def _gru_bwd_kernel(
    ur_pre_ref,  # (1, B, 2H) update/reset pre-activations at t
    c_pre_ref,  # (1, B, H) candidate pre-activation at t
    hprev_ref,  # (1, B, H) h_{t-1}
    dh_seq_ref,  # (1, B, H) output cotangent at t
    m_ref,  # (1, 1, B)
    w_ref,  # (H, 3H) = [W_u | W_r | W_c]
    dhT_ref,  # (B, H) cotangent of final h
    dx_ref,  # out (1, B, 3H)
    dw_ref,  # out (H, 3H) — absent when accumulate_dw=False
    dh_s,  # scratch (B, H): dL/dh_t carry
    dw_s,  # scratch (H, 3H) f32 accumulator — absent when accumulate_dw=False
    *,
    accumulate_dw: bool = True,
):
    """Reverse-time GRU step (t = T-1-s via the index maps), replacing the
    round-2 scan-replay VJP. Forward (gru_cell):
        u = σ(xu + h@Wu);  r = σ(xr + h@Wr);  c = tanh(xc + (r·h)@Wc)
        h' = (1-u)·h + u·c, masked h' = m·h' + (1-m)·h
    The pre-activations have no sequential dependency (h_seq is saved) so
    they are recomputed OUTSIDE in batched matmuls; only the dh carry is
    sequential. Reference counterpart: hl_gpu_gru.cuh backward."""
    s = pl.program_id(0)

    @pl.when(s == 0)
    def _():
        dh_s[:] = dhT_ref[:]
        if accumulate_dw:
            dw_s[:] = jnp.zeros_like(dw_s)

    H = dh_s.shape[-1]
    ur = jax.nn.sigmoid(ur_pre_ref[0].astype(jnp.float32))
    u, r = ur[:, :H], ur[:, H:]
    c = jnp.tanh(c_pre_ref[0].astype(jnp.float32))
    h_prev = hprev_ref[0]
    h_prev32 = h_prev.astype(jnp.float32)
    m = m_ref[0, 0][:, None]

    dh_total = dh_seq_ref[0].astype(jnp.float32) + dh_s[:].astype(jnp.float32)
    dh_raw = m * dh_total
    dc_act = dh_raw * u
    du_act = dh_raw * (c - h_prev32)
    dh_prev = (1 - m) * dh_total + dh_raw * (1 - u)

    dc_pre = dc_act * (1 - c * c)
    dt = dx_ref.dtype
    w_c = w_ref[:, 2 * H:]
    drh = jnp.dot(
        dc_pre.astype(dt), w_c.T, preferred_element_type=jnp.float32
    )  # cotangent of (r·h_prev)
    dr_act = drh * h_prev32
    dh_prev = dh_prev + drh * r

    du_pre = du_act * u * (1 - u)
    dr_pre = dr_act * r * (1 - r)
    dur = jnp.concatenate([du_pre, dr_pre], axis=1)
    w_ur = w_ref[:, : 2 * H]
    dh_prev = dh_prev + jnp.dot(
        dur.astype(dt), w_ur.T, preferred_element_type=jnp.float32
    )

    dx_ref[0] = jnp.concatenate([du_pre, dr_pre, dc_pre], axis=1).astype(dt)
    dh_s[:] = dh_prev.astype(dh_s.dtype)
    if accumulate_dw:
        rh = (r * h_prev32).astype(dt)
        dw_s[:, : 2 * H] = dw_s[:, : 2 * H] + jnp.dot(
            h_prev.T, dur.astype(dt), preferred_element_type=jnp.float32
        )
        dw_s[:, 2 * H:] = dw_s[:, 2 * H:] + jnp.dot(
            rh.T, dc_pre.astype(dt), preferred_element_type=jnp.float32
        )

        @pl.when(s == pl.num_programs(0) - 1)
        def _():
            dw_ref[:] = dw_s[:].astype(dw_ref.dtype)


def _gru_bwd_kernel_nodw(
    ur_pre_ref, c_pre_ref, hprev_ref, dh_seq_ref, m_ref, w_ref, dhT_ref,
    dx_ref, dh_s,
):
    _gru_bwd_kernel(
        ur_pre_ref, c_pre_ref, hprev_ref, dh_seq_ref, m_ref, w_ref, dhT_ref,
        dx_ref, None, dh_s, None, accumulate_dw=False,
    )


_GRU_FUSED_DW_MAX_H = 640  # 12H² f32 accumulator bytes vs ~16 MB VMEM


def _gru_bwd_pallas(x_tbh, mask, w_rec, h_seq, dh_seq, dhT):
    T, B, H3 = x_tbh.shape
    H = H3 // 3
    dt = x_tbh.dtype
    zeros = jnp.zeros((1, B, H), dt)
    h_prev_seq = jnp.concatenate([zeros, h_seq[:-1]], axis=0)
    # batched pre-activation recompute (no recurrence): u/r first, then the
    # candidate path through r·h_prev
    ur_pre = x_tbh[:, :, : 2 * H] + jnp.einsum(
        "tbh,hk->tbk", h_prev_seq, w_rec[:, : 2 * H],
        preferred_element_type=jnp.float32,
    ).astype(dt)
    r_seq = jax.nn.sigmoid(ur_pre[:, :, H:].astype(jnp.float32))
    rh_seq = (r_seq * h_prev_seq.astype(jnp.float32)).astype(dt)
    c_pre = x_tbh[:, :, 2 * H:] + jnp.einsum(
        "tbh,hk->tbk", rh_seq, w_rec[:, 2 * H:],
        preferred_element_type=jnp.float32,
    ).astype(dt)
    fuse_dw = H <= _GRU_FUSED_DW_MAX_H
    rev = lambda t: (T - 1 - t, 0, 0)  # noqa: E731 — reverse-time index map
    out_specs = [pl.BlockSpec((1, B, H3), rev)]
    out_shape = [jax.ShapeDtypeStruct((T, B, H3), dt)]
    scratch = [pltpu.VMEM((B, H), dt)]
    if fuse_dw:
        out_specs.append(pl.BlockSpec((H, H3), lambda t: (0, 0)))
        out_shape.append(jax.ShapeDtypeStruct((H, H3), dt))
        scratch.append(pltpu.VMEM((H, H3), jnp.float32))
    outs = pl.pallas_call(
        _gru_bwd_kernel if fuse_dw else _gru_bwd_kernel_nodw,
        grid=(T,),
        in_specs=[
            pl.BlockSpec((1, B, 2 * H), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, B, H), rev),
            pl.BlockSpec((1, 1, B), rev),
            pl.BlockSpec((H, H3), lambda t: (0, 0)),
            pl.BlockSpec((B, H), lambda t: (0, 0)),
        ],
        out_specs=out_specs,
        out_shape=out_shape,
        scratch_shapes=scratch,
        interpret=_interpret(),
    )(
        ur_pre,
        c_pre,
        h_prev_seq,
        dh_seq,
        mask.astype(jnp.float32).reshape(T, 1, B),
        w_rec,
        dhT,
    )
    if fuse_dw:
        dx, dw = outs
    else:
        (dx,) = outs if isinstance(outs, (list, tuple)) else (outs,)
        dw_ur = jnp.einsum(
            "tbh,tbk->hk", h_prev_seq, dx[:, :, : 2 * H],
            preferred_element_type=jnp.float32,
        )
        dw_c = jnp.einsum(
            "tbh,tbk->hk", rh_seq, dx[:, :, 2 * H:],
            preferred_element_type=jnp.float32,
        )
        dw = jnp.concatenate([dw_ur, dw_c], axis=1).astype(dt)
    return dx, dw


def gru_fused(x_tbh, mask, w_rec, bias=None, reverse=False):
    """Fused GRU over the whole sequence (zero-boot, sigmoid/tanh).

    Mesh policy as lstm_fused: shard_map'd over dp when a mesh is
    active, dW psum'd in the backward."""
    if bias is not None:
        x_tbh = x_tbh + bias.astype(x_tbh.dtype)  # see lstm_fused
    w_rec = w_rec.astype(x_tbh.dtype)
    am = mesh_dispatch.current()
    core = _gru_core(am.batch_axis if am and am.dp > 1 else None)  # see lstm_fused
    call = mesh_dispatch.shard_batch(
        core, (1, 1, None), ((1, 3), (0, 2)), out_tree=_RNN_GRU_OUT_TREE)
    if reverse:
        h_seq, h_T = call(x_tbh[::-1], mask[::-1], w_rec)
        return h_seq[::-1], h_T
    return call(x_tbh, mask, w_rec)


@functools.lru_cache(maxsize=None)
def _gru_core(axis):
    """custom-VJP fused GRU; see _lstm_core for the axis/psum contract."""

    @jax.custom_vjp
    def core(x_tbh, mask, w_rec):
        h_seq, h_T = _gru_pallas_raw(x_tbh, mask, w_rec)
        return h_seq, h_T

    def fwd(x_tbh, mask, w_rec):
        h_seq, h_T = _gru_pallas_raw(x_tbh, mask, w_rec)
        return (h_seq, h_T), (x_tbh, mask, w_rec, h_seq)

    def bwd(res, ct):
        x_tbh, mask, w_rec, h_seq = res
        dh_seq, dhT = ct
        dx, dw = _gru_bwd_pallas(x_tbh, mask, w_rec, h_seq, dh_seq, dhT)
        if axis is not None:
            dw = jax.lax.psum(dw, axis)
        return dx, None, dw

    core.defvjp(fwd, bwd)
    return core
