"""NN op kernels: conv, pool, batch_norm, dropout, losses, metrics.

Reference coverage: paddle/operators/{conv_op,pool_op,batch_norm_op,
dropout_op,cross_entropy_op,softmax_with_cross_entropy_op,accuracy_op,
lrn_op}.cc plus the Gen-1 kernels they generalize (paddle/function/GemmConvOp,
gserver/layers/CudnnConvBaseLayer, CostLayer.cpp). Convs map to
lax.conv_general_dilated (MXU path — XLA lowers conv to systolic-array
matmuls internally); data layout is NCHW to match the reference API, XLA
re-layouts for TPU automatically.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDArray
from .. import amp
from ..core.registry import register_op


def _pair(v):
    if isinstance(v, (list, tuple)):
        return tuple(v)
    return (v, v)


# ------------------------------------------------------------------ conv ---
@register_op("conv2d")
def conv2d_kernel(ctx):
    """Reference: paddle/operators/conv_op.cc (REGISTER_OP conv2d);

    groups/dilation semantics per ConvOp::InferShape."""
    x = ctx.input("Input")  # [N, C, H, W] (or NHWC per data_format)
    w = ctx.input("Filter")  # [out_c, in_c/groups, kh, kw] always OIHW
    stride = _pair(ctx.attr("strides", (1, 1)))
    pad = _pair(ctx.attr("paddings", (0, 0)))
    dil = _pair(ctx.attr("dilations", (1, 1)))
    groups = ctx.attr("groups", 1)
    # NHWC: channels-minor is the TPU-preferred layout (channel dim maps
    # to the 128-wide lane dimension without a relayout); the parameter
    # keeps the reference's OIHW shape for checkpoint compatibility and is
    # transposed at trace time (weights are small; XLA folds this)
    fmt = ctx.attr("data_format", "NCHW")
    if fmt == "NHWC":
        w = jnp.transpose(w, (2, 3, 1, 0))  # OIHW -> HWIO
    xc, wc = amp.cast_inputs(ctx, x, w)
    # under amp the conv runs bf16→bf16 and the OUTPUT stays bf16 (the MXU
    # accumulates f32 internally; keeping the activation at 2 B/elem is the
    # HBM-traffic win — see amp.py). A mixed preferred_element_type would
    # break conv's VJP transpose rule, so f32 accumulation is only
    # requested on the pure-f32 path.
    acc = jnp.float32 if xc.dtype == jnp.float32 else None
    out = jax.lax.conv_general_dilated(
        xc,
        wc,
        window_strides=stride,
        padding=[(pad[0], pad[0]), (pad[1], pad[1])],
        rhs_dilation=dil,
        feature_group_count=groups,
        dimension_numbers=(
            (fmt, "OIHW" if fmt == "NCHW" else "HWIO", fmt)
        ),
        preferred_element_type=acc,
    )
    if ctx.has_input("Bias"):
        bshape = (1, -1, 1, 1) if fmt == "NCHW" else (1, 1, 1, -1)
        bias = ctx.input("Bias").reshape(bshape)
        out = out + bias.astype(out.dtype)
    ctx.set_output("Output", out)


@register_op("conv2d_transpose")
def conv2d_transpose_kernel(ctx):
    """Reference: paddle/operators/conv_transpose_op.cc — Filter layout

    [in_c, out_c, kh, kw]. Expressed as the fractionally-strided conv:
    lhs dilated by the stride, spatially-flipped kernel in OIHW, padding
    k-1-p (verified element-wise against torch's conv_transpose2d)."""
    x = ctx.input("Input")
    w = ctx.input("Filter")  # [in_c, out_c, kh, kw]
    stride = _pair(ctx.attr("strides", (1, 1)))
    pad = _pair(ctx.attr("paddings", (0, 0)))
    kh, kw = w.shape[2], w.shape[3]
    wk = jnp.transpose(w, (1, 0, 2, 3))[:, :, ::-1, ::-1]  # OIHW, flipped
    xc, wc = amp.cast_inputs(ctx, x, wk)
    acc = jnp.float32 if xc.dtype == jnp.float32 else None
    out = jax.lax.conv_general_dilated(
        xc,
        wc,
        window_strides=(1, 1),
        padding=[(kh - 1 - pad[0], kh - 1 - pad[0]),
                 (kw - 1 - pad[1], kw - 1 - pad[1])],
        lhs_dilation=stride,
        dimension_numbers=("NCHW", "OIHW", "NCHW"),
        preferred_element_type=acc,
    )
    if ctx.has_input("Bias"):
        bias = ctx.input("Bias").reshape((1, -1, 1, 1))
        out = out + bias.astype(out.dtype)
    ctx.set_output("Output", out)


# ------------------------------------------------------------------ pool ---
@register_op("pool2d")
def pool2d_kernel(ctx):
    """Reference: paddle/operators/pool_op.cc — max/avg, ksize/strides/

    paddings, global_pooling."""
    x = ctx.input("X")  # [N, C, H, W] (or NHWC per data_format)
    ptype = ctx.attr("pooling_type", "max")
    ksize = _pair(ctx.attr("ksize", (2, 2)))
    stride = _pair(ctx.attr("strides", (2, 2)))
    pad = _pair(ctx.attr("paddings", (0, 0)))
    fmt = ctx.attr("data_format", "NCHW")
    hw = slice(2, 4) if fmt == "NCHW" else slice(1, 3)
    if ctx.attr("global_pooling", False):
        ksize = x.shape[hw]
        stride = ksize
        pad = (0, 0)
    sp_pad = ((pad[0], pad[0]), (pad[1], pad[1]))
    if fmt == "NCHW":
        window = (1, 1) + tuple(ksize)
        strides = (1, 1) + tuple(stride)
        pads = ((0, 0), (0, 0)) + sp_pad
    else:
        window = (1,) + tuple(ksize) + (1,)
        strides = (1,) + tuple(stride) + (1,)
        pads = ((0, 0),) + sp_pad + ((0, 0),)
    if ptype == "max":
        init = -jnp.inf
        out = jax.lax.reduce_window(x, init, jax.lax.max, window, strides, pads)
    else:
        summed = jax.lax.reduce_window(x, 0.0, jax.lax.add, window, strides, pads)
        if ctx.attr("exclusive", True) and pad != (0, 0):
            ones = jnp.ones_like(x)
            counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, pads)
            out = summed / counts
        else:
            out = summed / float(ksize[0] * ksize[1])
    ctx.set_output("Out", out)


# ------------------------------------------------------------ batch norm ---
@register_op("batch_norm")
def batch_norm_kernel(ctx):
    """Reference: paddle/operators/batch_norm_op.cc. Train mode computes

    batch stats and updates the running mean/var persistables; eval mode
    consumes them. NCHW: stats over (N, H, W)."""
    x = ctx.input("X")
    scale, bias = ctx.input("Scale"), ctx.input("Bias")
    mean_v, var_v = ctx.input("Mean"), ctx.input("Variance")
    momentum = ctx.attr("momentum", 0.9)
    eps = ctx.attr("epsilon", 1e-5)
    is_test = ctx.attr("is_test", False)

    ch = x.ndim - 1 if ctx.attr("data_format", "NCHW") == "NHWC" else 1
    axes = tuple(i for i in range(x.ndim) if i != ch)
    shape = tuple(-1 if i == ch else 1 for i in range(x.ndim))
    # stats in f32 even when activations are bf16 (amp): mean/var of a
    # large batch loses precision in bf16; running stats stay f32 masters
    x32 = x.astype(jnp.float32)
    if is_test:
        mean, var = mean_v, var_v
    else:
        from ..flags import FLAGS

        if FLAGS.bn_bf16_stats:
            # escape-route experiment (PERF.md r4): square in the io
            # dtype, reduce with f32 accumulation, E[x^2]-E[x]^2 var
            mean = jnp.mean(x, axis=axes, dtype=jnp.float32)
            sq = jnp.mean(x * x, axis=axes, dtype=jnp.float32)
            var = jnp.maximum(sq - mean * mean, 0.0)
        else:
            mean = jnp.mean(x32, axis=axes)
            var = jnp.var(x32, axis=axes)
        new_mean = momentum * mean_v + (1 - momentum) * mean
        new_var = momentum * var_v + (1 - momentum) * var
        # running stats flow back into the Scope as persistables
        ctx.env[ctx.op.inputs["Mean"][0]] = new_mean
        ctx.env[ctx.op.inputs["Variance"][0]] = new_var
    inv = jax.lax.rsqrt(var + eps)
    out = (x32 - mean.reshape(shape)) * inv.reshape(shape) * scale.reshape(
        shape
    ) + bias.reshape(shape)
    ctx.set_output("Y", out.astype(x.dtype))


@register_op("layer_norm")
def layer_norm_kernel(ctx):
    """Reference: paddle/operators/layer_norm_op.cc (added late in v0.11)."""
    x = ctx.input("X")
    eps = ctx.attr("epsilon", 1e-5)
    begin = ctx.attr("begin_norm_axis", 1)
    axes = tuple(range(begin, x.ndim))
    x32 = x.astype(jnp.float32)  # stats in f32 under amp (see batch_norm)
    mean = jnp.mean(x32, axis=axes, keepdims=True)
    var = jnp.var(x32, axis=axes, keepdims=True)
    out = (x32 - mean) * jax.lax.rsqrt(var + eps)
    if ctx.has_input("Scale"):
        out = out * ctx.input("Scale")
    if ctx.has_input("Bias"):
        out = out + ctx.input("Bias")
    ctx.set_output("Y", out.astype(x.dtype))


# --------------------------------------------------------------- dropout ---
@register_op("dropout")
def dropout_kernel(ctx):
    """Reference: paddle/operators/dropout_op.cc — upscale-in-train off

    (reference scales at inference? No: reference multiplies by (1-p) at
    test time is NOT done; it masks without rescale in train). v0.11
    semantics: train: out = x * mask, mask ~ Bernoulli(1-p); test:
    out = x * (1-p)."""
    x = ctx.input("X")
    p = ctx.attr("dropout_prob", 0.5)
    if ctx.attr("is_test", False):
        ctx.set_output("Out", x * (1.0 - p) if isinstance(x, jnp.ndarray) else x.with_data(x.data * (1.0 - p)))
        return
    data = x.data if isinstance(x, LoDArray) else x
    mask = jax.random.bernoulli(ctx.rng(), 1.0 - p, data.shape)
    out = data * mask.astype(data.dtype)
    ctx.set_output("Out", x.with_data(out) if isinstance(x, LoDArray) else out)


# ---------------------------------------------------------------- losses ---
@register_op("cross_entropy")
def cross_entropy_kernel(ctx):
    """Reference: paddle/operators/cross_entropy_op.cc — X is a probability

    distribution [N, D]; Label is int [N, 1] (or soft labels [N, D])."""
    x = ctx.input("X")
    label = ctx.input("Label")
    eps = 1e-8
    if ctx.attr("soft_label", False):
        out = -jnp.sum(label * jnp.log(x + eps), axis=-1, keepdims=True)
    else:
        lbl = label[..., 0] if label.ndim == x.ndim else label
        picked = jnp.take_along_axis(x, lbl[..., None].astype(jnp.int32), axis=-1)
        out = -jnp.log(picked + eps)
    ctx.set_output("Y", out)


@register_op("softmax_with_cross_entropy")
def softmax_with_cross_entropy_kernel(ctx):
    """Reference: paddle/operators/softmax_with_cross_entropy_op.cc —

    numerically-stable fused version. Ragged (LoDArray) logits/labels give
    a per-token LoD loss with padding slots zeroed (the reference computes
    token losses over the flat no-padding layout for free)."""
    logits_in = ctx.input("Logits")
    label_in = ctx.input("Label")
    ragged = isinstance(logits_in, LoDArray)
    logits = logits_in.data if ragged else logits_in
    label = label_in.data if isinstance(label_in, LoDArray) else label_in
    # softmax/log in f32 even under amp (loss numerics)
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    if ctx.attr("soft_label", False):
        loss = -jnp.sum(label * logp, axis=-1, keepdims=True)
    else:
        lbl = label[..., 0] if label.ndim == logits.ndim else label
        lbl = jnp.clip(lbl.astype(jnp.int32), 0, logits.shape[-1] - 1)
        loss = -jnp.take_along_axis(logp, lbl[..., None], axis=-1)
    if ragged:
        loss = jnp.where(logits_in.token_mask[:, None], loss, 0.0)
        ctx.set_output("Softmax", logits_in.with_data(jnp.exp(logp)))
        ctx.set_output("Loss", logits_in.with_data(loss))
    else:
        ctx.set_output("Softmax", jnp.exp(logp))
        ctx.set_output("Loss", loss)


@register_op("square_error_cost")
def square_error_cost_kernel(ctx):
    """Reference: paddle/operators/squared_l2_distance_op.cc /

    gserver CostLayer sum_of_squares."""
    x, y = ctx.input("X"), ctx.input("Y")
    ctx.set_output("Out", jnp.square(x - y))


@register_op("huber_loss")
def huber_loss_kernel(ctx):
    x, y = ctx.input("X"), ctx.input("Y")
    d = ctx.attr("delta", 1.0)
    r = y - x
    a = jnp.abs(r)
    loss = jnp.where(a <= d, 0.5 * r * r, d * (a - 0.5 * d))
    ctx.set_output("Out", loss)


# --------------------------------------------------------------- metrics ---
@register_op("accuracy")
def accuracy_kernel(ctx):
    """Reference: paddle/operators/accuracy_op.cc — top-k indices vs label."""
    indices = ctx.input("Indices")  # [N, k] from top_k
    label = ctx.input("Label")  # [N, 1]
    correct = jnp.any(indices == label.astype(indices.dtype), axis=-1)
    ctx.set_output("Accuracy", jnp.mean(correct.astype(jnp.float32)))
    if ctx.has_output("Correct"):
        ctx.set_output("Correct", jnp.sum(correct.astype(jnp.int32)))
    if ctx.has_output("Total"):
        ctx.set_output("Total", jnp.asarray(indices.shape[0], jnp.int32))


# ------------------------------------------------------------------- lrn ---
@register_op("lrn")
def lrn_kernel(ctx):
    """Reference: paddle/operators/lrn_op.cc — local response norm across

    channels (AlexNet/GoogleNet)."""
    x = ctx.input("X")  # [N, C, H, W]
    n = ctx.attr("n", 5)
    k = ctx.attr("k", 2.0)
    alpha = ctx.attr("alpha", 1e-4)
    beta = ctx.attr("beta", 0.75)
    sq = jnp.square(x)
    half = n // 2
    pad = jnp.pad(sq, ((0, 0), (half, half), (0, 0), (0, 0)))
    windows = sum(pad[:, i : i + x.shape[1]] for i in range(n))
    ctx.set_output("Out", x / jnp.power(k + alpha * windows, beta))
