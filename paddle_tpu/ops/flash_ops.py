"""Flash attention: fused block-wise attention for long sequences.

Reference lineage: the reference (2017) predates transformer attention —
its fused-kernel philosophy lives in cuda/include/hl_lstm.h:42; this is
the modern long-context analogue (SURVEY.md §5.7's "seam for future
CP/ring-attention"). XLA's unfused attention materializes the [B, H, T, T]
score matrix in HBM (16 GB at T=32k bf16 — impossible); flash attention
streams K/V blocks through VMEM with an online softmax, O(T) memory.

Compute path: on TPU, JAX's Pallas TPU flash kernel
(jax.experimental.pallas.ops.tpu.flash_attention — public JAX library
code, used the way lax.conv uses XLA) with its custom VJP; anywhere else,
the jnp reference formulation. Layout here is [B, T, H, D] (the
framework's sequence-parallel convention, parallel/ring_attention.py);
the kernel's [B, H, T, D] transpose happens at the boundary and XLA
folds it into the kernel's operand layout.

`paddle_tpu.parallel.ulysses_attention` routes its per-device full-
sequence attention through here, so the SP path gets the fused kernel
for free.
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..core.registry import register_op

NEG_INF = -1e30  # large-finite mask fill (inf would NaN the softmax grads)


def scaled_dot_product_attention(q, k, v, causal: bool = False):
    """[B, T, H, D] attention, plain jnp — the numerical oracle for the
    flash kernel AND for ring/Ulysses sequence parallelism (re-exported
    by paddle_tpu.parallel; single implementation lives here)."""
    d = q.shape[-1]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(d)
    if causal:
        Tq, Tk = s.shape[-2], s.shape[-1]
        mask = jnp.arange(Tq)[:, None] >= jnp.arange(Tk)[None, :]
        s = jnp.where(mask, s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p, v)


_reference = scaled_dot_product_attention


def _shapes_flash_ok(q, k) -> bool:
    """Backend-independent shape rules (separately testable): 128-aligned
    q AND kv sequence lengths (the kernel's block divisibility — default
    blocks are 128 and clamp to the sequence), lane-aligned head dim."""
    Tq, Dq = q.shape[1], q.shape[3]
    Tk = k.shape[1]
    return Tq % 128 == 0 and Tk % 128 == 0 and Dq in (64, 128, 256)


# Dispatch policy (round 3, benchmarks/flash_block_tuning.json): with
# v5e-tuned block sizes the kernel BEATS XLA's fused attention fwd+bwd
# from T=1024 up — 1.4-1.5x at T=1-2k, 2.0x at 4k, 2.6x at 8k, 3.5x at
# 16k (the library's all-128 default blocks were why round 2 measured
# 0.59-0.71x). Below the measured window, or when the shape rules fail,
# XLA keeps the job; the score-bytes rule stays as the memory-capability
# route for shapes outside the measured-win window (XLA stops compiling
# outright around several GB of scores).
_FLASH_MIN_T = 1024
_SCORE_BYTES_THRESHOLD = 1.5e9


def _prefers_flash(q, k) -> bool:
    import numpy as np

    from . import mesh_dispatch

    B, Tq, H, _ = q.shape
    Tk = k.shape[1]
    if Tq >= _FLASH_MIN_T and Tk >= _FLASH_MIN_T:
        return True  # measured-win regime with tuned blocks
    # the shard_map'd kernel runs at the PER-SHARD batch (B/dp under a
    # mesh), so the score-buffer rule must see that batch too — same
    # eligibility discipline as the decoder/RNN kernels. local_batch
    # returns 0 when dp does not divide B; flash_attention falls back
    # to the XLA formulation for that case anyway.
    Bl = mesh_dispatch.local_batch(B)
    if Bl == 0:
        return False
    # scores inherit the input dtype in the reference formulation: f32
    # inputs double the buffer vs bf16
    itemsize = np.dtype(q.dtype).itemsize
    return Bl * H * Tq * Tk * itemsize > _SCORE_BYTES_THRESHOLD


def flash_eligible(q, k=None) -> bool:
    k = q if k is None else k
    return (
        jax.default_backend() == "tpu"
        and _shapes_flash_ok(q, k)
        and _prefers_flash(q, k)
    )


def _v5e_block_sizes(Tq: int, Tk: int, dtype=None):
    """Block choice for the TPU kernel. Consult order (tune/overrides):
    forced/tuned {block_q, block_k} for this (Tq, Tk, dtype, device) —
    validated against the shared legality predicate
    (tune/space.flash_block_legal: blocks must DIVIDE the 128-aligned
    sequence) — else the v5e-tuned analytic default
    (benchmarks/flash_block_tuning.json): 512-wide q/k blocks win up to
    T=4096, 1024 from 8192; repeated-trial medians confirm 512/512 at
    T=1024/2048 (1.4-1.5x over XLA). The default rounds its target down
    to the largest 128-multiple divisor (e.g. T=1280 → 256)."""
    import numpy as np

    from jax.experimental.pallas.ops.tpu.flash_attention import BlockSizes

    from ..tune import overrides as tune_overrides
    from ..tune.space import flash_block_legal

    def blk(T):
        if T % 128:
            # _flash_kernel is gate-free (benchmarks call it directly);
            # without this check b would decrement to 0 and `T % 0` raise
            raise ValueError(
                f"flash kernel requires a 128-aligned sequence, got T={T}"
            )
        b = min(T, 512 if T < 8192 else 1024)
        while T % b:
            b -= 128
        return b

    qb, kb = 0, 0
    ov = tune_overrides.lookup(
        "flash_attention", {"Tq": Tq, "Tk": Tk},
        np.dtype(dtype).name if dtype is not None else "bfloat16")
    if ov is not None:
        oq = int(ov.config.get("block_q", 0))
        ok = int(ov.config.get("block_k", 0))
        if flash_block_legal(oq, ok, Tq, Tk):
            qb, kb = oq, ok
        elif ov.source in ("forced", "env"):
            import warnings

            warnings.warn(
                f"forced flash blocks q={oq} k={ok} do not divide "
                f"Tq={Tq} Tk={Tk}; using the analytic default",
                stacklevel=2)
    if not qb:
        qb, kb = blk(Tq), blk(Tk)
    return BlockSizes(
        block_q=qb, block_k_major=kb, block_k=kb, block_b=1,
        block_q_major_dkv=qb, block_k_major_dkv=kb,
        block_k_dkv=kb, block_q_dkv=qb,
        block_k_major_dq=kb, block_k_dq=kb, block_q_dq=qb,
    )


def _flash_kernel(q, k, v, causal: bool):
    """Direct fused-kernel call, no dispatch gate (benchmarks and the
    eligible path both come through here)."""
    from jax.experimental.pallas.ops.tpu.flash_attention import (
        flash_attention as _tpu_flash,
    )

    bhtd = lambda x: jnp.transpose(x, (0, 2, 1, 3))  # noqa: E731
    o = _tpu_flash(
        bhtd(q), bhtd(k), bhtd(v), causal=causal,
        sm_scale=float(1.0 / math.sqrt(q.shape[-1])),
        block_sizes=_v5e_block_sizes(q.shape[1], k.shape[1], q.dtype),
    )
    return jnp.transpose(o, (0, 2, 1, 3))


def flash_attention(q, k, v, causal: bool = False):
    """[B, T, H, D] attention. From T=1024 the v5e-block-tuned fused
    kernel is the fast path (1.4-3.5x over XLA's fused attention fwd+bwd,
    benchmarks/flash_block_tuning.json) as well as the O(T)-memory path;
    below that window XLA keeps the job unless the score buffer would
    exceed the memory threshold. Numerics: bf16 io with f32
    online-softmax accumulation inside the kernel (matches the reference
    formulation to bf16 eps)."""
    if q.ndim != 4:
        raise ValueError(f"expected [B, T, H, D], got {q.shape}")
    if not flash_eligible(q, k):
        return _reference(q, k, v, causal)
    from . import mesh_dispatch

    am = mesh_dispatch.current()
    if am is not None and am.dp > 1:
        # mesh policy (ops/mesh_dispatch.py): a bare pallas_call cannot
        # be GSPMD-partitioned, so the kernel shard_maps over dp (batch
        # dim 0; no weights -> no cotangent psums). Under an mp axis the
        # wrap replicates heads (a resharding GSPMD inserts); sharding
        # heads over mp inside the wrap is a future multi-chip lever.
        # A batch dp does not divide falls back to the XLA formulation,
        # which GSPMD partitions natively.
        if q.shape[0] % am.dp:
            return _reference(q, k, v, causal)
        import functools

        call = mesh_dispatch.shard_batch(
            functools.partial(_flash_kernel, causal=causal),
            (0, 0, 0), ((0, 4),))
        return call(q, k, v)
    return _flash_kernel(q, k, v, causal)


@register_op("flash_attention")
def flash_attention_kernel(ctx):
    """Program-IR face of the dispatcher: Q/K/V are [B, T, E] packed
    multi-head projections; num_heads splits E. Used by
    layers.multi_head_attention (models/transformer.py)."""
    q, k, v = ctx.input("Q"), ctx.input("K"), ctx.input("V")
    heads = ctx.attr("num_heads")
    causal = ctx.attr("causal", True)
    B, T, E = q.shape
    if E % heads:
        raise ValueError(f"hidden dim {E} not divisible by heads {heads}")
    D = E // heads
    split = lambda x: x.reshape(B, x.shape[1], heads, D)  # noqa: E731
    o = flash_attention(split(q), split(k), split(v), causal=causal)
    ctx.set_output("Out", o.reshape(B, T, E))
