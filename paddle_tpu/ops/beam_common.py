"""Shared beam-search machinery.

Reference: the expand/prune/backtrack cycle of
RecurrentGradientMachine::beamSearch (RecurrentGradientMachine.h:309) and
beam_search_op.cc/beam_search_decode_op.cc. Used by both the fixed
attention-GRU decoder (attention_ops.py) and the generic sub-block decoder
(generation_ops.py) so the semantics can't diverge.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e9


def init_scores(B: int, K: int, dtype=jnp.float32):
    """[B, K] scores with only beam 0 live at t=0, so the first expansion

    isn't K duplicates of the same hypothesis."""
    return (
        jnp.where(jnp.arange(K) == 0, 0.0, NEG_INF) * jnp.ones((B, 1))
    ).astype(dtype)


def freeze_finished(logp, finished, eos: int):
    """Finished hypotheses may only emit EOS, at zero cost; every other

    continuation is -inf so no child of a frozen beam can re-enter the
    top-k ahead of a live hypothesis."""
    V = logp.shape[-1]
    eos_only = jnp.where(
        jnp.arange(V) == eos, 0.0, jnp.asarray(NEG_INF, logp.dtype)
    )
    return jnp.where(finished[..., None], eos_only, logp)


def expand_prune(scores, logp, K: int):
    """Add per-token log-probs, take the global top-K over [K*V].

    Returns (new_scores [B,K], parent [B,K], token [B,K] int32)."""
    B = scores.shape[0]
    V = logp.shape[-1]
    total = scores[..., None] + logp
    top_sc, top_idx = jax.lax.top_k(total.reshape(B, K * V), K)
    return top_sc, top_idx // V, (top_idx % V).astype(jnp.int32)


def backtrack(parents, toks, B: int, K: int):
    """Walk the (parent, token) trellis backwards → ids [B, K, T]."""

    def back(beam_idx, pt):
        parent, tok = pt
        t = jnp.take_along_axis(tok, beam_idx, axis=1)
        prev = jnp.take_along_axis(parent, beam_idx, axis=1)
        return prev, t

    last = jnp.broadcast_to(jnp.arange(K)[None], (B, K))
    _, ids_rev = jax.lax.scan(back, last, (parents, toks), reverse=True)
    return jnp.moveaxis(ids_rev, 0, -1)


def finalize(ids, scores, eos: int, T: int, length_normalize: bool):
    """Lengths to first EOS (inclusive), optional length-normalized

    re-sort best-first. Returns (ids, scores, lengths)."""
    is_eos = ids == eos
    any_eos = is_eos.any(axis=-1)
    first_eos = jnp.argmax(is_eos, axis=-1)
    lengths = jnp.where(any_eos, first_eos + 1, T).astype(jnp.int32)
    if length_normalize:
        scores = scores / jnp.maximum(lengths, 1).astype(scores.dtype)
        order = jnp.argsort(-scores, axis=1)
        scores = jnp.take_along_axis(scores, order, axis=1)
        ids = jnp.take_along_axis(ids, order[..., None], axis=1)
        lengths = jnp.take_along_axis(lengths, order, axis=1)
    return ids, scores, lengths
