"""Recurrent op kernels: LSTM / GRU / simple RNN over ragged batches.

Reference: paddle/operators/lstm_op.cc + operators/math/lstm_compute (the
fused cell math), cuda/include/hl_gpu_lstm.cuh / hl_lstm.h:42
(hl_lstm_parallel_forward — the hand-fused per-timestep CUDA kernels), and
Gen-1 gserver/layers/LstmLayer.cpp / GatedRecurrentLayer.cpp.

TPU design: the reference reorders ragged sequences into per-timestep
dense batches (sequence2batch) and launches one fused kernel per step.
Here the same layout transform happens once (LoDArray.to_batch), then a
single `lax.scan` carries (h, c) across timesteps — XLA fuses the gate
matmul + elementwise into one MXU-friendly loop body, which is exactly
what hl_lstm_parallel_forward hand-wrote. Padding steps are masked so the
carry freezes past each sequence's end (no-padding semantics preserved).

Gate layout in the packed 4H weight/bias: [i, f, g(candidate), o]; GRU
packed 3H: [u(update), r(reset), c(candidate)].
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op
from ..flags import FLAGS
from . import mesh_dispatch, pallas_kernels
from .activation_ops import _ACTIVATIONS


def _act(name):
    if name == "identity" or name is None:
        return lambda v: v
    fn = _ACTIVATIONS[name]
    return lambda v: fn(v, {})


def lstm_scan(
    x_tbh,  # [T, B, 4H] projected input
    mask,  # [T, B]
    w_rec,  # [H, 4H]
    bias,  # [4H] or None
    w_peephole=None,  # [3H] (Wic, Wfc, Woc) or None
    h0=None,
    c0=None,
    gate_act="sigmoid",
    cell_act="tanh",
    cand_act="tanh",
    reverse=False,
):
    """Core masked LSTM scan. Returns (h_seq [T,B,H], (h_T, c_T))."""
    T, B, H4 = x_tbh.shape
    H = H4 // 4
    ga, ca, da = _act(gate_act), _act(cell_act), _act(cand_act)
    # uniform compute dtype: under amp the projected input arrives bf16
    # while weights/bias/boot-state are f32 masters — cast them down so
    # the scan carry dtype is stable (bf16 keeps the recurrence HBM-light;
    # the recurrent matmul still accumulates f32 on the MXU below)
    dt = x_tbh.dtype
    w_rec = w_rec.astype(dt)
    bias = None if bias is None else bias.astype(dt)
    w_peephole = None if w_peephole is None else w_peephole.astype(dt)
    h0 = jnp.zeros((B, H), dt) if h0 is None else h0.astype(dt)
    c0 = jnp.zeros((B, H), dt) if c0 is None else c0.astype(dt)
    if reverse:
        x_tbh = x_tbh[::-1]
        mask = mask[::-1]
    if w_peephole is not None:
        w_ic, w_fc, w_oc = jnp.split(w_peephole, 3)
    else:
        w_ic = w_fc = w_oc = None

    def step(carry, inp):
        h_prev, c_prev = carry
        x_t, m_t = inp
        gates = x_t + jnp.dot(
            h_prev, w_rec, preferred_element_type=jnp.float32
        ).astype(x_t.dtype)
        if bias is not None:
            gates = gates + bias
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        if w_ic is not None:
            i = i + c_prev * w_ic
            f = f + c_prev * w_fc
        i, f = ga(i), ga(f)
        c = f * c_prev + i * da(g)
        if w_oc is not None:
            o = o + c * w_oc
        o = ga(o)
        h = o * ca(c)
        m = m_t[:, None].astype(x_t.dtype)
        h = m * h + (1 - m) * h_prev
        c = m * c + (1 - m) * c_prev
        return (h, c), h

    (h_T, c_T), h_seq = jax.lax.scan(step, (h0, c0), (x_tbh, mask))
    if reverse:
        h_seq = h_seq[::-1]
    return h_seq, (h_T, c_T)


def gru_cell(xp, h_prev, w_rec, ga, da):
    """One GRU step on a pre-projected (and biased) input xp [..., 3H].

    w_rec packs [H, 2H] update/reset + [H, H] candidate as [H, 3H].
    Reference: operators/math/detail/gru_kernel.h:62 gru_finalOutput —
    h = (1-u)*h_prev + u*c. Shared by gru_scan and the attention decoder."""
    H = h_prev.shape[-1]
    w_ur, w_c = w_rec[:, : 2 * H], w_rec[:, 2 * H :]
    x_ur, x_c = xp[..., : 2 * H], xp[..., 2 * H :]
    ur = ga(
        x_ur
        + jnp.dot(h_prev, w_ur, preferred_element_type=jnp.float32).astype(xp.dtype)
    )
    u, r = ur[..., :H], ur[..., H:]
    c = da(
        x_c
        + jnp.dot(r * h_prev, w_c, preferred_element_type=jnp.float32).astype(
            xp.dtype
        )
    )
    return (1 - u) * h_prev + u * c


def gru_scan(
    x_tbh,  # [T, B, 3H]
    mask,  # [T, B]
    w_rec,  # [H, 2H] for update/reset + [H, H] candidate packed as [H, 3H]
    bias,  # [3H] or None
    h0=None,
    gate_act="sigmoid",
    cand_act="tanh",
    reverse=False,
):
    """Masked GRU scan (reference: operators/gru_op.cc, hl_gpu_gru.cuh)."""
    T, B, H3 = x_tbh.shape
    H = H3 // 3
    ga, da = _act(gate_act), _act(cand_act)
    dt = x_tbh.dtype  # uniform carry dtype under amp (see lstm_scan)
    w_rec = w_rec.astype(dt)
    bias = None if bias is None else bias.astype(dt)
    h0 = jnp.zeros((B, H), dt) if h0 is None else h0.astype(dt)
    if reverse:
        x_tbh = x_tbh[::-1]
        mask = mask[::-1]
    def step(h_prev, inp):
        x_t, m_t = inp
        if bias is not None:
            x_t = x_t + bias
        h = gru_cell(x_t, h_prev, w_rec, ga, da)
        m = m_t[:, None].astype(x_t.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    h_T, h_seq = jax.lax.scan(step, h0, (x_tbh, mask))
    if reverse:
        h_seq = h_seq[::-1]
    return h_seq, h_T


def stacked_lstm2_scan(x_tbh, mask, w1, b1, wx2, w2, b2):
    """Two stacked LSTM layers in ONE masked scan: layer 2's input
    projection (h1 @ wx2) runs inside the step, so the sequential step
    count is T instead of 2T. Measured ≈1.2× on the recurrence at
    dispatch-floor-bound cells (experiments/exp_lstm_smallcell.py,
    PERF.md r4 small-cell section). Standard gates only (sigmoid/tanh,
    no peepholes, forward)."""
    T, B, H4 = x_tbh.shape
    H = H4 // 4
    dt = x_tbh.dtype
    w1, wx2, w2 = (w.astype(dt) for w in (w1, wx2, w2))
    b1 = None if b1 is None else b1.astype(dt)
    b2 = None if b2 is None else b2.astype(dt)
    z = jnp.zeros((B, H), dt)

    def cell(x_t, h_prev, c_prev, w, b, m):
        gates = x_t + jnp.dot(
            h_prev, w, preferred_element_type=jnp.float32).astype(dt)
        if b is not None:
            gates = gates + b
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = (jax.nn.sigmoid(v) for v in (i, f, o))
        c = f * c_prev + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return m * h + (1 - m) * h_prev, m * c + (1 - m) * c_prev

    def step(carry, inp):
        h1, c1, h2, c2 = carry
        x_t, m_t = inp
        m = m_t[:, None].astype(dt)
        h1, c1 = cell(x_t, h1, c1, w1, b1, m)
        xp2 = jnp.dot(h1, wx2,
                      preferred_element_type=jnp.float32).astype(dt)
        h2, c2 = cell(xp2, h2, c2, w2, b2, m)
        return (h1, c1, h2, c2), h2

    (_, _, h2_T, c2_T), h2_seq = jax.lax.scan(
        step, (z, z, z, z), (x_tbh, mask))
    return h2_seq, (h2_T, c2_T)


@register_op("stacked_lstm2")
def stacked_lstm2_kernel(ctx):
    """Two stacked LSTM layers with the inter-layer projection absorbed
    (the hot structure of benchmark/paddle/rnn/rnn.py). Trace-time
    dispatch: where the per-layer fused Pallas kernel is eligible it
    wins more than layer-packing (each layer's whole sequence is one
    kernel), so the op runs two fused layers with a batched inter-layer
    matmul; otherwise the single stacked scan halves the sequential
    step count of the two-scan formulation."""
    x: LoDArray = ctx.input("Input")  # [*, 4H] pre-projected layer 1
    w1, wx2, w2 = (ctx.input(k) for k in ("Weight1", "WX2", "Weight2"))
    b1 = ctx.input("Bias1") if ctx.has_input("Bias1") else None
    b2 = ctx.input("Bias2") if ctx.has_input("Bias2") else None
    max_len = ctx.attr("max_len") or x.capacity
    x_tb, mask = x.to_batch(max_len=max_len)
    B, H = x_tb.shape[1], w1.shape[0]
    if FLAGS.use_fused_rnn and pallas_kernels.lstm_supported(
            mesh_dispatch.local_batch(B), H, "sigmoid", "tanh", "tanh",
            None, itemsize=x_tb.dtype.itemsize):
        h1_seq, _ = pallas_kernels.lstm_fused(x_tb, mask, w1, bias=b1)
        xp2 = jnp.dot(h1_seq, wx2.astype(h1_seq.dtype),
                      preferred_element_type=jnp.float32
                      ).astype(h1_seq.dtype)
        h2_seq, _ = pallas_kernels.lstm_fused(xp2, mask, w2, bias=b2)
    else:
        h2_seq, _ = stacked_lstm2_scan(x_tb, mask, w1, b1, wx2, w2, b2)
    ctx.set_output("Hidden", LoDArray.from_batch(h2_seq, mask, x))


def stacked_lstm_book_scan(x_tbh, mask, ws, bs, was, wbs, fbs):
    """N stacked LSTM layers in ONE masked scan, with the book's
    inter-layer structure (understand_sentiment stacked_lstm_net):
    layer i's gate projection fc_i = fc_{i-1} @ WA_i + h_{i-1} @ WB_i
    (+ bias) — the concat-fc over [fc_prev, lstm_prev] — computed
    inside the step, so the sequential step count is T instead of nT.
    Returns (fc_n_seq, h_n_seq): the book pools BOTH streams.
    Standard gates only (sigmoid/tanh, forward) — the book's config."""
    T, B, H4 = x_tbh.shape
    H = H4 // 4
    n = len(ws)
    dt = x_tbh.dtype
    ws = [w.astype(dt) for w in ws]
    bs = [None if b is None else b.astype(dt) for b in bs]
    was = [w.astype(dt) for w in was]
    wbs = [w.astype(dt) for w in wbs]
    fbs = [None if b is None else b.astype(dt) for b in fbs]
    z = jnp.zeros((B, H), dt)

    def cell(x_t, h_prev, c_prev, w, b, m):
        gates = x_t + jnp.dot(
            h_prev, w, preferred_element_type=jnp.float32).astype(dt)
        if b is not None:
            gates = gates + b
        i, f, g, o = jnp.split(gates, 4, axis=-1)
        i, f, o = (jax.nn.sigmoid(v) for v in (i, f, o))
        c = f * c_prev + i * jnp.tanh(g)
        h = o * jnp.tanh(c)
        return m * h + (1 - m) * h_prev, m * c + (1 - m) * c_prev

    def step(carry, inp):
        states = list(carry)  # [(h_i, c_i)] * n
        x_t, m_t = inp
        m = m_t[:, None].astype(dt)
        fc = x_t
        for i in range(n):
            if i > 0:
                fc = (jnp.dot(fc, was[i - 1],
                              preferred_element_type=jnp.float32)
                      + jnp.dot(states[i - 1][0], wbs[i - 1],
                                preferred_element_type=jnp.float32)
                      ).astype(dt)
                if fbs[i - 1] is not None:
                    fc = fc + fbs[i - 1]
            states[i] = cell(fc, *states[i], ws[i], bs[i], m)
        return tuple(states), (fc, states[-1][0])

    init = tuple((z, z) for _ in range(n))
    _, (fc_seq, h_seq) = jax.lax.scan(step, init, (x_tbh, mask))
    return fc_seq, h_seq


@register_op("stacked_lstm")
def stacked_lstm_kernel(ctx):
    """N-layer book-structure stacked LSTM (reference: fluid book
    understand_sentiment stacked_lstm_net, stacked_num layers) as ONE
    op. Default formulation: layer by layer — each layer a fused Pallas
    kernel where eligible (else a masked scan), with the inter-layer
    concat-fc as a BATCHED matmul over the full [T, B, ·] sequence.

    Measured (experiments/exp_stacked_book.py, benchmarks/
    stacked_book.json): at the book's dispatch-bound hid=128 no
    formulation separates from the tunnel's noise floor (op-vs-
    per-layer swung 0.79x-1.30x across identical interleaved runs);
    at hid=512 the op is stably neutral (1.01x). The layer-by-layer
    default stands on the structural argument: the book's [4H, 4H]
    concat-fc runs as ONE [T*B, 4H] batched matmul per layer here,
    where the stacked_lstm2-style single scan would run it as T
    sequential [B, 4H] matmuls. (stacked_lstm2's pure stack won its
    trade 1.25-1.46x — far above this noise floor — because its
    inter-layer op is the thin [H, 4H] projection.) The single-scan
    formulation stays available under FLAGS.stacked_lstm_single_scan,
    parity-tested.

    Inputs: Input (layer-1 [*, 4H] projection, LoDArray), Weights (n of
    [H, 4H]), WAs (n-1 of [4H, 4H]: fc_prev half of the inter-layer
    fc), WBs (n-1 of [H, 4H]: lstm_prev half), Biases (n of [4H],
    optional), FcBiases (n-1 of [4H], optional).
    Outputs: FcOut and Hidden — the book pools both streams."""
    x: LoDArray = ctx.input("Input")
    ws = ctx.inputs("Weights")
    was = ctx.inputs("WAs")
    wbs = ctx.inputs("WBs")
    n = len(ws)
    bs = ctx.inputs("Biases") if ctx.has_input("Biases") else [None] * n
    fbs = (ctx.inputs("FcBiases") if ctx.has_input("FcBiases")
           else [None] * (n - 1))
    max_len = ctx.attr("max_len") or x.capacity
    x_tb, mask = x.to_batch(max_len=max_len)
    B, H = x_tb.shape[1], ws[0].shape[0]
    dt = x_tb.dtype
    if FLAGS.stacked_lstm_single_scan:
        fc_seq, h_seq = stacked_lstm_book_scan(
            x_tb, mask, ws, bs, was, wbs, fbs)
    else:
        fused = FLAGS.use_fused_rnn and pallas_kernels.lstm_supported(
            mesh_dispatch.local_batch(B), H, "sigmoid", "tanh", "tanh",
            None, itemsize=x_tb.dtype.itemsize)
        fc_seq = x_tb
        h_seq = None
        for i in range(n):
            if i > 0:
                fc_seq = (jnp.dot(fc_seq, was[i - 1].astype(dt),
                                  preferred_element_type=jnp.float32)
                          + jnp.dot(h_seq, wbs[i - 1].astype(dt),
                                    preferred_element_type=jnp.float32)
                          ).astype(dt)
                if fbs[i - 1] is not None:
                    fc_seq = fc_seq + fbs[i - 1].astype(dt)
            if fused:
                h_seq, _ = pallas_kernels.lstm_fused(fc_seq, mask, ws[i],
                                                     bias=bs[i])
            else:
                h_seq, _ = lstm_scan(
                    fc_seq, mask, ws[i].astype(dt),
                    None if bs[i] is None else bs[i].astype(dt))
    ctx.set_output("FcOut", LoDArray.from_batch(fc_seq, mask, x))
    ctx.set_output("Hidden", LoDArray.from_batch(h_seq, mask, x))


@register_op("dynamic_lstm")
def dynamic_lstm_kernel(ctx):
    """Reference: paddle/operators/lstm_op.cc / fluid layers nn.py:227.

    Input is the pre-projected [*, 4H] LoDArray (the x @ W_x fc happens in
    the preceding layer, matching the reference API)."""
    x: LoDArray = ctx.input("Input")
    w = ctx.input("Weight")  # [H, 4H]
    b = ctx.input("Bias") if ctx.has_input("Bias") else None
    use_peep = ctx.attr("use_peepholes", False)
    peep = None
    if b is not None and use_peep:
        b, peep = b[: w.shape[1]], b[w.shape[1] :]
    max_len = ctx.attr("max_len") or x.capacity
    x_tb, mask = x.to_batch(max_len=max_len)
    gate_act = ctx.attr("gate_activation", "sigmoid")
    cell_act = ctx.attr("cell_activation", "tanh")
    cand_act = ctx.attr("candidate_activation", "tanh")
    reverse = ctx.attr("is_reverse", False)
    B, H = x_tb.shape[1], w.shape[0]
    if FLAGS.use_fused_rnn and pallas_kernels.lstm_supported(
        mesh_dispatch.local_batch(B), H, gate_act, cell_act, cand_act,
        peep, itemsize=x_tb.dtype.itemsize,
    ):
        h_seq, (h_T, c_T) = pallas_kernels.lstm_fused(
            x_tb, mask, w, bias=b, reverse=reverse
        )
    else:
        h_seq, (h_T, c_T) = lstm_scan(
            x_tb,
            mask,
            w,
            b,
            w_peephole=peep,
            gate_act=gate_act,
            cell_act=cell_act,
            cand_act=cand_act,
            reverse=reverse,
        )
    ctx.set_output("Hidden", LoDArray.from_batch(h_seq, mask, x))
    if ctx.has_output("LastH"):
        ctx.set_output("LastH", h_T)
    if ctx.has_output("LastC"):
        ctx.set_output("LastC", c_T)


@register_op("dynamic_gru")
def dynamic_gru_kernel(ctx):
    """Reference: paddle/operators/gru_op.cc / Gen-1 GatedRecurrentLayer."""
    x: LoDArray = ctx.input("Input")
    w = ctx.input("Weight")  # [H, 3H]
    b = ctx.input("Bias") if ctx.has_input("Bias") else None
    max_len = ctx.attr("max_len") or x.capacity
    x_tb, mask = x.to_batch(max_len=max_len)
    gate_act = ctx.attr("gate_activation", "sigmoid")
    cand_act = ctx.attr("candidate_activation", "tanh")
    reverse = ctx.attr("is_reverse", False)
    B, H = x_tb.shape[1], w.shape[0]
    if FLAGS.use_fused_rnn and pallas_kernels.gru_supported(
        mesh_dispatch.local_batch(B), H, gate_act, cand_act,
        itemsize=x_tb.dtype.itemsize
    ):
        h_seq, h_T = pallas_kernels.gru_fused(
            x_tb, mask, w, bias=b, reverse=reverse
        )
    else:
        h_seq, h_T = gru_scan(
            x_tb,
            mask,
            w,
            b,
            gate_act=gate_act,
            cand_act=cand_act,
            reverse=reverse,
        )
    ctx.set_output("Hidden", LoDArray.from_batch(h_seq, mask, x))
    if ctx.has_output("LastH"):
        ctx.set_output("LastH", h_T)


@register_op("simple_rnn")
def simple_rnn_kernel(ctx):
    """Gen-1 RecurrentLayer.cpp: h_t = act(x_t + h_{t-1} @ W)."""
    x: LoDArray = ctx.input("Input")
    w = ctx.input("Weight")  # [H, H]
    b = ctx.input("Bias") if ctx.has_input("Bias") else None
    act = _act(ctx.attr("activation", "tanh"))
    max_len = ctx.attr("max_len") or x.capacity
    x_tb, mask = x.to_batch(max_len=max_len)

    def step(h_prev, inp):
        x_t, m_t = inp
        h = x_t + jnp.dot(h_prev, w, preferred_element_type=jnp.float32).astype(
            x_t.dtype
        )
        if b is not None:
            h = h + b
        h = act(h)
        m = m_t[:, None].astype(x_t.dtype)
        h = m * h + (1 - m) * h_prev
        return h, h

    B, H = x_tb.shape[1], w.shape[0]
    h0 = jnp.zeros((B, H), x_tb.dtype)
    h_T, h_seq = jax.lax.scan(step, h0, (x_tb, mask))
    ctx.set_output("Hidden", LoDArray.from_batch(h_seq, mask, x))
    if ctx.has_output("LastH"):
        ctx.set_output("LastH", h_T)
