"""recurrent_group op kernel: trace a step sub-block into lax.scan.

Reference: RecurrentGradientMachine::forward
(gserver/gradientmachines/RecurrentGradientMachine.h:54 — ragged-to-frame
index maps :374-383, per-timestep frames :428, memory links :342). Instead
of cloning the step network per frame, the sub-block is traced ONCE as the
body of a `lax.scan` over the time-major dense form of the inputs; the
validity mask freezes memories past each sequence's end, reproducing the
frame machinery's per-sequence last state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


@register_op("recurrent_group")
def recurrent_group_kernel(ctx):
    seqs = ctx.inputs("Seq")
    boots = ctx.inputs("Boot")
    if not seqs or not isinstance(seqs[0], LoDArray):
        raise TypeError("recurrent_group inputs must be LoDArray sequences")
    first = seqs[0]
    max_len = ctx.attr("max_len") or first.capacity
    is_reverse = ctx.attr("is_reverse", False)

    for s in seqs[1:]:
        # all step inputs must share one LoD layout (the reference's
        # RecurrentGradientMachine asserts identical sequence layouts)
        if s.capacity != first.capacity or s.max_seqs != first.max_seqs:
            raise ValueError(
                "recurrent_group step inputs have different LoD capacities: "
                f"{s.capacity}x{s.max_seqs} vs {first.capacity}x{first.max_seqs}"
            )
    xs, mask = [], None
    for s in seqs:
        b, m = s.to_batch(max_len)  # [T, B, ...], [T, B]
        xs.append(b)
        # AND of all masks: if lengths disagree (checkable only at runtime),
        # a token counts only where every input has one
        mask = m if mask is None else jnp.logical_and(mask, m)
    B = first.max_seqs

    seq_inner = list(ctx.attr("seq_inner"))
    mem_inner = list(ctx.attr("mem_inner"))
    mem_update = list(ctx.attr("mem_update"))
    mem_has_boot = list(ctx.attr("mem_has_boot"))
    mem_shape = [tuple(s) for s in ctx.attr("mem_shape")]
    mem_init = list(ctx.attr("mem_init_value"))
    mem_dtype = list(ctx.attr("mem_dtype"))
    out_inner = list(ctx.attr("out_inner"))

    carries = []
    boot_it = iter(boots)
    for has_boot, shape, init, dt in zip(
        mem_has_boot, mem_shape, mem_init, mem_dtype
    ):
        if has_boot:
            bv = next(boot_it)
            bv = bv.data if isinstance(bv, LoDArray) else bv
            if bv.shape[0] != B:
                raise ValueError(
                    f"memory boot batch {bv.shape[0]} != sequence batch {B}"
                )
            carries.append(bv)
        else:
            carries.append(jnp.full((B,) + shape, init, jnp.dtype(dt)))

    block = ctx.executor.program.blocks[ctx.attr("sub_block")]
    outer_env = dict(ctx.env)  # closure: params, statics, @RNG@/@AMP@

    # per-group RNG stream: consume one counter from the outer stream, then
    # fold the timestep in so each frame draws fresh randomness (dropout in
    # the step body gets a new mask per t, matching per-frame semantics)
    base_key = jax.random.fold_in(
        outer_env["@RNG@"], outer_env.get("@RNG_COUNTER@", 0)
    )
    ctx.env["@RNG_COUNTER@"] = outer_env.get("@RNG_COUNTER@", 0) + 1

    if is_reverse:
        xs = [jnp.flip(x, axis=0) for x in xs]
        mask = jnp.flip(mask, axis=0)

    t_idx = jnp.arange(mask.shape[0], dtype=jnp.int32)

    def body(carry, step):
        step_xs, m, t = step  # tuple of [B, ...], [B], scalar t
        env = dict(outer_env)
        env["@RNG@"] = jax.random.fold_in(base_key, t)
        env["@RNG_COUNTER@"] = 0
        for name, x in zip(seq_inner, step_xs):
            env[name] = x
        for name, c in zip(mem_inner, carry):
            env[name] = c
        ctx.executor.run_ops(block.ops, env, dict(env), block)
        new_carry = tuple(
            jnp.where(m.reshape((B,) + (1,) * (env[u].ndim - 1)), env[u], c)
            for u, c in zip(mem_update, carry)
        )
        outs = tuple(env[o] for o in out_inner)
        return new_carry, outs

    final, outs = jax.lax.scan(body, tuple(carries), (tuple(xs), mask, t_idx))

    if is_reverse:
        outs = tuple(jnp.flip(o, axis=0) for o in outs)
        mask = jnp.flip(mask, axis=0)

    for i, o in enumerate(outs):
        ctx.set_output("Out", LoDArray.from_batch(o, mask, first), i)
    for i, f in enumerate(final):
        if i < len(ctx.op.outputs.get("FinalMem", [])):
            ctx.set_output("FinalMem", f, i)
