"""recurrent_group op kernel: trace a step sub-block into lax.scan.

Reference: RecurrentGradientMachine::forward
(gserver/gradientmachines/RecurrentGradientMachine.h:54 — ragged-to-frame
index maps :374-383, per-timestep frames :428, memory links :342). Instead
of cloning the step network per frame, the sub-block is traced ONCE as the
body of a `lax.scan` over the time-major dense form of the inputs; the
validity mask freezes memories past each sequence's end, reproducing the
frame machinery's per-sequence last state.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


def _build_carries(ctx, boots, B):
    """Memory carries from the op's mem_* attrs (+ boot batch validation).

    Shared by recurrent_group / nested_recurrent_group."""
    carries = []
    boot_it = iter(boots)
    for has_boot, shape, init, dt in zip(
        ctx.attr("mem_has_boot"),
        [tuple(s_) for s_ in ctx.attr("mem_shape")],
        ctx.attr("mem_init_value"),
        ctx.attr("mem_dtype"),
    ):
        if has_boot:
            bv = next(boot_it)
            bv = bv.data if isinstance(bv, LoDArray) else bv
            if bv.shape[0] != B:
                raise ValueError(
                    f"memory boot batch {bv.shape[0]} != sequence batch {B}"
                )
            carries.append(bv)
        else:
            carries.append(jnp.full((B,) + shape, init, jnp.dtype(dt)))
    return carries


def _group_rng(ctx, outer_env):
    """Consume one outer RNG counter for the whole group; per-step fold-in

    of the returned base key gives each frame fresh randomness."""
    base_key = jax.random.fold_in(
        outer_env["@RNG@"], outer_env.get("@RNG_COUNTER@", 0)
    )
    ctx.env["@RNG_COUNTER@"] = outer_env.get("@RNG_COUNTER@", 0) + 1
    return base_key


@register_op("recurrent_group")
def recurrent_group_kernel(ctx):
    seqs = ctx.inputs("Seq")
    boots = ctx.inputs("Boot")
    if not seqs or not isinstance(seqs[0], LoDArray):
        raise TypeError("recurrent_group inputs must be LoDArray sequences")
    first = seqs[0]
    max_len = ctx.attr("max_len") or first.capacity
    is_reverse = ctx.attr("is_reverse", False)

    for s in seqs[1:]:
        # all step inputs must share one LoD layout (the reference's
        # RecurrentGradientMachine asserts identical sequence layouts)
        if s.capacity != first.capacity or s.max_seqs != first.max_seqs:
            raise ValueError(
                "recurrent_group step inputs have different LoD capacities: "
                f"{s.capacity}x{s.max_seqs} vs {first.capacity}x{first.max_seqs}"
            )
    xs, mask = [], None
    for s in seqs:
        b, m = s.to_batch(max_len)  # [T, B, ...], [T, B]
        xs.append(b)
        # AND of all masks: if lengths disagree (checkable only at runtime),
        # a token counts only where every input has one
        mask = m if mask is None else jnp.logical_and(mask, m)
    B = first.max_seqs

    seq_inner = list(ctx.attr("seq_inner"))
    mem_inner = list(ctx.attr("mem_inner"))
    mem_update = list(ctx.attr("mem_update"))
    out_inner = list(ctx.attr("out_inner"))

    carries = _build_carries(ctx, boots, B)

    block = ctx.executor.program.blocks[ctx.attr("sub_block")]
    outer_env = dict(ctx.env)  # closure: params, statics, @RNG@/@AMP@
    # per-group RNG stream: each frame draws fresh randomness (dropout in
    # the step body gets a new mask per t, matching per-frame semantics)
    base_key = _group_rng(ctx, outer_env)

    if is_reverse:
        xs = [jnp.flip(x, axis=0) for x in xs]
        mask = jnp.flip(mask, axis=0)

    t_idx = jnp.arange(mask.shape[0], dtype=jnp.int32)

    def body(carry, step):
        step_xs, m, t = step  # tuple of [B, ...], [B], scalar t
        env = dict(outer_env)
        env["@RNG@"] = jax.random.fold_in(base_key, t)
        env["@RNG_COUNTER@"] = 0
        for name, x in zip(seq_inner, step_xs):
            env[name] = x
        for name, c in zip(mem_inner, carry):
            env[name] = c
        ctx.executor.run_ops(block.ops, env, dict(env), block)
        new_carry = tuple(
            jnp.where(m.reshape((B,) + (1,) * (env[u].ndim - 1)), env[u], c)
            for u, c in zip(mem_update, carry)
        )
        outs = tuple(env[o] for o in out_inner)
        return new_carry, outs

    final, outs = jax.lax.scan(body, tuple(carries), (tuple(xs), mask, t_idx))

    if is_reverse:
        outs = tuple(jnp.flip(o, axis=0) for o in outs)
        mask = jnp.flip(mask, axis=0)

    for i, o in enumerate(outs):
        ctx.set_output("Out", LoDArray.from_batch(o, mask, first), i)
    for i, f in enumerate(final):
        if i < len(ctx.op.outputs.get("FinalMem", [])):
            ctx.set_output("FinalMem", f, i)


def _lod_from_lengths(lengths, capacity: int, like_data, trailing_shape,
                      num_seqs):
    """Build an empty LoDArray with the given per-sequence lengths."""
    offsets = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(lengths, dtype=jnp.int32)]
    )
    total = offsets[-1]
    pos = jnp.arange(capacity, dtype=jnp.int32)
    seq_ids = jnp.searchsorted(offsets, pos, side="right").astype(jnp.int32) - 1
    seq_ids = jnp.where(pos < total, seq_ids, -1)
    data = jnp.zeros((capacity,) + tuple(trailing_shape), like_data.dtype)
    return LoDArray(data, seq_ids, lengths.astype(jnp.int32), num_seqs)


@register_op("nested_recurrent_group")
def nested_recurrent_group_kernel(ctx):
    """Outer recurrence over sub-sequences of 2-level ragged inputs.

    Reference: RecurrentGradientMachine::createInFrameInfo_subseq
    (RecurrentGradientMachine.h:374-383) — frame t of outer sequence b is
    that sequence's t-th SUB-sequence. Densified to [S, B, L, ...] + masks
    with segment ops over (seq_ids, sub_seq_ids), then scanned like
    recurrent_group; outputs form a 1-level sequence with one token per
    sub-sequence."""
    seqs = ctx.inputs("Seq")
    boots = ctx.inputs("Boot")
    first: LoDArray = seqs[0]
    if first.sub_seq_ids is None:
        raise ValueError("nested_recurrent_group needs a 2-level LoDArray "
                         "(built via LoDArray.from_nested_sequences)")
    S = ctx.attr("max_subseqs")
    L = ctx.attr("max_sublen")
    B = first.max_seqs
    C = first.capacity
    # the global subsequence-id space must cover every sub in the batch
    # regardless of how they distribute across sequences; each sub has at
    # least one token, so the flat capacity bounds it
    G = C

    def sub_layout(sq):
        """Gather map from THIS input's own (seq_ids, sub_seq_ids):

        (flat [S,B,L], tok_mask [S,B,L], num_subs [B])."""
        sub_ids = sq.sub_seq_ids
        seq_ids = sq.seq_ids
        valid_tok = sub_ids >= 0
        sub_clip = jnp.where(valid_tok, sub_ids, 0)
        sub_len = jnp.zeros((G,), jnp.int32).at[sub_clip].add(
            valid_tok.astype(jnp.int32))
        big = jnp.asarray(C, jnp.int32)
        tok_pos = jnp.arange(C, dtype=jnp.int32)
        sub_start = jax.ops.segment_min(
            jnp.where(valid_tok, tok_pos, big), sub_clip, num_segments=G)
        seq_of_sub = jax.ops.segment_max(
            jnp.where(valid_tok, seq_ids, -1), sub_clip, num_segments=G)
        sub_valid = sub_len > 0
        num_subs = jnp.zeros((B,), jnp.int32).at[
            jnp.where(sub_valid, seq_of_sub, 0)
        ].add(sub_valid.astype(jnp.int32))
        first_sub = jax.ops.segment_min(
            jnp.where(sub_valid, jnp.arange(G, dtype=jnp.int32), G),
            jnp.where(sub_valid, seq_of_sub, 0), num_segments=B)
        first_sub = jnp.where(num_subs > 0, first_sub, 0)
        # gather map: (s, b, l) -> flat token index
        b_idx = jnp.arange(B, dtype=jnp.int32)[None, :, None]     # [1,B,1]
        s_idx = jnp.arange(S, dtype=jnp.int32)[:, None, None]     # [S,1,1]
        l_idx = jnp.arange(L, dtype=jnp.int32)[None, None, :]     # [1,1,L]
        g = jnp.clip(first_sub[b_idx] + s_idx, 0, G - 1)          # [S,B,1]
        flat = jnp.clip(sub_start[g] + l_idx, 0, C - 1)           # [S,B,L]
        tok_mask = (s_idx < num_subs[b_idx]) & (l_idx < sub_len[g])
        return flat, tok_mask, num_subs

    mem_inner = list(ctx.attr("mem_inner"))
    mem_update = list(ctx.attr("mem_update"))
    seq_inner = list(ctx.attr("seq_inner"))
    seq_inner_mask = list(ctx.attr("seq_inner_mask"))
    out_inner = list(ctx.attr("out_inner"))

    # derive each input's gather map from its OWN sub-layout and AND the
    # masks: a misaligned second input must not be sliced at the first
    # input's boundaries (the reference asserts identical layouts)
    raw_subs, tok_mask, num_subs = [], None, None
    for sq in seqs:
        if sq.capacity != C or sq.max_seqs != B:
            raise ValueError("nested step inputs must share one LoD layout")
        if sq.sub_seq_ids is None:
            raise ValueError(
                "nested_recurrent_group inputs must all be 2-level LoDArrays")
        flat_i, tm_i, ns_i = sub_layout(sq)
        raw_subs.append(sq.data[flat_i])  # [S, B, L, ...]
        tok_mask = tm_i if tok_mask is None else tok_mask & tm_i
        num_subs = ns_i if num_subs is None else jnp.minimum(num_subs, ns_i)
    subs = [
        jnp.where(tok_mask.reshape(tok_mask.shape + (1,) * (d.ndim - 3)), d, 0)
        for d in raw_subs
    ]
    step_mask = (
        jnp.arange(S, dtype=jnp.int32)[:, None] < num_subs[None, :]
    )  # [S, B]

    carries = _build_carries(ctx, boots, B)

    block = ctx.executor.program.blocks[ctx.attr("sub_block")]
    outer_env = dict(ctx.env)
    base_key = _group_rng(ctx, outer_env)

    def body(carry, step):
        step_subs, step_tok_mask, m, t = step
        env = dict(outer_env)
        env["@RNG@"] = jax.random.fold_in(base_key, t)
        env["@RNG_COUNTER@"] = 0
        for name, v in zip(seq_inner, step_subs):
            env[name] = v
        for name in seq_inner_mask:
            env[name] = step_tok_mask
        for name, c_ in zip(mem_inner, carry):
            env[name] = c_
        ctx.executor.run_ops(block.ops, env, dict(env), block)
        new_carry = tuple(
            jnp.where(m.reshape((B,) + (1,) * (env[u].ndim - 1)), env[u], c_)
            for u, c_ in zip(mem_update, carry))
        outs = tuple(env[o] for o in out_inner)
        return new_carry, outs

    final, outs = jax.lax.scan(
        body, tuple(carries),
        (tuple(subs), tok_mask, step_mask, jnp.arange(S, dtype=jnp.int32)),
    )

    # sequences with more than S subsequences are TRUNCATED (same semantics
    # as RecurrentGroup.max_len): the output claims only the steps that ran
    out_lens = jnp.minimum(num_subs, S)
    for i, o in enumerate(outs):
        like = _lod_from_lengths(
            out_lens, B * S, o, o.shape[2:], first.num_seqs
        )
        ctx.set_output("Out", LoDArray.from_batch(o, step_mask, like), i)
    for i, f in enumerate(final):
        if i < len(ctx.op.outputs.get("FinalMem", [])):
            ctx.set_output("FinalMem", f, i)
