"""Linear-chain CRF: log-likelihood + Viterbi decoding.

Reference: gserver/layers/LinearChainCRF.cpp (forward/backward/decode),
CRFLayer.cpp / CRFDecodingLayer.cpp, and Fluid's
operators/linear_chain_crf_op.cc + crf_decoding_op.cc.

Transition parameter layout follows the reference
(LinearChainCRF.cpp:23-32): shape [D+2, D] where row 0 is the start
weights a, row 1 the end weights b, rows 2.. the tag→tag transition
matrix w.

TPU design: the reference runs per-sequence dynamic loops on CPU; here
the ragged batch converts once to dense [T, B, D] + mask and BOTH the
forward (logsumexp) recursion and the Viterbi (max/argmax) recursion are
single `lax.scan`s over time, with per-sequence lengths handled by
freezing the carry past each end (same masking idiom as the RNN scans).
The gradient of the log-likelihood comes from jax.grad of the
logsumexp recursion — replacing LinearChainCRF::backward's hand-written
forward-backward expectations with autodiff of the forward pass, which
is mathematically identical.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op


def crf_nll(emission_l: LoDArray, label_l: LoDArray, transition, max_len=None):
    """Per-sequence negative log-likelihood [max_seqs]."""
    D = emission_l.data.shape[-1]
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    emit_tb, mask = emission_l.to_batch(max_len=max_len)  # [T, B, D], [T, B]
    lbl = label_l.data
    if lbl.ndim == 2 and lbl.shape[1] == 1:
        lbl = lbl[:, 0]
    lbl_tb, _ = label_l.with_data(lbl.astype(jnp.int32)).to_batch(max_len=max_len)
    lbl_tb = jnp.clip(lbl_tb, 0, D - 1)
    T, B, _ = emit_tb.shape
    lengths = emission_l.lengths  # [B]

    # ---- partition function: alpha recursion, carry frozen past seq end
    alpha0 = start_w[None, :] + emit_tb[0]  # [B, D]

    def fwd(alpha, inp):
        e_t, m_t = inp
        new = (
            jax.scipy.special.logsumexp(
                alpha[:, :, None] + trans[None], axis=1
            )
            + e_t
        )
        alpha = jnp.where(m_t[:, None], new, alpha)
        return alpha, None

    alpha_T, _ = jax.lax.scan(fwd, alpha0, (emit_tb[1:], mask[1:]))
    log_z = jax.scipy.special.logsumexp(alpha_T + end_w[None, :], axis=-1)

    # ---- gold path score
    emit_score = jnp.take_along_axis(emit_tb, lbl_tb[..., None], axis=-1)[..., 0]
    emit_sum = jnp.sum(jnp.where(mask, emit_score, 0.0), axis=0)  # [B]
    trans_score = trans[lbl_tb[:-1], lbl_tb[1:]]  # [T-1, B]
    trans_sum = jnp.sum(jnp.where(mask[1:], trans_score, 0.0), axis=0)
    first_lbl = lbl_tb[0]
    last_idx = jnp.clip(lengths - 1, 0, T - 1)
    last_lbl = jnp.take_along_axis(lbl_tb, last_idx[None, :], axis=0)[0]
    gold = emit_sum + trans_sum + start_w[first_lbl] + end_w[last_lbl]

    nll = log_z - gold
    valid = jnp.arange(B) < emission_l.num_seqs
    return jnp.where(valid, nll, 0.0)


def crf_viterbi(emission_l: LoDArray, transition, max_len=None):
    """Viterbi decode → dense tags [T, B] int32 + the batch mask."""
    start_w, end_w, trans = transition[0], transition[1], transition[2:]
    emit_tb, mask = emission_l.to_batch(max_len=max_len)
    T, B, D = emit_tb.shape

    alpha0 = start_w[None, :] + emit_tb[0]

    def fwd(alpha, inp):
        e_t, m_t = inp
        scores = alpha[:, :, None] + trans[None]  # [B, D_prev, D]
        best_prev = jnp.argmax(scores, axis=1).astype(jnp.int32)  # [B, D]
        new = jnp.max(scores, axis=1) + e_t
        alpha_next = jnp.where(m_t[:, None], new, alpha)
        # frozen steps use identity backpointers so backtracking through
        # padding preserves the final tag
        ident = jnp.broadcast_to(jnp.arange(D, dtype=jnp.int32)[None], (B, D))
        bp = jnp.where(m_t[:, None], best_prev, ident)
        return alpha_next, bp

    alpha_T, bps = jax.lax.scan(fwd, alpha0, (emit_tb[1:], mask[1:]))
    last_tag = jnp.argmax(alpha_T + end_w[None, :], axis=-1).astype(jnp.int32)

    def back(tag, bp):
        prev = jnp.take_along_axis(bp, tag[:, None], axis=1)[:, 0]
        return prev, tag

    first_tag, tags_rev = jax.lax.scan(back, last_tag, bps, reverse=True)
    tags = jnp.concatenate([first_tag[None], tags_rev])  # [T, B]
    return tags, mask


@register_op("linear_chain_crf")
def linear_chain_crf_kernel(ctx):
    """Outputs LogLikelihood [max_seqs, 1] = NEGATIVE log-likelihood per

    sequence (matching linear_chain_crf_op.cc, whose output is the nll
    that the book model feeds to mean())."""
    emission: LoDArray = ctx.input("Emission")
    label: LoDArray = ctx.input("Label")
    transition = ctx.input("Transition")
    nll = crf_nll(emission, label, transition, max_len=ctx.attr("max_len"))
    ctx.set_output("LogLikelihood", nll[:, None])


@register_op("crf_decoding")
def crf_decoding_kernel(ctx):
    """Viterbi path (reference: crf_decoding_op.cc). Without Label: the

    decoded tag per token (LoD aligned). With Label: 0/1 correctness per
    token (the reference's semantics for the eval path)."""
    emission: LoDArray = ctx.input("Emission")
    transition = ctx.input("Transition")
    tags, mask = crf_viterbi(emission, transition, max_len=ctx.attr("max_len"))
    tags_lod = LoDArray.from_batch(tags[..., None], mask, emission)
    tags_lod = tags_lod.with_data(tags_lod.data.astype(jnp.int32))
    if ctx.has_input("Label"):
        label: LoDArray = ctx.input("Label")
        lbl = label.data
        if lbl.ndim == 1:
            lbl = lbl[:, None]
        correct = (tags_lod.data == lbl.astype(jnp.int32)).astype(jnp.int32)
        correct = jnp.where(emission.token_mask[:, None], correct, 0)
        ctx.set_output("ViterbiPath", emission.with_data(correct))
    else:
        ctx.set_output("ViterbiPath", tags_lod)
