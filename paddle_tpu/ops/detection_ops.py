"""SSD detection op kernels: prior boxes, multibox loss, detection output.

Reference: paddle/gserver/layers/PriorBox.cpp (prior generation + clip),
MultiBoxLossLayer.cpp (bipartite-free per-prior matching, hard negative
mining with neg_pos_ratio, smooth-l1 loc loss + softmax conf loss),
DetectionOutputLayer.cpp + DetectionUtil.cpp (decode + per-class NMS),
and the detection config helpers in
python/paddle/trainer_config_helpers/layers.py.

TPU-static design: ground truth arrives as a padded dense [N, G, 4] box
tensor + [N, G] labels (label 0 = background = padding slot), instead of the
reference's ragged LoD input; NMS runs a fixed keep_top_k greedy loop under
lax.fori_loop with masks — everything static-shaped.

Boxes are corner-form (xmin, ymin, xmax, ymax), normalized to [0, 1].
Encoding is the SSD center-variance scheme (DetectionUtil.cpp encodeBBox).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.lod import LoDArray
from ..core.registry import register_op


def _data(x):
    return x.data if isinstance(x, LoDArray) else x


def _corner_to_center(b):
    w = b[..., 2] - b[..., 0]
    h = b[..., 3] - b[..., 1]
    cx = b[..., 0] + 0.5 * w
    cy = b[..., 1] + 0.5 * h
    return cx, cy, w, h


def iou_matrix(a, b):
    """Pairwise IoU: a [..., A, 4], b [..., B, 4] → [..., A, B]."""
    lt = jnp.maximum(a[..., :, None, :2], b[..., None, :, :2])
    rb = jnp.minimum(a[..., :, None, 2:], b[..., None, :, 2:])
    wh = jnp.maximum(rb - lt, 0.0)
    inter = wh[..., 0] * wh[..., 1]
    area_a = jnp.maximum(a[..., 2] - a[..., 0], 0.0) * jnp.maximum(
        a[..., 3] - a[..., 1], 0.0
    )
    area_b = jnp.maximum(b[..., 2] - b[..., 0], 0.0) * jnp.maximum(
        b[..., 3] - b[..., 1], 0.0
    )
    union = area_a[..., :, None] + area_b[..., None, :] - inter
    return inter / jnp.maximum(union, 1e-10)


def encode_boxes(gt, priors, variances):
    """SSD center-variance encoding (DetectionUtil.cpp encodeBBox)."""
    gcx, gcy, gw, gh = _corner_to_center(gt)
    pcx, pcy, pw, ph = _corner_to_center(priors)
    tx = (gcx - pcx) / (pw * variances[..., 0])
    ty = (gcy - pcy) / (ph * variances[..., 1])
    tw = jnp.log(jnp.maximum(gw / jnp.maximum(pw, 1e-10), 1e-10)) / variances[..., 2]
    th = jnp.log(jnp.maximum(gh / jnp.maximum(ph, 1e-10), 1e-10)) / variances[..., 3]
    return jnp.stack([tx, ty, tw, th], axis=-1)


def decode_boxes(loc, priors, variances):
    """Inverse of encode_boxes (DetectionUtil.cpp decodeBBox)."""
    pcx, pcy, pw, ph = _corner_to_center(priors)
    cx = pcx + loc[..., 0] * variances[..., 0] * pw
    cy = pcy + loc[..., 1] * variances[..., 1] * ph
    w = pw * jnp.exp(loc[..., 2] * variances[..., 2])
    h = ph * jnp.exp(loc[..., 3] * variances[..., 3])
    return jnp.stack(
        [cx - 0.5 * w, cy - 0.5 * h, cx + 0.5 * w, cy + 0.5 * h], axis=-1
    )


def make_prior_boxes(layer_h, layer_w, image_h, image_w, min_sizes, max_sizes,
                     aspect_ratios, variance, clip=True):
    """NumPy prior-box table — static per config, computed once at trace time
    (PriorBox.cpp:84-140 loop nest, including the 1/ar flip and the
    sqrt(min*max) square prior)."""
    if max_sizes:
        # reference PriorBox.cpp init: CHECK_EQ(minSize_.size(), maxSize_.size())
        if len(max_sizes) != len(min_sizes):
            raise ValueError(
                f"max_sizes ({len(max_sizes)}) must match min_sizes "
                f"({len(min_sizes)}) — PriorBox.cpp pairs them elementwise")
    ars = [1.0]
    for ar in aspect_ratios:
        if abs(ar - 1.0) < 1e-6:
            continue
        ars.extend([ar, 1.0 / ar])
    step_w = image_w / layer_w
    step_h = image_h / layer_h
    boxes = []
    for hh in range(layer_h):
        for ww in range(layer_w):
            cx = (ww + 0.5) * step_w
            cy = (hh + 0.5) * step_h
            for s, mn in enumerate(min_sizes):
                for ar in ars:
                    bw = mn * math.sqrt(ar)
                    bh = mn / math.sqrt(ar)
                    boxes.append([(cx - bw / 2) / image_w, (cy - bh / 2) / image_h,
                                  (cx + bw / 2) / image_w, (cy + bh / 2) / image_h])
                if max_sizes:
                    sz = math.sqrt(mn * max_sizes[s])
                    boxes.append([(cx - sz / 2) / image_w, (cy - sz / 2) / image_h,
                                  (cx + sz / 2) / image_w, (cy + sz / 2) / image_h])
    out = np.asarray(boxes, np.float32)
    if clip:
        out = np.clip(out, 0.0, 1.0)
    var = np.tile(np.asarray(variance, np.float32)[None, :], (out.shape[0], 1))
    return out, var


@register_op("prior_box")
def prior_box_kernel(ctx):
    x = _data(ctx.input("Input"))
    img = _data(ctx.input("Image"))
    boxes, var = make_prior_boxes(
        x.shape[2], x.shape[3], img.shape[2], img.shape[3],
        list(ctx.attr("min_sizes")), list(ctx.attr("max_sizes") or []),
        list(ctx.attr("aspect_ratios")), list(ctx.attr("variances")),
        ctx.attr("clip", True),
    )
    ctx.set_output("Boxes", jnp.asarray(boxes))
    ctx.set_output("Variances", jnp.asarray(var))


@register_op("multibox_loss")
def multibox_loss_kernel(ctx):
    """MultiBoxLossLayer.cpp semantics, padded-dense:
    Loc [N,K,4] or [N,K*4]; Conf [N,K,C]; Priors [K,4]; PriorVar [K,4];
    GtBox [N,G,4]; GtLabel [N,G] int (0 = background = padding).
    Per-prior match = argmax IoU over gts, positive if IoU>threshold; conf
    loss on positives + hardest negatives (neg_pos_ratio)."""
    loc = _data(ctx.input("Loc"))
    conf = _data(ctx.input("Conf"))
    priors = _data(ctx.input("Priors"))
    pvar = _data(ctx.input("PriorVar"))
    gt = _data(ctx.input("GtBox"))
    gtl = _data(ctx.input("GtLabel")).astype(jnp.int32)
    thresh = ctx.attr("overlap_threshold", 0.5)
    neg_ratio = ctx.attr("neg_pos_ratio", 3.0)
    n = gt.shape[0]
    k = priors.shape[0]
    loc = loc.reshape(n, k, 4)
    c = conf.shape[-1] if conf.ndim == 3 else conf.shape[1] // k
    conf = conf.reshape(n, k, c)

    gt_valid = (gtl > 0).astype(jnp.float32)  # [N, G]
    iou = iou_matrix(
        jnp.broadcast_to(priors[None], (n, k, 4)), gt
    ) * gt_valid[:, None, :]  # [N, K, G]
    best_gt = jnp.argmax(iou, axis=-1)  # [N, K]
    best_iou = jnp.max(iou, axis=-1)
    pos = (best_iou > thresh).astype(jnp.float32)  # [N, K]
    matched_box = jnp.take_along_axis(gt, best_gt[..., None], axis=1)
    matched_lbl = jnp.take_along_axis(gtl, best_gt, axis=1)  # [N, K]

    # localization loss (smooth l1 on positives)
    target = encode_boxes(matched_box, priors[None], pvar[None])
    d = loc - target
    a = jnp.abs(d)
    sl1 = jnp.where(a < 1.0, 0.5 * d * d, a - 0.5).sum(-1)
    loc_loss = (sl1 * pos).sum(-1)  # [N]

    # confidence loss: softmax CE; target = matched label for pos, 0 for neg
    tgt = jnp.where(pos > 0, matched_lbl, 0)
    logp = jax.nn.log_softmax(conf, axis=-1)
    ce = -jnp.take_along_axis(logp, tgt[..., None], axis=-1)[..., 0]  # [N, K]

    # hard negative mining: keep top (neg_ratio * num_pos) negatives by CE
    num_pos = pos.sum(-1)  # [N]
    num_neg = jnp.minimum(neg_ratio * num_pos, float(k))
    neg_ce = jnp.where(pos > 0, -jnp.inf, ce)
    order = jnp.argsort(-neg_ce, axis=-1)
    rank = jnp.argsort(order, axis=-1).astype(jnp.float32)  # rank of each prior
    neg_sel = (rank < num_neg[:, None]).astype(jnp.float32) * (1.0 - pos)
    conf_loss = (ce * (pos + neg_sel)).sum(-1)

    denom = jnp.maximum(num_pos, 1.0)
    ctx.set_output("Out", ((loc_loss + conf_loss) / denom)[:, None])


def _nms_loop(boxes, scores, keep_top_k, nms_threshold):
    """Greedy NMS as a fixed-iteration scan: boxes [M,4], scores [M] →
    (indices [keep_top_k], valid [keep_top_k])."""
    m = boxes.shape[0]

    def body(carry, _):
        alive_scores = carry
        i = jnp.argmax(alive_scores)
        best = alive_scores[i]
        ious = iou_matrix(boxes[i][None], boxes)[0]
        keep = alive_scores * jnp.where(ious > nms_threshold, 0.0, 1.0)
        keep = keep.at[i].set(0.0)
        return keep, (i, best > 0.0)

    _, (idx, valid) = jax.lax.scan(
        body, scores, None, length=min(keep_top_k, m)
    )
    return idx, valid


@register_op("detection_output")
def detection_output_kernel(ctx):
    """DetectionOutputLayer.cpp: decode + per-class NMS + keep_top_k.
    Output: dense [N, keep_top_k, 6] rows (label, score, x1, y1, x2, y2);
    empty slots have label -1 (the reference emits a ragged LoD result —
    padded-dense is the static TPU equivalent)."""
    loc = _data(ctx.input("Loc"))
    conf = _data(ctx.input("Conf"))
    priors = _data(ctx.input("Priors"))
    pvar = _data(ctx.input("PriorVar"))
    conf_thresh = ctx.attr("confidence_threshold", 0.01)
    nms_thresh = ctx.attr("nms_threshold", 0.45)
    nms_top_k = ctx.attr("nms_top_k", 400)
    keep_top_k = ctx.attr("keep_top_k", 200)
    background_id = ctx.attr("background_id", 0)

    n = conf.shape[0]
    k = priors.shape[0]
    loc = loc.reshape(n, k, 4)
    c = conf.shape[-1] if conf.ndim == 3 else conf.shape[1] // k
    conf = jax.nn.softmax(conf.reshape(n, k, c), axis=-1)
    decoded = decode_boxes(loc, priors[None], pvar[None])  # [N, K, 4]

    per_class = min(nms_top_k, k)

    def per_image(boxes, probs):
        rows = []
        for cls in range(c):
            if cls == background_id:
                continue
            s = jnp.where(probs[:, cls] > conf_thresh, probs[:, cls], 0.0)
            idx, valid = _nms_loop(boxes, s, per_class, nms_thresh)
            sel_boxes = boxes[idx]
            sel_scores = probs[idx, cls] * valid
            lab = jnp.where(valid, float(cls), -1.0)
            rows.append(
                jnp.concatenate(
                    [lab[:, None], sel_scores[:, None], sel_boxes], axis=-1
                )
            )
        allrows = jnp.concatenate(rows, axis=0)  # [(C-1)*per_class, 6]
        order = jnp.argsort(-allrows[:, 1])
        top = allrows[order[:keep_top_k]]
        pad = keep_top_k - top.shape[0]
        if pad > 0:
            top = jnp.pad(top, ((0, pad), (0, 0)), constant_values=-1.0)
        return jnp.where(top[:, 1:2] > 0, top, -jnp.ones_like(top))

    ctx.set_output("Out", jax.vmap(per_image)(decoded, conf))
