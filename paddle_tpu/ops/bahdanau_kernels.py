"""Fused Bahdanau attention decoder (Pallas) — the NMT hot path.

Reference philosophy: the reference's answer to a hot recurrent cell was a
hand-written fused kernel (cuda/include/hl_lstm.h:42, hl_gpu_gru.cuh);
its RecurrentGradientMachine ran the book's `simple_attention` decoder
(trainer_config_helpers/networks.py) frame by frame. Here the analogous
hot loop is the attention-GRU decoder scan: 51% of the NMT step
(benchmarks/nmt_breakdown.json), dominated by materializing
`tanh(enc_proj + dec_proj)` [B, S, A] to HBM every timestep — ~6.6 MB
written + read per step forward, and the default scan VJP additionally
saves that tensor per step (~330 MB of residuals) and accumulates a
[B, S, A] enc_proj gradient through the reverse-scan carry (~26 MB of
traffic per step).

TPU design — three small Pallas kernels around one custom-VJP scan:

  fwd (per step, grid over batch tiles): score+softmax+context entirely
      in VMEM — tanh(ep+dp)·v, masked softmax over S, alpha-weighted
      context — never materializing [B, S, A]. Emits ctx and alpha
      (alpha is [B, S]: tiny; it is the only per-step residual beyond
      the h/ctx vectors).
  bwd step (per reverse step): recomputes the tanh tile-locally and
      produces d(dec_proj) and d(scores) — the two step-local gradients
      the sequential dh chain needs. d(enc_proj) is NOT accumulated here.
  bwd phase-2 (once, grid (batch tiles, T)): re-walks all steps with a
      VMEM accumulator to produce d(enc_proj), folding the dv reduction
      in — the [B, S, A]-sized gradient is written exactly once.

The GRU cell's backward is hand-derived batched XLA (gates recomputed
from the saved h/ctx sequences in batched MXU matmuls — same recipe as
the fused GRU kernel, pallas_kernels.py); only the dh carry is
sequential. enc_proj enters as a differentiated INPUT, so the enc-side
projection (enc @ WaEnc) and its gradients stay in ordinary XLA outside
the boundary.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .pallas_kernels import _VMEM_BUDGET

# trace-time dispatch counters: which formulation each (re)trace of the
# fused decoder actually engaged. Tests reset this and assert the bench
# geometry takes the fused path — a silent fallback to the scan (e.g. a
# config off the eligibility grid) must fail loudly, not just run slow.
dispatch_stats = {"fused_calls": 0, "seq_fwd": 0, "scan_fwd": 0,
                  "seq_bwd": 0, "scan_bwd": 0}


def reset_dispatch_stats():
    for k in dispatch_stats:
        dispatch_stats[k] = 0
    _decoder_fn.cache_clear()  # custom-VJP fns re-trace → counters fire


def _bblk(B: int, Sp: int, A: int, C: int, itemsize: int) -> int:
    """Batch tile shared by ALL the attention kernels (fwd, bwd-step,
    phase-2 use one eligibility so a config never runs fused forward and
    then fails to tile the backward). Legality (divisibility + the
    family-wide VMEM working-set model) lives in tune/space.py
    `bahdanau_blk_legal` — ONE model shared with the autotuner's
    candidate generator, so the tuner can never emit a tile this
    dispatch would reject.

    Consult order (tune/overrides.py): forced override (programmatic
    force(), or the legacy PT_ATTN_BBLK env knob — still honored) ->
    tuned table entry for this (shape, dtype, device) -> the analytic
    default below. A FORCED tile that fails legality warns and disables
    the fused path (the operator pinned it for a sweep; silently
    substituting would invalidate the sweep); a stale TABLE entry that
    fails legality is ignored and the analytic default applies.

    Analytic default: 8 measured best on v5e at the NMT shapes (256k
    tok/s vs 217k at 16/32, bs256 sweep — larger tiles triple the f32
    temporaries and spill); 4 and 2 are fallback candidates for SMALL
    batches only (a sub-8 tile is a legal Mosaic block shape only when
    it spans the whole batch dim — the last-two-dims (8k, 128k)-or-full
    rule; B=4 and B=2 verified lowering and matching on v5e hardware,
    round 5)."""
    from ..tune import overrides as tune_overrides
    from ..tune.cache import ITEMSIZE_DTYPE
    from ..tune.space import bahdanau_blk_legal

    if B <= 0:  # mesh-local batch that the dp axis does not divide
        return 0
    ov = tune_overrides.lookup(
        "bahdanau_attention", {"B": B, "Sp": Sp, "A": A, "C": C},
        ITEMSIZE_DTYPE.get(itemsize, f"itemsize{itemsize}"))
    if ov is not None:
        b = int(ov.config.get("bblk", 0))
        if b and bahdanau_blk_legal(b, B, Sp, A, C, itemsize):
            return b
        if ov.source in ("forced", "env"):
            import warnings

            warnings.warn(
                f"forced attention tile bblk={b} ({ov.source}) fails "
                f"eligibility at B={B} Sp={Sp} A={A} C={C} "
                f"(divisibility or VMEM); fused attention decoder "
                f"DISABLED for this shape", stacklevel=2)
            return 0
        # stale table entry (tuned on other geometry/version): ignore
    for b in (8, 4, 2):
        if bahdanau_blk_legal(b, B, Sp, A, C, itemsize):
            return b
    return 0


def _interpret() -> bool:
    return jax.default_backend() != "tpu"


def _backend_ok() -> bool:
    from .pallas_kernels import backend_ok

    return backend_ok("fused_attention_interpret")


def _pad_s(s: int) -> int:
    from ..tune.space import pad_s  # one padding rule, shared with tuner

    return pad_s(s)


def _tmask_bt(tmask_tb):
    """[T, B] f32 target mask → [B, Tp] (T padded to a sublane multiple)
    for the whole-sequence kernels: the per-step mask column is selected
    in-kernel with an iota-match reduce over the resident [blk, Tp]
    tile. A (1, blk) block of the [T, B] layout is an illegal Mosaic
    tile (last-two block dims must be (8k, 128k) or span the array) —
    found the day the whole-sequence kernels first met the real TPU
    lowering; interpret mode does not check tiling."""
    T, B = tmask_tb.shape
    tp = ((T + 7) // 8) * 8
    return jnp.pad(tmask_tb.astype(jnp.float32).T, [(0, 0), (0, tp - T)])


def _tmask_col(tmask_ref, t):
    """Select mask column t from the resident [blk, Tp] tile → [blk, 1]
    (iota-match reduce: lane-dim dynamic slices are the one indexing
    mode Mosaic restricts; a masked sum is layout-native)."""
    blk, tp = tmask_ref.shape
    sel = jax.lax.broadcasted_iota(jnp.int32, (blk, tp), 1) == t
    return jnp.sum(jnp.where(sel, tmask_ref[:], 0.0), axis=1,
                   keepdims=True)


def fused_decoder_eligible(B: int, S: int, A: int, C: int, dtype) -> bool:
    from ..flags import FLAGS

    if not FLAGS.use_fused_attention or not _backend_ok():
        return False
    sp = _pad_s(S)
    item = jnp.dtype(dtype).itemsize
    return (
        dtype in (jnp.bfloat16, jnp.float32)
        and A % 128 == 0
        and C % 128 == 0
        and _bblk(B, sp, A, C, item) > 0
    )


# ---------------------------------------------------------------- kernels --
def _attn_fwd_kernel(ep_ref, enc_ref, dp_ref, v_ref, mask_ref,
                     ctx_ref, alpha_ref):
    ep = ep_ref[:].astype(jnp.float32)          # [b, Sp, A]
    dp = dp_ref[:].astype(jnp.float32)          # [b, A]
    t = jnp.tanh(ep + dp[:, None, :])
    scores = jnp.sum(t * v_ref[0].astype(jnp.float32)[None, None, :], -1)
    scores = jnp.where(mask_ref[:] > 0, scores, -1e9)   # [b, Sp]
    m = jnp.max(scores, -1, keepdims=True)
    e = jnp.exp(scores - m)
    alpha = e / jnp.sum(e, -1, keepdims=True)
    alpha_ref[:] = alpha
    enc = enc_ref[:]                             # [b, Sp, C]
    ctx = jax.lax.dot_general(
        alpha[:, None, :].astype(enc.dtype), enc,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                            # [b, 1, C]
    ctx_ref[:] = ctx[:, 0, :].astype(ctx_ref.dtype)


def _attn_bwd_kernel(ep_ref, enc_ref, dp_ref, v_ref, mask_ref,
                     dctx_ref, alpha_ref, ddp_ref, dsc_ref):
    enc = enc_ref[:]                             # [b, Sp, C]
    dctx = dctx_ref[:]                           # [b, C]
    # dalpha[b,s] = sum_c dctx[b,c] * enc[b,s,c]
    dalpha = jax.lax.dot_general(
        dctx[:, None, :], enc, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )[:, 0, :]                                   # [b, Sp]
    alpha = alpha_ref[:]                         # [b, Sp] f32
    tot = jnp.sum(alpha * dalpha, -1, keepdims=True)
    dsc = alpha * (dalpha - tot)
    dsc = jnp.where(mask_ref[:] > 0, dsc, 0.0)
    dsc_ref[:] = dsc
    ep = ep_ref[:].astype(jnp.float32)
    dp = dp_ref[:].astype(jnp.float32)
    t = jnp.tanh(ep + dp[:, None, :])
    omt2 = (1.0 - t * t)                         # [b, Sp, A]
    # ddp[b,a] = sum_s dsc[b,s] * (1-t^2)[b,s,a] * v[a]
    ddp = jax.lax.dot_general(
        dsc[:, None, :].astype(omt2.dtype), omt2,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )[:, 0, :] * v_ref[0].astype(jnp.float32)[None, :]
    ddp_ref[:] = ddp.astype(ddp_ref.dtype)


def _attn_phase2_kernel(ep_ref, dp_ref, dsc_ref, v_ref,
                        dep_ref, dv_ref, dep_acc, dv_acc):
    b = pl.program_id(0)
    t = pl.program_id(1)

    @pl.when(jnp.logical_and(b == 0, t == 0))
    def _():
        dv_acc[:] = jnp.zeros_like(dv_acc)

    @pl.when(t == 0)
    def _():
        dep_acc[:] = jnp.zeros_like(dep_acc)

    ep = ep_ref[:].astype(jnp.float32)           # [b, Sp, A]
    dp = dp_ref[:].astype(jnp.float32)           # [1, b, A]
    th = jnp.tanh(ep + dp[0][:, None, :])
    dsc = dsc_ref[:][0]                          # [b, Sp] f32
    dep_t = dsc[:, :, None] * (1.0 - th * th) \
        * v_ref[0].astype(jnp.float32)[None, None, :]

    # accumulate in the f32 scratch (an io-dtype read-modify-write over
    # ~T steps loses low-order gradient bits under bf16 AMP); cast to
    # the io dtype exactly once on the final t
    dep_acc[:] = dep_acc[:] + dep_t
    # dv[a] += sum_{b,s} tanh[b,s,a] * dsc[b,s]
    dv_acc[:] = dv_acc[:] + jnp.sum(
        th * dsc[:, :, None], axis=(0, 1), keepdims=False
    )[None, :]

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dep_ref[:] = dep_acc[:].astype(dep_ref.dtype)

    @pl.when(jnp.logical_and(b == pl.num_programs(0) - 1,
                             t == pl.num_programs(1) - 1))
    def _():
        dv_ref[:] = dv_acc[:]


# ------------------------------------------------------------ kernel calls --
def _attn_fwd(ep, enc, dp, v, maskf, interpret):
    B, Sp, A = ep.shape
    C = enc.shape[-1]
    blk = _bblk(B, Sp, A, C, ep.dtype.itemsize)
    nb = B // blk
    ctx, alpha = pl.pallas_call(
        _attn_fwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((blk, Sp, A), lambda b: (b, 0, 0)),
            pl.BlockSpec((blk, Sp, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((blk, A), lambda b: (b, 0)),
            pl.BlockSpec((1, A), lambda b: (0, 0)),
            pl.BlockSpec((blk, Sp), lambda b: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((blk, C), lambda b: (b, 0)),
            pl.BlockSpec((blk, Sp), lambda b: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, C), enc.dtype),
            jax.ShapeDtypeStruct((B, Sp), jnp.float32),
        ],
        interpret=interpret,
    )(ep, enc, dp, v.reshape(1, -1), maskf)
    return ctx, alpha


def _attn_bwd_step(ep, enc, dp, v, maskf, dctx, alpha, interpret):
    B, Sp, A = ep.shape
    C = enc.shape[-1]
    blk = _bblk(B, Sp, A, C, ep.dtype.itemsize)
    nb = B // blk
    ddp, dsc = pl.pallas_call(
        _attn_bwd_kernel,
        grid=(nb,),
        in_specs=[
            pl.BlockSpec((blk, Sp, A), lambda b: (b, 0, 0)),
            pl.BlockSpec((blk, Sp, C), lambda b: (b, 0, 0)),
            pl.BlockSpec((blk, A), lambda b: (b, 0)),
            pl.BlockSpec((1, A), lambda b: (0, 0)),
            pl.BlockSpec((blk, Sp), lambda b: (b, 0)),
            pl.BlockSpec((blk, C), lambda b: (b, 0)),
            pl.BlockSpec((blk, Sp), lambda b: (b, 0)),
        ],
        out_specs=[
            pl.BlockSpec((blk, A), lambda b: (b, 0)),
            pl.BlockSpec((blk, Sp), lambda b: (b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, A), ep.dtype),
            jax.ShapeDtypeStruct((B, Sp), jnp.float32),
        ],
        interpret=interpret,
    )(ep, enc, dp, v.reshape(1, -1), maskf, dctx, alpha)
    return ddp, dsc


def _attn_phase2(ep, dp_seq, dsc_seq, v, C, interpret):
    B, Sp, A = ep.shape
    T = dp_seq.shape[0]
    # same blk as the fwd/bwd kernels (the shared _bblk cost model
    # covers phase-2's accumulator, so this cannot return 0 here)
    blk = _bblk(B, Sp, A, C, ep.dtype.itemsize)
    nb = B // blk
    dep, dv = pl.pallas_call(
        _attn_phase2_kernel,
        grid=(nb, T),
        in_specs=[
            pl.BlockSpec((blk, Sp, A), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, blk, A), lambda b, t: (t, b, 0)),
            pl.BlockSpec((1, blk, Sp), lambda b, t: (t, b, 0)),
            pl.BlockSpec((1, A), lambda b, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((blk, Sp, A), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, A), lambda b, t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((B, Sp, A), ep.dtype),
            jax.ShapeDtypeStruct((1, A), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((blk, Sp, A), jnp.float32),
                        pltpu.VMEM((1, A), jnp.float32)],
        interpret=interpret,
    )(ep, dp_seq, dsc_seq, v.reshape(1, -1))
    return dep, dv[0]


# ------------------------------------------- whole-sequence forward kernel --
def _decoder_seq_kernel(ep_ref, enc_ref, mask_ref, xpx_ref, tmask_ref,
                        h0_ref, wadec_ref, v_ref, wxc_ref, wur_ref, wc_ref,
                        h_ref, alpha_ref, ctx_ref, h_s):
    """One grid step = (timestep t, batch tile b): Bahdanau attention +
    GRU cell entirely in VMEM, hidden state carried in scratch across t
    (the fused-LSTM whole-sequence pattern, pallas_kernels.py, extended
    with the attention prologue). xpx is the hoisted input half of the
    gate projection (trg @ wx[:E] + bias — no sequential dependency)."""
    t = pl.program_id(0)
    b = pl.program_id(1)
    H = h0_ref.shape[-1]
    blk = h0_ref.shape[0]
    rows = pl.ds(b * blk, blk)  # this tile's rows of the [B, H] scratch

    @pl.when(t == 0)
    def _():
        h_s[rows, :] = h0_ref[:]

    h = h_s[rows, :]                              # [blk, H]
    dp = jnp.dot(h, wadec_ref[:],
                 preferred_element_type=jnp.float32)      # [blk, A]
    th = jnp.tanh(ep_ref[:].astype(jnp.float32) + dp[:, None, :])
    scores = jnp.sum(th * v_ref[0].astype(jnp.float32)[None, None, :], -1)
    scores = jnp.where(mask_ref[:] > 0, scores, -1e9)
    m = jnp.max(scores, -1, keepdims=True)
    e = jnp.exp(scores - m)
    alpha = e / jnp.sum(e, -1, keepdims=True)
    alpha_ref[:] = alpha[None]
    enc = enc_ref[:]
    ctx = jax.lax.dot_general(
        alpha[:, None, :].astype(enc.dtype), enc,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )[:, 0, :]                                     # [blk, C] f32
    ctx_c = ctx.astype(enc.dtype)
    ctx_ref[:] = ctx_c[None]
    # gate pre-activations: hoisted x-half + ctx-half
    xp = xpx_ref[:][0] + jnp.dot(ctx_c, wxc_ref[:]).astype(h.dtype)
    ur = jax.nn.sigmoid(
        xp[..., : 2 * H]
        + jnp.dot(h, wur_ref[:]).astype(h.dtype))
    u, r = ur[..., :H], ur[..., H:]
    c = jnp.tanh(
        xp[..., 2 * H:]
        + jnp.dot(r * h, wc_ref[:]).astype(h.dtype))
    h_new = (1 - u) * h + u * c
    tm = _tmask_col(tmask_ref, t).astype(h.dtype)  # [blk, 1]
    h_out = tm * h_new + (1 - tm) * h
    h_s[rows, :] = h_out
    h_ref[:] = h_out[None]


def _decoder_seq_fwd(ep, enc, maskf, xpx, tmask, h0, wa_dec, v, wx_c,
                     w_ur, w_c, interpret):
    B, Sp, A = ep.shape
    C = enc.shape[-1]
    T = xpx.shape[0]
    H = h0.shape[-1]
    G3 = xpx.shape[-1]
    blk = _bblk(B, Sp, A, C, ep.dtype.itemsize)
    nb = B // blk
    tmask_bt = _tmask_bt(tmask)
    tp = tmask_bt.shape[1]
    h_seq, alpha_seq, ctx_seq = pl.pallas_call(
        _decoder_seq_kernel,
        grid=(T, nb),
        in_specs=[
            pl.BlockSpec((blk, Sp, A), lambda t, b: (b, 0, 0)),
            pl.BlockSpec((blk, Sp, C), lambda t, b: (b, 0, 0)),
            pl.BlockSpec((blk, Sp), lambda t, b: (b, 0)),
            pl.BlockSpec((1, blk, G3), lambda t, b: (t, b, 0)),
            pl.BlockSpec((blk, tp), lambda t, b: (b, 0)),
            pl.BlockSpec((blk, H), lambda t, b: (b, 0)),
            pl.BlockSpec((H, A), lambda t, b: (0, 0)),
            pl.BlockSpec((1, A), lambda t, b: (0, 0)),
            pl.BlockSpec((C, G3), lambda t, b: (0, 0)),
            pl.BlockSpec((H, 2 * H), lambda t, b: (0, 0)),
            pl.BlockSpec((H, H), lambda t, b: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, H), lambda t, b: (t, b, 0)),
            pl.BlockSpec((1, blk, Sp), lambda t, b: (t, b, 0)),
            pl.BlockSpec((1, blk, C), lambda t, b: (t, b, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, H), h0.dtype),
            jax.ShapeDtypeStruct((T, B, Sp), jnp.float32),
            jax.ShapeDtypeStruct((T, B, C), enc.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((B, H), h0.dtype)],
        interpret=interpret,
    )(ep, enc, maskf, xpx, tmask_bt, h0, wa_dec, v.reshape(1, -1), wx_c,
      w_ur, w_c)
    return h_seq, alpha_seq, ctx_seq


def _mega_vmem_ok(B, Sp, A, C, E, H, itemsize) -> bool:
    """Whole-sequence forward kernel working set: resident weights +
    streamed ep/enc tiles + f32 tanh temporaries + the full-batch [B, H]
    hidden-state scratch + the double-buffered per-step output blocks
    (h/alpha/ctx)."""
    blk = _bblk(B, Sp, A, C, itemsize)
    if blk == 0:
        return False
    weights = (H * A + C * 3 * H + H * 3 * H + A) * itemsize
    streams = 2 * blk * (Sp * (A + C) + 3 * H + E) * itemsize
    temps = 3 * blk * Sp * A * 4
    h_scratch = B * H * itemsize
    outs = 2 * blk * (H * itemsize + Sp * 4 + C * itemsize)
    return weights + streams + temps + h_scratch + outs <= _VMEM_BUDGET


# ------------------------------------------- whole-sequence backward kernel --
def _decoder_seq_bwd_kernel(ep_ref, enc_ref, maskf_ref, g_ref, tmask_ref,
                            hp_ref, u_ref, r_ref, c_ref, dp_ref, alpha_ref,
                            v_ref, wc_ref, wur_ref, wxc_ref, wadec_ref,
                            dxp_ref, dctx_ref, ddp_ref, dh0_ref, dep_ref,
                            dv_ref, dh_s, dep_s, dv_s):
    """One grid step = (batch tile b, reverse timestep): the ENTIRE
    decoder backward step — GRU cell backward, attention backward, and
    the d(enc_proj)/d(v) accumulation (the separate phase-2 kernel folded
    in) — with the sequential dh carry held in f32 VMEM scratch. The grid
    walks t forward; every [T, ...] BlockSpec indexes timestep T-1-t, so
    each batch tile sees its steps newest-first while its ep/enc tiles
    stay resident across the whole T walk. Replaces T per-step kernel
    dispatches + T reverse-scan XLA step bodies + the phase-2 dispatch
    with ONE kernel (the bwd analogue of _decoder_seq_kernel; the
    fused-kernel philosophy of the reference's hl_lstm.h:42 backward)."""
    b = pl.program_id(0)
    t = pl.program_id(1)

    @pl.when(t == 0)
    def _():
        dh_s[:] = jnp.zeros_like(dh_s)
        dep_s[:] = jnp.zeros_like(dep_s)

    @pl.when(jnp.logical_and(b == 0, t == 0))
    def _():
        dv_s[:] = jnp.zeros_like(dv_s)

    io_dt = hp_ref.dtype
    # f32 io: force true-f32 MXU passes so the kernel is at least as
    # accurate as the scan path (verified vs f64 ground truth); bf16
    # io: default precision — Mosaic rejects fp32-precision contractions
    # on bf16 operands, and accumulation is f32 regardless
    prec = (jax.lax.Precision.HIGHEST if io_dt == jnp.float32 else None)
    hp = hp_ref[:][0].astype(jnp.float32)        # [blk, H]
    u = u_ref[:][0].astype(jnp.float32)
    r = r_ref[:][0].astype(jnp.float32)
    c = c_ref[:][0].astype(jnp.float32)
    g = g_ref[:][0].astype(jnp.float32)
    tt = pl.num_programs(1) - 1 - t              # the timestep this
    m = _tmask_col(tmask_ref, tt)                # grid step walks
    dh = dh_s[:] + g
    dh_cell = dh * m
    dh_prev = dh * (1.0 - m)
    # GRU cell backward (h = (1-u) hp + u c)
    du = dh_cell * (c - hp)
    dc = dh_cell * u
    dh_prev = dh_prev + dh_cell * (1.0 - u)
    dpre_c = dc * (1.0 - c * c)                  # [blk, H]
    drh = jax.lax.dot_general(                   # dpre_c @ w_c.T
        dpre_c.astype(io_dt), wc_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec)
    dr = drh * hp
    dh_prev = dh_prev + drh * r
    dpre_u = du * u * (1.0 - u)
    dpre_r = dr * r * (1.0 - r)
    dur = jnp.concatenate([dpre_u, dpre_r], -1)  # [blk, 2H]
    dh_prev = dh_prev + jax.lax.dot_general(     # dur @ w_ur.T
        dur.astype(io_dt), wur_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec)
    dxp = jnp.concatenate([dur, dpre_c], -1)     # [blk, 3H]
    dxp_ref[:] = dxp.astype(dxp_ref.dtype)[None]
    dctx = jax.lax.dot_general(                  # dxp @ wx_ctx.T
        dxp.astype(io_dt), wxc_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec)      # [blk, C]
    dctx_ref[:] = dctx.astype(dctx_ref.dtype)[None]
    # attention backward + fused dep/dv accumulation
    enc = enc_ref[:]
    dalpha = jax.lax.dot_general(
        dctx[:, None, :].astype(enc.dtype), enc,
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=prec)[:, 0, :]     # [blk, Sp]
    alpha = alpha_ref[:][0]                              # [blk, Sp] f32
    tot = jnp.sum(alpha * dalpha, -1, keepdims=True)
    dsc = alpha * (dalpha - tot)
    dsc = jnp.where(maskf_ref[:] > 0, dsc, 0.0)
    th = jnp.tanh(ep_ref[:].astype(jnp.float32)
                  + dp_ref[:][0].astype(jnp.float32)[:, None, :])
    omt2 = 1.0 - th * th                                 # [blk, Sp, A]
    v = v_ref[0].astype(jnp.float32)
    ddp = jax.lax.dot_general(
        dsc[:, None, :].astype(omt2.dtype), omt2,
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
        precision=prec)[:, 0, :] * v[None, :]
    ddp_ref[:] = ddp.astype(ddp_ref.dtype)[None]
    dh_prev = dh_prev + jax.lax.dot_general(     # ddp @ wa_dec.T
        ddp.astype(io_dt), wadec_ref[:], (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
        precision=prec)
    dep_s[:] = dep_s[:] + dsc[:, :, None] * omt2 * v[None, None, :]
    dv_s[:] = dv_s[:] + jnp.sum(th * dsc[:, :, None], axis=(0, 1))[None, :]
    dh_s[:] = dh_prev

    @pl.when(t == pl.num_programs(1) - 1)
    def _():
        dh0_ref[:] = dh_prev.astype(dh0_ref.dtype)
        dep_ref[:] = dep_s[:].astype(dep_ref.dtype)

    @pl.when(jnp.logical_and(b == pl.num_programs(0) - 1,
                             t == pl.num_programs(1) - 1))
    def _():
        dv_ref[:] = dv_s[:]


def _decoder_seq_bwd(ep, enc, maskf, g_seq, tmask, hp_seq, u_seq, r_seq,
                     c_seq, dp_seq, alpha_seq, v, w_c, w_ur, wx_c, wa_dec,
                     h0_dtype, interpret):
    B, Sp, A = ep.shape
    C = enc.shape[-1]
    T, _, H = hp_seq.shape
    dt = hp_seq.dtype
    blk = _bblk(B, Sp, A, C, ep.dtype.itemsize)
    nb = B // blk
    tmask_bt = _tmask_bt(tmask)
    tp = tmask_bt.shape[1]
    return pl.pallas_call(
        _decoder_seq_bwd_kernel,
        grid=(nb, T),
        in_specs=[
            pl.BlockSpec((blk, Sp, A), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((blk, Sp, C), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((blk, Sp), lambda b, t: (b, 0)),
            pl.BlockSpec((1, blk, H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((blk, tp), lambda b, t: (b, 0)),
            pl.BlockSpec((1, blk, H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, A), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, Sp), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, A), lambda b, t: (0, 0)),
            pl.BlockSpec((H, H), lambda b, t: (0, 0)),
            pl.BlockSpec((H, 2 * H), lambda b, t: (0, 0)),
            pl.BlockSpec((C, 3 * H), lambda b, t: (0, 0)),
            pl.BlockSpec((H, A), lambda b, t: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, blk, 3 * H), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, C), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((1, blk, A), lambda b, t: (T - 1 - t, b, 0)),
            pl.BlockSpec((blk, H), lambda b, t: (b, 0)),
            pl.BlockSpec((blk, Sp, A), lambda b, t: (b, 0, 0)),
            pl.BlockSpec((1, A), lambda b, t: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((T, B, 3 * H), dt),
            jax.ShapeDtypeStruct((T, B, C), dt),
            jax.ShapeDtypeStruct((T, B, A), dt),
            jax.ShapeDtypeStruct((B, H), h0_dtype),
            jax.ShapeDtypeStruct((B, Sp, A), ep.dtype),
            jax.ShapeDtypeStruct((1, A), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((blk, H), jnp.float32),
            pltpu.VMEM((blk, Sp, A), jnp.float32),
            pltpu.VMEM((1, A), jnp.float32),
        ],
        interpret=interpret,
    )(ep, enc, maskf, g_seq, tmask_bt, hp_seq, u_seq, r_seq, c_seq,
      dp_seq, alpha_seq, v.reshape(1, -1), w_c, w_ur, wx_c, wa_dec)


def _mega_bwd_vmem_ok(B, Sp, A, C, H, T, itemsize) -> bool:
    """Whole-sequence backward kernel working set: resident ep/enc tiles
    + resident weights + f32 scratch (dh, dep accumulator, dv) + f32
    tanh/omt2/dep-term temporaries + double-buffered per-step streams
    and output blocks + the resident [blk, Tp] f32 tmask tile + the
    once-written dep/dh0 output blocks."""
    blk = _bblk(B, Sp, A, C, itemsize)
    if blk == 0:
        return False
    tiles = blk * Sp * (A + C) * itemsize
    weights = (H * H + 2 * H * H + C * 3 * H + H * A + A) * itemsize
    scratch = (blk * H + blk * Sp * A + A) * 4
    temps = 3 * blk * Sp * A * 4
    # alpha streams at f32 regardless of io dtype
    streams = 2 * blk * ((5 * H + A + 1) * itemsize + Sp * 4)
    # the [blk, Tp] tmask tile stays resident across the whole T walk
    # (f32, T padded to a sublane multiple); small at bench T but a
    # long-T config must not pass the model and then fail Mosaic's
    # VMEM allocation at compile time
    tmask = blk * (((T + 7) // 8) * 8) * 4
    outs = 2 * blk * (3 * H + C + A) * itemsize \
        + blk * Sp * A * itemsize + blk * H * itemsize + A * 4
    return tiles + weights + scratch + temps + streams + tmask + outs \
        <= _VMEM_BUDGET


# -------------------------------------------------- the decoder, custom VJP --
def _gru_fwd_step(xp, h_prev, wh, H):
    w_ur, w_c = wh[:, : 2 * H], wh[:, 2 * H:]
    ur = jax.nn.sigmoid(
        xp[..., : 2 * H]
        + jnp.dot(h_prev, w_ur).astype(xp.dtype))
    u, r = ur[..., :H], ur[..., H:]
    c = jnp.tanh(
        xp[..., 2 * H:]
        + jnp.dot(r * h_prev, w_c).astype(xp.dtype))
    return (1 - u) * h_prev + u * c


@functools.lru_cache(maxsize=None)
def _decoder_fn(interpret: bool, axis=None):
    """custom-VJP'd teacher-forcing decoder over padded-S operands.

    (enc, ep, maskf [B,Sp], trg [T,B,E], tmask [T,B], h0,
     wa_dec [H,A], v [A], wx [(E+C),3H], wh [H,3H], bias [3H]) -> h_seq.

    `axis` names the dp shard_map axis when the call runs under a mesh
    (mesh_dispatch policy): operands are then per-shard, and the weight
    cotangents — per-shard partial sums over the local batch — are
    psum'd in the backward (check_vma is off, so no automatic psum).
    """

    def forward(enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias):
        from ..flags import FLAGS

        H = h0.shape[-1]
        E = trg.shape[-1]
        B, Sp, A = ep.shape
        if FLAGS.fused_attention_seq_fwd and _mega_vmem_ok(
                B, Sp, A, enc.shape[-1], E, H, ep.dtype.itemsize):
            # whole-sequence kernel: every per-step dispatch collapses
            # into one pallas_call; the x-half of the gate projection
            # has no sequential dependency and hoists to one batched
            # matmul
            dispatch_stats["seq_fwd"] += 1
            xpx = (jnp.dot(trg, wx[:E]).astype(trg.dtype) + bias)
            return _decoder_seq_fwd(
                ep, enc, maskf, xpx, tmask.astype(jnp.float32), h0,
                wa_dec, v, wx[E:], wh[:, : 2 * H], wh[:, 2 * H:],
                interpret)

        dispatch_stats["scan_fwd"] += 1

        def step(h_prev, inp):
            x_t, m_t = inp
            dp = jnp.dot(h_prev, wa_dec).astype(h_prev.dtype)
            ctx, alpha = _attn_fwd(ep, enc, dp, v, maskf, interpret)
            xin = jnp.concatenate([x_t, ctx.astype(x_t.dtype)], -1)
            xp = jnp.dot(xin, wx).astype(x_t.dtype) + bias
            h = _gru_fwd_step(xp, h_prev, wh, H)
            m = m_t[:, None].astype(h.dtype)
            h = m * h + (1 - m) * h_prev
            return h, (h, alpha, ctx)

        _, (h_seq, alpha_seq, ctx_seq) = jax.lax.scan(
            step, h0, (trg, tmask))
        return h_seq, alpha_seq, ctx_seq

    @jax.custom_vjp
    def f(enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias):
        h_seq, _, _ = forward(enc, ep, maskf, trg, tmask, h0, wa_dec, v,
                              wx, wh, bias)
        return h_seq

    def fwd(enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias):
        h_seq, alpha_seq, ctx_seq = forward(
            enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias)
        res = (enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias,
               h_seq, alpha_seq, ctx_seq)
        return h_seq, res

    def bwd(res, g_seq):
        from ..flags import FLAGS

        (enc, ep, maskf, trg, tmask, h0, wa_dec, v, wx, wh, bias,
         h_seq, alpha_seq, ctx_seq) = res
        T, B, H = h_seq.shape
        E = trg.shape[-1]
        dt = h_seq.dtype
        g_seq = g_seq.astype(dt)
        # ---- batched recompute of every gate (MXU, no sequential dep) --
        hp_seq = jnp.concatenate([h0[None], h_seq[:-1]], 0)   # h_{t-1}
        dp_seq = jnp.dot(hp_seq, wa_dec).astype(dt)           # [T,B,A]
        xin_seq = jnp.concatenate([trg, ctx_seq.astype(dt)], -1)
        xp_seq = jnp.dot(xin_seq, wx).astype(dt) + bias
        w_ur, w_c = wh[:, : 2 * H], wh[:, 2 * H:]
        ur_seq = jax.nn.sigmoid(
            xp_seq[..., : 2 * H] + jnp.dot(hp_seq, w_ur).astype(dt))
        u_seq, r_seq = ur_seq[..., :H], ur_seq[..., H:]
        rh_seq = r_seq * hp_seq
        c_seq = jnp.tanh(
            xp_seq[..., 2 * H:] + jnp.dot(rh_seq, w_c).astype(dt))

        if FLAGS.fused_attention_seq_bwd and _mega_bwd_vmem_ok(
                B, ep.shape[1], ep.shape[-1], enc.shape[-1], H, T,
                ep.dtype.itemsize):
            # whole-sequence backward kernel: the reverse dh chain, the
            # per-step attention backward, AND the phase-2 dep/dv
            # accumulation run in ONE pallas_call (T per-step dispatches
            # + the phase-2 dispatch collapse into a single kernel)
            dispatch_stats["seq_bwd"] += 1
            (dxp_seq, dctx_seq, ddp_seq, dh0, dep, dv2) = _decoder_seq_bwd(
                ep, enc, maskf, g_seq, tmask.astype(jnp.float32), hp_seq,
                u_seq, r_seq, c_seq, dp_seq, alpha_seq, v,
                w_c, w_ur, wx[E:], wa_dec, h0.dtype, interpret)
            dv = dv2[0]
        else:
            dispatch_stats["scan_bwd"] += 1

            def back_step(dh_carry, inp):
                g_t, m_t, hp, u, r, c, dp, alpha = inp
                dh = dh_carry + g_t
                m = m_t[:, None].astype(dt)
                dh_cell = dh * m
                dh_prev = dh * (1 - m)
                # GRU cell backward (h = (1-u) hp + u c)
                du = dh_cell * (c - hp)
                dc = dh_cell * u
                dh_prev = dh_prev + dh_cell * (1 - u)
                dpre_c = dc * (1 - c * c)
                drh = jnp.dot(dpre_c, w_c.T).astype(dt)
                dr = drh * hp
                dh_prev = dh_prev + drh * r
                dpre_u = du * u * (1 - u)
                dpre_r = dr * r * (1 - r)
                dur = jnp.concatenate([dpre_u, dpre_r], -1)
                dh_prev = dh_prev + jnp.dot(dur, w_ur.T).astype(dt)
                dxp = jnp.concatenate([dur, dpre_c], -1)      # [B,3H]
                dctx = jnp.dot(dxp, wx[E:].T).astype(dt)
                # attention backward, step-local outputs only
                ddp, dsc = _attn_bwd_step(ep, enc, dp, v, maskf, dctx,
                                          alpha, interpret)
                dh_prev = dh_prev + jnp.dot(ddp, wa_dec.T).astype(dt)
                return dh_prev, (dxp, dctx, dsc, ddp)

            dh0, (dxp_seq, dctx_seq, dsc_seq, ddp_seq) = jax.lax.scan(
                back_step,
                jnp.zeros_like(h0),
                (g_seq, tmask, hp_seq, u_seq, r_seq, c_seq, dp_seq,
                 alpha_seq),
                reverse=True,
            )
            # the [B,Sp,A]-sized gradient, written exactly once
            dep, dv = _attn_phase2(ep, dp_seq, dsc_seq, v, enc.shape[-1],
                                   interpret)
        # ---- shared tail: dx + batched parameter grads -----------------
        dx_seq = jnp.einsum("tbg,eg->tbe", dxp_seq, wx[:E]).astype(dt)
        dwx = jnp.einsum("tbi,tbg->ig", xin_seq, dxp_seq)
        dbias = jnp.sum(dxp_seq, (0, 1))
        dw_ur = jnp.einsum("tbh,tbg->hg", hp_seq, dxp_seq[..., : 2 * H])
        dw_c = jnp.einsum("tbh,tbg->hg", rh_seq, dxp_seq[..., 2 * H:])
        dwh = jnp.concatenate([dw_ur, dw_c], -1)
        dwa_dec = jnp.einsum("tbh,tba->ha", hp_seq, ddp_seq)
        denc = jnp.einsum("tbs,tbc->bsc", alpha_seq.astype(dt),
                          dctx_seq).astype(enc.dtype)
        dv = dv.astype(jnp.float32)
        if axis is not None:
            # replicated-weight cotangents: per-shard partials -> global
            dwx, dbias, dwh, dwa_dec, dv = (
                jax.lax.psum(g, axis)
                for g in (dwx, dbias, dwh, dwa_dec, dv))
        return (denc, dep, jnp.zeros_like(maskf), dx_seq,
                jnp.zeros_like(tmask), dh0, dwa_dec.astype(wa_dec.dtype),
                dv.astype(v.dtype), dwx.astype(wx.dtype),
                dwh.astype(wh.dtype), dbias)

    f.defvjp(fwd, bwd)
    return f


def fused_attention_decoder(enc_b, enc_proj, enc_mask, trg_b, trg_mask,
                            h0, wa_dec, v_att, wx, wh, bias):
    """Public entry: unpadded [B, S, ·] operands; pads S for the kernels.

    enc_mask is bool [B, S]; trg_mask float [T, B]; bias may be None.
    Returns h_seq [T, B, H].
    """
    B, S, A = enc_proj.shape
    sp = _pad_s(S)
    pad = [(0, 0), (0, sp - S), (0, 0)]
    ep = jnp.pad(enc_proj, pad)
    enc = jnp.pad(enc_b, pad)
    maskf = jnp.pad(enc_mask.astype(jnp.float32), [(0, 0), (0, sp - S)])
    if bias is None:
        bias = jnp.zeros((wx.shape[1],), trg_b.dtype)
    dispatch_stats["fused_calls"] += 1
    from . import mesh_dispatch

    am = mesh_dispatch.current()
    # axis only when shard_batch will actually wrap (dp > 1)
    f = _decoder_fn(_interpret(),
                    am.batch_axis if am and am.dp > 1 else None)
    # mesh policy (ops/mesh_dispatch.py): the kernels run per-shard
    # under shard_map — batch-sharded operands, replicated weights
    call = mesh_dispatch.shard_batch(
        f, (0, 0, 0, 1, 1, 0, None, None, None, None, None), ((1, 3),))
    return call(enc, ep, maskf, trg_b, trg_mask.astype(jnp.float32),
                h0, wa_dec, v_att, wx, wh, bias)
