"""CTC (Connectionist Temporal Classification) loss.

Reference: gserver/layers/CTCLayer.cpp + LinearChainCTC.cpp (in-tree
implementation) and WarpCTCLayer.cpp / cuda/src/hl_warpctc_wrap.cc (the
warp-ctc binding); Fluid: operators/warpctc_op.cc.

TPU design: the classic alpha recursion over the blank-extended label
sequence [b, l1, b, l2, …, b], in log space, as one `lax.scan` over time
with static shapes [T, B, 2L+1]: variable input lengths freeze the alpha
carry via the batch mask (same idiom as the RNN scans), variable label
lengths mask the extended positions and pick the per-sequence final
states by index. Gradients come from jax.grad of the scan — replacing
warp-ctc's hand-written beta recursion.

Blank id is configurable (attr `blank`, default 0 — warp-ctc layout).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.lod import LoDArray
from ..core.registry import register_op

_NEG = -1e30


def ctc_loss(logits_l: LoDArray, label_l: LoDArray, blank: int = 0,
             max_len=None, max_label_len=None, log_input: bool = False):
    """Per-sequence CTC negative log-likelihood [max_seqs].

    logits_l: LoD [*, C] acoustic frames (unnormalized unless log_input);
    label_l: LoD int tokens (must not contain `blank`)."""
    logit_tb, in_mask = logits_l.to_batch(max_len=max_len)  # [T, B, C]
    lbl = label_l.data
    if lbl.ndim == 2 and lbl.shape[1] == 1:
        lbl = lbl[:, 0]
    lbl_tb, _ = label_l.with_data(lbl.astype(jnp.int32)).to_batch(
        max_len=max_label_len, time_major=False
    )  # [B, L]
    B, L = lbl_tb.shape
    T = logit_tb.shape[0]
    C = logit_tb.shape[-1]
    logp = logit_tb if log_input else jax.nn.log_softmax(logit_tb, axis=-1)

    lab_lens = label_l.lengths  # [B]
    in_lens = logits_l.lengths

    # blank-extended labels ext [B, S], S = 2L+1
    S = 2 * L + 1
    ext = jnp.full((B, S), blank, jnp.int32)
    ext = ext.at[:, 1::2].set(jnp.clip(lbl_tb, 0, C - 1))
    pos = jnp.arange(S)[None, :]  # [1, S]
    valid_pos = pos < (2 * lab_lens[:, None] + 1)  # [B, S]
    # can we skip from s-2 to s? only onto a non-blank that differs from s-2
    ext_m2 = jnp.pad(ext, ((0, 0), (2, 0)), constant_values=-1)[:, :S]
    can_skip = (pos % 2 == 1) & (ext != ext_m2)  # odd positions are labels

    def emit(logp_t):  # [B, C] → [B, S] log-prob of each extended symbol
        return jnp.take_along_axis(logp_t, ext, axis=-1)

    alpha0 = jnp.full((B, S), _NEG)
    e0 = emit(logp[0])
    alpha0 = alpha0.at[:, 0].set(e0[:, 0])
    has_label = lab_lens > 0
    alpha0 = alpha0.at[:, 1].set(jnp.where(has_label, e0[:, 1], _NEG))

    def step(alpha, inp):
        logp_t, m_t = inp
        a_m1 = jnp.pad(alpha, ((0, 0), (1, 0)), constant_values=_NEG)[:, :S]
        a_m2 = jnp.pad(alpha, ((0, 0), (2, 0)), constant_values=_NEG)[:, :S]
        acc = jnp.logaddexp(alpha, a_m1)
        acc = jnp.where(can_skip, jnp.logaddexp(acc, a_m2), acc)
        new = acc + emit(logp_t)
        new = jnp.where(valid_pos, new, _NEG)
        alpha = jnp.where(m_t[:, None], new, alpha)
        return alpha, None

    alpha_T, _ = jax.lax.scan(step, alpha0, (logp[1:], in_mask[1:]))

    # loss = -log(alpha[2*lab_len] + alpha[2*lab_len - 1])
    s_last = 2 * lab_lens  # [B] (blank after last label)
    a_end = jnp.take_along_axis(alpha_T, s_last[:, None], axis=1)[:, 0]
    s_prev = jnp.clip(2 * lab_lens - 1, 0, S - 1)
    a_prev = jnp.take_along_axis(alpha_T, s_prev[:, None], axis=1)[:, 0]
    a_prev = jnp.where(has_label, a_prev, _NEG)
    nll = -jnp.logaddexp(a_end, a_prev)
    valid = (jnp.arange(B) < logits_l.num_seqs) & (in_lens > 0)
    return jnp.where(valid, nll, 0.0)


@register_op("ctc_greedy_decoder")
def ctc_greedy_decoder_kernel(ctx):
    """Best-path decode: per-frame argmax, collapse repeats, drop blanks.

    Reference: operators/ctc_align_op.cc (CTCAlign) / the decode path of
    CTCErrorEvaluator.cpp. Outputs dense Ids [B, T] int32 (padded with
    -1) and Lengths [B]; static shapes, collapse via keep-mask + cumsum
    scatter."""
    logits: LoDArray = ctx.input("Logits")
    blank = ctx.attr("blank", 0)
    logit_tb, mask = logits.to_batch(max_len=ctx.attr("max_len"))  # [T,B,C]
    pred = jnp.argmax(logit_tb, axis=-1).astype(jnp.int32)  # [T, B]
    prev = jnp.pad(pred, ((1, 0), (0, 0)), constant_values=-1)[:-1]
    keep = (pred != blank) & (pred != prev) & mask  # [T, B]
    T, B = pred.shape
    # output slot per kept frame: exclusive cumsum of keep along time
    slot = jnp.cumsum(keep.astype(jnp.int32), axis=0) - keep.astype(jnp.int32)
    slot = jnp.where(keep, slot, T)  # dump dropped frames past the end
    out = jnp.full((B, T + 1), -1, jnp.int32)
    out = out.at[jnp.arange(B)[None, :], slot].set(
        jnp.where(keep, pred, -1)
    )[:, :T]
    lengths = jnp.sum(keep, axis=0).astype(jnp.int32)
    ctx.set_output("Ids", out)
    ctx.set_output("Lengths", lengths)


@register_op("warpctc")
def warpctc_kernel(ctx):
    """Reference: operators/warpctc_op.cc / WarpCTCLayer.cpp. Outputs the

    per-sequence loss [max_seqs, 1]; norm_by_times divides by the input
    length (the reference flag)."""
    logits: LoDArray = ctx.input("Logits")
    label: LoDArray = ctx.input("Label")
    nll = ctc_loss(
        logits,
        label,
        blank=ctx.attr("blank", 0),
        max_len=ctx.attr("max_len"),
        max_label_len=ctx.attr("max_label_len"),
    )
    if ctx.attr("norm_by_times", False):
        nll = nll / jnp.maximum(logits.lengths, 1).astype(nll.dtype)
    ctx.set_output("Loss", nll[:, None])
