"""Scale-out serving: a load-aware router over N replica processes.

Reference lineage: the Go master dispatches task shards to a trainer
fleet and re-dispatches when a trainer dies (PAPER.md's distributed
story — etcd discovery, health leases, failover). The serving rebuild
needs the same shape on the INFERENCE side: one `serving/server.py`
process is one chip's worth of QPS no matter how fast its continuous
batcher runs (ROADMAP open item 2), so "millions of users" means a
front-end that spreads `/predict` and `/generate` over a fleet and
survives any one replica dying mid-request.

Layers, bottom-up:

- `ReplicaClient`  — the router's view of one replica: its base URL, a
  per-replica CircuitBreaker (resilience.breaker — the containment the
  reference delegated to etcd leases), the last health snapshot (queue
  depth, slot occupancy from the replica's /healthz `load` block), and
  a router-local in-flight counter.
- `Router`         — join-shortest-queue picking over admitted
  replicas (`pick()` is PURE in-memory state: an AST lint bans
  blocking I/O in the pick hot path), dispatch with
  retry-on-other-replica for shed/503 and transport errors, chunked
  NDJSON streaming pass-through, a background health-probe loop that
  feeds snapshots and re-admits recovered replicas through the
  breaker's half-open probe, and fleet gauges/counters in the unified
  obs.MetricsRegistry (`pt_replica_up{replica=}`, routed/retried/
  failed-over counters) so ONE /metrics scrape on the router covers
  the fleet.
- `RouterServer`   — threaded stdlib-HTTP front-end: POST /predict*
  and /generate* forward; GET /healthz /stats /metrics answer locally.
- `ReplicaProcess` — a spawned `python -m paddle_tpu serve` subprocess
  (port 0, URL parsed from its startup line) with ready-wait and
  kill/terminate for chaos tests.
- `WarmPool`       — pre-forked, warmed standby replicas so a traffic
  spike (or a SIGKILLed replica) is absorbed by promotion, not by a
  cold model load + warmup in the serving path.
- `Fleet`          — N managed replicas + router + a supervisor loop
  that notices dead replica processes, trips their breaker, and
  promotes a standby from the warm pool; `cli serve --replicas N`
  builds one.

Correlation: the router mints (or forwards) `X-PT-Request-Id`; the
replica adopts it for its batcher/scheduler request id, so one armed
trace capture shows router pick → replica queue → pool step → stream
for a single request across BOTH processes' exports.

Status mapping at the router: a replica's 503 (shed / its own model
breaker) triggers a retry on the next-best replica; transport errors
feed the replica's breaker and fail over the same way; only when every
admitted replica has been tried does the client see a 503 (always with
Retry-After — the fleet being saturated is retryable by contract).
Non-503 replica responses (200/400/404/500/504) relay verbatim: they
prove the replica is alive, and re-running a deadline-blown or
model-failing request elsewhere would double device work for the same
outcome.
"""

from __future__ import annotations

import collections
import http.client
import json
import os
import re
import signal
import subprocess
import sys
import threading
import time
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..fleetctl.tenancy import SLO_HEADER, SLOPolicy, resolve_class
from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..resilience.breaker import STATE_CODES, CircuitBreaker
from .server import REQUEST_ID_HEADER

__all__ = [
    "Fleet",
    "NoReplicaError",
    "ReplicaClient",
    "ReplicaProcess",
    "Router",
    "RouterServer",
    "WarmPool",
    "make_router_server",
]


class NoReplicaError(RuntimeError):
    """Every replica is open-circuited, excluded, or absent: the
    request was not dispatched anywhere (router answers 503 +
    Retry-After — retryable by contract)."""


class ReplicaClient:
    """The router's view of one replica. All fields the pick hot path
    reads are plain attributes updated by the probe loop / dispatch
    bookkeeping — `score()` never touches the network."""

    def __init__(self, name: str, url: str,
                 breaker: Optional[CircuitBreaker] = None,
                 process: Optional["ReplicaProcess"] = None,
                 phase: Optional[str] = None):
        if phase not in (None, "prefill", "decode"):
            raise ValueError(f"replica phase must be None, 'prefill' or "
                             f"'decode', got {phase!r}")
        self.name = name
        self.phase = phase     # disagg replica class (None = monolithic)
        self.url = url.rstrip("/")
        m = re.match(r"https?://([^/:]+):(\d+)", self.url)
        if not m:
            raise ValueError(f"replica url must be http://host:port, "
                             f"got {url!r}")
        self.host, self.port = m.group(1), int(m.group(2))
        self.breaker = breaker or CircuitBreaker(
            failure_threshold=3, reset_timeout_s=2.0)
        self.process = process
        self.inflight = 0          # router-local dispatched-not-done
        self.up = False            # last probe outcome
        self.draining = False      # retiring (rollout/scale-down):
        #                            finishes in-flight work, never
        #                            picked for new requests
        self.snapshot: Dict[str, Any] = {}  # /healthz "load" block
        self.versions: Dict[str, str] = {}  # /healthz model→fingerprint
        self.last_probe_s = 0.0
        self.last_picked = 0       # pick-sequence tie-break (JSQ ties
        #                            round-robin instead of pile-on)

    def score(self, slo: Optional[str] = None) -> float:
        """Join-shortest-queue load score: router-tracked in-flight
        (fresh, covers the probe staleness window) plus the replica's
        last-reported queue depth and active slots. With `slo` given
        and a per-class breakdown in the snapshot, the CLASS's own
        queue depth is scored instead of the total — a replica whose
        backlog is all batch work still looks short to interactive
        traffic (the batch tier sheds for it on admission). Lower =
        less loaded. Pure reads — no I/O, no locks.

        Phase-classed replicas (serving/disagg) score on their OWN
        phase's signal: a prefill replica on queue depth + compute
        backlog (queue age — its decode pool never fills, so slots are
        meaningless), a decode replica on how few FREE slots remain
        (the shipped request is about to occupy one; in-flight covers
        the handoff window before a probe refreshes the snapshot)."""
        snap = self.snapshot
        depth: Optional[float] = None
        if slo is not None:
            classes = snap.get("classes")
            if isinstance(classes, dict) and slo in classes:
                depth = float(classes[slo])
        if depth is None:
            depth = float(snap.get("queue_depth", 0))
        if self.phase == "prefill":
            return (2.0 * self.inflight
                    + depth
                    + 0.001 * float(snap.get("queue_age_ms", 0.0)))
        if self.phase == "decode":
            free = max(0.0, float(snap.get("max_slots", 0))
                       - float(snap.get("active_slots", 0)))
            return 2.0 * self.inflight + depth - free
        return (2.0 * self.inflight
                + depth
                + float(snap.get("active_slots", 0)))

    def describe(self) -> Dict[str, Any]:
        return {
            "url": self.url,
            "up": self.up,
            "phase": self.phase,
            "draining": self.draining,
            "breaker": self.breaker.state(),
            "inflight": self.inflight,
            "score": self.score(),
            "load": dict(self.snapshot),
            "versions": dict(self.versions),
        }


class _Lease:
    """One dispatched request: holds the picked replica's in-flight
    slot until the response is fully relayed."""

    __slots__ = ("router", "replica", "conn", "resp", "stream", "status",
                 "headers", "body", "_closed")

    def __init__(self, router, replica, conn, resp, stream, status,
                 headers, body=None):
        self.router = router
        self.replica = replica
        self.conn = conn
        self.resp = resp
        self.stream = stream
        self.status = status
        self.headers = headers
        self.body = body
        self._closed = False

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.router._release(self.replica)
        if self.conn is not None:
            try:
                self.conn.close()
            except OSError:
                pass


class Router:
    """Load-aware request router over a set of ReplicaClients."""

    def __init__(
        self,
        replicas: Sequence[str] = (),
        probe_interval_s: float = 0.5,
        probe_timeout_s: float = 2.0,
        request_timeout_s: float = 120.0,
        breaker_kw: Optional[dict] = None,
        registry: Optional[obs_metrics.MetricsRegistry] = None,
        slo_policy: Optional[SLOPolicy] = None,
    ):
        self.probe_interval_s = probe_interval_s
        self.probe_timeout_s = probe_timeout_s
        self.request_timeout_s = request_timeout_s
        # per-model SLO classes (fleetctl.tenancy): the router resolves
        # a request's class ONCE (model default, demotable by header/
        # body), scores the pick with it, and forwards it so the
        # replica's admission tiers agree with the pick
        self.slo_policy = slo_policy or SLOPolicy()
        self._breaker_kw = dict(breaker_kw or {})
        self._lock = threading.Lock()
        self._replicas: "collections.OrderedDict[str, ReplicaClient]" = (
            collections.OrderedDict())
        self._seq = 0
        self._next_name = 0
        self._prober: Optional[threading.Thread] = None
        self._probe_cond = threading.Condition()
        self._stopping = False
        self.registry = registry or obs_metrics.registry()
        # fleet counters: full pt_-prefixed names straight on the
        # unified registry (MetricSet would prepend ptserving_); the
        # labeled ones declare per replica in add_replica
        for name, help in (
            ("pt_router_requests_total",
             "requests accepted by the router front-end"),
            ("pt_router_retried_total",
             "dispatch attempts retried on another replica after a "
             "shed/503 response"),
            ("pt_router_unroutable_total",
             "requests that found no admittable replica (client saw a "
             "retryable 503)"),
        ):
            self.registry.declare_counter(name, help=help)
        self.registry.add_collector(self._fleet_families)
        for url in replicas:
            self.add_replica(url)

    # -- fleet membership ----------------------------------------------
    def _add_locked(self, url: str, name: Optional[str],
                    process: Optional["ReplicaProcess"],
                    breaker: Optional[CircuitBreaker],
                    phase: Optional[str] = None) -> ReplicaClient:
        """Create + register one client. Caller holds self._lock."""
        if name is None:
            name = f"r{self._next_name}"
        self._next_name += 1
        if name in self._replicas:
            raise ValueError(f"replica {name!r} already registered")
        if breaker is None and self._breaker_kw:
            breaker = CircuitBreaker(**self._breaker_kw)
        r = ReplicaClient(name, url, process=process, breaker=breaker,
                          phase=phase)
        self._replicas[name] = r
        return r

    def _declare_replica_counters(self, name: str) -> None:
        # per-replica counters declare at registration so the scrape
        # surface is complete before the first request routes
        for cname, chelp in (
            ("pt_router_routed_total",
             "requests dispatched to this replica"),
            ("pt_router_failed_over_total",
             "dispatches abandoned on this replica after a transport "
             "error (failed over to another)"),
        ):
            self.registry.declare_counter(cname, help=chelp,
                                          labels={"replica": name})

    def add_replica(self, url: str, name: Optional[str] = None,
                    process: Optional["ReplicaProcess"] = None,
                    breaker: Optional[CircuitBreaker] = None,
                    phase: Optional[str] = None
                    ) -> ReplicaClient:
        with self._lock:
            r = self._add_locked(url, name, process, breaker, phase)
        self._declare_replica_counters(r.name)
        self._probe_now()
        return r

    def remove_replica(self, name: str,
                       retire_series: bool = False
                       ) -> Optional[ReplicaClient]:
        """Drop one replica from the rotation. `retire_series=True` —
        the DELIBERATE retirement path (scale-down, rollout drain) —
        also removes the replica's labeled counter series from the
        registry so a scaled-down fleet doesn't accumulate dead
        `pt_router_*{replica=}` series (the `pt_replica_*` gauges are
        collector-rendered from live membership, so they drop with the
        client). FAILURE removal keeps the series: a SIGKILLed
        replica's routed/failed-over history is evidence (test_fleet
        pins this)."""
        with self._lock:
            r = self._replicas.pop(name, None)
        if r is not None and retire_series:
            for cname in ("pt_router_routed_total",
                          "pt_router_failed_over_total"):
                self.registry.remove_series(cname,
                                            labels={"replica": name})
        return r

    def set_draining(self, name: str, draining: bool = True) -> bool:
        """Mark a replica as retiring: it finishes what it has but
        pick() never selects it again. Returns False for unknown
        names."""
        with self._lock:
            r = self._replicas.get(name)
            if r is None:
                return False
            r.draining = draining
            return True

    def flip(self, add: Sequence[Tuple[str, Optional["ReplicaProcess"]]],
             drain: Sequence[str]) -> List[ReplicaClient]:
        """ATOMIC membership change — the rollout's cutover point:
        under one lock acquisition the new replicas join the rotation
        and the old ones are marked draining, so there is no instant
        where a request can find neither version (zero-downtime
        contract; fleetctl/rollout.py drains + removes the old ones
        afterwards). Returns the clients added."""
        added: List[ReplicaClient] = []
        with self._lock:
            for name in drain:
                r = self._replicas.get(name)
                if r is not None:
                    r.draining = True
            for url, process in add:
                added.append(self._add_locked(url, None, process, None))
        for r in added:
            self._declare_replica_counters(r.name)
        self._probe_now()
        return added

    def replicas(self) -> List[ReplicaClient]:
        with self._lock:
            return list(self._replicas.values())

    # -- the pick hot path (NO blocking I/O — AST-linted) ---------------
    def pick(self, exclude: Sequence[str] = (),
             slo: Optional[str] = None,
             phase: Optional[str] = None) -> Optional[ReplicaClient]:
        """Join-shortest-queue over admitted replicas: lowest score()
        wins, ties go to the least-recently-picked (round-robin under
        uniform load instead of herding onto one replica). With `slo`
        given, replicas are scored by that class's own queue depth
        (per-class JSQ — batch backlog doesn't repel interactive
        traffic). With `phase` given, only replicas of that disagg
        class compete (each class scores on its own signal — see
        ReplicaClient.score). Draining replicas (rollout/scale-down)
        are never picked. Reads ONLY router-local state — breaker
        admission, in-flight counters and the probe loop's cached
        snapshots; never the network."""
        with self._lock:
            # scan with would_admit() (non-consuming) so a HALF_OPEN
            # replica that loses the JSQ comparison keeps its probe
            # budget; only the winner pays admit()
            ranked = sorted(
                ((r.score(slo), r.last_picked, r)
                 for r in self._replicas.values()
                 if r.name not in exclude and not r.draining
                 and (phase is None or r.phase == phase)
                 and r.breaker.would_admit()),
                key=lambda t: t[:2])
            for _, _, best in ranked:
                if not best.breaker.admit():
                    continue  # raced OPEN since the scan; next-best
                self._seq += 1
                best.last_picked = self._seq
                best.inflight += 1
                return best
        return None

    def _release(self, replica: ReplicaClient) -> None:
        with self._lock:
            replica.inflight = max(0, replica.inflight - 1)

    # -- dispatch -------------------------------------------------------
    def dispatch(self, path: str, body: bytes,
                 request_id: Optional[str] = None,
                 headers: Optional[Dict[str, str]] = None,
                 slo: Optional[str] = None,
                 phase: Optional[str] = None,
                 exclude: Sequence[str] = ()) -> _Lease:
        """POST `body` to the best replica; returns a _Lease whose
        response is either buffered (`lease.body`) or streaming
        (`lease.resp` — chunked NDJSON, relay then `close()`).

        `slo` drives the per-class pick and is forwarded in
        X-PT-SLO-Class so the replica's admission tiers agree with the
        score the pick used. `phase` restricts the pick to one disagg
        replica class; `exclude` pre-blacklists replica names (the
        disagg dispatcher's re-prefill avoids the replica whose
        payload just failed).

        Failover contract: a 503 (replica shed / its model breaker)
        and any transport error move on to the next-best replica the
        first attempt didn't use; transport errors additionally feed
        the replica's ROUTER-side breaker. Raises NoReplicaError when
        no admittable replica remains."""
        self.registry.counter_inc("pt_router_requests_total")
        if slo is not None:
            headers = dict(headers or {})
            headers[SLO_HEADER] = slo
        tried: List[str] = list(exclude)
        last_shed: Optional[_Lease] = None
        while True:
            replica = self.pick(exclude=tried, slo=slo, phase=phase)
            if replica is None:
                if last_shed is not None:
                    # every admitted replica shed: relay the final 503
                    # (it carries Retry-After) rather than inventing one
                    return last_shed
                self.registry.counter_inc("pt_router_unroutable_total")
                raise NoReplicaError(
                    f"no replica available for {path} "
                    f"(tried {tried or 'none'}); retry later")
            tried.append(replica.name)
            if last_shed is not None:
                last_shed.close()
                last_shed = None
            try:
                lease = self._attempt(replica, path, body, request_id,
                                      headers)
            except (OSError, http.client.HTTPException) as e:
                # transport failure: the replica is gone or wedged —
                # feed its breaker and fail the request over
                self._release(replica)
                replica.breaker.record_failure()
                self.registry.counter_inc(
                    "pt_router_failed_over_total",
                    labels={"replica": replica.name})
                if obs_trace._armed:
                    obs_trace.instant(
                        "router.failover", cat="router",
                        replica=replica.name, request_id=request_id,
                        error=f"{type(e).__name__}: {e}")
                continue
            replica.breaker.record_success()
            if lease.status == 503:
                # shed / model-circuit-open: replica alive but refusing
                # — retry elsewhere, keep the last 503 as the fallback
                self.registry.counter_inc("pt_router_retried_total")
                last_shed = lease
                continue
            self.registry.counter_inc("pt_router_routed_total",
                                      labels={"replica": replica.name})
            return lease

    def _attempt(self, replica: ReplicaClient, path: str, body: bytes,
                 request_id: Optional[str],
                 headers: Optional[Dict[str, str]]) -> _Lease:
        """One POST to one replica. Raises OSError/HTTPException on
        transport failure (caller fails over); returns a _Lease
        otherwise. The replica's in-flight slot is already held by
        pick() and travels with the lease."""
        conn = http.client.HTTPConnection(
            replica.host, replica.port, timeout=self.request_timeout_s)
        hdrs = {"Content-Type": "application/json"}
        if headers:
            hdrs.update(headers)
        if request_id:
            hdrs[REQUEST_ID_HEADER] = request_id
        try:
            conn.request("POST", path, body=body, headers=hdrs)
            resp = conn.getresponse()
            ctype = resp.getheader("Content-Type", "")
            stream = "ndjson" in ctype
            resp_headers = [
                (k, v) for k, v in resp.getheaders()
                if k.lower() in ("content-type", "retry-after",
                                 REQUEST_ID_HEADER.lower())
            ]
            if stream:
                return _Lease(self, replica, conn, resp, True,
                              resp.status, resp_headers)
            payload = resp.read()  # short read raises → failover
        except BaseException:
            conn.close()
            raise
        conn.close()
        return _Lease(self, replica, None, None, False, resp.status,
                      resp_headers, body=payload)

    # -- health probing -------------------------------------------------
    def start(self) -> "Router":
        with self._probe_cond:
            if self._prober is not None and self._prober.is_alive():
                return self
            self._stopping = False
            self._prober = threading.Thread(
                target=self._probe_loop, name="ptrouter-probe",
                daemon=True)
            self._prober.start()
        return self

    def close(self) -> None:
        with self._probe_cond:
            self._stopping = True
            self._probe_cond.notify_all()
        if self._prober is not None:
            self._prober.join(timeout=5.0)
        self.registry.remove_collector(self._fleet_families)

    def _probe_now(self) -> None:
        """Wake the probe loop (a just-added replica should be scored
        from a fresh snapshot, not a zero one)."""
        with self._probe_cond:
            self._probe_cond.notify_all()

    def _probe_loop(self) -> None:
        while True:
            with self._probe_cond:
                if self._stopping:
                    return
            for r in self.replicas():
                self.probe_one(r)
            with self._probe_cond:
                if self._stopping:
                    return
                self._probe_cond.wait(timeout=self.probe_interval_s)

    def probe_one(self, replica: ReplicaClient) -> bool:
        """One /healthz round-trip: refresh the replica's load snapshot
        and feed its breaker — a success while HALF_OPEN closes the
        circuit (re-admission after recovery/restart needs no traffic),
        a failure counts toward tripping it."""
        try:
            with urllib.request.urlopen(
                    replica.url + "/healthz",
                    timeout=self.probe_timeout_s) as f:
                payload = json.loads(f.read().decode())
        except Exception:
            replica.up = False
            replica.breaker.record_failure()
            return False
        replica.up = payload.get("status") in ("ok", "degraded")
        replica.snapshot = payload.get("load") or {}
        replica.versions = payload.get("versions") or {}
        replica.last_probe_s = time.monotonic()
        if replica.up and replica.breaker.state() != "closed":
            # the half-open probe budget is spent on a HEALTH CHECK,
            # not a user request: record the success to close it
            replica.breaker.admit()
            replica.breaker.record_success()
        return replica.up

    # -- introspection / metrics ---------------------------------------
    def health(self) -> Dict[str, Any]:
        reps = {r.name: r.describe() for r in self.replicas()}
        n_up = sum(1 for d in reps.values()
                   if d["up"] and d["breaker"] == "closed")
        status = ("ok" if n_up == len(reps) and reps else
                  "degraded" if n_up else "down")
        return {"status": status, "replicas": reps}

    def stats(self) -> Dict[str, Any]:
        reg = self.registry
        return {
            "replicas": {r.name: r.describe() for r in self.replicas()},
            "requests_total": reg.counter_value(
                "pt_router_requests_total"),
            "retried_total": reg.counter_value("pt_router_retried_total"),
            "unroutable_total": reg.counter_value(
                "pt_router_unroutable_total"),
            "routed": {
                r.name: reg.counter_value(
                    "pt_router_routed_total", labels={"replica": r.name})
                for r in self.replicas()
            },
            "failed_over": {
                r.name: reg.counter_value(
                    "pt_router_failed_over_total",
                    labels={"replica": r.name})
                for r in self.replicas()
            },
        }

    def _fleet_families(self):
        """Render-time collector: per-replica gauges in the unified
        registry, so one /metrics scrape on the router reports fleet
        state (ISSUE 9 satellite)."""
        reps = self.replicas()
        if not reps:
            return []
        up, state, queue, slots, inflight, draining = ([], [], [], [],
                                                       [], [])
        for r in reps:
            lb = {"replica": r.name}
            up.append((lb, 1.0 if r.up else 0.0))
            state.append((lb, float(STATE_CODES[r.breaker.state()])))
            queue.append((lb, float(r.snapshot.get("queue_depth", 0))))
            slots.append((lb, float(r.snapshot.get("active_slots", 0))))
            inflight.append((lb, float(r.inflight)))
            draining.append((lb, 1.0 if r.draining else 0.0))
        fams = [
            ("pt_replica_up", "gauge",
             "1 while the replica's last health probe succeeded", up),
            ("pt_replica_breaker_state", "gauge",
             "router-side replica circuit state "
             "(0=closed 1=half_open 2=open)", state),
            ("pt_replica_queue_depth", "gauge",
             "admission-queue depth last reported by the replica",
             queue),
            ("pt_replica_active_slots", "gauge",
             "active decode slots last reported by the replica", slots),
            ("pt_replica_inflight", "gauge",
             "router-tracked requests in flight on the replica",
             inflight),
            ("pt_replica_draining", "gauge",
             "1 while the replica is retiring (rollout/scale-down): "
             "finishing in-flight work, excluded from picks", draining),
        ]
        # disagg: per-phase breakdown of the same signals, one series
        # per replica CLASS ({phase=prefill|decode}) so dashboards see
        # the two classes' load separately without relabeling the
        # per-replica families above
        agg: Dict[str, Dict[str, float]] = {}
        for r in reps:
            if r.phase is None:
                continue
            a = agg.setdefault(r.phase, {"queue_depth": 0.0,
                                         "inflight": 0.0,
                                         "free_slots": 0.0,
                                         "replicas": 0.0})
            a["replicas"] += 1.0
            a["queue_depth"] += float(r.snapshot.get("queue_depth", 0))
            a["inflight"] += float(r.inflight)
            a["free_slots"] += max(
                0.0, float(r.snapshot.get("max_slots", 0))
                - float(r.snapshot.get("active_slots", 0)))
        if agg:
            def _series(key):
                return [({"phase": p}, v[key])
                        for p, v in sorted(agg.items())]
            fams.extend([
                ("pt_phase_replicas", "gauge",
                 "replicas registered in this disagg phase class",
                 _series("replicas")),
                ("pt_phase_queue_depth", "gauge",
                 "admission-queue depth summed over one phase class",
                 _series("queue_depth")),
                ("pt_phase_inflight", "gauge",
                 "router-tracked in-flight requests summed over one "
                 "phase class", _series("inflight")),
                ("pt_phase_free_slots", "gauge",
                 "free decode slots summed over one phase class "
                 "(prefill replicas report 0)", _series("free_slots")),
            ])
        return fams


# -- HTTP front-end ----------------------------------------------------------


class _RouterHandler(BaseHTTPRequestHandler):
    server: "RouterServer"
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # quiet by default
        pass

    def _send(self, code: int, payload, content_type="application/json",
              extra_headers=()):
        body = (payload if isinstance(payload, bytes)
                else json.dumps(payload).encode())
        self.send_response(code)
        self.send_header("Content-Type", content_type)
        self.send_header("Content-Length", str(len(body)))
        for k, v in extra_headers:
            self.send_header(k, v)
        self.end_headers()
        self.wfile.write(body)

    def _error(self, code: int, message: str):
        self._send(code, {"error": message},
                   extra_headers=(
                       (("Retry-After", "1"),) if code == 503 else ()))

    def do_GET(self):
        router = self.server.router
        if self.path == "/healthz":
            h = router.health()
            self._send(200, h)
        elif self.path == "/stats":
            self._send(200, router.stats())
        elif self.path == "/metrics":
            self._send(200, router.registry.render().encode(),
                       content_type="text/plain; version=0.0.4")
        elif self.path == "/admin/fleet":
            self._send(200, self.server.admin_fleet())
        else:
            self._error(404, f"no route {self.path!r}")

    def do_POST(self):
        if self.path == "/admin/rollout":
            self._admin_rollout()
            return
        if not (self.path.startswith("/predict")
                or self.path.startswith("/generate")):
            self._error(404, f"no route {self.path!r}")
            return
        router = self.server.router
        length = int(self.headers.get("Content-Length", 0))
        body = self.rfile.read(length)
        # mint-or-forward: the correlation id crosses the router hop in
        # this header; the replica adopts it (server._request_id)
        rid = (self.headers.get(REQUEST_ID_HEADER)
               or obs_trace.new_request_id("rt"))
        try:
            slo = self._resolve_slo(body)
        except ValueError as e:
            self._error(400, f"bad request: {e}")
            return
        # disagg topology: /generate requests phase-split through the
        # dispatcher (prefill pick → payload ship → pinned decode pick);
        # /predict and everything else keep the monolithic path
        disagg = getattr(self.server, "disagg", None)
        try:
            with obs_trace.span("http.route", cat="router",
                                path=self.path, request_id=rid,
                                slo=slo):
                if disagg is not None and self.path.startswith(
                        "/generate"):
                    lease = disagg.generate(self.path, body,
                                            request_id=rid, slo=slo)
                else:
                    lease = router.dispatch(self.path, body,
                                            request_id=rid, slo=slo)
        except NoReplicaError as e:
            self._error(503, str(e))
            return
        try:
            if lease.stream:
                self._relay_stream(lease, rid)
            else:
                extra = list(lease.headers)
                if not any(k.lower() == REQUEST_ID_HEADER.lower()
                           for k, _ in extra):
                    extra.append((REQUEST_ID_HEADER, rid))
                ctype = dict((k.lower(), v) for k, v in lease.headers).get(
                    "content-type", "application/json")
                self._send(lease.status, lease.body, content_type=ctype,
                           extra_headers=[
                               (k, v) for k, v in extra
                               if k.lower() != "content-type"])
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away; replica finishes server-side
        finally:
            lease.close()

    def _resolve_slo(self, body: bytes) -> str:
        """The request's SLO class, resolved ONCE at the router: the
        model's class (from the path) is the default; the request may
        demote itself via X-PT-SLO-Class or the body "slo" field.
        Raises ValueError on an unknown class name (400)."""
        model = "default"
        for route in ("/predict/", "/generate/"):
            if self.path.startswith(route):
                model = self.path[len(route):]
                break
        requested = self.headers.get(SLO_HEADER)
        if not requested and b'"slo"' in body:
            try:
                requested = json.loads(body).get("slo")
            except (ValueError, AttributeError):
                requested = None
        return resolve_class(
            self.server.router.slo_policy.class_of(model), requested)

    def _admin_rollout(self) -> None:
        """POST /admin/rollout {"model_dir": ..., "model": opt}: run a
        zero-downtime rollout of the artifact at model_dir through the
        attached fleet (cli `paddle_tpu fleetctl rollout` calls this).
        Blocking — the reply is the rollout report."""
        fleet = self.server.fleet
        if fleet is None:
            self._error(501, "no fleet attached to this router "
                             "(serve --replicas builds one)")
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
            req = json.loads(self.rfile.read(length) or b"{}")
            model_dir = req["model_dir"]
        except (ValueError, KeyError, TypeError) as e:
            self._error(400, f"bad request: {e}")
            return
        from ..fleetctl.rollout import RolloutError, RolloutManager

        try:
            report = RolloutManager(fleet).rollout(
                model_dir, model=req.get("model", "default"))
        except RolloutError as e:
            self._error(409, str(e))
            return
        except Exception as e:
            self._error(500, f"{type(e).__name__}: {e}")
            return
        self._send(200, report)

    def _relay_stream(self, lease: _Lease, rid: str) -> None:
        """Chunked NDJSON pass-through, one line per chunk. A replica
        dying MID-STREAM cannot be failed over (the client already has
        bytes): emit a terminal retryable {"event": "error"} line and
        feed the replica's breaker."""
        self.send_response(lease.status)
        ctype = dict((k.lower(), v) for k, v in lease.headers).get(
            "content-type", "application/x-ndjson")
        self.send_header("Content-Type", ctype)
        self.send_header("Transfer-Encoding", "chunked")
        self.send_header(REQUEST_ID_HEADER, rid)
        self.end_headers()
        replica = lease.replica
        try:
            while True:
                try:
                    line = lease.resp.readline()
                except (OSError, http.client.HTTPException) as e:
                    replica.breaker.record_failure()
                    self.server.router.registry.counter_inc(
                        "pt_router_failed_over_total",
                        labels={"replica": replica.name})
                    err = json.dumps({
                        "event": "error",
                        "error": f"replica {replica.name} lost "
                                 f"mid-stream ({type(e).__name__}); "
                                 "retry the request",
                        "kind": "ReplicaLostError",
                        "retryable": True,
                    })
                    self._write_chunk(err.encode() + b"\n")
                    break
                if not line:
                    break
                self._write_chunk(line)
            self._write_chunk(b"")
        except (BrokenPipeError, ConnectionResetError):
            pass  # client went away mid-stream

    def _write_chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")
        self.wfile.flush()


class RouterServer(ThreadingHTTPServer):
    daemon_threads = True

    def __init__(self, addr, router: Router,
                 fleet: Optional["Fleet"] = None,
                 autoscaler=None, disagg=None):
        super().__init__(addr, _RouterHandler)
        self.router = router
        # control-plane attachments (cli _serve_fleet wires these): the
        # fleet enables /admin/rollout; the autoscaler reports through
        # /admin/fleet; a DisaggDispatcher phase-splits /generate
        self.fleet = fleet
        self.autoscaler = autoscaler
        self.disagg = disagg

    def admin_fleet(self) -> Dict[str, Any]:
        """GET /admin/fleet: one control-plane status document —
        router health, fleet size/warm-pool state, autoscaler stats."""
        out: Dict[str, Any] = {"router": self.router.health()}
        if self.fleet is not None:
            out["fleet"] = self.fleet.describe()
        if self.autoscaler is not None:
            out["autoscaler"] = self.autoscaler.stats()
        return out

    @property
    def port(self) -> int:
        return self.server_address[1]

    def serve_background(self) -> threading.Thread:
        self.router.start()
        t = threading.Thread(target=self.serve_forever,
                             name="ptrouter-http", daemon=True)
        t.start()
        return t


def make_router_server(router: Router, host: str = "127.0.0.1",
                       port: int = 0, fleet: Optional["Fleet"] = None,
                       autoscaler=None, disagg=None) -> RouterServer:
    """Bind (port 0 = OS-assigned; read `server.port`)."""
    return RouterServer((host, port), router, fleet=fleet,
                        autoscaler=autoscaler, disagg=disagg)


# -- replica processes + warm pool -------------------------------------------

_URL_RE = re.compile(r"serving .* on (http://[\w.\-]+:\d+)")


class ReplicaProcess:
    """One `python -m paddle_tpu serve` subprocess. The replica binds
    port 0 and prints its URL; `wait_ready()` parses it from stdout and
    then blocks until /healthz answers, so a 'ready' replica is warmed
    and immediately routable."""

    def __init__(self, model_args: Sequence[str], host: str = "127.0.0.1",
                 extra_args: Sequence[str] = (),
                 env: Optional[Dict[str, str]] = None,
                 name: Optional[str] = None):
        self.name = name
        argv = [sys.executable, "-m", "paddle_tpu", "serve",
                *model_args, "--host", host, "--port", "0", *extra_args]
        self.argv = argv
        self.proc = subprocess.Popen(
            argv, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            env=env, text=True)
        self.url: Optional[str] = None
        self._lines: "collections.deque[str]" = collections.deque(
            maxlen=200)
        self._url_event = threading.Event()
        self._drain = threading.Thread(target=self._drain_stdout,
                                       daemon=True)
        self._drain.start()

    def _drain_stdout(self) -> None:
        # the pipe must keep draining for the replica's whole life or a
        # chatty child blocks on a full pipe; keep a ring of lines for
        # failure diagnosis
        for line in self.proc.stdout:
            self._lines.append(line.rstrip("\n"))
            if self.url is None:
                m = _URL_RE.search(line)
                if m:
                    self.url = m.group(1)
                    self._url_event.set()
        self._url_event.set()  # EOF: wake waiters (spawn failed)

    def wait_ready(self, timeout: float = 120.0) -> str:
        """Block until the replica printed its URL and /healthz
        answers. Raises RuntimeError (with the captured output tail) if
        the process died or the timeout passed first."""
        deadline = time.monotonic() + timeout
        self._url_event.wait(timeout=timeout)
        if self.url is None:
            raise RuntimeError(
                f"replica {self.name or self.argv} did not report a URL "
                f"(exit={self.proc.poll()}):\n" + "\n".join(self._lines))
        while time.monotonic() < deadline:
            if self.proc.poll() is not None:
                raise RuntimeError(
                    f"replica {self.name or self.url} exited "
                    f"{self.proc.returncode} before ready:\n"
                    + "\n".join(self._lines))
            try:
                with urllib.request.urlopen(self.url + "/healthz",
                                            timeout=2.0) as f:
                    if f.status == 200:
                        return self.url
            except Exception:
                time.sleep(0.05)
        raise RuntimeError(f"replica {self.name or self.url} not "
                           f"healthy within {timeout}s")

    def poll(self) -> Optional[int]:
        return self.proc.poll()

    def kill(self) -> None:
        """SIGKILL — the chaos-test death: no drain, no goodbye."""
        if self.proc.poll() is None:
            self.proc.send_signal(signal.SIGKILL)

    def terminate(self) -> None:
        """SIGTERM — the graceful death: the replica drains in-flight
        generation streams before exiting (cli serve's handler)."""
        if self.proc.poll() is None:
            self.proc.terminate()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        try:
            return self.proc.wait(timeout=timeout)
        except subprocess.TimeoutExpired:
            return None

    def output_tail(self, n: int = 40) -> str:
        return "\n".join(list(self._lines)[-n:])


class WarmPool:
    """Pre-forked standby replicas. `spawn_fn()` returns a
    ReplicaProcess; the filler thread keeps `standby` of them spawned,
    warmed, and /healthz-ready so `take()` is promotion, not a cold
    start — the warm-pool half of the traffic-spike/failover story."""

    def __init__(self, spawn_fn, standby: int = 1,
                 ready_timeout_s: float = 180.0):
        self.spawn_fn = spawn_fn
        self.standby = standby
        self.ready_timeout_s = ready_timeout_s
        self._cond = threading.Condition()
        self._ready: List[ReplicaProcess] = []
        self._stopping = False
        self._filler: Optional[threading.Thread] = None
        self.spawned_total = 0
        self.spawn_failures = 0

    def start(self) -> "WarmPool":
        with self._cond:
            if self._filler is not None and self._filler.is_alive():
                return self
            self._stopping = False
            self._filler = threading.Thread(
                target=self._fill_loop, name="ptrouter-warmpool",
                daemon=True)
            self._filler.start()
        return self

    def _fill_loop(self) -> None:
        while True:
            with self._cond:
                if self._stopping:
                    return
                # drop standbys that died while parked
                self._ready = [p for p in self._ready
                               if p.poll() is None]
                need = self.standby - len(self._ready)
                if need <= 0:
                    self._cond.wait(timeout=0.25)
                    continue
            try:
                p = self.spawn_fn()
                p.wait_ready(timeout=self.ready_timeout_s)
            except Exception:
                self.spawn_failures += 1
                time.sleep(0.5)  # don't hot-loop a broken spawner
                continue
            with self._cond:
                if self._stopping:
                    p.kill()
                    return
                self._ready.append(p)
                self.spawned_total += 1
                self._cond.notify_all()

    def ready_count(self) -> int:
        with self._cond:
            return len(self._ready)

    def take(self, timeout: float = 0.0) -> Optional[ReplicaProcess]:
        """A ready standby (None if none within `timeout`); taking one
        wakes the filler to spawn its replacement."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while True:
                self._ready = [p for p in self._ready if p.poll() is None]
                if self._ready:
                    p = self._ready.pop(0)
                    self._cond.notify_all()
                    return p
                rest = deadline - time.monotonic()
                if rest <= 0 or self._stopping:
                    return None
                self._cond.wait(timeout=rest)

    def stop(self) -> None:
        with self._cond:
            self._stopping = True
            parked = list(self._ready)
            self._ready.clear()
            self._cond.notify_all()
        for p in parked:
            p.kill()
        if self._filler is not None:
            self._filler.join(timeout=5.0)


class Fleet:
    """N managed replicas behind one Router, with warm-pool
    replacement: a supervisor loop notices a dead replica process,
    trips its router breaker (no threshold wait — the process table IS
    proof), removes it, and promotes a warmed standby. `cli serve
    --replicas N [--standby K]` builds one of these."""

    def __init__(self, spawn_fn, replicas: int = 2, standby: int = 0,
                 router: Optional[Router] = None,
                 supervise_interval_s: float = 0.25,
                 ready_timeout_s: float = 180.0):
        if replicas < 1:
            raise ValueError(f"replicas must be >= 1, got {replicas}")
        self.spawn_fn = spawn_fn
        self.n_replicas = replicas
        self.ready_timeout_s = ready_timeout_s
        self.supervise_interval_s = supervise_interval_s
        self.router = router or Router()
        self.warm = WarmPool(spawn_fn, standby=standby,
                             ready_timeout_s=ready_timeout_s) \
            if standby > 0 else None
        self._procs: Dict[str, ReplicaProcess] = {}
        # deliberately-retiring replicas: moved OUT of _procs (so the
        # supervisor never mistakes the coming exit for a death and
        # promotes a standby against the scale-down) and held here
        # until drained + reaped
        self._retiring: Dict[str, ReplicaProcess] = {}
        self._scale_lock = threading.Lock()
        self._super: Optional[threading.Thread] = None
        self._stop_event = threading.Event()
        self.replaced_total = 0
        self.retired_total = 0
        # rollout hook (cli _serve_fleet sets this): model_dir → a
        # spawn_fn producing replicas that serve THAT artifact with
        # this fleet's serve flags; fleetctl/rollout.py uses it to warm
        # the new version and to repoint standby respawns after a flip
        self.spawn_template = None

    def start(self) -> "Fleet":
        # spawn the whole rotation CONCURRENTLY (model load + warmup
        # dominate), then register each as it turns ready
        procs = [self.spawn_fn() for _ in range(self.n_replicas)]
        for p in procs:
            p.wait_ready(timeout=self.ready_timeout_s)
            self._register(p)
        if self.warm is not None:
            self.warm.start()
        self.router.start()
        self._stop_event.clear()
        self._super = threading.Thread(target=self._supervise,
                                       name="ptrouter-fleet",
                                       daemon=True)
        self._super.start()
        return self

    def _register(self, p: ReplicaProcess) -> ReplicaClient:
        r = self.router.add_replica(p.url, process=p)
        p.name = r.name
        self._procs[r.name] = p
        return r

    def _supervise(self) -> None:
        while not self._stop_event.wait(self.supervise_interval_s):
            for name, p in list(self._procs.items()):
                if p.poll() is None:
                    continue
                # process is DEAD: trip + remove, then promote a warm
                # standby if one is ready (never block the supervisor
                # on a spawn — the filler replaces in the background)
                dead = self.router.remove_replica(name)
                if dead is not None:
                    dead.breaker.trip()
                self._procs.pop(name, None)
                if self.warm is not None:
                    repl = self.warm.take(timeout=0.0)
                    if repl is not None:
                        self._register(repl)
                        self.replaced_total += 1

    # -- elastic capacity (the autoscaler's actuators) ------------------
    def size(self) -> int:
        return len(self._procs)

    def describe(self) -> Dict[str, Any]:
        return {
            "replicas": len(self._procs),
            "retiring": sorted(self._retiring),
            "warm_ready": (self.warm.ready_count()
                           if self.warm is not None else 0),
            "standby": (self.warm.standby
                        if self.warm is not None else 0),
            "replaced_total": self.replaced_total,
            "retired_total": self.retired_total,
        }

    def scale_up(self, n: int = 1) -> List[str]:
        """Promote up to `n` warm standbys into the rotation. NON-
        blocking: only already-/healthz-ready standbys are taken (the
        warm pool's filler replaces them in the background), so an
        autoscaler tick never waits out a cold model load. Returns the
        names registered."""
        names: List[str] = []
        if self.warm is None:
            return names
        with self._scale_lock:
            for _ in range(n):
                p = self.warm.take(timeout=0.0)
                if p is None:
                    break
                names.append(self._register(p).name)
        return names

    def scale_down(self, n: int = 1,
                   drain_timeout_s: float = 30.0) -> List[str]:
        """Retire the `n` least-loaded replicas: mark them draining
        (immediately invisible to pick()), then drain + remove +
        SIGTERM in a background thread — the caller (an autoscaler
        tick) never blocks on the drain. At least one replica always
        survives. Returns the names being retired."""
        with self._scale_lock:
            candidates = [
                r for r in self.router.replicas()
                if not r.draining and r.name in self._procs
            ]
            candidates.sort(key=lambda r: r.score())
            n = min(n, len(self._procs) - 1)
            victims = [r.name for r in candidates[:max(0, n)]]
            for name in victims:
                self.router.set_draining(name)
                self._retiring[name] = self._procs.pop(name)
        if victims:
            threading.Thread(
                target=self._drain_and_retire,
                args=(victims, drain_timeout_s),
                name="ptrouter-retire", daemon=True).start()
        return victims

    def retire(self, names: Sequence[str],
               drain_timeout_s: float = 30.0) -> None:
        """Synchronously drain + remove + terminate the named replicas
        (the rollout's old-version drain). The names must already be
        draining (router.flip / set_draining) — this moves their
        processes out of supervision and reaps them."""
        with self._scale_lock:
            for name in names:
                if name in self._procs:
                    self.router.set_draining(name)
                    self._retiring[name] = self._procs.pop(name)
        self._drain_and_retire(list(names), drain_timeout_s)

    def _drain_and_retire(self, names: Sequence[str],
                          drain_timeout_s: float) -> None:
        """Wait (bounded) until each named replica reports an empty
        queue and has no router-tracked in-flight work, then remove it
        WITH series retirement and terminate its process. In-flight
        streams run to 'done' — SIGTERM only lands after the router
        sees zero in-flight, and cli serve's handler drains anyway."""
        deadline = time.monotonic() + drain_timeout_s
        clients = {r.name: r for r in self.router.replicas()}
        for name in names:
            r = clients.get(name)
            while r is not None and time.monotonic() < deadline:
                if (r.inflight == 0
                        and not r.snapshot.get("queue_depth", 0)):
                    break
                time.sleep(0.02)
            self.router.remove_replica(name, retire_series=True)
            p = self._retiring.pop(name, None)
            if p is not None:
                p.terminate()
                if p.wait(timeout=max(5.0,
                                      deadline - time.monotonic())) \
                        is None:
                    p.kill()
            self.retired_total += 1

    def set_spawn_fn(self, spawn_fn) -> None:
        """Repoint replica creation (rollout cutover): future warm-pool
        standbys and supervisor replacements spawn the NEW version."""
        self.spawn_fn = spawn_fn
        if self.warm is not None:
            self.warm.spawn_fn = spawn_fn

    def adopt(self, p: ReplicaProcess) -> ReplicaClient:
        """Register an externally spawned, already-ready replica into
        the rotation + supervision (rollout warms new-version replicas
        before the router ever sees them)."""
        return self._register(p)

    def stop(self, graceful: bool = False) -> None:
        self._stop_event.set()
        if self._super is not None:
            self._super.join(timeout=5.0)
        if self.warm is not None:
            self.warm.stop()
        self.router.close()
        procs = list(self._procs.values()) + list(self._retiring.values())
        for p in procs:
            (p.terminate if graceful else p.kill)()
        for p in procs:
            if p.wait(timeout=30.0 if graceful else 10.0) is None:
                p.kill()
        self._procs.clear()
        self._retiring.clear()


def replica_spawner(model_args: Sequence[str], host: str = "127.0.0.1",
                    extra_args: Sequence[str] = (),
                    env: Optional[Dict[str, str]] = None):
    """A spawn_fn for Fleet/WarmPool over `cli serve` argv fragments
    (e.g. model_args=["--model_dir", d]). The child inherits (a copy
    of) this process's environment unless `env` overrides it."""
    base_env = dict(os.environ if env is None else env)

    def spawn() -> ReplicaProcess:
        return ReplicaProcess(model_args, host=host,
                              extra_args=extra_args, env=base_env)

    return spawn
