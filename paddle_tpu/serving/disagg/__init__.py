"""Disaggregated prefill/decode serving (phase-specialized replicas).

The reference framework's signature distributed move is the
DistributeTranspiler: rewrite ONE program into role-specialized
sub-programs (trainer/pserver) that exchange state over send/recv. This
package is the serving analog. Generation has two phases with opposite
rooflines — the encoder PREFIX is compute-bound (wants big mesh-sharded
batches through the engine's shape buckets), the token DECODE loop is
bandwidth-bound (wants the dense device-resident slot pool) — so a
monolithic replica is mis-provisioned for one of them at any instant.

Disaggregation splits the fleet into two replica CLASSES running the
SAME artifact and the SAME server binary:

- a **prefill replica** answers POST /prefill: runs the bucketed prefix
  program (ContinuousScheduler.prefill — no decode pool is ever
  allocated) and returns the request's boot state as a serialized
  handoff payload (handoff.py; optional int8 packing ~2x);
- a **decode replica** answers POST /admit: validates the payload's
  DecodeState schema fingerprint, restores the rows onto its own
  devices (pipeline/elastic.restore_handoff_rows) and admits them
  through the SAME jitted pool_admit dynamic-update a local prefix
  uses — bit-identity with monolithic serving is structural, not
  tested-into-existence;
- the **DisaggDispatcher** (router-side) gives requests phases: JSQ
  picks a prefill replica on queue depth/compute backlog, then PINS a
  decode replica on free slots at prefill completion, ships the payload
  and relays the token stream through the existing chunked-NDJSON
  pass-through. Decode death after handoff → the payload retries on
  another decode replica; when none remains, ONE breaker-gated
  re-prefill elsewhere before the retryable 503.

Fleet-wise, DisaggFleet makes WarmPool standbys promotable into EITHER
class (deficit-based assignment vs per-class targets) and PhaseFleet
adapts each class for its own stock Autoscaler — prefill scaling on
queue age, decode on slot occupancy.
"""

from .handoff import (HandoffError, HandoffSchemaError, pack_handoff,
                      payload_schema, unpack_handoff, validate_handoff)
from .dispatch import DisaggDispatcher
from .fleet import DisaggFleet, PhaseFleet, make_phase_autoscalers

__all__ = [
    "HandoffError",
    "HandoffSchemaError",
    "pack_handoff",
    "unpack_handoff",
    "payload_schema",
    "validate_handoff",
    "DisaggDispatcher",
    "DisaggFleet",
    "PhaseFleet",
    "make_phase_autoscalers",
]
