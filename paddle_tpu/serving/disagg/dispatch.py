"""DisaggDispatcher: the router-side phase split of /generate.

A monolithic /generate routes once. Disaggregated, one request crosses
the fleet twice, and this dispatcher is the seam: JSQ-pick a PREFILL
replica (scored on queue depth + compute backlog), POST the original
request to its /prefill, take the handoff payload it returns, then PIN
a DECODE replica (scored on free slots) and POST the payload to its
/admit — whose response (buffered JSON or chunked NDJSON token stream)
is returned as an ordinary router _Lease for the existing pass-through
relay. The router handler cannot tell a disagg lease from a monolithic
one; streaming, request-id propagation and mid-stream death semantics
are all inherited.

Failure semantics (ISSUE 18): the decode-side dispatch already retries
the SAME payload on the next-best decode replica (Router.dispatch
failover — the payload is bytes, nothing is consumed by a dead TCP
connection). Only when the whole decode class refuses (NoReplicaError:
every breaker open / every replica draining, or a unanimous shed) does
the dispatcher spend ONE re-prefill on a DIFFERENT prefill replica —
breaker-gated like every pick — before relaying the retryable 503.
Mid-stream decode death is the client's retry (the relay's terminal
ReplicaLostError line), exactly as monolithic serving.

The phase-pick path (`generate` up to the first dispatch call) is
AST-linted against blocking I/O the same way Router.pick is: every
network round-trip happens inside Router.dispatch, never while
choosing where to send the request.
"""

from __future__ import annotations

import json
import time
from typing import Optional

from ...obs import trace as obs_trace
from ..metrics import HANDOFF_BUCKETS
from ..router import NoReplicaError

__all__ = ["DisaggDispatcher"]


class DisaggDispatcher:
    """Phase-split /generate over a phase-classed Router.

    `quant` ("int8") asks prefill replicas to pack float state per-row
    symmetric int8 (~2x payload cut); `max_reprefills` bounds the
    re-prefill recovery after a decode-class failure."""

    def __init__(self, router, quant: Optional[str] = None,
                 max_reprefills: int = 1):
        if quant not in (None, "int8"):
            raise ValueError(
                f"unsupported handoff quant {quant!r} (only 'int8')")
        self.router = router
        self.quant = quant
        self.max_reprefills = max_reprefills
        self.registry = router.registry
        for name, help in (
            ("pt_handoff_total",
             "prefill→decode handoffs admitted by a decode replica"),
            ("pt_handoff_bytes_total",
             "handoff payload bytes shipped prefill→decode"),
            ("pt_disagg_reprefills_total",
             "re-prefills on another replica after the decode class "
             "refused a payload"),
        ):
            self.registry.declare_counter(name, help=help)
        self._handoff_s = self.registry.histogram(
            "pt_handoff_seconds", buckets=HANDOFF_BUCKETS,
            help="prefill-completion to decode-admission transfer time")

    # -- the phase-pick + ship path (NO blocking I/O outside
    #    Router.dispatch — AST-linted like Router.pick) ------------------
    def generate(self, path: str, body: bytes,
                 request_id: Optional[str] = None,
                 slo: Optional[str] = None):
        """Serve one /generate request through the two phases; returns
        the decode-side _Lease (relay + close() belong to the caller).
        Raises NoReplicaError only when neither phase can make
        progress."""
        model = "default"
        if path.startswith("/generate/"):
            model = path[len("/generate/"):]
        # one parse to learn the stream/timeout options (they travel in
        # the /admit query string — the admit body is opaque payload
        # bytes) and to stamp the quant ask; an unparsable body is
        # forwarded as-is and the prefill replica's 400 relayed
        pf_body = body
        stream = False
        timeout_ms = None
        try:
            req = json.loads(body or b"{}")
            stream = bool(req.get("stream"))
            timeout_ms = req.get("timeout_ms")
            if self.quant:
                req["handoff_quant"] = self.quant
            pf_body = json.dumps(req).encode()
        except (ValueError, AttributeError):
            pass

        pf = self.router.dispatch(
            "/prefill/" + model, pf_body, request_id=request_id,
            slo=slo, phase="prefill")
        if pf.status != 200:
            return pf  # shed/4xx relayed verbatim (carries Retry-After)
        payload = pf.body
        used_prefill = pf.replica.name
        pf.close()

        qs = []
        if stream:
            qs.append("stream=1")
        if timeout_ms is not None:
            qs.append(f"timeout_ms={int(timeout_ms)}")
        admit_path = ("/admit/" + model
                      + ("?" + "&".join(qs) if qs else ""))
        octet = {"Content-Type": "application/octet-stream"}

        reprefills = 0
        while True:
            t0 = time.monotonic()
            self.registry.counter_inc("pt_handoff_bytes_total",
                                      by=float(len(payload)))
            lease = None
            try:
                with obs_trace.span("disagg.handoff", cat="disagg",
                                    model=model, request_id=request_id,
                                    bytes=len(payload)):
                    # internal failover retries the SAME payload on the
                    # next-best decode replica; only class-wide refusal
                    # falls out of this call
                    lease = self.router.dispatch(
                        admit_path, payload, request_id=request_id,
                        headers=octet, slo=slo, phase="decode")
            except NoReplicaError:
                pass
            if lease is not None and lease.status != 503:
                self._handoff_s.observe(time.monotonic() - t0)
                self.registry.counter_inc("pt_handoff_total")
                return lease
            # the decode class refused the payload wholesale: ONE
            # breaker-gated re-prefill on a DIFFERENT prefill replica
            # (a fresh payload + fresh picks), then the retryable 503
            if reprefills >= self.max_reprefills:
                if lease is not None:
                    return lease  # the unanimous shed's own 503
                raise NoReplicaError(
                    f"no decode replica admitted the handoff for "
                    f"{path} after {reprefills} re-prefill(s); "
                    f"retry later")
            reprefills += 1
            if lease is not None:
                lease.close()
            self.registry.counter_inc("pt_disagg_reprefills_total")
            if obs_trace._armed:
                obs_trace.instant(
                    "disagg.reprefill", cat="disagg", model=model,
                    request_id=request_id, excluded=used_prefill)
            pf = self.router.dispatch(
                "/prefill/" + model, pf_body, request_id=request_id,
                slo=slo, phase="prefill", exclude=(used_prefill,))
            if pf.status != 200:
                return pf
            payload = pf.body
            used_prefill = pf.replica.name
            pf.close()

    def stats(self):
        reg = self.registry
        return {
            "quant": self.quant,
            "handoffs_total": reg.counter_value("pt_handoff_total"),
            "handoff_bytes_total": reg.counter_value(
                "pt_handoff_bytes_total"),
            "reprefills_total": reg.counter_value(
                "pt_disagg_reprefills_total"),
        }
