"""Handoff payload: a request's decode boot state on the wire.

The payload is the send/recv edge of the disaggregated topology — what
the DistributeTranspiler's send/recv ops are to trainer/pserver, this
format is to prefill/decode. A prefill replica serializes the HOST-side
boot state tuples that `ContinuousScheduler.prefill` gathered (one d2h
fence, mesh outputs already all-gathered), the dispatcher ships the
bytes, and a decode replica validates + unpacks them into
`submit_handoff`, which re-places rows onto its own devices. The state
never round-trips through a re-run of the prefix program, so monolithic
bit-identity holds by construction.

Layout: `b"PTHO1" | u32 header_len | header JSON | raw buffers`, buffers
concatenated in header order (boots then per-example rows, each
optionally followed by its per-row scale vector). The header carries the
artifact's DecodeState schema identity (io.generation_state_fingerprint)
so a mixed-version fleet mid-rollout fails at the /admit boundary with a
typed error naming the fix — never as a shape crash inside the pool.

int8 packing reuses the quant/ per-tensor-symmetric recipe at per-ROW
granularity, exactly the scheduler's `q_rows` arithmetic (absmax/127
scale, round + clip; dequant is `q * scale` in f32 then cast): transfer
bytes drop ~2x for float32 state (4x per float tensor, minus the scale
vector and any raw-shipped integer state). Non-float state tensors ride
raw — quantizing token ids would corrupt them, and they are small.
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Sequence, Tuple

import numpy as np

__all__ = ["HandoffError", "HandoffSchemaError", "MAGIC", "pack_handoff",
           "payload_schema", "unpack_handoff", "validate_handoff"]

MAGIC = b"PTHO1"
_LEN = struct.Struct(">I")

# one-command fix named by every schema rejection: roll the whole fleet
# to a single artifact version (warm + verify + flip + drain)
_ROLLOUT_CMD = ("paddle_tpu fleetctl rollout --router <url> "
                "--model_dir <new artifact>")


class HandoffError(ValueError):
    """A handoff payload is malformed (bad magic, truncated buffers,
    unknown quant mode) — the bytes themselves are unusable."""


class HandoffSchemaError(HandoffError):
    """The payload is well-formed but its DecodeState schema identity
    does not match the admitting artifact: the prefill and decode
    replicas are serving different decode-state layouts (mixed-version
    fleet mid-rollout). Rejected at the /admit boundary — before any
    state touches the pool — with the fix in the message."""


def payload_schema(gen_meta: Dict[str, Any]) -> Dict[str, Any]:
    """The schema identity block a replica stamps on payloads it emits
    and checks on payloads it admits, from the artifact's generation
    sidecar (io.load_inference_model backfills the fingerprint for
    pre-disagg artifacts, so this never returns an empty identity)."""
    from ... import io as pt_io

    if not gen_meta:
        raise HandoffError(
            "model has no generation sidecar — disagg handoff serves "
            "generation models only")
    return {
        "schema_version": int(gen_meta.get(
            "schema_version", pt_io.GENERATION_SCHEMA_VERSION)),
        "state_fingerprint": (
            gen_meta.get("state_fingerprint")
            or pt_io.generation_state_fingerprint(gen_meta)),
    }


def _dtype(name: str) -> np.dtype:
    try:
        return np.dtype(name)
    except TypeError:
        import ml_dtypes  # registered by jax; bfloat16 et al.

        return np.dtype(getattr(ml_dtypes, name))


def _quantizable(a: np.ndarray) -> bool:
    k = np.dtype(a.dtype).kind
    return k == "f" or np.dtype(a.dtype).name in (
        "bfloat16", "float8_e4m3fn", "float8_e5m2")


def _pack_group(arrays: Sequence[np.ndarray], quant: Optional[str],
                specs: list, chunks: list) -> None:
    from ...ops.quant_kernels import INT8_MAX

    for a in arrays:
        a = np.ascontiguousarray(a)
        spec = {"dtype": np.dtype(a.dtype).name,
                "shape": [int(d) for d in a.shape]}
        if quant == "int8" and _quantizable(a):
            n = a.shape[0]
            xf = a.astype(np.float32)
            absmax = np.max(np.abs(xf.reshape(n, -1)), axis=1) \
                if a.size else np.zeros((n,), np.float32)
            # the scheduler q_rows recipe, per ROW: absmax/127 scale,
            # round + clip (np.round is round-half-even, same as jnp)
            scale = (np.maximum(absmax, 1e-30) / INT8_MAX).astype(
                np.float32)
            q = np.clip(
                np.round(xf / scale.reshape((n,) + (1,) * (a.ndim - 1))),
                -INT8_MAX, INT8_MAX).astype(np.int8)
            spec["q"] = True
            chunks.append(q.tobytes())
            chunks.append(np.ascontiguousarray(scale).tobytes())
        else:
            spec["q"] = False
            chunks.append(a.tobytes())
        specs.append(spec)


def pack_handoff(boots: Sequence[np.ndarray], pes: Sequence[np.ndarray],
                 schema: Dict[str, Any], model: str,
                 request_id: Optional[str] = None,
                 quant: Optional[str] = None) -> bytes:
    """Serialize one request's boot state (host arrays [n, ...]) into a
    self-describing payload. `schema` is payload_schema(...) of the
    EMITTING artifact; `quant="int8"` packs float tensors per-row
    symmetric int8 (+f32 scale vector each)."""
    if quant not in (None, "int8"):
        raise HandoffError(
            f"unsupported handoff quant {quant!r} (only 'int8')")
    boots, pes = tuple(boots), tuple(pes)
    rows = {int(a.shape[0]) for a in boots + pes}
    if len(rows) != 1:
        raise HandoffError(
            f"handoff state arrays must share the row axis; got row "
            f"counts {sorted(rows)}")
    specs_b: list = []
    specs_p: list = []
    chunks: list = []
    _pack_group(boots, quant, specs_b, chunks)
    _pack_group(pes, quant, specs_p, chunks)
    header = {
        "version": 1,
        "model": model,
        "request_id": request_id,
        "rows": rows.pop(),
        "quant": quant,
        "schema_version": int(schema["schema_version"]),
        "state_fingerprint": str(schema["state_fingerprint"]),
        "boots": specs_b,
        "pes": specs_p,
    }
    hdr = json.dumps(header, sort_keys=True,
                     separators=(",", ":")).encode()
    return b"".join([MAGIC, _LEN.pack(len(hdr)), hdr] + chunks)


def _unpack_group(specs: list, data: bytes, off: int):
    out = []
    for spec in specs:
        dt = _dtype(spec["dtype"])
        shape = tuple(spec["shape"])
        if spec.get("q"):
            n = int(np.prod(shape, dtype=np.int64))
            q = np.frombuffer(data, np.int8, count=n, offset=off)
            off += n
            rows = shape[0] if shape else 0
            scale = np.frombuffer(data, np.float32, count=rows,
                                  offset=off)
            off += scale.nbytes
            q = q.reshape(shape)
            sc = scale.reshape((rows,) + (1,) * (len(shape) - 1))
            # dequant mirrors pool_admit_q: q*scale in f32, then cast
            out.append((q.astype(np.float32) * sc).astype(dt))
        else:
            n = int(np.prod(shape, dtype=np.int64))
            a = np.frombuffer(data, dt, count=n, offset=off)
            off += a.nbytes
            out.append(a.reshape(shape))
    return tuple(out), off


def unpack_handoff(data: bytes) -> Tuple[Dict[str, Any], tuple, tuple]:
    """Parse a payload into (header, boots, pes) host arrays, int8
    tensors already dequantized. Raises HandoffError on malformed
    bytes; schema acceptance is the caller's validate_handoff call."""
    if not data.startswith(MAGIC):
        raise HandoffError(
            "not a handoff payload (bad magic) — /admit takes the bytes "
            "a /prefill call returned, verbatim")
    try:
        (hlen,) = _LEN.unpack_from(data, len(MAGIC))
        off = len(MAGIC) + _LEN.size
        header = json.loads(data[off:off + hlen].decode())
        off += hlen
        boots, off = _unpack_group(header["boots"], data, off)
        pes, off = _unpack_group(header["pes"], data, off)
    except HandoffError:
        raise
    except Exception as e:
        raise HandoffError(
            f"truncated or corrupt handoff payload "
            f"({type(e).__name__}: {e})") from e
    if off != len(data):
        raise HandoffError(
            f"handoff payload has {len(data) - off} trailing bytes — "
            "truncated header or mismatched buffer specs")
    return header, boots, pes


def validate_handoff(header: Dict[str, Any],
                     gen_meta: Dict[str, Any]) -> None:
    """Admission gate: the payload's DecodeState schema identity must
    match the ADMITTING artifact's. Runs before any array is even
    unpacked into the pool, so a mixed-version fleet fails loudly with
    the one-command fix instead of a shape crash mid-pool."""
    want = payload_schema(gen_meta)
    got_v = header.get("schema_version")
    got_fp = header.get("state_fingerprint")
    if got_v != want["schema_version"]:
        raise HandoffSchemaError(
            f"handoff schema version {got_v} != this replica's "
            f"{want['schema_version']}: prefill and decode replicas "
            f"disagree on the DecodeState wire format — roll the whole "
            f"fleet to one version: {_ROLLOUT_CMD}")
    if got_fp != want["state_fingerprint"]:
        raise HandoffSchemaError(
            f"handoff state fingerprint {got_fp} != this replica's "
            f"{want['state_fingerprint']}: the prefill replica serves a "
            f"different decode-state layout (mixed artifact versions "
            f"mid-rollout?) — roll the whole fleet to one artifact: "
            f"{_ROLLOUT_CMD}")
