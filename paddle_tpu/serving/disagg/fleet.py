"""DisaggFleet: one warm pool, two replica classes, two control loops.

The fleet layer's piece of disaggregation is CLASS MEMBERSHIP, not
process shape: every replica is the same `serve` binary (it answers
/generate, /prefill and /admit alike), so a WarmPool standby is
promotable into EITHER class and the class is assigned at router
registration time. Assignment is deficit-based against per-class
targets — when the supervisor replaces a dead prefill replica, the
prefill class is the one short a member, so the promoted standby lands
there; an autoscaler's targeted scale_up bumps its class's target and
registers into it explicitly.

Each class then gets its OWN stock Autoscaler via the PhaseFleet
adapter: the prefill loop sees only prefill replicas (its pressure is
queue depth/age — compute backlog), the decode loop only decode
replicas (its pressure is slot occupancy). Neither loop knows disagg
exists; `family=` keeps their metric families apart
(pt_autoscale_prefill_*, pt_autoscale_decode_*).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ...fleetctl.autoscaler import Autoscaler, AutoscalerConfig
from ..router import (Fleet, ReplicaClient, ReplicaProcess, Router)

__all__ = ["DisaggFleet", "PhaseFleet", "PhaseAutoscalers",
           "make_phase_autoscalers"]

PHASES = ("prefill", "decode")


class DisaggFleet(Fleet):
    """A Fleet whose rotation is split into prefill/decode classes."""

    def __init__(self, spawn_fn, prefill_replicas: int = 1,
                 decode_replicas: int = 1, standby: int = 0,
                 router: Optional[Router] = None, **kw):
        if prefill_replicas < 1 or decode_replicas < 1:
            raise ValueError(
                f"a disagg fleet needs >= 1 replica per class, got "
                f"prefill={prefill_replicas} decode={decode_replicas}")
        # per-class DESIRED sizes; deficit assignment and the >=1
        # floors key off these, and targeted scaling moves them
        self.targets: Dict[str, int] = {"prefill": int(prefill_replicas),
                                        "decode": int(decode_replicas)}
        super().__init__(spawn_fn,
                         replicas=prefill_replicas + decode_replicas,
                         standby=standby, router=router, **kw)

    # -- class membership ----------------------------------------------
    def phase_counts(self) -> Dict[str, int]:
        """Live (non-draining, supervised) members per class."""
        counts = {ph: 0 for ph in PHASES}
        for r in self.router.replicas():
            if (r.phase in counts and not r.draining
                    and r.name in self._procs):
                counts[r.phase] += 1
        return counts

    def _register(self, p: ReplicaProcess,
                  phase: Optional[str] = None) -> ReplicaClient:
        # deficit-based assignment: a phase-agnostic standby (start(),
        # supervisor replacement) joins whichever class is furthest
        # below its target — this is what makes ONE warm pool serve
        # both classes
        if phase is None:
            counts = self.phase_counts()
            deficits = {ph: self.targets[ph] - counts[ph]
                        for ph in PHASES}
            phase = ("prefill"
                     if deficits["prefill"] > deficits["decode"]
                     else "decode")
        r = self.router.add_replica(p.url, process=p, phase=phase)
        p.name = r.name
        self._procs[r.name] = p
        return r

    def adopt(self, p: ReplicaProcess,
              phase: Optional[str] = None) -> ReplicaClient:
        return self._register(p, phase=phase)

    def describe(self) -> Dict[str, Any]:
        d = super().describe()
        d["phases"] = {ph: {"replicas": n, "target": self.targets[ph]}
                       for ph, n in self.phase_counts().items()}
        return d

    # -- per-class capacity (the phase autoscalers' actuators) ----------
    def scale_up(self, n: int = 1, phase: str = "decode") -> List[str]:
        """Promote up to `n` ready standbys INTO `phase`, bumping its
        target so a later replacement lands in the same class. Same
        non-blocking contract as Fleet.scale_up."""
        names: List[str] = []
        if self.warm is None:
            return names
        with self._scale_lock:
            for _ in range(n):
                p = self.warm.take(timeout=0.0)
                if p is None:
                    break
                self.targets[phase] += 1
                names.append(self._register(p, phase=phase).name)
        return names

    def scale_down(self, n: int = 1, drain_timeout_s: float = 30.0,
                   phase: str = "decode") -> List[str]:
        """Retire the `n` least-loaded replicas OF `phase`; at least
        one replica of each class always survives (a topology with an
        empty phase cannot serve at all)."""
        with self._scale_lock:
            candidates = [
                r for r in self.router.replicas()
                if (not r.draining and r.name in self._procs
                    and r.phase == phase)
            ]
            candidates.sort(key=lambda r: r.score())
            n = min(n, len(candidates) - 1)
            victims = [r.name for r in candidates[:max(0, n)]]
            for name in victims:
                self.targets[phase] = max(1, self.targets[phase] - 1)
                self.router.set_draining(name)
                self._retiring[name] = self._procs.pop(name)
        if victims:
            threading.Thread(
                target=self._drain_and_retire,
                args=(victims, drain_timeout_s),
                name="ptrouter-retire", daemon=True).start()
        return victims


class _PhaseRouterView:
    """The slice of a Router one phase's autoscaler reads: replicas()
    filtered to the class, same registry. Pure pass-through — the
    signal read stays AST-lint-clean."""

    def __init__(self, router: Router, phase: str):
        self._router = router
        self.phase = phase

    @property
    def registry(self):
        return self._router.registry

    def replicas(self) -> List[ReplicaClient]:
        return [r for r in self._router.replicas()
                if r.phase == self.phase]


class PhaseFleet:
    """Adapter presenting ONE class of a DisaggFleet under the stock
    Fleet actuator surface (size / scale_up / scale_down / router), so
    an unmodified Autoscaler scales a single phase."""

    def __init__(self, fleet: DisaggFleet, phase: str):
        if phase not in PHASES:
            raise ValueError(f"unknown phase {phase!r}")
        self.fleet = fleet
        self.phase = phase
        self.router = _PhaseRouterView(fleet.router, phase)

    def size(self) -> int:
        return self.fleet.phase_counts()[self.phase]

    def scale_up(self, n: int = 1) -> List[str]:
        return self.fleet.scale_up(n, phase=self.phase)

    def scale_down(self, n: int = 1,
                   drain_timeout_s: float = 30.0) -> List[str]:
        return self.fleet.scale_down(n, drain_timeout_s=drain_timeout_s,
                                     phase=self.phase)


class PhaseAutoscalers:
    """The pair of per-class control loops, under the one-autoscaler
    surface RouterServer/admin_fleet expects (start/stop/tick/stats)."""

    def __init__(self, prefill: Autoscaler, decode: Autoscaler):
        self.prefill = prefill
        self.decode = decode

    def start(self) -> "PhaseAutoscalers":
        self.prefill.start()
        self.decode.start()
        return self

    def stop(self) -> None:
        self.prefill.stop()
        self.decode.stop()

    def tick(self) -> Dict[str, Optional[str]]:
        return {"prefill": self.prefill.tick(),
                "decode": self.decode.tick()}

    def stats(self) -> Dict[str, Any]:
        return {"prefill": self.prefill.stats(),
                "decode": self.decode.stats()}


def make_phase_autoscalers(
        fleet: DisaggFleet,
        prefill_config: Optional[AutoscalerConfig] = None,
        decode_config: Optional[AutoscalerConfig] = None,
        **kw) -> PhaseAutoscalers:
    """Two stock Autoscalers over one DisaggFleet, each scaling its
    class on ITS phase's signal. Defaults encode the phase rooflines:

    - prefill pressure is COMPUTE BACKLOG — queue depth and queue age
      cross early; the occupancy signal is disabled (a prefill replica
      never fills decode slots, its occupancy is pinned at 0, which
      would otherwise read as permanently idle);
    - decode pressure is SLOT OCCUPANCY — the pool filling up is what
      degrades inter-token latency; queue-age pressure is left loose
      (handoffs clear the queue in one admit, age spikes are noise).
    """
    if prefill_config is None:
        prefill_config = AutoscalerConfig(
            up_queue_depth=2.0, down_queue_depth=0.25,
            up_queue_age_ms=150.0, down_queue_age_ms=10.0,
            up_occupancy=2.0, down_occupancy=0.0)
    if decode_config is None:
        decode_config = AutoscalerConfig(
            up_queue_depth=8.0, down_queue_depth=0.5,
            up_queue_age_ms=1e9, down_queue_age_ms=1e6,
            up_occupancy=0.85, down_occupancy=0.30)
    return PhaseAutoscalers(
        Autoscaler(PhaseFleet(fleet, "prefill"), prefill_config,
                   family="pt_autoscale_prefill", **kw),
        Autoscaler(PhaseFleet(fleet, "decode"), decode_config,
                   family="pt_autoscale_decode", **kw))
