"""Continuous batching for generation serving: token-level scheduler
with device-resident decode state.

Why request-granularity batching loses on generation: the batch-mode
`beam_search_group` program scans for `max_len` steps no matter when a
request's beams finish, so a padded slot does max_len steps of work to
produce avg_len useful tokens, and a new request waits for the WHOLE
batch to drain before it can start (PERF.md measures the ragged-batch
analogue of this waste at 1.48-1.59x on training inputs; generation
adds the drain-latency term on top).

The continuous scheduler inverts the loop: a fixed pool of `max_slots`
decode slots whose state (beam memories, cumulative scores, the
(parent, token) trellis) stays ON DEVICE between steps as one
`DecodeState` pytree. Each iteration:

  1. ADMIT  — queued requests occupy free slots (the model's encoder
              prefix runs once per request through the engine's shape
              buckets; boot states are written into the pool by a
              jitted dynamic-update).
  2. STEP   — ONE jitted pool step advances every active slot by one
              token (the same `beam_step` the batch kernel scans —
              per-slot math is bit-identical to batch-mode decode).
  3. STREAM — the current best-beam token of every active slot is
              pushed to its request's event queue (provisional until
              the final backtrack, as in any beam-search streamer).
  4. RETIRE — slots whose beams all finished (or hit max_len) are
              backtracked, their results delivered, and the slot freed
              for the next admission — early-exit compaction: a short
              request never pays for a long neighbour.

Deadline/shed semantics mirror the MicroBatcher contract: a bounded
admission queue sheds with ShedError/503, deadlines are checked at
admission AND re-checked after slot admission/first step so a request
never streams a late first token past its deadline (DeadlineError/504).
A shared per-model CircuitBreaker (resilience.breaker) counts step
failures so /generate trips the same breaker /predict does. The
`serving.predict` fault point is fired each pool step: an injected
fault aborts in-flight requests with GenerationAborted (503, retryable)
and recovers the slots for subsequent traffic.

Generation serving v3 adds two levers on top of the slot pool:

- PREFIX CACHE (`prefix_cache_mb`) — the raw feed row is hashed and
  hot prefix states (boots + per-example rows) stay device-resident in
  a byte-budgeted LRU (serving/prefix_cache.py). A hit admits by
  copying the pooled state into the slot through the SAME `pool_admit`
  dynamic-update a fresh prefix uses — zero prefix dispatches, so the
  first token of a shared-prefix request costs one pool step. With
  `prefix_cache_quant="int8"` entries are stored int8-quantized
  (per-tensor symmetric, the quant/ recipe) and dequantized inside the
  jitted admit copy: ~4x more cached prefixes per HBM byte, at a
  bounded admit delta (the fp mode stays bit-identical).

- SPECULATIVE DECODING (`draft_model`) — a small draft model proposes
  `draft_k` tokens per slot greedily (one fused scan), and the target
  verifies all of them in ONE jitted `pool_verify` scan of the same
  `beam_step` the pool step runs. Per-slot halt masks stop a slot's
  advance at the first draft/target mismatch — KEEPING the divergent
  target token, so every applied step is an unconditioned `beam_step`
  and the output is structurally bit-identical to plain decoding for
  ANY accept pattern (a rejected draft degrades to exactly one plain
  step). The win on a recurrent step net is dispatch fusion: one
  draft dispatch + one verify dispatch + ONE d2h fence move up to
  `draft_k` tokens per slot, vs one dispatch + fence per token.
"""

from __future__ import annotations

import os
import queue
import threading
import time
from collections import OrderedDict
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import trace as obs_trace
from ..resilience import faults
from ..resilience.breaker import CircuitBreaker, CircuitOpenError
from .batcher import AdmissionQueue, DeadlineError, ShedError
from .metrics import (FIRST_TOKEN_BUCKETS, TOKEN_INTERVAL_BUCKETS,
                      VERIFY_ROUND_BUCKETS, MetricSet)
from .prefix_cache import PrefixCache, prefix_row_key

__all__ = ["ContinuousScheduler", "GenHandle", "GenerationAborted",
           "DeadlineError", "ShedError", "CircuitOpenError"]


class GenerationAborted(ShedError):
    """A pool step failed mid-flight: the request was aborted, slots
    recovered — retry (maps to HTTP 503 + Retry-After)."""


class GenHandle:
    """Client-side handle for one generation request.

    `events()` yields dicts as decoding progresses:
      {"event": "token", "row": r, "step": t, "token": id}   per step
      {"event": "done",  "outputs": {...}}                   terminal
      {"event": "error", "error": msg, "kind": clsname}      terminal
    `result()` blocks to the terminal event and returns the outputs
    dict (ids [n,K,T], scores [n,K], lengths [n,K]) or raises."""

    def __init__(self, rows: int):
        self.rows = rows
        self.request_id: Optional[str] = None  # set by _GenRequest
        self._q: "queue.Queue[dict]" = queue.Queue()
        self._done = threading.Event()
        self._outputs: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    # -- scheduler side -------------------------------------------------
    def _emit_token(self, row: int, step: int, token: int) -> None:
        self._q.put({"event": "token", "row": row, "step": step,
                     "token": token})

    def _finish(self, outputs: Dict[str, np.ndarray]) -> None:
        self._outputs = outputs
        self._done.set()
        self._q.put({"event": "done", "outputs": outputs})

    def _fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return
        self._exc = exc
        self._done.set()
        self._q.put({"event": "error", "error": str(exc),
                     "kind": type(exc).__name__})

    # -- client side ----------------------------------------------------
    def events(self, timeout: Optional[float] = None):
        while True:
            ev = self._q.get(timeout=timeout)
            yield ev
            if ev["event"] in ("done", "error"):
                return

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self._done.wait(timeout=timeout):
            raise DeadlineError("generation result timed out")
        if self._exc is not None:
            raise self._exc
        assert self._outputs is not None
        return self._outputs


class _GenRequest:
    __slots__ = ("feed", "rows", "handle", "deadline", "submitted_at",
                 "first_token_at", "last_token_at", "boots", "pes",
                 "dboots", "dpes", "cached", "cache_keys",
                 "next_row", "live_rows", "results", "failed",
                 "request_id", "slo_class", "enqueued_at")

    def __init__(self, feed, rows: int, deadline: float,
                 request_id: Optional[str] = None,
                 slo_class: str = "interactive"):
        self.feed = feed
        self.rows = rows
        self.slo_class = slo_class
        self.enqueued_at = 0.0  # stamped by AdmissionQueue.put
        # correlation key: every span this request touches — enqueue on
        # the client thread, admit/prefix/first-token/retire on the
        # scheduler worker, the HTTP span on the handler thread —
        # carries this id (ISSUE 8 queue→admit→pool-step→stream flow).
        # A router-minted id (X-PT-Request-Id) is adopted verbatim so
        # the router hop joins the same chain.
        self.request_id = request_id or obs_trace.new_request_id("gen")
        self.handle = GenHandle(rows)
        self.handle.request_id = self.request_id
        self.deadline = deadline
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.boots = None  # prefix outputs, set at first admission
        self.pes = None
        self.dboots = None  # draft-model prefix outputs (spec decoding)
        self.dpes = None
        self.cached = None  # row -> PrefixCache entry (cache-hit rows)
        self.cache_keys = None  # row -> cache key (for miss insertion)
        self.next_row = 0  # next un-admitted row
        self.live_rows = 0  # rows currently holding slots
        self.results: Dict[int, tuple] = {}  # row -> (ids, scores, lengths)
        self.failed = False

    def fail(self, exc: BaseException) -> None:
        """Terminal failure (AdmissionQueue contract + scheduler paths)."""
        self.failed = True
        self.handle._fail(exc)


class ContinuousScheduler:
    """Token-level continuous-batching scheduler over one engine's
    generative model. One worker thread owns the decode pool; any
    number of client threads submit()."""

    def __init__(
        self,
        engine,
        max_slots: int = 8,
        max_queue: int = 64,
        timeout_ms: float = 30000.0,
        breaker: Optional[CircuitBreaker] = None,
        metrics: Optional[MetricSet] = None,
        prefix_cache_mb: float = 0.0,
        prefix_cache_quant: Optional[str] = None,
        draft_model: Optional[str] = None,
        draft_k: int = 4,
        max_prefix_programs: int = 32,
    ):
        from ..ops import generation_ops as G

        self.engine = engine
        op = G.find_generation_op(engine.program)
        if op is None:
            raise ValueError(
                f"model {engine.model_name!r} has no beam_search_group "
                "op — continuous batching serves generation programs "
                "(layers.BeamSearchDecoder); use predict() for "
                "feed-forward models")
        self._G = G
        self.spec = G.gen_spec_from_op(op)
        block0 = engine.program.global_block()
        gen_idx = block0.ops.index(op)
        if any(o.type != "beam_search_group" for o in block0.ops[gen_idx + 1:]):
            raise ValueError(
                "ops after the beam_search_group op are not supported by "
                "the continuous scheduler (its outputs feed post-decode "
                "ops the pool step cannot incrementalize)")
        self._prefix_ops = block0.ops[:gen_idx]
        self._block0 = block0
        self._check_step_closures(engine.program)
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = max_slots
        self.max_queue = max_queue
        self.timeout_s = timeout_ms / 1e3
        self.breaker = breaker
        self.metrics = metrics or engine.metrics

        import jax

        self._jax = jax
        # persistables snapshot: generation serving assumes frozen
        # weights (the engine contract); committed once, passed to every
        # jitted call so jit never re-traces on placement
        scope = engine.scope
        self._params = {
            v.name: jax.device_put(scope.get(v.name))
            for v in engine.program.persistables() if scope.has(v.name)
        }

        # pool state (allocated on first admission or warmup-from-meta)
        self._state = None  # DecodeState
        self._mem_specs = None  # ((trailing shape, dtype), ...)
        self._pe_specs = None
        self._pool_step = None  # jitted (params, active, state) -> state
        self._pool_admit = None  # jitted (state, slot, boots, pes) -> state
        self._pool_admit_q = None  # int8-entry admit (dequant fused)
        self._q_rows = None  # jitted per-tensor int8 row quantizer
        self._pool_verify = None  # speculative D-step verify scan
        # jitted prefix-PROGRAM cache: LRU-capped on program count
        # (satellite of serving v3 — the padded-shape-keyed dict was
        # unbounded, so a tail of novel shapes pinned every traced
        # program forever). Evictions land on the UNIFIED pt_ registry,
        # mirroring the predict path's compile-cache accounting.
        if max_prefix_programs < 1:
            raise ValueError(
                f"max_prefix_programs must be >= 1, got "
                f"{max_prefix_programs}")
        self.max_prefix_programs = max_prefix_programs
        self._prefix_cache: "OrderedDict[tuple, Any]" = OrderedDict()
        self.prefix_program_evictions = 0
        self.metrics.registry.declare_counter(
            "pt_gen_prefix_evictions_total",
            help="jitted generation prefix programs evicted from the "
                 "scheduler's LRU compile cache")
        self.compiles = 0

        # device-resident prefix-STATE cache (serving v3 tentpole):
        # raw-feed-row hash -> pooled (boots, pe_rows) in HBM; a hit
        # admits via pool_admit with zero prefix dispatches
        if prefix_cache_quant not in (None, "int8"):
            raise ValueError(
                f"unsupported prefix_cache_quant {prefix_cache_quant!r} "
                "(only 'int8')")
        self.prefix_cache_quant = prefix_cache_quant
        self._pcache = (PrefixCache(int(prefix_cache_mb * (1 << 20)))
                        if prefix_cache_mb > 0 else None)

        # speculative decoding (serving v3 tentpole): the draft rig is
        # built up front so a bad --draft_model fails at construction,
        # not on the first request. CLI knob overrides the artifact's
        # draft-model sidecar (io.save_inference_model(draft_model=...))
        if draft_k < 1:
            raise ValueError(f"draft_k must be >= 1, got {draft_k}")
        self.draft_k = int(draft_k)
        self._draft = None
        sidecar = getattr(engine, "draft_meta", None) or {}
        draft_dir = draft_model or sidecar.get("dir")
        if draft_dir and not os.path.isabs(draft_dir) \
                and getattr(engine, "model_dir", None):
            cand = os.path.join(engine.model_dir, draft_dir)
            if os.path.isdir(cand):
                draft_dir = cand
        if draft_dir:
            self._init_draft(draft_dir)

        self._cond = threading.Condition()
        # the admission queue shares MicroBatcher's deadline/shed
        # semantics (serving/batcher.py) — one contract for both paths
        self._aq = AdmissionQueue(max_queue, self._cond, self.metrics,
                                  prefix="gen_")
        self._slot_req: List[Optional[Tuple[_GenRequest, int]]] = (
            [None] * max_slots)
        self._active = np.zeros(max_slots, bool)
        self._partial: Optional[_GenRequest] = None  # rows still waiting
        self._worker: Optional[threading.Thread] = None
        self._stopping = False

        # accounting (engine-parity dispatch/sync counters + gen stats)
        self.dispatches_total = 0
        self.syncs_total = 0
        self.steps_total = 0
        self.admitted_total = 0
        self.retired_total = 0
        self.tokens_total = 0
        # disagg phase accounting: prefill-only requests served and
        # wire-handoff requests admitted into the pool
        self.prefills_total = 0
        self.handoffs_admitted_total = 0
        self._occupancy_steps = 0  # sum of active-slot count over steps
        self._first_tok = self.metrics.histogram(
            "gen_first_token_seconds", buckets=FIRST_TOKEN_BUCKETS,
            help="submit-to-first-streamed-token latency")
        self._per_tok = self.metrics.histogram(
            "gen_token_seconds", buckets=TOKEN_INTERVAL_BUCKETS,
            help="inter-token interval per request")
        self.metrics.gauge(
            "gen_slot_occupancy",
            lambda: float(self._active.sum()) / self.max_slots,
            help="fraction of decode slots occupied")
        self.metrics.gauge(
            "gen_queue_depth", lambda: self._aq.depth(),
            help="generation requests waiting for a slot")
        # pre-registered counters: the scrape surface is complete from
        # construction, not dependent on traffic having arrived
        self.metrics.declare_counter(
            "gen_requests_total", help="generation requests accepted")
        self.metrics.declare_counter(
            "gen_steps_total", help="decode pool steps executed")
        self.metrics.declare_counter(
            "gen_tokens_total",
            help="tokens streamed across all generation requests")
        self.metrics.declare_counter(
            "circuit_open_total",
            help="requests rejected because the model's circuit "
                 "breaker was open")
        # serving v3 surfaces (pre-registered even when the feature is
        # off, so the scrape surface never depends on configuration)
        # disagg phase surfaces (serving/disagg): a monolithic replica
        # scrapes these at 0, a phase replica moves exactly one of them
        self.metrics.declare_counter(
            "gen_prefill_total",
            help="prefill-phase requests served (prefix program only, "
                 "state shipped to a decode replica)")
        self.metrics.declare_counter(
            "gen_handoff_admitted_total",
            help="wire-handoff requests admitted into the decode pool")
        self.metrics.declare_counter(
            "gen_prefix_hits_total",
            help="request rows admitted from the device-resident "
                 "prefix cache (no prefix dispatch)")
        self.metrics.declare_counter(
            "gen_prefix_misses_total",
            help="request rows that ran the full prefix program")
        self.metrics.declare_counter(
            "gen_prefix_cache_evictions_total",
            help="prefix states evicted from the device-resident LRU "
                 "(byte budget pressure)")
        self.metrics.declare_counter(
            "gen_draft_tokens_total",
            help="tokens proposed by the draft model")
        self.metrics.declare_counter(
            "gen_draft_accepted_total",
            help="proposed tokens converted to emitted target tokens "
                 "(the divergence-correcting target step included)")
        self.metrics.declare_counter(
            "gen_verify_rounds_total",
            help="speculative verify rounds (one draft dispatch + one "
                 "target verify dispatch each)")
        self.verify_rounds_total = 0
        self._draft_proposed = 0
        self._draft_accepted = 0
        self._verify_lat = self.metrics.histogram(
            "gen_verify_round_seconds", buckets=VERIFY_ROUND_BUCKETS,
            help="latency of one speculative round (draft propose + "
                 "target verify + fence)")
        self.metrics.gauge(
            "gen_prefix_cache_entries",
            lambda: float(len(self._pcache)) if self._pcache else 0.0,
            help="prefix states resident in the device LRU")
        self.metrics.gauge(
            "gen_prefix_cache_bytes",
            lambda: float(self._pcache.bytes) if self._pcache else 0.0,
            help="HBM bytes held by cached prefix states")
        self.metrics.gauge(
            "gen_prefix_hit_rate",
            lambda: self._pcache.hit_rate() if self._pcache else 0.0,
            help="prefix cache hit rate since start")
        self.metrics.gauge(
            "gen_accept_rate",
            lambda: (self._draft_accepted / self._draft_proposed
                     if self._draft_proposed else 0.0),
            help="fraction of the drafted window converted to emitted "
                 "tokens (tokens-per-round / draft_k)")

    def _check_step_closures(self, program, spec=None) -> None:
        """The pool-step env holds parameters and declared per-example
        tensors ONLY (batch-mode decode sees the whole block-0 env, so
        it tolerates undeclared closures the scheduler cannot): reject
        step bodies that close over other outer values up front, with a
        fix, instead of a KeyError mid-trace. Also applied to the
        draft model's step body (its propose scan has the same env
        contract)."""
        spec = spec or self.spec
        persist = {v.name for v in program.persistables()}
        produced = ({spec.prev_inner} | set(spec.mem_inner)
                    | set(spec.per_example))
        refs: set = set()
        stack = [spec.sub_block]
        while stack:
            b = program.blocks[stack.pop()]
            for sop in b.ops:
                refs.update(n for n in sop.input_names()
                            if n not in produced)
                produced.update(sop.output_names())
                inner = sop.attrs.get("sub_block")
                if isinstance(inner, int):
                    stack.append(inner)
        missing = sorted(refs - persist)
        if missing:
            raise ValueError(
                f"generation step body closes over non-parameter outer "
                f"value(s) {missing}: continuous batching keeps only "
                "parameters and declared per-example tensors device-"
                "resident — declare them with gen.per_example_input()")

    # -- speculative decoding rig ---------------------------------------
    def _init_draft(self, draft_dir: str) -> None:
        """Load + validate the draft model and resolve everything the
        fused propose program needs (runner, step block, device-placed
        params). Fails at construction, not on the first request."""
        from .engine import ServingEngine
        from ..core.executor import _BlockRunner

        d_eng = ServingEngine(
            draft_dir, policy=self.engine.policy,
            model_name=f"{self.engine.model_name}.draft",
            metrics=self.metrics)
        dspec = d_eng.generation_spec()
        if dspec is None:
            raise ValueError(
                f"draft model {draft_dir!r} has no beam_search_group "
                "op — speculative decoding drafts with a (small) "
                "generation model over the same vocabulary")
        spec = self.spec
        if (dspec.bos_id, dspec.eos_id) != (spec.bos_id, spec.eos_id):
            raise ValueError(
                f"draft model {draft_dir!r} decodes with "
                f"bos/eos=({dspec.bos_id},{dspec.eos_id}) but the "
                f"target uses ({spec.bos_id},{spec.eos_id}) — draft "
                "proposals would never verify")
        if sorted(d_eng.feed_names) != sorted(self.engine.feed_names):
            raise ValueError(
                f"draft model feeds {sorted(d_eng.feed_names)} != "
                f"target feeds {sorted(self.engine.feed_names)}: the "
                "draft prefix runs on the SAME request feed")
        self._check_step_closures(d_eng.program, dspec)
        jax = self._jax
        prog = d_eng.program
        op = self._G.find_generation_op(prog)
        block0 = prog.global_block()
        gen_idx = block0.ops.index(op)
        self._draft = {
            "engine": d_eng,
            "dir": draft_dir,
            "spec": dspec,
            "params": {
                v.name: jax.device_put(d_eng.scope.get(v.name))
                for v in prog.persistables() if d_eng.scope.has(v.name)
            },
            "prefix_ops": block0.ops[:gen_idx],
            "block0": block0,
            "runner": _BlockRunner(prog),
            "block": prog.blocks[dspec.sub_block],
            "amp": prog.amp_dtype,
            # slot-pool state (allocated by _ensure_draft_pool)
            "mem_specs": None, "pe_specs": None,
            "mems": None, "tok": None, "pe": None,
            "admit": None, "admit_q": None, "propose": None,
        }

    def _ensure_draft_pool(self, dmem_specs, dpe_specs) -> None:
        """Allocate the draft's single-hypothesis slot state (mems
        [S, ...], last-token [S], per-example [S, ...]) and compile its
        admit + fused D-step propose programs. The propose scan's mems
        HISTORY feeds pool_verify's draft-sync gather: after `a`
        accepted steps the draft state that consumed the accepted
        tokens is exactly the state after propose step `a` (accepted
        means the proposals MATCHED the emitted tokens), so syncing is
        a per-slot select, never a replay."""
        d = self._draft
        if d["mems"] is not None:
            if (dmem_specs, dpe_specs) != (d["mem_specs"], d["pe_specs"]):
                raise ValueError(
                    f"draft state geometry changed mid-serve: pool "
                    f"holds {d['mem_specs']}/{d['pe_specs']}, request "
                    f"produced {dmem_specs}/{dpe_specs}")
            return
        jax, jnp = self._jax, self._jax.numpy
        G, S, D = self._G, self.max_slots, self.draft_k
        dspec, runner, block = d["spec"], d["runner"], d["block"]
        amp = d["amp"]
        d["mem_specs"], d["pe_specs"] = dmem_specs, dpe_specs
        d["mems"] = tuple(
            jnp.zeros((S,) + shp, dt) for shp, dt in dmem_specs)
        d["tok"] = jnp.full((S,), dspec.bos_id, jnp.int32)
        d["pe"] = tuple(
            jnp.zeros((S,) + shp, dt) for shp, dt in dpe_specs)

        def d_admit_body(mems, tok, pe, slot, boots, pe_rows):
            mems = tuple(
                jax.lax.dynamic_update_index_in_dim(m, b, slot, 0)
                for m, b in zip(mems, boots))
            tok = jax.lax.dynamic_update_index_in_dim(
                tok, jnp.int32(dspec.bos_id), slot, 0)
            pe = tuple(
                jax.lax.dynamic_update_index_in_dim(p, r, slot, 0)
                for p, r in zip(pe, pe_rows))
            return mems, tok, pe

        def d_admit_q(mems, tok, pe, slot, qboots, bscales, qpes,
                      pscales):
            boots = tuple(
                (q.astype(jnp.float32) * s).astype(dt)
                for q, s, (_, dt) in zip(qboots, bscales, dmem_specs))
            pe_rows = tuple(
                (q.astype(jnp.float32) * s).astype(dt)
                for q, s, (_, dt) in zip(qpes, pscales, dpe_specs))
            return d_admit_body(mems, tok, pe, slot, boots, pe_rows)

        def d_propose(dparams, mems, tok, pe):
            """D greedy steps; returns (drafts [D, S], per-mem history
            [D, S, ...]) — history row i is the state AFTER consuming
            proposal i's input, the sync source for pool_verify."""
            def body(carry, _):
                m, t = carry
                env = dict(dparams)
                env["@RNG@"] = jax.random.PRNGKey(0)
                env["@RNG_COUNTER@"] = 0
                env["@AMP@"] = amp
                for name, v in zip(dspec.per_example, pe):
                    env[name] = v
                nm, nt = G.greedy_step(runner, block, dspec, env, m, t)
                return (nm, nt), (nt, nm)

            (_, _), (drafts, hist) = jax.lax.scan(
                body, (mems, tok), jnp.arange(D, dtype=jnp.int32))
            return drafts, hist

        d["admit"] = jax.jit(d_admit_body)
        d["admit_q"] = jax.jit(d_admit_q)
        d["propose"] = jax.jit(d_propose)
        self.compiles += 2

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "ContinuousScheduler":
        with self._cond:
            if self._worker is not None and self._worker.is_alive():
                return self
            self._stopping = False
            self._worker = threading.Thread(
                target=self._run,
                name=f"ptgen-{self.engine.model_name}", daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = False,
             drain_timeout_s: float = 60.0) -> None:
        """Stop the pool worker. drain=True lets queued + in-flight
        generation finish first (bounded by drain_timeout_s) — the
        graceful half of the replica SIGTERM contract; whatever is
        still in flight past the bound fails with a retryable
        ShedError so a router can re-run it elsewhere."""
        if drain:
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                with self._cond:
                    # depth() is lock-free (the cond is NOT reentrant)
                    if not self._aq.depth() and not self._active.any() \
                            and self._partial is None:
                        break
                time.sleep(0.01)
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        # fail whatever is still queued/in flight
        self._drain_queue(ShedError("scheduler stopped"))
        with self._cond:
            self._abort_inflight_locked(ShedError("scheduler stopped"))

    # -- client side ----------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               slo: Optional[str] = None) -> GenHandle:
        if self.breaker is not None and not self.breaker.admit():
            self.metrics.counter_inc(
                "circuit_open_total",
                help="requests rejected because the model's circuit "
                     "breaker was open")
            raise CircuitOpenError(
                f"circuit open for model {self.engine.model_name!r}; "
                "retry later")
        rows = {v.shape[0] for v in feed.values()
                if hasattr(v, "ndim") and v.ndim >= 1}
        if len(rows) != 1:
            raise ValueError(
                f"generation feeds must share the batch axis; got row "
                f"counts {sorted(rows)}")
        n = rows.pop()
        deadline = time.monotonic() + (
            timeout_ms / 1e3 if timeout_ms is not None else self.timeout_s)
        req = _GenRequest(feed, n, deadline, request_id=request_id,
                          slo_class=slo or "interactive")
        with self._cond:
            if self._stopping:
                raise ShedError("scheduler stopped")
        self._aq.put(req)  # sheds with ShedError/503 when full
        if obs_trace._armed:
            # enqueue marker on the CLIENT thread; the worker-side admit
            # span carries the same request_id, linking the hand-off
            obs_trace.instant("gen.enqueue", cat="gen",
                              request_id=req.request_id, rows=n)
        self.metrics.counter_inc(
            "gen_requests_total", help="generation requests accepted")
        return req.handle

    def generate(self, feed: Dict[str, np.ndarray],
                 timeout_ms: Optional[float] = None) -> Dict[str, np.ndarray]:
        """submit + wait: the non-streaming convenience used by
        ServingEngine.generate(). Grace mirrors MicroBatcher.predict
        (cold pool-step compiles can outlast the deadline alone)."""
        h = self.submit(feed, timeout_ms=timeout_ms)
        budget = (timeout_ms / 1e3 if timeout_ms is not None
                  else self.timeout_s)
        return h.result(timeout=budget + max(1.0, budget))

    # -- disagg phase split (serving/disagg) -----------------------------
    def prefill(self, feed: Dict[str, np.ndarray],
                request_id: Optional[str] = None) -> Tuple[tuple, tuple]:
        """PREFILL phase of disaggregated serving: run ONLY the bucketed
        prefix program and return the request's boot state as host
        arrays sliced to the true row count — the payload of a
        prefill→decode handoff. serving/disagg packs and ships it; the
        decode replica admits it via submit_handoff through the same
        pool_admit path a local prefix uses, so the phase split never
        takes a different numeric path. No pool is touched: a
        pure-prefill replica spends its HBM on big mesh-sharded prefix
        batches, never on decode slots. The whole tuple crosses d2h in
        ONE device_get fence (elastic.gather_handoff_rows), which is
        also where mesh-sharded prefix outputs all-gather to host."""
        from ..pipeline import elastic

        if self.breaker is not None and not self.breaker.admit():
            self.metrics.counter_inc(
                "circuit_open_total",
                help="requests rejected because the model's circuit "
                     "breaker was open")
            raise CircuitOpenError(
                f"circuit open for model {self.engine.model_name!r}; "
                "retry later")
        rows = {v.shape[0] for v in feed.values()
                if hasattr(v, "ndim") and v.ndim >= 1}
        if len(rows) != 1:
            raise ValueError(
                f"generation feeds must share the batch axis; got row "
                f"counts {sorted(rows)}")
        n = rows.pop()
        with obs_trace.span("gen.prefill", cat="gen",
                            request_id=request_id, rows=n):
            padded, _, _ = self.engine._pad_feed(
                {k: np.asarray(v) for k, v in feed.items()})
            jnp = self._jax.numpy
            padded = {k: jnp.asarray(v) for k, v in padded.items()}
            fn = self._build_prefix(padded)
            boots, pes = fn(self._params, padded)
            boots = elastic.gather_handoff_rows(boots, n)
            pes = elastic.gather_handoff_rows(pes, n)
        self.dispatches_total += 1
        self.syncs_total += 1
        self.prefills_total += 1
        self.metrics.counter_inc(
            "gen_prefill_total",
            help="prefill-phase requests served (prefix program only, "
                 "state shipped to a decode replica)")
        return boots, pes

    def submit_handoff(self, boots, pes,
                       timeout_ms: Optional[float] = None,
                       request_id: Optional[str] = None,
                       slo: Optional[str] = None) -> GenHandle:
        """DECODE phase of disaggregated serving: enqueue a request
        whose prefix state arrived over the wire (host arrays [n, ...]
        from a prefill replica's `prefill()`). State is placed onto this
        replica's devices here — the restore half of the elastic handoff
        — and then admitted into free slots by the worker through the
        SAME jitted pool_admit dynamic-update a locally-prefixed request
        uses: bit-identity with monolithic serving is structural.
        Deadline/shed/breaker semantics match submit()."""
        from ..pipeline import elastic

        if self._draft is not None:
            raise ValueError(
                "disagg handoff does not carry draft-model state: serve "
                "the decode class without --draft_model (speculative "
                "decoding composes with monolithic serving only)")
        if self.breaker is not None and not self.breaker.admit():
            self.metrics.counter_inc(
                "circuit_open_total",
                help="requests rejected because the model's circuit "
                     "breaker was open")
            raise CircuitOpenError(
                f"circuit open for model {self.engine.model_name!r}; "
                "retry later")
        boots, pes = tuple(boots), tuple(pes)
        rows = {int(a.shape[0]) for a in boots + pes}
        if len(rows) != 1:
            raise ValueError(
                f"handoff state arrays must share the row axis; got row "
                f"counts {sorted(rows)}")
        n = rows.pop()
        deadline = time.monotonic() + (
            timeout_ms / 1e3 if timeout_ms is not None else self.timeout_s)
        req = _GenRequest(None, n, deadline, request_id=request_id,
                          slo_class=slo or "interactive")
        mesh = getattr(self.engine, "mesh", None)
        req.boots = elastic.restore_handoff_rows(boots, mesh)
        req.pes = elastic.restore_handoff_rows(pes, mesh)
        with self._cond:
            if self._stopping:
                raise ShedError("scheduler stopped")
        self._aq.put(req)  # sheds with ShedError/503 when full
        if obs_trace._armed:
            obs_trace.instant("gen.handoff_enqueue", cat="gen",
                              request_id=req.request_id, rows=n)
        self.handoffs_admitted_total += 1
        self.metrics.counter_inc(
            "gen_handoff_admitted_total",
            help="wire-handoff requests admitted into the decode pool")
        self.metrics.counter_inc(
            "gen_requests_total", help="generation requests accepted")
        return req.handle

    # -- pool construction ---------------------------------------------
    def _build_prefix(self, padded: Dict[str, Any], draft: bool = False):
        """Jitted encoder prefix: (params, feed) -> (boots, pes); one
        compile per engine shape bucket (the slot-state compile cache is
        keyed off the SAME buckets predict uses). `draft=True` builds
        the same program over the DRAFT model's prefix ops (speculative
        decoding boots draft slot state from the same request feed).

        The program cache is a count-capped LRU (max_prefix_programs):
        a tail of novel padded shapes evicts the coldest traced program
        instead of pinning every one forever; evictions are counted on
        the unified registry (pt_gen_prefix_evictions_total)."""
        from ..core.executor import _BlockRunner, _feed_signature

        key = _feed_signature(padded)
        if draft:
            key = ("draft",) + key
        fn = self._prefix_cache.get(key)
        if fn is not None:
            self._prefix_cache.move_to_end(key)
            return fn
        jax, jnp = self._jax, self._jax.numpy
        if draft:
            d = self._draft
            runner, spec = d["runner"], d["spec"]
            block0, ops = d["block0"], d["prefix_ops"]
            amp = d["amp"]
        else:
            runner = _BlockRunner(self.engine.program)
            spec, block0, ops = self.spec, self._block0, self._prefix_ops
            amp = self.engine.program.amp_dtype

        def prefix(params, feed):
            env = dict(params)
            env.update(feed)
            env["@RNG@"] = jax.random.PRNGKey(0)
            env["@RNG_COUNTER@"] = 0
            env["@AMP@"] = amp
            runner.run_ops(ops, env, dict(env), block0)
            boots = tuple(env[n] for n in spec.boot_names)
            pes = tuple(env[n] for n in spec.per_example_names)
            return boots, pes

        fn = jax.jit(prefix)
        while len(self._prefix_cache) >= self.max_prefix_programs:
            self._prefix_cache.popitem(last=False)
            self.prefix_program_evictions += 1
            self.metrics.registry.counter_inc(
                "pt_gen_prefix_evictions_total",
                help="jitted generation prefix programs evicted from "
                     "the scheduler's LRU compile cache")
        self._prefix_cache[key] = fn
        self.compiles += 1
        return fn

    def _ensure_pool(self, mem_specs, pe_specs) -> None:
        """Allocate the DecodeState pool + compile step/admit for these
        per-slot trailing shapes (once per model: the decode state
        geometry is fixed by the program, not by traffic)."""
        if self._state is not None:
            if (mem_specs, pe_specs) != (self._mem_specs, self._pe_specs):
                raise ValueError(
                    f"generation state geometry changed mid-serve: pool "
                    f"holds {self._mem_specs}/{self._pe_specs}, request "
                    f"produced {mem_specs}/{pe_specs} — decode-state "
                    "trailing shapes must be static (pad variable-length "
                    "encoder outputs to a fixed bucket)")
            return
        jax, jnp = self._jax, self._jax.numpy
        from ..core.executor import _BlockRunner
        from ..ops import beam_common

        G, spec, S = self._G, self.spec, self.max_slots
        K, T = spec.beam_size, spec.max_len
        self._mem_specs, self._pe_specs = mem_specs, pe_specs
        self._state = G.DecodeState(
            mems=tuple(jnp.zeros((S, K) + shp, dt) for shp, dt in mem_specs),
            tok=jnp.full((S, K), spec.bos_id, jnp.int32),
            scores=jnp.zeros((S, K), jnp.float32),
            fin=jnp.ones((S, K), bool),
            step=jnp.zeros((S,), jnp.int32),
            parents=jnp.zeros((S, K, T), jnp.int32),
            trellis_tok=jnp.full((S, K, T), spec.eos_id, jnp.int32),
            pe=tuple(jnp.zeros((S * K,) + shp, dt) for shp, dt in pe_specs),
        )
        runner = _BlockRunner(self.engine.program)
        block = self.engine.program.blocks[spec.sub_block]
        amp = self.engine.program.amp_dtype

        def pool_step(params, active, state):
            env = dict(params)
            env["@RNG@"] = jax.random.PRNGKey(0)
            env["@RNG_COUNTER@"] = 0
            env["@AMP@"] = amp
            for name, v in zip(spec.per_example, state.pe):
                env[name] = v
            new_mems, new_tok, new_sc, new_fin, parent = G.beam_step(
                runner, block, spec, env,
                state.mems, state.tok, state.scores, state.fin)
            u2 = active[:, None]
            mems = tuple(
                jnp.where(active.reshape((S,) + (1,) * (m.ndim - 1)), nm, m)
                for nm, m in zip(new_mems, state.mems))
            tok = jnp.where(u2, new_tok, state.tok)
            sc = jnp.where(u2, new_sc, state.scores)
            fin = jnp.where(u2, new_fin, state.fin)
            at_t = (jnp.arange(T)[None, None, :]
                    == state.step[:, None, None]) & active[:, None, None]
            parents = jnp.where(at_t, parent[:, :, None], state.parents)
            ttok = jnp.where(at_t, new_tok[:, :, None], state.trellis_tok)
            stp = state.step + active.astype(jnp.int32)
            return G.DecodeState(mems, tok, sc, fin, stp, parents, ttok,
                                 state.pe)

        def pool_admit(state, slot, boots, pe_rows):
            mems = tuple(
                jax.lax.dynamic_update_index_in_dim(
                    m, jnp.broadcast_to(b, (K,) + b.shape), slot, 0)
                for m, b in zip(state.mems, boots))
            tok = jax.lax.dynamic_update_index_in_dim(
                state.tok, jnp.full((K,), spec.bos_id, jnp.int32), slot, 0)
            sc = jax.lax.dynamic_update_index_in_dim(
                state.scores, beam_common.init_scores(1, K)[0], slot, 0)
            fin = jax.lax.dynamic_update_index_in_dim(
                state.fin, jnp.zeros((K,), bool), slot, 0)
            stp = jax.lax.dynamic_update_index_in_dim(
                state.step, jnp.zeros((), jnp.int32), slot, 0)
            pe = tuple(
                jax.lax.dynamic_update_slice_in_dim(
                    p, jnp.repeat(r[None], K, axis=0), slot * K, axis=0)
                for p, r in zip(state.pe, pe_rows))
            # parents/trellis_tok stay stale: the pool step overwrites
            # columns 0..t-1 before retirement ever backtracks them
            return state._replace(mems=mems, tok=tok, scores=sc, fin=fin,
                                  step=stp, pe=pe)

        def pool_admit_q(state, slot, qboots, bscales, qpes, pscales):
            # int8-pooled cache entry: dequant FUSED into the admit copy
            # (the f32 intermediates never round-trip through HBM as a
            # separate dispatch) — the quant/ per-tensor symmetric
            # recipe: x ≈ q * scale, scale = absmax/127
            boots = tuple(
                (q.astype(jnp.float32) * s).astype(dt)
                for q, s, (_, dt) in zip(qboots, bscales, mem_specs))
            pe_rows = tuple(
                (q.astype(jnp.float32) * s).astype(dt)
                for q, s, (_, dt) in zip(qpes, pscales, pe_specs))
            return pool_admit(state, slot, boots, pe_rows)

        def q_rows(boots, pe_rows):
            # per-tensor symmetric int8 for ONE request row's prefix
            # state (quant_kernels recipe: absmax/INT8_MAX scale,
            # round + clip) — runs once per cache insertion
            from ..ops.quant_kernels import INT8_MAX

            def q(x):
                xf = x.astype(jnp.float32)
                scale = jnp.maximum(
                    jnp.max(jnp.abs(xf)), 1e-30) / INT8_MAX
                qv = jnp.clip(jnp.round(xf / scale),
                              -INT8_MAX, INT8_MAX).astype(jnp.int8)
                return qv, scale

            qb = [q(b) for b in boots]
            qp = [q(p) for p in pe_rows]
            return (tuple(v for v, _ in qb), tuple(s for _, s in qb),
                    tuple(v for v, _ in qp), tuple(s for _, s in qp))

        self._pool_step = jax.jit(pool_step)
        self._pool_admit = jax.jit(pool_admit)
        self.compiles += 2
        if self._pcache is not None and self.prefix_cache_quant == "int8":
            self._pool_admit_q = jax.jit(pool_admit_q)
            self._q_rows = jax.jit(q_rows)
            self.compiles += 2

        if self._draft is not None:
            D = self.draft_k

            def pool_verify(params, active, state, drafts, hist,
                            dmems, dtok):
                """ONE speculative round: scan `beam_step` D times with
                a per-slot go mask. Every APPLIED step is the exact
                pool_step update (same beam_step, same masked writes,
                same trellis column), so the emitted stream is
                bit-identical to plain decoding for any accept pattern;
                `go` only decides HOW MANY of the D steps apply. A slot
                halts at its first draft/target mismatch — KEEPING the
                divergent target token — and at finish/max_len (so the
                emitted-token count matches plain mode exactly)."""
                def body(carry, i):
                    st, go = carry
                    env = dict(params)
                    env["@RNG@"] = jax.random.PRNGKey(0)
                    env["@RNG_COUNTER@"] = 0
                    env["@AMP@"] = amp
                    for name, v in zip(spec.per_example, st.pe):
                        env[name] = v
                    new_mems, new_tok, new_sc, new_fin, parent = \
                        G.beam_step(runner, block, spec, env,
                                    st.mems, st.tok, st.scores, st.fin)
                    u2 = go[:, None]
                    mems = tuple(
                        jnp.where(
                            go.reshape((S,) + (1,) * (m.ndim - 1)), nm, m)
                        for nm, m in zip(new_mems, st.mems))
                    tok = jnp.where(u2, new_tok, st.tok)
                    sc = jnp.where(u2, new_sc, st.scores)
                    fin = jnp.where(u2, new_fin, st.fin)
                    at_t = (jnp.arange(T)[None, None, :]
                            == st.step[:, None, None]) & go[:, None, None]
                    parents = jnp.where(at_t, parent[:, :, None],
                                        st.parents)
                    ttok = jnp.where(at_t, new_tok[:, :, None],
                                     st.trellis_tok)
                    stp = st.step + go.astype(jnp.int32)
                    nst = G.DecodeState(mems, tok, sc, fin, stp,
                                        parents, ttok, st.pe)
                    matched = new_tok[:, 0] == drafts[i]
                    go = (go & matched & (stp < T)
                          & ~fin.all(axis=1))
                    return (nst, go), None

                (st, _), _ = jax.lax.scan(
                    body, (state, active),
                    jnp.arange(D, dtype=jnp.int32))
                adv = st.step - state.step  # [S] applied steps, 0..D
                # draft sync fused in: after `a` applied steps the
                # draft state that consumed the emitted tokens is
                # exactly propose-history row a-1 (inputs dtok,
                # drafts[0..a-2] — all but the last emitted token,
                # which becomes the next round's dtok)
                moved = adv > 0
                idx = jnp.maximum(adv - 1, 0)
                new_dmems = tuple(
                    jnp.where(
                        moved.reshape((S,) + (1,) * (dm.ndim - 1)),
                        jnp.take_along_axis(
                            h, idx.reshape((1, S) + (1,) * (h.ndim - 2)),
                            axis=0)[0],
                        dm)
                    for h, dm in zip(hist, dmems))
                new_dtok = jnp.where(moved, st.tok[:, 0], dtok)
                return st, new_dmems, new_dtok, adv

            self._pool_verify = jax.jit(pool_verify)
            self.compiles += 1

    def warmup(self) -> int:
        """Pre-compile the slot machinery so the first live request
        never pays the pool-step trace: prefix programs for every feed
        bucket (zero feeds, exactly like ServingEngine.warmup) and —
        when the artifact's meta.json records generation-state specs
        (io.save_inference_model) — the pool step + admit programs,
        without running any request through the model source.
        Returns the number of programs compiled."""
        before = self.compiles
        meta = getattr(self.engine.program, "_generation_meta", None)
        if meta and self._state is None:
            try:
                mem_specs = tuple(
                    (tuple(int(d) for d in m["shape"]), np.dtype(m["dtype"]))
                    for m in meta.get("state", []))
                pe_specs = tuple(
                    (tuple(int(d) for d in m["shape"]), np.dtype(m["dtype"]))
                    for m in meta.get("per_example", []))
                self._ensure_pool(mem_specs, pe_specs)
            except (KeyError, TypeError, ValueError) as e:
                import warnings

                warnings.warn(
                    f"generation meta of model "
                    f"{self.engine.model_name!r} unusable for pool "
                    f"warmup ({e}); slot state compiles on first "
                    "request", stacklevel=2)
        if self._state is not None:
            # trace+compile step and admit against the real pool state
            jnp = self._jax.numpy
            active = jnp.zeros((self.max_slots,), bool)
            self._state = self._pool_step(self._params, active, self._state)
            boots = tuple(jnp.zeros(shp, dt) for shp, dt in self._mem_specs)
            pes = tuple(jnp.zeros(shp, dt) for shp, dt in self._pe_specs)
            self._state = self._pool_admit(
                self._state, jnp.int32(0), boots, pes)
            # leave the pool empty: the warmup admit wrote slot 0 but
            # _active stays False so its garbage never steps or retires
            if self._pool_admit_q is not None:
                # int8 cache machinery: row quantizer + dequant-admit
                qb, bs, qp, ps = self._q_rows(boots, pes)
                self._state = self._pool_admit_q(
                    self._state, jnp.int32(0), qb, bs, qp, ps)
        if self._draft is not None and self._draft["mems"] is None:
            # the draft artifact's own generation meta gives its state
            # geometry without running a request through it
            dmeta = getattr(self._draft["engine"].program,
                            "_generation_meta", None)
            if dmeta:
                try:
                    dmem_specs = tuple(
                        (tuple(int(x) for x in m["shape"]),
                         np.dtype(m["dtype"]))
                        for m in dmeta.get("state", []))
                    dpe_specs = tuple(
                        (tuple(int(x) for x in m["shape"]),
                         np.dtype(m["dtype"]))
                        for m in dmeta.get("per_example", []))
                    self._ensure_draft_pool(dmem_specs, dpe_specs)
                except (KeyError, TypeError, ValueError):
                    pass  # draft pool compiles on first request instead
        if self._draft is not None and self._draft["mems"] is not None:
            jnp = self._jax.numpy
            d = self._draft
            db = tuple(jnp.zeros(shp, dt) for shp, dt in d["mem_specs"])
            dpr = tuple(jnp.zeros(shp, dt) for shp, dt in d["pe_specs"])
            d["mems"], d["tok"], d["pe"] = d["admit"](
                d["mems"], d["tok"], d["pe"], jnp.int32(0), db, dpr)
            drafts, hist = d["propose"](
                d["params"], d["mems"], d["tok"], d["pe"])
            if self._state is not None and self._pool_verify is not None:
                # all-False mask: traces the verify scan, changes nothing
                active = jnp.zeros((self.max_slots,), bool)
                st, ndm, ndt, _ = self._pool_verify(
                    self._params, active, self._state, drafts, hist,
                    d["mems"], d["tok"])
                self._state = st
                d["mems"], d["tok"] = ndm, ndt
        pol = self.engine.policy
        for nb in pol.batch_buckets:
            for tb in (pol.seq_len_buckets or (None,)):
                feed = self.engine._zero_bucket_feed(nb, tb)
                if feed is None:
                    continue
                padded = {k: self._jax.numpy.asarray(v)
                          for k, v in feed.items()}
                self._build_prefix(padded)
                if self._draft is not None:
                    self._build_prefix(padded, draft=True)
        return self.compiles - before

    # -- worker ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while (not self._aq.depth() and not self._active.any()
                       and self._partial is None and not self._stopping):
                    self._cond.wait()
                if self._stopping:
                    return
            try:
                self._admit_ready()
            except Exception:
                # per-request admission failures are delivered on the
                # request handle inside _admit_ready; anything reaching
                # here is a scheduler bug — surface it on every handle
                import traceback

                traceback.print_exc()
            if self._active.any():
                if self._draft is not None:
                    self._spec_round()
                else:
                    self._step_once()
            else:
                time.sleep(0.001)  # queue non-empty but nothing admitted

    def _free_slots(self) -> List[int]:
        return [i for i in range(self.max_slots) if not self._active[i]]

    def _admit_ready(self) -> None:
        free = self._free_slots()
        while free:
            req = self._partial
            if req is None:
                with self._cond:
                    # pop() fails already-expired requests with
                    # DeadlineError (the queued-side deadline check)
                    req = self._aq.pop()
                if req is None:
                    return
                try:
                    self._run_prefix(req)
                except Exception as e:
                    req.fail(e)
                    free = self._free_slots()
                    continue
            admitted_any = False
            with obs_trace.span("gen.admit", cat="gen",
                                request_id=req.request_id):
                while free and req.next_row < req.rows:
                    slot = free.pop(0)
                    row = req.next_row
                    self._admit_row(req, row, slot)
                    req.next_row += 1
                    req.live_rows += 1
                    admitted_any = True
            self._partial = req if req.next_row < req.rows else None
            # deadline RE-CHECK after slot admission: the prefix run (a
            # possible cold bucket compile) may have eaten the budget —
            # free the slots now rather than stream a late first token
            if admitted_any and req.first_token_at is None \
                    and req.deadline <= time.monotonic():
                self._evict_request(req)
                self._deadline_fail(req, "deadline exceeded during slot "
                                         "admission (cold compile? warm "
                                         "the engine)")
            free = self._free_slots()
            if self._partial is not None:
                return  # head-of-line request still owns the next slots

    def _run_prefix(self, req: _GenRequest) -> None:
        if req.boots is not None:
            # HANDOFF admission (serving/disagg): the prefix already ran
            # on a prefill replica and this request carries device-placed
            # boot state. Wire-schema fingerprints were validated at the
            # /admit boundary; geometry is re-checked against the live
            # pool here, then the rows flow through the UNCHANGED
            # _admit_row → pool_admit path below.
            mem_specs = tuple((tuple(b.shape[1:]), np.dtype(b.dtype))
                              for b in req.boots)
            pe_specs = tuple((tuple(p.shape[1:]), np.dtype(p.dtype))
                             for p in req.pes)
            self._ensure_pool(mem_specs, pe_specs)
            return
        d = self._draft
        if self._pcache is not None:
            # device prefix-state cache probe: per-ROW raw-feed hash, so
            # a request shares entries regardless of batch neighbours
            keys = [prefix_row_key(self.engine.fingerprint, req.feed, r)
                    for r in range(req.rows)]
            req.cache_keys = keys
            ents = [self._pcache.get(k) for k in keys]
            hits = sum(e is not None for e in ents)
            misses = req.rows - hits
            if hits:
                self.metrics.counter_inc(
                    "gen_prefix_hits_total", by=float(hits),
                    help="request rows admitted from the device-"
                         "resident prefix cache (no prefix dispatch)")
            if misses:
                self.metrics.counter_inc(
                    "gen_prefix_misses_total", by=float(misses),
                    help="request rows that ran the full prefix "
                         "program")
            pool_ready = self._state is not None and (
                d is None or d["mems"] is not None)
            if not misses and pool_ready:
                # ALL rows cached: admit straight from the pooled
                # states — ZERO prefix dispatches; the first token of
                # this request costs one pool step
                if obs_trace._armed:
                    obs_trace.instant(
                        "gen.prefix_hit", cat="gen",
                        request_id=req.request_id, rows=req.rows)
                req.cached = ents
                return
            # any miss (or cold pool): the padded batch prefix runs for
            # every row anyway, so hit rows admit from the FRESH states
            # and only missing rows are inserted below
        with obs_trace.span("gen.prefix", cat="gen",
                            request_id=req.request_id, rows=req.rows):
            padded, n, _ = self.engine._pad_feed(
                {k: np.asarray(v) for k, v in req.feed.items()})
            jnp = self._jax.numpy
            padded = {k: jnp.asarray(v) for k, v in padded.items()}
            fn = self._build_prefix(padded)
            boots, pes = fn(self._params, padded)
        mem_specs = tuple((tuple(b.shape[1:]), np.dtype(b.dtype))
                          for b in boots)
        pe_specs = tuple((tuple(p.shape[1:]), np.dtype(p.dtype))
                         for p in pes)
        self._ensure_pool(mem_specs, pe_specs)
        req.boots = boots  # [nb, ...] device arrays; rows sliced on admit
        req.pes = pes
        self.dispatches_total += 1
        if d is not None:
            # the draft model boots ITS slot state from the same feed
            with obs_trace.span("gen.prefix", cat="gen",
                                request_id=req.request_id,
                                rows=req.rows, draft=True):
                dfn = self._build_prefix(padded, draft=True)
                dboots, dpes = dfn(d["params"], padded)
            dmem_specs = tuple((tuple(b.shape[1:]), np.dtype(b.dtype))
                               for b in dboots)
            dpe_specs = tuple((tuple(p.shape[1:]), np.dtype(p.dtype))
                              for p in dpes)
            self._ensure_draft_pool(dmem_specs, dpe_specs)
            req.dboots = dboots
            req.dpes = dpes
            self.dispatches_total += 1
        if self._pcache is not None:
            for r in range(req.rows):
                if req.cache_keys[r] not in self._pcache:
                    self._cache_insert(req, r)

    def _cache_insert(self, req: _GenRequest, row: int) -> None:
        """Pool one row's prefix state (target + draft) into the device
        LRU — fp arrays as-is, or int8 payloads + per-tensor scales."""
        tb = tuple(b[row] for b in req.boots)
        tp = tuple(p[row] for p in req.pes)
        d = self._draft
        db = dp = None
        if d is not None:
            db = tuple(b[row] for b in req.dboots)
            dp = tuple(p[row] for p in req.dpes)
        if self.prefix_cache_quant == "int8":
            t_pay = self._q_rows(tb, tp)
            d_pay = self._q_rows(db, dp) if d is not None else None
        else:
            t_pay = (tb, tp)
            d_pay = (db, dp) if d is not None else None
        payload = {"t": t_pay, "d": d_pay}
        nbytes = sum(
            int(leaf.nbytes)
            for leaf in self._jax.tree_util.tree_leaves(payload))
        evicted = self._pcache.put(req.cache_keys[row], payload, nbytes)
        if evicted:
            self.metrics.counter_inc(
                "gen_prefix_cache_evictions_total", by=float(evicted),
                help="prefix states evicted from the device-resident "
                     "LRU (byte budget pressure)")

    def _admit_row(self, req: _GenRequest, row: int, slot: int) -> None:
        jnp = self._jax.numpy
        d = self._draft
        if req.boots is None:
            # cache-hit admission: pooled state -> slot through the
            # SAME jitted dynamic-update a fresh prefix uses (int8
            # entries dequantize inside the copy)
            t_pay = req.cached[row]["t"]
            d_pay = req.cached[row]["d"]
            if self.prefix_cache_quant == "int8":
                qb, bs, qp, ps = t_pay
                self._state = self._pool_admit_q(
                    self._state, jnp.int32(slot), qb, bs, qp, ps)
                if d is not None:
                    qb, bs, qp, ps = d_pay
                    d["mems"], d["tok"], d["pe"] = d["admit_q"](
                        d["mems"], d["tok"], d["pe"], jnp.int32(slot),
                        qb, bs, qp, ps)
            else:
                boots, pes = t_pay
                self._state = self._pool_admit(
                    self._state, jnp.int32(slot), boots, pes)
                if d is not None:
                    dboots, dpes = d_pay
                    d["mems"], d["tok"], d["pe"] = d["admit"](
                        d["mems"], d["tok"], d["pe"], jnp.int32(slot),
                        dboots, dpes)
        else:
            boots = tuple(b[row] for b in req.boots)
            pes = tuple(p[row] for p in req.pes)
            self._state = self._pool_admit(
                self._state, jnp.int32(slot), boots, pes)
            if d is not None:
                dboots = tuple(b[row] for b in req.dboots)
                dpes = tuple(p[row] for p in req.dpes)
                d["mems"], d["tok"], d["pe"] = d["admit"](
                    d["mems"], d["tok"], d["pe"], jnp.int32(slot),
                    dboots, dpes)
        self._slot_req[slot] = (req, row)
        self._active[slot] = True
        self.admitted_total += 1

    def _step_once(self) -> None:
        jnp = self._jax.numpy
        armed = obs_trace._armed  # hot per-token path: guard all trace work
        if armed:
            obs_trace._begin("gen.pool_step", "gen",
                             {"step": self.steps_total,
                              "active": int(self._active.sum())})
            obs_trace.counter("gen_active_slots", int(self._active.sum()))
        try:
            # the same chaos point engine.predict fires: a generation
            # step failure must fan out, feed the breaker, and free the
            # pool — never wedge the worker thread
            faults.fire("serving.predict", model=self.engine.model_name,
                        path="generate")
            active = jnp.asarray(self._active)
            self._state = self._pool_step(self._params, active, self._state)
            # ONE host fence for everything the streaming loop reads —
            # three separate np.asarray calls would pay three d2h
            # round-trips per decode step
            tok, fin, stp = self._jax.device_get(
                (self._state.tok, self._state.fin, self._state.step))
        except Exception as e:
            if armed:
                obs_trace._end()
            if self.breaker is not None:
                self.breaker.record_failure()
            with self._cond:
                self._abort_inflight_locked(GenerationAborted(
                    f"generation pool step failed "
                    f"({type(e).__name__}: {e}); in-flight requests "
                    "aborted, slots recovered — retry"))
            return
        if armed:
            obs_trace._end()
        self.dispatches_total += 1
        self.syncs_total += 1
        self.steps_total += 1
        self._occupancy_steps += int(self._active.sum())
        self.metrics.counter_inc(
            "gen_steps_total", help="decode pool steps executed")
        now = time.monotonic()
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            req, row = self._slot_req[slot]
            t = int(stp[slot])
            if req.first_token_at is None and req.deadline <= now:
                # satellite contract: a late FIRST token is never
                # streamed — the client already gave up
                self._evict_request(req)
                self._deadline_fail(req, "deadline exceeded before the "
                                         "first token (cold pool-step "
                                         "compile? warm the engine)")
                continue
            if req.first_token_at is None:
                req.first_token_at = now
                self._first_tok.observe(now - req.submitted_at)
                if armed:
                    obs_trace.instant(
                        "gen.first_token", cat="gen",
                        request_id=req.request_id, slot=slot)
            if req.last_token_at is not None:
                self._per_tok.observe(now - req.last_token_at)
            req.last_token_at = now
            self.tokens_total += 1
            self.metrics.counter_inc(
                "gen_tokens_total",
                help="tokens streamed across all generation requests")
            req.handle._emit_token(row, t - 1, int(tok[slot, 0]))
            if bool(fin[slot].all()) or t >= self.spec.max_len:
                self._retire(slot, req, row, t)

    def _spec_round(self) -> None:
        """ONE speculative round over the pool: draft proposes draft_k
        tokens per slot (one fused dispatch), the target verifies them
        all in one `pool_verify` dispatch, then ONE host fence streams
        every accepted token — up to draft_k tokens per slot for the
        2-dispatch/1-fence cost plain decoding pays PER TOKEN. Every
        applied step is an exact pool_step update, so the streamed
        tokens (and final backtrack) are bit-identical to plain
        decoding; a fully-rejected draft degrades to exactly one plain
        step."""
        jnp = self._jax.numpy
        armed = obs_trace._armed  # hot per-round path: guard all trace work
        d = self._draft
        D = self.draft_k
        if armed:
            obs_trace._begin("gen.verify", "gen",
                             {"round": self.verify_rounds_total,
                              "active": int(self._active.sum())})
            obs_trace.counter("gen_active_slots", int(self._active.sum()))
        t0 = time.monotonic()
        try:
            faults.fire("serving.predict", model=self.engine.model_name,
                        path="generate")
            active = jnp.asarray(self._active)
            drafts, hist = d["propose"](
                d["params"], d["mems"], d["tok"], d["pe"])
            st, ndm, ndt, adv = self._pool_verify(
                self._params, active, self._state, drafts, hist,
                d["mems"], d["tok"])
            self._state = st
            d["mems"], d["tok"] = ndm, ndt
            # ONE host fence for everything the streaming loop reads:
            # beam-0 trellis row (the exact per-step token stream —
            # column t is written with the step-t token and never
            # rewritten), finish mask, step counters, accepted counts
            ttok0, fin, stp, adv_h = self._jax.device_get(
                (st.trellis_tok[:, 0, :], st.fin, st.step, adv))
        except Exception as e:
            if armed:
                obs_trace._end()
            if self.breaker is not None:
                self.breaker.record_failure()
            with self._cond:
                self._abort_inflight_locked(GenerationAborted(
                    f"speculative verify round failed "
                    f"({type(e).__name__}: {e}); in-flight requests "
                    "aborted, slots recovered — retry"))
            return
        if armed:
            obs_trace._end()
        self._verify_lat.observe(time.monotonic() - t0)
        n_active = int(self._active.sum())
        adv_sum = int(adv_h.sum())
        self.dispatches_total += 2
        self.syncs_total += 1
        self.steps_total += D  # the device ran D beam_steps per slot
        self.verify_rounds_total += 1
        self._occupancy_steps += adv_sum  # productive slot-steps
        self._draft_proposed += D * n_active
        self._draft_accepted += adv_sum
        self.metrics.counter_inc(
            "gen_steps_total", by=float(D),
            help="decode pool steps executed")
        self.metrics.counter_inc(
            "gen_verify_rounds_total",
            help="speculative verify rounds (one draft dispatch + one "
                 "target verify dispatch each)")
        self.metrics.counter_inc(
            "gen_draft_tokens_total", by=float(D * n_active),
            help="tokens proposed by the draft model")
        self.metrics.counter_inc(
            "gen_draft_accepted_total", by=float(adv_sum),
            help="proposed tokens converted to emitted target tokens "
                 "(the divergence-correcting target step included)")
        now = time.monotonic()
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            req, row = self._slot_req[slot]
            a = int(adv_h[slot])
            t_new = int(stp[slot])
            if req.first_token_at is None and req.deadline <= now:
                # same contract as _step_once: a late FIRST token is
                # never streamed
                self._evict_request(req)
                self._deadline_fail(req, "deadline exceeded before the "
                                         "first token (cold pool-step "
                                         "compile? warm the engine)")
                continue
            if a <= 0:
                continue  # defensive: active slots always advance >= 1
            if req.first_token_at is None:
                req.first_token_at = now
                self._first_tok.observe(now - req.submitted_at)
                if armed:
                    obs_trace.instant(
                        "gen.first_token", cat="gen",
                        request_id=req.request_id, slot=slot)
            if req.last_token_at is not None:
                # the round's tokens arrive as one burst; the interval
                # histogram records per-ROUND cadence in this mode
                self._per_tok.observe(now - req.last_token_at)
            req.last_token_at = now
            self.tokens_total += a
            self.metrics.counter_inc(
                "gen_tokens_total", by=float(a),
                help="tokens streamed across all generation requests")
            for t in range(t_new - a, t_new):
                req.handle._emit_token(row, t, int(ttok0[slot, t]))
            if bool(fin[slot].all()) or t_new >= self.spec.max_len:
                self._retire(slot, req, row, t_new)

    def _retire(self, slot: int, req: _GenRequest, row: int,
                t_star: int) -> None:
        """Early-exit compaction: backtrack THIS slot's trellis over its
        own t* steps, deliver, and free the slot immediately — the rest
        of the pool keeps decoding."""
        with obs_trace.span("gen.retire", cat="gen",
                            request_id=req.request_id, slot=slot,
                            steps=t_star):
            parents = np.asarray(self._state.parents[slot])  # [K, T]
            toks = np.asarray(self._state.trellis_tok[slot])
            scores = np.asarray(self._state.scores[slot])
            ids, out_scores, lengths = _finalize_slot(
                parents, toks, scores, t_star, self.spec)
        req.results[row] = (ids, out_scores, lengths)
        self._active[slot] = False
        self._slot_req[slot] = None
        req.live_rows -= 1
        self.retired_total += 1
        if len(req.results) == req.rows and not req.failed:
            outs = {
                "ids": np.stack(
                    [req.results[r][0] for r in range(req.rows)]),
                "scores": np.stack(
                    [req.results[r][1] for r in range(req.rows)]),
                "lengths": np.stack(
                    [req.results[r][2] for r in range(req.rows)]),
            }
            if self.breaker is not None:
                self.breaker.record_success()
            req.handle._finish(outs)

    # -- failure paths --------------------------------------------------
    def _deadline_fail(self, req: _GenRequest, msg: str) -> None:
        # post-admission deadline re-check failure path: shared counter
        # + DeadlineError delivery via the AdmissionQueue contract
        self._aq.expire(req, msg)

    def _evict_request(self, req: _GenRequest) -> None:
        for slot in range(self.max_slots):
            if self._active[slot] and self._slot_req[slot] is not None \
                    and self._slot_req[slot][0] is req:
                self._active[slot] = False
                self._slot_req[slot] = None
                req.live_rows -= 1
        if self._partial is req:
            self._partial = None

    def _abort_inflight_locked(self, exc: Exception) -> None:
        seen = set()
        for slot in range(self.max_slots):
            entry = self._slot_req[slot]
            if entry is not None and id(entry[0]) not in seen:
                seen.add(id(entry[0]))
                entry[0].fail(exc)
            self._slot_req[slot] = None
            self._active[slot] = False
        if self._partial is not None:
            if id(self._partial) not in seen:
                self._partial.fail(exc)
            self._partial = None

    def _drain_queue(self, exc: Exception) -> None:
        self._aq.drain(exc)

    # -- accounting -----------------------------------------------------
    def occupancy(self) -> float:
        """Time-weighted slot occupancy since start (1.0 = every slot
        busy every step — zero padding waste)."""
        return (self._occupancy_steps / (self.steps_total * self.max_slots)
                if self.steps_total else 0.0)

    def stats(self) -> Dict[str, Any]:
        out = {
            "max_slots": self.max_slots,
            "active_slots": int(self._active.sum()),
            "queue_depth": self._aq.depth(),
            "occupancy": round(self.occupancy(), 4),
            "steps_total": self.steps_total,
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
            "tokens_total": self.tokens_total,
            "dispatches_total": self.dispatches_total,
            "syncs_total": self.syncs_total,
            "compiles": self.compiles,
            "beam_size": self.spec.beam_size,
            "max_len": self.spec.max_len,
            "prefix_programs": {
                "entries": len(self._prefix_cache),
                "cap": self.max_prefix_programs,
                "evictions": self.prefix_program_evictions,
            },
        }
        if self._pcache is not None:
            pc = self._pcache.stats()
            pc["quant"] = self.prefix_cache_quant or "fp"
            out["prefix_cache"] = pc
        if self._draft is not None:
            out["speculative"] = {
                "draft_dir": self._draft["dir"],
                "draft_k": self.draft_k,
                "verify_rounds_total": self.verify_rounds_total,
                "proposed_total": self._draft_proposed,
                "accepted_total": self._draft_accepted,
                "accept_rate": round(
                    self._draft_accepted / self._draft_proposed, 4)
                if self._draft_proposed else 0.0,
            }
        return out


def _finalize_slot(parents: np.ndarray, toks: np.ndarray,
                   scores: np.ndarray, t_star: int, spec):
    """Backtrack + finalize ONE retired slot, numpy mirror of
    ops/beam_common.backtrack + finalize restricted to t* steps.

    Bit-identity with batch-mode decode: past the step where every beam
    finished, the batch kernel's expand/prune is the identity (frozen
    beams emit EOS at zero cost, top_k keeps the already-descending
    score order), so columns t* .. T-1 of its trellis backtrack to EOS
    and the scores never change — padding with eos_id reproduces the
    full-T result exactly. Integer gathers and the length-normalize
    float32 division round identically in numpy and XLA."""
    K = parents.shape[0]
    T = spec.max_len
    ids = np.full((K, T), spec.eos_id, np.int32)
    idx = np.arange(K)
    for t in range(t_star - 1, -1, -1):
        ids[:, t] = toks[idx, t]
        idx = parents[idx, t]
    is_eos = ids == spec.eos_id
    any_eos = is_eos.any(axis=-1)
    first_eos = is_eos.argmax(axis=-1)
    lengths = np.where(any_eos, first_eos + 1, T).astype(np.int32)
    scores = scores.astype(np.float32)
    if spec.length_normalize:
        scores = scores / np.maximum(lengths, 1).astype(scores.dtype)
        order = np.argsort(-scores, kind="stable")
        scores = scores[order]
        ids = ids[order]
        lengths = lengths[order]
    return ids, scores, lengths
