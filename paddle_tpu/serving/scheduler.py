"""Continuous batching for generation serving: token-level scheduler
with device-resident decode state.

Why request-granularity batching loses on generation: the batch-mode
`beam_search_group` program scans for `max_len` steps no matter when a
request's beams finish, so a padded slot does max_len steps of work to
produce avg_len useful tokens, and a new request waits for the WHOLE
batch to drain before it can start (PERF.md measures the ragged-batch
analogue of this waste at 1.48-1.59x on training inputs; generation
adds the drain-latency term on top).

The continuous scheduler inverts the loop: a fixed pool of `max_slots`
decode slots whose state (beam memories, cumulative scores, the
(parent, token) trellis) stays ON DEVICE between steps as one
`DecodeState` pytree. Each iteration:

  1. ADMIT  — queued requests occupy free slots (the model's encoder
              prefix runs once per request through the engine's shape
              buckets; boot states are written into the pool by a
              jitted dynamic-update).
  2. STEP   — ONE jitted pool step advances every active slot by one
              token (the same `beam_step` the batch kernel scans —
              per-slot math is bit-identical to batch-mode decode).
  3. STREAM — the current best-beam token of every active slot is
              pushed to its request's event queue (provisional until
              the final backtrack, as in any beam-search streamer).
  4. RETIRE — slots whose beams all finished (or hit max_len) are
              backtracked, their results delivered, and the slot freed
              for the next admission — early-exit compaction: a short
              request never pays for a long neighbour.

Deadline/shed semantics mirror the MicroBatcher contract: a bounded
admission queue sheds with ShedError/503, deadlines are checked at
admission AND re-checked after slot admission/first step so a request
never streams a late first token past its deadline (DeadlineError/504).
A shared per-model CircuitBreaker (resilience.breaker) counts step
failures so /generate trips the same breaker /predict does. The
`serving.predict` fault point is fired each pool step: an injected
fault aborts in-flight requests with GenerationAborted (503, retryable)
and recovers the slots for subsequent traffic.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..obs import trace as obs_trace
from ..resilience import faults
from ..resilience.breaker import CircuitBreaker, CircuitOpenError
from .batcher import AdmissionQueue, DeadlineError, ShedError
from .metrics import (FIRST_TOKEN_BUCKETS, TOKEN_INTERVAL_BUCKETS,
                      MetricSet)

__all__ = ["ContinuousScheduler", "GenHandle", "GenerationAborted",
           "DeadlineError", "ShedError", "CircuitOpenError"]


class GenerationAborted(ShedError):
    """A pool step failed mid-flight: the request was aborted, slots
    recovered — retry (maps to HTTP 503 + Retry-After)."""


class GenHandle:
    """Client-side handle for one generation request.

    `events()` yields dicts as decoding progresses:
      {"event": "token", "row": r, "step": t, "token": id}   per step
      {"event": "done",  "outputs": {...}}                   terminal
      {"event": "error", "error": msg, "kind": clsname}      terminal
    `result()` blocks to the terminal event and returns the outputs
    dict (ids [n,K,T], scores [n,K], lengths [n,K]) or raises."""

    def __init__(self, rows: int):
        self.rows = rows
        self.request_id: Optional[str] = None  # set by _GenRequest
        self._q: "queue.Queue[dict]" = queue.Queue()
        self._done = threading.Event()
        self._outputs: Optional[Dict[str, np.ndarray]] = None
        self._exc: Optional[BaseException] = None

    # -- scheduler side -------------------------------------------------
    def _emit_token(self, row: int, step: int, token: int) -> None:
        self._q.put({"event": "token", "row": row, "step": step,
                     "token": token})

    def _finish(self, outputs: Dict[str, np.ndarray]) -> None:
        self._outputs = outputs
        self._done.set()
        self._q.put({"event": "done", "outputs": outputs})

    def _fail(self, exc: BaseException) -> None:
        if self._done.is_set():
            return
        self._exc = exc
        self._done.set()
        self._q.put({"event": "error", "error": str(exc),
                     "kind": type(exc).__name__})

    # -- client side ----------------------------------------------------
    def events(self, timeout: Optional[float] = None):
        while True:
            ev = self._q.get(timeout=timeout)
            yield ev
            if ev["event"] in ("done", "error"):
                return

    def result(self, timeout: Optional[float] = None) -> Dict[str, np.ndarray]:
        if not self._done.wait(timeout=timeout):
            raise DeadlineError("generation result timed out")
        if self._exc is not None:
            raise self._exc
        assert self._outputs is not None
        return self._outputs


class _GenRequest:
    __slots__ = ("feed", "rows", "handle", "deadline", "submitted_at",
                 "first_token_at", "last_token_at", "boots", "pes",
                 "next_row", "live_rows", "results", "failed",
                 "request_id", "slo_class", "enqueued_at")

    def __init__(self, feed, rows: int, deadline: float,
                 request_id: Optional[str] = None,
                 slo_class: str = "interactive"):
        self.feed = feed
        self.rows = rows
        self.slo_class = slo_class
        self.enqueued_at = 0.0  # stamped by AdmissionQueue.put
        # correlation key: every span this request touches — enqueue on
        # the client thread, admit/prefix/first-token/retire on the
        # scheduler worker, the HTTP span on the handler thread —
        # carries this id (ISSUE 8 queue→admit→pool-step→stream flow).
        # A router-minted id (X-PT-Request-Id) is adopted verbatim so
        # the router hop joins the same chain.
        self.request_id = request_id or obs_trace.new_request_id("gen")
        self.handle = GenHandle(rows)
        self.handle.request_id = self.request_id
        self.deadline = deadline
        self.submitted_at = time.monotonic()
        self.first_token_at: Optional[float] = None
        self.last_token_at: Optional[float] = None
        self.boots = None  # prefix outputs, set at first admission
        self.pes = None
        self.next_row = 0  # next un-admitted row
        self.live_rows = 0  # rows currently holding slots
        self.results: Dict[int, tuple] = {}  # row -> (ids, scores, lengths)
        self.failed = False

    def fail(self, exc: BaseException) -> None:
        """Terminal failure (AdmissionQueue contract + scheduler paths)."""
        self.failed = True
        self.handle._fail(exc)


class ContinuousScheduler:
    """Token-level continuous-batching scheduler over one engine's
    generative model. One worker thread owns the decode pool; any
    number of client threads submit()."""

    def __init__(
        self,
        engine,
        max_slots: int = 8,
        max_queue: int = 64,
        timeout_ms: float = 30000.0,
        breaker: Optional[CircuitBreaker] = None,
        metrics: Optional[MetricSet] = None,
    ):
        from ..ops import generation_ops as G

        self.engine = engine
        op = G.find_generation_op(engine.program)
        if op is None:
            raise ValueError(
                f"model {engine.model_name!r} has no beam_search_group "
                "op — continuous batching serves generation programs "
                "(layers.BeamSearchDecoder); use predict() for "
                "feed-forward models")
        self._G = G
        self.spec = G.gen_spec_from_op(op)
        block0 = engine.program.global_block()
        gen_idx = block0.ops.index(op)
        if any(o.type != "beam_search_group" for o in block0.ops[gen_idx + 1:]):
            raise ValueError(
                "ops after the beam_search_group op are not supported by "
                "the continuous scheduler (its outputs feed post-decode "
                "ops the pool step cannot incrementalize)")
        self._prefix_ops = block0.ops[:gen_idx]
        self._block0 = block0
        self._check_step_closures(engine.program)
        if max_slots < 1:
            raise ValueError(f"max_slots must be >= 1, got {max_slots}")
        self.max_slots = max_slots
        self.max_queue = max_queue
        self.timeout_s = timeout_ms / 1e3
        self.breaker = breaker
        self.metrics = metrics or engine.metrics

        import jax

        self._jax = jax
        # persistables snapshot: generation serving assumes frozen
        # weights (the engine contract); committed once, passed to every
        # jitted call so jit never re-traces on placement
        scope = engine.scope
        self._params = {
            v.name: jax.device_put(scope.get(v.name))
            for v in engine.program.persistables() if scope.has(v.name)
        }

        # pool state (allocated on first admission or warmup-from-meta)
        self._state = None  # DecodeState
        self._mem_specs = None  # ((trailing shape, dtype), ...)
        self._pe_specs = None
        self._pool_step = None  # jitted (params, active, state) -> state
        self._pool_admit = None  # jitted (state, slot, boots, pes) -> state
        self._prefix_cache: Dict[tuple, Any] = {}
        self.compiles = 0

        self._cond = threading.Condition()
        # the admission queue shares MicroBatcher's deadline/shed
        # semantics (serving/batcher.py) — one contract for both paths
        self._aq = AdmissionQueue(max_queue, self._cond, self.metrics,
                                  prefix="gen_")
        self._slot_req: List[Optional[Tuple[_GenRequest, int]]] = (
            [None] * max_slots)
        self._active = np.zeros(max_slots, bool)
        self._partial: Optional[_GenRequest] = None  # rows still waiting
        self._worker: Optional[threading.Thread] = None
        self._stopping = False

        # accounting (engine-parity dispatch/sync counters + gen stats)
        self.dispatches_total = 0
        self.syncs_total = 0
        self.steps_total = 0
        self.admitted_total = 0
        self.retired_total = 0
        self.tokens_total = 0
        self._occupancy_steps = 0  # sum of active-slot count over steps
        self._first_tok = self.metrics.histogram(
            "gen_first_token_seconds", buckets=FIRST_TOKEN_BUCKETS,
            help="submit-to-first-streamed-token latency")
        self._per_tok = self.metrics.histogram(
            "gen_token_seconds", buckets=TOKEN_INTERVAL_BUCKETS,
            help="inter-token interval per request")
        self.metrics.gauge(
            "gen_slot_occupancy",
            lambda: float(self._active.sum()) / self.max_slots,
            help="fraction of decode slots occupied")
        self.metrics.gauge(
            "gen_queue_depth", lambda: self._aq.depth(),
            help="generation requests waiting for a slot")
        # pre-registered counters: the scrape surface is complete from
        # construction, not dependent on traffic having arrived
        self.metrics.declare_counter(
            "gen_requests_total", help="generation requests accepted")
        self.metrics.declare_counter(
            "gen_steps_total", help="decode pool steps executed")
        self.metrics.declare_counter(
            "gen_tokens_total",
            help="tokens streamed across all generation requests")
        self.metrics.declare_counter(
            "circuit_open_total",
            help="requests rejected because the model's circuit "
                 "breaker was open")

    def _check_step_closures(self, program) -> None:
        """The pool-step env holds parameters and declared per-example
        tensors ONLY (batch-mode decode sees the whole block-0 env, so
        it tolerates undeclared closures the scheduler cannot): reject
        step bodies that close over other outer values up front, with a
        fix, instead of a KeyError mid-trace."""
        spec = self.spec
        persist = {v.name for v in program.persistables()}
        produced = ({spec.prev_inner} | set(spec.mem_inner)
                    | set(spec.per_example))
        refs: set = set()
        stack = [spec.sub_block]
        while stack:
            b = program.blocks[stack.pop()]
            for sop in b.ops:
                refs.update(n for n in sop.input_names()
                            if n not in produced)
                produced.update(sop.output_names())
                inner = sop.attrs.get("sub_block")
                if isinstance(inner, int):
                    stack.append(inner)
        missing = sorted(refs - persist)
        if missing:
            raise ValueError(
                f"generation step body closes over non-parameter outer "
                f"value(s) {missing}: continuous batching keeps only "
                "parameters and declared per-example tensors device-"
                "resident — declare them with gen.per_example_input()")

    # -- lifecycle ------------------------------------------------------
    def start(self) -> "ContinuousScheduler":
        with self._cond:
            if self._worker is not None and self._worker.is_alive():
                return self
            self._stopping = False
            self._worker = threading.Thread(
                target=self._run,
                name=f"ptgen-{self.engine.model_name}", daemon=True)
            self._worker.start()
        return self

    def stop(self, drain: bool = False,
             drain_timeout_s: float = 60.0) -> None:
        """Stop the pool worker. drain=True lets queued + in-flight
        generation finish first (bounded by drain_timeout_s) — the
        graceful half of the replica SIGTERM contract; whatever is
        still in flight past the bound fails with a retryable
        ShedError so a router can re-run it elsewhere."""
        if drain:
            deadline = time.monotonic() + drain_timeout_s
            while time.monotonic() < deadline:
                with self._cond:
                    # depth() is lock-free (the cond is NOT reentrant)
                    if not self._aq.depth() and not self._active.any() \
                            and self._partial is None:
                        break
                time.sleep(0.01)
        with self._cond:
            self._stopping = True
            self._cond.notify_all()
        if self._worker is not None:
            self._worker.join(timeout=10.0)
        # fail whatever is still queued/in flight
        self._drain_queue(ShedError("scheduler stopped"))
        with self._cond:
            self._abort_inflight_locked(ShedError("scheduler stopped"))

    # -- client side ----------------------------------------------------
    def submit(self, feed: Dict[str, np.ndarray],
               timeout_ms: Optional[float] = None,
               request_id: Optional[str] = None,
               slo: Optional[str] = None) -> GenHandle:
        if self.breaker is not None and not self.breaker.admit():
            self.metrics.counter_inc(
                "circuit_open_total",
                help="requests rejected because the model's circuit "
                     "breaker was open")
            raise CircuitOpenError(
                f"circuit open for model {self.engine.model_name!r}; "
                "retry later")
        rows = {v.shape[0] for v in feed.values()
                if hasattr(v, "ndim") and v.ndim >= 1}
        if len(rows) != 1:
            raise ValueError(
                f"generation feeds must share the batch axis; got row "
                f"counts {sorted(rows)}")
        n = rows.pop()
        deadline = time.monotonic() + (
            timeout_ms / 1e3 if timeout_ms is not None else self.timeout_s)
        req = _GenRequest(feed, n, deadline, request_id=request_id,
                          slo_class=slo or "interactive")
        with self._cond:
            if self._stopping:
                raise ShedError("scheduler stopped")
        self._aq.put(req)  # sheds with ShedError/503 when full
        if obs_trace._armed:
            # enqueue marker on the CLIENT thread; the worker-side admit
            # span carries the same request_id, linking the hand-off
            obs_trace.instant("gen.enqueue", cat="gen",
                              request_id=req.request_id, rows=n)
        self.metrics.counter_inc(
            "gen_requests_total", help="generation requests accepted")
        return req.handle

    def generate(self, feed: Dict[str, np.ndarray],
                 timeout_ms: Optional[float] = None) -> Dict[str, np.ndarray]:
        """submit + wait: the non-streaming convenience used by
        ServingEngine.generate(). Grace mirrors MicroBatcher.predict
        (cold pool-step compiles can outlast the deadline alone)."""
        h = self.submit(feed, timeout_ms=timeout_ms)
        budget = (timeout_ms / 1e3 if timeout_ms is not None
                  else self.timeout_s)
        return h.result(timeout=budget + max(1.0, budget))

    # -- pool construction ---------------------------------------------
    def _build_prefix(self, padded: Dict[str, Any]):
        """Jitted encoder prefix: (params, feed) -> (boots, pes); one
        compile per engine shape bucket (the slot-state compile cache is
        keyed off the SAME buckets predict uses)."""
        from ..core.executor import _BlockRunner, _feed_signature

        key = _feed_signature(padded)
        fn = self._prefix_cache.get(key)
        if fn is not None:
            return fn
        jax, jnp = self._jax, self._jax.numpy
        runner = _BlockRunner(self.engine.program)
        spec, block0, ops = self.spec, self._block0, self._prefix_ops
        amp = self.engine.program.amp_dtype

        def prefix(params, feed):
            env = dict(params)
            env.update(feed)
            env["@RNG@"] = jax.random.PRNGKey(0)
            env["@RNG_COUNTER@"] = 0
            env["@AMP@"] = amp
            runner.run_ops(ops, env, dict(env), block0)
            boots = tuple(env[n] for n in spec.boot_names)
            pes = tuple(env[n] for n in spec.per_example_names)
            return boots, pes

        fn = jax.jit(prefix)
        self._prefix_cache[key] = fn
        self.compiles += 1
        return fn

    def _ensure_pool(self, mem_specs, pe_specs) -> None:
        """Allocate the DecodeState pool + compile step/admit for these
        per-slot trailing shapes (once per model: the decode state
        geometry is fixed by the program, not by traffic)."""
        if self._state is not None:
            if (mem_specs, pe_specs) != (self._mem_specs, self._pe_specs):
                raise ValueError(
                    f"generation state geometry changed mid-serve: pool "
                    f"holds {self._mem_specs}/{self._pe_specs}, request "
                    f"produced {mem_specs}/{pe_specs} — decode-state "
                    "trailing shapes must be static (pad variable-length "
                    "encoder outputs to a fixed bucket)")
            return
        jax, jnp = self._jax, self._jax.numpy
        from ..core.executor import _BlockRunner
        from ..ops import beam_common

        G, spec, S = self._G, self.spec, self.max_slots
        K, T = spec.beam_size, spec.max_len
        self._mem_specs, self._pe_specs = mem_specs, pe_specs
        self._state = G.DecodeState(
            mems=tuple(jnp.zeros((S, K) + shp, dt) for shp, dt in mem_specs),
            tok=jnp.full((S, K), spec.bos_id, jnp.int32),
            scores=jnp.zeros((S, K), jnp.float32),
            fin=jnp.ones((S, K), bool),
            step=jnp.zeros((S,), jnp.int32),
            parents=jnp.zeros((S, K, T), jnp.int32),
            trellis_tok=jnp.full((S, K, T), spec.eos_id, jnp.int32),
            pe=tuple(jnp.zeros((S * K,) + shp, dt) for shp, dt in pe_specs),
        )
        runner = _BlockRunner(self.engine.program)
        block = self.engine.program.blocks[spec.sub_block]
        amp = self.engine.program.amp_dtype

        def pool_step(params, active, state):
            env = dict(params)
            env["@RNG@"] = jax.random.PRNGKey(0)
            env["@RNG_COUNTER@"] = 0
            env["@AMP@"] = amp
            for name, v in zip(spec.per_example, state.pe):
                env[name] = v
            new_mems, new_tok, new_sc, new_fin, parent = G.beam_step(
                runner, block, spec, env,
                state.mems, state.tok, state.scores, state.fin)
            u2 = active[:, None]
            mems = tuple(
                jnp.where(active.reshape((S,) + (1,) * (m.ndim - 1)), nm, m)
                for nm, m in zip(new_mems, state.mems))
            tok = jnp.where(u2, new_tok, state.tok)
            sc = jnp.where(u2, new_sc, state.scores)
            fin = jnp.where(u2, new_fin, state.fin)
            at_t = (jnp.arange(T)[None, None, :]
                    == state.step[:, None, None]) & active[:, None, None]
            parents = jnp.where(at_t, parent[:, :, None], state.parents)
            ttok = jnp.where(at_t, new_tok[:, :, None], state.trellis_tok)
            stp = state.step + active.astype(jnp.int32)
            return G.DecodeState(mems, tok, sc, fin, stp, parents, ttok,
                                 state.pe)

        def pool_admit(state, slot, boots, pe_rows):
            mems = tuple(
                jax.lax.dynamic_update_index_in_dim(
                    m, jnp.broadcast_to(b, (K,) + b.shape), slot, 0)
                for m, b in zip(state.mems, boots))
            tok = jax.lax.dynamic_update_index_in_dim(
                state.tok, jnp.full((K,), spec.bos_id, jnp.int32), slot, 0)
            sc = jax.lax.dynamic_update_index_in_dim(
                state.scores, beam_common.init_scores(1, K)[0], slot, 0)
            fin = jax.lax.dynamic_update_index_in_dim(
                state.fin, jnp.zeros((K,), bool), slot, 0)
            stp = jax.lax.dynamic_update_index_in_dim(
                state.step, jnp.zeros((), jnp.int32), slot, 0)
            pe = tuple(
                jax.lax.dynamic_update_slice_in_dim(
                    p, jnp.repeat(r[None], K, axis=0), slot * K, axis=0)
                for p, r in zip(state.pe, pe_rows))
            # parents/trellis_tok stay stale: the pool step overwrites
            # columns 0..t-1 before retirement ever backtracks them
            return state._replace(mems=mems, tok=tok, scores=sc, fin=fin,
                                  step=stp, pe=pe)

        self._pool_step = jax.jit(pool_step)
        self._pool_admit = jax.jit(pool_admit)
        self.compiles += 2

    def warmup(self) -> int:
        """Pre-compile the slot machinery so the first live request
        never pays the pool-step trace: prefix programs for every feed
        bucket (zero feeds, exactly like ServingEngine.warmup) and —
        when the artifact's meta.json records generation-state specs
        (io.save_inference_model) — the pool step + admit programs,
        without running any request through the model source.
        Returns the number of programs compiled."""
        before = self.compiles
        meta = getattr(self.engine.program, "_generation_meta", None)
        if meta and self._state is None:
            try:
                mem_specs = tuple(
                    (tuple(int(d) for d in m["shape"]), np.dtype(m["dtype"]))
                    for m in meta.get("state", []))
                pe_specs = tuple(
                    (tuple(int(d) for d in m["shape"]), np.dtype(m["dtype"]))
                    for m in meta.get("per_example", []))
                self._ensure_pool(mem_specs, pe_specs)
            except (KeyError, TypeError, ValueError) as e:
                import warnings

                warnings.warn(
                    f"generation meta of model "
                    f"{self.engine.model_name!r} unusable for pool "
                    f"warmup ({e}); slot state compiles on first "
                    "request", stacklevel=2)
        if self._state is not None:
            # trace+compile step and admit against the real pool state
            jnp = self._jax.numpy
            active = jnp.zeros((self.max_slots,), bool)
            self._state = self._pool_step(self._params, active, self._state)
            boots = tuple(jnp.zeros(shp, dt) for shp, dt in self._mem_specs)
            pes = tuple(jnp.zeros(shp, dt) for shp, dt in self._pe_specs)
            self._state = self._pool_admit(
                self._state, jnp.int32(0), boots, pes)
            # leave the pool empty: the warmup admit wrote slot 0 but
            # _active stays False so its garbage never steps or retires
        pol = self.engine.policy
        for nb in pol.batch_buckets:
            for tb in (pol.seq_len_buckets or (None,)):
                feed = self.engine._zero_bucket_feed(nb, tb)
                if feed is None:
                    continue
                self._build_prefix(
                    {k: self._jax.numpy.asarray(v) for k, v in feed.items()})
        return self.compiles - before

    # -- worker ---------------------------------------------------------
    def _run(self) -> None:
        while True:
            with self._cond:
                while (not self._aq.depth() and not self._active.any()
                       and self._partial is None and not self._stopping):
                    self._cond.wait()
                if self._stopping:
                    return
            try:
                self._admit_ready()
            except Exception:
                # per-request admission failures are delivered on the
                # request handle inside _admit_ready; anything reaching
                # here is a scheduler bug — surface it on every handle
                import traceback

                traceback.print_exc()
            if self._active.any():
                self._step_once()
            else:
                time.sleep(0.001)  # queue non-empty but nothing admitted

    def _free_slots(self) -> List[int]:
        return [i for i in range(self.max_slots) if not self._active[i]]

    def _admit_ready(self) -> None:
        free = self._free_slots()
        while free:
            req = self._partial
            if req is None:
                with self._cond:
                    # pop() fails already-expired requests with
                    # DeadlineError (the queued-side deadline check)
                    req = self._aq.pop()
                if req is None:
                    return
                try:
                    self._run_prefix(req)
                except Exception as e:
                    req.fail(e)
                    free = self._free_slots()
                    continue
            admitted_any = False
            with obs_trace.span("gen.admit", cat="gen",
                                request_id=req.request_id):
                while free and req.next_row < req.rows:
                    slot = free.pop(0)
                    row = req.next_row
                    self._admit_row(req, row, slot)
                    req.next_row += 1
                    req.live_rows += 1
                    admitted_any = True
            self._partial = req if req.next_row < req.rows else None
            # deadline RE-CHECK after slot admission: the prefix run (a
            # possible cold bucket compile) may have eaten the budget —
            # free the slots now rather than stream a late first token
            if admitted_any and req.first_token_at is None \
                    and req.deadline <= time.monotonic():
                self._evict_request(req)
                self._deadline_fail(req, "deadline exceeded during slot "
                                         "admission (cold compile? warm "
                                         "the engine)")
            free = self._free_slots()
            if self._partial is not None:
                return  # head-of-line request still owns the next slots

    def _run_prefix(self, req: _GenRequest) -> None:
        with obs_trace.span("gen.prefix", cat="gen",
                            request_id=req.request_id, rows=req.rows):
            padded, n, _ = self.engine._pad_feed(
                {k: np.asarray(v) for k, v in req.feed.items()})
            jnp = self._jax.numpy
            padded = {k: jnp.asarray(v) for k, v in padded.items()}
            fn = self._build_prefix(padded)
            boots, pes = fn(self._params, padded)
        mem_specs = tuple((tuple(b.shape[1:]), np.dtype(b.dtype))
                          for b in boots)
        pe_specs = tuple((tuple(p.shape[1:]), np.dtype(p.dtype))
                         for p in pes)
        self._ensure_pool(mem_specs, pe_specs)
        req.boots = boots  # [nb, ...] device arrays; rows sliced on admit
        req.pes = pes
        self.dispatches_total += 1

    def _admit_row(self, req: _GenRequest, row: int, slot: int) -> None:
        jnp = self._jax.numpy
        boots = tuple(b[row] for b in req.boots)
        pes = tuple(p[row] for p in req.pes)
        self._state = self._pool_admit(
            self._state, jnp.int32(slot), boots, pes)
        self._slot_req[slot] = (req, row)
        self._active[slot] = True
        self.admitted_total += 1

    def _step_once(self) -> None:
        jnp = self._jax.numpy
        armed = obs_trace._armed  # hot per-token path: guard all trace work
        if armed:
            obs_trace._begin("gen.pool_step", "gen",
                             {"step": self.steps_total,
                              "active": int(self._active.sum())})
            obs_trace.counter("gen_active_slots", int(self._active.sum()))
        try:
            # the same chaos point engine.predict fires: a generation
            # step failure must fan out, feed the breaker, and free the
            # pool — never wedge the worker thread
            faults.fire("serving.predict", model=self.engine.model_name,
                        path="generate")
            active = jnp.asarray(self._active)
            self._state = self._pool_step(self._params, active, self._state)
            # ONE host fence for everything the streaming loop reads —
            # three separate np.asarray calls would pay three d2h
            # round-trips per decode step
            tok, fin, stp = self._jax.device_get(
                (self._state.tok, self._state.fin, self._state.step))
        except Exception as e:
            if armed:
                obs_trace._end()
            if self.breaker is not None:
                self.breaker.record_failure()
            with self._cond:
                self._abort_inflight_locked(GenerationAborted(
                    f"generation pool step failed "
                    f"({type(e).__name__}: {e}); in-flight requests "
                    "aborted, slots recovered — retry"))
            return
        if armed:
            obs_trace._end()
        self.dispatches_total += 1
        self.syncs_total += 1
        self.steps_total += 1
        self._occupancy_steps += int(self._active.sum())
        self.metrics.counter_inc(
            "gen_steps_total", help="decode pool steps executed")
        now = time.monotonic()
        for slot in range(self.max_slots):
            if not self._active[slot]:
                continue
            req, row = self._slot_req[slot]
            t = int(stp[slot])
            if req.first_token_at is None and req.deadline <= now:
                # satellite contract: a late FIRST token is never
                # streamed — the client already gave up
                self._evict_request(req)
                self._deadline_fail(req, "deadline exceeded before the "
                                         "first token (cold pool-step "
                                         "compile? warm the engine)")
                continue
            if req.first_token_at is None:
                req.first_token_at = now
                self._first_tok.observe(now - req.submitted_at)
                if armed:
                    obs_trace.instant(
                        "gen.first_token", cat="gen",
                        request_id=req.request_id, slot=slot)
            if req.last_token_at is not None:
                self._per_tok.observe(now - req.last_token_at)
            req.last_token_at = now
            self.tokens_total += 1
            self.metrics.counter_inc(
                "gen_tokens_total",
                help="tokens streamed across all generation requests")
            req.handle._emit_token(row, t - 1, int(tok[slot, 0]))
            if bool(fin[slot].all()) or t >= self.spec.max_len:
                self._retire(slot, req, row, t)

    def _retire(self, slot: int, req: _GenRequest, row: int,
                t_star: int) -> None:
        """Early-exit compaction: backtrack THIS slot's trellis over its
        own t* steps, deliver, and free the slot immediately — the rest
        of the pool keeps decoding."""
        with obs_trace.span("gen.retire", cat="gen",
                            request_id=req.request_id, slot=slot,
                            steps=t_star):
            parents = np.asarray(self._state.parents[slot])  # [K, T]
            toks = np.asarray(self._state.trellis_tok[slot])
            scores = np.asarray(self._state.scores[slot])
            ids, out_scores, lengths = _finalize_slot(
                parents, toks, scores, t_star, self.spec)
        req.results[row] = (ids, out_scores, lengths)
        self._active[slot] = False
        self._slot_req[slot] = None
        req.live_rows -= 1
        self.retired_total += 1
        if len(req.results) == req.rows and not req.failed:
            outs = {
                "ids": np.stack(
                    [req.results[r][0] for r in range(req.rows)]),
                "scores": np.stack(
                    [req.results[r][1] for r in range(req.rows)]),
                "lengths": np.stack(
                    [req.results[r][2] for r in range(req.rows)]),
            }
            if self.breaker is not None:
                self.breaker.record_success()
            req.handle._finish(outs)

    # -- failure paths --------------------------------------------------
    def _deadline_fail(self, req: _GenRequest, msg: str) -> None:
        # post-admission deadline re-check failure path: shared counter
        # + DeadlineError delivery via the AdmissionQueue contract
        self._aq.expire(req, msg)

    def _evict_request(self, req: _GenRequest) -> None:
        for slot in range(self.max_slots):
            if self._active[slot] and self._slot_req[slot] is not None \
                    and self._slot_req[slot][0] is req:
                self._active[slot] = False
                self._slot_req[slot] = None
                req.live_rows -= 1
        if self._partial is req:
            self._partial = None

    def _abort_inflight_locked(self, exc: Exception) -> None:
        seen = set()
        for slot in range(self.max_slots):
            entry = self._slot_req[slot]
            if entry is not None and id(entry[0]) not in seen:
                seen.add(id(entry[0]))
                entry[0].fail(exc)
            self._slot_req[slot] = None
            self._active[slot] = False
        if self._partial is not None:
            if id(self._partial) not in seen:
                self._partial.fail(exc)
            self._partial = None

    def _drain_queue(self, exc: Exception) -> None:
        self._aq.drain(exc)

    # -- accounting -----------------------------------------------------
    def occupancy(self) -> float:
        """Time-weighted slot occupancy since start (1.0 = every slot
        busy every step — zero padding waste)."""
        return (self._occupancy_steps / (self.steps_total * self.max_slots)
                if self.steps_total else 0.0)

    def stats(self) -> Dict[str, Any]:
        return {
            "max_slots": self.max_slots,
            "active_slots": int(self._active.sum()),
            "queue_depth": self._aq.depth(),
            "occupancy": round(self.occupancy(), 4),
            "steps_total": self.steps_total,
            "admitted_total": self.admitted_total,
            "retired_total": self.retired_total,
            "tokens_total": self.tokens_total,
            "dispatches_total": self.dispatches_total,
            "syncs_total": self.syncs_total,
            "compiles": self.compiles,
            "beam_size": self.spec.beam_size,
            "max_len": self.spec.max_len,
        }


def _finalize_slot(parents: np.ndarray, toks: np.ndarray,
                   scores: np.ndarray, t_star: int, spec):
    """Backtrack + finalize ONE retired slot, numpy mirror of
    ops/beam_common.backtrack + finalize restricted to t* steps.

    Bit-identity with batch-mode decode: past the step where every beam
    finished, the batch kernel's expand/prune is the identity (frozen
    beams emit EOS at zero cost, top_k keeps the already-descending
    score order), so columns t* .. T-1 of its trellis backtrack to EOS
    and the scores never change — padding with eos_id reproduces the
    full-T result exactly. Integer gathers and the length-normalize
    float32 division round identically in numpy and XLA."""
    K = parents.shape[0]
    T = spec.max_len
    ids = np.full((K, T), spec.eos_id, np.int32)
    idx = np.arange(K)
    for t in range(t_star - 1, -1, -1):
        ids[:, t] = toks[idx, t]
        idx = parents[idx, t]
    is_eos = ids == spec.eos_id
    any_eos = is_eos.any(axis=-1)
    first_eos = is_eos.argmax(axis=-1)
    lengths = np.where(any_eos, first_eos + 1, T).astype(np.int32)
    scores = scores.astype(np.float32)
    if spec.length_normalize:
        scores = scores / np.maximum(lengths, 1).astype(scores.dtype)
        order = np.argsort(-scores, kind="stable")
        scores = scores[order]
        ids = ids[order]
        lengths = lengths[order]
    return ids, scores, lengths
