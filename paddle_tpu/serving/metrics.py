"""Serving metrics: histograms + gauges in Prometheus text format.

Reference lineage: the Gen-1 runtime prints a StatSet table of named
timers (paddle/utils/Stat.h:230 printAllStatus); a serving front-end
needs the same accounting *scrapeable* — latency quantiles, batch-size
distribution, queue depth, and compile-cache hit rate in the Prometheus
text exposition format. This module builds on the existing
`profiler.StatSet` plumbing (every serving timer also lands in the
global stat table, so `print_all_status()` keeps working) and adds the
two things StatSet lacks: bucketed histograms with quantile estimates
and a point-in-time gauge/counter export.

Everything here is host-side and thread-safe (the HTTP front-end
scrapes /metrics from a different thread than the batcher observes
from); no JAX in this module.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..profiler import StatSet

__all__ = ["Histogram", "MetricSet", "DEFAULT_LATENCY_BUCKETS",
           "FIRST_TOKEN_BUCKETS", "TOKEN_INTERVAL_BUCKETS"]

# seconds; spans sub-ms CPU fc models to multi-second cold compiles
DEFAULT_LATENCY_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0, 30.0,
)

# generation-serving latency grids (continuous batching): first-token
# latency is queue wait + prefix run + one pool step (ms to seconds —
# a cold compile lands in the tail buckets and is visible as such);
# the inter-token interval is ~one pool step (sub-ms to tens of ms).
FIRST_TOKEN_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)
TOKEN_INTERVAL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0,
)


class Histogram:
    """Cumulative-bucket histogram (Prometheus `histogram` type).

    Quantiles are estimated from the bucket counts (each returns the
    upper bound of the bucket containing the quantile — the standard
    `histogram_quantile` resolution, good enough for p50/p95/p99
    dashboards without keeping samples)."""

    def __init__(self, name: str, buckets: Sequence[float],
                 help: str = ""):
        self.name = name
        self.help = help
        self.bounds: Tuple[float, ...] = tuple(sorted(buckets))
        self.counts = [0] * (len(self.bounds) + 1)  # last = +Inf
        self.sum = 0.0
        self.count = 0
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        with self._lock:
            self.sum += value
            self.count += 1
            for i, b in enumerate(self.bounds):
                if value <= b:
                    self.counts[i] += 1
                    return
            self.counts[-1] += 1

    def percentile(self, q: float) -> float:
        """Upper bound of the bucket holding quantile q in [0, 1];
        0.0 when empty, the largest finite bound for the +Inf bucket."""
        with self._lock:
            if self.count == 0:
                return 0.0
            target = q * self.count
            cum = 0
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                if cum >= target:
                    return b
            return self.bounds[-1] if self.bounds else 0.0

    def render(self) -> List[str]:
        lines = []
        if self.help:
            lines.append(f"# HELP {self.name} {self.help}")
        lines.append(f"# TYPE {self.name} histogram")
        with self._lock:
            cum = 0
            for i, b in enumerate(self.bounds):
                cum += self.counts[i]
                lines.append(f'{self.name}_bucket{{le="{_fmt(b)}"}} {cum}')
            cum += self.counts[-1]
            lines.append(f'{self.name}_bucket{{le="+Inf"}} {cum}')
            lines.append(f"{self.name}_sum {_fmt(self.sum)}")
            lines.append(f"{self.name}_count {self.count}")
        return lines


def _fmt(v: float) -> str:
    # prometheus floats: integral values without the trailing .0 noise
    return str(int(v)) if float(v).is_integer() else repr(float(v))


class MetricSet:
    """Registry of histograms, counters, and gauge callables with one
    `render()` to the Prometheus text format.

    Gauges are *callables* evaluated at scrape time (queue depth, cache
    size): the instrumented component owns the value, the metric set
    only knows how to read it — no double bookkeeping. A StatSet can be
    attached; its timers render as `<name>_seconds_total` /
    `<name>_count` counter pairs so the serving path's REGISTER_TIMER
    accounting is scrapeable too."""

    def __init__(self, namespace: str = "ptserving",
                 stat_set: Optional[StatSet] = None):
        self.namespace = namespace
        self.stat_set = stat_set
        self._histograms: Dict[str, Histogram] = {}
        self._counters: Dict[str, float] = {}
        self._counter_help: Dict[str, str] = {}
        self._gauges: Dict[str, Tuple[Callable[[], float], str]] = {}
        self._lock = threading.Lock()

    # -- registration ---------------------------------------------------
    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  help: str = "") -> Histogram:
        full = f"{self.namespace}_{name}"
        with self._lock:
            h = self._histograms.get(full)
            if h is None:
                h = self._histograms[full] = Histogram(full, buckets, help)
            return h

    def counter_inc(self, name: str, by: float = 1.0,
                    help: str = "") -> None:
        full = f"{self.namespace}_{name}"
        with self._lock:
            self._counters[full] = self._counters.get(full, 0.0) + by
            if help:
                self._counter_help.setdefault(full, help)

    def counter_value(self, name: str) -> float:
        with self._lock:
            return self._counters.get(f"{self.namespace}_{name}", 0.0)

    def gauge(self, name: str, fn: Callable[[], float],
              help: str = "") -> None:
        with self._lock:
            self._gauges[f"{self.namespace}_{name}"] = (fn, help)

    # -- export ---------------------------------------------------------
    def render(self) -> str:
        lines: List[str] = []
        with self._lock:
            hists = list(self._histograms.values())
            counters = sorted(self._counters.items())
            helps = dict(self._counter_help)
            gauges = sorted(self._gauges.items())
        for h in hists:
            lines.extend(h.render())
            # convenience quantile gauges so dashboards don't need
            # histogram_quantile(); same data, pre-reduced
            for q, label in ((0.5, "p50"), (0.95, "p95"), (0.99, "p99")):
                lines.append(f"{h.name}_{label} {_fmt(h.percentile(q))}")
        for name, v in counters:
            if name in helps:
                lines.append(f"# HELP {name} {helps[name]}")
            lines.append(f"# TYPE {name} counter")
            lines.append(f"{name} {_fmt(v)}")
        for name, (fn, help) in gauges:
            if help:
                lines.append(f"# HELP {name} {help}")
            lines.append(f"# TYPE {name} gauge")
            try:
                lines.append(f"{name} {_fmt(float(fn()))}")
            except Exception:
                lines.append(f"{name} NaN")
        if self.stat_set is not None:
            for name, s in sorted(self.stat_set.as_dict().items()):
                metric = f"{self.namespace}_timer_{_sanitize(name)}"
                lines.append(f"# TYPE {metric}_seconds_total counter")
                lines.append(f"{metric}_seconds_total {_fmt(s['total'])}")
                lines.append(f"{metric}_count {s['count']}")
        return "\n".join(lines) + "\n"


def _sanitize(name: str) -> str:
    return "".join(c if c.isalnum() or c == "_" else "_" for c in name)
