"""Serving metrics: a namespaced view over the unified registry.

Reference lineage: the Gen-1 runtime prints a StatSet table of named
timers (paddle/utils/Stat.h:230 printAllStatus); a serving front-end
needs the same accounting *scrapeable*. Since ISSUE 8 the storage and
the Prometheus renderer live in `paddle_tpu.obs.metrics` — ONE
process-wide MetricsRegistry shared with the trainer's counters, the
fault registry, the trace session, and the global StatSet — and this
module is the serving-flavored view of it: a `MetricSet` prepends its
namespace (`ptserving_` by default) to every family it registers, and
`render()` returns the WHOLE unified exposition, so the HTTP `/metrics`
endpoint scrapes training-side families too.

Everything here is host-side and thread-safe (the HTTP front-end
scrapes /metrics from a different thread than the batcher observes
from); no JAX in this module.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Sequence

from ..obs import metrics as _obs_metrics
from ..obs.metrics import (DEFAULT_LATENCY_BUCKETS, Histogram,  # noqa: F401
                           _sanitize)

__all__ = ["Histogram", "MetricSet", "DEFAULT_LATENCY_BUCKETS",
           "FIRST_TOKEN_BUCKETS", "TOKEN_INTERVAL_BUCKETS",
           "VERIFY_ROUND_BUCKETS", "HANDOFF_BUCKETS"]

# generation-serving latency grids (continuous batching): first-token
# latency is queue wait + prefix run + one pool step (ms to seconds —
# a cold compile lands in the tail buckets and is visible as such);
# the inter-token interval is ~one pool step (sub-ms to tens of ms).
FIRST_TOKEN_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5, 5.0, 10.0,
)
TOKEN_INTERVAL_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0,
)
# one speculative round = draft propose dispatch + target verify
# dispatch + one d2h fence; moves up to draft_k tokens per slot, so the
# grid sits between the per-token and first-token grids.
VERIFY_ROUND_BUCKETS = (
    0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25, 0.5,
    1.0, 2.5,
)
# disagg handoff transfer (prefill completion → decode admission):
# payload serialize + one router hop + schema validate + admit
# enqueue. Loopback sub-ms; cross-host fp32 big-beam state reaches
# seconds, which is exactly what --handoff_quant int8 halves.
HANDOFF_BUCKETS = (
    0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
    0.5, 1.0, 2.5, 5.0,
)


class MetricSet:
    """Namespaced registration view over the process-wide
    MetricsRegistry (obs.metrics.registry()).

    Gauges are *callables* evaluated at scrape time (queue depth, cache
    size): the instrumented component owns the value, the registry only
    knows how to read it — no double bookkeeping. `stat_set` is kept
    for API compatibility: the GLOBAL StatSet already rides the unified
    render as `pt_timer_*`; a private StatSet passed here is attached
    under this view's namespace."""

    def __init__(self, namespace: str = "ptserving",
                 stat_set=None,
                 registry: Optional[_obs_metrics.MetricsRegistry] = None):
        self.namespace = namespace
        self.registry = registry if registry is not None \
            else _obs_metrics.registry()
        self.stat_set = stat_set
        if stat_set is not None and not _is_global_stat_set(stat_set):
            self.registry.attach_stat_set(
                stat_set, prefix=f"{namespace}_timer_")

    def _full(self, name: str) -> str:
        return f"{self.namespace}_{name}"

    # -- registration ---------------------------------------------------
    def histogram(self, name: str,
                  buckets: Sequence[float] = DEFAULT_LATENCY_BUCKETS,
                  help: str = "") -> Histogram:
        return self.registry.histogram(self._full(name), buckets, help)

    def declare_counter(self, name: str, help: str = "",
                        labels: Optional[Dict[str, Any]] = None) -> None:
        """Pre-register the series at 0 (component constructors call
        this so a scraper never sees the family appear mid-flight)."""
        self.registry.declare_counter(self._full(name), help, labels)

    def counter_inc(self, name: str, by: float = 1.0, help: str = "",
                    labels: Optional[Dict[str, Any]] = None) -> None:
        self.registry.counter_inc(self._full(name), by, help, labels)

    def counter_value(self, name: str,
                      labels: Optional[Dict[str, Any]] = None) -> float:
        return self.registry.counter_value(self._full(name), labels)

    def gauge(self, name: str, fn: Callable[[], float],
              help: str = "") -> None:
        self.registry.gauge(self._full(name), fn, help)

    # -- export ---------------------------------------------------------
    def render(self) -> str:
        """The UNIFIED exposition — every family in the process-wide
        registry, not just this namespace (serving /metrics is a view
        of the whole runtime; ISSUE 8)."""
        return self.registry.render()


def _is_global_stat_set(stat_set) -> bool:
    from .. import profiler

    return stat_set is profiler.global_stat_set()
